"""Scheduler-cache snapshot: the per-cycle view of nodes + assigned pods.

Analog of the upstream shared lister snapshot the reference's hot loop
iterates (SURVEY.md section 3.2).  Plugins that need cluster-wide context
(PodTopologySpread, InterPodAffinity) read it through the framework handle.
"""

from __future__ import annotations

from typing import Any

from kube_scheduler_simulator_tpu.models.nodeinfo import NodeInfo, build_node_infos

Obj = dict[str, Any]


def has_pending_nomination(pod: Obj) -> bool:
    """Unbound pod carrying a preemption nomination — the single
    definition shared by Snapshot (sequential reservation) and the batch
    engine's supported() gate, so the two paths can't drift."""
    return bool((pod.get("status") or {}).get("nominatedNodeName")) and not (
        (pod.get("spec") or {}).get("nodeName")
    )


def _pod_has_affinity(pod: Obj) -> bool:
    aff = (pod.get("spec") or {}).get("affinity") or {}
    pa = aff.get("podAffinity") or {}
    paa = aff.get("podAntiAffinity") or {}
    return bool(
        pa.get("requiredDuringSchedulingIgnoredDuringExecution")
        or pa.get("preferredDuringSchedulingIgnoredDuringExecution")
        or paa.get("requiredDuringSchedulingIgnoredDuringExecution")
        or paa.get("preferredDuringSchedulingIgnoredDuringExecution")
    )


def _pod_has_required_anti_affinity(pod: Obj) -> bool:
    aff = (pod.get("spec") or {}).get("affinity") or {}
    paa = aff.get("podAntiAffinity") or {}
    return bool(paa.get("requiredDuringSchedulingIgnoredDuringExecution"))


class Snapshot:
    """NodeInfos plus the two filtered node lists upstream maintains."""

    def __init__(self, nodes: list[Obj], pods: list[Obj], namespaces: "list[Obj] | None" = None):
        self.node_infos: list[NodeInfo] = build_node_infos(nodes, pods)
        self._by_name = {ni.name: ni for ni in self.node_infos}
        self.namespace_labels: dict[str, dict[str, str]] = {
            ns["metadata"]["name"]: ns["metadata"].get("labels") or {} for ns in namespaces or []
        }
        # UNBOUND pods nominated onto a node by preemption (upstream's
        # nominator): other pods' filter runs must account for them
        self.nominated: dict[str, list[Obj]] = {}
        for p in pods:
            if has_pending_nomination(p):
                self.nominated.setdefault(p["status"]["nominatedNodeName"], []).append(p)

    def get(self, name: str) -> "NodeInfo | None":
        return self._by_name.get(name)

    def nominated_pods(self, node_name: str) -> list[Obj]:
        return self.nominated.get(node_name, [])

    def have_pods_with_affinity(self) -> list[NodeInfo]:
        return [ni for ni in self.node_infos if any(_pod_has_affinity(p) for p in ni.pods)]

    def have_pods_with_required_anti_affinity(self) -> list[NodeInfo]:
        return [ni for ni in self.node_infos if any(_pod_has_required_anti_affinity(p) for p in ni.pods)]

    def assume(self, pod: Obj, node_name: str) -> None:
        """Account a pod onto a node (the cache 'assume' after Reserve)."""
        ni = self._by_name.get(node_name)
        if ni is not None:
            pod = dict(pod)
            spec = dict(pod.get("spec") or {})
            spec["nodeName"] = node_name
            pod["spec"] = spec
            ni.add_pod(pod)
        # an assumed pod is no longer a pending nomination — leaving it in
        # self.nominated would double-count its resources for later pods
        me = pod["metadata"]
        key = (me.get("namespace", "default"), me["name"])
        for nn, lst in list(self.nominated.items()):
            kept = [
                q
                for q in lst
                if (q["metadata"].get("namespace", "default"), q["metadata"]["name"]) != key
            ]
            if kept:
                self.nominated[nn] = kept
            elif nn in self.nominated:
                del self.nominated[nn]

    def forget(self, pod: Obj, node_name: str) -> None:
        ni = self._by_name.get(node_name)
        if ni is not None:
            ni.remove_pod(pod)
        # an assumed-then-forgotten pod (Permit reject, bind failure) gets
        # its nomination reservation back — assume() had dropped it
        if has_pending_nomination(pod):
            nn = pod["status"]["nominatedNodeName"]
            lst = self.nominated.setdefault(nn, [])
            me = (pod["metadata"].get("namespace", "default"), pod["metadata"]["name"])
            if all(
                (q["metadata"].get("namespace", "default"), q["metadata"]["name"]) != me
                for q in lst
            ):
                lst.append(pod)
