"""TPUBatchScorer bridge: serve the batch kernel in extenderv1 wire format.

SURVEY.md §7 step 8 / BASELINE.json's TPUBatchScorer deliverable: expose
``filter`` and ``prioritize`` in the scheduler-extender wire format the
reference proxies (reference simulator/scheduler/extender/extender.go:
122-148 — ``ExtenderArgs{pod, nodes, nodenames}`` in,
``ExtenderFilterResult{nodes/nodenames, failedNodes,
failedAndUnresolvableNodes}`` / ``HostPriorityList[{host, score}]`` out),
so a REAL kube-scheduler — the Go simulator's or any cluster's — can point
an extender stanza at this endpoint and delegate its Filter/Prioritize
work to the TPU kernel.

Semantics:
- Filter runs the kernelized filter plugins of the CURRENT simulator
  profile over the provided candidate nodes and splits failures into
  ``failedNodes`` vs ``failedAndUnresolvableNodes`` the way the in-tree
  plugins status them (NodeName / NodeAffinity / NodeUnschedulable are
  UnschedulableAndUnresolvable upstream).
- Prioritize returns each node's weighted total (Σ normalized×weight over
  the profile's kernelized score plugins) — the same number the trace
  records as the pod's finalscore sum — as the extender score.  The Go
  side multiplies by the extender's configured weight.
- Workloads the kernel does not cover fall back to the sequential oracle
  plugins, so the endpoint is always exact.

No feasible-node sampling is applied: the calling scheduler has already
chosen which nodes to offer (extenders see post-sampling candidates).
"""

from __future__ import annotations

from typing import Any


Obj = dict[str, Any]

# Upstream plugins whose Filter failures are UnschedulableAndUnresolvable
# (the calling kube-scheduler's preemption skips those nodes).  Shared
# with the batch engine's diagnosis classification so both bridge paths
# and the batch path agree.
def _is_unresolvable(plugin: str, message: str) -> bool:
    from kube_scheduler_simulator_tpu.scheduler.batch_engine import (
        FILTER_MESSAGES,
        UNRESOLVABLE_CODES,
    )

    codes = UNRESOLVABLE_CODES.get(plugin, False)
    if codes is False:
        return False
    if codes is None:  # every failure of this plugin
        return True
    # code-specific plugins: derive the unresolvable MESSAGES from the
    # same tables the batch engine's diagnosis uses, so the two paths
    # cannot diverge when the code set grows
    msgs = FILTER_MESSAGES.get(plugin, {})
    return message in {msgs[c] for c in codes if c in msgs}


class TPUScorerBridge:
    """Serve the current profile's kernels over extenderv1 JSON."""

    def __init__(self, scheduler_service: Any):
        import threading

        self.scheduler_service = scheduler_service
        self._engine: Any = None
        self._engine_fw: Any = None
        # ThreadingHTTPServer serves each request on its own thread; the
        # shared engine (jit cache, counters) is not thread-safe, so
        # kernel passes serialize here
        self._lock = threading.Lock()
        # Observability (surfaced via /api/v1/metrics)
        self.requests = {"filter": 0, "prioritize": 0}
        self.fallbacks = 0

    # ------------------------------------------------------------ plumbing

    def _framework(self):
        fw = self.scheduler_service.framework
        if fw is None:
            raise RuntimeError("scheduler not started")
        return fw

    def _engine_for(self, fw):
        if self._engine is None or self._engine_fw is not fw:
            from kube_scheduler_simulator_tpu.scheduler.batch_engine import BatchEngine

            eng = BatchEngine.from_framework(fw, trace=True)
            # extenders see post-sampling candidates — score all of them
            eng.percentage_of_nodes_to_score = 100
            self._engine = eng
            self._engine_fw = fw
        return self._engine

    def _nodes_from_args(self, args: Obj) -> "tuple[list[Obj], bool]":
        """Candidate nodes + whether the caller sent full objects
        (node-cache-capable callers send only ``nodenames``)."""
        nodes_obj = args.get("nodes")
        if nodes_obj and nodes_obj.get("items"):
            return list(nodes_obj["items"]), True
        store = self.scheduler_service.cluster_store
        by_name = {n["metadata"]["name"]: n for n in store.list("nodes")}
        names = args.get("nodenames") or []
        return [by_name[nm] for nm in names if nm in by_name], False

    def _run(self, pod: Obj, nodes: list[Obj]):
        """One kernel pass of the pod over the candidate nodes; None when
        the profile × workload needs the sequential fallback."""
        with self._lock:
            fw = self._framework()
            eng = self._engine_for(fw)
            ok, _why = eng.supported([pod], nodes)
            if not ok:
                return None
            store = self.scheduler_service.cluster_store
            return eng.schedule(
                nodes, store.list("pods"), [pod], store.list("namespaces")
            )

    # --------------------------------------------------------------- verbs

    def filter(self, args: Obj) -> Obj:
        """extenderv1 Filter: split candidates into passed / failed /
        failed-and-unresolvable."""
        self.requests["filter"] += 1
        pod = args.get("pod") or {}
        nodes, full_objects = self._nodes_from_args(args)
        try:
            result = self._run(pod, nodes)
            if result is not None:
                from kube_scheduler_simulator_tpu.plugins.resultstore import (
                    PASSED_FILTER_MESSAGE,
                )

                anno = result.filter_annotation(0)
                # candidates narrowed OUT by a NodeAffinity matchFields
                # PreFilter never appear in the annotation — they are
                # unresolvable failures, not passes
                narrowed = result._engine.prefilter_node_names(pod)
                failed: dict[str, str] = {}
                unresolvable: dict[str, str] = {}
                passed: list[Obj] = []
                for n in nodes:
                    nm = n["metadata"]["name"]
                    if narrowed is not None and nm not in narrowed:
                        unresolvable[nm] = "node(s) didn't satisfy plugin(s) prefilter result"
                        continue
                    entry = anno.get(nm) or {}
                    bad = next(
                        ((pl, msg) for pl, msg in entry.items() if msg != PASSED_FILTER_MESSAGE),
                        None,
                    )
                    if bad is None:
                        passed.append(n)
                    elif _is_unresolvable(bad[0], bad[1]):
                        unresolvable[nm] = bad[1]
                    else:
                        failed[nm] = bad[1]
            else:
                self.fallbacks += 1
                passed, failed, unresolvable = self._filter_fallback(pod, nodes)
        except Exception as e:
            return {"nodes": None, "nodenames": None, "failedNodes": None, "error": str(e)}
        out: Obj = {
            "failedNodes": failed,
            "failedAndUnresolvableNodes": unresolvable,
            "error": "",
        }
        if full_objects:
            out["nodes"] = {"items": passed}
            out["nodenames"] = None
        else:
            out["nodes"] = None
            out["nodenames"] = [n["metadata"]["name"] for n in passed]
        return out

    def prioritize(self, args: Obj) -> list[Obj]:
        """extenderv1 Prioritize: HostPriorityList of kernel score totals."""
        self.requests["prioritize"] += 1
        pod = args.get("pod") or {}
        nodes, _full = self._nodes_from_args(args)
        result = self._run(pod, nodes)
        if result is not None and "trace" in result.out:
            totals = result.totals_map(0)
            feasible = result.feasible_idx(0)
            return [
                {
                    "host": n["metadata"]["name"],
                    "score": totals.get(j, 0) if j in feasible else 0,
                }
                for j, n in enumerate(nodes)
            ]
        self.fallbacks += 1
        return self._prioritize_fallback(pod, nodes)

    # ----------------------------------------------------------- fallbacks

    def _filter_fallback(self, pod: Obj, nodes: list[Obj]):
        """Sequential oracle filters (exact for any workload)."""
        from kube_scheduler_simulator_tpu.models.framework import CycleState
        from kube_scheduler_simulator_tpu.models.nodeinfo import build_node_infos

        fw = self._framework()
        store = self.scheduler_service.cluster_store
        node_infos = build_node_infos(nodes, store.list("pods"))
        state = CycleState()
        self._oracle_pre_filter(fw, state, pod)
        passed, failed, unresolvable = [], {}, {}
        from kube_scheduler_simulator_tpu.models.framework import Code

        for ni in node_infos:
            bad = None
            for wp in fw.plugins["filter"]:
                status = wp.original.filter(state, pod, ni)
                if status is not None and not status.is_success():
                    # the oracle's own status carries the exact
                    # resolvability classification
                    bad = (status.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE, status.message())
                    break
            if bad is None:
                passed.append(ni.node)
            elif bad[0]:
                unresolvable[ni.name] = bad[1]
            else:
                failed[ni.name] = bad[1]
        return passed, failed, unresolvable

    def _prioritize_fallback(self, pod: Obj, nodes: list[Obj]) -> list[Obj]:
        from kube_scheduler_simulator_tpu.models.framework import CycleState
        from kube_scheduler_simulator_tpu.models.nodeinfo import build_node_infos

        fw = self._framework()
        store = self.scheduler_service.cluster_store
        node_infos = build_node_infos(nodes, store.list("pods"))
        state = CycleState()
        self._oracle_pre_filter(fw, state, pod)
        for wp in fw.plugins["pre_score"]:
            wp.original.pre_score(state, pod, [ni.node for ni in node_infos])
        totals = {ni.name: 0 for ni in node_infos}
        for wp in fw.plugins["score"]:
            raw: dict[str, int] = {}
            for ni in node_infos:
                score, status = wp.original.score(state, pod, ni)
                raw[ni.name] = score if status is None or status.is_success() else 0
            normalizer = getattr(wp.original, "normalize_scores", None)
            if normalizer is not None:
                normalizer(state, pod, raw)
            weight = fw.score_weights.get(wp.original.name, 1)
            for nm, s in raw.items():
                totals[nm] += s * weight
        return [{"host": nm, "score": int(s)} for nm, s in totals.items()]

    @staticmethod
    def _oracle_pre_filter(fw, state, pod: Obj) -> None:
        """PreFilter state the oracle plugins need (snapshot comes from the
        framework handle, matching the in-process cycle)."""
        snap = fw.handle.snapshot()
        if snap is None:
            from kube_scheduler_simulator_tpu.models.snapshot import Snapshot

            store = fw.handle.cluster_store
            snap = Snapshot(
                store.list("nodes"), store.list("pods"), store.list("namespaces")
            )
            fw.handle.set_snapshot(snap)
        for wp in fw.plugins["pre_filter"]:
            wp.original.pre_filter(state, pod)
