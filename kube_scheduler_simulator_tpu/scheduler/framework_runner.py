"""The scheduling framework runner: ScheduleOne over wrapped plugins.

Sequential rebuild of the upstream scheduling cycle the reference traces
(SURVEY.md section 3.2: PreFilter → Filter → [PostFilter] → PreScore →
Score → Normalize → selectHost → Reserve → Permit → PreBind → Bind), with
upstream's feasible-node sampling (percentageOfNodesToScore + rotating
start index) and the single-feasible-node scoring bypass.

This path produces the full per-plugin annotation trace through the result
store.  The TPU batch engine (scheduler/batch_engine.py) computes the same
results as tensors; this runner is the semantic oracle.
"""

from __future__ import annotations

from typing import Any

from kube_scheduler_simulator_tpu.models.framework import (
    Code,
    CycleState,
    PreFilterResult,
    Status,
    WaitingPod,
)
from kube_scheduler_simulator_tpu.models.nodeinfo import NodeInfo
from kube_scheduler_simulator_tpu.models.snapshot import Snapshot
from kube_scheduler_simulator_tpu.models.wrapped import WrappedPlugin

Obj = dict[str, Any]

MIN_FEASIBLE_NODES_TO_FIND = 100
MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND = 5
# upstream maxTimeout for permit Wait (15 minutes)
MAX_PERMIT_TIMEOUT_S = 15 * 60.0


def num_feasible_nodes_to_find(num_all_nodes: int, percentage: int) -> int:
    """Upstream sched.numFeasibleNodesToFind (module-level so the batch
    engine computes the identical sample cap, scheduler/batch_engine.py)."""
    if num_all_nodes < MIN_FEASIBLE_NODES_TO_FIND or percentage >= 100:
        return num_all_nodes
    adaptive = percentage
    if adaptive <= 0:
        adaptive = 50 - num_all_nodes // 125
        if adaptive < MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND:
            adaptive = MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND
    num_nodes = num_all_nodes * adaptive // 100
    if num_nodes < MIN_FEASIBLE_NODES_TO_FIND:
        return MIN_FEASIBLE_NODES_TO_FIND
    return num_nodes


class FrameworkHandle:
    """What plugins can reach (upstream framework.Handle analog)."""

    def __init__(self, cluster_store: Any = None):
        self.cluster_store = cluster_store
        self.framework: "Framework | None" = None
        self._snapshot: "Snapshot | None" = None

    def snapshot(self) -> "Snapshot | None":
        return self._snapshot

    def set_snapshot(self, snap: Snapshot) -> None:
        self._snapshot = snap

    # upstream framework.Handle's waiting-pod surface (plugins use these
    # to approve/reject parked pods, e.g. coscheduling-style gangs)
    def get_waiting_pod(self, namespace: str, name: str):
        return self.framework.get_waiting_pod(namespace, name) if self.framework else None

    def iterate_over_waiting_pods(self):
        return self.framework.iterate_over_waiting_pods() if self.framework else []


class ScheduleResult:
    __slots__ = ("selected_node", "feasible_nodes", "diagnosis", "status", "nominated_node", "waiting_on")

    def __init__(
        self,
        selected_node: "str | None" = None,
        feasible_nodes: "list[str] | None" = None,
        diagnosis: "dict[str, Status] | None" = None,
        status: "Status | None" = None,
        nominated_node: "str | None" = None,
        waiting_on: "str | None" = None,
    ):
        self.selected_node = selected_node
        self.feasible_nodes = feasible_nodes or []
        self.diagnosis = diagnosis or {}
        self.status = status
        self.nominated_node = nominated_node
        # node the pod is parked on at Permit (WaitingPod machinery)
        self.waiting_on = waiting_on

    @property
    def success(self) -> bool:
        return self.selected_node is not None


class Framework:
    """One scheduling profile's plugin set, ready to schedule pods."""

    EXTENSION_POINTS = (
        "queue_sort",
        "pre_filter",
        "filter",
        "post_filter",
        "pre_score",
        "score",
        "reserve",
        "permit",
        "pre_bind",
        "bind",
        "post_bind",
    )

    def __init__(
        self,
        plugins: dict[str, list[WrappedPlugin]],
        handle: FrameworkHandle,
        score_weights: "dict[str, int] | None" = None,
        percentage_of_nodes_to_score: int = 0,
        seed: int = 0,
        profile_name: str = "default-scheduler",
        tie_break: str = "reservoir",
        clock: "Any | None" = None,
    ):
        self.plugins = {p: list(plugins.get(p, [])) for p in self.EXTENSION_POINTS}
        self.handle = handle
        handle.framework = self
        self.score_weights = dict(score_weights or {})
        # Optional plugin-weight OVERRIDE (the learned scoring head,
        # tuning/): SchedulerService.set_plugin_weights installs a
        # name → float map here; the weighted-sum below and the batch
        # engine (from_framework) both read it, so a round keeps the
        # same weighting whichever path it takes.  score_weights itself
        # stays the profile's integer config — restoring the default is
        # just clearing this.
        self.score_weight_override: "dict[str, float] | None" = None
        self.percentage_of_nodes_to_score = percentage_of_nodes_to_score
        self.seed = seed
        self.next_start_node_index = 0
        # Number of schedule_one attempts so far; keys the tie-break draw
        # (utils/hashing.py) so the batch kernel — which processes pod i of
        # a round as attempt sched_counter+i — makes the identical pick.
        self.sched_counter = 0
        self.profile_name = profile_name
        # pods parked at Permit (key → WaitingPod); see allow_waiting_pod
        self.waiting_pods: dict[str, WaitingPod] = {}
        # injectable time source for Permit deadlines: scenario replay
        # drives a deterministic timeline clock through here so gang
        # scheduleTimeoutSeconds expiry replays byte-identically
        import time as _time

        self.clock = clock or _time.monotonic
        # waiting pods RESOLVED (allowed-and-bound or rejected) since the
        # service last drained — fills whether the resolution came from a
        # service call or a PLUGIN cascade (gang release/rejection), so
        # the service can record failures it would otherwise never see
        self.resolved_waiting: list[tuple[Obj, "ScheduleResult"]] = []
        # "reservoir" = upstream selectHost semantics (uniform over tied
        # maxima), made deterministic via a counter-keyed hash draw shared
        # with the batch kernel; "first" = first-max in visit order,
        # matching the batch engine's argmax — used by parity tests.
        self.tie_break = tie_break
        # ExtenderService (scheduler/extender.py); None = no extenders.
        # Hooks mirror upstream: filter narrowing after plugin filters,
        # additive prioritize scores, extender binder preferred over bind
        # plugins.
        self.extender_service = None

    # ------------------------------------------------------------- utilities

    def num_feasible_nodes_to_find(self, num_all_nodes: int) -> int:
        """Upstream sched.numFeasibleNodesToFind."""
        return num_feasible_nodes_to_find(num_all_nodes, self.percentage_of_nodes_to_score)

    def run_filter_plugins_silently(
        self,
        state: CycleState,
        pod: Obj,
        node_info: NodeInfo,
        snapshot: "Snapshot | None" = None,
    ) -> bool:
        """Run the ORIGINAL filter plugins without recording (used by
        preemption's victim search).  With ``snapshot``, other pods'
        pending nominations on this node are accounted first — upstream's
        dry run goes through RunFilterPluginsWithNominatedPods, so a
        preemptor can't be nominated onto capacity already reserved for a
        higher-priority nominee."""
        if snapshot is not None:
            from kube_scheduler_simulator_tpu.plugins.intree.queue_bind import pod_priority

            me = pod["metadata"]
            nominated = [
                q
                for q in snapshot.nominated_pods(node_info.name)
                if pod_priority(q) >= pod_priority(pod)
                and not (
                    q["metadata"]["name"] == me["name"]
                    and q["metadata"].get("namespace", "default") == me.get("namespace", "default")
                )
            ]
            if nominated:
                scratch = NodeInfo(node_info.node)
                for p in node_info.pods:
                    scratch.add_pod(p)
                cloned = state.clone()
                for q in nominated:
                    scratch.add_pod(q)
                    for wp in self.plugins["filter"]:
                        add = getattr(wp.original, "add_pod_to_state", None)
                        if add is not None:
                            add(cloned, pod, q, node_info)
                if not self._silent_pass(cloned, pod, scratch):
                    return False
        return self._silent_pass(state, pod, node_info)

    def _silent_pass(self, state: CycleState, pod: Obj, node_info: NodeInfo) -> bool:
        for wp in self.plugins["filter"]:
            status = wp.original.filter(state, pod, node_info)
            if status is not None and not status.is_success():
                return False
        return True

    # ---------------------------------------------------------- schedule one

    def schedule_one(self, pod: Obj, snapshot: Snapshot) -> ScheduleResult:
        self.handle.set_snapshot(snapshot)
        state = CycleState()
        # One attempt = one tie-break counter tick, consumed or not (the
        # batch kernel ticks once per scan step the same way).
        self._attempt = self.sched_counter
        self.sched_counter += 1

        # PreFilter
        merged_result = PreFilterResult(None)
        for wp in self.plugins["pre_filter"]:
            result, status = wp.pre_filter(state, pod)
            if status is not None and not status.is_success():
                if status.is_skip():
                    continue
                diagnosis = {ni.name: status for ni in snapshot.node_infos}
                return ScheduleResult(diagnosis=diagnosis, status=status)
            if result is not None:
                merged_result = merged_result.merge(result)

        node_infos = snapshot.node_infos
        if not merged_result.all_nodes():
            assert merged_result.node_names is not None
            node_infos = [ni for ni in node_infos if ni.name in merged_result.node_names]
            if not node_infos:
                status = Status.unresolvable("node(s) didn't satisfy plugin(s) prefilter result")
                return ScheduleResult(status=status)

        # Filter with feasible-node sampling + rotating start index
        num_all = len(snapshot.node_infos)
        num_to_find = self.num_feasible_nodes_to_find(num_all)
        feasible: list[NodeInfo] = []
        diagnosis: dict[str, Status] = {}
        processed = 0
        n = len(node_infos)
        for i in range(n):
            ni = node_infos[(self.next_start_node_index + i) % n]
            processed += 1
            status = self._run_filters_with_nominated(state, pod, ni, snapshot)
            if status is None:
                feasible.append(ni)
                if len(feasible) >= num_to_find:
                    break
            else:
                diagnosis[ni.name] = status
        self.next_start_node_index = (self.next_start_node_index + processed) % n if n else 0

        # Extender filter pass (upstream findNodesThatPassExtenders).  A
        # non-ignorable extender failure fails this scheduling attempt.
        if feasible and self.extender_service is not None and self.extender_service.extenders:
            try:
                passed, failed = self.extender_service.run_filter(pod, [ni.node for ni in feasible])
            except Exception as e:
                return ScheduleResult(status=Status.error(str(e)), diagnosis=diagnosis)
            passed_names = {nd["metadata"]["name"] for nd in passed}
            for nm, reason in failed.items():
                diagnosis[nm] = Status.unschedulable(reason)
            feasible = [ni for ni in feasible if ni.name in passed_names]

        if not feasible:
            nominated = self._run_post_filters(state, pod, diagnosis)
            status = Status.unschedulable(
                f"0/{num_all} nodes are available"
            )
            return ScheduleResult(diagnosis=diagnosis, status=status, nominated_node=nominated)

        # Single feasible node: skip scoring (upstream optimization).
        if len(feasible) == 1:
            selected = feasible[0].name
        else:
            selected, score_status = self._score_and_select(state, pod, feasible)
            if selected is None:
                return ScheduleResult(status=score_status, diagnosis=diagnosis)

        # Reserve
        for wp in self.plugins["reserve"]:
            status = wp.reserve(state, pod, selected)
            if status is not None and not status.is_success():
                self._unreserve(state, pod, selected)
                return ScheduleResult(status=status, diagnosis=diagnosis)
        snapshot.assume(pod, selected)

        # Permit: Wait parks the pod in waiting_pods (upstream's
        # waitingPodsMap) — binding happens when every waiting plugin
        # calls allow_waiting_pod, or the pod is rejected/expired.
        wait_timeouts: dict[str, float] = {}
        for wp in self.plugins["permit"]:
            status, timeout = wp.permit(state, pod, selected)
            if status is not None and status.is_wait():
                # upstream clamps 0/negative AND oversized timeouts to the
                # 15 min max
                t = float(timeout) if timeout and timeout > 0 else MAX_PERMIT_TIMEOUT_S
                wait_timeouts[wp.original.name] = min(t, MAX_PERMIT_TIMEOUT_S)
            elif status is not None and not status.is_success():
                snapshot.forget(pod, selected)
                self._unreserve(state, pod, selected)
                return ScheduleResult(status=status, diagnosis=diagnosis)
        if wait_timeouts:
            waiting = WaitingPod(pod, selected, state, wait_timeouts, self.clock())
            self.waiting_pods[waiting.key] = waiting
            return ScheduleResult(diagnosis=diagnosis, waiting_on=selected)

        return self._finish_binding(
            state, pod, selected, diagnosis, [ni.name for ni in feasible], snapshot
        )

    def _finish_binding(
        self,
        state: CycleState,
        pod: Obj,
        selected: str,
        diagnosis: dict[str, Status],
        feasible_names: list[str],
        snapshot: "Snapshot | None",
    ) -> ScheduleResult:
        """PreBind → Bind → PostBind (also runs when a waiting pod is
        finally allowed, where the round snapshot no longer exists)."""

        def fail(status: Status) -> ScheduleResult:
            if snapshot is not None:
                snapshot.forget(pod, selected)
            self._unreserve(state, pod, selected)
            return ScheduleResult(status=status, diagnosis=diagnosis)

        # PreBind
        for wp in self.plugins["pre_bind"]:
            status = wp.pre_bind(state, pod, selected)
            if status is not None and not status.is_success():
                return fail(status)

        # Bind: an interested extender binder takes precedence over bind
        # plugins (upstream sched.extendersBinding).
        binder = (
            self.extender_service.find_binder(pod)
            if self.extender_service is not None and self.extender_service.extenders
            else None
        )
        if binder is not None:
            idx, _ext = binder
            meta = pod["metadata"]
            try:
                result = self.extender_service.bind(
                    idx,
                    {
                        "podName": meta["name"],
                        "podNamespace": meta.get("namespace", "default"),
                        "podUID": meta.get("uid", ""),
                        "node": selected,
                    },
                )
            except Exception as e:  # webhook down/timeout: clean up state
                return fail(Status.error(str(e)))
            if result and result.get("error"):
                return fail(Status.error(result["error"]))
            # Upstream: the extender webhook binds against the apiserver
            # itself.  Our extender can't reach the in-memory store, so the
            # simulator performs the store bind on its behalf after a
            # successful response.
            store = getattr(self.handle, "cluster_store", None)
            if store is not None:
                meta = pod["metadata"]
                store.bind_pod(meta.get("namespace", "default"), meta["name"], selected)
        else:
            for wp in self.plugins["bind"]:
                status = wp.bind(state, pod, selected)
                if status is not None and status.is_skip():
                    continue
                if status is not None and not status.is_success():
                    return fail(status)
                break

        for wp in self.plugins["post_bind"]:
            wp.post_bind(state, pod, selected)

        return ScheduleResult(
            selected_node=selected,
            feasible_nodes=feasible_names,
            diagnosis=diagnosis,
        )

    # --------------------------------------------------------- waiting pods

    def get_waiting_pod(self, namespace: str, name: str) -> "WaitingPod | None":
        """upstream Handle.GetWaitingPod analog."""
        return self.waiting_pods.get(f"{namespace}/{name}")

    def iterate_over_waiting_pods(self):
        """upstream Handle.IterateOverWaitingPods analog."""
        return list(self.waiting_pods.values())

    def allow_waiting_pod(self, namespace: str, name: str, plugin: str) -> "ScheduleResult | None":
        """Plugin ``plugin`` approves the waiting pod; once every permit
        plugin has approved, the bind cycle completes (upstream
        waitingPod.Allow).  Returns the final result when binding ran."""
        wp = self.get_waiting_pod(namespace, name)
        if wp is None:
            return None
        wp.pending.discard(plugin)
        # an approved plugin's timer stops (upstream Allow cancels it)
        wp.deadlines.pop(plugin, None)
        if wp.pending:
            return None
        del self.waiting_pods[wp.key]
        res = self._finish_binding(wp.state, wp.pod, wp.node_name, {}, [], None)
        self.resolved_waiting.append((wp.pod, res))
        return res

    def reject_waiting_pod(self, namespace: str, name: str, message: str = "rejected") -> "ScheduleResult | None":
        """upstream waitingPod.Reject: unreserve and fail the pod."""
        wp = self.waiting_pods.pop(f"{namespace}/{name}", None)
        if wp is None:
            return None
        # the pod is already out of the map, so plugin cascades triggered
        # by this unreserve (gang teardown) terminate
        self._unreserve(wp.state, wp.pod, wp.node_name)
        res = ScheduleResult(status=Status.unschedulable(message))
        self.resolved_waiting.append((wp.pod, res))
        return res

    def expire_waiting_pods(self, now: "float | None" = None) -> dict[str, ScheduleResult]:
        """Reject every waiting pod whose earliest permit deadline passed
        (upstream rejects on timer expiry)."""
        now = self.clock() if now is None else now
        out: dict[str, ScheduleResult] = {}
        for key in [k for k, w in self.waiting_pods.items() if w.earliest_deadline() <= now]:
            ns, name = key.split("/", 1)
            res = self.reject_waiting_pod(ns, name, "pod rejected: permit wait timeout expired")
            if res is not None:
                out[key] = res
        return out

    # ------------------------------------------------------------- internals

    def _run_filters(self, state: CycleState, pod: Obj, ni: NodeInfo) -> "Status | None":
        """Run filter plugins in order; stop at first failure (upstream
        RunFilterPlugins semantics — later plugins don't run, so their
        entries are absent from the annotation, as in the reference)."""
        for wp in self.plugins["filter"]:
            status = wp.filter(state, pod, ni)
            if status is not None and not status.is_success():
                return status
        return None

    def _run_filters_with_nominated(
        self, state: CycleState, pod: Obj, ni: NodeInfo, snapshot: Snapshot
    ) -> "Status | None":
        """Upstream RunFilterPluginsWithNominatedPods: when equal-or-
        higher-priority pods are NOMINATED onto the node (preemption
        happened, victims evicted, nominee not yet bound), the pod must
        pass filters BOTH with those pods' resources accounted AND
        without them — otherwise it could steal the capacity preemption
        just freed for the nominee."""
        from kube_scheduler_simulator_tpu.plugins.intree.queue_bind import pod_priority

        me = pod["metadata"]
        nominated = [
            q
            for q in snapshot.nominated_pods(ni.name)
            if pod_priority(q) >= pod_priority(pod)
            and not (
                q["metadata"]["name"] == me["name"]
                and q["metadata"].get("namespace", "default") == me.get("namespace", "default")
            )
        ]
        if nominated:
            scratch = NodeInfo(ni.node)
            for p in ni.pods:
                scratch.add_pod(p)
            # cloned cycle state + AddPod extensions so STATE-based
            # plugins (InterPodAffinity, PodTopologySpread) see the
            # nominated pods too, not just node-resource readers
            cloned = state.clone()
            for q in nominated:
                scratch.add_pod(q)
                for wp in self.plugins["filter"]:
                    add = getattr(wp.original, "add_pod_to_state", None)
                    if add is not None:
                        add(cloned, pod, q, ni)
            status = self._run_filters(cloned, pod, scratch)
            if status is not None and not status.is_success():
                return status
        return self._run_filters(state, pod, ni)

    def _run_post_filters(self, state: CycleState, pod: Obj, diagnosis: dict[str, Status]) -> "str | None":
        for wp in self.plugins["post_filter"]:
            nominated, status = wp.post_filter(state, pod, diagnosis)
            if status is None or status.is_success():
                return nominated
        return None

    def _score_and_select(
        self, state: CycleState, pod: Obj, feasible: list[NodeInfo]
    ) -> "tuple[str | None, Status | None]":
        # PreScore: a non-success status aborts the cycle (upstream
        # RunPreScorePlugins fails scheduling on the first error).
        nodes = [ni.node for ni in feasible]
        for wp in self.plugins["pre_score"]:
            status = wp.pre_score(state, pod, nodes)
            if status is not None and not status.is_success():
                if status.is_skip():
                    continue
                return None, status

        totals: dict[str, int] = {ni.name: 0 for ni in feasible}
        for wp in self.plugins["score"]:
            raw: dict[str, int] = {}
            for ni in feasible:
                score, status = wp.score(state, pod, ni)
                if status is not None and not status.is_success():
                    score = 0
                raw[ni.name] = score
            wp.normalize_scores(state, pod, raw)
            weights = self.score_weight_override or self.score_weights
            weight = weights.get(wp.original.name, 1)
            for name, s in raw.items():
                totals[name] += s * weight

        # Extender prioritize pass (additive weighted scores).
        if self.extender_service is not None and self.extender_service.extenders:
            ext_totals = self.extender_service.run_prioritize(pod, nodes)
            for name, s in ext_totals.items():
                if name in totals:
                    totals[name] += s

        return self._select_host(totals), None

    def _select_host(self, totals: dict[str, int]) -> str:
        """Upstream selectHost: max score, uniform tie-break over tied
        maxima (reference mirrors the reservoir form at
        scheduler/scheduler.go:323-344).  The pick is the k-th tied
        candidate in visit order with k from the counter-keyed hash draw —
        bit-identical to the batch kernel's selection (ops/batch.py)."""
        best_score: "int | None" = None
        tied: list[str] = []
        for name, score in totals.items():
            if best_score is None or score > best_score:
                best_score = score
                tied = [name]
            elif score == best_score:
                tied.append(name)
        if not tied:
            return ""
        if self.tie_break != "reservoir" or len(tied) == 1:
            return tied[0]
        from kube_scheduler_simulator_tpu.utils.hashing import tie_break_draw

        return tied[tie_break_draw(self.seed, self._attempt) % len(tied)]

    def _unreserve(self, state: CycleState, pod: Obj, node_name: str) -> None:
        for wp in reversed(self.plugins["reserve"]):
            wp.unreserve(state, pod, node_name)

    def sort_pods(self, pods: list[Obj]) -> list[Obj]:
        """Order the activeQ by the QueueSort plugin (PrioritySort default).

        Ties (neither less(a,b) nor less(b,a)) MUST compare equal so the
        stable sort preserves arrival order.  The old comparator returned
        1 for ties ("a > b"), which is inconsistent (it also claims b > a)
        — Timsort then emits a length-dependent permutation of the tied
        group, so two otherwise-identical workloads whose creationTimestamps
        straddle a wall-clock second boundary differently scheduled in
        DIFFERENT orders (the test_mixed_everything_differential flake)."""
        qs = self.plugins["queue_sort"]
        if not qs:
            return list(pods)
        import functools

        less = qs[0].less

        def cmp(a: Obj, b: Obj) -> int:
            if less(a, b):
                return -1
            if less(b, a):
                return 1
            return 0

        return sorted(pods, key=functools.cmp_to_key(cmp))
