#!/usr/bin/env python
"""Host-path perf smoke: the fused streamed path must BEAT the serial
per-tick round loop on this host, by at least a generous committed
floor — the tier-1 step that turns a host-path perf regression (commit
bloat, renderer falling off the capsule path, overlap lost to an
accidental sync) into a loud failure instead of a quiet bench drift.

Runs the cfg13b-hostpath-v2 measurement (bench.run_profile_report) at
smoke size: the same steady-churn workload through both modes,
min-of-3 walls each, byte parity checked, per-wave stage profiles
attached.  The floor is deliberately WAY below the committed
BENCH_hostpath.json speedup (1.51x at full size on 1 core; 0.8x–1.7x
observed run-to-run at smoke size on this 1-vCPU host) so shared-host
noise can't flake tier-1, while a real regression — the fused path
losing badly to serial — still trips it with margin.

Exit 0 = fused/serial >= FLOOR, parity 0 mismatches, profiler engaged,
named stages >= 95% of the fused leg's span.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")

# the generous committed floor: fused must stay at least this fraction
# of serial throughput at smoke size.  The bar is "fused must not LOSE
# badly" (a real host-path regression lands well under 0.5x), NOT
# "reproduce the bench row under noise": at smoke size the ~3 s walls
# swing 0.8x–1.7x run-to-run on a shared 1-vCPU host even at min-of-3,
# so a tight floor would flake tier-1 on scheduler jitter alone.  The
# honest at-scale number lives in BENCH_hostpath.json (1.51x, 1 core).
FLOOR = 0.5


def main() -> int:
    import bench

    row = bench.run_profile_report(runs=3, quick=True)

    if row["parity_mismatches_fused_vs_serial"] != 0:
        print(
            f"perf-smoke: {row['parity_mismatches_fused_vs_serial']} parity "
            "mismatches between fused and serial runs",
            file=sys.stderr,
        )
        return 1
    ratio = row["fused_speedup_vs_serial"]
    if ratio < FLOOR:
        print(
            f"perf-smoke: fused path regressed — {ratio:.2f}x vs serial "
            f"(floor {FLOOR}): serial={row['wall_s_serial']}s "
            f"fused={row['wall_s_fused']}s",
            file=sys.stderr,
        )
        return 1
    if row["stream_waves_total"] < row["ticks"]:
        print(
            f"perf-smoke: streamed path never engaged — "
            f"waves={row['stream_waves_total']} over {row['ticks']} ticks",
            file=sys.stderr,
        )
        return 1
    # the profiler rode along on both modes and its stage vector
    # partitions each profiled wall (tests/test_profile.py pins the
    # exact invariant; here we just require it engaged and non-trivial)
    for mode in ("serial", "fused"):
        stages = row[f"profile_stages_{mode}"]
        if not stages or sum(s["seconds"] for s in stages.values()) <= 0.0:
            print(f"perf-smoke: profiler never engaged on the {mode} run", file=sys.stderr)
            return 1
    # the attribution invariant (ISSUE 20): on the fused leg the NAMED
    # stages — everything except the derived host_other remainder —
    # must cover >= 95% of span (union of record walls + orphan ambient
    # stamps: real clock time, overlap counted once).  This is what
    # makes the stage table trustworthy: a new hot-path cost that lands
    # outside every stamp shows up HERE as lost coverage, not as a
    # silently growing host_other nobody is looking at.  Structural,
    # not load-sensitive: coverage is about stamps existing, so it
    # holds at smoke size under contention (97-99% observed; serial
    # runs ~95-98% and is deliberately not pinned — its between-round
    # queue work is orphan-stamped from outside any wave record).
    cov = row["profile_coverage_fused"]
    if cov["named_share_pct"] < 95.0:
        print(
            f"perf-smoke: fused attribution coverage regressed — named "
            f"stages cover {cov['named_share_pct']}% of span "
            f"(floor 95%): {cov}",
            file=sys.stderr,
        )
        return 1
    print(
        f"perf-smoke OK: fused {ratio:.2f}x vs serial (floor {FLOOR}) — "
        f"serial={row['wall_s_serial']}s fused={row['wall_s_fused']}s, "
        f"{row['scheduled']} pods, parity 0 mismatches, "
        f"waves={row['stream_waves_total']}, "
        f"named {cov['named_share_pct']}% of span (floor 95%)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
