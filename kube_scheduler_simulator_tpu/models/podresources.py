"""Pod effective resource-request computation.

Upstream semantics (k8s resource helpers used by NodeResourcesFit's
computePodResourceRequest, which the reference traces through its wrapped
plugins): effective request = max(max(initContainers), sum(containers))
per resource, plus pod overhead.

Canonical internal units (shared with the TPU feature encoder):
- cpu            -> milli-cores (MilliValue)
- memory         -> bytes (Value)
- ephemeral-storage -> bytes
- everything else (hugepages, extended resources) -> Value
"""

from __future__ import annotations

from typing import Any, Mapping

from kube_scheduler_simulator_tpu.utils.quantity import milli_value, value

Obj = Mapping[str, Any]

CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"


def is_fit_resource(r: str) -> bool:
    """Whether NodeResourcesFit checks resource ``r`` (upstream
    InsufficientResource: cpu/memory/ephemeral-storage, hugepages-*,
    attachable-volumes-*, extended "<domain>/<name>" resources).  The
    single source of truth for BOTH the sequential Fit plugin
    (plugins/intree/noderesources.py) and the batch encoder
    (ops/encode.py) — they must never diverge."""
    return (
        r in (CPU, MEMORY, EPHEMERAL_STORAGE)
        or "/" in r
        or r.startswith("hugepages-")
        or r.startswith("attachable-volumes-")
    )


def _to_internal(resource: str, q: Any) -> int:
    if resource == CPU:
        return milli_value(q)
    return value(q)


def _requests_of(container: Obj) -> dict[str, int]:
    reqs = (container.get("resources") or {}).get("requests") or {}
    return {r: _to_internal(r, q) for r, q in reqs.items()}


def pod_resource_request(pod: Obj) -> dict[str, int]:
    """Effective resource request of a pod in canonical internal units."""
    spec = pod.get("spec") or {}
    total: dict[str, int] = {}
    for c in spec.get("containers") or []:
        for r, v in _requests_of(c).items():
            total[r] = total.get(r, 0) + v
    for c in spec.get("initContainers") or []:
        for r, v in _requests_of(c).items():
            if v > total.get(r, 0):
                total[r] = v
    for r, q in (spec.get("overhead") or {}).items():
        total[r] = total.get(r, 0) + _to_internal(r, q)
    return total


def node_allocatable(node: Obj) -> dict[str, int]:
    """Node allocatable in canonical internal units (falls back to capacity)."""
    status = node.get("status") or {}
    alloc = status.get("allocatable") or status.get("capacity") or {}
    return {r: _to_internal(r, q) for r, q in alloc.items()}
