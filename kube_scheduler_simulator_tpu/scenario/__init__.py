"""Scenario replay engine (KEP-140 scenario-based simulation).

The reference only scaffolds this (scenario/ kubebuilder project with an
empty Reconcile, reference scenario/controllers/scenario_controller.go:48-55);
the full design lives in keps/140-scenario-based-simulation/README.md and
is implemented here as a first-class engine over the in-memory store.
"""

from kube_scheduler_simulator_tpu.scenario.engine import ScenarioEngine
from kube_scheduler_simulator_tpu.scenario.operator import ScenarioOperator
from kube_scheduler_simulator_tpu.scenario.result import allocation_rate, node_utilization
from kube_scheduler_simulator_tpu.scenario.simulation import run_scheduler_simulation
from kube_scheduler_simulator_tpu.scenario.simulator_operator import SimulatorOperator

__all__ = [
    "ScenarioEngine",
    "ScenarioOperator",
    "SimulatorOperator",
    "allocation_rate",
    "node_utilization",
    "run_scheduler_simulation",
]
