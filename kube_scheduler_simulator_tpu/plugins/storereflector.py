"""Store reflector: copies scheduling results onto Pod annotations.

Rebuild of the reference's shared reflector (reference
simulator/scheduler/storereflector/storereflector.go:21-167): it holds N
ResultStores, hooks pod updates, and when a pod finishes a scheduling
attempt merges every store's results into the pod's annotations, appends
the merged map to the ``result-history`` annotation, then deletes the
stores' entries.  The reference needs informer goroutines + conflict-retry;
our store delivers update hooks synchronously, but the retry loop is kept
for the kube-backed adapter.
"""

from __future__ import annotations

import json
from sys import intern
from typing import Any

from kube_scheduler_simulator_tpu.native import fastjson as _fastjson
from kube_scheduler_simulator_tpu.plugins import annotations as anno
from kube_scheduler_simulator_tpu.plugins.resultstore import ResultStore
from kube_scheduler_simulator_tpu.utils.gojson import go_marshal, go_string, go_string_key
from kube_scheduler_simulator_tpu.utils.retry import ConflictError, retry_on_conflict

Obj = dict[str, Any]

RESULT_STORE_KEY = "PluginResultStoreKey"
EXTENDER_STORE_KEY = "ExtenderResultStoreKey"


class StoreReflector:
    def __init__(self) -> None:
        self._stores: dict[str, Any] = {}
        self._in_flush: set[str] = set()
        self._pending: dict[str, Obj] = {}
        # pod key → (length, last-64-chars) of the result-history value
        # this reflector last wrote.  Trust for the byte-splice append
        # requires the CURRENT value to match both: a foreign write (user
        # PUT, import) of even the same length would have to reproduce the
        # exact tail of the last entry to be spliced onto unvalidated.
        # Entries are dropped when the pod is deleted (a recreated pod
        # must not inherit trust for an unrelated annotation value).
        self._history_written: dict[str, tuple[int, str]] = {}

    def add_result_store(self, store: Any, key: str) -> None:
        self._stores[key] = store

    def remove_result_store(self, key: str) -> None:
        """Drop a registered store (scheduler restarts rebuild per-profile
        stores; stale ones must not keep merging results)."""
        self._stores.pop(key, None)

    def get_result_store(self, key: str) -> "Any | None":
        return self._stores.get(key)

    def result_stores(self) -> list[Any]:
        return list(self._stores.values())

    # ------------------------------------------------------------------ hook

    def register_to_cluster_store(self, cluster_store: Any) -> None:
        """ResisterResultSavingToInformer analog (storereflector.go:55-72).

        The reference's informer handler runs asynchronously, after the
        scheduling cycle that triggered the update has finished recording
        (including the Bind result).  We reproduce that ordering by queueing
        the pod here and flushing from ``flush_all`` at cycle end.
        """
        cluster_store.on_update("pods", lambda old, new: self._on_pod_update(new))
        cluster_store.subscribe(["pods"], self._on_pod_event)

    def _on_pod_event(self, ev: Any) -> None:
        if ev.type == "DELETED":
            meta = ev.obj["metadata"]
            key = f"{meta.get('namespace', 'default')}/{meta['name']}"
            self._history_written.pop(key, None)
            self._pending.pop(key, None)

    def _on_pod_update(self, pod: Obj) -> None:
        ns = pod["metadata"].get("namespace", "default")
        name = pod["metadata"]["name"]
        self._pending[f"{ns}/{name}"] = pod

    def flush_all(self, cluster_store: Any, skip_keys: "set[str] | None" = None) -> None:
        """Flush every queued pod's results to its annotations.

        ``skip_keys`` (ns/name) stay queued WITH their stored results —
        pods parked at Permit must keep accumulating until the binding
        cycle finishes, exactly as the reference's reflector only fires on
        pod-update events (which a waiting pod hasn't produced yet)."""
        requeue: dict[str, Obj] = {}
        while self._pending:
            key, pod = self._pending.popitem()
            if skip_keys and key in skip_keys:
                requeue[key] = pod
                continue
            self.flush_pod(cluster_store, pod)
        self._pending.update(requeue)

    # ----------------------------------------------------------------- flush

    def flush_pod(self, cluster_store: Any, pod: Obj) -> None:
        """storeAllResultToPodFunc analog (storereflector.go:78-146).

        The annotation write itself fires another pod-update event; in the
        reference the (async) informer sees it after DeleteData so it
        no-ops, here the synchronous hook needs an explicit reentrancy
        guard plus delete-before-write.
        """
        ns = pod["metadata"].get("namespace", "default")
        name = pod["metadata"]["name"]
        key = f"{ns}/{name}"
        if key in self._in_flush:
            return

        merged: dict[str, str] = {}
        escs: dict[str, str] = {}
        had_any = False
        for store in self._stores.values():
            if not store.has_result(pod):
                continue
            result = store.get_stored_result(pod)
            if result:
                had_any = True
                merged.update(result)
                getter = getattr(store, "get_stored_escs", None)
                if getter is not None:
                    escs.update(getter(pod))
        if not had_any:
            return
        for store in self._stores.values():
            store.delete_data(pod)

        def apply() -> None:
            try:
                fresh = cluster_store.get("pods", name, ns)
            except KeyError:
                return
            annotations = dict(fresh["metadata"].get("annotations") or {})
            annotations.update(merged)
            existing = (fresh["metadata"].get("annotations") or {}).get(anno.RESULT_HISTORY)
            rec = self._history_written.get(key)
            trusted = (
                rec is not None
                and existing is not None
                and rec[0] == len(existing)
                and existing[-64:] == rec[1]
            )
            new_history = _updated_history(existing, merged, trusted=trusted, escs=escs)
            annotations[anno.RESULT_HISTORY] = new_history
            fresh["metadata"]["annotations"] = annotations
            cluster_store.update("pods", fresh, owned=True)
            self._history_written[key] = (len(new_history), new_history[-64:])

        self._in_flush.add(key)
        try:
            retry_on_conflict(apply, sleep=lambda _: None)
        except ConflictError:
            pass
        finally:
            self._in_flush.discard(key)

    def flush_wave(self, cluster_store: Any, pods: "list[Obj]") -> None:
        """``flush_pod`` for a whole commit wave in ONE store transaction.

        Byte-identical to flushing each pod individually — same store
        merge, same history splice, same trust bookkeeping — but the
        wave's annotation patches commit through the store's bulk-apply
        entry point: one lock acquisition and one batched watch-event
        dispatch instead of N get/update round-trips.  Each pod's
        read-modify-write runs atomically under the store lock, so a
        mid-wave conflict (the per-pod path's retry_on_conflict case)
        cannot occur; pods deleted since the kernel decided are skipped,
        exactly as flush_pod's vanished-pod path does."""
        wave: list[Obj] = []
        wave_keys: list[str] = []
        for pod in pods:
            ns = pod["metadata"].get("namespace", "default")
            name = pod["metadata"]["name"]
            # interned: the same pods retry across waves, and the key
            # doubles as the _history_written index — one str object
            # per pod for the store's whole lifetime
            key = intern(f"{ns}/{name}")
            if key in self._in_flush:
                continue
            wave.append(pod)
            wave_keys.append(key)
        if not wave:
            return
        # columnar drain: ONE lock round-trip per result store for the
        # whole wave (get_stored_result + escs + delete_data fused),
        # cells owned by this frame.  Foreign duck-typed stores without
        # the wave API keep the per-pod path, in registration order so
        # later stores still override earlier keys.
        stores = list(self._stores.values())
        cols: list[Any] = [
            drain(wave)
            if (drain := getattr(store, "drain_wave_results", None)) is not None
            else store
            for store in stores
        ]
        muts: list[tuple[str, str, Any]] = []
        keys: list[str] = []
        for i, pod in enumerate(wave):
            ns = pod["metadata"].get("namespace", "default")
            name = pod["metadata"]["name"]
            key = wave_keys[i]
            merged: "dict[str, str] | None" = None
            escs: "dict[str, str] | None" = None
            for col in cols:
                if isinstance(col, list):
                    cell = col[i]
                    if cell is None:
                        continue
                    if merged is None:
                        merged, escs = cell  # owned: adopt without copy
                    else:
                        merged.update(cell[0])
                        escs.update(cell[1])
                elif col.has_result(pod):
                    result = col.get_stored_result(pod)
                    if result:
                        if merged is None:
                            merged, escs = {}, {}
                        merged.update(result)
                        getter = getattr(col, "get_stored_escs", None)
                        if getter is not None:
                            escs.update(getter(pod))
            if merged is None:
                continue
            for col, store in zip(cols, stores):
                if col is store:  # drained cols already popped their data
                    store.delete_data(pod)

            def mutate(cur: Obj, key=key, merged=merged, escs=escs) -> Obj:
                # copy-on-write along the changed path only (bulk_update's
                # read-only contract): everything but metadata/annotations
                # is shared with the replaced object
                meta = cur["metadata"]
                annotations = dict(meta.get("annotations") or {})
                annotations.update(merged)
                existing = (meta.get("annotations") or {}).get(anno.RESULT_HISTORY)
                rec = self._history_written.get(key)
                trusted = (
                    rec is not None
                    and existing is not None
                    and rec[0] == len(existing)
                    and existing[-64:] == rec[1]
                )
                new_history = _updated_history(existing, merged, trusted=trusted, escs=escs)
                annotations[anno.RESULT_HISTORY] = new_history
                self._history_written[key] = (len(new_history), new_history[-64:])
                return {**cur, "metadata": {**meta, "annotations": annotations}}

            muts.append((name, ns, mutate))
            keys.append(key)
        if not muts:
            return
        self._in_flush.update(keys)
        try:
            cluster_store.bulk_update("pods", muts)
        finally:
            self._in_flush.difference_update(keys)


# annotation keys repeat per pod — marshal each key fragment once
_KEY_FRAGS: dict[str, str] = {}


def _entry_parts(new_results: dict[str, str], escs: "dict[str, str] | None" = None):
    """(key fragments, values, escaped twins) for a history entry, in
    go_marshal key order — the ONE place that decides which keys enter
    the entry.  ``escs`` maps annotation keys to pre-escaped bodies (the
    batch engine emits them alongside the plain values; escaping the
    quote-dense megabyte documents at this point would cost more than
    the whole splice)."""
    keys = sorted(k for k in new_results if k != anno.RESULT_HISTORY)
    frags = []
    for k in keys:
        frag = _KEY_FRAGS.get(k)
        if frag is None:
            frag = _KEY_FRAGS[k] = go_string_key(k)
        frags.append(frag)
    vals = [new_results[k] for k in keys]
    esc_list = [escs.get(k) if escs else None for k in keys]
    return frags, vals, esc_list


def _entry_json(new_results: dict[str, str], escs: "dict[str, str] | None" = None) -> str:
    """go_marshal of the history entry, assembled from fragments: the
    entry is a flat map whose VALUES are the (often megabyte) annotation
    bodies just built — the native single-pass escape (or ``go_string``'s
    replace chain) avoids re-scanning everything through json.dumps, and
    pre-escaped twins (``escs``) embed without any scan at all."""
    frags, vals, esc_list = _entry_parts(new_results, escs)
    entry = None
    if _fastjson is not None:
        try:
            entry = _fastjson.history_entry(
                frags, vals, [e if isinstance(e, str) else None for e in esc_list]
            )
        except UnicodeEncodeError:  # lone surrogates: take the Python path
            entry = None
    if entry is None:
        # deferred (tuple) twins can't embed here — escape the plain value
        entry = "{" + ",".join(
            frag + ('"' + e + '"' if isinstance(e, str) else go_string(v))
            for frag, v, e in zip(frags, vals, esc_list)
        ) + "}"
    return entry


def _updated_history(
    existing: "str | None",
    new_results: dict[str, str],
    trusted: bool = False,
    escs: "dict[str, str] | None" = None,
) -> str:
    """updateResultHistory analog (storereflector.go:148-167): history is a
    JSON array of annotation maps, one per scheduling attempt.

    With ``trusted`` (the reflector wrote this pod's history itself since
    boot and the stored value still carries its exact length + tail), the
    new attempt is SPLICED onto the existing array bytes instead of
    parse-append-re-marshal: prior attempts embed the full (often
    megabyte-scale) annotation set, and re-escaping them on every attempt
    makes history maintenance quadratic.  Splicing is byte-identical
    because the existing string is this function's own compact output.
    Untrusted values (imported snapshots, foreign annotations) are
    parse-validated; corrupt or non-array values reset to a fresh
    single-entry history, as before."""
    if _fastjson is not None and (
        not existing
        or (trusted and (existing == "[]" or (existing.startswith("[{") and existing.endswith("}]"))))
    ):
        # one C buffer builds splice + entry together (no intermediate
        # entry string, no Python concat of the megabyte history).  The
        # megabyte filter/score values embed from DEFERRED twin specs
        # (batch engine) — their escaped bytes are emitted here, exactly
        # once, straight into the trail — or from pre-escaped str twins
        # where a caller still passes them.
        frags, vals, esc_list = _entry_parts(new_results, escs)
        try:
            out = _fastjson.history_append2(existing or None, frags, vals, esc_list)
        except UnicodeEncodeError:
            out = None
        if out is not None:
            return out
    entry_json = _entry_json(new_results, escs)
    if existing:
        if trusted:
            if existing == "[]":
                return "[" + entry_json + "]"
            if existing.startswith("[{") and existing.endswith("}]"):
                return existing[:-1] + "," + entry_json + "]"
        try:  # foreign/corrupt annotation: fall back to parse-append
            history = json.loads(existing)
        except json.JSONDecodeError:
            history = []
        if not isinstance(history, list):
            history = []
        if not history:
            return "[" + entry_json + "]"
        # re-marshal the validated prior attempts, splice the new entry
        return go_marshal(history)[:-1] + "," + entry_json + "]"
    return "[" + entry_json + "]"
