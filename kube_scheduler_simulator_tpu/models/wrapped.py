"""Wrapped (debuggable) plugins: delegate to the original, record results.

Rebuild of the reference's core wrappedPlugin (reference
simulator/scheduler/plugin/wrappedplugin.go:253-765): every plugin is
wrapped under the name ``<Original>Wrapped``; each extension-point call
delegates to the original and records the outcome in the ResultStore, with
optional user Before/After extender hooks per point (reference
wrappedplugin.go:47-171 defines the 11 extender interfaces — here a single
duck-typed extender object with ``before_<point>`` / ``after_<point>``
methods plays that role, created per-plugin via a PluginExtenderInitializer
receiving the shared store).
"""

from __future__ import annotations

from typing import Any, Callable

from kube_scheduler_simulator_tpu.models.framework import Code, CycleState, Status
from kube_scheduler_simulator_tpu.plugins.resultstore import (
    PASSED_FILTER_MESSAGE,
    SUCCESS_MESSAGE,
    WAIT_MESSAGE,
    ResultStore,
)

Obj = dict[str, Any]

PLUGIN_SUFFIX = "Wrapped"


def plugin_name(name: str) -> str:
    return name + PLUGIN_SUFFIX


def original_name(wrapped: str) -> str:
    return wrapped[: -len(PLUGIN_SUFFIX)] if wrapped.endswith(PLUGIN_SUFFIX) else wrapped


def _ns(pod: Obj) -> str:
    return pod["metadata"].get("namespace", "default")


def _name(pod: Obj) -> str:
    return pod["metadata"]["name"]


def _status_message(status: "Status | None") -> str:
    if status is None or status.is_success():
        return SUCCESS_MESSAGE
    if status.is_wait():
        return WAIT_MESSAGE
    return status.message()


class WrappedPlugin:
    """Wraps one plugin instance; exposes the same extension points."""

    def __init__(self, store: ResultStore, original: Any, extender: Any = None):
        self.store = store
        self.original = original
        self.extender = extender
        self.name = plugin_name(original.name)

    # ---- capability probes (mirror the NewWrappedPlugin type asserts)

    def implements(self, point: str) -> bool:
        return hasattr(self.original, point)

    def _hook(self, hook_name: str) -> "Callable | None":
        if self.extender is None:
            return None
        return getattr(self.extender, hook_name, None)

    # ----------------------------------------------------------- extension points

    def pre_filter(self, state: CycleState, pod: Obj):
        before = self._hook("before_pre_filter")
        if before is not None:
            result, status = before(state, pod)
            if status is not None and not status.is_success():
                return result, status
        result, status = self.original.pre_filter(state, pod)
        self.store.add_pre_filter_result(
            _ns(pod), _name(pod), self.original.name, _status_message(status), result
        )
        after = self._hook("after_pre_filter")
        if after is not None:
            return after(state, pod, result, status)
        return result, status

    def filter(self, state: CycleState, pod: Obj, node_info: Any) -> "Status | None":
        before = self._hook("before_filter")
        if before is not None:
            status = before(state, pod, node_info)
            if status is not None and not status.is_success():
                return status
        status = self.original.filter(state, pod, node_info)
        msg = PASSED_FILTER_MESSAGE if status is None or status.is_success() else status.message()
        self.store.add_filter_result(_ns(pod), _name(pod), node_info.name, self.original.name, msg)
        after = self._hook("after_filter")
        if after is not None:
            return after(state, pod, node_info, status)
        return status

    def post_filter(self, state: CycleState, pod: Obj, filtered_node_status_map: dict[str, Status]):
        before = self._hook("before_post_filter")
        if before is not None:
            nominated, status = before(state, pod, filtered_node_status_map)
            if status is not None and not status.is_success():
                return nominated, status
        nominated, status = self.original.post_filter(state, pod, filtered_node_status_map)
        self.store.add_post_filter_result(
            _ns(pod),
            _name(pod),
            nominated or "",
            self.original.name,
            sorted(filtered_node_status_map.keys()),
        )
        after = self._hook("after_post_filter")
        if after is not None:
            return after(state, pod, filtered_node_status_map, nominated, status)
        return nominated, status

    def pre_score(self, state: CycleState, pod: Obj, nodes: list[Obj]) -> "Status | None":
        before = self._hook("before_pre_score")
        if before is not None:
            status = before(state, pod, nodes)
            if status is not None and not status.is_success():
                return status
        status = self.original.pre_score(state, pod, nodes)
        self.store.add_pre_score_result(_ns(pod), _name(pod), self.original.name, _status_message(status))
        after = self._hook("after_pre_score")
        if after is not None:
            return after(state, pod, nodes, status)
        return status

    def score(self, state: CycleState, pod: Obj, node_info: Any) -> "tuple[int, Status | None]":
        before = self._hook("before_score")
        if before is not None:
            score, status = before(state, pod, node_info.name)
            if status is not None and not status.is_success():
                return score, status
        score, status = self.original.score(state, pod, node_info)
        self.store.add_score_result(_ns(pod), _name(pod), node_info.name, self.original.name, score)
        after = self._hook("after_score")
        if after is not None:
            return after(state, pod, node_info.name, score, status)
        return score, status

    def normalize_scores(self, state: CycleState, pod: Obj, scores: dict[str, int]) -> "Status | None":
        before = self._hook("before_normalize_score")
        if before is not None:
            status = before(state, pod, scores)
            if status is not None and not status.is_success():
                return status
        status = None
        if hasattr(self.original, "normalize_scores"):
            status = self.original.normalize_scores(state, pod, scores)
        after = self._hook("after_normalize_score")
        if after is not None:
            status = after(state, pod, scores, status)
        for node_name, s in scores.items():
            self.store.add_normalized_score_result(_ns(pod), _name(pod), node_name, self.original.name, s)
        return status

    def reserve(self, state: CycleState, pod: Obj, node_name: str) -> "Status | None":
        before = self._hook("before_reserve")
        if before is not None:
            status = before(state, pod, node_name)
            if status is not None and not status.is_success():
                return status
        status = None
        if hasattr(self.original, "reserve"):
            status = self.original.reserve(state, pod, node_name)
        if status is None or status.is_success():
            self.store.add_selected_node(_ns(pod), _name(pod), node_name)
        self.store.add_reserve_result(_ns(pod), _name(pod), self.original.name, _status_message(status))
        after = self._hook("after_reserve")
        if after is not None:
            return after(state, pod, node_name, status)
        return status

    def unreserve(self, state: CycleState, pod: Obj, node_name: str) -> None:
        if hasattr(self.original, "unreserve"):
            self.original.unreserve(state, pod, node_name)

    def permit(self, state: CycleState, pod: Obj, node_name: str):
        before = self._hook("before_permit")
        if before is not None:
            status, timeout = before(state, pod, node_name)
            if status is not None and not status.is_success():
                return status, timeout
        status, timeout = self.original.permit(state, pod, node_name)
        self.store.add_permit_result(
            _ns(pod), _name(pod), self.original.name, _status_message(status), timeout
        )
        after = self._hook("after_permit")
        if after is not None:
            return after(state, pod, node_name, status, timeout)
        return status, timeout

    def pre_bind(self, state: CycleState, pod: Obj, node_name: str) -> "Status | None":
        before = self._hook("before_pre_bind")
        if before is not None:
            status = before(state, pod, node_name)
            if status is not None and not status.is_success():
                return status
        status = self.original.pre_bind(state, pod, node_name)
        self.store.add_pre_bind_result(_ns(pod), _name(pod), self.original.name, _status_message(status))
        after = self._hook("after_pre_bind")
        if after is not None:
            return after(state, pod, node_name, status)
        return status

    def bind(self, state: CycleState, pod: Obj, node_name: str) -> "Status | None":
        before = self._hook("before_bind")
        if before is not None:
            status = before(state, pod, node_name)
            if status is not None and not status.is_success():
                return status
        status = self.original.bind(state, pod, node_name)
        self.store.add_bind_result(_ns(pod), _name(pod), self.original.name, _status_message(status))
        after = self._hook("after_bind")
        if after is not None:
            return after(state, pod, node_name, status)
        return status

    def post_bind(self, state: CycleState, pod: Obj, node_name: str) -> None:
        if hasattr(self.original, "post_bind"):
            self.original.post_bind(state, pod, node_name)

    def less(self, pod_info1: Obj, pod_info2: Obj) -> bool:
        return self.original.less(pod_info1, pod_info2)
