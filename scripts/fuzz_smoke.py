#!/usr/bin/env python
"""Differential fuzz smoke (tier-1): a bounded seeded sweep of composite
scenarios through the byte-parity differential runner, plus the chaos
and mesh legs — the adversary every future PR inherits (docs/fuzzing.md).

Bounded mode (default): a FIXED seed list drives ``KSS_FUZZ_SCENARIOS``
(default 25) generated scenarios, each composing >= 3 subsystems
(gang / preemption / autoscale / churn / retune), through
batch-vs-oracle and streamed-vs-serial byte diffs; then one scenario
re-runs with injected kernel failures (parity must hold and the degrade
must be counted) and one through a ``KSS_MESH_DEVICES=2`` sharded pair.
Any unexplained byte divergence exits 1 — after confirming it standalone,
shrinking it (``KSS_FUZZ_SHRINK_STEPS`` checks), and dumping the
minimized repro + verdict to /tmp for triage and fixture promotion.

Long-haul mode (nightlies): ``KSS_FUZZ_BUDGET=<seconds>`` keeps
generating fresh scenario indices until the wall-clock budget runs out.

Exit 0 = every scenario at parity; nonzero = divergence (or a harness
invariant broke).
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:  # the axon plugin dials the TPU tunnel even when CPU-pinned
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

from kube_scheduler_simulator_tpu.fuzz import (  # noqa: E402
    MESH_STREAM,
    CoverageMap,
    FuzzHarness,
    KernelChaos,
    fuzz_knobs,
    generate_scenario,
    run_differential,
    shrink,
)
from kube_scheduler_simulator_tpu.fuzz import chaos as chaos_mod  # noqa: E402


def triage_divergence(scn, kinds, shrink_budget: int) -> dict:
    """Confirm a divergence standalone (fresh services), shrink it, dump
    the minimized repro to /tmp — the triage trail docs/fuzzing.md walks."""
    comparisons = tuple(kinds)
    # ONE standalone harness for the confirmation AND every shrink check:
    # a fresh harness per check would recompile 2-4 service pairs up to
    # KSS_FUZZ_SHRINK_STEPS times and blow the tier-1 step budget before
    # the repro dump lands; reset() keeps each candidate internally
    # aligned (both pair members replay the same candidate sequence)
    standalone = FuzzHarness()

    def still_fails(s):
        v, _ = run_differential(s, standalone, comparisons=comparisons)
        return bool(v["divergences"])

    out: dict = {"scenario": scn["name"], "kinds": list(kinds)}
    if not still_fails(scn):
        out["standalone"] = "did NOT reproduce standalone (cross-scenario context?)"
        return out
    mini, stats = shrink(scn, still_fails, max_checks=shrink_budget)
    out["standalone"] = "reproduced"
    out["shrink_steps"] = stats["steps"]
    path = f"/tmp/kss_fuzz_{scn['name']}.json"
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"kinds": list(kinds), "scenario": mini}, f, sort_keys=True, indent=2)
    out["repro"] = path
    return out


def main() -> int:
    knobs = fuzz_knobs()
    t0 = time.monotonic()
    harness = FuzzHarness()
    cov = CoverageMap()
    report = {"scenarios": 0, "divergences": {}, "shrink_steps": 0}
    failures: list[dict] = []
    scenarios: list[dict] = []

    def judge(scn) -> None:
        if len(scn["features"]) < 3:
            raise AssertionError(f"{scn['name']} composes only {scn['features']}")
        v, _states = run_differential(scn, harness)
        scenarios.append(scn)
        report["scenarios"] += 1
        for kind in v["divergences"]:
            report["divergences"][kind] = report["divergences"].get(kind, 0) + 1
        if v["divergences"]:
            print(f"fuzz-smoke DIVERGENCE {scn['name']} {v['divergences']}", file=sys.stderr)
            print(json.dumps(v["comparisons"], indent=1)[:4000], file=sys.stderr)
            tri = triage_divergence(scn, v["divergences"], knobs["shrink_steps"])
            report["shrink_steps"] += tri.get("shrink_steps", 0)
            failures.append(tri)
            print(f"fuzz-smoke triage: {json.dumps(tri)}", file=sys.stderr)

    if knobs["budget_s"] > 0:
        # long-haul: fresh indices until the budget is spent
        i = 0
        while time.monotonic() - t0 < knobs["budget_s"]:
            judge(generate_scenario(knobs["seed"], i, coverage=cov))
            i += 1
    else:
        # bounded tier-1 mode: a fixed seed list (seed, seed+1)
        seeds = (knobs["seed"], knobs["seed"] + 1)
        per_seed = (knobs["scenarios"] + len(seeds) - 1) // len(seeds)
        for seed in seeds:
            for i in range(per_seed):
                judge(generate_scenario(seed, i, coverage=cov))

    # ---- chaos leg: injected kernel failures must degrade, not diverge
    chaos_scn = generate_scenario(
        knobs["seed"] + 7, 0, features=frozenset({"preemption", "churn", "retune"})
    )
    trips = {"n": 0}
    _orig_exit = chaos_mod.KernelChaos.__exit__

    def _spy_exit(self, *exc):
        trips["n"] += self.trips
        return _orig_exit(self, *exc)

    chaos_mod.KernelChaos.__exit__ = _spy_exit
    try:
        v, _ = run_differential(
            chaos_scn, harness,
            chaos={"roles": ["batch", "stream-on"], "fail_events": [0, 3]},
        )
    finally:
        chaos_mod.KernelChaos.__exit__ = _orig_exit
    if v["divergences"]:
        print(f"fuzz-smoke: CHAOS run diverged: {v['divergences']}", file=sys.stderr)
        return 1
    if trips["n"] < 2:
        print(f"fuzz-smoke: chaos never tripped (trips={trips['n']})", file=sys.stderr)
        return 1
    explained = {k: n for c in v["comparisons"] for k, n in c["explained"].items()}
    if not any("kernel error" in r for m in explained.values() for r in m):
        print(f"fuzz-smoke: chaos degrade not counted: {explained}", file=sys.stderr)
        return 1
    report["scenarios"] += 1

    # ---- mesh leg: one scenario sharded over a 2-device virtual mesh
    shard_scn = generate_scenario(
        knobs["seed"] + 8, 0, features=frozenset({"preemption", "churn", "retune"})
    )
    v, _ = run_differential(shard_scn, harness, comparisons=("shard-vs-single",))
    if v["divergences"]:
        print("fuzz-smoke: shard-vs-single diverged", file=sys.stderr)
        print(json.dumps(v["comparisons"], indent=1)[:4000], file=sys.stderr)
        report["divergences"]["shard-vs-single"] = (
            report["divergences"].get("shard-vs-single", 0) + 1
        )
        failures.append({"scenario": shard_scn["name"], "kinds": ["shard-vs-single"]})
    report["scenarios"] += 1
    _store, shard_svc = harness.service("default", "shard")
    if shard_svc.metrics()["sharded_dispatches_total"] <= 0:
        print("fuzz-smoke: the shard leg never sharded a dispatch", file=sys.stderr)
        return 1

    # ---- mesh × stream leg: the fused fast path (sharded engines on a
    # STREAMED feed vs serial single-device) — drives the PR 13 fusion
    # from day one, coverage-tagged as an execution-mode bucket
    fuse_scn = generate_scenario(
        knobs["seed"] + 9, 0, features=frozenset({"preemption", "churn", "retune"})
    )
    v, _ = run_differential(fuse_scn, harness, comparisons=("shard-stream-vs-serial",))
    cov.note_exec(fuse_scn["features"], MESH_STREAM)
    if v["divergences"]:
        print("fuzz-smoke: shard-stream-vs-serial diverged", file=sys.stderr)
        print(json.dumps(v["comparisons"], indent=1)[:4000], file=sys.stderr)
        report["divergences"]["shard-stream-vs-serial"] = (
            report["divergences"].get("shard-stream-vs-serial", 0) + 1
        )
        failures.append({"scenario": fuse_scn["name"], "kinds": ["shard-stream-vs-serial"]})
    report["scenarios"] += 1
    _store_f, fuse_svc = harness.service("default", "shard-stream")
    fuse_m = fuse_svc.metrics()
    if fuse_m["sharded_dispatches_total"] <= 0:
        print("fuzz-smoke: the mesh-stream leg never sharded a dispatch", file=sys.stderr)
        return 1
    if fuse_m["stream_waves_total"] <= 0:
        print("fuzz-smoke: the mesh-stream leg never streamed a wave", file=sys.stderr)
        return 1

    # ---- process-kill leg: the crash adversary (fuzz/chaos.py
    # ProcessChaos + state/journal.py): a generated composite scenario
    # runs journaled in a subprocess, is SIGKILLed at seeded
    # journal-record indices, recovers in a fresh process, finishes, and
    # must byte-match an uninterrupted subprocess run — with zero torn
    # records and zero partially-bound gangs at the recovery point
    from kube_scheduler_simulator_tpu.fuzz import ProcessChaos

    crash_scn = generate_scenario(
        knobs["seed"] + 10, 0, features=frozenset({"preemption", "churn", "retune"})
    )
    cv = ProcessChaos(
        crash_scn, kill_records=(knobs["seed"] + 13, 7), child_timeout_s=240
    ).run()
    report["scenarios"] += 1
    # second composite: gang × autoscale × churn — the features whose
    # process state (parked quorums, unneeded-streak timers) burned the
    # most recovery bugs during bring-up; one mid-run kill point
    gang_scn = generate_scenario(
        knobs["seed"] + 11, 0, features=frozenset({"gang", "autoscale", "churn"})
    )
    gv = ProcessChaos(gang_scn, kill_records=(55,), child_timeout_s=240).run()
    report["scenarios"] += 1
    if gv["divergences"] or gv["truncated_records"] or gv["partial_gangs"]:
        print(
            f"fuzz-smoke: gang ProcessChaos leg broke: div={gv['divergences']} "
            f"torn={gv['truncated_records']} partial_gangs={gv['partial_gangs']}",
            file=sys.stderr,
        )
        print(json.dumps(gv["first_mismatch"], indent=1)[:4000], file=sys.stderr)
        return 1
    if cv["truncated_records"] or cv["partial_gangs"]:
        print(
            f"fuzz-smoke: ProcessChaos invariants broke: torn={cv['truncated_records']} "
            f"partial_gangs={cv['partial_gangs']}",
            file=sys.stderr,
        )
        return 1
    if cv["divergences"]:
        print(
            f"fuzz-smoke: ProcessChaos diverged at kill points {cv['divergences']}",
            file=sys.stderr,
        )
        print(json.dumps(cv["first_mismatch"], indent=1)[:4000], file=sys.stderr)
        report["divergences"]["process-crash"] = len(cv["divergences"])
        # shrink through the SAME ddmin machinery as the differential
        # legs — still_fails re-runs the whole kill/recover cycle, so the
        # check budget is deliberately small (3 subprocesses per check);
        # reproduce against the kill point that actually DIVERGED
        kill_seed = cv["divergences"][0]

        def crash_still_fails(s):
            vv = ProcessChaos(s, kill_records=(kill_seed,), child_timeout_s=240).run()
            return bool(vv["divergences"])

        mini, sstats = shrink(crash_scn, crash_still_fails, max_checks=12)
        report["shrink_steps"] += sstats["steps"]
        path = f"/tmp/kss_fuzz_crash_{crash_scn['name']}.json"
        with open(path, "w", encoding="utf-8") as f:
            json.dump(
                {"kinds": ["process-crash"], "kill_records": [kill_seed], "scenario": mini},
                f,
                sort_keys=True,
                indent=2,
            )
        failures.append(
            {"scenario": crash_scn["name"], "kinds": ["process-crash"], "repro": path}
        )
    if cv["replayed_records"] <= 0:
        print("fuzz-smoke: ProcessChaos recovery replayed nothing", file=sys.stderr)
        return 1

    # ---- worker-fault leg: the execution-plane adversary (fuzz/chaos.py
    # WorkerChaos + ops/procmesh.py supervision): a shard worker is
    # SIGKILLed at a seeded dispatch — the supervisor must respawn the
    # ensemble from the AOT cache, re-dispatch the abandoned wave, and
    # match the in-process bytes; on hosts where the ensemble can't
    # engage the leg is a loud counted skip (the fault-matrix smoke,
    # scripts/resilience_smoke.py, carries the full matrix)
    from kube_scheduler_simulator_tpu.fuzz.chaos import WorkerChaos, leaked_worker_pids

    wnodes = [h["object"] for t in crash_scn["ticks"] for h in t
              if h["op"] == "create" and h["kind"] == "nodes"]
    # the WorkerChaos cluster is {nodes, pods} only — strip the
    # PriorityClass references the composite scenario's pods may carry
    # (admission would reject them); both legs see the same clones, so
    # the parity bar is unaffected
    wpods = []
    for t in crash_scn["ticks"]:
        for h in t:
            if h["op"] == "create" and h["kind"] == "pods":
                p = json.loads(json.dumps(h["object"]))
                p["spec"].pop("priorityClassName", None)
                p["spec"].pop("priority", None)
                wpods.append(p)
    if wnodes and wpods:
        wv = WorkerChaos(
            {"name": "worker-fault", "nodes": wnodes, "pods": wpods[:24]},
            mode="kill", fault_at=0, nprocs=1, heartbeat_s=0.3, timeout_s=120.0,
        ).run()
        report["scenarios"] += 1
        if wv["engaged"]:
            if wv["divergences"] or not wv["fired"] or wv["respawns"] < 1:
                print(
                    f"fuzz-smoke: WorkerChaos leg broke: fired={wv['fired']} "
                    f"respawns={wv['respawns']} div={wv['divergences'][:4]} "
                    f"first={wv['first_mismatch']}",
                    file=sys.stderr,
                )
                report["divergences"]["worker-fault"] = len(wv["divergences"]) or 1
                return 1
        else:
            print(
                f"fuzz-smoke: WorkerChaos leg skipped loudly — ensemble could not "
                f"engage (verdict={wv['bringup_verdict']!r})"
            )
        if leaked_worker_pids():
            print(
                f"fuzz-smoke: WorkerChaos leaked workers {leaked_worker_pids()}",
                file=sys.stderr,
            )
            return 1

    # ---- metrics wiring: the sweep reports into a live service
    _store_m, svc_m = harness.service("default", "batch")
    svc_m.note_fuzz_report(report)
    from kube_scheduler_simulator_tpu.server.metrics import render_metrics

    class _DI:
        cluster_store = _store_m

        def scheduler_service(self):
            return svc_m

    text = render_metrics(_DI())
    for needle in (
        "simulator_fuzz_scenarios_total",
        "simulator_fuzz_divergences_total",
        "simulator_fuzz_shrink_steps_total",
    ):
        if needle not in text:
            print(f"fuzz-smoke: /metrics missing {needle}", file=sys.stderr)
            return 1

    wall = time.monotonic() - t0
    if failures:
        print(
            f"fuzz-smoke FAIL: {len(failures)} diverging scenario(s) of "
            f"{report['scenarios']} in {wall:.0f}s — minimized repros in /tmp "
            f"(promote to fuzz/fixtures/ after the fix per docs/fuzzing.md)",
            file=sys.stderr,
        )
        return 1
    print(
        f"fuzz-smoke OK: {report['scenarios']} scenarios, 0 unexplained divergences, "
        f"chaos degrade counted ({trips['n']} trips), shard leg sharded, "
        f"mesh-stream leg streamed {fuse_m['stream_waves_total']} sharded waves, "
        f"process-crash leg byte-identical at kill points {cv['kill_points']} "
        f"({cv['replayed_records']} records replayed, 0 torn), "
        f"worker-fault leg "
        + (
            f"byte-identical across {wv['respawns']} respawn(s)"
            if wnodes and wpods and wv["engaged"]
            else "loudly skipped"
        )
        + ", "
        f"{wall:.0f}s; coverage: {json.dumps(cov.summary())}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
