"""NodeResourcesFit + NodeResourcesBalancedAllocation (upstream v1.26).

The headline Filter+Score plugin pair.  Semantics mirrored:

- effective pod request = max(init, sum(containers)) + overhead
  (models.podresources), with upstream's non-zero defaults
  (100m CPU / 200Mi memory) applied per container for scoring
- Filter reasons: "Too many pods" / "Insufficient <resource>"
  (upstream noderesources/fit.go InsufficientResource)
- LeastAllocated score: int64 math
  sum_r weight_r * (alloc_r - requested_r) * 100 / alloc_r / sum weights
- BalancedAllocation: 1 - std of requested fractions, float64 then
  truncated to int64

The vectorized twin of this file is ops/fit.py; the batch engine uses that,
this class is the parity oracle and the sequential-path implementation.
"""

from __future__ import annotations

import math
from typing import Any

from kube_scheduler_simulator_tpu.models.framework import MAX_NODE_SCORE, CycleState, Status
from kube_scheduler_simulator_tpu.models.nodeinfo import NodeInfo
from kube_scheduler_simulator_tpu.models.podresources import (
    CPU,
    EPHEMERAL_STORAGE,
    MEMORY,
    PODS,
    is_fit_resource,
    pod_resource_request,
)
from kube_scheduler_simulator_tpu.utils.quantity import milli_value, value

Obj = dict[str, Any]

# util.GetNonzeroRequests defaults (upstream pkg/scheduler/util).
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024

# RequestedToCapacityRatio scoring (upstream noderesources/
# requested_to_capacity_ratio.go): user shape scores are 0..10
# (config.MaxCustomPriorityScore) and scale to the 0..100 node-score range.
MAX_CUSTOM_PRIORITY_SCORE = 10


def go_div(a: int, b: int) -> int:
    """Go integer division (truncation toward zero — Python's ``//``
    floors, which differs for negative numerators, and the broken-linear
    shape interpolation has negative score deltas on descending ramps)."""
    if b == 0:
        return 0
    q = abs(a) // abs(b)
    return q if (a >= 0) == (b >= 0) else -q


def broken_linear(p: int, shape: "tuple[tuple[int, int], ...]") -> int:
    """helper.BuildBrokenLinearFunction: piecewise-linear interpolation
    over (utilization, score) points with Go integer arithmetic; clamps
    to the first/last point outside the shape's utilization range."""
    for i, (u, s) in enumerate(shape):
        if p <= u:
            if i == 0:
                return s
            u0, s0 = shape[i - 1]
            return s0 + go_div((s - s0) * (p - u0), u - u0)
    return shape[-1][1]


def pod_non_zero_request(pod: Obj) -> dict[str, int]:
    """cpu/memory request with per-container non-zero defaults (used by the
    scoring path, upstream NodeInfo.NonZeroRequested)."""
    spec = pod.get("spec") or {}
    cpu = 0
    mem = 0
    for c in spec.get("containers") or []:
        reqs = (c.get("resources") or {}).get("requests") or {}
        cpu += milli_value(reqs[CPU]) if CPU in reqs else DEFAULT_MILLI_CPU_REQUEST
        mem += value(reqs[MEMORY]) if MEMORY in reqs else DEFAULT_MEMORY_REQUEST
    init_cpu = 0
    init_mem = 0
    for c in spec.get("initContainers") or []:
        reqs = (c.get("resources") or {}).get("requests") or {}
        init_cpu = max(init_cpu, milli_value(reqs[CPU]) if CPU in reqs else DEFAULT_MILLI_CPU_REQUEST)
        init_mem = max(init_mem, value(reqs[MEMORY]) if MEMORY in reqs else DEFAULT_MEMORY_REQUEST)
    cpu = max(cpu, init_cpu)
    mem = max(mem, init_mem)
    overhead = spec.get("overhead") or {}
    if CPU in overhead:
        cpu += milli_value(overhead[CPU])
    if MEMORY in overhead:
        mem += value(overhead[MEMORY])
    return {CPU: cpu, MEMORY: mem}


def node_non_zero_requested(node_info: NodeInfo) -> dict[str, int]:
    cpu = 0
    mem = 0
    for p in node_info.pods:
        r = pod_non_zero_request(p)
        cpu += r[CPU]
        mem += r[MEMORY]
    return {CPU: cpu, MEMORY: mem}


class NodeResourcesFit:
    name = "NodeResourcesFit"

    PRE_FILTER_KEY = "PreFilterNodeResourcesFit"

    def __init__(self, args: "Obj | None" = None):
        args = args or {}
        strategy = (args.get("scoringStrategy") or {})
        self.strategy_type = strategy.get("type") or "LeastAllocated"
        resources = strategy.get("resources") or [
            {"name": CPU, "weight": 1},
            {"name": MEMORY, "weight": 1},
        ]
        self.score_resources = [(r["name"], int(r.get("weight") or 1)) for r in resources]
        # RequestedToCapacityRatio shape: (utilization, score*10) points,
        # utilization ascending (upstream scales config scores 0..10 up to
        # the 0..100 node-score range at build time).  The default ramp is
        # the canonical bin-packing shape (score rises with utilization).
        shape = (strategy.get("requestedToCapacityRatio") or {}).get("shape") or [
            {"utilization": 0, "score": 0},
            {"utilization": 100, "score": MAX_CUSTOM_PRIORITY_SCORE},
        ]
        self.rtcr_shape = tuple(
            sorted(
                (int(pt.get("utilization") or 0), int(pt.get("score") or 0) * (MAX_NODE_SCORE // MAX_CUSTOM_PRIORITY_SCORE))
                for pt in shape
            )
        )

    # -- PreFilter: compute the effective request once per pod
    def pre_filter(self, state: CycleState, pod: Obj):
        state.write(self.PRE_FILTER_KEY, pod_resource_request(pod))
        return None, None

    def filter(self, state: CycleState, pod: Obj, node_info: NodeInfo) -> "Status | None":
        req = state.read(self.PRE_FILTER_KEY)
        if req is None:
            req = pod_resource_request(pod)
        reasons: list[str] = []
        if len(node_info.pods) + 1 > node_info.allowed_pod_number():
            reasons.append("Too many pods")
        for r, want in req.items():
            if want == 0 or not is_fit_resource(r):
                continue
            have = node_info.allocatable.get(r, 0) - node_info.requested.get(r, 0)
            if want > have:
                reasons.append(f"Insufficient {r}")
        if reasons:
            return Status.unschedulable(*reasons)
        return None

    # -- Score (LeastAllocated / MostAllocated / RequestedToCapacityRatio)
    def score(self, state: CycleState, pod: Obj, node_info: NodeInfo) -> "tuple[int, Status | None]":
        pod_req = pod_non_zero_request(pod)
        node_req = node_non_zero_requested(node_info)
        node_score = 0
        weight_sum = 0
        for r, weight in self.score_resources:
            alloc = node_info.allocatable.get(r, 0)
            if r in (CPU, MEMORY):
                requested = node_req.get(r, 0) + pod_req.get(r, 0)
            else:
                requested = node_info.requested.get(r, 0) + pod_resource_request(pod).get(r, 0)
            node_score += self._score_one(requested, alloc) * weight
            weight_sum += weight
        if weight_sum == 0:
            return 0, None
        return node_score // weight_sum, None

    def _score_one(self, requested: int, alloc: int) -> int:
        if self.strategy_type == "RequestedToCapacityRatio":
            # upstream resourceScoringFunction: over-capacity (or zero
            # capacity) evaluates the shape at maxUtilization, NOT 0
            if alloc == 0 or requested > alloc:
                return broken_linear(100, self.rtcr_shape)
            return broken_linear(requested * 100 // alloc, self.rtcr_shape)
        if alloc == 0:
            return 0
        if self.strategy_type == "MostAllocated":
            if requested > alloc:
                return 0
            return requested * MAX_NODE_SCORE // alloc
        # LeastAllocated (default)
        if requested > alloc:
            return 0
        return (alloc - requested) * MAX_NODE_SCORE // alloc


class NodeResourcesBalancedAllocation:
    name = "NodeResourcesBalancedAllocation"

    def __init__(self, args: "Obj | None" = None):
        args = args or {}
        resources = args.get("resources") or [{"name": CPU, "weight": 1}, {"name": MEMORY, "weight": 1}]
        self.resources = [r["name"] for r in resources]

    def score(self, state: CycleState, pod: Obj, node_info: NodeInfo) -> "tuple[int, Status | None]":
        pod_req = pod_non_zero_request(pod)
        node_req = node_non_zero_requested(node_info)
        fractions: list[float] = []
        for r in self.resources:
            alloc = node_info.allocatable.get(r, 0)
            if alloc == 0:
                fractions.append(1.0)
                continue
            if r in (CPU, MEMORY):
                requested = node_req.get(r, 0) + pod_req.get(r, 0)
            else:
                requested = node_info.requested.get(r, 0) + pod_resource_request(pod).get(r, 0)
            frac = requested / alloc
            fractions.append(min(frac, 1.0))
        if len(fractions) == 2:
            std = abs(fractions[0] - fractions[1]) / 2
        elif len(fractions) > 2:
            mean = sum(fractions) / len(fractions)
            std = math.sqrt(sum((f - mean) ** 2 for f in fractions) / len(fractions))
        else:
            std = 0.0
        return int((1 - std) * MAX_NODE_SCORE), None
