"""Render-once wire-bytes cache: each object's serialized JSON is built
at most once per resourceVersion and shared verbatim across every
consumer — list documents, watch events (initial ADDED sweep, backlog
replay, live fan-out), single-object GETs, and each tenant session's
own plane (the cache hangs off the session's store, so isolation is
structural).

Why: the profiler's ``watch_render`` stage showed the same pod being
``json.dumps``-ed once PER list/watch consumer per mutation — with 256
watch clients (cfg15's fan-out leg) that is 256 identical renders of
identical bytes.  The cache keys on ``(kind, namespace, name)`` and
stores ``(resourceVersion, {(apiVersion, kind): json})`` — an object
serves under more than one groupVersion (e.g. events under core v1 and
events.k8s.io), and each variant renders lazily on first use.

Byte parity is the contract: a cached string must equal
``json.dumps(envelope(obj))`` of the uncached renderer EXACTLY
(tests/test_wirecache.py diffs both paths across mutations, patches,
SSA writes, sessions, and journal recovery).  Renders therefore use the
same default separators and the same ``dict(obj)`` + ``setdefault``
envelope the HTTP layer uses.

Invalidation is belt and braces:

- the LOOKUP compares the entry's resourceVersion against the live
  object's — a stale entry can never be served, even if an explicit
  invalidation were missed (correctness does not depend on hooks);
- the store still invalidates eagerly on every mutation/replay
  (``ClusterStore._emit``, ``replay_object``/``replay_event``,
  ``clear_for_replay``) so deleted objects don't pin bytes and the
  ``wirecache_invalidations_total`` counter means what it says.

DELETED events are rendered but never inserted: their delete-stamped
object has no future readers, and the entry was just purged — caching
it would leak one entry per churned object.

Knobs: ``KSS_WIRECACHE=0`` disables the cache entirely (the serving
layer falls back to the exact pre-cache render path, byte-for-byte);
``KSS_WIRECACHE_MAX`` caps entries (oldest-inserted evicted first).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any

Obj = dict[str, Any]


def wirecache_enabled() -> bool:
    return os.environ.get("KSS_WIRECACHE", "1") != "0"


def max_entries_from_env() -> int:
    n = int(os.environ.get("KSS_WIRECACHE_MAX", "65536"))
    if n < 1:
        raise ValueError(f"KSS_WIRECACHE_MAX must be >= 1, got {n}")
    return n


class WireCache:
    """(kind, namespace, name) -> (resourceVersion, {(apiVersion,
    kindName): json_str}).  Thread-safe: HTTP handler threads and the
    scheduling thread share it; renders happen outside the lock (the
    rendered object is frozen by the store's replacement contract, so
    concurrent renders of the same version produce identical bytes)."""

    def __init__(self, max_entries: "int | None" = None, profiler: Any = None):
        self.max_entries = (
            max_entries_from_env() if max_entries is None else max_entries
        )
        # the wave profiler (ops/profile.py): miss renders stamp
        # ``watch_render`` ambiently; None = unprofiled
        self.profiler = profiler
        self._lock = threading.Lock()
        self._map: "dict[tuple, tuple[str, dict]]" = {}
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    # ---------------------------------------------------------- rendering

    def _render(self, obj: Obj, api_version: str, kind_name: str) -> str:
        # EXACTLY the HTTP layer's envelope + json.dumps (default
        # separators, ensure_ascii) — the parity pin depends on it
        t0 = time.perf_counter()
        out = dict(obj)
        out.setdefault("apiVersion", api_version)
        out.setdefault("kind", kind_name)
        s = json.dumps(out)
        prof = self.profiler
        if prof is not None:
            prof.ambient("watch_render", time.perf_counter() - t0)
        return s

    def obj_json(
        self,
        kind: str,
        obj: Obj,
        api_version: str,
        kind_name: str,
        insert: bool = True,
    ) -> str:
        """The object's wire JSON (enveloped), served from cache when the
        entry matches the object's own resourceVersion, else rendered —
        and inserted unless ``insert=False`` (DELETED events)."""
        meta = obj.get("metadata") or {}
        key = (kind, meta.get("namespace"), meta.get("name"))
        rv = meta.get("resourceVersion")
        vkey = (api_version, kind_name)
        with self._lock:
            entry = self._map.get(key)
            if entry is not None and entry[0] == rv:
                s = entry[1].get(vkey)
                if s is not None:
                    self.hits += 1
                    return s
            self.misses += 1
        s = self._render(obj, api_version, kind_name)
        if insert and rv is not None:
            with self._lock:
                entry = self._map.get(key)
                if entry is not None and entry[0] == rv:
                    entry[1][vkey] = s
                elif entry is None or self._newer(rv, entry[0]):
                    # backlog replays render OLDER versions of a live
                    # object — never let one overwrite a newer entry
                    if entry is None and len(self._map) >= self.max_entries:
                        self._map.pop(next(iter(self._map)))
                    self._map[key] = (rv, {vkey: s})
        return s

    @staticmethod
    def _newer(rv: str, cur: "str | None") -> bool:
        try:
            return cur is None or int(rv) >= int(cur)
        except (TypeError, ValueError):
            return True

    def event_line(self, type_: str, obj_json: str) -> bytes:
        """One watch-stream line from already-rendered object bytes —
        byte-identical to ``json.dumps({"type": ..., "object": env})``
        (the type tags are plain ASCII literals)."""
        return ('{"type": "%s", "object": %s}\n' % (type_, obj_json)).encode()

    def list_doc(
        self,
        list_kind: str,
        api_version: str,
        resource_version: str,
        item_jsons: "list[str]",
    ) -> bytes:
        """Splice a kube List document from cached per-item bytes —
        byte-identical to ``json.dumps`` of the dict the uncached path
        builds (same key order, default separators)."""
        return (
            '{"kind": %s, "apiVersion": %s, "metadata": {"resourceVersion": %s}, '
            '"items": [%s]}'
            % (
                json.dumps(list_kind),
                json.dumps(api_version),
                json.dumps(resource_version),
                ", ".join(item_jsons),
            )
        ).encode()

    # -------------------------------------------------------- invalidation

    def invalidate(self, kind: str, meta: "dict | None", deleted: bool = False) -> None:
        """Drop the object's entry (called by the store on every
        mutation/replay, under the store lock).  ``deleted`` is
        informational — both cases purge; the flag keeps the call sites
        self-documenting."""
        meta = meta or {}
        key = (kind, meta.get("namespace"), meta.get("name"))
        with self._lock:
            if self._map.pop(key, None) is not None:
                self.invalidations += 1

    def clear(self) -> None:
        with self._lock:
            n = len(self._map)
            self._map.clear()
            self.invalidations += n

    # ------------------------------------------------------------ surfaces

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
                "entries": len(self._map),
                "max_entries": self.max_entries,
            }
