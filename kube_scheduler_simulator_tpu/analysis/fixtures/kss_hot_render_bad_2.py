"""KSS-HOT-RENDER bad fixture 2: the store-shaped variants — a per-event
``_clone`` in the emit loop and a while-drain that re-dumps per item."""

import json


def _clone(o):
    return json.loads(json.dumps(o))


def emit_all(events, subscribers):
    for ev in events:
        for sub in subscribers:
            sub(_clone(ev))  # expect-finding


def drain(queue):
    out = []
    while queue:
        item = queue.pop()
        out.append(json.dumps(item))  # expect-finding
    return out
