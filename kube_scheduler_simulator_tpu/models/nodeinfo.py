"""NodeInfo: a node plus its scheduled pods and aggregated resource usage.

Analog of the upstream framework.NodeInfo snapshot entries that the
reference's hot Filter/Score loop iterates (SURVEY.md section 3.2 hot loop;
reference scheduler/scheduler.go:174-267 mirrors the loop nest).
"""

from __future__ import annotations

from typing import Any

from kube_scheduler_simulator_tpu.models.podresources import (
    PODS,
    node_allocatable,
    pod_resource_request,
)

Obj = dict[str, Any]


class NodeInfo:
    __slots__ = ("node", "pods", "requested", "allocatable")

    def __init__(self, node: Obj):
        self.node = node
        self.pods: list[Obj] = []
        self.requested: dict[str, int] = {}
        self.allocatable: dict[str, int] = node_allocatable(node)

    @property
    def name(self) -> str:
        return self.node["metadata"]["name"]

    def add_pod(self, pod: Obj) -> None:
        self.pods.append(pod)
        for r, v in pod_resource_request(pod).items():
            self.requested[r] = self.requested.get(r, 0) + v

    def remove_pod(self, pod: Obj) -> None:
        uid = pod["metadata"].get("uid")
        name = pod["metadata"].get("name")
        for i, p in enumerate(self.pods):
            if (uid and p["metadata"].get("uid") == uid) or (not uid and p["metadata"].get("name") == name):
                self.pods.pop(i)
                for r, v in pod_resource_request(pod).items():
                    self.requested[r] = self.requested.get(r, 0) - v
                return

    def allowed_pod_number(self) -> int:
        return self.allocatable.get(PODS, 0)


def build_node_infos(nodes: list[Obj], pods: list[Obj]) -> list[NodeInfo]:
    """Build the scheduler-cache snapshot: NodeInfo per node, with every
    already-assigned pod accounted on its node."""
    infos = [NodeInfo(n) for n in nodes]
    by_name = {ni.name: ni for ni in infos}
    for p in pods:
        node_name = (p.get("spec") or {}).get("nodeName")
        if node_name and node_name in by_name:
            by_name[node_name].add_pod(p)
    return infos
