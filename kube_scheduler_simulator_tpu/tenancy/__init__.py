"""Multi-tenant session plane (docs/multitenancy.md).

Isolated per-session control planes — store, scheduler, queue, watch
epoch, journal namespace — over ONE shared compiled-kernel substrate,
so N tenants with the same scheduler config cost one compile, not N.
"""

from kube_scheduler_simulator_tpu.tenancy.manager import (
    DEFAULT_SESSION,
    InvalidSessionError,
    Session,
    SessionError,
    SessionExistsError,
    SessionManager,
    TooManySessionsError,
    UnknownSessionError,
    session_knobs,
)
from kube_scheduler_simulator_tpu.tenancy.substrate import SUBSTRATE, ExecutableSubstrate

__all__ = [
    "DEFAULT_SESSION",
    "ExecutableSubstrate",
    "InvalidSessionError",
    "SUBSTRATE",
    "Session",
    "SessionError",
    "SessionExistsError",
    "SessionManager",
    "TooManySessionsError",
    "UnknownSessionError",
    "session_knobs",
]
