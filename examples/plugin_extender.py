"""Sample plugin extender: export NodeResourcesFit's PreFilter state.

Python rebuild of the reference's plugin-extender sample (reference
simulator/docs/sample/plugin-extender/extender.go:16-80), which hooks
AfterPreFilter on NodeResourcesFit and exports the plugin's computed
preFilterState (the pod's resource request) into a custom pod annotation
via the shared result store — the designed fault-injection / state-export
surface of the debuggable scheduler (reference wrappedplugin.go:47-171).

An extender is any object with ``before_<point>`` / ``after_<point>``
methods; it is attached per plugin name through
``SchedulerService.set_plugin_extenders`` (the library surface
``pkg.debuggablescheduler.new_scheduler_command(plugin_extenders=...)``,
the reference's WithPluginExtenders).

Run the demo:  PYTHONPATH=. python examples/plugin_extender.py
"""

from __future__ import annotations

import json
from typing import Any

Obj = dict[str, Any]

EXPORT_ANNOTATION = "scheduler-simulator/prefilter-state-fit"


class FitPreFilterExporter:
    """AfterPreFilter hook on NodeResourcesFit: records the request the
    plugin computed (what the Go sample extracts via reflection from the
    upstream preFilterState) as a custom result annotation."""

    def __init__(self, result_store: Any):
        self.result_store = result_store

    def after_pre_filter(self, state, pod: Obj, result, status):
        from kube_scheduler_simulator_tpu.models.podresources import pod_resource_request

        ns = pod["metadata"].get("namespace", "default")
        name = pod["metadata"]["name"]
        request = {k: str(v) for k, v in sorted(pod_resource_request(pod).items())}
        self.result_store.add_custom_result(
            ns, name, EXPORT_ANNOTATION, json.dumps(request, separators=(",", ":"))
        )
        return result, status


def main() -> None:
    from kube_scheduler_simulator_tpu.pkg.debuggablescheduler import new_scheduler
    from kube_scheduler_simulator_tpu.state.store import ClusterStore

    store = ClusterStore()
    store.create(
        "nodes",
        {
            "metadata": {"name": "node-1"},
            "status": {"allocatable": {"cpu": "4", "memory": "8Gi", "pods": "110"}},
        },
    )
    store.create(
        "pods",
        {
            "metadata": {"name": "pod-1", "namespace": "default"},
            "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "500m", "memory": "256Mi"}}}]},
        },
    )
    svc, _result_store = new_scheduler(
        store,
        plugin_extenders={"NodeResourcesFit": FitPreFilterExporter},
    )
    svc.schedule_pending(max_rounds=1)
    pod = store.get("pods", "pod-1")
    annos = pod["metadata"].get("annotations") or {}
    print("selected-node:", annos.get("scheduler-simulator/selected-node"))
    print(f"{EXPORT_ANNOTATION}:", annos.get(EXPORT_ANNOTATION))


if __name__ == "__main__":
    main()
