"""Runtime trace discipline: compile counting and the RecompileGuard.

The static rules catch contract violations the AST can see; the one it
can't is the PR 7 pathology — code that is perfectly legal python but
RECOMPILES on every call because something non-hashable-stable (a fresh
``lower()``, a traced-weights config reaching a placeholder executable,
a shape that misses its bucket) lands in the jit cache key.  On a
steady-state workload the contract is: after the warmup wave, zero new
backend compiles.

JAX already emits exactly the right signal:
``/jax/core/compile/backend_compile_duration`` fires once per backend
compile and never on a cached dispatch.  ``jax.monitoring`` listeners
cannot be unregistered individually, so this module installs ONE
process-global listener (idempotently) that feeds a monotone counter;
:class:`RecompileGuard` snapshots the counter on entry and asserts the
delta on exit.

Usage::

    warmup()                        # compiles are expected here
    with RecompileGuard("steady-state waves"):
        for _ in range(n):          # re-dispatch only
            step()

Wired into tier-1 via scripts/stream_smoke.py and scripts/tune_smoke.py
(steady-state second pass over a warmed service), and pinned against
the PR 7 estimator contract in tests/test_contracts.py (a live weight
override must not recompile the second estimate).
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_installed = False
_compiles = 0

_EVENT = "/jax/core/compile/backend_compile_duration"


def _listener(event: str, duration: float, **kwargs) -> None:
    global _compiles
    if event == _EVENT:
        # += is a read-modify-write, and compiles can fire from more than
        # one thread (stream session vs commit thread) — take the lock so
        # a concurrent pair never loses an increment; compiles are rare
        # and multi-second, so the lock costs nothing
        with _lock:
            _compiles += 1


def _ensure_installed() -> None:
    global _installed
    with _lock:
        if not _installed:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(_listener)
            _installed = True


def compile_count() -> int:
    """Monotone count of JAX backend compiles since the listener was
    installed (installs it on first call — counts start at the first
    guard/count usage, not process start)."""
    _ensure_installed()
    return _compiles


class RecompileError(AssertionError):
    """A guarded region compiled when its contract said it must not."""


class RecompileGuard:
    """Assert at most ``max_compiles`` backend compiles inside the block.

    ``name`` labels the violated contract in the error message.  The
    guard is reentrant-safe (each instance snapshots independently) and
    usable as a plain counter: ``guard.compiles`` after exit holds the
    delta whether or not it raised... it only raises when the delta
    exceeds ``max_compiles``.
    """

    def __init__(self, name: str = "steady state", max_compiles: int = 0):
        self.name = name
        self.max_compiles = int(max_compiles)
        self.compiles = 0
        self._t0 = 0

    def __enter__(self) -> "RecompileGuard":
        _ensure_installed()
        self._t0 = compile_count()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.compiles = compile_count() - self._t0
        if exc_type is None and self.compiles > self.max_compiles:
            raise RecompileError(
                f"RecompileGuard({self.name!r}): {self.compiles} backend "
                f"compile(s) inside a region whose contract allows "
                f"{self.max_compiles} — something in the guarded dispatch "
                "path is rebuilding executables per call (fresh lower(), "
                "unstable cache key, or an unbucketed shape)."
            )
        return False
