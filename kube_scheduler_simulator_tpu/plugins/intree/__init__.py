"""In-tree plugin implementations (upstream v1.26 semantics).

Each plugin implements the per-pod Python protocol from models.framework
(exact upstream messages and integer math — the parity oracle), and the hot
five additionally have vectorized JAX kernels in ``ops`` that the batch
engine uses (SURVEY.md section 7 north-star five).
"""

from kube_scheduler_simulator_tpu.plugins.intree.registry import (
    DEFAULT_PLUGIN_ORDER,
    DEFAULT_SCORE_WEIGHTS,
    in_tree_registry,
)

__all__ = ["in_tree_registry", "DEFAULT_PLUGIN_ORDER", "DEFAULT_SCORE_WEIGHTS"]
