"""Seeded composite-scenario generation.

A *fuzz scenario* is a deterministic function of ``(seed, index)``: a
timeline of ticks, each a list of store operations (creates / deletes /
patches / live weight retunes), composing at least three of the repo's
subsystems at once — gang PodGroups (the Tesserae workload class),
preemption-inducing priority/PDB mixes, autoscale node-group timelines,
mid-stream node/taint churn with PDB flips, and live
``set_plugin_weights`` retunes.  The structure (which subsystems a
scenario exercises) is drawn through :mod:`fuzz.coverage`'s
diversity-seeking buckets, not uniform noise; everything below the
bucket — sizes, shapes, arrival order, flip timing — comes from the
scenario's own ``random.Random``.

The op vocabulary is deliberately tiny and JSON-serializable (the
shrinker deletes ops and re-serializes scenarios into committed
fixtures):

    {"op": "create", "kind": K, "object": {...}}
    {"op": "delete", "kind": K, "name": N, "namespace": NS}
    {"op": "patch",  "kind": K, "name": N, "namespace": NS, "body": {...}}
    {"op": "weights", "weights": {name: w, ...}}

Determinism rules mirror the scenario families that came before
(gang/scenario.py, tuning/scenario.py): seeded rng + counter names +
explicit creationTimestamps (PrioritySort tie-breaks on them — the wall
clock must never leak in; the runner additionally pins both store and
service clocks with :class:`utils.SimClock`).  Churn deletes only touch
pods created two or more ticks earlier, the invariant that keeps a feed
phase-insensitive between the streamed and serial pipelines
(scripts/stream_smoke.py established it).
"""

from __future__ import annotations

import random
from typing import Any

from kube_scheduler_simulator_tpu.fuzz.coverage import (
    FEATURES,
    MIN_COMPOSE,
    CoverageMap,
)

Obj = dict[str, Any]

ZONES = ("z0", "z1", "z2")

# names valid in both the default and the gang profile's score set —
# what a live retune op may override (tuning/validate.py mapping form)
RETUNE_NAMES = ("NodeResourcesFit", "TaintToleration", "PodTopologySpread", "InterPodAffinity")
RETUNE_VALUES = (0.5, 1.0, 2.0, 3.0)

GANG_TIMEOUTS = (3.0, 5.0, 300.0)


def _stamp(i: int) -> str:
    """Deterministic, strictly ordered creationTimestamp per pod index."""
    return f"2024-06-01T{(i // 3600) % 24:02d}:{(i // 60) % 60:02d}:{i % 60:02d}Z"


def _create(kind: str, obj: Obj) -> Obj:
    return {"op": "create", "kind": kind, "object": obj}


def _delete(kind: str, name: str, namespace: "str | None" = "default") -> Obj:
    return {"op": "delete", "kind": kind, "name": name, "namespace": namespace}


def _patch(kind: str, name: str, body: Obj, namespace: "str | None" = "default") -> Obj:
    return {"op": "patch", "kind": kind, "name": name, "namespace": namespace, "body": body}


def _node(prefix: str, i: int, cpu_m: int, mem_mi: int, taints: "list | None" = None) -> Obj:
    name = f"{prefix}-n{i}"
    n: Obj = {
        "metadata": {
            "name": name,
            "labels": {
                "kubernetes.io/hostname": name,
                "topology.kubernetes.io/zone": ZONES[i % len(ZONES)],
                "disk": "ssd" if i % 2 == 0 else "hdd",
            },
        },
        "status": {
            "allocatable": {"cpu": f"{cpu_m}m", "memory": f"{mem_mi}Mi", "pods": "48"}
        },
    }
    if taints:
        n["spec"] = {"taints": taints}
    return n


def _pod(
    prefix: str,
    i: int,
    rng: random.Random,
    *,
    cpu_m: "int | None" = None,
    mem_mi: "int | None" = None,
    labels: "dict | None" = None,
    priority_class: "str | None" = None,
    group: "str | None" = None,
    spread: "bool | None" = None,
    selector: "bool | None" = None,
) -> Obj:
    labels = dict(labels or {})
    labels.setdefault("app", f"a{i % 3}")
    if group is not None:
        from kube_scheduler_simulator_tpu.gang.podgroups import POD_GROUP_LABEL

        labels[POD_GROUP_LABEL] = group
    spec: Obj = {
        "containers": [
            {
                "name": "c",
                "resources": {
                    "requests": {
                        "cpu": f"{cpu_m if cpu_m is not None else rng.choice((100, 250, 500, 900))}m",
                        "memory": f"{mem_mi if mem_mi is not None else rng.choice((128, 256, 512))}Mi",
                    }
                },
            }
        ]
    }
    if priority_class:
        spec["priorityClassName"] = priority_class
    if spread if spread is not None else rng.random() < 0.3:
        spec["topologySpreadConstraints"] = [
            {
                "maxSkew": 2,
                "topologyKey": "topology.kubernetes.io/zone",
                "whenUnsatisfiable": "DoNotSchedule",
                "labelSelector": {"matchLabels": {"app": labels["app"]}},
            }
        ]
    if selector if selector is not None else rng.random() < 0.2:
        spec["nodeSelector"] = {"disk": "ssd"}
    return {
        "metadata": {
            "name": f"{prefix}-p{i:04d}",
            "namespace": "default",
            "labels": labels,
            "creationTimestamp": _stamp(i),
        },
        "spec": spec,
    }


def generate_scenario(
    seed: int,
    index: int = 0,
    coverage: "CoverageMap | None" = None,
    features: "frozenset[str] | None" = None,
) -> Obj:
    """One composite scenario, a pure function of ``(seed, index)`` given
    the coverage map's accumulated counts.  ``features`` overrides the
    coverage draw (the shrinker and fixtures replay a recorded set)."""
    rng = random.Random(f"kss-fuzz:{seed}:{index}")
    if features is None:
        if coverage is None:
            features = frozenset(rng.sample(FEATURES, rng.randint(MIN_COMPOSE, len(FEATURES))))
        else:
            features = coverage.choose_features(rng)
    if coverage is not None:
        coverage.note(features)
    prefix = f"fz{seed}x{index}"
    n_ticks = rng.randint(6, 8)
    ticks: list[list[Obj]] = [[] for _ in range(n_ticks)]
    pod_i = 0
    # (name, created_tick) of churn-deletable pods; gang members and
    # preemption actors are excluded — deleting a parked / mid-preemption
    # pod from the feed would make the stream projection phase-sensitive
    deletable: list[tuple[str, int]] = []
    deleted: set[str] = set()

    # ---- tick 0: the cluster -------------------------------------------
    n_nodes = rng.randint(5, 8)
    cpu_shapes = (4000, 8000, 12000)
    for i in range(n_nodes):
        taints = None
        if rng.random() < 0.34:
            taints = [{"key": "spot", "value": "true", "effect": "PreferNoSchedule"}]
        ticks[0].append(
            _create("nodes", _node(prefix, i, rng.choice(cpu_shapes), rng.choice((8192, 16384)), taints))
        )
    next_node_i = n_nodes

    if "preemption" in features:
        ticks[0].append(
            _create(
                "priorityclasses",
                {"metadata": {"name": f"{prefix}-prio-high"}, "value": 100000},
            )
        )
        ticks[0].append(
            _create(
                "priorityclasses",
                {"metadata": {"name": f"{prefix}-prio-low"}, "value": 10},
            )
        )
        # PDB over the filler cohort: some victims are budget-protected
        ticks[0].append(
            _create(
                "poddisruptionbudgets",
                {
                    "metadata": {"name": f"{prefix}-pdb", "namespace": "default"},
                    "spec": {
                        "minAvailable": rng.randint(1, 3),
                        "selector": {"matchLabels": {"cohort": f"{prefix}-filler"}},
                    },
                },
            )
        )

    if "autoscale" in features:
        ticks[0].append(
            _create(
                "nodegroups",
                {
                    "metadata": {"name": f"{prefix}-pool"},
                    "spec": {
                        "minSize": 0,
                        "maxSize": rng.randint(2, 4),
                        "template": {
                            "metadata": {
                                "labels": {
                                    "topology.kubernetes.io/zone": rng.choice(ZONES),
                                    "disk": "ssd",
                                }
                            },
                            "status": {
                                "allocatable": {
                                    "cpu": "8000m",
                                    "memory": "16Gi",
                                    "pods": "48",
                                }
                            },
                        },
                    },
                },
            )
        )

    # ---- base workload: plain pods arriving over the early/mid ticks ---
    arrivals = rng.randint(10, 18)
    for _ in range(arrivals):
        t = rng.randint(1, n_ticks - 3)
        p = _pod(prefix, pod_i, rng)
        ticks[t].append(_create("pods", p))
        deletable.append((p["metadata"]["name"], t))
        pod_i += 1

    if "preemption" in features:
        # low-priority filler early, then a high-priority burst that
        # exceeds what is left — the PostFilter victim search has to act
        filler_t = 1
        for _ in range(rng.randint(6, 10)):
            p = _pod(
                prefix,
                pod_i,
                rng,
                cpu_m=rng.choice((1500, 2500)),
                mem_mi=1024,
                labels={"cohort": f"{prefix}-filler"},
                priority_class=f"{prefix}-prio-low",
                spread=False,
                selector=False,
            )
            ticks[filler_t].append(_create("pods", p))
            pod_i += 1
        burst_t = rng.randint(3, n_ticks - 3)
        for _ in range(rng.randint(3, 5)):
            p = _pod(
                prefix,
                pod_i,
                rng,
                cpu_m=rng.choice((2500, 3500)),
                mem_mi=2048,
                priority_class=f"{prefix}-prio-high",
                spread=False,
                selector=False,
            )
            ticks[burst_t].append(_create("pods", p))
            pod_i += 1
        if rng.random() < 0.6:
            # PDB flip mid-run: the protection the victim search must
            # honor changes under the engines' feet
            flip_t = min(burst_t + 1, n_ticks - 2)
            ticks[flip_t].append(
                _patch(
                    "poddisruptionbudgets",
                    f"{prefix}-pdb",
                    {"spec": {"minAvailable": rng.randint(0, 4)}},
                )
            )

    if "gang" in features:
        n_groups = rng.randint(2, 3)
        for g in range(n_groups):
            arrive = rng.randint(1, n_ticks - 4)
            members = rng.randint(2, 4)
            # one group may arrive short of quorum: its members park at
            # Permit and the (possibly small) gang timeout has to expire
            # them — the rejection-cascade path
            short = g == n_groups - 1 and rng.random() < 0.5
            created = members - 1 if short else members
            gname = f"{prefix}-job{g}"
            ticks[arrive].append(
                _create(
                    "podgroups",
                    {
                        "metadata": {"name": gname, "namespace": "default"},
                        "spec": {
                            "minMember": members,
                            "scheduleTimeoutSeconds": rng.choice(GANG_TIMEOUTS),
                            "topologyPackKey": "topology.kubernetes.io/zone",
                        },
                    },
                )
            )
            for m in range(created):
                p = _pod(
                    prefix,
                    pod_i,
                    rng,
                    cpu_m=1000,
                    mem_mi=1024,
                    group=gname,
                    spread=False,
                    selector=False,
                )
                ticks[arrive].append(_create("pods", p))
                pod_i += 1
            if not short and rng.random() < 0.5:
                # job completes: members + group deleted two ticks later
                done = min(arrive + 2, n_ticks - 1)
                for m in range(created):
                    ticks[done].append(
                        _delete("pods", f"{prefix}-p{pod_i - created + m:04d}")
                    )
                ticks[done].append(_delete("podgroups", gname))

    if "churn" in features:
        # pod churn: delete settled pods (created >= 2 ticks earlier —
        # the stream-feed phase-insensitivity rule)
        for t in range(3, n_ticks - 1):
            settled = [nm for nm, ct in deletable if ct <= t - 2 and nm not in deleted]
            for nm in rng.sample(settled, min(len(settled), rng.randint(0, 2))):
                deleted.add(nm)
                ticks[t].append(_delete("pods", nm))
        # node churn: drop one base node mid-run, add a fresh one later,
        # and flip taints on another — every encode-invalidation gate at once
        if rng.random() < 0.7:
            t = rng.randint(2, n_ticks - 3)
            ticks[t].append(_delete("nodes", f"{prefix}-n{rng.randrange(n_nodes)}", None))
        if rng.random() < 0.7:
            t = rng.randint(2, n_ticks - 2)
            ticks[t].append(
                _create(
                    "nodes",
                    _node(prefix, next_node_i, rng.choice(cpu_shapes), 16384),
                )
            )
            next_node_i += 1
        if rng.random() < 0.7:
            t = rng.randint(2, n_ticks - 2)
            ticks[t].append(
                _patch(
                    "nodes",
                    f"{prefix}-n{rng.randrange(n_nodes)}",
                    {
                        "spec": {
                            "taints": [
                                {"key": "spot", "value": "true", "effect": "PreferNoSchedule"}
                            ]
                        }
                    },
                    None,
                )
            )

    if "retune" in features:
        # live set_plugin_weights retunes mid-run (value-only changes: the
        # traced engines re-dispatch, never recompile)
        for _ in range(rng.randint(1, 2)):
            t = rng.randint(1, n_ticks - 2)
            mapping = {
                nm: rng.choice(RETUNE_VALUES)
                for nm in rng.sample(RETUNE_NAMES, rng.randint(1, 3))
            }
            ticks[t].append({"op": "weights", "weights": mapping})

    return {
        "name": f"fuzz-{prefix}",
        "seed": seed,
        "index": index,
        "features": sorted(features),
        "profile": "gang" if "gang" in features else "default",
        "stepSeconds": 1.0,
        "ticks": ticks,
    }
