"""The web UI, served at GET / by the simulator server.

Functional rebuild of the reference's Nuxt2/Vuetify SPA (reference web/,
SURVEY.md §2.2) as a single static page (no build step, no node_modules):

- per-resource views with pods bucketed under their node (or
  "unscheduled"), mirroring web/store/pod.ts:12-50
- per-kind DATA TABLES for every kind (the reference's
  web/components/ResourceViews/DataTables), toggled with the cluster view
- create resources from editable YAML templates served by the backend
  (web/components/lib/templates/*), POSTed as application/yaml; EDIT any
  object as YAML and apply (?format=yaml GET + YAML PUT — the reference's
  monaco editor role, no client-side YAML lib)
- per-pod scheduling-result dialog rendering every
  scheduler-simulator/* annotation, with the result-history annotation
  expanded into a per-attempt viewer (the reference's result dialog)
- scheduler configuration editor (GET/POST /api/v1/schedulerconfiguration)
- export / import / reset buttons
- live updates over the /api/v1/listwatchresources stream
"""

HTML = r"""<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>kube-scheduler-simulator (TPU)</title>
<style>
  :root { --bg:#fafafa; --panel:#fff; --line:#e0e0e0; --accent:#326ce5; --mono:ui-monospace,Menlo,Consolas,monospace; }
  * { box-sizing:border-box; }
  body { margin:0; font:14px/1.45 system-ui,sans-serif; background:var(--bg); color:#222; }
  header { background:var(--accent); color:#fff; padding:10px 16px; display:flex; gap:12px; align-items:center; }
  header h1 { font-size:16px; margin:0 auto 0 0; font-weight:600; }
  button { background:#fff; color:var(--accent); border:1px solid #fff3; border-radius:4px; padding:5px 10px; cursor:pointer; font-weight:600; }
  main button { border-color:var(--accent); }
  main { display:grid; grid-template-columns: 2fr 1fr; gap:12px; padding:12px; }
  .panel { background:var(--panel); border:1px solid var(--line); border-radius:6px; padding:10px 12px; overflow:auto; }
  .node { border:1px solid var(--line); border-radius:6px; margin:8px 0; }
  .node>h3 { margin:0; padding:6px 10px; background:#f0f4ff; font-size:13px; border-bottom:1px solid var(--line); }
  .pod { display:inline-block; margin:6px; padding:4px 10px; background:#e8f0fe; border:1px solid #c6d7fb; border-radius:12px; cursor:pointer; font-size:12px; }
  .pod.unsched { background:#fdecea; border-color:#f6c8c4; }
  .kindrow { margin:4px 0; } .kindrow b { display:inline-block; width:160px; }
  .item { display:inline-block; margin:2px; padding:2px 8px; border:1px solid var(--line); border-radius:10px; font-size:12px; cursor:pointer; }
  dialog { width:min(900px,90vw); border:1px solid var(--line); border-radius:8px; }
  pre, textarea { font-family:var(--mono); font-size:12px; }
  textarea { width:100%; min-height:220px; }
  table.kv { border-collapse:collapse; width:100%; } .kv td { border-bottom:1px solid var(--line); padding:4px 6px; vertical-align:top; }
  .kv td:first-child { white-space:nowrap; color:#555; }
  .muted { color:#777; font-size:12px; }
  h2 { font-size:14px; margin:4px 0 8px; }
  .yamleditor { display:flex; gap:0; border:1px solid var(--line); border-radius:6px; overflow:hidden; max-height:380px; }
  .yamleditor .gutter { margin:0; padding:6px 8px; background:#f4f6fa; color:#99a; text-align:right; user-select:none; min-width:34px; overflow:hidden; }
  .yamleditor .highlight { margin:0; padding:6px 8px; flex:1; overflow:auto; white-space:pre; }
  .yamleditor textarea { flex:1; border:none; outline:none; resize:vertical; min-height:280px; padding:6px 8px; }
  .y-k { color:#1a56b0; font-weight:600; } .y-s { color:#188038; } .y-c { color:#999; font-style:italic; } .y-n { color:#b3261e; }
  .errline { background:#fdecea; color:#b3261e; font-weight:700; border-radius:3px; padding:0 2px; }
  .errmsg { color:#b3261e; display:inline-block; margin-left:10px; }
  .util { float:right; font-size:11px; border-radius:9px; padding:1px 8px; color:#fff; }
  .util.cool { background:#1e8e3e; } .util.warm { background:#f9ab00; } .util.hot { background:#d93025; }
</style>
</head>
<body>
<header>
  <h1>kube-scheduler-simulator <span class="muted" style="color:#cfe0ff">TPU-native</span></h1>
  <select id="sessionsel" onchange="onSessionPick()" title="session" style="border:none;border-radius:4px;padding:5px 8px"><option value="default">default</option></select>
  <input id="search" type="search" placeholder="filter…" style="border:none;border-radius:4px;padding:5px 8px;min-width:140px" oninput="onSearch()">
  <button id="viewtoggle" onclick="toggleView()">Tables</button>
  <button onclick="openMetrics()">Metrics</button>
  <button onclick="newResource()">+ Create</button>
  <button onclick="openSchedConfig()">Scheduler&nbsp;Config</button>
  <button onclick="doExport()">Export</button>
  <button onclick="doImport()">Import</button>
  <button onclick="doReset()">Reset</button>
</header>
<main id="clusterview">
  <div class="panel">
    <h2>Nodes &amp; Pods</h2>
    <div id="nodes"></div>
  </div>
  <div class="panel">
    <h2>Other resources</h2>
    <div id="others"></div>
    <h2 style="margin-top:14px">Autoscaler</h2>
    <div id="autoscaler" class="muted">…</div>
    <h2 style="margin-top:14px">Tuning</h2>
    <div id="tuning" class="muted">…</div>
  </div>
</main>
<main id="tablesview" style="display:none; grid-template-columns:1fr;">
  <div class="panel"><div id="tables"></div></div>
</main>
<dialog id="dlg"><div id="dlgbody"></div><p style="text-align:right"><button onclick="dlg.close()">Close</button></p></dialog>
<script src="/webui.js"></script>

</body>
</html>
"""

# The UI behavior is componentized into real asset files (the role of the
# reference's web/components/*), served individually at /webui/{name} and
# as the single concatenated /webui.js the page loads (classic scripts
# share one top-level lexical environment, so the concat is equivalent).
import os as _os

_ASSET_DIR = _os.path.join(_os.path.dirname(__file__), "webui_assets")
MODULE_ORDER = [
    "state.js",      # shared store: kinds, objects-by-key, search filter
    "api.js",        # fetch wrapper + HTML escaping + full refresh
    "sessions.js",   # session picker: X-KSS-Session fetch routing

    "quantity.js",   # kube resource.Quantity parsing + usage bars
    "editor.js",     # YAML editor pane: gutter, highlighting, error lines
    "clusterview.js",# nodes-and-pods view with utilization badges
    "tables.js",     # per-kind data tables (reference DataTables role)
    "dialogs.js",    # pod results / node capacity / object dialogs
    "forms.js",      # create/edit YAML, scheduler config, export/import
    "metrics.js",    # Prometheus metrics panel
    "autoscaler.js", # node-group table + autoscaler action feed
    "tuning.js",     # learned-scoring-head panel: run tuner, compare weights
    "watch.js",      # live list-watch stream + workload polling
    "main.js",       # bootstrap
]


def _load_modules() -> "dict[str, str]":
    out = {}
    for name in MODULE_ORDER:
        with open(_os.path.join(_ASSET_DIR, name), encoding="utf-8") as f:
            out[name] = f.read()
    return out


MODULES = _load_modules()
JS = "\n".join(f"// ==== {name} ====\n{src}" for name, src in MODULES.items())



# YAML creation templates per store kind, served at /api/v1/templates/{kind}
# (the role of the reference's web/components/lib/templates/*.yaml files).
# generateName is honored by the store with a deterministic counter suffix.
TEMPLATES_YAML = {
    "pods": """metadata:
  generateName: pod-
  namespace: default
  labels: {}
spec:
  containers:
    - name: main
      image: registry.k8s.io/pause:3.5
      resources:
        requests:
          cpu: 100m
          memory: 128Mi
  restartPolicy: Always
""",
    "nodes": """metadata:
  generateName: node-
  labels:
    topology.kubernetes.io/zone: zone-a
spec: {}
status:
  capacity:
    cpu: "4"
    memory: 32Gi
    pods: "110"
  allocatable:
    cpu: "4"
    memory: 32Gi
    pods: "110"
""",
    "deployments": """metadata:
  generateName: deployment-
  namespace: default
spec:
  replicas: 3
  selector:
    matchLabels:
      app: example
  template:
    metadata:
      labels:
        app: example
    spec:
      containers:
        - name: main
          resources:
            requests:
              cpu: 100m
              memory: 128Mi
""",
    "persistentvolumes": """metadata:
  generateName: pv-
spec:
  capacity:
    storage: 1Gi
  accessModes:
    - ReadWriteOnce
  persistentVolumeReclaimPolicy: Delete
  storageClassName: standard
""",
    "persistentvolumeclaims": """metadata:
  generateName: pvc-
  namespace: default
spec:
  accessModes:
    - ReadWriteOnce
  storageClassName: standard
  resources:
    requests:
      storage: 1Gi
""",
    "storageclasses": """metadata:
  generateName: storageclass-
provisioner: kubernetes.io/no-provisioner
volumeBindingMode: WaitForFirstConsumer
reclaimPolicy: Delete
""",
    "priorityclasses": """metadata:
  generateName: priorityclass-
value: 1000000
globalDefault: false
""",
    "namespaces": """metadata:
  generateName: namespace-
""",
    "nodegroups": """metadata:
  generateName: nodegroup-
spec:
  minSize: 0
  maxSize: 10
  priority: 0
  template:
    metadata:
      labels:
        topology.kubernetes.io/zone: zone-a
    spec: {}
    status:
      allocatable:
        cpu: "8"
        memory: 32Gi
        pods: "110"
""",
    "podgroups": """metadata:
  generateName: podgroup-
  namespace: default
spec:
  minMember: 4
  scheduleTimeoutSeconds: 300
  topologyPackKey: topology.kubernetes.io/zone
""",
    "scenarios": """metadata:
  generateName: scenario-
  namespace: default
spec:
  operations:
    - id: "1"
      step:
        major: 1
      createOperation:
        typeMeta:
          kind: Node
        object:
          metadata:
            generateName: node-
          status:
            allocatable:
              cpu: "4"
              memory: 32Gi
              pods: "110"
    - id: "2"
      step:
        major: 2
      createOperation:
        typeMeta:
          kind: Pod
        object:
          metadata:
            generateName: pod-
            namespace: default
          spec:
            containers:
              - name: main
                resources:
                  requests:
                    cpu: 100m
                    memory: 128Mi
    - id: "3"
      step:
        major: 3
      doneOperation: {}
""",
}
