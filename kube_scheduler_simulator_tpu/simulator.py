"""Simulator entry point: boot order mirroring the reference.

Reference ``startSimulator`` (simulator/simulator.go:32-106): config →
control plane → DI container → scheduler → optional cluster import → HTTP
server → wait for SIGTERM.  Here the control plane is the in-memory
ClusterStore (no external etcd / in-process kube-apiserver needed), and
the scheduler can run its TPU batch path.

Run:  python -m kube_scheduler_simulator_tpu  [--config config.yaml]
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

from kube_scheduler_simulator_tpu.config.simulator_config import new_config
from kube_scheduler_simulator_tpu.server import DIContainer, SimulatorServer
from kube_scheduler_simulator_tpu.services.importer import FileSnapSource

logger = logging.getLogger("simulator")


def start_simulator(config_path: "str | None" = None, use_batch: str = "auto", block: bool = True):
    cfg = new_config(config_path)

    # Read-replica mode (KSS_REPLICA_OF=<primary's KSS_JOURNAL_DIR>):
    # boot the same HTTP server read-only over a journal-shipped store —
    # no scheduler, no controllers, writes 405 until a promotion.
    from kube_scheduler_simulator_tpu.replication.replica import replica_knobs

    rknobs = replica_knobs()
    if rknobs is not None:
        from kube_scheduler_simulator_tpu.replication.replica import ReplicaContainer

        rdi = ReplicaContainer(rknobs["directory"], poll_s=rknobs["poll_s"], use_batch=use_batch)
        rdi.start_following()
        rserver = SimulatorServer(
            rdi,
            port=cfg.port,
            cors_allowed_origins=cfg.cors_allowed_origin_list,
            kube_api_port=cfg.kube_api_port,
        )
        rport = rserver.start(background=True)
        logger.info(
            "read replica started on :%d following %s", rport, rknobs["directory"]
        )
        if not block:
            return rserver
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        signal.signal(signal.SIGINT, lambda *_: stop.set())
        try:
            stop.wait()
        finally:
            rserver.shutdown()
        return rserver

    external_source = None
    if cfg.external_import_enabled and cfg.kubeconfig:
        # The reference imports via client-go against a real cluster
        # (importer.go:44-60); this build accepts a ResourcesForSnap file
        # exported from any cluster (kubectl-based exporters produce it).
        external_source = FileSnapSource(cfg.kubeconfig)

    di = DIContainer(
        initial_scheduler_cfg=cfg.initial_scheduler_cfg,
        use_batch=use_batch,
        external_snap_source=external_source,
        autoscale=cfg.autoscale,
        autoscaler_opts={
            "expander": cfg.autoscaler_expander,
            "scale_down_utilization_threshold": cfg.autoscaler_scale_down_threshold,
            "scale_down_unneeded_rounds": cfg.autoscaler_scale_down_rounds,
        },
    )
    if di.import_cluster_resource_service() is not None:
        di.import_cluster_resource_service().import_cluster_resources()

    server = SimulatorServer(
        di,
        port=cfg.port,
        cors_allowed_origins=cfg.cors_allowed_origin_list,
        kube_api_port=cfg.kube_api_port,
    )
    port = server.start(background=True)
    logger.info(
        "simulator server started on :%d (kube API on :%s)", port, server.kube_api_port
    )
    if cfg.etcd_url:
        # accepted-but-inert compatibility knob: a reference compose file
        # migrating here should hear that, not silence (docs/
        # simulator-server-config.md; VERDICT r5 #8)
        logger.warning(
            "etcdURL=%r is accepted for reference compatibility but INERT: "
            "this build has no etcd — state lives in the in-memory store; "
            "use /api/v1/export and /api/v1/import for persistence",
            cfg.etcd_url,
        )

    if not block:
        return server

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    try:
        stop.wait()
    finally:
        server.shutdown()
    return server


def main() -> None:
    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s %(message)s")
    ap = argparse.ArgumentParser(description="TPU-native kube-scheduler-simulator")
    ap.add_argument("--config", default=None, help="SimulatorConfiguration YAML path")
    ap.add_argument(
        "--use-batch",
        default="auto",
        choices=["off", "auto", "force"],
        help="TPU batch scheduling mode (default: auto)",
    )
    args = ap.parse_args()
    start_simulator(args.config, use_batch=args.use_batch)


if __name__ == "__main__":
    main()
