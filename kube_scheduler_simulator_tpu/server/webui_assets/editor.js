// YAML editor pane: line-number gutter, syntax-highlight preview, and
// server-error line marking — the role monaco plays in the reference UI
// (reference web/package.json:18-28 pulls monaco-editor; this page has no
// build step, so the editor is hand-rolled at the size the workflows
// need).  All create/edit/config dialogs route through openYamlEditor.

let activeEditor = null;

function yamlHighlightLine(line) {
  if (/^\s*#/.test(line)) return `<span class="y-c">${esc(line)}</span>`;
  const m = line.match(/^(\s*(?:- )?)("[^"]*"|'[^']*'|[^\s:#][^:]*)(:)(.*)$/);
  if (!m) return esc(line);
  let out = esc(m[1]) + `<span class="y-k">${esc(m[2])}</span>` + ":";
  const val = m[4];
  if (/^\s*["']/.test(val)) out += `<span class="y-s">${esc(val)}</span>`;
  else if (/^\s*-?[0-9.]+\s*$/.test(val)) out += `<span class="y-n">${esc(val)}</span>`;
  else out += esc(val);
  return out;
}

function yamlHighlight(src) {
  return String(src).split("\n").map(yamlHighlightLine).join("\n");
}

function renderGutter(gutter, count, errLine) {
  const out = [];
  for (let i = 1; i <= count; i++) {
    out.push(i === errLine ? `<span class="errline">${i}</span>` : String(i));
  }
  gutter.dataset.count = count;
  gutter.innerHTML = out.join("\n");
}

function markErrorLine(gutter, n) {
  renderGutter(gutter, Number(gutter.dataset.count) || 1, n);
}

function openYamlEditor(titleHtml, text, onApply, extraHtml) {
  const body = document.getElementById("dlgbody");
  body.innerHTML = `<h2>${titleHtml}</h2>` + (extraHtml || "");
  const wrap = document.createElement("div");
  wrap.className = "yamleditor";
  const gutter = document.createElement("pre");
  gutter.className = "gutter";
  const hl = document.createElement("pre");
  hl.className = "highlight";
  const ta = document.createElement("textarea");
  ta.id = "editbody";
  ta.value = text;
  ta.spellcheck = false;
  const err = document.createElement("p");
  err.className = "muted errmsg";
  const sync = () => {
    renderGutter(gutter, String(ta.value).split("\n").length, 0);
    hl.innerHTML = yamlHighlight(ta.value);
  };
  ta.oninput = sync;
  ta.onscroll = () => { gutter.scrollTop = hl.scrollTop = ta.scrollTop; };
  sync();
  wrap.appendChild(gutter);
  wrap.appendChild(hl);
  wrap.appendChild(ta);
  body.appendChild(wrap);
  const b = document.createElement("button");
  b.textContent = "Apply";
  b.addEventListener("click", async () => {
    err.textContent = "";
    try {
      await onApply(ta.value);
      activeEditor = null;
      dlg.close();
    } catch (e) {
      // surface the server's message and mark "line N" references in
      // the gutter (YAML parse errors carry them)
      err.textContent = e.message;
      const m = String(e.message).match(/line (\d+)/);
      if (m) markErrorLine(gutter, parseInt(m[1], 10));
    }
  });
  const p = document.createElement("p");
  p.appendChild(b);
  p.appendChild(err);
  body.appendChild(p);
  activeEditor = {ta, sync, gutter};
  dlg.showModal();
  return activeEditor;
}
