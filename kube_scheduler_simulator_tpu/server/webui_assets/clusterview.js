function render() {
  if (tablesMode) { renderTables(); return; }
  const nodesDiv = document.getElementById("nodes");
  nodesDiv.innerHTML = "";
  const buckets = {"(unscheduled)": []};
  for (const n of Object.values(state.nodes)) buckets[n.metadata.name] = [];
  for (const p of Object.values(state.pods)) {
    if (!matchesFilter(p)) continue;
    const nn = (p.spec||{}).nodeName;
    (buckets[nn] || buckets["(unscheduled)"]).push(p);
  }
  for (const [nodeName, pods] of Object.entries(buckets)) {
    if (nodeName === "(unscheduled)" && !pods.length) continue;
    const div = document.createElement("div");
    div.className = "node";
    const node = state.nodes[nodeName];
    const h = document.createElement("h3");
    h.textContent = nodeName + (node ? `  —  cpu ${((node.status||{}).allocatable||{}).cpu||"?"} / mem ${((node.status||{}).allocatable||{}).memory||"?"}` : "");
    if (node) {
      h.style.cursor = "pointer";
      h.onclick = () => showNode(node);
      // at-a-glance cpu pressure: requested/allocatable badge, colored
      // like the capacity bars in the node dialog
      const util = nodeCpuUtil(node, pods);
      const badge = document.createElement("span");
      badge.className = "util " + (util > 0.9 ? "hot" : util > 0.7 ? "warm" : "cool");
      badge.textContent = `${Math.min(100, Math.round(util * 100))}%`;
      h.appendChild(badge);
    }
    div.appendChild(h);
    for (const p of pods) {
      const s = document.createElement("span");
      s.className = "pod" + (nodeName === "(unscheduled)" ? " unsched" : "");
      s.textContent = key(p);
      s.onclick = () => showPod(p);
      div.appendChild(s);
    }
    nodesDiv.appendChild(div);
  }
  const others = document.getElementById("others");
  others.innerHTML = "";
  for (const k of KINDS) {
    if (k === "pods" || k === "nodes") continue;
    const row = document.createElement("div");
    row.className = "kindrow";
    row.innerHTML = `<b>${k}</b>`;
    for (const o of Object.values(state[k])) {
      if (!matchesFilter(o)) continue;
      const s = document.createElement("span");
      s.className = "item";
      s.textContent = key(o);
      s.onclick = () => showObject(k, o);
      row.appendChild(s);
    }
    others.appendChild(row);
  }
}

let tablesMode = false;
function toggleView() {
  tablesMode = !tablesMode;
  document.getElementById("clusterview").style.display = tablesMode ? "none" : "";
  document.getElementById("tablesview").style.display = tablesMode ? "grid" : "";
  document.getElementById("viewtoggle").textContent = tablesMode ? "Cluster" : "Tables";
  render();
}
