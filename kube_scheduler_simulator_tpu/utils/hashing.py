"""Deterministic 32-bit mixing for tie-break draws.

Upstream selectHost breaks score ties with an unseeded PRNG (reference
mirrors it at scheduler/scheduler.go:323-344) — any tied node is a valid
pick.  This build makes the draw reproducible AND path-independent: both
the sequential cycle (scheduler/framework_runner.py) and the batch kernel
(ops/batch.py) pick the k-th tied candidate in visit order, where k comes
from the same integer hash of (seed, per-pod attempt counter).  A counter-
keyed hash (rather than a shared PRNG stream) is what makes the two paths
agree: the draw for pod #c never depends on how many ties earlier pods had.

The kernel re-implements ``mix32`` with jnp.uint32 ops; the constants here
are the murmur3 finalizer's and must stay in sync with ops/batch.py.
"""

from __future__ import annotations

MASK32 = 0xFFFFFFFF
GOLDEN32 = 0x9E3779B9


def mix32(x: int) -> int:
    """murmur3's 32-bit finalizer (a bijection on uint32)."""
    x &= MASK32
    x ^= x >> 16
    x = (x * 0x85EBCA6B) & MASK32
    x ^= x >> 13
    x = (x * 0xC2B2AE35) & MASK32
    x ^= x >> 16
    return x


def tie_break_draw(seed: int, counter: int) -> int:
    """The uint32 draw for scheduling attempt ``counter`` under ``seed``."""
    return mix32(mix32(seed ^ GOLDEN32) ^ mix32(counter))
