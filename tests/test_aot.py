"""AOT executable artifact cache (ops/aot.py): jax.export round-trips.

The contract: serialize → reload → dispatch is BYTE-identical to a
fresh trace (bindings + the full annotation trail), a warm-loaded
engine holds zero steady-state recompiles, and every invalidation
(shape key, mesh spec, dtype regime, jax version, kernel digest,
corruption) is a COUNTED fallback to a fresh trace — never a crash.
Plus the committed reference artifacts under ``ops/aot_artifacts/``:
the repo carries module blobs a TPU host can load-and-run (exported
with platforms=["cpu","tpu"]), pinned here against the live kernel.
"""

from __future__ import annotations

import json
import os
import shutil

import numpy as np
import pytest

from kube_scheduler_simulator_tpu.analysis.runtime import RecompileGuard
from kube_scheduler_simulator_tpu.ops.aot import (
    COMMITTED_ARTIFACT_DIR,
    AotScanCache,
    reference_engine,
    reference_scan_workload,
)


def _mesh(n: int = 2):
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices("cpu")[:n]), ("nodes",))


def _docs(res, n_pods: int) -> list:
    """The byte surface under comparison: binding + filter/score/
    finalScore annotation JSON per pod."""
    return [
        (
            res.selected_nodes[i],
            res.filter_annotation_json(i),
            *res.score_annotations_json(i),
        )
        for i in range(n_pods)
    ]


@pytest.fixture()
def workload():
    return reference_scan_workload()


@pytest.fixture()
def cache_dir(tmp_path):
    return str(tmp_path / "aot")


class TestRoundTrip:
    def test_serialize_reload_dispatch_byte_identical(self, workload, cache_dir):
        nodes, pods = workload
        cold = reference_engine(cache_dir=cache_dir)
        d_cold = _docs(cold.schedule(nodes, pods, pods, []), len(pods))
        s = cold._aot.stats()
        assert s["aot_cache_misses_total"] == 1
        assert s["aot_cache_saves_total"] == 1
        assert s["aot_cache_fallbacks_by_reason"] == {}
        names = sorted(os.listdir(cache_dir))
        assert any(n.endswith(".bin") for n in names) and any(
            n.endswith(".json") for n in names
        )

        warm = reference_engine(cache_dir=cache_dir)
        d_warm = _docs(warm.schedule(nodes, pods, pods, []), len(pods))
        s = warm._aot.stats()
        assert s["aot_cache_hits_total"] == 1
        assert s["aot_cache_misses_total"] == 0
        # the warm engine never traced the scan (the compact fn still
        # builds fresh — it is not part of the artifact)
        assert d_warm == d_cold
        # steady state on the warm-loaded executable: zero recompiles
        with RecompileGuard("aot warm steady state") as g:
            d_again = _docs(warm.schedule(nodes, pods, pods, []), len(pods))
        assert g.compiles == 0
        assert d_again == d_cold

    def test_mesh_sharded_artifact_round_trip(self, workload, cache_dir):
        nodes, pods = workload
        single = reference_engine(cache_dir=cache_dir)
        d_single = _docs(single.schedule(nodes, pods, pods, []), len(pods))

        mesh_cold = reference_engine(mesh=_mesh(), cache_dir=cache_dir)
        d_mesh = _docs(mesh_cold.schedule(nodes, pods, pods, []), len(pods))
        s = mesh_cold._aot.stats()
        # the single-device artifact shares the shape digest but not the
        # configuration identity: classified, counted, then saved fresh
        assert s["aot_cache_fallbacks_by_reason"] == {"mesh-spec": 1}
        assert s["aot_cache_saves_total"] == 1
        assert d_mesh == d_single

        mesh_warm = reference_engine(mesh=_mesh(), cache_dir=cache_dir)
        d_warm = _docs(mesh_warm.schedule(nodes, pods, pods, []), len(pods))
        assert mesh_warm._aot.stats()["aot_cache_hits_total"] == 1
        assert d_warm == d_single
        with RecompileGuard("sharded aot warm steady state") as g:
            mesh_warm.schedule(nodes, pods, pods, [])
        assert g.compiles == 0


class TestInvalidation:
    def _seed(self, workload, cache_dir):
        nodes, pods = workload
        eng = reference_engine(cache_dir=cache_dir)
        docs = _docs(eng.schedule(nodes, pods, pods, []), len(pods))
        assert eng._aot.saves == 1
        return docs

    def test_jax_version_mismatch_counted_fresh_trace(self, workload, cache_dir):
        nodes, pods = workload
        d0 = self._seed(workload, cache_dir)
        side = next(
            os.path.join(cache_dir, n)
            for n in sorted(os.listdir(cache_dir))
            if n.endswith(".json")
        )
        with open(side) as f:
            j = json.load(f)
        j["jax-version"] = "0.0.1-foreign"
        with open(side, "w") as f:
            json.dump(j, f)
        eng = reference_engine(cache_dir=cache_dir)
        d1 = _docs(eng.schedule(nodes, pods, pods, []), len(pods))
        s = eng._aot.stats()
        assert s["aot_cache_fallbacks_by_reason"] == {"jax-version": 1}
        assert s["aot_cache_hits_total"] == 0
        assert d1 == d0  # the fresh trace, byte-identical

    def test_stale_artifact_is_refreshed_not_permanent(self, workload, cache_dir):
        """A rejected artifact must be OVERWRITTEN by the fresh build's
        save — a jax upgrade or kernel edit degrades the cache for one
        process, not forever (the save path self-heals)."""
        nodes, pods = workload
        self._seed(workload, cache_dir)
        side = next(
            os.path.join(cache_dir, n)
            for n in sorted(os.listdir(cache_dir))
            if n.endswith(".json")
        )
        with open(side) as f:
            j = json.load(f)
        j["jax-version"] = "0.0.1-foreign"
        with open(side, "w") as f:
            json.dump(j, f)
        healer = reference_engine(cache_dir=cache_dir)
        healer.schedule(nodes, pods, pods, [])
        s = healer._aot.stats()
        assert s["aot_cache_fallbacks_by_reason"] == {"jax-version": 1}
        assert s["aot_cache_saves_total"] == 1  # the stale file was replaced
        warm = reference_engine(cache_dir=cache_dir)
        warm.schedule(nodes, pods, pods, [])
        assert warm._aot.stats()["aot_cache_hits_total"] == 1

    def test_kernel_digest_mismatch_counted_fresh_trace(self, workload, cache_dir):
        nodes, pods = workload
        d0 = self._seed(workload, cache_dir)
        side = next(
            os.path.join(cache_dir, n)
            for n in sorted(os.listdir(cache_dir))
            if n.endswith(".json")
        )
        with open(side) as f:
            j = json.load(f)
        j["kernel-digest"] = "0" * 16
        with open(side, "w") as f:
            json.dump(j, f)
        eng = reference_engine(cache_dir=cache_dir)
        d1 = _docs(eng.schedule(nodes, pods, pods, []), len(pods))
        assert eng._aot.stats()["aot_cache_fallbacks_by_reason"] == {"kernel-digest": 1}
        assert d1 == d0

    def test_mesh_spec_mismatch_classified_not_missed(self, workload, cache_dir):
        """A mesh engine meeting a single-device-only cache must report
        WHY it fell back (mesh-spec), not a bare miss."""
        nodes, pods = workload
        self._seed(workload, cache_dir)
        eng = reference_engine(mesh=_mesh(), cache_dir=cache_dir)
        eng.schedule(nodes, pods, pods, [])
        s = eng._aot.stats()
        assert s["aot_cache_fallbacks_by_reason"] == {"mesh-spec": 1}
        assert s["aot_cache_misses_total"] == 0

    def test_shape_key_mismatch_is_a_miss(self, workload, cache_dir):
        nodes, pods = workload
        self._seed(workload, cache_dir)
        more_nodes, _ = reference_scan_workload(n_nodes=48)
        eng = reference_engine(cache_dir=cache_dir)
        eng.schedule(more_nodes, pods, pods, [])
        s = eng._aot.stats()
        assert s["aot_cache_misses_total"] == 1
        assert s["aot_cache_hits_total"] == 0

    def test_corrupt_artifact_counted_fresh_trace(self, workload, cache_dir):
        nodes, pods = workload
        d0 = self._seed(workload, cache_dir)
        bad = next(
            os.path.join(cache_dir, n)
            for n in sorted(os.listdir(cache_dir))
            if n.endswith(".bin")
        )
        with open(bad, "wb") as f:
            f.write(b"not a serialized module")
        eng = reference_engine(cache_dir=cache_dir)
        d1 = _docs(eng.schedule(nodes, pods, pods, []), len(pods))
        assert eng._aot.stats()["aot_cache_fallbacks_by_reason"] == {"corrupt": 1}
        assert d1 == d0

    def test_unwritable_cache_dir_never_fails_a_round(self, workload, tmp_path):
        nodes, pods = workload
        blocker = tmp_path / "blocked"
        blocker.write_text("a file where the cache dir should be")
        eng = reference_engine(cache_dir=str(blocker / "sub"))
        d1 = _docs(eng.schedule(nodes, pods, pods, []), len(pods))
        assert len(d1) == len(pods)
        s = eng._aot.stats()
        assert s["aot_cache_saves_total"] == 0
        assert s["aot_cache_fallbacks_by_reason"].get("export-error", 0) == 1


class TestCommittedArtifacts:
    """The checked-in reference artifacts: the repo ships modules a TPU
    host can load-and-run; CI pins them against the live kernel."""

    REGEN = (
        "committed AOT artifact does not load against the live tree — "
        "ops/batch.py changed since it was exported.  Regenerate with: "
        "JAX_PLATFORMS=cpu python scripts/gen_aot_artifact.py"
    )

    def _check(self, mesh):
        import jax

        nodes, pods = reference_scan_workload()
        warm = reference_engine(mesh=mesh, cache_dir=COMMITTED_ARTIFACT_DIR)
        before = sorted(os.listdir(COMMITTED_ARTIFACT_DIR))
        d_warm = _docs(warm.schedule(nodes, pods, pods, []), len(pods))
        s = warm._aot.stats()
        if s["aot_cache_fallbacks_by_reason"].get("jax-version"):
            pytest.skip(
                f"committed artifacts were exported under a different jax "
                f"({jax.__version__} here) — version fallback engaged as designed"
            )
        assert s["aot_cache_hits_total"] == 1, f"{self.REGEN} (stats: {s})"
        # the committed dir is read-only in spirit: a hit writes nothing
        assert sorted(os.listdir(COMMITTED_ARTIFACT_DIR)) == before
        fresh = reference_engine(mesh=mesh)
        assert fresh._aot is None
        d_fresh = _docs(fresh.schedule(nodes, pods, pods, []), len(pods))
        assert d_warm == d_fresh, "committed artifact dispatched different bytes"
        with RecompileGuard("committed artifact steady state") as g:
            warm.schedule(nodes, pods, pods, [])
        assert g.compiles == 0

    def test_single_device_artifact(self):
        self._check(mesh=None)

    def test_mesh_sharded_artifact(self):
        self._check(mesh=_mesh())

    def test_artifacts_declare_tpu_platform(self):
        """Every committed sidecar was exported for BOTH cpu and tpu —
        the load-and-run-on-a-TPU-host claim is in the artifact, not
        just the docs."""
        sides = [
            n for n in sorted(os.listdir(COMMITTED_ARTIFACT_DIR)) if n.endswith(".json")
        ]
        assert sides, "no committed artifacts — run scripts/gen_aot_artifact.py"
        for n in sides:
            with open(os.path.join(COMMITTED_ARTIFACT_DIR, n)) as f:
                side = json.load(f)
            assert set(side["platforms"]) >= {"cpu", "tpu"}, (n, side)


class TestServiceWiring:
    def test_service_metrics_and_render(self, monkeypatch, tmp_path):
        """KSS_AOT_CACHE_DIR reaches the service's engines through the
        normal env path, aggregates into service.metrics(), and renders
        on /metrics alongside the per-bank placer gauges."""
        monkeypatch.setenv("KSS_AOT_CACHE_DIR", str(tmp_path / "aot"))
        from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
        from kube_scheduler_simulator_tpu.server.metrics import render_metrics
        from kube_scheduler_simulator_tpu.state.store import ClusterStore
        from kube_scheduler_simulator_tpu.utils import SimClock

        store = ClusterStore(clock=SimClock(1_700_000_000.0))
        for i in range(6):
            store.create(
                "nodes",
                {
                    "metadata": {"name": f"n-{i}", "labels": {"kubernetes.io/hostname": f"n-{i}"}},
                    "status": {"allocatable": {"cpu": "8000m", "memory": "16Gi", "pods": "64"}},
                },
            )
        for i in range(4):
            store.create(
                "pods",
                {
                    "metadata": {"name": f"p-{i}", "namespace": "default"},
                    "spec": {
                        "containers": [
                            {"name": "c", "resources": {"requests": {"cpu": "100m"}}}
                        ]
                    },
                },
            )
        svc = SchedulerService(store, tie_break="first", use_batch="force", batch_min_work=1)
        svc.start_scheduler(None)
        svc.schedule_pending()
        m = svc.metrics()
        assert m["aot_cache_misses_total"] >= 1
        assert m["aot_cache_saves_total"] >= 1
        assert "placer_bank_rotations_total" in m
        assert isinstance(m["placer_banks"], dict)

        class _DI:
            cluster_store = store

            @staticmethod
            def scheduler_service():
                return svc

        text = render_metrics(_DI())
        for needle in (
            "simulator_aot_cache_hits_total",
            "simulator_aot_cache_misses_total",
            "simulator_aot_cache_saves_total",
            "simulator_aot_cache_fallbacks_total",
            "simulator_placer_bank_rotations_total",
            "simulator_placer_bank_scatter_updates_total",
            "simulator_placer_bank_plane_bytes_per_device",
        ):
            assert needle in text, needle
