"""KSS-DTYPE bad fixture 1: integer reductions without a pinned dtype.

Never imported — AST-only material for the rule self-test.  Lines
carrying the expect marker comment must be flagged, and no others.
"""

import jax.numpy as jnp


def victim_counts(mask, slots, feasible):
    pos = jnp.cumsum(mask.astype(jnp.int32), axis=1)  # expect-finding
    total = jnp.sum(feasible.astype(jnp.int32))  # expect-finding
    ranked = jnp.cumsum(slots > 0)  # expect-finding
    bools = jnp.sum(mask & feasible)  # expect-finding
    return pos, total, ranked, bools


def pinned_for_contrast(mask):
    # the same shapes with the dtype pinned: silent
    pos = jnp.cumsum(mask.astype(jnp.int32), axis=1, dtype=jnp.int32)
    total = jnp.sum(mask.astype(jnp.int32), dtype=jnp.int32)
    return pos, total
