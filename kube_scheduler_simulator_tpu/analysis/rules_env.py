"""KSS-ENV: every operator knob is documented; every documented knob is real.

The repo's env surface (``KSS_*`` / ``AUTOSCALE_*``) is its operator
API: an undocumented read is a knob nobody can discover, and a
documented name nobody reads is a knob that silently does nothing —
both directions have bitten (knobs documented in one PR, renamed in the
next).  The contract: the set of env names READ by the code equals the
set of names in ``docs/environment-variables.md``.

Read detection (AST): ``os.environ.get(K)`` / ``os.environ[K]`` /
``os.getenv(K)`` / ``environ.get(K)``, plus the repo's typed helpers —
any call whose callee name contains ``env`` (``env_str``, ``_env_pos``,
``env_float``...) with a matching string-literal first argument.
Writes (``os.environ[K] = ...``, ``setdefault``, monkeypatch) are not
reads.  Name literals that merely FLOW into a subprocess environment
dict are reads of nothing and are ignored.

Doc detection: every ``KSS_*``/``AUTOSCALE_*`` token in the doc file.

Findings are two-directional: ``undocumented env read`` anchored at the
read site, and ``documented but never read`` anchored at the doc line.
"""

from __future__ import annotations

import ast
import os
import re

from kube_scheduler_simulator_tpu.analysis.framework import (
    Finding,
    Project,
    Rule,
    SourceFile,
)

_NAME = re.compile(r"^(KSS|AUTOSCALE)_[A-Z0-9_]+$")
_DOC_TOKEN = re.compile(r"\b(?:KSS|AUTOSCALE)_[A-Z0-9_]+\b")
DOC_REL = "docs/environment-variables.md"


def _env_key(call: ast.Call) -> "str | None":
    """The env-var name a call READS, or None."""
    f = call.func
    first = call.args[0] if call.args else None
    lit = first.value if isinstance(first, ast.Constant) and isinstance(first.value, str) else None
    if lit is None or not _NAME.match(lit):
        return None
    if isinstance(f, ast.Attribute):
        # os.environ.get(K) / environ.get(K)
        if f.attr == "get":
            v = f.value
            if (isinstance(v, ast.Attribute) and v.attr == "environ") or (
                isinstance(v, ast.Name) and v.id == "environ"
            ):
                return lit
        if f.attr == "getenv":
            return lit
        if "env" in f.attr.lower():
            return lit
    elif isinstance(f, ast.Name) and "env" in f.id.lower():
        return lit
    return None


class EnvRule(Rule):
    name = "KSS-ENV"
    paths = None

    def check_file(self, src: SourceFile, ctx: Project) -> "list[Finding]":
        reads = ctx.shared.setdefault("env_reads", {})  # name → first (src, node)
        for node in ast.walk(src.tree):
            key = None
            if isinstance(node, ast.Call):
                key = _env_key(node)
            elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
                v = node.value
                is_environ = (isinstance(v, ast.Attribute) and v.attr == "environ") or (
                    isinstance(v, ast.Name) and v.id == "environ"
                )
                sl = node.slice
                if (
                    is_environ
                    and isinstance(sl, ast.Constant)
                    and isinstance(sl.value, str)
                    and _NAME.match(sl.value)
                ):
                    key = sl.value
            if key is not None and key not in reads:
                reads[key] = (src, node)
        return []

    def finalize(self, ctx: Project) -> "list[Finding]":
        if ctx.fixtures:
            # fixture runs carry their own miniature doc as a docstring:
            # the first fixture module's docstring lines starting with
            # "documents:" list the documented names
            documented: set[str] = set()
            doc_lines: dict[str, tuple[SourceFile, int]] = {}
            for src in ctx.files:
                if src.fixture_rule != self.name:
                    continue
                for i, line in enumerate(src.lines, 1):
                    if "documents:" in line:
                        for tok in _DOC_TOKEN.findall(line):
                            documented.add(tok)
                            doc_lines.setdefault(tok, (src, i))
        else:
            doc_path = os.path.join(ctx.root, DOC_REL)
            documented = set()
            doc_lines = {}
            if os.path.exists(doc_path):
                with open(doc_path, "r", encoding="utf-8") as f:
                    for i, line in enumerate(f, 1):
                        for tok in _DOC_TOKEN.findall(line):
                            documented.add(tok)
                            if tok not in doc_lines:
                                doc_lines[tok] = (None, i)

        reads: dict = ctx.shared.get("env_reads", {})
        out: list[Finding] = []
        for name, (src, node) in sorted(reads.items()):
            if name not in documented:
                out.append(
                    src.finding(
                        self.name,
                        node,
                        f"env var {name} is read here but not documented in "
                        f"{DOC_REL}: an undocumented knob is an operator API "
                        "nobody can discover. Add a row (name, default, "
                        "validation, effect).",
                    )
                )
        for name in sorted(documented - set(reads)):
            src, line = doc_lines[name]
            out.append(
                Finding(
                    rule=self.name,
                    file=src.rel if src is not None else DOC_REL,
                    line=line,
                    col=0,
                    symbol="<doc>",
                    message=(
                        f"env var {name} is documented but never read by the "
                        "code: a knob that silently does nothing. Delete the "
                        "row or implement the read."
                    ),
                )
            )
        return out
