"""Execution-level checks for the web UI's JavaScript (VERDICT r3 #5).

The image has no JS engine, so ``utils/jscheck`` implements the grammar:
a tokenizer + recursive-descent parser + scope resolver for the ES2017
subset the UI uses.  These tests parse the REAL served asset — a syntax
error or a misspelled identifier anywhere in it fails the suite — and
prove the checker's teeth by asserting that deliberately injected typos
turn it red (the round-3 verdict's done-condition).

The reference gets this guarantee from its Nuxt/TS build pipeline
(reference web/package.json:8-16); this is the no-toolchain analog.
"""

from __future__ import annotations

import re

import pytest

from kube_scheduler_simulator_tpu.server.webui import HTML, JS
from kube_scheduler_simulator_tpu.utils import jscheck
from kube_scheduler_simulator_tpu.utils.jscheck import JSError


def test_served_js_parses_and_resolves():
    # full parse + scope resolution: any syntax error or undeclared
    # identifier (typo'd function/variable/global) raises
    jscheck.check(JS)


def test_inline_html_handlers_resolve_against_js():
    """Every onclick/oninput/onchange snippet in the page (static HTML and
    the HTML fragments the JS itself injects) must parse and reference only
    names the JS declares at top level (or ids the page defines)."""
    top = jscheck.top_level_names(JS)
    # DOM elements with ids are window globals in browsers (the Close
    # button uses `dlg.close()`)
    ids = set(re.findall(r'id="([a-zA-Z_$][\w$]*)"', HTML) + re.findall(r'id="([a-zA-Z_$][\w$]*)"', JS))
    handlers = re.findall(r'on(?:click|input|change|submit)="([^"]+)"', HTML)
    handlers += re.findall(r'on(?:click|input|change|submit)="([^"]+)"', JS)
    assert len(handlers) >= 10, "expected the UI's toolbar handlers to be found"
    for snippet in handlers:
        jscheck.check(snippet, extra_globals=top | ids | {"this"})


@pytest.mark.parametrize(
    "name,mutate",
    [
        ("missing-paren", lambda js: js.replace("function render() {", "function render( {", 1)),
        ("unterminated-string", lambda js: js.replace('"(unscheduled)"', '"(unscheduled)', 1)),
        ("identifier-typo", lambda js: js.replace("renderTables();", "renderTable();", 1)),
        ("misspelled-global", lambda js: js.replace("document.getElementById", "documnet.getElementById", 1)),
        ("stray-brace", lambda js: js + "\n}"),
        ("broken-template", lambda js: js.replace("`/api/v1/resources/${k}`", "`/api/v1/resources/${k`", 1)),
        ("assign-to-undeclared", lambda js: js.replace("filterText = document", "filterTxt = document", 1)),
    ],
)
def test_injected_typo_turns_suite_red(name, mutate):
    broken = mutate(JS)
    assert broken != JS, f"{name}: mutation did not apply — marker moved?"
    with pytest.raises(JSError):
        jscheck.check(broken)


def test_checker_grammar_corners():
    """The constructs the UI leans on parse and resolve as a unit."""
    jscheck.check(
        """
        const K = [1, 2].map(x => x ** 2);
        async function f(a, b) {
          const {m, n} = await g(`t ${a} ${b.map(t=>`${t.k}=${t.v}`).join(",")}`);
          try { return m.replace(/&/g, "&amp;"); } catch (e) { return n || null; }
        }
        function g(s) { return {m: s, n: ""}; }
        for (const [k, v] of Object.entries({a: 1})) if (k) g(v);
        let x = 0;
        do { x += 1; } while (x < 3);
        switch (x) { case 3: break; default: x = 1; }
        """
    )
    with pytest.raises(JSError):
        jscheck.check("const a = ;")
    with pytest.raises(JSError):
        jscheck.check("function f( { return 1; }")
