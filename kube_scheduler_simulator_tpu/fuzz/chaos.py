"""The chaos layer: inject mid-run device faults the system must survive.

The scheduling engines promise that a kernel failure is never fatal and
never partial: a crashed dispatch, window fetch, or streamed
decision/result fetch degrades the round (or wave) to the sequential
path, byte-identical to a run where the crash never happened, with the
event counted (``batch_fallbacks`` / ``stream_drains_by_reason`` under
``kernel error: *``).  This module is the adversary that earns that
promise: a :class:`KernelChaos` context deterministically fails chosen
*device events* — every engine interaction gets a global sequence
number — and the differential runner then byte-compares the chaotic run
against a clean oracle.

Device events, in occurrence order across the whole context:

- ``schedule`` / ``schedule_async`` / ``schedule_waves`` — one event per
  engine call, ticked BEFORE dispatch (a failing event aborts with
  nothing committed);
- ``window`` — one per window fetched from a ``schedule_waves``
  iterator (failing event k leaves windows < k committed: the mid-round
  wave-restart shape);
- ``decisions`` / ``result`` — one per streamed fetch (failing before
  any of that wave committed).

Injection is via the service's ``_engine_for`` seam, so every profile
engine — and the stream session riding on it — sees the same chaos.
"""

from __future__ import annotations

from typing import Any, Iterator

Obj = dict[str, Any]


class ChaosError(RuntimeError):
    """The injected device fault (looks like any other kernel crash to
    the engines — they must not special-case it)."""


class _ChaosPendingBatch:
    """Wraps a PendingBatch so the streamed fetch points tick too."""

    def __init__(self, pb: Any, chaos: "KernelChaos"):
        object.__setattr__(self, "_pb", pb)
        object.__setattr__(self, "_chaos", chaos)

    def __getattr__(self, name: str) -> Any:
        return getattr(object.__getattribute__(self, "_pb"), name)

    def decisions(self) -> Any:
        self._chaos._tick("decisions")
        return self._pb.decisions()

    def result(self) -> Any:
        self._chaos._tick("result")
        return self._pb.result()


class _ChaosEngineProxy:
    """Forwards everything to the real engine; the dispatch surface
    (schedule / schedule_async / schedule_waves / window fetches) ticks
    the chaos counter first."""

    def __init__(self, eng: Any, chaos: "KernelChaos"):
        object.__setattr__(self, "_eng", eng)
        object.__setattr__(self, "_chaos", chaos)

    def __getattr__(self, name: str) -> Any:
        return getattr(object.__getattribute__(self, "_eng"), name)

    def schedule(self, *a: Any, **kw: Any) -> Any:
        self._chaos._tick("schedule")
        return self._eng.schedule(*a, **kw)

    def schedule_async(self, *a: Any, **kw: Any) -> Any:
        self._chaos._tick("schedule_async")
        return _ChaosPendingBatch(self._eng.schedule_async(*a, **kw), self._chaos)

    def schedule_waves(self, *a: Any, **kw: Any) -> Iterator:
        self._chaos._tick("schedule_waves")
        return self._chaos._wrap_windows(self._eng.schedule_waves(*a, **kw))


class KernelChaos:
    """Context manager failing the device events whose global sequence
    numbers are in ``fail_events``.  ``events`` counts all events seen,
    ``trips`` the injected failures — a test asserting chaos actually
    fired checks ``trips > 0``."""

    def __init__(self, svc: Any, fail_events: "frozenset[int] | set[int]" = frozenset({0})):
        self.svc = svc
        self.fail_events = frozenset(int(e) for e in fail_events)
        self.events = 0
        self.trips = 0
        self._orig: Any = None

    def _tick(self, what: str) -> None:
        e = self.events
        self.events += 1
        if e in self.fail_events:
            self.trips += 1
            raise ChaosError(f"injected kernel failure at device event #{e} ({what})")

    def _wrap_windows(self, gen: Iterator) -> Iterator:
        for item in gen:
            self._tick("window")
            yield item

    def __enter__(self) -> "KernelChaos":
        self._orig = self.svc._engine_for  # the bound method
        self.svc._engine_for = lambda fw: _ChaosEngineProxy(self._orig(fw), self)
        return self

    def __exit__(self, *exc: Any) -> None:
        # remove the instance attribute shadowing the class method
        self.svc.__dict__.pop("_engine_for", None)
        self._orig = None
