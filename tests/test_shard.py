"""Mesh-sharded scheduling: the node axis as the scale axis (ISSUE 9).

Pins the three promises of the sharded path:

- **Parity**: sharding the node axis over a mesh changes NO bytes —
  main scan (pre-existing suites), preemption victim search and
  autoscaler estimator (new here, randomized churn), including node
  counts that don't divide the device count (the engines pad).
- **The f32 story**: the batch kernel run with x64 DISABLED (the TPU
  dtype regime: float32 math, int32 planes) is byte-identical to the
  x64 sequential oracle at cfg4 scale — the GCD-scaled integer encoding
  is what makes low-precision device math exact.
- **TPU lowering**: the main scan (trace on/off), the victim search and
  the estimation dispatch all LOWER for the TPU platform, sharded and
  unsharded, via the cross-platform ``jax.export`` path — checkable
  from a CPU-only host; failures skip loudly with the reason.

Plus the ``KSS_MESH_DEVICES`` boundary validation (a bad device count
is a MeshConfigError naming the rule, never a jit shape error) and the
``shard_devices`` / ``sharded_dispatches_total`` /
``plane_shard_bytes_per_device`` observability contract.
"""

from __future__ import annotations

import random
from typing import Any

import numpy as np
import pytest

from kube_scheduler_simulator_tpu.ops import batch as B
from kube_scheduler_simulator_tpu.ops import encode as E
from kube_scheduler_simulator_tpu.ops import mesh as M
from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
from kube_scheduler_simulator_tpu.state.store import ClusterStore
from kube_scheduler_simulator_tpu.utils.parity import pod_parity_state

from tests.test_batch_parity import mk_node, mk_pod, profile_with

Obj = dict[str, Any]


def cpu_mesh(n: int):
    import jax
    from jax.sharding import Mesh

    devices = jax.devices("cpu")
    assert len(devices) >= n, "conftest forces an 8-device virtual CPU mesh"
    return Mesh(np.array(devices[:n]), ("nodes",))


def _stamp(p: Obj, i: int) -> Obj:
    p["metadata"]["creationTimestamp"] = f"2024-01-01T00:{i // 60:02d}:{i % 60:02d}Z"
    return p


# ------------------------------------------------- env-knob boundary


def test_mesh_env_knob_validation(monkeypatch):
    """KSS_MESH_DEVICES is validated at the boundary: every bad value is
    a MeshConfigError naming the broken rule — never a downstream jit
    shape error."""
    for bad in ("0", "-2", "abc", "1.5", ""):
        monkeypatch.setenv("KSS_MESH_DEVICES", bad)
        if bad.strip() == "":
            assert M.mesh_from_env() is None  # empty = unset
            continue
        with pytest.raises(M.MeshConfigError):
            M.mesh_from_env()
    # non-divisor counts (not a power of two: can't divide every node
    # bucket) are rejected with the padding rule in the message
    monkeypatch.setenv("KSS_MESH_DEVICES", "3")
    with pytest.raises(M.MeshConfigError, match="power of two"):
        M.mesh_from_env()
    # more devices than the host exposes
    monkeypatch.setenv("KSS_MESH_DEVICES", "1024")
    with pytest.raises(M.MeshConfigError, match="device"):
        M.mesh_from_env()
    # happy paths
    monkeypatch.setenv("KSS_MESH_DEVICES", "1")
    assert M.mesh_from_env() is None  # 1 = single-device, no mesh
    monkeypatch.setenv("KSS_MESH_DEVICES", "4")
    mesh = M.mesh_from_env()
    assert int(mesh.shape["nodes"]) == 4
    # resolve_mesh: "auto" consults the env; explicit Mesh passes through;
    # a mesh without the "nodes" axis is rejected
    assert int(M.resolve_mesh("auto").shape["nodes"]) == 4
    assert M.resolve_mesh(mesh) is mesh
    assert M.resolve_mesh(None) is None
    import jax
    from jax.sharding import Mesh

    with pytest.raises(M.MeshConfigError, match="nodes"):
        M.resolve_mesh(Mesh(np.array(jax.devices("cpu")[:2]), ("batch",)))


def test_service_mesh_env_plumbing(monkeypatch):
    """SchedulerService's default mesh="auto" picks the env knob up, the
    round runs sharded (byte-identical to single-device), and the
    shard_devices / sharded_dispatches_total /
    plane_shard_bytes_per_device observability lands in service.metrics()
    and the Prometheus rendering."""

    def build(env_devices: "str | None"):
        if env_devices is None:
            monkeypatch.delenv("KSS_MESH_DEVICES", raising=False)
        else:
            monkeypatch.setenv("KSS_MESH_DEVICES", env_devices)
        store = ClusterStore()
        # 13 nodes: deliberately NOT divisible by the 4-device mesh —
        # the engine pads the node axis to a device multiple
        for i in range(13):
            store.create("nodes", mk_node(f"n-{i}", cpu_m=4000, mem_mi=8192))
        rng = random.Random(5)
        for i in range(30):
            p = mk_pod(f"p-{i}", cpu_m=rng.choice([100, 200, 400]), mem_mi=128)
            store.create("pods", _stamp(p, i))
        svc = SchedulerService(store, tie_break="first", use_batch="force", batch_min_work=0)
        svc.start_scheduler(None)
        svc.schedule_pending(max_rounds=1)
        return store, svc

    s1, v1 = build(None)
    s2, v2 = build("4")
    assert v1.mesh is None and int(v2.mesh.shape["nodes"]) == 4
    d1, d2 = pod_parity_state(s1), pod_parity_state(s2)
    assert d1 == d2, "sharded round diverged from single-device bytes"
    m1, m2 = v1.metrics(), v2.metrics()
    assert m1["shard_devices"] == 0 and m1["sharded_dispatches_total"] == 0
    assert m2["shard_devices"] == 4
    assert m2["sharded_dispatches_total"] >= 1
    assert m2["plane_shard_bytes_per_device"] > 0
    # and the per-device bytes are genuinely smaller than the full tree
    assert m2["plane_shard_bytes_per_device"] < m2["device_bytes_uploaded_total"]

    class _DI:
        def __init__(self, svc):
            self._svc = svc
            self.cluster_store = svc.cluster_store

        def scheduler_service(self):
            return self._svc

    from kube_scheduler_simulator_tpu.server.metrics import render_metrics

    text = render_metrics(_DI(v2))
    assert "simulator_shard_devices 4" in text
    assert "simulator_sharded_dispatches_total" in text
    assert "simulator_plane_shard_bytes_per_device" in text


def test_field_sharding_non_divisible_is_clear_error():
    """Direct shard_device_problem users (no engine padding) get a clear
    ValueError naming the field and the fix, not a jit shape error."""
    mesh = cpu_mesh(8)
    with pytest.raises(ValueError, match="not divisible"):
        B.field_sharding(mesh, "alloc", np.zeros((13, 2)))


# ------------------------------------- preemption victim search, sharded


def _preempt_cluster(seed: int, n_nodes: int) -> ClusterStore:
    """A preemption-shaped cluster: full nodes, mixed-priority victims
    with PDB coverage, and higher-priority preemptors arriving last."""
    rng = random.Random(seed)
    store = ClusterStore()
    for i in range(n_nodes):
        store.create("nodes", mk_node(f"node-{i}", cpu_m=1000, mem_mi=2048))
    k = 0
    for i in range(n_nodes):
        for j in range(rng.choice([1, 2])):
            v = mk_pod(f"victim-{i}-{j}", cpu_m=rng.choice([400, 500]), mem_mi=128,
                       labels={"app": f"a{i % 3}"})
            v["spec"]["nodeName"] = f"node-{i}"
            v["spec"]["priority"] = rng.choice([0, 10])
            v.setdefault("status", {})["startTime"] = f"2024-01-01T01:00:{k % 60:02d}Z"
            store.create("pods", _stamp(v, k))
            k += 1
    store.create(
        "poddisruptionbudgets",
        {
            "metadata": {"name": "pdb-a0", "namespace": "default"},
            "spec": {"maxUnavailable": 1, "selector": {"matchLabels": {"app": "a0"}}},
        },
    )
    for i in range(3):
        vip = mk_pod(f"vip-{i}", cpu_m=rng.choice([600, 700]), mem_mi=64)
        vip["spec"]["priority"] = 1000
        store.create("pods", _stamp(vip, 100 + i))
    return store


def test_preemption_sharded_parity_randomized_churn():
    """The batched victim search sharded over a mesh is byte-identical
    to the unsharded batched path across randomized churn rounds —
    including a node count (7) the 4-device mesh must pad."""
    mesh = cpu_mesh(4)
    for seed, n_nodes in ((11, 7), (12, 8)):

        def run(m):
            store = _preempt_cluster(seed, n_nodes)
            svc = SchedulerService(
                store, tie_break="first", use_batch="auto", batch_min_work=0, mesh=m
            )
            svc.start_scheduler({"percentageOfNodesToScore": 100})
            svc.schedule_pending(max_rounds=1)
            # churn: evict one settled victim, add a fresh preemptor,
            # re-run — the second round's search sees mutated state
            for nm in sorted(
                p["metadata"]["name"]
                for p in store.list("pods")
                if p["metadata"]["name"].startswith("victim") and p["spec"].get("nodeName")
            )[:2]:
                store.delete("pods", nm, "default")
            extra = mk_pod("vip-late", cpu_m=500, mem_mi=64)
            extra["spec"]["priority"] = 2000
            store.create("pods", _stamp(extra, 200))
            svc.schedule_pending(max_rounds=1)
            return store, svc

        s1, v1 = run(None)
        s2, v2 = run(mesh)
        assert v2.stats["preempt_sharded_dispatches"] >= 1, "mesh search never engaged"
        assert v1.stats["preempt_sharded_dispatches"] == 0
        assert v1.stats["preempt_nominations"] == v2.stats["preempt_nominations"]
        d1, d2 = pod_parity_state(s1), pod_parity_state(s2)
        assert d1 == d2, (
            f"seed {seed}: sharded preemption diverged on "
            f"{sum(1 for kk in set(d1) | set(d2) if d1.get(kk) != d2.get(kk))} pods"
        )


# --------------------------------------- autoscaler estimator, sharded


def test_estimator_sharded_parity_randomized_churn():
    """Scale-up estimation sharded over the mesh returns the exact
    estimates of the unsharded dispatch across randomized churn (groups
    × pending pods mutate between estimates)."""
    from tests.test_autoscaler import mk_group, mk_pod as as_pod, mk_service
    from kube_scheduler_simulator_tpu.autoscaler.engine import ClusterAutoscaler

    mesh = cpu_mesh(4)
    for seed in (3, 4):

        def run(m):
            rng = random.Random(seed)
            store = ClusterStore()
            store.create("nodegroups", mk_group("small", mx=6, cpu="2000m", mem="4Gi"))
            store.create("nodegroups", mk_group("big", mx=5, cpu="8000m", mem="16Gi"))
            svc = mk_service(store)
            svc.mesh = m
            for i in range(rng.choice([5, 7])):
                store.create("pods", as_pod(f"p{i}", cpu=f"{rng.choice([500, 1500])}m"))
            svc.schedule_pending(max_rounds=1)
            asc = ClusterAutoscaler(store, svc)
            est1 = asc._estimator_for(svc.framework).estimate(
                sorted(asc.node_groups(), key=lambda g: g["metadata"]["name"]),
                {"small": 6, "big": 5},
                svc.framework.sort_pods(svc.pending_pods()),
            )
            # churn: more pending arrives, one group shrinks its headroom
            for i in range(3):
                store.create("pods", as_pod(f"q{i}", cpu="1200m"))
            est2 = asc._estimator_for(svc.framework).estimate(
                sorted(asc.node_groups(), key=lambda g: g["metadata"]["name"]),
                {"small": 2, "big": 5},
                svc.framework.sort_pods(svc.pending_pods()),
            )
            return [e.__dict__ for e in est1 + est2], asc._estimator

        r1, e1 = run(None)
        r2, e2 = run(mesh)
        assert e2.sharded_dispatches == 2 and e1.sharded_dispatches == 0
        assert e1.kernel_errors == 0 and e2.kernel_errors == 0
        assert all(e["method"] == "xla-batch" for e in r1)
        assert r1 == r2, f"seed {seed}: sharded estimation diverged"


# ------------------------------------------------- the f32 / TPU story


def test_f32_kernel_vs_x64_oracle_cfg4_scale():
    """VERDICT's standing wound: every parity suite forces x64, so the
    float32 numbers were unattested.  Run the batch kernel with x64
    DISABLED (float32 math, int32 planes — the TPU dtype regime) at
    cfg4 scale (5000 nodes, the cfg4 plugin mix) against the x64
    sequential oracle and pin ZERO byte mismatches on the annotation
    trail.  The oracle leg subsamples the pod queue (the bench's
    established method: with tie_break="first" the first K commits
    evolve identically), so its host wall stays test-sized while the
    kernel still scans the full cfg4 node axis."""
    import jax

    from bench import mk_node as b_node, mk_pod as b_pod
    from kube_scheduler_simulator_tpu.scheduler.batch_engine import BatchEngine

    N, P = 5000, 24
    rng = random.Random(42)
    nodes = [b_node(i) for i in range(N)]
    pods = [b_pod(i, rng, interpod=True) for i in range(P)]
    cfg = {
        "percentageOfNodesToScore": 100,
        "profiles": [profile_with(["NodeResourcesFit", "InterPodAffinity"])],
    }
    svc = SchedulerService(ClusterStore(), tie_break="first")
    for n in nodes:
        svc.cluster_store.create("nodes", n)
    for p in pods:
        svc.cluster_store.create("pods", p)
    svc.start_scheduler(cfg)
    fw = svc.framework
    pending = fw.sort_pods(svc.pending_pods())

    # f32 engine pass over the same pre-commit snapshot, x64 OFF.
    # (Explicit flag toggle, not jax.experimental.disable_x64(): the
    # context manager does not restore an env-var-derived True on exit.)
    jax.config.update("jax_enable_x64", False)
    try:
        assert jax.config.jax_enable_x64 is False
        # lower() picks the problem dtype from the live flag — attest f32
        tiny = E.encode(nodes[:2], [], pods[:1])
        assert B.lower(tiny)[0].alloc.dtype == np.float32
        eng = BatchEngine.from_framework(fw, trace=True, incremental=False)
        res = eng.schedule(
            svc.cluster_store.list("nodes"),
            svc.cluster_store.list("pods"),
            pending,
            svc.cluster_store.list("namespaces"),
        )
        filt = [res.filter_annotation_json(i) for i in range(P)]
        sco = [res.score_annotations_json(i) for i in range(P)]
    finally:
        jax.config.update("jax_enable_x64", True)

    # x64 sequential oracle commits the same queue
    assert jax.config.jax_enable_x64 is True
    svc.schedule_pending(max_rounds=1)

    mismatches = []
    compared = 0
    for i, key in enumerate(res.pod_keys):
        ns_, name_ = key.split("/", 1)
        pod = svc.cluster_store.get("pods", name_, ns_)
        annos = pod["metadata"].get("annotations") or {}
        if res.selected_nodes[i] != (pod.get("spec") or {}).get("nodeName"):
            mismatches.append((i, "binding"))
        for kind, got in (
            ("filter-result", filt[i]),
            ("score-result", sco[i][0]),
            ("finalscore-result", sco[i][1]),
        ):
            want = annos.get(f"scheduler-simulator/{kind}")
            if want is not None or got != "{}":
                compared += 1
                if want != got:
                    mismatches.append((i, kind))
    assert compared >= 2 * P, "annotation trail unexpectedly empty"
    assert not mismatches, (
        f"f32 kernel diverged from the x64 oracle on {len(mismatches)} "
        f"documents: {mismatches[:5]}"
    )


# ------------------------------------------------ TPU lowering dryruns


def _tiny_problem(node_multiple: int = 8):
    import __graft_entry__ as GE

    nodes, pods = GE._build_objects(P=8, N=32)
    pr = E.encode(nodes, pods, pods)
    pr = E.pad_problem(pr, node_multiple=node_multiple)
    return B.lower(pr)


def _require(ok: bool, info: str):
    """Pass, or skip LOUDLY with the lowering failure as the reason —
    the dryrun's contract (a silent pass would fake TPU coverage)."""
    if not ok:
        pytest.skip(f"TPU lowering dryrun unavailable: {info}")


@pytest.mark.parametrize("trace", [False, True])
def test_tpu_lowering_main_kernel(trace):
    """The main batch scan lowers for TPU — trace on and off, sharded
    (8-device mesh recorded in the export) and unsharded."""
    dp, dims = _tiny_problem()
    cfg = B.BatchConfig(
        filters=("NodeResourcesFit", "TaintToleration"),
        scores=(("NodeResourcesFit", 1), ("TaintToleration", 3)),
        trace=trace,
        sampling=False,
    )
    fn = B.build_batch_fn(cfg, dims)
    ok, info = M.tpu_lowering_dryrun(fn, (dp,))
    _require(ok, info)
    mesh = cpu_mesh(8)
    sdp = B.shard_device_problem(dp, mesh)
    ok, info = M.tpu_lowering_dryrun(fn, (sdp,))
    _require(ok, info)
    assert "8 device(s)" in info, info


def test_tpu_lowering_preemption_kernel():
    from kube_scheduler_simulator_tpu.preemption import kernel as PK

    U, N, V, R, PDB, S = 8, 32, 8, 2, 2, 8
    fn = PK.build_preempt_fn(U, N, V, R, PDB, S)
    args = (
        np.ones((U, N), bool), np.ones((U, R)), np.zeros(U, np.int64),
        np.zeros((U, S), bool),
        np.ones((N, R)), np.zeros((N, R)), np.zeros(N), np.full(N, 64.0),
        np.zeros((N, V, R)), np.zeros((N, V), np.int64), np.ones((N, V), bool),
        np.zeros((N, V, PDB), bool),
        np.zeros(PDB, np.int32), np.zeros((S, R)), np.zeros(S, np.int32),
    )
    ok, info = M.tpu_lowering_dryrun(fn, args)
    _require(ok, info)
    sargs = PK.shard_search_args(args, cpu_mesh(8))
    ok, info = M.tpu_lowering_dryrun(fn, sargs)
    _require(ok, info)
    assert "8 device(s)" in info, info


def test_tpu_lowering_estimator_kernel():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    dp, dims = _tiny_problem()
    cfg = B.BatchConfig(
        filters=("NodeResourcesFit",),
        scores=(("NodeResourcesFit", 1),),
        fit_strategy="MostAllocated",
        trace=False,
        sampling=False,
    )
    base = B.build_batch_fn(cfg, dims)
    axes = B.DeviceProblem(
        **{f: (0 if f == "node_active" else None) for f in B.DeviceProblem._fields}
    )
    vfn = jax.jit(jax.vmap(base, in_axes=(axes,)))
    G, N = 2, dims["N"]
    masks = np.zeros((G, N), bool)
    masks[0, : N // 2] = True
    masks[1, N // 2 :] = True
    ok, info = M.tpu_lowering_dryrun(vfn, (dp._replace(node_active=masks),))
    _require(ok, info)
    mesh = cpu_mesh(8)
    sdp = B.shard_device_problem(dp, mesh)
    sdp = sdp._replace(
        node_active=jax.device_put(masks, NamedSharding(mesh, P(None, "nodes")))
    )
    ok, info = M.tpu_lowering_dryrun(vfn, (sdp,))
    _require(ok, info)
    assert "8 device(s)" in info, info
