from kube_scheduler_simulator_tpu.state.store import (
    KINDS,
    NAMESPACED_KINDS,
    ClusterStore,
    Event,
    NotFoundError,
    AlreadyExistsError,
    ResourceExpiredError,
)
from kube_scheduler_simulator_tpu.state.journal import (
    Journal,
    JournalError,
    journal_from_env,
    journal_knobs,
)
from kube_scheduler_simulator_tpu.state.recovery import (
    RecoveryManager,
    RecoveryReport,
    boot_recover,
    build_checkpoint,
    restore_scheduler_state,
    scheduler_meta_provider,
    write_mark,
)

__all__ = [
    "KINDS",
    "NAMESPACED_KINDS",
    "ClusterStore",
    "Event",
    "NotFoundError",
    "AlreadyExistsError",
    "ResourceExpiredError",
    "Journal",
    "JournalError",
    "journal_from_env",
    "journal_knobs",
    "RecoveryManager",
    "RecoveryReport",
    "boot_recover",
    "build_checkpoint",
    "restore_scheduler_state",
    "scheduler_meta_provider",
    "write_mark",
]
