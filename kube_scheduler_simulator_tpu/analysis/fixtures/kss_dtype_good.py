"""KSS-DTYPE good fixture: pinned dtypes and float reductions — silent."""

import jax.numpy as jnp


def kernel_planes(n_nodes, mask, scores, weights):
    idx = jnp.arange(n_nodes, dtype=jnp.int32)
    acc = jnp.zeros((n_nodes, 2), dtype=jnp.float32)
    fail = jnp.full(n_nodes, -1, dtype=jnp.int8)
    flags = jnp.zeros((n_nodes,), bool)  # positional dtype idiom
    like = jnp.zeros_like(scores)  # inherits dtype
    pos = jnp.cumsum(mask.astype(jnp.int32), dtype=jnp.int32)
    # float reductions never promote: unpinned is fine
    total = jnp.sum(scores * weights)
    frac = jnp.sum(jnp.where(mask, scores, 0.0))
    cast_f = jnp.sum(mask.astype(scores.dtype))
    return idx, acc, fail, flags, like, pos, total, frac, cast_f
