"""Benchmark driver: the BASELINE.md configs on the TPU batch engine.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

The headline metric is pods×nodes plugin-scored per second on the largest
config that fits the run budget (BASELINE.md config table), measured over
the full batch pass (encode + transfer + XLA scan + result fetch) after one
compile warmup.  ``vs_baseline`` compares against the reference's only
quantitative cost model — the serialized O(pods × nodes × plugins) Go loop
(SURVEY.md §6: the reference publishes no benchmark numbers) — approximated
here by this repo's own sequential oracle on a subsampled workload,
extrapolated linearly.  Run with --quick for a smaller sweep.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

# The bench runs on whatever jax finds (real TPU under the driver; CPU in
# dev shells).  Do NOT force JAX_PLATFORMS here.


def _reexec_with_thp_malloc() -> None:
    """Re-exec once with huge-page-backed malloc (GLIBC_TUNABLES must be
    set before process start).  The churn bench holds gigabytes of
    annotation strings; 2 MB pages cut the TLB pressure that otherwise
    halves string throughput once the heap passes ~2 GB (measured ~20%
    end-to-end on cfg5).  Skipped when THP is disabled system-wide."""
    if os.environ.get("KSS_MALLOC_TUNED") or os.environ.get("KSS_NO_MALLOPT"):
        return
    try:
        with open("/sys/kernel/mm/transparent_hugepage/enabled") as f:
            if "[never]" in f.read():
                return
    except OSError:
        return
    env = dict(os.environ)
    env["KSS_MALLOC_TUNED"] = "1"
    tun = env.get("GLIBC_TUNABLES", "")
    if "glibc.malloc.hugetlb" not in tun:
        env["GLIBC_TUNABLES"] = (tun + ":" if tun else "") + "glibc.malloc.hugetlb=1"
        try:
            os.execve(sys.executable, [sys.executable] + sys.argv, env)
        except OSError:
            pass


def mk_node(i: int, zones: int = 8) -> dict:
    return {
        "metadata": {
            "name": f"node-{i}",
            "labels": {
                "topology.kubernetes.io/zone": f"zone-{i % zones}",
                "kubernetes.io/hostname": f"node-{i}",
                "disk": "ssd" if i % 2 else "hdd",
            },
        },
        "spec": (
            {"taints": [{"key": "spot", "value": "true", "effect": "PreferNoSchedule"}]}
            if i % 16 == 0
            else {}
        ),
        "status": {"allocatable": {"cpu": "64000m", "memory": "256Gi", "pods": "512"}},
    }


def mk_pod(i: int, rng: random.Random, spread: bool = False, interpod: bool = False) -> dict:
    spec: dict = {
        "containers": [
            {
                "name": "c",
                "resources": {
                    "requests": {
                        "cpu": f"{rng.choice([100, 250, 500, 1000])}m",
                        "memory": f"{rng.choice([128, 256, 512, 1024])}Mi",
                    }
                },
            }
        ]
    }
    labels = {"app": f"app-{i % 8}", "tier": "web" if i % 2 else "db"}
    if i % 4 == 0:
        spec["nodeSelector"] = {"disk": "ssd"}
    if spread:
        spec["topologySpreadConstraints"] = [
            {
                "maxSkew": 3,
                "topologyKey": "topology.kubernetes.io/zone",
                "whenUnsatisfiable": "DoNotSchedule",
                "labelSelector": {"matchLabels": {"app": f"app-{i % 8}"}},
            },
            {
                "maxSkew": 5,
                "topologyKey": "kubernetes.io/hostname",
                "whenUnsatisfiable": "ScheduleAnyway",
                "labelSelector": {"matchLabels": {"app": f"app-{i % 8}"}},
            },
        ]
    if interpod and i % 2:
        spec["affinity"] = {
            "podAntiAffinity": {
                "preferredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "weight": 10,
                        "podAffinityTerm": {
                            "labelSelector": {"matchLabels": {"app": f"app-{i % 8}"}},
                            "topologyKey": "kubernetes.io/hostname",
                        },
                    }
                ]
            }
        }
    return {"metadata": {"name": f"pod-{i}", "namespace": "default", "labels": labels}, "spec": spec}


def run_config(name, P, N, plugins, spread=False, interpod=False, oracle_sample=0):
    from kube_scheduler_simulator_tpu.scheduler.batch_engine import BatchEngine
    from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
    from kube_scheduler_simulator_tpu.state.store import ClusterStore

    rng = random.Random(42)
    nodes = [mk_node(i) for i in range(N)]
    pods = [mk_pod(i, rng, spread=spread, interpod=interpod) for i in range(P)]

    store = ClusterStore()
    for n in nodes:
        store.create("nodes", n)
    for p in pods:
        store.create("pods", p)
    svc = SchedulerService(store, tie_break="first")
    cfg = {"percentageOfNodesToScore": 100}
    if plugins is not None:
        cfg["profiles"] = [
            {
                "schedulerName": "default-scheduler",
                "plugins": {
                    "multiPoint": {
                        "enabled": [{"name": n} for n in ["PrioritySort", "DefaultBinder"] + plugins],
                        "disabled": [{"name": "*"}],
                    }
                },
            }
        ]
    svc.start_scheduler(cfg)
    fw = svc.framework
    eng = BatchEngine.from_framework(fw, trace=False)
    pending = fw.sort_pods(svc.pending_pods())
    ok, why = eng.supported(pending, nodes)
    assert ok, why

    all_pods = store.list("pods")
    namespaces = store.list("namespaces")
    # warmup (compile)
    t0 = time.perf_counter()
    res = eng.schedule(nodes, all_pods, pending, namespaces)
    compile_s = time.perf_counter() - t0
    # timed runs
    runs = []
    for _ in range(3):
        t0 = time.perf_counter()
        res = eng.schedule(nodes, all_pods, pending, namespaces)
        runs.append(time.perf_counter() - t0)
    best = min(runs)
    scheduled = sum(1 for s in res.selected_nodes if s)

    out = {
        "config": name,
        "pods": P,
        "nodes": N,
        # cfg1 is deliberately tiny: batch dispatch overhead exceeds the
        # sequential cycle there, which is why SchedulerService's auto
        # mode routes rounds below batch_min_work to the sequential path
        **({"note": "below batch_min_work in auto mode; sequential path serves this size"} if P * N < 2048 else {}),
        "wall_s": round(best, 4),
        "compile_s": round(compile_s, 2),
        "encode_s": round(eng.last_timings["encode_s"], 4),
        "device_s": round(eng.last_timings["device_s"], 4),
        "pods_nodes_per_s": round(P * N / best),
        "scheduled": scheduled,
    }

    # Baseline: this repo's sequential oracle (stands in for the reference's
    # serialized Go loop, which publishes no numbers) on a subsample,
    # extrapolated linearly in pods.  The same subsample doubles as the
    # BASELINE.md parity columns: with tie_break="first" and the same queue
    # order, the first `sample` commits evolve identically in both paths,
    # so selected-node identity and finalscore deltas are exact.
    if oracle_sample:
        sample = min(oracle_sample, P)
        svc2 = SchedulerService(ClusterStore(), tie_break="first")
        for n in nodes:
            svc2.cluster_store.create("nodes", n)
        for p in pods[:sample]:
            svc2.cluster_store.create("pods", p)
        svc2.start_scheduler(cfg)
        # traced kernel pass over the SAME subsampled cluster (captured
        # before the sequential run commits bindings)
        fw2 = svc2.framework
        pending2 = fw2.sort_pods(svc2.pending_pods())
        eng2 = BatchEngine.from_framework(fw2, trace=True)
        res2 = eng2.schedule(
            svc2.cluster_store.list("nodes"),
            svc2.cluster_store.list("pods"),
            pending2,
            svc2.cluster_store.list("namespaces"),
        )
        t0 = time.perf_counter()
        svc2.schedule_pending(max_rounds=1)
        seq_s = (time.perf_counter() - t0) * (P / sample)
        out["seq_est_s"] = round(seq_s, 2)
        out["speedup_vs_seq"] = round(seq_s / best, 1)
        identical = 0
        max_delta = 0
        for i, key in enumerate(res2.pod_keys):
            ns_, name_ = key.split("/", 1)
            pod = svc2.cluster_store.get("pods", name_, ns_)
            annos = pod["metadata"].get("annotations") or {}
            # compare the BINDING (profile-independent; the selected-node
            # annotation only exists when reserve plugins are enabled)
            if res2.selected_nodes[i] == (pod.get("spec") or {}).get("nodeName"):
                identical += 1
            want_final = json.loads(annos.get("scheduler-simulator/finalscore-result", "{}"))
            _score, got_final = res2.score_annotations(i)
            # symmetric: nodes/plugins present in only ONE side count as
            # a delta vs 0 (a one-directional walk would hide batch-only
            # divergences)
            for node_name in set(want_final) | set(got_final):
                want_row = want_final.get(node_name) or {}
                got_row = got_final.get(node_name) or {}
                for plug in set(want_row) | set(got_row):
                    delta = abs(int(got_row.get(plug, 0)) - int(want_row.get(plug, 0)))
                    max_delta = max(max_delta, delta)
        out["parity_selected_identical_pct"] = round(100.0 * identical / sample, 2)
        out["parity_max_abs_dfinalscore"] = max_delta
    return out


def run_churn(P_total=10000, N=5000, waves=5, delete_frac=0.1):
    """BASELINE cfg5: scenario-replay churn — the FULL default-plugins
    profile (percentageOfNodesToScore=0, so feasible-node sampling engages
    at this node count), pods arriving in waves with 10% of bound pods
    deleted between waves (keps/140 churn semantics).  Measures end-to-end
    service throughput: encode + kernel + commit + annotation flush every
    wave, compiled executables reused across waves via shape bucketing."""
    from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
    from kube_scheduler_simulator_tpu.state.store import ClusterStore

    rng = random.Random(7)
    store = ClusterStore()
    for i in range(N):
        store.create("nodes", mk_node(i))
    svc = SchedulerService(store, tie_break="first", use_batch="auto")
    svc.start_scheduler(None)  # full default KubeSchedulerConfiguration

    per_wave = P_total // waves
    created = 0
    scheduled = 0
    waves_done = 0
    wave_walls = []
    device_s = 0.0
    budget_s = 480.0  # soft cap so a driver bench run always completes
    t0 = time.perf_counter()
    for w in range(waves):
        for _ in range(per_wave):
            store.create("pods", mk_pod(created, rng, spread=created % 3 == 0))
            created += 1
        tw = time.perf_counter()
        dev_before = svc._batch_engine.cum_timings.get("device_s", 0.0) if svc._batch_engine else 0.0
        results = svc.schedule_pending(max_rounds=1)
        wave_walls.append(round(time.perf_counter() - tw, 2))
        eng = svc._batch_engine
        if eng:
            # cum delta: correct across mid-wave kernel restarts and
            # fallback waves (last_timings would double-count those)
            device_s += eng.cum_timings.get("device_s", 0.0) - dev_before
        scheduled += sum(1 for r in results.values() if r.success)
        waves_done += 1
        if time.perf_counter() - t0 > budget_s and w + 1 < waves:
            break
        bound = [p for p in store.list("pods") if (p.get("spec") or {}).get("nodeName")]
        for p in rng.sample(bound, int(len(bound) * delete_frac)):
            store.delete("pods", p["metadata"]["name"], p["metadata"].get("namespace"))
    wall = time.perf_counter() - t0
    eng = svc._batch_engine
    return {
        "config": "cfg5-churn-default-profile",
        "pods": scheduled,
        "nodes": N,
        "waves": waves_done,
        "wall_s": round(wall, 4),
        "wave_walls_s": wave_walls,
        "device_s": round(device_s, 2),
        "scheduled": scheduled,
        "pods_per_s": round(scheduled / wall),
        "pods_nodes_per_s": round(scheduled * N / wall),
        "compiles": eng.compiles if eng else 0,
        "batch_fallbacks": svc.stats["batch_fallbacks"],
        # measured byte-exact annotation trail per currently-stored pod —
        # the end-to-end number above INCLUDES producing and storing it
        "annotation_bytes_per_pod": _mean_annotation_bytes(store),
    }


def _mean_annotation_bytes(store) -> int:
    total = n = 0
    for p in store.list("pods", copy_objects=False):
        a = p["metadata"].get("annotations") or {}
        if a:
            total += sum(len(v) for v in a.values())
            n += 1
    return round(total / n) if n else 0


RESULTS: list = []  # accumulated config rows (watchdog reads them)


def _emit_line(results: list) -> None:
    headline = next((r for r in results if r.get("config") == "cfg4-interpod" and "wall_s" in r), None)
    if headline is None:
        headline = next((r for r in reversed(results) if "pods_nodes_per_s" in r), {})
    line = {
        "metric": "pods x nodes plugin-scored per second (batch engine, 10k pods x 5k nodes)",
        "value": headline.get("pods_nodes_per_s", 0),
        "unit": "pod-node pairs/s",
        # reference publishes no numbers (SURVEY.md section 6); baseline 1.0
        # = this repo's sequential oracle (the reference's loop shape),
        # so vs_baseline is the measured speedup over that loop.
        "vs_baseline": headline.get("speedup_vs_seq", 0),
        "north_star": {
            "target": "10k pods x 5k nodes scored in <1 s on one TPU chip",
            "wall_s": headline.get("wall_s"),
            "met": bool(headline.get("wall_s") and headline["wall_s"] < 1.0),
        },
        "configs": results,
    }
    print(json.dumps(line), flush=True)


def _start_watchdog(limit_s: float = 900.0) -> None:
    """The TPU tunnel can wedge hard (even device enumeration hangs); if
    the sweep exceeds the limit, print whatever completed as the one
    JSON line and exit instead of hanging the driver silently."""
    import threading

    def bite() -> None:
        RESULTS.append({"config": "watchdog", "error": f"bench exceeded {limit_s}s (TPU tunnel wedged?)"})
        _emit_line(RESULTS)
        os._exit(0)

    t = threading.Timer(limit_s, bite)
    t.daemon = True
    t.start()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="small sweep (CI/dev)")
    args = ap.parse_args()
    _start_watchdog()

    if args.quick:
        configs = [
            ("cfg1-fit", 100, 10, ["NodeResourcesFit"], False, False, 100),
        ]
    else:
        # The BASELINE.md config table — the default sweep IS the mandate.
        configs = [
            ("cfg1-fit", 100, 10, ["NodeResourcesFit"], False, False, 100),
            ("cfg2-fit-taint-aff", 1000, 500, ["NodeResourcesFit", "TaintToleration", "NodeAffinity"], False, False, 200),
            ("cfg3-spread", 5000, 2000, ["NodeResourcesFit", "PodTopologySpread"], True, False, 100),
            ("cfg4-interpod", 10000, 5000, ["NodeResourcesFit", "InterPodAffinity"], False, True, 50),
        ]

    results = RESULTS
    for cfg in configs:
        try:
            results.append(run_config(*cfg))
        except Exception as e:  # keep the bench line printable on partial failure
            results.append({"config": cfg[0], "error": f"{type(e).__name__}: {e}"})
    if not args.quick:
        try:
            results.append(run_churn())
        except Exception as e:
            results.append({"config": "cfg5-churn-default-profile", "error": f"{type(e).__name__}: {e}"})
    _emit_line(results)


if __name__ == "__main__":
    # only the bench PROCESS re-execs (importers like the profiling
    # scripts must not be replaced out from under themselves)
    _reexec_with_thp_malloc()
    sys.exit(main())
