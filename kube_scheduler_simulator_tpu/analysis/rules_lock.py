"""KSS-LOCK: attributes written under a class's lock stay under it.

The motivating bug (PR 6): EncodeCache's fingerprint tables are
read-modify-write state — the streaming pipeline diffing off the commit
thread interleaved with a sequential encode and double-applied bound
deltas until the aggregates corrupted.  The fix serialized ``encode()``
under an RLock, and a satellite added the copy-on-write
``stats_snapshot`` read so the metrics scrape never queues behind a
cold encode.  Both halves of that fix are a CONTRACT: state written
under the lock is lock-guarded state, and any access outside the lock
is either a bug or a deliberate lock-free pattern that must say so.

Mechanized per class (any class that takes a ``*lock*``-named lock in a
``with`` statement — its own ``self._lock`` or a collaborator's
``self.svc._stats_lock``):

1. **Guarded paths** — dotted attribute paths written (attribute
   assignment, augmented assignment, or subscript store — mutating
   ``self.stats[k]`` guards ``self.stats``) inside a ``with <lock>:``
   block, or inside a method transitively called from one (the
   ``encode() → _encode_locked → _apply_bound_delta`` pattern).  Local
   aliases are canonicalized (``svc = self.svc; svc.stats[...]`` is an
   access of ``self.svc.stats``).
2. **Violations** — loads or stores of a guarded path outside the
   lock's scope, in any method but ``__init__``/``__new__``
   (construction precedes sharing).  A violation is cleared by a
   ``# lock-free:`` justification comment on the access line or
   anywhere in the enclosing method — the comment IS the contract's
   escape hatch, and it must say why (GIL-atomic single-writer bump,
   copy-on-write publish, ...).
"""

from __future__ import annotations

import ast

from kube_scheduler_simulator_tpu.analysis.framework import Finding, Project, Rule, SourceFile

_MARKER = "lock-free:"


def _dotted(node: ast.AST) -> "str | None":
    """Attribute/Name chains → 'self.svc.stats'; anything else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_lockish(path: "str | None") -> bool:
    return path is not None and "lock" in path.rsplit(".", 1)[-1].lower() and "." in path


class _MethodInfo:
    def __init__(self, node: ast.FunctionDef):
        self.node = node
        self.aliases: dict[str, str] = {}  # local name → canonical dotted path
        self.locked_spans: list[tuple[int, int, str]] = []  # (lo, hi, lock path)
        self.locks_taken: set[str] = set()
        # (lock path, self-method name) pairs: the callee is invoked
        # under exactly THAT lock — a flat callee set would cross-product
        # every callee with every lock the method takes anywhere
        self.calls_under_lock: set[tuple[str, str]] = set()
        self.calls_anywhere: set[str] = set()


class LockRule(Rule):
    name = "KSS-LOCK"
    paths = None

    # ---------------------------------------------------------- per class

    def _canon(self, info: _MethodInfo, path: str) -> str:
        head, _, rest = path.partition(".")
        base = info.aliases.get(head)
        if base is not None:
            return base + ("." + rest if rest else "")
        return path

    def _scan_method(self, m: ast.FunctionDef) -> _MethodInfo:
        info = _MethodInfo(m)
        for node in ast.walk(m):
            # alias tracking: name = <dotted path rooted at self/cls>,
            # subscripts stripped — ``d = self.svc.stats["k"]`` makes a
            # mutation of ``d`` a mutation of state under self.svc.stats
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                rhs_node = node.value
                while isinstance(rhs_node, ast.Subscript):
                    rhs_node = rhs_node.value
                rhs = _dotted(rhs_node)
                if rhs is not None and rhs.split(".", 1)[0] in ("self", "cls"):
                    info.aliases[node.targets[0].id] = rhs
            if isinstance(node, ast.With):
                for item in node.items:
                    path = _dotted(item.context_expr)
                    path = self._canon(info, path) if path else None
                    if _is_lockish(path):
                        info.locked_spans.append(
                            (node.lineno, node.end_lineno or node.lineno, path)
                        )
                        info.locks_taken.add(path)
                        for sub in ast.walk(node):
                            if isinstance(sub, ast.Call):
                                cp = _dotted(sub.func)
                                if cp is not None and cp.startswith("self."):
                                    info.calls_under_lock.add((path, cp.split(".", 1)[1]))
            if isinstance(node, ast.Call):
                cp = _dotted(node.func)
                if cp is not None and cp.startswith("self."):
                    info.calls_anywhere.add(cp.split(".", 1)[1])
        return info

    @staticmethod
    def _write_targets(node: ast.AST) -> "list[ast.AST]":
        if isinstance(node, ast.Assign):
            return node.targets
        if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            return [node.target]
        return []

    def _accessed_paths(self, info: _MethodInfo, node: ast.AST, store: bool):
        """Canonical self-rooted paths this node reads (store=False) or
        writes (store=True).  A subscript store on ``x.stats[k]`` is a
        write of ``x.stats``."""
        out: list[tuple[str, ast.AST]] = []

        def emit(e: ast.AST):
            target = e
            while isinstance(target, ast.Subscript):
                target = target.value
            path = _dotted(target)
            if path is None:
                return
            path = self._canon(info, path)
            if path.split(".", 1)[0] in ("self", "cls") and "." in path:
                out.append((path, e))

        if store:
            for t in self._write_targets(node):
                if isinstance(t, ast.Name):
                    # rebinding a LOCAL name (even an alias of guarded
                    # state) writes the binding, not the object
                    continue
                emit(t)
        else:
            if isinstance(node, (ast.Attribute, ast.Subscript)) and isinstance(
                getattr(node, "ctx", None), ast.Load
            ):
                emit(node)
        return out

    def check_file(self, src: SourceFile, ctx: Project) -> "list[Finding]":
        out: list[Finding] = []
        for cls in ast.walk(src.tree):
            if isinstance(cls, ast.ClassDef):
                out.extend(self._check_class(src, cls))
        return out

    def _check_class(self, src: SourceFile, cls: ast.ClassDef) -> "list[Finding]":
        methods = [
            n for n in cls.body if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        infos = {m.name: self._scan_method(m) for m in methods}
        if not any(i.locks_taken for i in infos.values()):
            return []

        # transitive closure: methods called (by self.name) from under a
        # lock run lock-held for that lock.  NOTE: lexically taking a lock
        # in a with-block covers only that span (locked_spans), it does
        # NOT make the whole method lock-held — `held` carries call-chain
        # propagation only.
        held: dict[str, set[str]] = {}
        # seed: direct calls under a with-lock — (lock, callee) pairs, so
        # a helper called under lock B is never marked held under lock A
        work: list[tuple[str, str]] = []
        for name, i in infos.items():
            for lock, callee in i.calls_under_lock:
                if callee in infos:
                    work.append((callee, lock))
        while work:
            callee, lock = work.pop()
            if lock in held.get(callee, set()):
                continue
            held.setdefault(callee, set()).add(lock)
            # everything the callee calls anywhere now also runs under it
            for sub in infos[callee].calls_anywhere:
                if sub in infos:
                    work.append((sub, lock))

        # guarded paths: writes under a lock (lexically in a span, or in a
        # lock-held method), keyed by lock path
        guarded: dict[str, set[str]] = {}

        def record_writes(name: str, i: _MethodInfo):
            for node in ast.walk(i.node):
                for path, _e in self._accessed_paths(i, node, store=True):
                    locks = self._locks_at(i, node.lineno) | held.get(name, set())
                    for lk in locks:
                        if path != lk:
                            guarded.setdefault(lk, set()).add(path)

        for name, i in infos.items():
            if name in ("__init__", "__new__"):
                continue
            record_writes(name, i)
        if not guarded:
            return []

        out: list[Finding] = []
        comments = src.comments()
        for name, i in infos.items():
            if name in ("__init__", "__new__"):
                continue
            method_justified = any(
                _MARKER in c
                for ln, c in comments.items()
                if i.node.lineno <= ln <= (i.node.end_lineno or i.node.lineno)
            )
            if method_justified:
                continue
            for node in ast.walk(i.node):
                accesses = self._accessed_paths(i, node, store=True) + self._accessed_paths(
                    i, node, store=False
                )
                for path, e in accesses:
                    for lock, paths in guarded.items():
                        if path not in paths:
                            continue
                        if lock in self._locks_at(i, e.lineno) or lock in held.get(name, set()):
                            continue
                        out.append(
                            src.finding(
                                self.name,
                                e,
                                f"'{path}' is written under {lock} elsewhere in "
                                f"{cls.name} but accessed here without it: either "
                                "take the lock, or mark the deliberate lock-free "
                                "pattern with a '# lock-free: <why>' comment "
                                "(GIL-atomic single-writer bump, copy-on-write "
                                "publish, ...).",
                            )
                        )
                        break
        # one finding per line: collapse duplicates from nested walks
        seen: set[tuple[int, str]] = set()
        uniq: list[Finding] = []
        for f in sorted(out, key=lambda f: (f.line, f.message)):
            if (f.line, f.message) not in seen:
                seen.add((f.line, f.message))
                uniq.append(f)
        return uniq

    @staticmethod
    def _locks_at(info: _MethodInfo, lineno: int) -> "set[str]":
        return {lock for lo, hi, lock in info.locked_spans if lo <= lineno <= hi}
