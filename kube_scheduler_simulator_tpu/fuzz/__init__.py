"""Differential scenario fuzzer + chaos engine.

Every subsystem since PR 1 ships with a sequential oracle and a
byte-parity bar — a free differential-testing oracle.  This package is
the engine that drives it (docs/fuzzing.md):

- :mod:`fuzz.generator` + :mod:`fuzz.coverage` — seeded composite
  scenarios (gang x preemption x autoscale x churn x retune), sampled
  for structural diversity over coverage buckets;
- :mod:`fuzz.runner` + :mod:`fuzz.verdict` — each scenario executed
  through independent paths (batch vs sequential oracle, streamed vs
  serial, sharded vs single-device) with the full annotation trail
  diffed byte-for-byte; counted exactness-gate drains are explained
  routing, any byte mismatch is a divergence;
- :mod:`fuzz.shrink` — deterministic minimization of diverging
  scenarios down to committed ``fuzz/fixtures/`` with exact expected
  bytes;
- :mod:`fuzz.chaos` — mid-run kernel-failure injection; the engines
  must degrade to the sequential path without committing a partial or
  divergent wave.

Tier-1 runs a bounded seeded sweep (scripts/fuzz_smoke.py); the
``KSS_FUZZ_*`` knobs (docs/environment-variables.md) select seed,
scenario budget, shrink budget and the long-haul mode.
"""

from kube_scheduler_simulator_tpu.fuzz.coverage import (
    FEATURES,
    MESH_STREAM,
    MIN_COMPOSE,
    CoverageMap,
)
from kube_scheduler_simulator_tpu.fuzz.generator import generate_scenario
from kube_scheduler_simulator_tpu.fuzz.runner import (
    DEFAULT_COMPARISONS,
    FuzzHarness,
    FuzzHarnessError,
    encode_state,
    fuzz_knobs,
    run_differential,
)
from kube_scheduler_simulator_tpu.fuzz.shrink import (
    FIXTURE_DIR,
    canonical_json,
    iter_fixture_paths,
    load_fixture,
    make_fixture,
    replay_fixture,
    shrink,
    write_fixture,
)
from kube_scheduler_simulator_tpu.fuzz.chaos import (
    ChaosError,
    KernelChaos,
    ProcessChaos,
    ProcessChaosError,
)

__all__ = [
    "FEATURES",
    "MESH_STREAM",
    "MIN_COMPOSE",
    "CoverageMap",
    "generate_scenario",
    "DEFAULT_COMPARISONS",
    "FuzzHarness",
    "FuzzHarnessError",
    "encode_state",
    "fuzz_knobs",
    "run_differential",
    "FIXTURE_DIR",
    "canonical_json",
    "iter_fixture_paths",
    "load_fixture",
    "make_fixture",
    "replay_fixture",
    "shrink",
    "write_fixture",
    "ChaosError",
    "KernelChaos",
    "ProcessChaos",
    "ProcessChaosError",
]
