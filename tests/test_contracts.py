"""The kernel-contract analyzer (analysis/): rules, baseline, runtime.

Three layers of pinning:

1. **Fixture matrix** — every rule fires on its known-bad fixtures at
   exactly the marked lines and stays silent on the good fixtures
   (the same matrix scripts/check_contracts.py --selftest enforces in
   tier-1; here each rule is additionally exercised through the API).
2. **Live tree** — the repository itself, with analysis/baseline.toml
   applied, has zero findings: the contracts hold on the code that
   ships, and any new violation fails this test before it ships.
3. **Runtime** — RecompileGuard counts real backend compiles: a warmed
   dispatch is silent, a fresh shape raises, and the PR 7 estimator
   contract (live weight override ⇒ zero new compiles on the second
   estimate) plus the service-boundary weight-swap contract (value-only
   set_plugin_weights ⇒ zero recompiles) hold on a real service.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
from typing import Any

import pytest

from kube_scheduler_simulator_tpu.analysis import (
    BaselineError,
    RecompileGuard,
    apply_baseline,
    compile_count,
    load_baseline,
    run_analysis,
)
from kube_scheduler_simulator_tpu.analysis.framework import PACKAGE, repo_root
from kube_scheduler_simulator_tpu.analysis.runtime import RecompileError
from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
from kube_scheduler_simulator_tpu.state.store import ClusterStore

from tests.test_batch_parity import mk_node, mk_pod

Obj = dict[str, Any]

ROOT = repo_root()
FIXDIR = os.path.join(ROOT, PACKAGE, "analysis", "fixtures")
RULES = ("KSS-DTYPE", "KSS-HOST-SYNC", "KSS-HOT-RENDER", "KSS-DONATE", "KSS-ENV", "KSS-LOCK")


# ---------------------------------------------------------- fixture matrix


def _fixture_report():
    return run_analysis(fixtures=True, baseline_path=None)


def _expected_lines(fname: str) -> set[int]:
    marker = re.compile(r"#\s*expect-finding\b")
    with open(os.path.join(FIXDIR, fname), "r", encoding="utf-8") as f:
        return {i for i, ln in enumerate(f.read().splitlines(), 1) if marker.search(ln)}


@pytest.mark.parametrize(
    "fname",
    sorted(f for f in os.listdir(FIXDIR) if f.endswith(".py")),
)
def test_fixture_matrix(fname):
    """Bad fixtures are flagged at exactly their marked lines (by the
    rule the fixture belongs to); good fixtures are silent."""
    report = _fixture_report()
    rel = f"{PACKAGE}/analysis/fixtures/{fname}"
    got = {f.line: f.rule for f in report["findings"] if f.file == rel}
    expected = _expected_lines(fname)
    if "_bad_" in fname:
        assert expected, f"{fname}: a bad fixture must carry expect markers"
        assert set(got) == expected, (
            f"{fname}: flagged lines {sorted(got)} != expected {sorted(expected)}"
        )
        slug = fname.split("_bad_")[0].replace("kss_", "kss-").replace("_", "-").upper()
        assert all(r == slug for r in got.values()), got
    else:
        assert not got, f"{fname}: good fixture flagged: {got}"
        assert not expected, f"{fname}: good fixture carries expect markers"


def test_every_rule_demonstrated_twice():
    report = _fixture_report()
    by_rule: dict[str, int] = {}
    for f in report["findings"]:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    for rule in RULES:
        assert by_rule.get(rule, 0) >= 2, (rule, by_rule)


# --------------------------------------------------------------- live tree


def test_live_tree_clean_with_baseline():
    """The shipping tree holds every contract (baseline applied); the
    baseline itself is fully used — a stale suppression is a failure
    here so the allowlist shrinks as code heals."""
    report = run_analysis()
    assert not report["errors"], report["errors"]
    assert not report["findings"], "\n".join(f.render() for f in report["findings"])
    assert not report["unused_suppressions"], [
        (s.rule, s.file, s.symbol) for s in report["unused_suppressions"]
    ]


def test_live_tree_has_baselined_findings():
    """The suppressions are real: running WITHOUT the baseline surfaces
    the justified findings (the baseline documents them, it doesn't
    imagine them)."""
    report = run_analysis(baseline_path=None)
    assert report["suppressed"] == []
    assert report["findings"], "baseline entries exist, so raw findings must too"


# ----------------------------------------------------------------- baseline


def test_baseline_requires_justification(tmp_path):
    p = tmp_path / "baseline.toml"
    p.write_text('[[suppress]]\nrule = "KSS-DTYPE"\n')
    with pytest.raises(BaselineError, match="justification"):
        load_baseline(str(p))
    p.write_text('[[suppress]]\nrule = "KSS-DTYPE"\njustification = "  "\n')
    with pytest.raises(BaselineError, match="justification"):
        load_baseline(str(p))
    p.write_text(
        '[[suppress]]\nrule = "KSS-DTYPE"\nbogus_key = 1\njustification = "x"\n'
    )
    with pytest.raises(BaselineError, match="unknown keys"):
        load_baseline(str(p))


def test_baseline_matching_and_unused(tmp_path):
    p = tmp_path / "baseline.toml"
    p.write_text(
        "\n".join(
            [
                "[[suppress]]",
                'rule = "KSS-DTYPE"',
                'file = "*/fixtures/kss_dtype_bad_1.py"',
                'justification = "test"',
                "[[suppress]]",
                'rule = "KSS-LOCK"',
                'symbol = "NoSuchClass.*"',
                'justification = "stale"',
            ]
        )
    )
    sups = load_baseline(str(p))
    findings = _fixture_report()["findings"]
    kept, suppressed = apply_baseline(findings, sups)
    assert suppressed and all(
        f.file.endswith("kss_dtype_bad_1.py") for f, _s in suppressed
    )
    assert all(not f.file.endswith("kss_dtype_bad_1.py") for f in kept)
    assert [s.rule for s in sups if not s.used] == ["KSS-LOCK"]


# ---------------------------------------------------------------- CLI gate


def test_cli_selftest_and_live_exit_codes():
    """The tier-1 wiring end to end: --selftest exit 0 (fixtures fire),
    live run exit 0 (tree clean), and an injected violation — a bad
    fixture dropped into the scanned tree — flips the live run nonzero."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    cli = os.path.join(ROOT, "scripts", "check_contracts.py")
    r = subprocess.run(
        [sys.executable, cli, "--selftest"], capture_output=True, text=True, env=env
    )
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run([sys.executable, cli], capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    # inject: a kernel-module file with an unpinned integer reduction
    bad = os.path.join(ROOT, PACKAGE, "ops", "_contracts_injected_violation.py")
    with open(bad, "w", encoding="utf-8") as f:
        f.write(
            "import jax.numpy as jnp\n\n\n"
            "def injected(mask):\n"
            "    return jnp.cumsum(mask.astype(jnp.int32))\n"
        )
    try:
        r = subprocess.run(
            [sys.executable, cli, "--json"], capture_output=True, text=True, env=env
        )
        assert r.returncode == 1, r.stdout + r.stderr
        assert "_contracts_injected_violation" in r.stdout
        assert '"ok": false' in r.stdout
    finally:
        os.unlink(bad)


# ------------------------------------------------------------------ runtime


def test_recompile_guard_counts_and_raises():
    import jax
    import jax.numpy as jnp

    import numpy as np

    fn = jax.jit(lambda x: x * 2.0 + 1.0)
    # inputs prepared OUTSIDE the guards: even a jnp.ones() literal
    # compiles its own broadcast kernel, which is exactly what the guard
    # is built to catch
    x3a, x3b, x5, x7 = (np.ones((n,), np.float32) for n in (3, 3, 5, 7))
    fn(x3a)  # warm outside the guard
    with RecompileGuard("warmed dispatch") as g:
        fn(x3b)
    assert g.compiles == 0
    before = compile_count()
    with pytest.raises(RecompileError, match="warm shapes"):
        with RecompileGuard("warm shapes"):
            fn(x5)  # fresh shape: must be counted and raised
    assert compile_count() > before
    # max_compiles budgets an expected warmup
    with RecompileGuard("bounded warmup", max_compiles=1) as g:
        fn(x7)
    assert g.compiles == 1


def _estimator_cluster() -> "tuple[ClusterStore, SchedulerService]":
    store = ClusterStore()
    store.create(
        "nodegroups",
        {
            "metadata": {"name": "g1"},
            "spec": {
                "minSize": 0,
                "maxSize": 8,
                "priority": 0,
                "template": {
                    "metadata": {"labels": {}},
                    "spec": {},
                    "status": {
                        "allocatable": {"cpu": "4000m", "memory": "8Gi", "pods": "20"}
                    },
                },
            },
        },
    )
    svc = SchedulerService(store, tie_break="first", use_batch="off")
    svc.start_scheduler(None)
    return store, svc


def test_estimator_weight_override_zero_recompiles_on_second_estimate():
    """The PR 7 estimator contract, pinned at the runtime layer: with a
    live traced-weights override installed, the FIRST estimate may
    compile (cold executables), the SECOND may not — the estimator's
    fn-cache plus its constant-folded weight pin must hold under the
    override, or every autoscaler pass becomes a compile storm."""
    from kube_scheduler_simulator_tpu.autoscaler import ClusterAutoscaler

    store, svc = _estimator_cluster()
    svc.set_plugin_weights({"NodeResourcesFit": 2.5})
    for i in range(4):
        store.create("pods", mk_pod(f"rg-{i}", cpu_m=1500, mem_mi=1024))
    svc.schedule_pending(max_rounds=1)
    asc = ClusterAutoscaler(store, svc)
    action = asc.scale_up(svc.pending_pods())
    assert action["method"] == "xla-batch", action
    est = asc._estimator
    assert est is not None and est.kernel_errors == 0
    with RecompileGuard("estimator second estimate under weight override"):
        action2 = asc.scale_up(svc.pending_pods())
    assert action2["method"] == "xla-batch", action2
    assert est.kernel_errors == 0


def test_set_plugin_weights_value_change_keeps_engines(monkeypatch):
    """The service-boundary half of the same contract: a VALUE-only
    weight change on an already-traced engine swaps the vector in place
    (zero recompiles, engines preserved); clearing the override is a
    mode change and legitimately rebuilds.  The incremental placer is
    pinned OFF so its lazily-engaged scatter kernels (whose row-bucket
    shapes vary with churn, a legitimate warmup) don't alias the
    contract under test."""
    monkeypatch.setenv("KSS_ENCODE_INCREMENTAL", "0")
    store = ClusterStore(clock=lambda: 1700000000.0)
    for i in range(4):
        store.create("nodes", mk_node(f"n-{i}", cpu_m=8000, mem_mi=16384))
    svc = SchedulerService(store, tie_break="first", use_batch="force", batch_min_work=0)
    svc.start_scheduler(None)
    svc.set_plugin_weights({"NodeResourcesFit": 2.0})
    for i in range(6):
        store.create("pods", mk_pod(f"w-{i}", cpu_m=200, mem_mi=256))
    svc.schedule_pending()  # warm the traced executables (cold uploads)
    for i in range(6):
        store.create("pods", mk_pod(f"w2-{i}", cpu_m=200, mem_mi=256))
    svc.schedule_pending()  # warm the placer's scatter-update kernels too
    eng_before = svc._batch_engine
    assert eng_before is not None and eng_before.cfg.traced_weights
    svc.set_plugin_weights({"NodeResourcesFit": 3.5})
    assert svc._batch_engine is eng_before, "value-only change must keep the engine"
    for i in range(6):
        store.create("pods", mk_pod(f"w3-{i}", cpu_m=200, mem_mi=256))
    with RecompileGuard("weight value change on warmed engines"):
        svc.schedule_pending()
    # clearing the override IS a mode change: engines rebuild
    svc.set_plugin_weights(None)
    assert svc._batch_engine is None
