// Autoscaler panel: node-group table + recent action feed, fed by
// GET /api/v1/autoscaler (autoscaler/engine.py status()).  Rendered into
// the #autoscaler section of the right-hand panel; polled alongside the
// workload kinds (the watch stream doesn't carry autoscaler state).
let autoscalerStatus = null;

async function refreshAutoscaler() {
  try {
    autoscalerStatus = await api("GET", "/api/v1/autoscaler");
  } catch (e) { autoscalerStatus = null; }
  renderAutoscaler();
}

function renderAutoscaler() {
  const root = document.getElementById("autoscaler");
  if (!root) return;
  const st = autoscalerStatus;
  if (!st || st.mode === "off" || st.mode === undefined) {
    root.innerHTML = '<span class="muted">autoscaler off (AUTOSCALE_MODE=on|scenario enables it)</span>';
    return;
  }
  let html = `<div class="muted">mode ${esc(st.mode)} · expander ${esc(st.expander || "")} · ` +
             `scale-ups ${(st.stats||{}).scale_ups||0} · scale-downs ${(st.stats||{}).scale_downs||0} · ` +
             `est ${(st.estimator||{}).dispatches||0} dispatches</div>`;
  const groups = st.groups || [];
  if (groups.length) {
    html += '<table class="kv"><tr><td><b>group</b></td><td><b>size</b></td><td><b>bounds</b></td><td><b>nodes</b></td></tr>';
    for (const g of groups) {
      html += `<tr><td>${esc(g.name)}</td><td>${g.currentSize}</td>` +
              `<td>[${g.minSize}, ${g.maxSize}]</td>` +
              `<td class="muted">${(g.nodes||[]).map(esc).join(", ")}</td></tr>`;
    }
    html += "</table>";
  } else {
    html += '<div class="muted">no node groups (create one via /api/v1/nodegroups)</div>';
  }
  const events = (st.events || []).slice(-8).reverse();
  if (events.length) {
    html += '<div style="margin-top:6px"><b>recent actions</b></div>';
    for (const ev of events) {
      const what = ev.action === "ScaleUp"
        ? `+${(ev.nodes||[]).length} node(s) → ${esc(ev.nodeGroup)} (${ev.podsFit} pods fit, ${esc(ev.method||"")})`
        : `-${(ev.nodes||[]).length} node(s) ← ${esc(ev.nodeGroup)} (util ${ev.utilization})`;
      html += `<div class="kindrow">${ev.action === "ScaleUp" ? "▲" : "▼"} ${what}</div>`;
    }
  }
  root.innerHTML = html;
}
