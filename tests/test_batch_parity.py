"""Batch (TPU kernel) vs sequential oracle parity.

The sequential framework runner pins the reference's upstream v1.26
scheduling semantics (it is itself golden-tested); these suites assert the
batch engine reproduces its decisions — selected node per pod, feasible
sets, raw/normalized scores — on randomized workloads covering the
BASELINE.md benchmark configs 1-4 plugin sets.

Tie-break is set to "first" on the oracle (argmax semantics) since the
upstream reservoir tie-break is intentionally random (BASELINE parity is
measured on finalscore + selected-node identity modulo score ties).
"""

from __future__ import annotations

import random
from typing import Any

import pytest

from kube_scheduler_simulator_tpu.config import scheduler_config as sc
from kube_scheduler_simulator_tpu.scheduler.batch_engine import BatchEngine
from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
from kube_scheduler_simulator_tpu.state.store import ClusterStore

Obj = dict[str, Any]


def mk_node(name: str, cpu_m: int, mem_mi: int, pods: int = 110, labels=None, taints=None, unschedulable=False) -> Obj:
    n: Obj = {
        "metadata": {"name": name, "labels": labels or {}},
        "status": {
            "allocatable": {
                "cpu": f"{cpu_m}m",
                "memory": f"{mem_mi}Mi",
                "pods": str(pods),
            }
        },
        "spec": {},
    }
    if taints:
        n["spec"]["taints"] = taints
    if unschedulable:
        n["spec"]["unschedulable"] = True
    return n


def mk_pod(name: str, cpu_m: int = 0, mem_mi: int = 0, labels=None, ns: str = "default", **spec_extra) -> Obj:
    reqs = {}
    if cpu_m:
        reqs["cpu"] = f"{cpu_m}m"
    if mem_mi:
        reqs["memory"] = f"{mem_mi}Mi"
    spec: Obj = {"containers": [{"name": "c", "resources": {"requests": reqs} if reqs else {}}]}
    spec.update(spec_extra)
    return {"metadata": {"name": name, "namespace": ns, "labels": labels or {}}, "spec": spec}


def profile_with(plugin_names: list[str]) -> Obj:
    """A profile enabling exactly the given plugins (plus queue/bind infra)."""
    base = ["PrioritySort", "DefaultBinder"]
    return {
        "schedulerName": "default-scheduler",
        "plugins": {
            "multiPoint": {
                "enabled": [{"name": n} for n in base + plugin_names],
                "disabled": [{"name": "*"}],
            }
        },
    }


def run_both(nodes, pods, profile_plugins=None, namespaces=None, tie_break="first", seed=0):
    """Run the sequential oracle and the batch engine on the same snapshot;
    return (oracle results dict, BatchResult, service)."""
    store = ClusterStore()
    for ns in namespaces or []:
        store.create("namespaces", ns)
    for n in nodes:
        store.create("nodes", n)
    for p in pods:
        store.create("pods", p)

    cfg = None
    if profile_plugins is not None:
        cfg = {"profiles": [profile_with(profile_plugins)], "percentageOfNodesToScore": 100}
    else:
        cfg = {"percentageOfNodesToScore": 100}

    svc = SchedulerService(store, tie_break=tie_break, seed=seed)
    svc.start_scheduler(cfg)
    fw = svc.framework

    # Batch engine snapshot BEFORE the oracle mutates the store.
    eng = BatchEngine.from_framework(fw, trace=True)
    pending = fw.sort_pods(svc.pending_pods())
    ok, why = eng.supported(pending, store.list("nodes"))
    assert ok, why
    batch = eng.schedule(store.list("nodes"), store.list("pods"), pending, store.list("namespaces"))

    oracle = svc.schedule_pending(max_rounds=1)
    return oracle, batch, svc


def assert_parity(oracle, batch, svc=None, check_scores: bool = True):
    """Selected-node parity for every pod, plus (when the service is given)
    score/finalScore parity against the oracle's recorded annotations."""
    import json

    from kube_scheduler_simulator_tpu.plugins import annotations as anno

    assignments = batch.assignments()
    for key, res in oracle.items():
        got = assignments.get(key)
        assert got == res.selected_node, (
            f"{key}: oracle={res.selected_node} batch={got}"
        )
    if not check_scores or svc is None:
        return
    store = svc.cluster_store
    for i, key in enumerate(batch.pod_keys):
        ns, name = key.split("/")
        annos = store.get("pods", name, ns)["metadata"].get("annotations") or {}
        got_score, got_final = batch.score_annotations(i)
        want_score = json.loads(annos.get(anno.SCORE_RESULT, "{}"))
        want_final = json.loads(annos.get(anno.FINALSCORE_RESULT, "{}"))
        assert got_score == want_score, f"{key} score: {got_score} != {want_score}"
        assert got_final == want_final, f"{key} finalScore: {got_final} != {want_final}"


# --------------------------------------------------------------- config 1


def test_reservoir_tie_break_parity():
    """Default tie handling ("reservoir" = counter-keyed uniform draw over
    tied maxima) must pick the same node in the batch kernel and the
    sequential cycle — identical nodes maximize ties."""
    random.seed(7)
    for seed in (0, 1, 12345):
        nodes = [mk_node(f"node-{i}", cpu_m=64000, mem_mi=65536) for i in range(9)]
        pods = [mk_pod(f"pod-{i}", cpu_m=100, mem_mi=128) for i in range(24)]
        oracle, batch, svc = run_both(
            nodes, pods, ["NodeResourcesFit"], tie_break="reservoir", seed=seed
        )
        assert_parity(oracle, batch, svc)
        # the draw must actually spread pods (not degenerate to first-max)
        picked = {r.selected_node for r in oracle.values()}
        assert len(picked) > 2, f"seed {seed} placed everything on {picked}"


def test_reservoir_batch_vs_sequential_service_paths():
    """The same SchedulerService workload/seed must yield identical
    placements whether a round runs via the batch engine or sequentially
    (the round-1 advisor finding: path choice must not change outcomes)."""

    def build() -> ClusterStore:
        store = ClusterStore()
        for i in range(8):
            store.create("nodes", mk_node(f"node-{i}", cpu_m=32000, mem_mi=32768))
        for i in range(20):
            store.create("pods", mk_pod(f"pod-{i}", cpu_m=100, mem_mi=128))
        return store

    cfg = {"profiles": [profile_with(["NodeResourcesFit"])], "percentageOfNodesToScore": 100}
    store_seq = build()
    svc_seq = SchedulerService(store_seq, seed=3, use_batch="off")
    svc_seq.start_scheduler(cfg)
    svc_seq.schedule_pending(max_rounds=1)

    store_bat = build()
    svc_bat = SchedulerService(store_bat, seed=3, use_batch="auto", batch_min_work=0)
    svc_bat.start_scheduler(cfg)
    svc_bat.schedule_pending(max_rounds=1)

    for i in range(20):
        seq = store_seq.get("pods", f"pod-{i}")["spec"].get("nodeName")
        bat = store_bat.get("pods", f"pod-{i}")["spec"].get("nodeName")
        assert seq == bat, f"pod-{i}: sequential={seq} batch={bat}"


def test_sampling_default_profile_500_nodes_parity():
    """The DEFAULT config at scale (500 nodes, percentageOfNodesToScore=0
    → numFeasibleNodesToFind sampling + rotating start index) must take
    the batch path and produce byte-identical annotations + placements to
    the sequential cycle — across two rounds, so the rotating start and
    attempt counter stay in sync after a batch commit (VERDICT item 3)."""
    rng = random.Random(1234)
    nodes = []
    for i in range(500):
        labels = {"kubernetes.io/hostname": f"node-{i}", "topology.kubernetes.io/zone": f"z{i % 4}"}
        taints = (
            [{"key": "spot", "value": "true", "effect": "NoSchedule"}] if i % 97 == 0 else None
        )
        nodes.append(
            mk_node(f"node-{i}", cpu_m=rng.choice([2000, 4000, 8000]), mem_mi=8192, labels=labels, taints=taints)
        )

    def mk_pods(lo: int, hi: int) -> list[Obj]:
        out = []
        for i in range(lo, hi):
            extra = {}
            if i % 5 == 0:
                extra["nodeSelector"] = {"topology.kubernetes.io/zone": f"z{i % 4}"}
            out.append(
                mk_pod(
                    f"pod-{i}",
                    cpu_m=rng.choice([100, 300, 700]),
                    mem_mi=rng.choice([128, 512]),
                    labels={"app": f"a{i % 3}"},
                    **extra,
                )
            )
        return out

    def build_svc(mode: str):
        store = ClusterStore()
        for n in nodes:
            store.create("nodes", n)
        svc = SchedulerService(store, seed=5, use_batch=mode, batch_min_work=0)
        svc.start_scheduler(None)  # DEFAULT profile, default pct (0 → sampling)
        return store, svc

    store_seq, svc_seq = build_svc("off")
    store_bat, svc_bat = build_svc("auto")

    pods_r1, pods_r2 = mk_pods(0, 24), mk_pods(24, 36)
    for round_pods in (pods_r1, pods_r2):
        for p in round_pods:
            store_seq.create("pods", dict(p))
            store_bat.create("pods", dict(p))
        svc_seq.schedule_pending(max_rounds=1)
        svc_bat.schedule_pending(max_rounds=1)

    # the batch engine must actually have COMMITTED both rounds (engine
    # engagement alone isn't enough — a post-schedule fallback would rerun
    # sequentially and still produce identical annotations)
    assert svc_bat.stats["batch_commits"] == 2, svc_bat.stats
    assert svc_bat.stats["batch_pods"] == 36, svc_bat.stats
    assert svc_seq.framework.next_start_node_index == svc_bat.framework.next_start_node_index
    assert svc_seq.framework.sched_counter == svc_bat.framework.sched_counter

    for i in range(36):
        seq_pod = store_seq.get("pods", f"pod-{i}")
        bat_pod = store_bat.get("pods", f"pod-{i}")
        assert seq_pod["spec"].get("nodeName") == bat_pod["spec"].get("nodeName"), (
            f"pod-{i}: seq={seq_pod['spec'].get('nodeName')} bat={bat_pod['spec'].get('nodeName')}"
        )
        seq_annos = seq_pod["metadata"].get("annotations") or {}
        bat_annos = bat_pod["metadata"].get("annotations") or {}
        assert seq_annos == bat_annos, (
            f"pod-{i} annotation divergence:\n"
            + "\n".join(
                f"  {k}:\n   seq={str(seq_annos.get(k))[:400]}\n   bat={str(bat_annos.get(k))[:400]}"
                for k in sorted(set(seq_annos) | set(bat_annos))
                if seq_annos.get(k) != bat_annos.get(k)
            )
        )


def test_shape_bucketing_reuses_compiled_executables():
    """10 rounds with varying pod counts must hit at most 2 jit cache
    entries (VERDICT item 4): P/N are padded to bucket boundaries with
    pod_active/node_active masking, so churn reuses executables."""
    nodes = [mk_node(f"node-{i}", cpu_m=64000, mem_mi=65536) for i in range(20)]
    store = ClusterStore()
    for n in nodes:
        store.create("nodes", n)
    svc = SchedulerService(store, tie_break="first")
    svc.start_scheduler({"profiles": [profile_with(["NodeResourcesFit"])], "percentageOfNodesToScore": 100})
    eng = BatchEngine.from_framework(svc.framework, trace=True)

    rng = random.Random(3)
    # 97..112 share the 112 bucket ({2^k, 1.25/1.5/1.75·2^(k-1)} series);
    # 200 lands in the 224 bucket — exactly 2 executables for 10 rounds
    sizes = [rng.randint(97, 112) for _ in range(9)] + [200]
    for round_no, size in enumerate(sizes):
        pods = [mk_pod(f"r{round_no}-pod-{i}", cpu_m=100, mem_mi=128) for i in range(size)]
        res = eng.schedule(nodes, pods, pods, [])
        assert all(s >= 0 for s in res.selected[:size])
        # padded rows never schedule
        assert all(s < 0 for s in res.selected[size:])
    assert len(eng._fn_cache) <= 2, f"{len(eng._fn_cache)} compiles for 10 rounds"


def test_fit_only_small():
    random.seed(0)
    nodes = [mk_node(f"node-{i}", cpu_m=4000, mem_mi=8192) for i in range(10)]
    pods = [mk_pod(f"pod-{i}", cpu_m=random.choice([100, 250, 500]), mem_mi=random.choice([128, 256, 512])) for i in range(30)]
    oracle, batch, svc = run_both(nodes, pods, ["NodeResourcesFit"])
    assert_parity(oracle, batch, svc)


def test_fit_heterogeneous_nodes_and_insufficient():
    random.seed(1)
    nodes = [
        mk_node(f"node-{i}", cpu_m=random.choice([1000, 2000, 4000]), mem_mi=random.choice([1024, 2048, 4096]), pods=random.choice([3, 5, 110]))
        for i in range(12)
    ]
    pods = [mk_pod(f"pod-{i}", cpu_m=random.choice([0, 300, 900, 1500]), mem_mi=random.choice([0, 512, 1500])) for i in range(40)]
    oracle, batch, svc = run_both(nodes, pods, ["NodeResourcesFit"])
    assert_parity(oracle, batch, svc)


def test_fit_balanced_allocation():
    random.seed(2)
    nodes = [mk_node(f"node-{i}", cpu_m=random.choice([2000, 4000]), mem_mi=random.choice([2048, 8192])) for i in range(8)]
    pods = [mk_pod(f"pod-{i}", cpu_m=random.choice([100, 700]), mem_mi=random.choice([128, 2048])) for i in range(25)]
    oracle, batch, svc = run_both(
        nodes, pods, ["NodeResourcesFit", "NodeResourcesBalancedAllocation"]
    )
    assert_parity(oracle, batch, svc)


# --------------------------------------------------------------- config 2


def test_fit_taints_affinity():
    random.seed(3)
    zones = ["a", "b", "c"]
    nodes = []
    for i in range(15):
        taints = []
        if i % 5 == 0:
            taints = [{"key": "dedicated", "value": "infra", "effect": "NoSchedule"}]
        if i % 7 == 0:
            taints.append({"key": "spot", "value": "true", "effect": "PreferNoSchedule"})
        nodes.append(
            mk_node(
                f"node-{i}",
                cpu_m=4000,
                mem_mi=8192,
                labels={"zone": zones[i % 3], "disk": "ssd" if i % 2 else "hdd"},
                taints=taints or None,
                unschedulable=(i == 13),
            )
        )
    pods = []
    for i in range(40):
        extra = {}
        if i % 4 == 0:
            extra["nodeSelector"] = {"disk": "ssd"}
        if i % 6 == 0:
            extra["tolerations"] = [{"key": "dedicated", "operator": "Equal", "value": "infra", "effect": "NoSchedule"}]
        if i % 3 == 0:
            extra["affinity"] = {
                "nodeAffinity": {
                    "preferredDuringSchedulingIgnoredDuringExecution": [
                        {"weight": 10, "preference": {"matchExpressions": [{"key": "zone", "operator": "In", "values": ["a"]}]}},
                        {"weight": 5, "preference": {"matchExpressions": [{"key": "disk", "operator": "In", "values": ["ssd"]}]}},
                    ]
                }
            }
        if i % 11 == 0:
            extra.setdefault("affinity", {})["nodeAffinity"] = {
                **extra.get("affinity", {}).get("nodeAffinity", {}),
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [
                        {"matchExpressions": [{"key": "zone", "operator": "NotIn", "values": ["c"]}]}
                    ]
                },
            }
        pods.append(mk_pod(f"pod-{i}", cpu_m=200, mem_mi=256, **extra))
    oracle, batch, svc = run_both(
        nodes,
        pods,
        ["NodeUnschedulable", "NodeName", "TaintToleration", "NodeAffinity", "NodeResourcesFit"],
    )
    assert_parity(oracle, batch, svc)


def test_node_name_pinning():
    nodes = [mk_node(f"node-{i}", 1000, 1024) for i in range(5)]
    pods = [
        mk_pod("pinned", cpu_m=100, nodeName=None),
    ]
    pods[0]["spec"]["nodeName"] = None
    # a pod pinned via required affinity matchFields
    pods = [
        mk_pod(
            "pinned-aff",
            cpu_m=100,
            affinity={
                "nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [
                            {"matchFields": [{"key": "metadata.name", "operator": "In", "values": ["node-3"]}]}
                        ]
                    }
                }
            },
        ),
        mk_pod("free", cpu_m=100),
    ]
    oracle, batch, svc = run_both(nodes, pods, ["NodeAffinity", "NodeResourcesFit"])
    assert_parity(oracle, batch, svc)
    assert batch.assignments()["default/pinned-aff"] == "node-3"


# --------------------------------------------------------------- config 3


def test_topology_spread():
    random.seed(4)
    zones = ["z1", "z2", "z3"]
    nodes = [
        mk_node(
            f"node-{i}",
            cpu_m=8000,
            mem_mi=16384,
            labels={"topology.kubernetes.io/zone": zones[i % 3], "kubernetes.io/hostname": f"node-{i}"},
        )
        for i in range(9)
    ]
    constraint = [
        {
            "maxSkew": 1,
            "topologyKey": "topology.kubernetes.io/zone",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "web"}},
        },
        {
            "maxSkew": 2,
            "topologyKey": "kubernetes.io/hostname",
            "whenUnsatisfiable": "ScheduleAnyway",
            "labelSelector": {"matchLabels": {"app": "web"}},
        },
    ]
    pods = [
        mk_pod(f"web-{i}", cpu_m=100, mem_mi=128, labels={"app": "web"}, topologySpreadConstraints=constraint)
        for i in range(18)
    ]
    # plus unrelated pods that don't match the selector
    pods += [mk_pod(f"other-{i}", cpu_m=100, labels={"app": "db"}) for i in range(6)]
    oracle, batch, svc = run_both(
        nodes, pods, ["NodeResourcesFit", "PodTopologySpread"]
    )
    assert_parity(oracle, batch, svc)


def test_topology_spread_missing_label():
    nodes = [
        mk_node("node-a", 4000, 8192, labels={"zone": "z1"}),
        mk_node("node-b", 4000, 8192, labels={"zone": "z2"}),
        mk_node("node-c", 4000, 8192, labels={}),  # missing key → filtered
    ]
    c = [
        {
            "maxSkew": 1,
            "topologyKey": "zone",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "x"}},
        }
    ]
    pods = [mk_pod(f"x-{i}", cpu_m=100, labels={"app": "x"}, topologySpreadConstraints=c) for i in range(6)]
    oracle, batch, svc = run_both(nodes, pods, ["NodeResourcesFit", "PodTopologySpread"])
    assert_parity(oracle, batch, svc)
    # node-c must never be selected
    assert "node-c" not in batch.assignments().values()


# --------------------------------------------------------------- config 4


def test_interpod_affinity_antiaffinity():
    random.seed(5)
    nodes = [
        mk_node(
            f"node-{i}",
            cpu_m=8000,
            mem_mi=16384,
            labels={"zone": ["z1", "z2", "z3"][i % 3], "kubernetes.io/hostname": f"node-{i}"},
        )
        for i in range(9)
    ]
    anti = {
        "podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {
                    "labelSelector": {"matchLabels": {"app": "db"}},
                    "topologyKey": "kubernetes.io/hostname",
                }
            ]
        }
    }
    aff = {
        "podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {
                    "labelSelector": {"matchLabels": {"app": "db"}},
                    "topologyKey": "zone",
                }
            ],
            "preferredDuringSchedulingIgnoredDuringExecution": [
                {
                    "weight": 50,
                    "podAffinityTerm": {
                        "labelSelector": {"matchLabels": {"app": "db"}},
                        "topologyKey": "zone",
                    },
                }
            ],
        }
    }
    pods = [mk_pod(f"db-{i}", cpu_m=500, mem_mi=512, labels={"app": "db"}, affinity=anti) for i in range(4)]
    pods += [mk_pod(f"web-{i}", cpu_m=100, mem_mi=128, labels={"app": "web"}, affinity=aff) for i in range(8)]
    oracle, batch, svc = run_both(
        nodes, pods, ["NodeResourcesFit", "InterPodAffinity"]
    )
    assert_parity(oracle, batch, svc)


def test_interpod_with_existing_pods():
    nodes = [
        mk_node(f"node-{i}", 8000, 16384, labels={"zone": ["z1", "z2"][i % 2], "kubernetes.io/hostname": f"node-{i}"})
        for i in range(6)
    ]
    # existing bound pod with anti-affinity against app=web
    existing = mk_pod(
        "guard",
        cpu_m=100,
        labels={"app": "guard"},
        affinity={
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {"labelSelector": {"matchLabels": {"app": "web"}}, "topologyKey": "zone"}
                ]
            }
        },
    )
    existing["spec"]["nodeName"] = "node-0"  # zone z1
    pods = [existing] + [mk_pod(f"web-{i}", cpu_m=100, labels={"app": "web"}) for i in range(4)]
    oracle, batch, svc = run_both(nodes, pods, ["NodeResourcesFit", "InterPodAffinity"])
    assert_parity(oracle, batch, svc)
    # all web pods must avoid zone z1 (nodes 0, 2, 4)
    for key, node in batch.assignments().items():
        if key.startswith("default/web"):
            assert node in ("node-1", "node-3", "node-5"), (key, node)


# ----------------------------------------------------- full default profile


def test_default_profile_mixed_workload():
    """Default KubeSchedulerConfiguration (all default plugins; volume &
    ports plugins unused by the workload so they're no-ops)."""
    random.seed(6)
    zones = ["z1", "z2", "z3"]
    nodes = [
        mk_node(
            f"node-{i}",
            cpu_m=random.choice([2000, 4000, 8000]),
            mem_mi=random.choice([4096, 8192]),
            labels={"topology.kubernetes.io/zone": zones[i % 3], "kubernetes.io/hostname": f"node-{i}"},
            taints=[{"key": "spot", "value": "true", "effect": "PreferNoSchedule"}] if i % 4 == 0 else None,
        )
        for i in range(12)
    ]
    pods = []
    for i in range(30):
        extra = {}
        if i % 5 == 0:
            extra["topologySpreadConstraints"] = [
                {
                    "maxSkew": 2,
                    "topologyKey": "topology.kubernetes.io/zone",
                    "whenUnsatisfiable": "DoNotSchedule",
                    "labelSelector": {"matchLabels": {"tier": "a"}},
                }
            ]
        if i % 7 == 0:
            extra["affinity"] = {
                "podAntiAffinity": {
                    "preferredDuringSchedulingIgnoredDuringExecution": [
                        {
                            "weight": 10,
                            "podAffinityTerm": {
                                "labelSelector": {"matchLabels": {"tier": "a"}},
                                "topologyKey": "kubernetes.io/hostname",
                            },
                        }
                    ]
                }
            }
        pods.append(
            mk_pod(
                f"pod-{i}",
                cpu_m=random.choice([100, 300, 600]),
                mem_mi=random.choice([128, 512]),
                labels={"tier": "a" if i % 2 == 0 else "b"},
                **extra,
            )
        )
    oracle, batch, svc = run_both(nodes, pods, profile_plugins=None)  # default config
    assert_parity(oracle, batch, svc)


def test_score_trace_matches_oracle_annotations():
    """The batch trace's score/finalScore maps must equal the oracle's
    recorded annotations (the parity oracle for the reference's
    scheduler-simulator/score-result format)."""
    random.seed(7)
    nodes = [mk_node(f"node-{i}", 4000, 8192, labels={"zone": ["a", "b"][i % 2]}) for i in range(6)]
    pods = [
        mk_pod(
            f"pod-{i}",
            cpu_m=random.choice([100, 400]),
            mem_mi=random.choice([128, 1024]),
            affinity={
                "nodeAffinity": {
                    "preferredDuringSchedulingIgnoredDuringExecution": [
                        {"weight": 7, "preference": {"matchExpressions": [{"key": "zone", "operator": "In", "values": ["a"]}]}}
                    ]
                }
            },
        )
        for i in range(8)
    ]
    oracle, batch, svc = run_both(
        nodes,
        pods,
        ["TaintToleration", "NodeAffinity", "NodeResourcesFit", "NodeResourcesBalancedAllocation"],
    )
    assert_parity(oracle, batch, svc)

    import json

    from kube_scheduler_simulator_tpu.plugins import annotations as anno

    store = svc.cluster_store
    for i, key in enumerate(batch.pod_keys):
        ns, name = key.split("/")
        pod = store.get("pods", name, ns)
        annos = pod["metadata"].get("annotations") or {}
        if int(batch.feasible_count[i]) <= 1:
            continue
        got_score, got_final = batch.score_annotations(i)
        want_score = json.loads(annos[anno.SCORE_RESULT])
        want_final = json.loads(annos[anno.FINALSCORE_RESULT])
        assert got_score == want_score, f"{key} score mismatch"
        assert got_final == want_final, f"{key} finalScore mismatch"


def test_service_batch_mode_byte_identical_annotations():
    """SchedulerService(use_batch='auto') must produce byte-identical pod
    annotations to the sequential path — the reference's core contract."""
    random.seed(9)

    def build_store():
        store = ClusterStore()
        for i in range(8):
            store.create(
                "nodes",
                mk_node(
                    f"node-{i}",
                    cpu_m=4000,
                    mem_mi=8192,
                    labels={"topology.kubernetes.io/zone": f"z{i % 2}", "kubernetes.io/hostname": f"node-{i}"},
                    taints=[{"key": "spot", "value": "t", "effect": "PreferNoSchedule"}] if i == 0 else None,
                ),
            )
        rng = random.Random(99)
        for i in range(20):
            store.create(
                "pods",
                mk_pod(
                    f"pod-{i}",
                    cpu_m=rng.choice([100, 400]),
                    mem_mi=rng.choice([128, 512]),
                    labels={"app": "a" if i % 2 else "b"},
                    topologySpreadConstraints=[
                        {
                            "maxSkew": 2,
                            "topologyKey": "topology.kubernetes.io/zone",
                            "whenUnsatisfiable": "DoNotSchedule",
                            "labelSelector": {"matchLabels": {"app": "a"}},
                        }
                    ]
                    if i % 3 == 0
                    else [],
                ),
            )
        return store

    cfg = {"percentageOfNodesToScore": 100}
    store_seq = build_store()
    svc_seq = SchedulerService(store_seq, tie_break="first", use_batch="off")
    svc_seq.start_scheduler(cfg)
    svc_seq.schedule_pending(max_rounds=1)

    store_bat = build_store()
    svc_bat = SchedulerService(store_bat, tie_break="first", use_batch="auto", batch_min_work=0)
    svc_bat.start_scheduler(cfg)
    results = svc_bat.schedule_pending(max_rounds=1)
    assert all(r.success for r in results.values())

    for i in range(20):
        seq_pod = store_seq.get("pods", f"pod-{i}")
        bat_pod = store_bat.get("pods", f"pod-{i}")
        seq_annos = seq_pod["metadata"].get("annotations") or {}
        bat_annos = bat_pod["metadata"].get("annotations") or {}
        assert seq_annos == bat_annos, (
            f"pod-{i} annotation divergence:\n"
            + "\n".join(
                f"  {k}:\n   seq={seq_annos.get(k)}\n   bat={bat_annos.get(k)}"
                for k in sorted(set(seq_annos) | set(bat_annos))
                if seq_annos.get(k) != bat_annos.get(k)
            )
        )
        assert seq_pod["spec"].get("nodeName") == bat_pod["spec"].get("nodeName")


def test_filter_trace_matches_oracle_annotations():
    random.seed(8)
    nodes = [
        mk_node(
            f"node-{i}",
            cpu_m=1000 if i < 2 else 4000,
            mem_mi=8192,
            taints=[{"key": "d", "value": "v", "effect": "NoSchedule"}] if i == 3 else None,
        )
        for i in range(6)
    ]
    pods = [mk_pod(f"pod-{i}", cpu_m=900, mem_mi=128) for i in range(4)]
    oracle, batch, svc = run_both(
        nodes, pods, ["TaintToleration", "NodeResourcesFit"]
    )
    assert_parity(oracle, batch, svc)

    import json

    from kube_scheduler_simulator_tpu.plugins import annotations as anno

    store = svc.cluster_store
    for i, key in enumerate(batch.pod_keys):
        ns, name = key.split("/")
        pod = store.get("pods", name, ns)
        annos = pod["metadata"].get("annotations") or {}
        want = json.loads(annos[anno.FILTER_RESULT])
        got = batch.filter_annotation(i)
        assert got == want, f"{key}: {got} != {want}"


def test_batch_preemption_composition_byte_identical():
    """VERDICT r1 item 6: a round where one pod needs preemption must not
    de-batch the rest — the 999 feasible pods commit via the kernel, only
    the preemptor runs the sequential cycle, and every pod's annotations
    are byte-identical to the all-sequential run (including the PostFilter
    trace and the freed-resources visibility for pods scheduled after the
    successful preemption)."""
    P, N = 1000, 20

    def build_store():
        store = ClusterStore()
        toleration = [{"key": "special", "operator": "Exists", "effect": "NoSchedule"}]
        for i in range(N):
            labels = {"kubernetes.io/hostname": f"node-{i}"}
            if i == 0:
                labels["special"] = "true"
            store.create(
                "nodes",
                mk_node(
                    f"node-{i}",
                    cpu_m=4000,
                    mem_mi=8192,
                    labels=labels,
                    # keep the 999 fillers off node-0 (they lack the
                    # toleration), so the freed capacity stays for round 2
                    taints=[{"key": "special", "effect": "NoSchedule"}] if i == 0 else None,
                ),
            )
        # low-priority victim filling the only "special" node
        victim = mk_pod("victim", cpu_m=3900, mem_mi=128)
        victim["spec"]["nodeName"] = "node-0"
        victim["spec"]["priority"] = 0
        victim["spec"]["tolerations"] = toleration
        store.create("pods", victim)
        # the preemptor fits only on node-0 (nodeSelector) and only after
        # the victim is evicted; highest priority, so it sorts first
        preemptor = mk_pod("preemptor", cpu_m=3800, mem_mi=128)
        preemptor["spec"]["priority"] = 100
        preemptor["spec"]["nodeSelector"] = {"special": "true"}
        preemptor["spec"]["tolerations"] = toleration
        store.create("pods", preemptor)
        rng = random.Random(4)
        for i in range(P - 1):
            p = mk_pod(f"pod-{i}", cpu_m=rng.choice([10, 20]), mem_mi=16)
            # deterministic queue order: the store stamps wall-clock
            # creationTimestamps, and PrioritySort tie-breaks on them — a
            # second boundary crossing at different indexes in the two
            # builds would divert the queues
            p["metadata"]["creationTimestamp"] = f"2024-01-01T00:{i // 60:02d}:{i % 60:02d}Z"
            store.create("pods", p)
        return store

    cfg = {"percentageOfNodesToScore": 100}
    store_seq = build_store()
    svc_seq = SchedulerService(store_seq, tie_break="first", use_batch="off")
    svc_seq.start_scheduler(cfg)
    svc_seq.schedule_pending(max_rounds=2)

    store_bat = build_store()
    svc_bat = SchedulerService(store_bat, tie_break="first", use_batch="auto", batch_min_work=0)
    svc_bat.start_scheduler(cfg)
    svc_bat.schedule_pending(max_rounds=2)

    # round 1: the preemptor's failure AND its victim search run on the
    # batch path (preemption/ handles the PostFilter), so every pod of
    # the round is a batch pod; round 2: the preemptor is NOMINATED and
    # pending — a pod must not account its own reservation, so that
    # round is sequential
    assert svc_bat.stats["sequential_pods"] == 1
    assert svc_bat.stats["batch_pods"] == P
    assert svc_bat.stats.get("batch_restarts", 0) == 1
    assert svc_bat.stats["preempt_nominations"] == 1
    assert svc_bat.stats["preempt_victims"] == 1
    assert svc_bat.stats["preempt_fallbacks"] == {}
    assert "nominated pods present (preemption in flight)" in svc_bat.stats["batch_fallbacks"]

    # victim evicted in both paths
    for st in (store_seq, store_bat):
        try:
            assert st.get("pods", "victim") is None
        except KeyError:
            pass
    assert store_bat.get("pods", "preemptor")["spec"].get("nodeName") == "node-0"

    names = ["preemptor"] + [f"pod-{i}" for i in range(P - 1)]
    for nm in names:
        seq_pod = store_seq.get("pods", nm)
        bat_pod = store_bat.get("pods", nm)
        seq_annos = seq_pod["metadata"].get("annotations") or {}
        bat_annos = bat_pod["metadata"].get("annotations") or {}
        assert seq_annos == bat_annos, (
            f"{nm} annotation divergence:\n"
            + "\n".join(
                f"  {k}:\n   seq={seq_annos.get(k)}\n   bat={bat_annos.get(k)}"
                for k in sorted(set(seq_annos) | set(bat_annos))
                if seq_annos.get(k) != bat_annos.get(k)
            )
        )
        assert seq_pod["spec"].get("nodeName") == bat_pod["spec"].get("nodeName"), nm


def test_large_scale_seeded_parity_sweep():
    """VERDICT r1 item 9: randomized parity at 1k pods x 500 nodes over the
    union of the BASELINE cfg2/3/4 plugin sets (Fit + Taint + NodeAffinity
    + PodTopologySpread + InterPodAffinity) — padding/precision/one-hot
    bugs that hide at toy scale surface here.  Asserts selected-node AND
    score/finalScore annotation parity for every pod (x64 CPU)."""
    P, N = 1000, 500
    rng = random.Random(1234)
    nodes = []
    for i in range(N):
        labels = {
            "topology.kubernetes.io/zone": f"z{i % 7}",
            "kubernetes.io/hostname": f"node-{i}",
            "disk": "ssd" if i % 3 else "hdd",
        }
        taints = (
            [{"key": "spot", "value": "true", "effect": rng.choice(["NoSchedule", "PreferNoSchedule"])}]
            if i % 11 == 0
            else None
        )
        nodes.append(
            mk_node(f"node-{i}", cpu_m=rng.choice([16000, 32000, 64000]), mem_mi=65536,
                    labels=labels, taints=taints)
        )
    pods = []
    for i in range(P):
        p = mk_pod(
            f"pod-{i}",
            cpu_m=rng.choice([50, 100, 250, 500]),
            mem_mi=rng.choice([64, 128, 256]),
            labels={"app": f"app-{i % 5}", "tier": "web" if i % 2 else "db"},
        )
        if i % 4 == 0:
            p["spec"]["nodeSelector"] = {"disk": "ssd"}
        if i % 6 == 0:
            p["spec"]["tolerations"] = [{"key": "spot", "operator": "Exists"}]
        if i % 3 == 0:
            p["spec"]["topologySpreadConstraints"] = [
                {
                    "maxSkew": 4,
                    "topologyKey": "topology.kubernetes.io/zone",
                    "whenUnsatisfiable": "DoNotSchedule",
                    "labelSelector": {"matchLabels": {"app": f"app-{i % 5}"}},
                },
                {
                    "maxSkew": 6,
                    "topologyKey": "kubernetes.io/hostname",
                    "whenUnsatisfiable": "ScheduleAnyway",
                    "labelSelector": {"matchLabels": {"app": f"app-{i % 5}"}},
                },
            ]
        if i % 5 == 1:
            p["spec"]["affinity"] = {
                "podAntiAffinity": {
                    "preferredDuringSchedulingIgnoredDuringExecution": [
                        {
                            "weight": 10,
                            "podAffinityTerm": {
                                "labelSelector": {"matchLabels": {"app": f"app-{i % 5}"}},
                                "topologyKey": "kubernetes.io/hostname",
                            },
                        }
                    ]
                }
            }
        pods.append(p)
    oracle, batch, svc = run_both(
        nodes,
        pods,
        ["NodeResourcesFit", "TaintToleration", "NodeAffinity", "PodTopologySpread", "InterPodAffinity"],
    )
    assert_parity(oracle, batch, svc)
    scheduled = sum(1 for r in oracle.values() if r.success)
    assert scheduled == P, f"only {scheduled}/{P} scheduled"


def run_single_vs_sharded(nodes, pods, filters, scores, volumes=None, trace=False, **schedule_kw):
    """Run BatchEngine single-device (pinned to one CPU device) and
    mesh-sharded over 8 virtual CPU devices on the same snapshot; assert
    identical selections + feasible counts — and, with ``trace=True``,
    byte-identical filter/score annotation JSON (the compact-trace path
    production runs).  Shared by the mesh parity suites here and in
    test_batch_volumes."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devices = jax.local_devices(backend="cpu")
    assert len(devices) >= 8, "conftest forces 8 virtual CPU devices"
    mesh = Mesh(np.array(devices[:8]), ("nodes",))
    with jax.default_device(devices[0]):
        res1 = BatchEngine(filters=filters, scores=scores, trace=trace).schedule(
            nodes, pods, pods, [], volumes=volumes, **schedule_kw
        )
    with mesh:
        res2 = BatchEngine(filters=filters, scores=scores, trace=trace, mesh=mesh).schedule(
            nodes, pods, pods, [], volumes=volumes, **schedule_kw
        )
    assert res1.selected_nodes == res2.selected_nodes
    assert list(res1.feasible_count) == list(res2.feasible_count)
    if trace:
        for i in range(len(pods)):
            assert str(res1.filter_annotation_json(i)) == str(res2.filter_annotation_json(i)), (
                f"pod {i}: filter annotation diverges under sharding"
            )
            s1, f1 = res1.score_annotations_json(i)
            s2, f2 = res2.score_annotations_json(i)
            assert str(s1) == str(s2) and str(f1) == str(f2), (
                f"pod {i}: score annotations diverge under sharding"
            )
            assert res1.diagnosis(i).keys() == res2.diagnosis(i).keys()
    return res1, res2


def test_batch_engine_mesh_sharded_parity():
    """BatchEngine(mesh=...) — the productized multi-chip path — must
    produce the identical selection to the single-device engine on a
    virtual 8-device CPU mesh (node axis sharded; reductions become XLA
    collectives)."""
    random.seed(21)
    nodes = [
        mk_node(
            f"node-{i}",
            cpu_m=random.choice([8000, 16000]),
            mem_mi=16384,
            labels={"kubernetes.io/hostname": f"node-{i}", "topology.kubernetes.io/zone": f"z{i % 4}"},
        )
        for i in range(32)
    ]
    pods = [
        mk_pod(
            f"pod-{i}",
            cpu_m=random.choice([200, 400, 800]),
            mem_mi=256,
            labels={"app": f"a{i % 3}"},
            topologySpreadConstraints=[
                {
                    "maxSkew": 3,
                    "topologyKey": "topology.kubernetes.io/zone",
                    "whenUnsatisfiable": "DoNotSchedule",
                    "labelSelector": {"matchLabels": {"app": f"a{i % 3}"}},
                }
            ]
            if i % 2 == 0
            else [],
        )
        for i in range(24)
    ]
    plugins = ["NodeResourcesFit", "TaintToleration", "PodTopologySpread"]
    scores = [("NodeResourcesFit", 1), ("TaintToleration", 3), ("PodTopologySpread", 2)]

    run_single_vs_sharded(nodes, pods, plugins, scores)

    # an UNEVEN node count must still work on the mesh (the node axis is
    # padded up to a multiple of the device count)
    run_single_vs_sharded(nodes[:9], pods, plugins, scores)

    # a nonzero rotation start compiles the SAMPLING kernel variant in —
    # its rotation-rank prefix sums are the most order-sensitive
    # cross-node reductions, so pin them under sharding too
    run_single_vs_sharded(nodes, pods, plugins, scores, start_index=5)


def test_batch_engine_mesh_sharded_trace_parity():
    """TRACE mode under sharding: the compact-trace path (per-plugin
    dtypes, blob fetch, host reconstruction, C assembly) must emit
    byte-identical annotation JSON whether the node axis is sharded over
    the mesh or not — this is the path production runs."""
    random.seed(22)
    nodes = [
        mk_node(
            f"node-{i}",
            cpu_m=random.choice([4000, 8000]),
            mem_mi=16384,
            labels={
                "kubernetes.io/hostname": f"node-{i}",
                "topology.kubernetes.io/zone": f"z{i % 4}",
                "disk": "ssd" if i % 2 else "hdd",
            },
        )
        for i in range(32)
    ]
    pods = []
    for i in range(24):
        p = mk_pod(
            f"pod-{i}",
            cpu_m=random.choice([200, 400, 800]),
            mem_mi=256,
            labels={"app": f"a{i % 3}"},
        )
        if i % 4 == 0:  # filter failures on half the nodes
            p["spec"]["nodeSelector"] = {"disk": "ssd"}
        if i % 2 == 0:
            p["spec"]["topologySpreadConstraints"] = [
                {
                    "maxSkew": 2,
                    "topologyKey": "topology.kubernetes.io/zone",
                    "whenUnsatisfiable": "DoNotSchedule",
                    "labelSelector": {"matchLabels": {"app": f"a{i % 3}"}},
                }
            ]
        pods.append(p)
    plugins = ["NodeResourcesFit", "TaintToleration", "NodeAffinity", "PodTopologySpread"]
    scores = [
        ("NodeResourcesFit", 1),
        ("TaintToleration", 3),
        ("NodeAffinity", 2),
        ("PodTopologySpread", 2),
    ]
    run_single_vs_sharded(nodes, pods, plugins, scores, trace=True)
    # uneven node count (mesh pads) and rotated start, traced
    run_single_vs_sharded(nodes[:9], pods, plugins, scores, trace=True)
    run_single_vs_sharded(nodes, pods, plugins, scores, trace=True, start_index=7)


def test_imagelocality_kernel_parity():
    """ImageLocality scores (size×spread, thresholded) must match the
    sequential plugin byte-for-byte — including nodes WITH images, which
    previously forced a whole-round sequential fallback."""
    random.seed(31)
    nodes = []
    for i in range(12):
        n = mk_node(f"node-{i}", cpu_m=16000, mem_mi=16384,
                    labels={"kubernetes.io/hostname": f"node-{i}"})
        images = []
        if i % 2 == 0:
            images.append({"names": ["registry.io/app:v1"], "sizeBytes": 600 * 1024 * 1024})
        if i % 3 == 0:
            images.append({"names": ["registry.io/db:v2"], "sizeBytes": 900 * 1024 * 1024})
        if images:
            n["status"]["images"] = images
        nodes.append(n)
    pods = []
    for i in range(18):
        p = mk_pod(f"pod-{i}", cpu_m=200, mem_mi=128)
        p["spec"]["containers"][0]["image"] = "registry.io/app:v1" if i % 2 else "registry.io/db:v2"
        if i % 5 == 0:
            p["spec"]["containers"].append(
                {"name": "c2", "image": "registry.io/app:v1", "resources": {"requests": {"cpu": "50m"}}}
            )
        pods.append(p)
    oracle, batch, svc = run_both(
        nodes, pods, ["NodeResourcesFit", "ImageLocality"]
    )
    assert_parity(oracle, batch, svc)
    # the kernel must actually have produced nonzero image scores
    import numpy as np

    raws = batch.out["trace"]["raw"]
    assert int(np.abs(raws).sum()) > 0


def test_imagelocality_no_longer_forces_fallback():
    store = ClusterStore()
    node = mk_node("node-0", cpu_m=64000, mem_mi=65536)
    node["status"]["images"] = [{"names": ["img:1"], "sizeBytes": 500 * 1024 * 1024}]
    store.create("nodes", node)
    store.create("nodes", mk_node("node-1", cpu_m=64000, mem_mi=65536))
    for i in range(10):
        store.create("pods", mk_pod(f"pod-{i}", cpu_m=100, mem_mi=64))
    svc = SchedulerService(store, tie_break="first", use_batch="auto", batch_min_work=0)
    svc.start_scheduler({"percentageOfNodesToScore": 100})  # default profile incl. ImageLocality
    svc.schedule_pending(max_rounds=1)
    assert svc.stats["batch_pods"] == 10, svc.stats


def test_nodeports_kernel_parity():
    """NodePorts (hostPort/protocol/hostIP conflicts, incl. the 0.0.0.0
    wildcard and ports consumed by commits WITHIN the round) must match
    the sequential plugin — previously any hostPort pod de-batched the
    round."""
    random.seed(41)
    nodes = [
        mk_node(f"node-{i}", cpu_m=32000, mem_mi=32768,
                labels={"kubernetes.io/hostname": f"node-{i}"})
        for i in range(5)
    ]
    # a bound pod already holds 8080/TCP on node-0
    holder = mk_pod("holder", cpu_m=100, mem_mi=64)
    holder["spec"]["nodeName"] = "node-0"
    holder["spec"]["containers"][0]["ports"] = [{"hostPort": 8080, "protocol": "TCP"}]
    pods = []
    for i in range(9):
        p = mk_pod(f"pod-{i}", cpu_m=100, mem_mi=64)
        if i % 3 == 0:
            p["spec"]["containers"][0]["ports"] = [{"hostPort": 8080, "protocol": "TCP"}]
        elif i % 3 == 1:
            p["spec"]["containers"][0]["ports"] = [
                {"hostPort": 8080, "protocol": "TCP", "hostIP": "10.0.0.1"}
            ]
        pods.append(p)
    store = ClusterStore()
    for n in nodes:
        store.create("nodes", n)
    store.create("pods", holder)
    for p in pods:
        store.create("pods", p)
    svc = SchedulerService(store, tie_break="first", seed=0)
    svc.start_scheduler(
        {"profiles": [profile_with(["NodeResourcesFit", "NodePorts"])], "percentageOfNodesToScore": 100}
    )
    fw = svc.framework
    eng = BatchEngine.from_framework(fw, trace=True)
    pending = fw.sort_pods(svc.pending_pods())
    ok, why = eng.supported(pending, store.list("nodes"))
    assert ok, why
    batch = eng.schedule(store.list("nodes"), store.list("pods"), pending, store.list("namespaces"))
    oracle = svc.schedule_pending(max_rounds=1)
    assert_parity(oracle, batch, svc)
    # the wildcard-IP pods (every 3rd) can only coexist one per node: with
    # 5 nodes and one port held, placements must spread and 8080-wanting
    # pods must avoid node-0
    for key, res in oracle.items():
        i = int(key.split("-")[-1])
        if i % 3 == 0 and res.success:
            assert res.selected_node != "node-0", key


def test_no_reserve_profile_omits_selected_node_annotation():
    """selected-node is recorded BY the wrapped Reserve hooks (reference
    wrappedplugin.go:616-645): a profile with no reserve plugins leaves it
    unset — on the batch path too (it used to write it unconditionally)."""
    import json as _json

    from kube_scheduler_simulator_tpu.plugins import annotations as anno

    def build_store():
        store = ClusterStore()
        for i in range(4):
            store.create("nodes", mk_node(f"node-{i}", 4000, 8192))
        for i in range(8):
            store.create("pods", mk_pod(f"pod-{i}", cpu_m=100, mem_mi=128))
        return store

    cfg = {
        "percentageOfNodesToScore": 100,
        "profiles": [profile_with(["NodeResourcesFit"])],  # no reserve plugins
    }
    store_seq = build_store()
    svc_seq = SchedulerService(store_seq, tie_break="first", use_batch="off")
    svc_seq.start_scheduler(cfg)
    svc_seq.schedule_pending(max_rounds=1)

    store_bat = build_store()
    svc_bat = SchedulerService(store_bat, tie_break="first", use_batch="auto", batch_min_work=0)
    svc_bat.start_scheduler(cfg)
    svc_bat.schedule_pending(max_rounds=1)
    assert svc_bat.stats["batch_pods"] == 8, svc_bat.stats

    for i in range(8):
        seq_annos = store_seq.get("pods", f"pod-{i}")["metadata"].get("annotations") or {}
        bat_annos = store_bat.get("pods", f"pod-{i}")["metadata"].get("annotations") or {}
        assert seq_annos.get(anno.SELECTED_NODE, "") == ""
        assert seq_annos == bat_annos, {
            k: (seq_annos.get(k), bat_annos.get(k))
            for k in set(seq_annos) | set(bat_annos)
            if seq_annos.get(k) != bat_annos.get(k)
        }
