"""KSS-ENV bad fixture 1: an undocumented read and a ghost knob.

The fixture-scoped "documentation" is the ``documents:`` line below —
it plays the role docs/environment-variables.md plays on the live tree.
"""

# documents: KSS_FIXTURE_DOCUMENTED KSS_FIXTURE_GHOST  # expect-finding

import os


def load_config():
    # read but not documented anywhere in the fixture set:
    raw = os.environ.get("KSS_FIXTURE_UNDOCUMENTED")  # expect-finding
    return raw
