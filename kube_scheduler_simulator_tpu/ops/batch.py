"""The TPU batch scheduling kernel: one compiled lax.scan over the pod queue.

This lifts the reference's hot loop — per pod × per node × per plugin
Filter/Score calls serialized through a store mutex (reference
simulator/scheduler/plugin/wrappedplugin.go:420-445,523-548;
resultstore/store.go:423-437) — into a single XLA computation
(BASELINE.json north star).  Scheduling is inherently sequential (each bind
consumes node resources), so the batch shape is a ``lax.scan`` whose carry
is the cluster's dynamic state and whose body vectorizes one full
scheduling cycle over ALL nodes:

    carry = (requested [N,R], nonzero [N,2], pod_count [N],
             ports_used [N,PT], restr_used [N,VR], cloud_used [N,3],
             csi_att [N,V], spread_counts [SG,N],
             ip_sel/ip_own/ip_anti [G,D+1])
    step  = filters [N] → scores [N] → normalize → argmax → scatter-commit

Every per-plugin semantic (first-failure short circuit, per-plugin
normalization, weight application, single-feasible-node scoring bypass)
matches the sequential oracle in scheduler/framework_runner.py, which in
turn pins the reference's upstream v1.26 behavior.  Static string semantics
were pre-lowered by ops/encode.py; nothing here touches a string.

All math is in the problem dtype (float64 under x64 for bit-exact parity
tests on CPU; float32 on TPU, kept exact by the encoder's GCD scaling for
the filter path and ratio formulations for scores).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kube_scheduler_simulator_tpu.ops.encode import BatchProblem

MAX_NODE_SCORE = 100.0
NEG = -1e18


class BatchConfig(NamedTuple):
    """Static (compile-time) plugin configuration for the batch kernel."""

    filters: tuple  # subset of FILTER_KERNELS, in profile order
    scores: tuple   # ((kernel_name, weight), ...) in profile order
    fit_strategy: str = "LeastAllocated"
    # scoringStrategy.resources: ((col, weight), ...) over the nz axis
    # (0 = cpu, 1 = memory) — upstream default is cpu:1, memory:1
    fit_resources: tuple = ((0, 1), (1, 1))
    # RequestedToCapacityRatio shape: ((utilization, score·10), ...) points
    # ascending in utilization (only read when fit_strategy selects it)
    fit_shape: tuple = ()
    trace: bool = False
    # selectHost tie handling: "first" = first tied max in visit order;
    # "reservoir" = k-th tied max with k from the counter-keyed hash draw
    # (utils/hashing.py) — bit-identical to the sequential _select_host.
    tie_break: str = "first"
    seed: int = 0
    # False compiles out the feasible-node sampling machinery (rotation
    # ranks + rotated prefix sums) — valid only when sample_k covers every
    # node AND the start index is 0, where visit order == index order.
    # BatchEngine picks the variant per round; both share the jit cache.
    sampling: bool = True
    # True lifts the per-plugin score weights out of ``scores`` into the
    # TRACED DeviceProblem.plugin_w [S] vector: weight changes re-dispatch
    # the same executable instead of recompiling (the tuner's rollout
    # loop, SchedulerService weight overrides).  False (default) keeps the
    # weights constant-folded from ``scores`` — byte-identical executables
    # to the pre-traced build.
    traced_weights: bool = False
    # Softmax-relaxed decision head (tuning/relax.py): τ > 0 rewrites the
    # commit one-hot as a straight-through estimator — forward values are
    # EXACTLY the hard argmax decision (relaxed and hard rollouts agree
    # bit-for-bit), but the backward pass routes d(carry)/d(weights)
    # through softmax(totals/τ) over the sampled nodes, which is what
    # makes whole rollouts differentiable in the plugin weights.  0 = off.
    relax_tau: float = 0.0


FILTER_KERNELS = (
    "NodeUnschedulable",
    "NodeName",
    "NodePorts",
    "TaintToleration",
    "NodeAffinity",
    "NodeResourcesFit",
    "VolumeRestrictions",
    "EBSLimits",
    "GCEPDLimits",
    "NodeVolumeLimits",
    "AzureDiskLimits",
    "VolumeBinding",
    "VolumeZone",
    "PodTopologySpread",
    "InterPodAffinity",
)
# per-family cloud volume-count limits: (cloud_cnt column, default limit),
# sourced from the oracle plugin classes so limits can't drift
from kube_scheduler_simulator_tpu.plugins.intree.volumes import CLOUD_LIMIT_PLUGINS

CLOUD_LIMIT_COL = {
    cls.name: (col, float(cls.default_limit)) for col, cls in enumerate(CLOUD_LIMIT_PLUGINS)
}
SCORE_KERNELS = (
    "NodeResourcesFit",
    "NodeResourcesBalancedAllocation",
    "TaintToleration",
    "NodeAffinity",
    "PodTopologySpread",
    "InterPodAffinity",
    "ImageLocality",
)

# How each score kernel's NormalizeScore relates raw → normalized; drives
# the trace-fetch plan (build_compact_fn): "identity" plugins fetch ONE
# int8 plane that serves as both raw and norm; "default"/"default_reverse"
# /"minmax" fetch raw only and the host recomputes norm with exact integer
# arithmetic (equal to the kernel's float path for |raw| < 2^15 — the
# dtype chooser falls back to fetching norm beyond that); "custom"
# (PodTopologySpread's mx+mn-raw form needs the ignored-node mask the
# trace doesn't carry) fetches both.
NORMALIZE_KIND = {
    "NodeResourcesFit": "identity",
    "NodeResourcesBalancedAllocation": "identity",
    "ImageLocality": "identity",
    "TaintToleration": "default_reverse",
    "NodeAffinity": "default",
    "InterPodAffinity": "minmax",
    "PodTopologySpread": "custom",
}


def fail_pack_mode(code_max: int, n_filters: int) -> int:
    """How the (first-fail plugin, code) planes travel: 0 = one uint8
    nibble pair, 1 = one uint16 byte pair, 2/3 = separate planes with
    int16/int32 codes.  Both the compact-fn builder and the engine's
    executable cache key derive from THIS function — the packing decision
    determines the blob manifest, so the two must never disagree."""
    if code_max <= 15 and n_filters + 1 <= 15:
        return 0
    if code_max <= 255 and n_filters + 1 <= 255:
        return 1
    return 2 if code_max <= 0x7FFF else 3


def raw_dtype_for(mn: int, mx: int) -> str:
    """Minimal fetch dtype for a raw-score plane, with headroom so the
    choice (part of the compact-executable cache key) stays stable as the
    cluster fills."""
    if -100 <= mn and mx <= 100:
        return "int8"
    if -30000 <= mn and mx <= 30000:
        return "int16"
    return "int32"


def trace_fetch_plan(cfg: "BatchConfig", raw_dtypes: "tuple[str, ...]"):
    """Per score plugin: (fetch_raw, fetch_norm, host_norm_kind | None)."""
    plan = []
    for k, (s, _w) in enumerate(cfg.scores):
        kind = NORMALIZE_KIND.get(s, "custom")
        if kind == "identity":
            plan.append((False, True, None))
        elif kind == "custom" or raw_dtypes[k] == "int32":
            # int32 raws: the host's integer normalize is no longer
            # provably equal to the kernel's float path — fetch norm too
            plan.append((True, True, None))
        else:
            plan.append((True, False, kind))
    return tuple(plan)


class DeviceProblem(NamedTuple):
    """BatchProblem lowered to device arrays (a pytree, jit-traceable)."""

    alloc: Any            # [N,R]
    max_pods: Any         # [N]
    nz_alloc: Any         # [N,2]
    pod_req: Any          # [P,R]
    pod_nonzero: Any      # [P,2]
    fit_checked: Any      # [P,R] bool
    # Pairwise features, factored through (pod-class × node-class) matrices
    # — a few MB of transfer instead of ~700 MB of dense [P,N] at 10k×5k;
    # the kernel expands them on-device (_expand_features) into the
    # taint_fail / taint_prefer / unsched_ok / aff_code / aff_pref /
    # name_ok / incl [P,N] fields below, which lower() leaves as scalar
    # placeholders.
    taint_cls: Any        # [L,T] int16: first untolerated taint idx or -1
    taint_prefer_cls: Any # [L,T] int16
    taint_unsched_cls: Any# [L,T] bool
    pod_tol_idx: Any      # [P] int32
    node_taint_idx: Any   # [N] int32
    node_unsched: Any     # [N] bool
    aff_code_cls: Any     # [A,M] int8
    incl_cls: Any         # [A,M] bool
    aff_pref_cls: Any     # [B,M] int32
    pod_aff_idx: Any      # [P] int32
    pod_pref_idx: Any     # [P] int32
    node_label_idx: Any   # [N] int32
    img_cls: Any          # [IC,MC] int8: COMPLETE ImageLocality score
    pod_img_idx: Any      # [P] int32
    node_img_idx: Any     # [N] int32
    name_target: Any      # [P] int32: -1 free, node idx, -2 absent node
    pod_ports: Any        # [P,PT] bool: wanted host-port classes
    port_conflict: Any    # [PT,PT] bool: class-pair conflicts
    # Volume plugins (ops/encode._encode_volumes): static class matrices
    # for VolumeBinding/VolumeZone, NodePorts-style conflict classes for
    # VolumeRestrictions, per-family counts for the cloud limits, and the
    # (driver, volume-id) attachment model for CSI NodeVolumeLimits.
    vb_cls: Any           # [VC,M] int8: VolumeBinding code per class pair
    vz_cls: Any           # [VC,M] int8: VolumeZone code per class pair
    pod_vol_idx: Any      # [P] int32: pod volume-class index
    pod_restr: Any        # [P,VR] bool: wanted volume-conflict classes
    restr_conflict: Any   # [VR,VR]: class-pair conflicts
    cloud_cnt: Any        # [P,3]: per-family cloud volume counts
    pod_csi: Any          # [P,V] bool: wanted CSI volume-id classes
    csi_drv_oh: Any       # [V,DR]: volume-id → driver one-hot
    csi_seed_used: Any    # [N,DR]: existing per-driver attachments not in V
    csi_limit: Any        # [N,DR]: per-driver caps (CSINode allocatable)
    taint_fail: Any       # [P,N] int16 (expanded on-device)
    taint_prefer: Any     # [P,N] (expanded on-device)
    unsched_ok: Any       # [P,N] bool (expanded on-device)
    aff_code: Any         # [P,N] int8 (expanded on-device)
    aff_pref: Any         # [P,N] (expanded on-device)
    name_ok: Any          # [P,N] bool (expanded on-device)
    incl: Any             # [P,N] bool (expanded on-device)
    img_score: Any        # [P,N] (expanded on-device)
    vb_code: Any          # [P,N] int8 (expanded on-device)
    vz_code: Any          # [P,N] int8 (expanded on-device)
    node_domain: Any      # [KT,N] int32
    spf: Any              # spread filter constraints (key,grp,skew,self) [P,KC]
    sps: Any              # spread score constraints [P,KS]
    spread_match: Any     # [SG,P] bool
    gdom: Any             # [G,N] int32 (domain of each group's key per node)
    term_match: Any       # [G,P]
    ip_aff_g: Any         # [P,KA]
    ip_anti_g: Any        # [P,KB]
    ip_pref_g: Any        # [P,KP]
    ip_pref_w: Any        # [P,KP]
    ip_own_g: Any         # [P,KO]
    ip_own_w: Any         # [P,KO]
    ip_self_match: Any    # [P] bool
    pod_active: Any       # [P] bool (False = padding row, never committed)
    node_active: Any      # [N] bool (False = padding column, never feasible)
    tb_base: Any          # [] uint32: attempt counter of the round's first pod
    # Traced per-plugin score weights [S] (cfg.traced_weights); a scalar
    # placeholder when the weights are constant-folded from cfg.scores.
    plugin_w: Any
    # Feasible-node sampling (upstream numFeasibleNodesToFind + rotating
    # start index, mirrored from framework_runner.schedule_one's filter
    # loop).  All three are traced scalars: value changes don't recompile.
    sample_k: Any         # [] int32: stop after this many feasible nodes
    start0: Any           # [] int32: rotation start index for the first pod
    n_true: Any           # [] int32: real node count (modulus; N minus padding)
    # Per-used-topology-key expansion data.  Domain-level [D+1] vectors are
    # expanded to node vectors WITHOUT per-element gathers of the mutable
    # carry (XLA serializes those inside the scan, ~10x slower):
    # - "identity" keys (hostname-like bijections, dom = base + n): a
    #   dynamic_slice + valid mask — free;
    # - interned keys (zones): a small [size, N] one-hot matmul.
    # The static structure (kind, base, size per key) lives in
    # dims["key_struct"]; the arrays here are traced inputs.
    key_valid: Any        # tuple of [N] bool, per used key
    key_oh: Any           # tuple of [size,N] one-hots ([0,N] for identity keys)
    g_ku: Any             # [G] local key index per term group
    spf_ku: Any           # [P, KC] local key per filter constraint
    sps_ku: Any           # [P, KS] local key per score constraint
    # initial carry
    requested0: Any       # [N,R]
    nonzero0: Any         # [N,2]
    pod_count0: Any       # [N]
    ports_used0: Any      # [N,PT]: used host-port class counts
    restr_used0: Any      # [N,VR]: occupying volume-conflict counts
    cloud_used0: Any      # [N,3]: per-family cloud volume counts
    csi_attached0: Any    # [N,V]: CSI volume-id attachment bits
    spread_counts0: Any   # [SG,N]
    ip_sel0: Any          # [G,D+1]
    ip_own0: Any          # [G,D+1]
    ip_anti0: Any         # [G,D+1]


def lower(pr: BatchProblem, dtype=None) -> "tuple[DeviceProblem, dict]":
    """Convert host BatchProblem → DeviceProblem (+ static dims dict).

    The returned arrays are HOST (numpy) arrays: callers ship the whole
    pytree with ONE ``jax.device_put`` (plain or sharded — see
    BatchEngine._schedule / shard_device_problem).  Through a tunneled
    TPU every individual H2D dispatch pays ~100 ms latency, so ~70
    per-field transfers would cost more than the kernel itself."""
    if dtype is None:
        dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    f = lambda x: np.asarray(x, dtype=dtype)
    i32 = lambda x: np.asarray(x, dtype=np.int32)
    b = lambda x: np.asarray(x, dtype=bool)
    D = pr.D
    group_key = np.asarray(pr.group_key)
    gdom = np.asarray(pr.node_domain)[np.clip(group_key, 0, None)]  # [G,N]
    pad = lambda a: np.concatenate([a, np.zeros((a.shape[0], 1), a.dtype)], axis=1)

    # Used topology keys → local index + static expansion structure
    # (see DeviceProblem.key_valid/key_oh and dims["key_struct"]).
    node_domain = np.asarray(pr.node_domain)
    used_keys: list[int] = sorted(
        {int(k) for k in group_key.tolist() if pr.G}
        | {int(k) for k in np.asarray(pr.spf_key).ravel().tolist() if k >= 0}
        | {int(k) for k in np.asarray(pr.sps_key).ravel().tolist() if k >= 0}
    )
    ku_of = {k: u for u, k in enumerate(used_keys)}
    N = pr.N
    key_base = list(getattr(pr, "key_base", []))
    key_identity = list(getattr(pr, "key_identity", []))
    key_struct: list[tuple] = []
    key_valid: list[np.ndarray] = []
    key_oh: list[np.ndarray] = []
    for k in used_keys:
        dom = node_domain[k]
        valid = dom >= 0
        base = key_base[k] if k < len(key_base) else 0
        if key_identity[k] if k < len(key_identity) else False:
            key_struct.append(("identity", base, N))
            key_valid.append(valid)
            key_oh.append(np.zeros((0, N), dtype=np.float32))
        else:
            size = int(dom[valid].max() - base + 1) if valid.any() else 1
            oh = np.zeros((size, N), dtype=np.float32)
            oh[dom[valid] - base, np.nonzero(valid)[0]] = 1.0
            key_struct.append(("onehot", base, size))
            key_valid.append(valid)
            key_oh.append(oh)
    if not used_keys:
        key_struct.append(("identity", 0, N))
        key_valid.append(np.zeros(N, dtype=bool))
        key_oh.append(np.zeros((0, N), dtype=np.float32))

    def remap(keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys)
        lut = np.zeros(max((max(ku_of, default=0) + 1, 1)), dtype=keys.dtype)
        for k, u in ku_of.items():
            lut[k] = u
        return lut[np.clip(keys, 0, len(lut) - 1)]

    g_ku = remap(group_key) if pr.G else np.zeros(1, dtype=np.int32)
    spf_ku = remap(np.asarray(pr.spf_key))
    sps_ku = remap(np.asarray(pr.sps_key))
    dp = DeviceProblem(
        alloc=f(pr.alloc),
        max_pods=f(pr.max_pods),
        nz_alloc=f(pr.nz_alloc),
        pod_req=f(pr.pod_req),
        pod_nonzero=f(pr.pod_nonzero),
        fit_checked=b(pr.fit_checked),
        taint_cls=np.asarray(pr.taint_cls, dtype=np.int16),
        taint_prefer_cls=np.asarray(pr.taint_prefer_cls, dtype=np.int16),
        taint_unsched_cls=b(pr.taint_unsched_cls),
        pod_tol_idx=i32(pr.pod_tol_idx),
        node_taint_idx=i32(pr.node_taint_idx),
        node_unsched=b(pr.node_unsched),
        aff_code_cls=np.asarray(pr.aff_code_cls, dtype=np.int8),
        incl_cls=b(pr.incl_cls),
        aff_pref_cls=i32(pr.aff_pref_cls),
        pod_aff_idx=i32(pr.pod_aff_idx),
        pod_pref_idx=i32(pr.pod_pref_idx),
        node_label_idx=i32(pr.node_label_idx),
        img_cls=np.asarray(pr.img_cls, dtype=np.int8),
        pod_img_idx=i32(pr.pod_img_idx),
        node_img_idx=i32(pr.node_img_idx),
        name_target=i32(pr.name_target),
        pod_ports=b(pr.pod_ports),
        port_conflict=f(pr.port_conflict),
        vb_cls=np.asarray(pr.vb_cls, dtype=np.int8),
        vz_cls=np.asarray(pr.vz_cls, dtype=np.int8),
        pod_vol_idx=i32(pr.pod_vol_idx),
        pod_restr=b(pr.pod_restr),
        restr_conflict=f(pr.restr_conflict),
        cloud_cnt=f(pr.cloud_cnt),
        pod_csi=b(pr.pod_csi),
        csi_drv_oh=f(pr.csi_drv_oh),
        csi_seed_used=f(pr.csi_seed_used),
        csi_limit=f(pr.csi_limit),
        # expanded on-device inside the jitted kernel (_expand_features)
        taint_fail=np.int32(0),
        taint_prefer=np.int32(0),
        unsched_ok=np.int32(0),
        aff_code=np.int32(0),
        aff_pref=np.int32(0),
        name_ok=np.int32(0),
        incl=np.int32(0),
        img_score=np.int32(0),
        vb_code=np.int32(0),
        vz_code=np.int32(0),
        node_domain=i32(pr.node_domain),
        spf=(i32(pr.spf_key), i32(pr.spf_group), f(pr.spf_skew), f(pr.spf_self)),
        sps=(i32(pr.sps_key), i32(pr.sps_group), f(pr.sps_skew), f(pr.sps_self)),
        spread_match=f(pr.spread_match),
        gdom=i32(gdom),
        term_match=f(pr.term_match),
        ip_aff_g=i32(pr.ip_aff_g),
        ip_anti_g=i32(pr.ip_anti_g),
        ip_pref_g=i32(pr.ip_pref_g),
        ip_pref_w=f(pr.ip_pref_w),
        ip_own_g=i32(pr.ip_own_g),
        ip_own_w=f(pr.ip_own_w),
        ip_self_match=b(pr.ip_self_match),
        pod_active=b(pr.pod_active),
        node_active=b(pr.node_active),
        tb_base=np.uint32(0),
        plugin_w=np.int32(0),
        sample_k=np.int32(pr.N_true),
        start0=np.int32(0),
        n_true=np.int32(pr.N_true),
        key_valid=tuple(b(v) for v in key_valid),
        key_oh=tuple(f(o) for o in key_oh),
        g_ku=i32(g_ku),
        spf_ku=i32(spf_ku),
        sps_ku=i32(sps_ku),
        requested0=f(pr.requested0),
        nonzero0=f(pr.nonzero0),
        pod_count0=f(pr.pod_count0),
        ports_used0=f(pr.ports_used0),
        restr_used0=f(pr.restr_used0),
        cloud_used0=f(pr.cloud_used0),
        csi_attached0=f(pr.csi_attached0),
        spread_counts0=f(pr.spread_counts0),
        ip_sel0=f(pad(np.asarray(pr.ip_sel0))),
        ip_own0=f(pad(np.asarray(pr.ip_own0))),
        ip_anti0=f(pad(np.asarray(pr.ip_anti0))),
    )
    dims = dict(
        P=pr.P, N=pr.N, R=pr.R, D=D, SG=pr.SG, G=pr.G, PT=pr.PT,
        KC=pr.KC, KS=pr.KS, KA=pr.KA, KB=pr.KB, KP=pr.KP, KO=pr.KO,
        VR=pr.VR, VID=pr.VID, DR=pr.DR, CLOUD=pr.CLOUD,
        key_struct=tuple(key_struct),
    )
    return dp, dims


# --------------------------------------------------------------- primitives

def _mv(a, b):
    """Matvec at HIGHEST precision: the one-hot expansions must stay exact
    integer arithmetic on TPU (default f32 matmul precision is bf16-based)."""
    return jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)


def _mix32(x):
    """murmur3 32-bit finalizer — constants MUST match utils/hashing.py."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _floordiv(a, b):
    """Go integer division for non-negative operands, in floats."""
    return jnp.floor(a / jnp.where(b == 0, 1.0, b)) * (b != 0)


def _truncdiv(a, b):
    """Go integer division with truncation toward zero, in floats (the
    broken-linear shape interpolation has negative numerators on
    descending ramps, where floor and trunc differ)."""
    return jnp.trunc(a / jnp.where(b == 0, 1.0, b)) * (b != 0)


def _broken_linear(p, shape: tuple):
    """helper.BuildBrokenLinearFunction over static (utilization, score)
    points: clamp outside the range, Go-integer interpolation inside.
    Descending-index sweep so the FIRST point with p <= utilization wins
    (later writes overwrite earlier ones)."""
    out = jnp.full_like(p, float(shape[-1][1]))
    for i in range(len(shape) - 1, -1, -1):
        u, s = shape[i]
        if i == 0:
            v = jnp.full_like(p, float(s))
        else:
            u0, s0 = shape[i - 1]
            v = float(s0) + _truncdiv(float(s - s0) * (p - float(u0)), float(max(u - u0, 1)))
        out = jnp.where(p <= float(u), v, out)
    return out


def _default_normalize(raw, feasible, reverse: bool):
    """helper.DefaultNormalizeScore over the feasible set (int semantics)."""
    mx = jnp.max(jnp.where(feasible, raw, 0.0))
    scaled = _floordiv(raw * MAX_NODE_SCORE, mx)
    out = jnp.where(reverse, MAX_NODE_SCORE - scaled, scaled)
    zero_case = MAX_NODE_SCORE if reverse else 0.0
    return jnp.where(mx == 0, zero_case, out)


def _minmax_normalize(raw, feasible):
    """InterPodAffinity's ScoreExtensions: MAX*(v-min)/(max-min), trunc."""
    mn = jnp.min(jnp.where(feasible, raw, jnp.inf))
    mx = jnp.max(jnp.where(feasible, raw, -jnp.inf))
    diff = mx - mn
    return jnp.where(diff > 0, jnp.floor(MAX_NODE_SCORE * (raw - mn) / jnp.where(diff == 0, 1.0, diff)), 0.0)


# ------------------------------------------------------------------- kernel

NODE_AXIS_SPECS = {
    # [N, ...] node-major state: shard axis 0
    "alloc": (0,),
    "max_pods": (0,),
    "nz_alloc": (0,),
    "requested0": (0,),
    "nonzero0": (0,),
    "pod_count0": (0,),
    # per-node class-index vectors — the on-device [P,N] feature
    # expansion inherits the node sharding from these
    "node_taint_idx": (0,),
    "node_label_idx": (0,),
    "node_img_idx": (0,),
    "node_unsched": (0,),
    "node_active": (0,),
    # [KT/SG/G, N]: shard the node axis
    "node_domain": (1,),
    "spread_counts0": (1,),
    "gdom": (1,),
    "ports_used0": (0,),
    "restr_used0": (0,),
    "cloud_used0": (0,),
    "csi_attached0": (0,),
    "csi_seed_used": (0,),
    "csi_limit": (0,),
}


def field_sharding(mesh, name: str, val, axis_name: str = "nodes"):
    """The mesh sharding for one DeviceProblem field: node-axis fields
    (NODE_AXIS_SPECS) shard their node axis, everything else replicates.
    Shared by the whole-tree placement (shard_device_problem) and the
    per-plane delta uploads (DevicePlacer), so the two can never disagree
    about a field's layout."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    axes = NODE_AXIS_SPECS.get(name)
    if axes is None:
        return NamedSharding(mesh, P())
    nm = mesh.shape[axis_name]
    ndim = getattr(val, "ndim", 1)
    for ax in axes:
        if val.shape[ax] % nm:
            raise ValueError(
                f"{name} axis {ax} ({val.shape[ax]}) not divisible by the "
                f"{nm}-device mesh — pad the node axis to a multiple "
                f"(BatchEngine does via pad_problem(node_multiple=...))"
            )
    parts = [axis_name if i in axes else None for i in range(max(ndim, 1))]
    return NamedSharding(mesh, P(*parts))


def shard_device_problem(dp: "DeviceProblem", mesh, axis_name: str = "nodes") -> "DeviceProblem":
    """Place a lowered DeviceProblem onto ``mesh`` with the NODE axis
    sharded — the tensor-parallel axis of this workload: every per-step
    filter/score is elementwise over nodes, and the cross-node reductions
    (feasible counts, normalize max/min, argmax select) become XLA
    collectives over ICI.  Everything else (pod-axis features, class
    matrices, [G,D] counts) replicates.  This is the scaling-axis mapping
    SURVEY.md §5 calls out: the reference scales via goroutine parallelism
    over nodes; the TPU build scales the node axis across chips."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    replicated = NamedSharding(mesh, P())
    shardings = DeviceProblem(
        **{
            name: (
                tuple(replicated for _ in val)
                if isinstance(val, tuple)
                else field_sharding(mesh, name, val, axis_name)
            )
            for name, val in dp._asdict().items()
        }
    )
    # one pytree-level transfer instead of ~70 per-field dispatches
    return jax.device_put(dp, shardings)


def tree_nbytes(dp: "DeviceProblem") -> int:
    """Host bytes a full placement of ``dp`` would upload (ndarray leaves
    only; traced scalars are noise) — the accounting for the non-cached
    placement path."""
    total = 0
    for val in dp:
        for leaf in (val if isinstance(val, tuple) else (val,)):
            if isinstance(leaf, np.ndarray) and leaf.ndim:
                total += leaf.nbytes
    return total


def tree_shard_bytes_per_device(dp: "DeviceProblem", n_devices: int) -> int:
    """Per-device bytes of a full sharded placement of ``dp``: node-axis
    planes (NODE_AXIS_SPECS) split across the mesh, everything else
    replicated in full on every device — the memory-scaling claim of the
    sharded path, surfaced as ``plane_shard_bytes_per_device``."""
    n = max(int(n_devices), 1)
    total = 0
    for name, val in dp._asdict().items():
        sharded = name in NODE_AXIS_SPECS
        for leaf in (val if isinstance(val, tuple) else (val,)):
            if isinstance(leaf, np.ndarray) and leaf.ndim:
                total += leaf.nbytes // n if sharded else leaf.nbytes
    return total


def _scatter_rows(buf, idx, rows):
    return buf.at[idx].set(rows)


# donating the stale buffer lets XLA update the plane in place; CPU has no
# donation support (it would warn per call), so the copying variant serves
# the virtual-mesh/test path
_scatter_donate = jax.jit(_scatter_rows, donate_argnums=(0,))
_scatter_copy = jax.jit(_scatter_rows)


def placer_scatter_frac(default: float = 0.25) -> float:
    """The placer's ≤frac-changed scatter-update threshold, from the
    ``KSS_PLACER_SCATTER_FRAC`` env knob (default 0.25 — ship row deltas
    as a jitted scatter while at most a quarter of the plane's rows
    changed, full re-upload past that).  Validated hard: an unparseable
    or out-of-range value raises instead of silently running with a
    threshold the operator didn't set."""
    import os

    raw = os.environ.get("KSS_PLACER_SCATTER_FRAC")
    if raw is None or not raw.strip():
        return default
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(
            f"KSS_PLACER_SCATTER_FRAC must be a float in (0, 1], got {raw!r}"
        ) from None
    if not 0.0 < v <= 1.0:
        raise ValueError(f"KSS_PLACER_SCATTER_FRAC must be in (0, 1], got {raw!r}")
    return v


class DevicePlacer:
    """Device-resident DeviceProblem: delta uploads across rounds.

    The engine lowers a fresh host-side DeviceProblem every round, but on
    a churn workload most planes are bytes-identical to the previous
    round's — the node/class features only change when the CLUSTER
    changes, not when pods churn.  This cache keeps the previous round's
    device buffers (keyed by the static shape/config key the executables
    are keyed by) and, per plane:

    - byte-identical host plane      → reuse the resident buffer (0 bytes
                                       uploaded);
    - few changed rows (≤ ¼)         → ship (indices, rows) and apply a
                                       small jitted scatter-update, the
                                       stale buffer donated in place and
                                       the sharding preserved (multichip
                                       node-axis meshes keep working);
    - otherwise / shape changed      → full upload, batched with every
                                       other changed plane into ONE
                                       ``jax.device_put`` (through a
                                       tunneled TPU each dispatch pays the
                                       full latency).

    CARRY0_FIELDS are never cached: both kernel paths donate the initial
    carry, so their buffers die inside the round by design.

    ``bytes_uploaded`` counts actual H2D traffic (full planes + scatter
    indices/rows); ``plane_reuses``/``scatter_updates``/``full_uploads``
    break the decisions out for /metrics.

    ``place(..., bank=)`` selects one of several independent resident
    plane SETS per shape key — the streaming pipeline's double buffer.
    A streamed round k+1 places into the bank wave k-1 used (the banks
    alternate per wave), so its scatter-updates never donate a buffer
    wave k's still-in-flight kernel reads; the bank's host arrays are
    one wave staler, which on a churn workload still leaves the large
    majority of planes byte-identical.  Bank 0 with no alternation is
    the pre-streaming behavior, unchanged.

    ``scatter_max_frac`` defaults from the ``KSS_PLACER_SCATTER_FRAC``
    env knob (see :func:`placer_scatter_frac`); an explicit argument
    wins.
    """

    def __init__(self, mesh=None, axis_name: str = "nodes", max_keys: int = 2,
                 scatter_max_frac: "float | None" = None):
        self.mesh = mesh
        self.axis_name = axis_name
        self.max_keys = max_keys
        self.scatter_max_frac = (
            placer_scatter_frac() if scatter_max_frac is None else scatter_max_frac
        )
        self.bytes_uploaded = 0
        self.plane_reuses = 0
        self.scatter_updates = 0
        self.full_uploads = 0
        # per-bank observability (the streaming double buffer): how often
        # the pipeline rotated banks, and scatter traffic per bank —
        # surfaced as /metrics gauges so a stuck rotation (one bank
        # starving while the other churns) is visible from a scrape
        self.bank_rotations = 0
        self.scatter_updates_by_bank: dict[int, int] = {}
        self._last_bank: "dict[Any, int]" = {}  # shape key → last bank placed
        # key → {(field, sub): (host ndarray, device array)}
        self._cache: "dict[Any, dict]" = {}
        self._order: list = []

    def _entry(self, key, bank: int = 0) -> dict:
        """The resident plane dict for ``(key, bank)``.  The LRU budget
        (``max_keys``) counts distinct SHAPE keys — banks nest under
        their key and are evicted with it — so a non-streaming engine
        (bank 0 only) retains exactly as many plane sets as before
        streaming existed, and memory grows with banks only when the
        pipeline actually alternates them."""
        banks = self._cache.get(key)
        if banks is None:
            banks = self._cache[key] = {}
            self._order.append(key)
            while len(self._order) > self.max_keys:
                evicted = self._order.pop(0)
                self._cache.pop(evicted, None)
                self._last_bank.pop(evicted, None)
        else:
            self._order.remove(key)
            self._order.append(key)
        entry = banks.get(bank)
        if entry is None:
            entry = banks[bank] = {}
        return entry

    def _scatter(self, cached_dev, idx, rows):
        """Apply a row update to a resident plane, preserving its sharding.

        The changed-row count is padded to a bucket boundary (repeating
        the first index with its own new row — idempotent under set) so
        the jitted update sees O(log) distinct K shapes instead of one
        trace/compile per exact count, matching the repo's static-shape
        bucketing convention."""
        from kube_scheduler_simulator_tpu.ops.encode import _bucket

        k = min(_bucket(len(idx)), cached_dev.shape[0])
        if k > len(idx):
            pad = k - len(idx)
            idx = np.concatenate([idx, np.full(pad, idx[0], dtype=idx.dtype)])
            rows = np.concatenate([rows, np.repeat(rows[:1], pad, axis=0)])
        sharding = cached_dev.sharding
        on_cpu = next(iter(cached_dev.devices())).platform == "cpu"
        fn = _scatter_copy if on_cpu else _scatter_donate
        out = fn(cached_dev, idx, rows)
        if self.mesh is not None and out.sharding != sharding:
            out = jax.device_put(out, sharding)
        self.bytes_uploaded += idx.nbytes + rows.nbytes
        self.scatter_updates += 1
        return out

    def bank_stats(self, n_devices: int = 0) -> "dict[int, dict]":
        """Per-bank resident-state snapshot for /metrics: scatter-update
        count plus the PER-DEVICE bytes of each bank's resident planes
        (node-sharded planes split across ``n_devices``, everything else
        counted in full — the same accounting as
        :func:`tree_shard_bytes_per_device`; ``n_devices``<=1 means
        single-device, full bytes)."""
        n = max(int(n_devices), 1)
        out: dict[int, dict] = {}
        for banks in self._cache.values():
            for bank, entry in banks.items():
                b = out.setdefault(bank, {"resident_plane_bytes_per_device": 0, "planes": 0})
                for (name, _sub), (host, _dev) in entry.items():
                    sharded = name in NODE_AXIS_SPECS
                    b["resident_plane_bytes_per_device"] += (
                        host.nbytes // n if sharded else host.nbytes
                    )
                    b["planes"] += 1
        for bank in self.scatter_updates_by_bank:
            out.setdefault(bank, {"resident_plane_bytes_per_device": 0, "planes": 0})
        for bank in out:
            out[bank]["scatter_updates"] = self.scatter_updates_by_bank.get(bank, 0)
        return out

    def place(self, dp: "DeviceProblem", key, bank: int = 0) -> "DeviceProblem":
        """Place ``dp`` on device, reusing/delta-updating resident planes.
        ``bank`` selects the resident plane set (double-buffer lane) —
        diffs and scatter-donations only ever touch that bank's buffers."""
        bank = int(bank)
        prev = self._last_bank.get(key)
        if prev is not None and prev != bank:
            self.bank_rotations += 1
        self._last_bank[key] = bank
        entry = self._entry(key, int(bank))
        out: dict[str, Any] = {}
        uploads: dict = {}      # (field, sub) → host value (one device_put)
        scatters: list = []     # ((field, sub), cached_dev, idx, rows)
        new_hosts: dict = {}    # (field, sub) → host ndarray (cache refresh)

        def want(path, name, val):
            """Route one leaf: reuse, scatter, or full upload."""
            if not isinstance(val, np.ndarray) or val.ndim == 0 or name in CARRY0_FIELDS:
                uploads[path] = val
                if isinstance(val, np.ndarray) and val.ndim:
                    self.bytes_uploaded += val.nbytes
                return
            new_hosts[path] = val
            cached = entry.get(path)
            if cached is not None:
                host_old, dev_old = cached
                if host_old.shape == val.shape and host_old.dtype == val.dtype:
                    if val.size == 0:  # zero-width planes (e.g. identity key_oh)
                        out_leaves[path] = dev_old
                        self.plane_reuses += 1
                        return
                    diff = (val != host_old)
                    if val.ndim > 1:
                        diff = diff.reshape(val.shape[0], -1).any(axis=1)
                    changed = np.nonzero(diff)[0]
                    if changed.size == 0:
                        out_leaves[path] = dev_old
                        self.plane_reuses += 1
                        return
                    if changed.size <= max(1, int(val.shape[0] * self.scatter_max_frac)):
                        scatters.append(
                            (path, dev_old,
                             changed.astype(np.int32),
                             np.ascontiguousarray(val[changed]))
                        )
                        return
            uploads[path] = val
            self.bytes_uploaded += val.nbytes
            self.full_uploads += 1

        out_leaves: dict = {}
        for name, val in dp._asdict().items():
            if isinstance(val, tuple):
                for i, leaf in enumerate(val):
                    want((name, i), name, leaf)
            else:
                want((name, None), name, val)

        if uploads:
            if self.mesh is not None:
                shardings = {
                    path: field_sharding(self.mesh, path[0], val, self.axis_name)
                    for path, val in uploads.items()
                }
                placed = jax.device_put(uploads, shardings)
            else:
                placed = jax.device_put(uploads)
            out_leaves.update(placed)
        for path, dev_old, idx, rows in scatters:
            out_leaves[path] = self._scatter(dev_old, idx, rows)
        if scatters:
            self.scatter_updates_by_bank[bank] = (
                self.scatter_updates_by_bank.get(bank, 0) + len(scatters)
            )

        # refresh the resident cache (lower() allocates fresh host arrays
        # every round, so holding the references is safe)
        for path, host in new_hosts.items():
            entry[path] = (host, out_leaves[path])

        # reassemble the namedtuple (tuple fields from their leaves)
        for name, val in dp._asdict().items():
            if isinstance(val, tuple):
                out[name] = tuple(out_leaves[(name, i)] for i in range(len(val)))
            else:
                out[name] = out_leaves[(name, None)]
        return DeviceProblem(**out)


def build_compact_fn(
    cfg: BatchConfig,
    dims: dict,
    W: int,
    WS: int,
    raw_dtypes: "tuple[str, ...] | None" = None,
    code_max: int = 1 << 30,
    in_step_ws0: "int | None" = None,
):
    """Build the trace-compaction function: reduce the [P,N] trace arrays
    to exactly what the annotation writer reads, and nothing more —
    through a tunneled TPU (~10 MB/s D2H) the fetch volume IS the trace
    cost, and a dense per-filter fetch is minutes per round.

    - The filter trail records, per visited node, "passed" for every
      plugin before the FIRST failure and the failure itself (the
      sequential cycle short-circuits there) — so one (plugin, code)
      plane suffices, not F planes.
    - Scores only exist at FEASIBLE nodes (≤ sample_k of them), so the
      score stacks compact to WS = bucket(max feasible), not the visited
      width W.
    - The visited ids themselves are NOT fetched: the visit window is
      deterministic from (sample_start, sample_processed, n_true), and
      the host reproduces the ascending-index column order with
      arithmetic (BatchResult._visited_ids).
    - The feasible ids are NOT fetched either when filters are present:
      a visited node is feasible iff its fail_plug is -1, so the host
      derives them (reconstruct_trace) instead of moving [P,WS] int32.
    - Per-plugin score planes move at minimal dtype (``raw_dtypes``, from
      the kernel's raw_minmax), and only the planes the fetch plan needs
      (trace_fetch_plan): identity-normalized plugins move one int8
      plane; host-normalizable plugins move raw only.

    Every output plane is bitcast to uint8 and concatenated into ONE flat
    blob: through the tunnel, each fetched array pays a full roundtrip's
    latency on top of its bytes, so a dozen per-plane fetches cost more
    than the data itself.  The host unpacks by the (name, dtype, shape)
    manifest this builder returns alongside the jitted function.

    Planes (exact integers by kernel construction; casts lossless):
      fail8     [P,W]  uint8      (plug+1)<<4 | code when every failure
                                  code fits 4 bits (``code_max``)
      fail      [P,W]  uint16     (plug+1)<<8 | code when codes fit 8 bits
      fail_plug/fail_code separate planes otherwise
      sids      [P,WS] int32      only when cfg.filters is empty
      raw:k     [P,WS] raw_dtypes[k]  where the plan fetches raw
      norm:k    [P,WS] int8       where the plan fetches norm
    """
    P, N = dims["P"], dims["N"]
    mode = fail_pack_mode(code_max, len(cfg.filters))
    pack8 = mode == 0
    pack16 = mode == 1
    code_dtype_name = "int16" if mode == 2 else "int32"
    code_dtype = getattr(jnp, code_dtype_name)
    raw_dtypes = raw_dtypes or tuple("int32" for _ in cfg.scores)
    plan = trace_fetch_plan(cfg, raw_dtypes)

    manifest: "list[tuple[str, str, tuple]]" = []
    if cfg.filters:
        if pack8:
            manifest.append(("fail8", "uint8", (P, W)))
        elif pack16:
            manifest.append(("fail", "uint16", (P, W)))
        else:
            manifest.append(("fail_plug", "int8", (P, W)))
            manifest.append(("fail_code", code_dtype_name, (P, W)))
    else:
        manifest.append(("sids", "int32", (P, WS)))
    for k, (_s, _w) in enumerate(cfg.scores):
        fetch_raw, fetch_norm, _host = plan[k]
        if fetch_raw:
            manifest.append((f"raw:{k}", raw_dtypes[k], (P, WS)))
        if fetch_norm:
            manifest.append((f"norm:{k}", "int8", (P, WS)))

    def run(out: dict, n_true):
        idx = jnp.arange(N, dtype=jnp.int32)[None, :]
        d = idx - out["sample_start"][:, None]
        rank = jnp.where(d >= 0, d, d + n_true)
        # padded node columns can alias into the rank window when the
        # rotation start is nonzero — they were never really visited
        visited = (rank < out["sample_processed"][:, None]) & (idx < n_true)
        rows = jnp.arange(P, dtype=jnp.int32)[:, None]

        def partition_ids(mask, Wd):
            """ids of True entries per row, ascending, padded to width Wd
            — exactly argsort(where(mask, idx, N+idx))[:, :Wd], but as a
            cumsum + scatter stable partition: the ids are already
            sorted, so a comparison sort per row is pure overhead (the
            two argsorts here were the dominant trace cost on CPU)."""
            pos = jnp.cumsum(mask.astype(jnp.int32), axis=1, dtype=jnp.int32) - 1
            dest = jnp.where(mask & (pos < Wd), pos, Wd)
            ids = jnp.zeros((P, Wd), dtype=jnp.int32).at[
                rows, dest
            ].set(jnp.broadcast_to(idx, mask.shape), mode="drop")
            cnt = jnp.minimum(pos[:, -1] + 1, Wd)
            valid = jnp.arange(Wd, dtype=jnp.int32)[None, :] < cnt[:, None]
            return ids, valid

        res = {}
        if cfg.filters:
            order, valid = partition_ids(visited, W)
            take = lambda a: jnp.take_along_axis(a, order, axis=1)
            # the step already tracked (first failing filter, code) planes
            plug = jnp.where(valid, take(out["fail_plug"]), -1)
            code = jnp.where(valid, take(out["fail_code"]), 0)
            if pack8:
                res["fail8"] = (
                    ((plug + 1).astype(jnp.uint8) << 4) | code.astype(jnp.uint8)
                )
            elif pack16:
                res["fail"] = (
                    ((plug + 1).astype(jnp.uint16) << 8)
                    | code.astype(jnp.uint16)
                )
            else:
                res["fail_plug"] = plug.astype(jnp.int8)
                res["fail_code"] = code.astype(code_dtype)
        if in_step_ws0 is not None:
            # the scan already compacted score planes to [P, in_step_ws0]
            # in ascending-id feasible order — just slice to the fetch
            # width and mask positionally
            svalid = (
                jnp.arange(WS, dtype=jnp.int32)[None, :]
                < out["feasible_count"].astype(jnp.int32)[:, None]
            )
            stake = lambda a: a[:, :WS]
        else:
            feas = out["feasible"]
            sorder, svalid = partition_ids(feas, WS)
            stake = lambda a: jnp.take_along_axis(a, sorder, axis=1)
            if not cfg.filters:
                res["sids"] = jnp.where(svalid, sorder, -1).astype(jnp.int32)
        stakem = lambda a: jnp.where(svalid, stake(a), 0)
        for k, (s, _w) in enumerate(cfg.scores):
            fetch_raw, fetch_norm, _host = plan[k]
            if fetch_raw:
                res[f"raw:{k}"] = stakem(out[f"raw:{s}"]).astype(getattr(jnp, raw_dtypes[k]))
            if fetch_norm:
                res[f"norm:{k}"] = stakem(out[f"norm:{s}"]).astype(jnp.int8)
        parts = [
            lax.bitcast_convert_type(res[name], jnp.uint8).reshape(-1)
            for name, _dt, _shape in manifest
        ]
        return jnp.concatenate(parts)

    return jax.jit(run), manifest


def unpack_compact_blob(blob: np.ndarray, manifest: "list[tuple[str, str, tuple]]") -> dict:
    """Slice the single fetched uint8 blob back into named planes (host
    views, no copies beyond the one D2H transfer)."""
    out: dict = {}
    off = 0
    for name, dt, shape in manifest:
        n = int(np.prod(shape)) * np.dtype(dt).itemsize
        out[name] = blob[off : off + n].view(dt).reshape(shape)
        off += n
    if "fail8" in out:
        packed = out.pop("fail8")
        out["fail_plug"] = ((packed >> 4).astype(np.int16) - 1).astype(np.int8)
        out["fail_code"] = (packed & 0xF).astype(np.uint8)
    elif "fail" in out:
        packed = out.pop("fail")
        out["fail_plug"] = ((packed >> 8).astype(np.int16) - 1).astype(np.int8)
        out["fail_code"] = (packed & 0xFF).astype(np.uint8)
    return out


def _host_default_normalize(raw: np.ndarray, valid: np.ndarray, reverse: bool) -> np.ndarray:
    """helper.DefaultNormalizeScore recomputed on host over the compacted
    feasible window — integer arithmetic, equal to the kernel's float
    path for the int8/int16 raws the fetch plan routes here."""
    r = np.where(valid, raw, 0).astype(np.int64)
    mx = r.max(axis=1)
    q = (r * int(MAX_NODE_SCORE)) // np.maximum(mx, 1)[:, None]
    out = int(MAX_NODE_SCORE) - q if reverse else q
    out = np.where(mx[:, None] == 0, int(MAX_NODE_SCORE) if reverse else 0, out)
    return np.where(valid, out, 0).astype(np.int8)


def _host_minmax_normalize(raw: np.ndarray, valid: np.ndarray) -> np.ndarray:
    """InterPodAffinity's MAX*(v-min)/(max-min) on host (see above)."""
    r = raw.astype(np.int64)
    big = np.int64(1) << 40
    mn = np.where(valid, r, big).min(axis=1)
    mx = np.where(valid, r, -big).max(axis=1)
    diff = mx - mn
    q = ((r - mn[:, None]) * int(MAX_NODE_SCORE)) // np.maximum(diff, 1)[:, None]
    out = np.where(diff[:, None] > 0, q, 0)
    return np.where(valid, out, 0).astype(np.int8)


def reconstruct_trace(
    cfg: BatchConfig,
    fetched: "dict[str, np.ndarray]",
    sample_start: np.ndarray,
    sample_processed: np.ndarray,
    n_true: int,
    feasible_count: np.ndarray,
    raw_dtypes: "tuple[str, ...]",
    p_true: int,
    WS: int,
) -> dict:
    """Expand the minimal fetch back to the trace interface the annotation
    writer reads (sids [P,WS] int32, raw [S,P,WS] int32, norm [S,P,WS]
    int8, fail planes) — all host-side numpy, no further D2H.

    Rows ≥ ``p_true`` are shape padding (pod_active=False in the kernel):
    their planes are left empty — no consumer reads them."""
    P = len(sample_start)
    fp = fetched.get("fail_plug")
    out: dict = {}
    if fp is not None:
        out["fail_plug"] = fp
        out["fail_code"] = fetched["fail_code"]
        W = fp.shape[1]
        r = np.arange(W, dtype=np.int32)[None, :]
        proc = np.minimum(sample_processed.astype(np.int32), n_true)[:, None]
        ids = (sample_start.astype(np.int32)[:, None] + r) % max(n_true, 1)
        # ascending-id column order (invalid columns pushed past the end),
        # matching the compact planes' argsort
        ids = np.sort(np.where(r < proc, ids, n_true + r), axis=1)
        in_window = r < proc
        in_window[p_true:] = False
        feas = in_window & (fp < 0)
        pos = np.cumsum(feas, axis=1) - 1
        take = feas & (pos < WS)
        sids = np.full((P, WS), -1, dtype=np.int32)
        rows = np.broadcast_to(np.arange(P)[:, None], (P, W))
        sids[rows[take], pos[take]] = ids[take].astype(np.int32)
        counts = feas.sum(axis=1)
        if not np.array_equal(counts[:p_true], feasible_count[:p_true]):
            raise RuntimeError(
                "derived feasible ids disagree with the kernel's feasible counts"
            )
        out["sids"] = sids
        # keep the sorted visit-id matrix: per-pod annotation builders
        # read their visited windows from it (first `processed` columns
        # of a row) instead of re-deriving and re-sorting per pod
        out["visit_ids"] = ids.astype(np.int64, copy=False)
    else:
        out["sids"] = fetched["sids"]
    if cfg.scores:
        valid = out["sids"] >= 0
        S = len(cfg.scores)
        raw = np.zeros((S, P, WS), dtype=np.int32)
        norm = np.zeros((S, P, WS), dtype=np.int8)
        plan = trace_fetch_plan(cfg, raw_dtypes)
        for k in range(S):
            fetch_raw, fetch_norm, host = plan[k]
            if fetch_raw:
                raw[k] = fetched[f"raw:{k}"]
            if fetch_norm:
                norm[k] = fetched[f"norm:{k}"]
                if not fetch_raw:
                    raw[k] = norm[k]  # identity-normalized plugin
            elif host == "default":
                norm[k] = _host_default_normalize(raw[k], valid, reverse=False)
            elif host == "default_reverse":
                norm[k] = _host_default_normalize(raw[k], valid, reverse=True)
            elif host == "minmax":
                norm[k] = _host_minmax_normalize(raw[k], valid)
        out["raw"] = raw
        out["norm"] = norm
    return out


CARRY0_FIELDS = (
    "requested0", "nonzero0", "pod_count0", "ports_used0", "restr_used0",
    "cloud_used0", "csi_attached0", "spread_counts0",
    "ip_sel0", "ip_own0", "ip_anti0", "start0",
)

# DeviceProblem fields carrying the pod axis (axis 0 / axis 1): the
# windowed scan slices exactly these to its [offset, offset+Wp) view.
POD_WINDOW_AXIS0 = (
    "pod_req", "pod_nonzero", "fit_checked", "pod_tol_idx", "pod_aff_idx",
    "pod_pref_idx", "pod_img_idx", "name_target", "pod_ports", "pod_vol_idx",
    "pod_restr", "cloud_cnt", "pod_csi", "ip_aff_g", "ip_anti_g", "ip_pref_g",
    "ip_pref_w", "ip_own_g", "ip_own_w", "ip_self_match", "pod_active",
    "spf_ku", "sps_ku",
)
POD_WINDOW_AXIS1 = ("spread_match", "term_match")


def slice_pod_window(dp: DeviceProblem, offset, Wp: int) -> DeviceProblem:
    """The [offset, offset+Wp) pod-window view of a DeviceProblem (traced
    offset, static width) — everything the scan step reads per pod is
    sliced; node-axis state and class matrices pass through.  tb_base
    shifts by the offset so the counter-keyed tie-break draws stay those
    of the pod's GLOBAL queue position."""
    offset = jnp.asarray(offset, jnp.int32)
    repl: dict = {
        f: lax.dynamic_slice_in_dim(getattr(dp, f), offset, Wp, axis=0)
        for f in POD_WINDOW_AXIS0
    }
    repl.update(
        {
            f: lax.dynamic_slice_in_dim(getattr(dp, f), offset, Wp, axis=1)
            for f in POD_WINDOW_AXIS1
        }
    )
    repl["spf"] = tuple(lax.dynamic_slice_in_dim(a, offset, Wp, axis=0) for a in dp.spf)
    repl["sps"] = tuple(lax.dynamic_slice_in_dim(a, offset, Wp, axis=0) for a in dp.sps)
    repl["tb_base"] = dp.tb_base + offset.astype(jnp.uint32)
    return dp._replace(**repl)


def build_batch_fn(
    cfg: BatchConfig,
    dims: dict,
    donate: bool = False,
    ws0: "int | None" = None,
    window: "int | None" = None,
):
    """Build the jitted batch scheduling function for a static config/dims.

    Returns fn(dp: DeviceProblem) → dict of result arrays.  With
    ``donate``, the DeviceProblem's buffers are donated — the initial
    carry aliases into the scan carry instead of being copied; callers
    must not reuse ``dp`` after the call (BatchEngine builds a fresh one
    per round).

    ``window`` (static pod-window width Wp): returns
    fn(carry0, dp, offset) → ys instead, scanning ONLY pods
    [offset, offset+Wp) and returning the final carry under
    ``ys["_final_carry"]`` — the commit pipeline chains windows through
    it, keeping the carry on device, and dispatches window k+1 before
    window k's trace is fetched so device execution overlaps the host
    commit.  carry0 is donated (each window's carry aliases forward);
    ``dp`` must arrive with the CARRY0_FIELDS slimmed to scalars (the
    real initial carry travels as the first window's carry0).  Windowed
    scans are byte-equivalent to one full scan: the scan body is
    identical and the carry chains exactly.

    ``ws0`` (trace mode, sampling on): a STATIC upper bound on per-pod
    feasible nodes — bucket(sample_k).  When set, the per-step score
    planes are compacted in the step itself (cumsum + scatter over the
    feasible mask, ascending node id — the same order the post-pass
    compaction would produce) so the scan emits [P, ws0] score planes
    instead of [P, N]: at 10k x 5k with the default profile that is ~10x
    less trace memory materialized per round, which is the dominant
    in-context kernel cost on a host where those planes fault fresh
    pages every round.  Callers must key their fn cache on ws0 (it
    depends on sample_k, which is otherwise a traced scalar)."""
    P, N, D = dims["P"], dims["N"], dims["D"]
    Pw = window or P  # pods per scan (the full pod axis, or one window)
    KC, KS = dims["KC"], dims["KS"]
    KA, KB, KP, KO = dims["KA"], dims["KB"], dims["KP"], dims["KO"]
    G, SG = dims["G"], dims["SG"]
    # the Fit filter packs per-resource insufficiency into an int32 bitmask
    # (BatchEngine.supported() pre-rejects such workloads; this is the
    # backstop for direct kernel users)
    if dims["R"] > 30:
        raise ValueError(
            f"{dims['R']} distinct checked resources exceed the int32 reason bitmask (30)"
        )
    use_spread_f = "PodTopologySpread" in cfg.filters and KC > 0
    use_spread_s = any(k == "PodTopologySpread" for k, _ in cfg.scores) and KS > 0
    use_ip = G > 0 and (
        "InterPodAffinity" in cfg.filters or any(k == "InterPodAffinity" for k, _ in cfg.scores)
    )
    key_struct = dims["key_struct"]
    KU = len(key_struct)

    def expand_u(u: int, vec, dp):
        """Domain vector [D+1] → per-node values [N] for static key u."""
        kind, base, size = key_struct[u]
        if kind == "identity":
            return lax.dynamic_slice(vec, (base,), (N,)) * dp.key_valid[u]
        return _mv(vec[base : base + size], dp.key_oh[u])

    def expand_switch(u, vec, dp):
        """Same, for a TRACED key index (lax.switch over the static set)."""
        if KU == 1:
            return expand_u(0, vec, dp)
        return lax.switch(u, [lambda v, uu=uu: expand_u(uu, v, dp) for uu in range(KU)], vec)

    # the carry ALWAYS contains ports_used / restr_used / cloud_used /
    # csi_att (dummy [N,1]/[N,3] columns when the workload doesn't exercise
    # them) — only the per-plugin work is gated, matching the SG/G
    # convention, so the carry structure never branches
    use_ports = dims["PT"] > 0
    use_restr = dims["VR"] > 0 and "VolumeRestrictions" in cfg.filters
    use_cloud = dims["CLOUD"] > 0
    use_csi = dims["VID"] > 0 and "NodeVolumeLimits" in cfg.filters

    def step(dp: DeviceProblem, carry, xs):
        (
            requested, nonzero, pod_count, ports_used, restr_used, cloud_used,
            csi_att, spread_counts, ip_sel, ip_own, ip_anti, start,
        ) = carry
        i = xs
        dt = requested.dtype
        pod_req = dp.pod_req[i]
        # First-failure tracking IN the step (what the annotation trail
        # records — the cycle short-circuits at the first failing filter):
        # two [N] planes per pod instead of F per-filter planes, an
        # order-of-magnitude less HBM traffic and fetch volume in trace
        # mode.  fail_plug = index into cfg.filters, -1 = all passed.
        fail_plug = jnp.full(N, -1, dtype=jnp.int8)
        fail_code = jnp.zeros(N, dtype=jnp.int32)

        # ---------------------------------------------------------- filters
        feasible = dp.node_active  # padding columns are never feasible
        filter_pos = {f: k for k, f in enumerate(cfg.filters)}

        def apply(name, code):
            nonlocal feasible, fail_plug, fail_code
            if cfg.trace:
                hit = (fail_plug < 0) & (code != 0)
                fail_plug = jnp.where(hit, jnp.int8(filter_pos[name]), fail_plug)
                fail_code = jnp.where(hit, code, fail_code)
            feasible = feasible & (code == 0)

        for name in cfg.filters:
            if name == "NodeUnschedulable":
                apply(name, jnp.where(dp.unsched_ok[i], 0, 1))
            elif name == "NodeName":
                apply(name, jnp.where(dp.name_ok[i], 0, 1))
            elif name == "NodePorts" and use_ports:
                # ports_used is already in wanted-class conflict space
                # (encode seeds bound pods through the conflict relation;
                # commits below add C @ pod_ports)
                clash = jnp.sum(ports_used * dp.pod_ports[i][None, :].astype(dt), axis=1)
                apply(name, (clash > 0).astype(jnp.int32))
            elif name == "TaintToleration":
                tfail = dp.taint_fail[i].astype(jnp.int32)
                apply(name, jnp.where(tfail < 0, 0, tfail + 1))
            elif name == "NodeAffinity":
                apply(name, dp.aff_code[i].astype(jnp.int32))
            elif name == "NodeResourcesFit":
                free = dp.alloc - requested
                want = pod_req
                insuff = (want[None, :] > free) & dp.fit_checked[i][None, :]
                too_many = pod_count + 1.0 > dp.max_pods
                # bit 0: Too many pods; bit r+1: Insufficient resource r
                code = too_many.astype(jnp.int32)
                for r in range(dims["R"]):
                    code = code | (insuff[:, r].astype(jnp.int32) << (r + 1))
                apply(name, code)
            elif name == "VolumeBinding":
                apply(name, dp.vb_code[i].astype(jnp.int32))
            elif name == "VolumeZone":
                apply(name, dp.vz_code[i].astype(jnp.int32))
            elif name == "VolumeRestrictions" and use_restr:
                clash = jnp.sum(restr_used * dp.pod_restr[i][None, :].astype(dt), axis=1)
                apply(name, (clash > 0).astype(jnp.int32))
            elif name in CLOUD_LIMIT_COL and use_cloud:
                col, limit = CLOUD_LIMIT_COL[name]
                want = dp.cloud_cnt[i, col]
                over = (want > 0) & (cloud_used[:, col] + want > limit)
                apply(name, over.astype(jnp.int32))
            elif name == "NodeVolumeLimits" and use_csi:
                pod_v = dp.pod_csi[i].astype(dt)
                new = pod_v[None, :] * (1.0 - csi_att)            # [N,V]
                need_d = _mv(new, dp.csi_drv_oh)                  # [N,DR]
                used_d = dp.csi_seed_used + _mv(csi_att, dp.csi_drv_oh)
                over = (need_d > 0) & (used_d + need_d > dp.csi_limit)
                apply(name, jnp.any(over, axis=1).astype(jnp.int32))
            elif name == "PodTopologySpread" and use_spread_f:
                code = jnp.zeros(N, dtype=jnp.int32)
                incl_row = dp.incl[i]
                key_row, grp_row, skew_row, self_row = dp.spf
                for k in range(KC):
                    key = key_row[i, k]
                    active = key >= 0
                    dom = jnp.take(dp.node_domain, jnp.clip(key, 0), axis=0)  # [N]
                    m = jnp.take(spread_counts, grp_row[i, k], axis=0)  # [N]
                    contributing = incl_row & (dom >= 0)
                    mc = jnp.where(contributing, m, 0.0)

                    def spread_branch(u):
                        def br(operands):
                            mc_, contributing_ = operands
                            kind, base, size = key_struct[u]
                            if kind == "identity":
                                # each node is its own domain
                                present = contributing_
                                mn = jnp.min(jnp.where(present, mc_, jnp.inf))
                                match = mc_ * dp.key_valid[u]
                            else:
                                oh = dp.key_oh[u]
                                dc = _mv(oh, mc_)  # [size]
                                present = _mv(oh, contributing_.astype(dt)) > 0
                                mn = jnp.min(jnp.where(present, dc, jnp.inf))
                                match = _mv(dc, oh)
                            has_any = jnp.any(present)
                            return match, jnp.where(has_any, mn, 0.0)
                        return br

                    u = dp.spf_ku[i, k]
                    if KU == 1:
                        match_num, min_match = spread_branch(0)((mc, contributing))
                    else:
                        match_num, min_match = lax.switch(
                            u, [spread_branch(uu) for uu in range(KU)], (mc, contributing)
                        )
                    skew = match_num + self_row[i, k] - min_match
                    k_code = jnp.where(dom < 0, 1, jnp.where(skew > skew_row[i, k], 2, 0))
                    k_code = jnp.where(active, k_code, 0)
                    code = jnp.where(code == 0, k_code, code)
                apply(name, code)
            elif name == "InterPodAffinity" and use_ip:
                tm = dp.term_match[:, i]  # [G]
                # collapse over groups per used key, then expand to nodes
                # through the static one-hot (exact: one-hot entries are 0/1)
                poison = jnp.zeros(N, dtype=dt)
                for u in range(KU):
                    vec = _mv(tm * (dp.g_ku == u), ip_anti)  # [D+1]
                    poison = poison + expand_u(u, vec, dp)
                code = jnp.where(poison > 0, 1, 0).astype(jnp.int32)
                # own required affinity
                if KA > 0:
                    sat = jnp.ones(N, dtype=bool)
                    total_any = jnp.zeros((), dtype=dt)
                    for k in range(KA):
                        g = dp.ip_aff_g[i, k]
                        active = g >= 0
                        gs = jnp.clip(g, 0)
                        row = ip_sel[gs]  # [D+1]
                        dom = dp.gdom[gs]
                        cnt = expand_switch(dp.g_ku[gs], row, dp)  # [N]
                        sat = sat & (jnp.where(active, (cnt > 0) & (dom >= 0), True))
                        total_any = total_any + jnp.where(active, jnp.sum(row[:D]), 0.0)
                    has_aff = dp.ip_aff_g[i, 0] >= 0
                    escape = (total_any == 0) & dp.ip_self_match[i]
                    aff_fail = has_aff & ~sat & ~escape
                    code = jnp.where((code == 0) & aff_fail, 2, code)
                if KB > 0:
                    for k in range(KB):
                        g = dp.ip_anti_g[i, k]
                        active = g >= 0
                        gs = jnp.clip(g, 0)
                        cnt = expand_switch(dp.g_ku[gs], ip_sel[gs], dp)
                        fail = active & (cnt > 0)
                        code = jnp.where((code == 0) & fail, 3, code)
                apply(name, code)
            # else: kernel inactive for this problem (no constraints) —
            # it can never fail, so it contributes nothing to the planes

        # ------------------------------------------- feasible-node sampling
        # Upstream visits nodes from a rotating start index and stops after
        # sample_k feasible ones (framework_runner.schedule_one); here the
        # visit order is expressed as a per-node rank r = (n - start) mod
        # n_true, and "the first K feasible in visit order" falls out of a
        # windowed prefix sum — no gathers, everything elementwise.
        # cfg.sampling=False compiles the machinery out (valid when K
        # covers all nodes and start==0: every feasible node is sampled,
        # visit order == index order, the start index never moves).
        nt = dp.n_true
        K = dp.sample_k
        idx = jnp.arange(N, dtype=jnp.int32)
        if cfg.sampling:
            r = jnp.where(idx >= start, idx - start, idx - start + nt)  # visit rank

            def rot_cumsum(mask):
                """c[n] = number of True entries with visit rank <= r[n] (a
                cumsum in rotation order), plus the total count."""
                pref = jnp.cumsum(mask.astype(jnp.int32), dtype=jnp.int32)
                tot = pref[N - 1]
                ps = jnp.where(start == 0, 0, jnp.take(pref, jnp.maximum(start - 1, 0)))
                return jnp.where(idx >= start, pref - ps, pref + (tot - ps)), tot

            c, total = rot_cumsum(feasible)
            sampled = feasible & (c <= K)
            # nodes actually visited: up to and including the K-th feasible.
            # dtype pin: under x64 jnp.sum promotes int32 to int64, which
            # would widen the start-index carry and break the scan's
            # carry-type invariant (x64 CPU + real sampling only).
            processed = jnp.where(
                total >= K,
                jnp.sum(jnp.where(feasible & (c == K), r + 1, 0), dtype=jnp.int32),
                nt,
            )
            count = jnp.minimum(total, K) * dp.pod_active[i]
        else:
            r = idx

            def rot_cumsum(mask):
                pref = jnp.cumsum(mask.astype(jnp.int32), dtype=jnp.int32)
                return pref, pref[N - 1]

            sampled = feasible
            total = jnp.sum(feasible.astype(jnp.int32), dtype=jnp.int32)
            processed = nt
            count = total * dp.pod_active[i]

        # ----------------------------------------------------------- scores
        raws = {}
        norms = {}
        totals = jnp.zeros(N, dtype=dt)
        for k_s, (name, weight) in enumerate(cfg.scores):
            if name == "NodeResourcesFit":
                req_nz = nonzero + dp.pod_nonzero[i][None, :]  # [N,2]
                a = dp.nz_alloc
                if cfg.fit_strategy == "MostAllocated":
                    per_r = jnp.where((a > 0) & (req_nz <= a), _floordiv(req_nz * MAX_NODE_SCORE, a), 0.0)
                elif cfg.fit_strategy == "RequestedToCapacityRatio":
                    # piecewise-linear shape over the utilization ratio;
                    # zero/over capacity evaluates the shape at 100, not 0
                    util = jnp.where(
                        (a > 0) & (req_nz <= a), _floordiv(req_nz * MAX_NODE_SCORE, a), 100.0
                    )
                    per_r = _broken_linear(util, cfg.fit_shape)
                else:  # LeastAllocated
                    per_r = jnp.where((a > 0) & (req_nz <= a), _floordiv((a - req_nz) * MAX_NODE_SCORE, a), 0.0)
                wsum = float(sum(w for _, w in cfg.fit_resources)) or 1.0
                raw = _floordiv(
                    sum(per_r[:, c] * float(w) for c, w in cfg.fit_resources), wsum
                )
                norm = raw  # no ScoreExtensions
            elif name == "NodeResourcesBalancedAllocation":
                req_nz = nonzero + dp.pod_nonzero[i][None, :]
                a = dp.nz_alloc
                frac = jnp.where(a > 0, jnp.minimum(req_nz / jnp.where(a == 0, 1.0, a), 1.0), 1.0)
                std = jnp.abs(frac[:, 0] - frac[:, 1]) / 2.0
                raw = jnp.floor((1.0 - std) * MAX_NODE_SCORE)
                norm = raw
            elif name == "ImageLocality":
                # the complete upstream score was precomputed per
                # (pod-image-class, node-image-class) at encode time —
                # it's pure per-pair, no ScoreExtensions
                raw = dp.img_score[i]
                norm = raw
            elif name == "TaintToleration":
                raw = dp.taint_prefer[i]
                norm = _default_normalize(raw, sampled, reverse=True)
            elif name == "NodeAffinity":
                raw = dp.aff_pref[i]
                norm = _default_normalize(raw, sampled, reverse=False)
            elif name == "PodTopologySpread" and use_spread_s:
                key_row, grp_row, skew_row, self_row = dp.sps
                has_constraints = key_row[i, 0] >= 0
                # require-all mask: all active constraint keys present
                has_all = jnp.ones(N, dtype=bool)
                for k in range(KS):
                    key = key_row[i, k]
                    dom = jnp.take(dp.node_domain, jnp.clip(key, 0), axis=0)
                    has_all = has_all & jnp.where(key >= 0, dom >= 0, True)
                raw_f = jnp.zeros(N, dtype=dt)
                for k in range(KS):
                    key = key_row[i, k]
                    active = key >= 0
                    dom = jnp.take(dp.node_domain, jnp.clip(key, 0), axis=0)
                    m = jnp.take(spread_counts, grp_row[i, k], axis=0)
                    contributing = has_all & (dom >= 0)
                    mc = jnp.where(contributing, m, 0.0)
                    fni = sampled & has_all & (dom >= 0)

                    def score_branch(u):
                        def br(operands):
                            mc_, fni_ = operands
                            kind, base, size = key_struct[u]
                            if kind == "identity":
                                cnt_ = mc_ * dp.key_valid[u]
                                tsize_ = jnp.sum(fni_.astype(dt))
                            else:
                                oh = dp.key_oh[u]
                                dc = _mv(oh, mc_)
                                cnt_ = _mv(dc, oh)
                                tsize_ = jnp.sum((_mv(oh, fni_.astype(dt)) > 0).astype(dt))
                            return cnt_, tsize_
                        return br

                    u = dp.sps_ku[i, k]
                    if KU == 1:
                        cnt, tsize = score_branch(0)((mc, fni))
                    else:
                        cnt, tsize = lax.switch(u, [score_branch(uu) for uu in range(KU)], (mc, fni))
                    w = jnp.log(tsize + 2.0)
                    raw_f = raw_f + jnp.where(active, cnt * w + (skew_row[i, k] - 1.0), 0.0)
                raw = jnp.round(raw_f)
                ignored = ~has_all
                considered = sampled & ~ignored
                mn = jnp.min(jnp.where(considered, raw, jnp.inf))
                mx = jnp.max(jnp.where(considered, raw, -jnp.inf))
                any_considered = jnp.any(considered)
                norm = jnp.where(
                    mx == 0,
                    MAX_NODE_SCORE,
                    _floordiv(MAX_NODE_SCORE * (mx + mn - raw), mx),
                )
                norm = jnp.where(ignored | ~any_considered, 0.0, norm)
                norm = jnp.where(has_constraints, norm, 0.0)
                raw = jnp.where(has_constraints, raw, 0.0)
            elif name == "InterPodAffinity" and use_ip:
                tm = dp.term_match[:, i]
                raw = jnp.zeros(N, dtype=dt)
                for u in range(KU):
                    vec = _mv(tm * (dp.g_ku == u), ip_own)  # [D+1]
                    raw = raw + expand_u(u, vec, dp)
                for k in range(KP):
                    g = dp.ip_pref_g[i, k]
                    active = g >= 0
                    gs = jnp.clip(g, 0)
                    w = dp.ip_pref_w[i, k]
                    cnt = expand_switch(dp.g_ku[gs], ip_sel[gs], dp)
                    raw = raw + jnp.where(active, w * cnt, 0.0)
                norm = _minmax_normalize(raw, sampled)
            else:
                raw = jnp.zeros(N, dtype=dt)
                norm = raw
            if cfg.trace:
                raws[name] = raw
                norms[name] = norm
            if cfg.traced_weights:
                totals = totals + norm * dp.plugin_w[k_s]
            else:
                totals = totals + norm * float(weight)

        # Single-feasible-node bypass: scores are skipped (annotations omit
        # them); selection is the lone feasible node either way.  Ties are
        # ordered by VISIT rank (the sequential cycle iterates feasible
        # nodes in rotation order), not node index.
        masked = jnp.where(sampled, totals, NEG)
        mx = jnp.max(masked)
        tied = sampled & (masked == mx)
        if cfg.tie_break == "reservoir":
            # k-th tied max in visit order, k from the counter-keyed draw —
            # the same pick the sequential _select_host makes for attempt
            # tb_base + i (utils/hashing.py).
            ct, t_count = rot_cumsum(tied)
            counter = dp.tb_base + i.astype(jnp.uint32)
            seed_mix = _mix32(jnp.uint32((cfg.seed ^ 0x9E3779B9) & 0xFFFFFFFF))
            draw = _mix32(seed_mix ^ _mix32(counter))
            k = (draw % jnp.maximum(t_count, 1).astype(jnp.uint32)).astype(jnp.int32)
            sel = jnp.argmax(tied & (ct == k + 1)).astype(jnp.int32)
        else:
            # first tied max in visit order = minimal visit rank
            sel = jnp.argmin(jnp.where(tied, r, jnp.int32(2) * nt + N)).astype(jnp.int32)
        sel = jnp.where(count > 0, sel, -1)

        # ----------------------------------------------------------- commit
        commit = count > 0
        onehot = (jnp.arange(N, dtype=jnp.int32) == sel) & commit  # [N]
        oh = onehot.astype(dt)
        if cfg.relax_tau > 0:
            # straight-through relaxed head: forward value IS the hard
            # one-hot (byte parity with relax off), backward routes
            # through softmax(totals/τ) over the sampled nodes so
            # d(committed planes)/d(plugin_w) is nonzero — the gradient
            # tuner's whole-rollout surrogate (tuning/relax.py)
            soft = jax.nn.softmax(
                jnp.where(sampled, totals / float(cfg.relax_tau), NEG)
            ) * commit.astype(dt)
            oh = soft + lax.stop_gradient(oh - soft)
        requested = requested + oh[:, None] * pod_req[None, :]
        nonzero = nonzero + oh[:, None] * dp.pod_nonzero[i][None, :]
        pod_count = pod_count + oh
        if use_ports:
            # project the committed pod's triples onto every wanted class
            # they conflict with (its own classes included — C is reflexive
            # on identical triples)
            proj = _mv(dp.port_conflict, dp.pod_ports[i].astype(dt))  # [PT]
            ports_used = ports_used + oh[:, None] * proj[None, :]
        if use_restr:
            rproj = _mv(dp.restr_conflict, dp.pod_restr[i].astype(dt))  # [VR]
            restr_used = restr_used + oh[:, None] * rproj[None, :]
        if use_cloud:
            cloud_used = cloud_used + oh[:, None] * dp.cloud_cnt[i][None, :]
        if use_csi:
            # attachment bits OR in the committed pod's volume ids (shared
            # PVC-backed ids stay one attachment — max, not add)
            csi_att = jnp.maximum(csi_att, oh[:, None] * dp.pod_csi[i][None, :].astype(dt))
        if SG > 0:
            spread_counts = spread_counts + dp.spread_match[:, i][:, None] * oh[None, :]
        if use_ip:
            sel_safe = jnp.clip(sel, 0)
            d_g = dp.gdom[:, sel_safe]  # [G]
            d_g = jnp.where((d_g >= 0) & commit, d_g, D)
            ip_sel = ip_sel.at[jnp.arange(ip_sel.shape[0], dtype=jnp.int32), d_g].add(dp.term_match[:, i] * commit)
            for k in range(KO):
                g = dp.ip_own_g[i, k]
                active = (g >= 0) & commit
                gs = jnp.clip(g, 0)
                dd = dp.gdom[gs, sel_safe]
                dd = jnp.where((dd >= 0) & active, dd, D)
                ip_own = ip_own.at[gs, dd].add(dp.ip_own_w[i, k] * active)
            for k in range(KB):
                g = dp.ip_anti_g[i, k]
                active = (g >= 0) & commit
                gs = jnp.clip(g, 0)
                dd = dp.gdom[gs, sel_safe]
                dd = jnp.where((dd >= 0) & active, dd, D)
                ip_anti = ip_anti.at[gs, dd].add(jnp.where(active, 1.0, 0.0))

        # the rotating start advances by the number of visited nodes
        # (upstream: next_start_node_index = (start + processed) % n)
        next_start = jnp.where(nt > 0, (start + processed) % jnp.maximum(nt, 1), 0)
        next_start = jnp.where(dp.pod_active[i], next_start, start)
        carry = (
            requested, nonzero, pod_count, ports_used, restr_used, cloud_used,
            csi_att, spread_counts, ip_sel, ip_own, ip_anti, next_start,
        )
        out = {
            "selected": sel,
            "feasible_count": count,
            "sample_start": start,
            "sample_processed": processed,
        }
        if cfg.trace:
            out["fail_plug"] = fail_plug
            out["fail_code"] = fail_code
            if ws0 is not None and ws0 < N and cfg.filters:
                # in-step score compaction: scatter the ≤ sample_k ≤ ws0
                # feasible nodes' values to [ws0], ascending node id —
                # byte-identical to the post-pass take_along_axis(sorder)
                # (same order, same values), emitted at a tenth the size
                pos_id = jnp.cumsum(sampled.astype(jnp.int32), dtype=jnp.int32) - 1
                dest = jnp.where(sampled & (pos_id < ws0), pos_id, ws0)

                def compact1(v):
                    return jnp.zeros(ws0, v.dtype).at[dest].set(v, mode="drop")

                for n_ in raws:
                    out[f"raw:{n_}"] = compact1(raws[n_])
                    out[f"norm:{n_}"] = compact1(norms[n_])
            else:
                out["feasible"] = sampled
                for n_ in raws:
                    out[f"raw:{n_}"] = raws[n_]
                    out[f"norm:{n_}"] = norms[n_]
        return carry, out

    def _expand_features(dp: DeviceProblem, dt) -> DeviceProblem:
        """Expand the factored (pod-class × node-class) feature matrices to
        the dense [P,N] views the step math reads.  Runs on-device inside
        the jitted computation — the host never builds or ships them."""
        pair = lambda cls, pi, ni: jnp.take(jnp.take(cls, pi, axis=0), ni, axis=1)
        tu = pair(dp.taint_unsched_cls, dp.pod_tol_idx, dp.node_taint_idx)
        idx_n = jnp.arange(N, dtype=jnp.int32)
        tgt = dp.name_target[:, None]
        return dp._replace(
            taint_fail=pair(dp.taint_cls, dp.pod_tol_idx, dp.node_taint_idx),
            taint_prefer=pair(dp.taint_prefer_cls, dp.pod_tol_idx, dp.node_taint_idx).astype(dt),
            unsched_ok=(~dp.node_unsched)[None, :] | tu,
            aff_code=pair(dp.aff_code_cls, dp.pod_aff_idx, dp.node_label_idx),
            aff_pref=pair(dp.aff_pref_cls, dp.pod_pref_idx, dp.node_label_idx).astype(dt),
            name_ok=jnp.where(tgt == -1, True, tgt == idx_n[None, :]),
            incl=pair(dp.incl_cls, dp.pod_aff_idx, dp.node_label_idx),
            img_score=pair(dp.img_cls, dp.pod_img_idx, dp.node_img_idx).astype(dt),
            vb_code=pair(dp.vb_cls, dp.pod_vol_idx, dp.node_label_idx),
            vz_code=pair(dp.vz_cls, dp.pod_vol_idx, dp.node_label_idx),
        )

    def _scan(carry0, dp: DeviceProblem, offset=None):
        if window is not None:
            dp = slice_pod_window(dp, offset, window)
        dp = _expand_features(dp, carry0[0].dtype)
        carry, ys = lax.scan(functools.partial(step, dp), carry0, jnp.arange(Pw, dtype=jnp.int32))
        ys["final_requested"] = carry[0]
        ys["final_nonzero"] = carry[1]  # [N,2] committed cpu/mem (objectives)
        ys["final_pod_count"] = carry[2]
        ys["final_start"] = carry[-1]
        # One fetchable [5,P] view of the per-pod scalar outputs: each
        # device→host fetch pays a full host↔device roundtrip (tens of ms
        # through a tunneled TPU), so non-trace callers read this single
        # array instead of five.
        ys["packed_pod"] = jnp.stack(
            [
                ys["selected"].astype(jnp.int32),
                ys["feasible_count"].astype(jnp.int32),
                ys["sample_start"].astype(jnp.int32),
                ys["sample_processed"].astype(jnp.int32),
                jnp.broadcast_to(ys["final_start"], (Pw,)).astype(jnp.int32),
            ]
        )
        if cfg.trace:
            # [S+1,2] trace meta, one tiny fetch: per-score-plugin
            # feasible-window raw extrema (drives raw_dtype_for) plus the
            # global max filter-failure code (drives fail-plane packing)
            feas = ys.get("feasible")
            if feas is None:
                # in-step-compacted planes: validity is positional
                # (column < that pod's feasible count); masked-out
                # positions contribute 0 to the extrema exactly as the
                # non-feasible nodes did in the full-width planes
                feas = (
                    jnp.arange(ws0, dtype=jnp.int32)[None, :]
                    < ys["feasible_count"].astype(jnp.int32)[:, None]
                )
            else:
                # padding pod rows (pod_active=False) still carry sampled
                # nodes in the full-width planes; the in-step path zeroes
                # them via feasible_count — mask here too so both paths
                # select identical fetch dtypes for identical rounds
                feas = feas & dp.pod_active[:, None]
            rows = [
                jnp.stack(
                    [
                        jnp.min(jnp.where(feas, ys[f"raw:{s}"], 0)).astype(jnp.int32),
                        jnp.max(jnp.where(feas, ys[f"raw:{s}"], 0)).astype(jnp.int32),
                    ]
                )
                for s, _w in cfg.scores
            ]
            code_max = (
                jnp.max(ys["fail_code"]).astype(jnp.int32)
                if cfg.filters
                else jnp.int32(0)
            )
            rows.append(jnp.stack([jnp.int32(0), code_max]))
            ys["trace_meta"] = jnp.stack(rows)
        return carry, ys

    if window is not None:

        def run_windowed(carry0, dp: DeviceProblem, offset):
            carry, ys = _scan(carry0, dp, offset)
            ys["_final_carry"] = carry
            return ys

        return jax.jit(run_windowed, donate_argnums=(0,))

    def run(dp: DeviceProblem):
        carry0 = tuple(getattr(dp, f) for f in CARRY0_FIELDS)
        _carry, ys = _scan(carry0, dp)
        return ys

    # the returned callable exposes its exportable jit target + calling
    # convention so the AOT artifact cache (ops/aot.py) can serialize the
    # lowered module and a warm engine can rebuild the same fn(dp) shape
    # around a deserialized one
    if not donate:
        jitted = jax.jit(run)
        jitted.jit_target = jitted
        jitted.split_carry = False
        return jitted

    # Donate ONLY the initial carry (as its own jit argument) and return
    # the final carry so every donated buffer has an output to alias into
    # — donating the whole DeviceProblem would warn about the feature
    # matrices, which are pure inputs with nothing to alias.
    def run_donate(carry0, dp: DeviceProblem):
        carry, ys = _scan(carry0, dp)
        ys["_final_carry"] = carry
        return ys

    jitted = jax.jit(run_donate, donate_argnums=(0,))

    def fn(dp: DeviceProblem):
        carry0 = tuple(getattr(dp, f) for f in CARRY0_FIELDS)
        # the donated buffers must not also arrive through dp
        slim = dp._replace(**{f: jnp.int32(0) for f in CARRY0_FIELDS})
        return jitted(carry0, slim)

    fn.jit_target = jitted
    fn.split_carry = True
    return fn
