"""KSS-HOST-SYNC bad fixture 2: sync in scan/vmap bodies + helpers."""

import jax
import jax.numpy as jnp
from jax import lax


def scan_step(carry, x):
    total = carry + x
    while total > 0:  # expect-finding
        total = total - 1.0
    flag = total.item()  # expect-finding
    return total, flag


def helper(feasible):
    # reachable through the vmapped lane below: tainted via jnp result
    count = jnp.sum(feasible, dtype=jnp.int32)
    n = int(count)  # expect-finding
    return n


def lane(row):
    return helper(row > 0)


def run(rows, c0, xs):
    out = jax.vmap(lane)(rows)
    carry, ys = lax.scan(scan_step, c0, xs)
    return out, carry, ys
