"""KubeSchedulerConfiguration handling: defaults, wrapping, plugin-set merge.

Python rebuild of the reference's config-transformation layer:

- ``default_scheduler_config`` — the v1.26 default single-profile config
  (reference simulator/scheduler/config/config.go:9-15 via upstream scheme
  defaulting; plugin order pinned by reference
  simulator/scheduler/config/plugin_test.go:150-167).
- ``merge_plugin_set`` — upstream default_plugins.go merge logic the
  reference clones (reference simulator/scheduler/plugin/plugins.go:229-284).
- ``convert_for_simulator`` — rewrites every PluginSet to wrapped names and
  disables the default MultiPoint with "*"
  (reference simulator/scheduler/plugin/plugins.go:173-225).
- ``get_score_plugin_weight`` — zero weight → 1
  (reference plugins.go:288-303).
- ``effective_plugins`` — expands MultiPoint + per-point overrides into
  ordered per-extension-point plugin name lists (upstream framework
  runtime expansion).

Configs are plain dicts in the kubescheduler.config.k8s.io/v1 wire shape.
"""

from __future__ import annotations

import copy
from typing import Any

from kube_scheduler_simulator_tpu.models.wrapped import PLUGIN_SUFFIX, plugin_name
from kube_scheduler_simulator_tpu.plugins.intree import (
    DEFAULT_PLUGIN_ORDER,
    DEFAULT_SCORE_WEIGHTS,
)

Obj = dict[str, Any]

EXTENSION_POINT_KEYS = (
    "queueSort",
    "preFilter",
    "filter",
    "postFilter",
    "preScore",
    "score",
    "reserve",
    "permit",
    "preBind",
    "bind",
    "postBind",
)

# Which framework method marks membership of each config extension point.
POINT_METHODS = {
    "queueSort": "less",
    "preFilter": "pre_filter",
    "filter": "filter",
    "postFilter": "post_filter",
    "preScore": "pre_score",
    "score": "score",
    "reserve": "reserve",
    "permit": "permit",
    "preBind": "pre_bind",
    "bind": "bind",
    "postBind": "post_bind",
}


def default_multipoint_enabled() -> list[Obj]:
    out: list[Obj] = []
    for name in DEFAULT_PLUGIN_ORDER:
        entry: Obj = {"name": name}
        if name in DEFAULT_SCORE_WEIGHTS:
            entry["weight"] = DEFAULT_SCORE_WEIGHTS[name]
        out.append(entry)
    return out


def default_scheduler_config() -> Obj:
    """The defaulted KubeSchedulerConfiguration (single default profile)."""
    return {
        "apiVersion": "kubescheduler.config.k8s.io/v1",
        "kind": "KubeSchedulerConfiguration",
        "parallelism": 16,
        "percentageOfNodesToScore": 0,
        "profiles": [
            {
                "schedulerName": "default-scheduler",
                "plugins": {"multiPoint": {"enabled": default_multipoint_enabled()}},
                "pluginConfig": default_plugin_config(),
            }
        ],
        "extenders": [],
    }


def default_plugin_config() -> list[Obj]:
    """Default per-plugin args (the subset our plugins consume)."""
    return [
        {
            "name": "DefaultPreemption",
            "args": {"minCandidateNodesPercentage": 10, "minCandidateNodesAbsolute": 100},
        },
        {
            "name": "InterPodAffinity",
            "args": {"hardPodAffinityWeight": 1},
        },
        {
            "name": "NodeAffinity",
            "args": {},
        },
        {
            "name": "NodeResourcesBalancedAllocation",
            "args": {"resources": [{"name": "cpu", "weight": 1}, {"name": "memory", "weight": 1}]},
        },
        {
            "name": "NodeResourcesFit",
            "args": {
                "scoringStrategy": {
                    "type": "LeastAllocated",
                    "resources": [{"name": "cpu", "weight": 1}, {"name": "memory", "weight": 1}],
                }
            },
        },
        {
            "name": "PodTopologySpread",
            "args": {"defaultingType": "System"},
        },
        {
            "name": "VolumeBinding",
            "args": {"bindTimeoutSeconds": 600},
        },
    ]


# --------------------------------------------------------------------- merge


def merge_plugin_set(default_set: Obj, custom_set: Obj) -> Obj:
    """Clone of the upstream mergePluginSet logic (reference
    plugins.go:229-284): custom Disabled (incl. "*") suppresses defaults;
    custom Enabled replaces same-name defaults in place, the rest append."""
    disabled: list[Obj] = []
    disabled_names: set[str] = set()
    for p in custom_set.get("disabled") or []:
        disabled.append({"name": p["name"]})
        disabled_names.add(p["name"])
    for p in default_set.get("disabled") or []:
        disabled.append({"name": p["name"]})
        disabled_names.add(p["name"])

    enabled_custom = {p["name"]: (i, p) for i, p in enumerate(custom_set.get("enabled") or [])}
    replaced: set[int] = set()
    enabled: list[Obj] = []
    if "*" not in disabled_names:
        for p in default_set.get("enabled") or []:
            if p["name"] in disabled_names:
                continue
            if p["name"] in enabled_custom:
                idx, custom = enabled_custom[p["name"]]
                replaced.add(idx)
                p = custom
            enabled.append(copy.deepcopy(p))
    for i, p in enumerate(custom_set.get("enabled") or []):
        if i not in replaced:
            enabled.append(copy.deepcopy(p))
    return {"enabled": enabled, "disabled": disabled}


def convert_for_simulator(plugins: Obj) -> Obj:
    """ConvertForSimulator analog (reference plugins.go:173-205): every
    PluginSet rewritten to wrapped names; the MultiPoint set is merged with
    the in-tree defaults, then the whole default MultiPoint is disabled
    with "*" so only the wrapped plugins run."""
    out: Obj = {}
    for key in EXTENSION_POINT_KEYS:
        out[key] = _apply_plugin_set(plugins.get(key) or {}, {})
    merged = _apply_plugin_set(
        plugins.get("multiPoint") or {}, {"enabled": default_multipoint_enabled()}
    )
    merged["disabled"] = [{"name": "*"}]
    out["multiPoint"] = merged
    return out


def _apply_plugin_set(pls_set: Obj, in_tree: Obj) -> Obj:
    merged = merge_plugin_set(in_tree, pls_set)
    enabled = []
    for p in merged["enabled"]:
        q = {"name": plugin_name(p["name"])}
        if "weight" in p:
            q["weight"] = p["weight"]
        enabled.append(q)
    disabled = []
    for p in merged["disabled"]:
        name = p["name"] if p["name"] == "*" else plugin_name(p["name"])
        disabled.append({"name": name})
    return {"enabled": enabled, "disabled": disabled}


def get_score_plugin_weight(cfg: Obj) -> dict[str, int]:
    """Weights of enabled score plugins; zero weight → 1 (reference
    plugins.go:288-303).  Keys are unwrapped plugin names."""
    weights: dict[str, int] = {}
    profile = (cfg.get("profiles") or [{}])[0]
    plugins = profile.get("plugins") or {}
    enabled = list((plugins.get("score") or {}).get("enabled") or [])
    enabled += list((plugins.get("multiPoint") or {}).get("enabled") or [])
    for p in enabled:
        name = p["name"]
        if name.endswith(PLUGIN_SUFFIX):
            name = name[: -len(PLUGIN_SUFFIX)]
        weights[name] = int(p.get("weight") or 0) or 1
    return weights


# ----------------------------------------------------------------- expansion


def effective_plugins(profile: Obj, capabilities: dict[str, set[str]]) -> dict[str, list[Obj]]:
    """Expand a profile's plugin config into ordered per-point lists.

    ``capabilities``: plugin name → set of config point keys it implements.
    MultiPoint plugins join every point they implement (upstream MultiPoint
    expansion); point-specific Enabled/Disabled then override.
    """
    plugins = profile.get("plugins") or {}
    # merge_plugin_set already applies Disabled (incl. "*") to the DEFAULT
    # set only — custom Enabled entries always survive, per upstream
    # mergePluginSet semantics (reference plugins.go:229-284).
    multi = merge_plugin_set({"enabled": default_multipoint_enabled()}, plugins.get("multiPoint") or {})
    out: dict[str, list[Obj]] = {}
    for point in EXTENSION_POINT_KEYS:
        base = [p for p in multi["enabled"] if point in capabilities.get(p["name"], set())]
        point_set = plugins.get(point) or {}
        out[point] = merge_plugin_set({"enabled": base}, point_set)["enabled"]
    return out


def plugin_args_by_name(profile: Obj) -> dict[str, Obj]:
    """pluginConfig merged over the defaults (reference NewPluginConfig,
    plugins.go:95-170 — user args override default args per plugin)."""
    args = {pc["name"]: copy.deepcopy(pc.get("args") or {}) for pc in default_plugin_config()}
    for pc in profile.get("pluginConfig") or []:
        name = pc["name"]
        if name.endswith(PLUGIN_SUFFIX):
            name = name[: -len(PLUGIN_SUFFIX)]
        user = copy.deepcopy(pc.get("args") or {})
        if name in args:
            merged = args[name]
            merged.update(user)
            args[name] = merged
        else:
            args[name] = user
    return args
