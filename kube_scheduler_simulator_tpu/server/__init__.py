"""HTTP API layer (reference simulator/server + handler + di)."""

from kube_scheduler_simulator_tpu.server.di import DIContainer
from kube_scheduler_simulator_tpu.server.server import SimulatorServer

__all__ = ["DIContainer", "SimulatorServer"]
