#!/usr/bin/env python
"""The kernel-contract checker CLI: AST rules + baseline, CI-enforced.

Modes:

- default            — run every rule over the live tree (package +
                       scripts + bench.py) with analysis/baseline.toml
                       applied; exit 0 iff no unbaselined findings and
                       no unparseable files.
- --selftest         — run the rules over analysis/fixtures/ and check
                       the fixture matrix: every ``# expect-finding``
                       line in a ``*_bad_*`` fixture must be flagged by
                       exactly its rule, good fixtures must be clean,
                       and every rule must fire at least twice.  Exit 0
                       iff the matrix holds — this is the checker
                       checking itself, run by tier-1 BEFORE the live
                       tree so a broken rule can't silently pass it.
- --json             — machine-readable report (findings, suppressed
                       with justifications, unused suppressions) for
                       dashboarding.
- --rules A,B        — restrict to a comma-separated rule subset.
- --no-baseline      — show everything the rules see (triage mode).

Exit codes: 0 clean, 1 findings/matrix failures, 2 internal error
(malformed baseline, unparseable checker input).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def _selftest(as_json: bool) -> int:
    import json
    import re

    from kube_scheduler_simulator_tpu.analysis import run_analysis
    from kube_scheduler_simulator_tpu.analysis.framework import PACKAGE, repo_root

    report = run_analysis(fixtures=True, baseline_path=None)
    found: dict[tuple[str, int], str] = {}
    for f in report["findings"]:
        found.setdefault((f.file, f.line), f.rule)

    fdir = os.path.join(repo_root(), PACKAGE, "analysis", "fixtures")
    failures: list[str] = []
    fired: dict[str, int] = {}
    expect_re = re.compile(r"#\s*expect-finding\b")
    for fn in sorted(os.listdir(fdir)):
        if not fn.endswith(".py"):
            continue
        rel = f"{PACKAGE}/analysis/fixtures/{fn}"
        with open(os.path.join(fdir, fn), "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
        expected = {i for i, ln in enumerate(lines, 1) if expect_re.search(ln)}
        got = {line for (file, line) in found if file == rel}
        if "_bad_" in fn:
            if not expected:
                failures.append(f"{fn}: bad fixture carries no # expect-finding markers")
            missing = expected - got
            extra = got - expected
            if missing:
                failures.append(f"{fn}: lines {sorted(missing)} expected a finding, got none")
            if extra:
                failures.append(f"{fn}: unexpected findings on lines {sorted(extra)}")
            for line in expected & got:
                fired[found[(rel, line)]] = fired.get(found[(rel, line)], 0) + 1
        else:  # good fixtures must be silent
            if got:
                failures.append(f"{fn}: good fixture flagged on lines {sorted(got)}")
    for rule in ("KSS-DTYPE", "KSS-HOST-SYNC", "KSS-DONATE", "KSS-ENV", "KSS-LOCK"):
        if fired.get(rule, 0) < 2:
            failures.append(
                f"{rule}: fixture matrix demonstrates only {fired.get(rule, 0)} "
                "finding(s); the contract needs >=2 bad cases"
            )
    if as_json:
        print(json.dumps({"ok": not failures, "failures": failures, "fired": fired}, indent=2))
    elif failures:
        for msg in failures:
            print(f"selftest FAIL: {msg}", file=sys.stderr)
    else:
        print(
            "contract selftest OK: "
            + ", ".join(f"{r}={n}" for r, n in sorted(fired.items()))
        )
    return 1 if failures else 0


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true", help="machine-readable report")
    ap.add_argument("--selftest", action="store_true", help="run the fixture matrix")
    ap.add_argument("--no-baseline", action="store_true", help="ignore the baseline")
    ap.add_argument("--rules", default=None, help="comma-separated rule subset")
    args = ap.parse_args(argv)

    if args.selftest:
        return _selftest(args.json)

    from kube_scheduler_simulator_tpu.analysis import (
        BaselineError,
        default_rules,
        render_report,
        run_analysis,
    )

    rules = default_rules()
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - {r.name for r in rules}
        if unknown:
            print(f"unknown rule(s): {sorted(unknown)}", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in wanted]
    try:
        report = run_analysis(
            rules=rules, baseline_path=None if args.no_baseline else ""
        )
    except BaselineError as e:
        print(f"baseline error: {e}", file=sys.stderr)
        return 2
    print(render_report(report, as_json=args.json))
    return 1 if (report["findings"] or report["errors"]) else 0


if __name__ == "__main__":
    sys.exit(main())
