"""Embed the debuggable scheduler in your own scheduler program.

Rebuild of the reference's library surface (reference
simulator/pkg/debuggablescheduler/command.go:11-46 and
debuggable_scheduler.go:43-118): turn ANY scheduler setup into a
"debuggable" one whose every plugin is wrapped to record per-plugin
results as pod annotations, with user-supplied out-of-tree plugins and
per-plugin Before/After extenders.

Example (mirrors reference docs/sample/debuggable-scheduler/main.go):

    from kube_scheduler_simulator_tpu.pkg import debuggablescheduler

    scheduler, store = debuggablescheduler.new_scheduler(
        cluster_store,
        plugins={"NodeNumber": node_number_factory},          # WithPlugin
        plugin_extenders={"NodeResourcesFit": my_extender},   # WithPluginExtenders
        config=my_kube_scheduler_configuration,
    )
    scheduler.start_background()          # the upstream `command.Execute()`

The reference achieves config injection by overriding the scheme's
defaulting func ("black magic", debuggable_scheduler.go:108-116); here
construction is explicit, so no magic is needed — the converted profiles
are applied directly.
"""

from __future__ import annotations

from typing import Any, Callable

from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService

Obj = dict[str, Any]
PluginFactory = Callable[["Obj | None", Any], Any]
PluginExtenderInitializer = Callable[[Any], Any]


def new_scheduler(
    cluster_store: Any,
    plugins: "dict[str, PluginFactory] | None" = None,
    plugin_extenders: "dict[str, PluginExtenderInitializer] | None" = None,
    config: "Obj | None" = None,
    use_batch: str = "off",
    commit_wave: int = 256,
    pipeline: "bool | str" = "auto",
) -> "tuple[SchedulerService, Any]":
    """NewSchedulerCommand analog: returns (scheduler service, result store).

    ``plugins``: out-of-tree plugin name → factory(args, handle) — the
    WithPlugin option (command.go:35-39).
    ``plugin_extenders``: plugin name → initializer(result_store) returning
    an object with before_/after_ hook methods — the WithPluginExtenders
    option (command.go:41-46).
    ``commit_wave`` / ``pipeline``: the batch path's bulk-commit wave size
    and double-buffered round setting (SchedulerService docstring) — embed
    hosts running big batch rounds tune these alongside ``use_batch``.
    """
    svc = SchedulerService(
        cluster_store, use_batch=use_batch, commit_wave=commit_wave, pipeline=pipeline
    )
    if plugins:
        svc.set_out_of_tree_registries(dict(plugins))
        # out-of-tree plugins default to enabled at every point they
        # implement when the user names them in the config; a config that
        # doesn't mention them still registers them for profiles to enable.
    if plugin_extenders:
        svc.set_plugin_extenders(dict(plugin_extenders))
    svc.start_scheduler(config)
    return svc, svc.result_store
