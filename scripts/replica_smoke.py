#!/usr/bin/env python
"""Replication smoke (tier-1): the two-process failover drill.

A fixed churn scenario runs as a JOURNALED primary subprocess while a
hot-standby ``--mode follow`` subprocess tails its live journal
concurrently (replication/, docs/replication.md).  Three legs:

- **churn**: the primary exits cleanly; the follower must track it
  within ONE commit wave (``max_lag <= 1`` — one journal record is one
  wave) and its promotion must reproduce the primary's full annotation
  trail byte-for-byte.
- **failover**: the primary is SIGKILLed mid-wave at seeded record
  indices; the follower promotes and finishes the scenario — the
  promoted run must byte-match an uninterrupted baseline, with the
  follower's ``recovery_truncated_records_total == 0`` (the tailer
  never truncates; a kill-boundary tail is a crash-boundary step-over,
  not damage).
- **serve**: an in-process read replica behind the real HTTP server —
  reads 200 (and counted), writes 405, ``/metrics`` surfaces the
  ``replication_*`` family, promotion over HTTP unlocks writes.

A divergence ddmin-shrinks (fuzz/shrink.py) before reporting, like
fuzz_smoke.  Exit 0 = failover parity holds; nonzero = divergence or
harness failure.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:  # the axon plugin dials the TPU tunnel even when CPU-pinned
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

ROLE = {"use_batch": "auto", "commit_wave": 4, "checkpoint_every": 10}


def _node(i: int) -> dict:
    return {
        "op": "create",
        "kind": "nodes",
        "object": {
            "metadata": {"name": f"rpn-{i}", "labels": {"zone": f"z{i % 2}"}},
            "status": {
                "allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"},
                "capacity": {"cpu": "8", "memory": "16Gi", "pods": "110"},
            },
        },
    }


def _pod(i: int, cpu: str = "500m") -> dict:
    return {
        "op": "create",
        "kind": "pods",
        "object": {
            "metadata": {"name": f"rpp-{i}"},
            "spec": {
                "containers": [
                    {"name": "c", "resources": {"requests": {"cpu": cpu, "memory": "256Mi"}}}
                ]
            },
        },
    }


def smoke_scenario() -> dict:
    """Fixed journaled-churn timeline (the crash_smoke shape): pod
    storms sized to multiple commit waves, deletes, a cordon/uncordon
    patch pair — every tick a different mutation class for the
    follower to ship."""
    return {
        "name": "replica-smoke",
        "features": ["churn"],
        "stepSeconds": 1.0,
        "profile": "default",
        "ticks": [
            [_node(0), _node(1)] + [_pod(i) for i in range(8)],
            [_pod(i) for i in range(8, 14)]
            + [{"op": "delete", "kind": "pods", "name": "rpp-1", "namespace": "default"}],
            [
                _node(2),
                {
                    "op": "patch",
                    "kind": "nodes",
                    "name": "rpn-0",
                    "body": {"spec": {"unschedulable": True}},
                },
            ]
            + [_pod(i) for i in range(14, 18)],
            [
                {"op": "delete", "kind": "nodes", "name": "rpn-1"},
                {
                    "op": "patch",
                    "kind": "nodes",
                    "name": "rpn-0",
                    "body": {"spec": {"unschedulable": None}},
                },
                _pod(18),
            ],
        ],
    }


def _triage(scn: dict, kill_points: list, mismatch) -> None:
    """A divergence is a bug: shrink the scenario to the minimal
    failing timeline before reporting (the fuzz_smoke discipline)."""
    from kube_scheduler_simulator_tpu.fuzz.chaos import FailoverChaos, ProcessChaosError
    from kube_scheduler_simulator_tpu.fuzz.shrink import shrink

    first = (kill_points or [0])[0]

    def still_fails(cand: dict) -> bool:
        try:
            v = FailoverChaos(
                cand,
                kill_records=(first,) if first else (),
                role=ROLE,
                child_timeout_s=120,
            ).run()
        except ProcessChaosError:
            return False  # harness failure, not the divergence under shrink
        return bool(v["divergences"])

    mini, stats = shrink(scn, still_fails, max_checks=6)
    print(
        f"replica-smoke FAIL: promoted state diverged at kill points {kill_points}: "
        f"{json.dumps(mismatch)[:4000]}\n"
        f"shrunk repro ({stats['steps']} reductions): {json.dumps(mini)[:4000]}",
        file=sys.stderr,
    )


def _leg(verdict: dict, name: str, scn: dict) -> int:
    if verdict["divergences"]:
        _triage(scn, verdict["divergences"], verdict["first_mismatch"])
        return 1
    if verdict["truncated_records"] != 0:
        print(
            f"replica-smoke FAIL [{name}]: follower truncated "
            f"{verdict['truncated_records']} records (the tailer must never truncate "
            "and a kill boundary must read as a crash-boundary step-over)",
            file=sys.stderr,
        )
        return 1
    if verdict["torn_records"] != 0:
        print(
            f"replica-smoke FAIL [{name}]: {verdict['torn_records']} torn records "
            "shipped from clean SIGKILL boundaries",
            file=sys.stderr,
        )
        return 1
    if verdict["records_shipped"] <= 0:
        print(f"replica-smoke FAIL [{name}]: follower shipped no records", file=sys.stderr)
        return 1
    return 0


def _http_leg() -> int:
    """In-process read replica behind the real SimulatorServer."""
    import urllib.error
    import urllib.request

    from kube_scheduler_simulator_tpu.replication.replica import ReplicaContainer
    from kube_scheduler_simulator_tpu.server.server import SimulatorServer
    from kube_scheduler_simulator_tpu.state.journal import Journal
    from kube_scheduler_simulator_tpu.state.store import ClusterStore
    from kube_scheduler_simulator_tpu.utils.simclock import SimClock

    def fail(msg: str) -> int:
        print(f"replica-smoke FAIL [serve]: {msg}", file=sys.stderr)
        return 1

    with tempfile.TemporaryDirectory(prefix="kss-replica-serve-") as td:
        primary = ClusterStore(clock=SimClock(1_700_000_000.0))
        journal = Journal(td)
        primary.attach_journal(journal)
        primary.create("namespaces", {"metadata": {"name": "default"}})
        for i in range(3):
            primary.create("nodes", _node(i)["object"])
        with primary.journal_txn("wave"):
            for i in range(5):
                primary.create("pods", _pod(i)["object"])
        journal.close()

        di = ReplicaContainer(td, poll_s=0.01)
        server = SimulatorServer(di, port=0)
        port = server.start(background=True)
        base = f"http://127.0.0.1:{port}"
        try:
            with urllib.request.urlopen(f"{base}/api/v1/resources/pods") as r:
                if r.status != 200:
                    return fail(f"replica GET rc={r.status}")
                names = {o["metadata"]["name"] for o in json.load(r)["items"]}
            if names != {f"rpp-{i}" for i in range(5)}:
                return fail(f"replica served wrong pods: {sorted(names)}")
            try:
                req = urllib.request.Request(
                    f"{base}/api/v1/resources/pods",
                    data=json.dumps(_pod(99)["object"]).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                urllib.request.urlopen(req)
                return fail("write on a read replica did not 405")
            except urllib.error.HTTPError as e:
                if e.code != 405:
                    return fail(f"write on a read replica rc={e.code}, want 405")
            with urllib.request.urlopen(f"{base}/api/v1/replication") as r:
                status = json.load(r)
            if status["role"] != "replica" or status["readRequests"] < 1:
                return fail(f"replication status wrong pre-promotion: {status}")
            with urllib.request.urlopen(f"{base}/metrics") as r:
                text = r.read().decode()
            for needle in (
                "simulator_replication_records_shipped_total",
                "simulator_replication_lag_records",
                "simulator_replication_lag_seconds",
                "simulator_replica_promotions_total",
                "simulator_replica_read_requests_total",
            ):
                if needle not in text:
                    return fail(f"/metrics missing {needle}")
            if "simulator_replication_records_shipped_total 0" in text:
                return fail("/metrics reports zero shipped records on a fed replica")
            promote = urllib.request.Request(
                f"{base}/api/v1/replication/promote", data=b"", method="POST"
            )
            with urllib.request.urlopen(promote) as r:
                if r.status != 201:
                    return fail(f"promote rc={r.status}")
            create = urllib.request.Request(
                f"{base}/api/v1/resources/pods",
                data=json.dumps(_pod(99)["object"]).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(create) as r:
                if r.status != 201:
                    return fail(f"post-promotion write rc={r.status}")
            with urllib.request.urlopen(f"{base}/api/v1/replication") as r:
                if json.load(r)["role"] != "primary":
                    return fail("promoted replica still reports role=replica")
        finally:
            server.shutdown()
            di.close()
    return 0


def main() -> int:
    from kube_scheduler_simulator_tpu.fuzz.chaos import FailoverChaos

    t0 = time.monotonic()
    scn = smoke_scenario()

    # ---- churn: clean primary exit; the lag bar and parity
    churn = FailoverChaos(scn, kill_records=(), role=ROLE, child_timeout_s=240).run()
    print(
        f"replica-smoke churn: records={churn['records']} "
        f"shipped={churn['records_shipped']} max_lag={churn['max_lag']}"
    )
    rc = _leg(churn, "churn", scn)
    if rc:
        return rc
    if churn["max_lag"] > 1:
        print(
            f"replica-smoke FAIL [churn]: follower lag {churn['max_lag']} records "
            "exceeds one commit wave",
            file=sys.stderr,
        )
        return 1

    # ---- failover: SIGKILL the primary mid-wave (early + late), promote
    failover = FailoverChaos(
        scn, kill_records=(7, 10**9 + 9), role=ROLE, child_timeout_s=240
    ).run()
    print(
        f"replica-smoke failover: kill_points={failover['kill_points']} "
        f"shipped={failover['records_shipped']} replayed={failover['replayed_records']} "
        f"promotions={failover['promotions']}"
    )
    rc = _leg(failover, "failover", scn)
    if rc:
        return rc
    if failover["promotions"] != 2:
        print(
            f"replica-smoke FAIL [failover]: {failover['promotions']} promotions, want 2",
            file=sys.stderr,
        )
        return 1

    # ---- serve: the read replica behind the real HTTP server
    rc = _http_leg()
    if rc:
        return rc

    wall = time.monotonic() - t0
    print(
        f"replica-smoke OK: churn lag <= 1 wave ({churn['max_lag']}), "
        f"{len(failover['kill_points'])} failovers byte-identical "
        f"({failover['records_shipped']} records shipped, 0 torn, 0 truncated), "
        f"read replica served + promoted over HTTP; {wall:.0f}s"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
