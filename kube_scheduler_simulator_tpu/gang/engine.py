"""The gang round context: supportability gates + the batched gang replay.

``prepare_round`` builds (or refuses to build, with a counted reason) the
gang state for one batch segment whose profile runs the Coscheduling
oracle at Permit; ``GangRound`` then drives the replay's gang decisions:

- **park**: a kernel-scheduled gang member records its batch trace (the
  same categories the wrapped plugins record, permit = Wait + timeout)
  and parks in the framework's waiting map holding its reservation —
  byte-identical to the oracle cycle parking at Permit;
- **commit_release**: the member completing the quorum commits the WHOLE
  gang as one wave — ``ResultStore.add_wave_results`` for every member's
  bind-cycle records, ``ClusterStore.bulk_update`` binding all members
  in park order under one lock/one batched event dispatch, one reflector
  ``flush_wave`` — the all-or-nothing atomic commit;
- **note_window**: ONE gang-kernel dispatch per replay window (not per
  group) computes every group's all-or-nothing verdict and topology-
  packing metric from the selections (gang/kernel.run_window_verdict),
  cross-checked against host arithmetic (``gang_verdict_mismatch`` must
  stay 0).

Kernel-FAILED gang members take the exact sequential cycle (the service's
existing fallback), where the oracle Coscheduling PostFilter rejects the
parked siblings — so failure cascades run the same code on both paths and
cannot diverge.  Everything outside the envelope (quorum/minResources
gate failures, non-Coscheduling permit plugins, ``KSS_GANG_BATCH=0``)
falls back to the sequential round, counted per reason like
preemption/engine.py.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from kube_scheduler_simulator_tpu.gang import kernel as GK
from kube_scheduler_simulator_tpu.gang.encode import node_domain_ids
from kube_scheduler_simulator_tpu.gang.podgroups import (
    gang_batch_enabled,
    group_gate,
    group_info,
    pod_group_name,
)
from kube_scheduler_simulator_tpu.models.framework import CycleState, WaitingPod
from kube_scheduler_simulator_tpu.plugins.resultstore import (
    SUCCESS_MESSAGE,
    WAIT_MESSAGE,
    _go_duration,
)

Obj = dict[str, Any]

PLUGIN = "Coscheduling"


def prepare_round(
    service: Any, fw: Any, eng: Any, pending: list[Obj], nodes: list[Obj]
) -> "tuple[GangRound | None, str | None]":
    """Build the gang context for one batch segment, or (None, reason)
    when the round must run on the exact sequential oracle instead."""
    permit = [wp.original.name for wp in fw.plugins["permit"]]
    if permit != [PLUGIN]:
        return None, f"permit plugins {permit} are not the Coscheduling oracle"
    if not gang_batch_enabled():
        return None, "gang batch path disabled (KSS_GANG_BATCH=0)"
    store = service.cluster_store
    groups: dict[tuple[str, str], dict] = {}
    for p in pending:
        gname = pod_group_name(p)
        if not gname:
            continue
        ns = p["metadata"].get("namespace", "default")
        k = (ns, gname)
        if k in groups:
            continue
        # the oracle's PreFilter would reject this pod with a whole-round
        # result shape the replay can't reproduce — sequential, counted
        reason = group_gate(store, ns, gname)
        if reason is not None:
            return None, reason
        groups[k] = group_info(store.get("podgroups", gname, ns))
    return GangRound(service, fw, nodes, groups), None


def group_preview(store: Any, group: Obj) -> dict:
    """Feasibility preview for one PodGroup against the live cluster:
    the vmapped all-or-nothing scan (gang/kernel.run_feasibility) over
    the group's unbound members, with the group-granularity victim
    search when free capacity alone can't host the gang.  An ESTIMATION
    surface (GET /api/v1/podgroups/<name>?preview=1) — it never drives
    placement, exactly like the autoscaler's estimation kernel."""
    from kube_scheduler_simulator_tpu.gang.encode import encode_feasibility
    from kube_scheduler_simulator_tpu.models.snapshot import Snapshot
    from kube_scheduler_simulator_tpu.plugins.intree.queue_bind import pod_priority

    ns = group["metadata"].get("namespace") or "default"
    gname = group["metadata"]["name"]
    info = group_info(group)
    pods = store.list("pods", copy_objects=False)
    nodes = store.list("nodes", copy_objects=False)
    snap = Snapshot(nodes, pods, [])
    members = [
        p
        for p in pods
        if pod_group_name(p) == gname
        and (p["metadata"].get("namespace") or "default") == ns
        and not (p.get("spec") or {}).get("nodeName")
        and not p["metadata"].get("deletionTimestamp")
    ]
    pr = encode_feasibility([members], [info["topology_key"]], snap.node_infos)
    out = GK.run_feasibility(pr)
    feasible = bool(out["feasible"][0])
    res: dict = {
        "feasible": feasible,
        "distinctTopologyDomains": int(out["distinct_domains"][0]),
        "assignment": {
            m["metadata"]["name"]: (
                pr.node_names[int(out["assignment"][0, i])]
                if int(out["assignment"][0, i]) >= 0
                else None
            )
            for i, m in enumerate(members)
        },
    }
    if not feasible and members:
        try:
            pdbs = store.list("poddisruptionbudgets", copy_objects=False)
        except Exception:
            pdbs = []
        prio = min(pod_priority(p) for p in members)
        res["victimPreview"] = GK.group_victim_search(
            snap.node_infos, [(members, prio)], pdbs
        )[0]
    return res


class GangRound:
    """Gang replay state for one batch segment (see module docstring)."""

    def __init__(self, service: Any, fw: Any, nodes: list[Obj], groups: dict):
        self.service = service
        self.fw = fw
        self.groups = groups  # (ns, gname) -> group_info dict
        self.engaged = bool(groups)
        self.gid = {k: i for i, k in enumerate(groups)}
        self.node_id = {nd["metadata"]["name"]: i for i, nd in enumerate(nodes)}
        G = len(groups)
        self.min_member = np.array(
            [groups[k]["min_member"] for k in groups], dtype=np.int32
        ).reshape(G)
        if G:
            self.dom, self.D = node_domain_ids(
                nodes, [groups[k]["topology_key"] for k in groups]
            )
        else:
            self.dom, self.D = np.zeros((0, len(nodes)), np.int32), 1
        # members already holding capacity at round start
        self.bound = {k: 0 for k in groups}
        self.parked: dict[tuple[str, str], list[str]] = {k: [] for k in groups}
        self.parked_nodes: dict[tuple[str, str], list[int]] = {k: [] for k in groups}
        if groups:
            for p in service.cluster_store.list("pods", copy_objects=False):
                k = self._key_of(p)
                if (
                    k is not None
                    and (p.get("spec") or {}).get("nodeName")
                    and not p["metadata"].get("deletionTimestamp")
                ):
                    self.bound[k] += 1
            for w in fw.iterate_over_waiting_pods():
                k = self._key_of(w.pod)
                if k is not None:
                    self.parked[k].append(w.key)
                    self.parked_nodes[k].append(self.node_id.get(w.node_name, -1))

    # ------------------------------------------------------------- helpers

    def _key_of(self, pod: Obj) -> "tuple[str, str] | None":
        gname = pod_group_name(pod)
        if not gname:
            return None
        k = (pod["metadata"].get("namespace", "default"), gname)
        return k if k in self.groups else None

    def group_of(self, pod: Obj) -> "tuple[str, str] | None":
        return self._key_of(pod)

    def _prune_parked(self, k: "tuple[str, str]") -> None:
        """Drop parked entries no longer in the LIVE waiting map: a
        kernel-failed member's sequential cascade (Coscheduling
        PostFilter) rejects parked siblings mid-segment, and a stale
        count here would let completes() fire early and commit a PARTIAL
        gang — the one thing this engine exists to prevent."""
        live = self.fw.waiting_pods
        if all(sk in live for sk in self.parked[k]):
            return
        kept = [
            (sk, nid)
            for sk, nid in zip(self.parked[k], self.parked_nodes[k])
            if sk in live
        ]
        self.parked[k] = [sk for sk, _nid in kept]
        self.parked_nodes[k] = [nid for _sk, nid in kept]

    def completes(self, k: "tuple[str, str]") -> bool:
        """Would this member complete the quorum?  The same arithmetic the
        oracle Permit runs (bound + parked + 1 vs minMember)."""
        self._prune_parked(k)
        return self.bound[k] + len(self.parked[k]) + 1 >= self.groups[k]["min_member"]

    def _success_cats(
        self, result: Any, j: int, pod: Obj, node_name: str, point_names: dict
    ) -> dict:
        """The batch trace categories a kernel-scheduled gang member
        records (identical content to the wave commit's, which the
        commit-parity suite pins against the wrapped plugins)."""
        cats: dict = {}
        pf_names = point_names["pre_filter"]
        if pf_names:
            cats["preFilterStatus"] = {pn: SUCCESS_MESSAGE for pn in pf_names}
            if "NodeAffinity" in pf_names:
                names = result._engine.prefilter_node_names(pod)
                if names is not None:
                    cats["preFilterResult"] = {"NodeAffinity": sorted(names)}
        cats["filter"] = result.filter_annotation_pair(j)
        if int(result.feasible_count[j]) > 1:
            pre_score = {pn: SUCCESS_MESSAGE for pn in point_names["pre_score"]}
            if pre_score:
                cats["preScore"] = pre_score
            score_pair, final_pair = result.score_annotations_pairs(j)
            cats["score"] = score_pair
            cats["finalScore"] = final_pair
        if point_names["reserve"]:
            cats["selectedNode"] = node_name
            cats["reserve"] = {pn: SUCCESS_MESSAGE for pn in point_names["reserve"]}
        return cats

    # ---------------------------------------------------------------- park

    def park(
        self,
        result: Any,
        j: int,
        pod: Obj,
        node_name: str,
        snapshot: Any,
        point_names: dict,
    ) -> Any:
        """Park a kernel-scheduled gang member at Permit, exactly as the
        oracle cycle does: trace recorded (permit = Wait + the group's
        timeout), reservation held in the waiting map + round snapshot."""
        from kube_scheduler_simulator_tpu.scheduler.framework_runner import (
            MAX_PERMIT_TIMEOUT_S,
            ScheduleResult,
        )

        k = self._key_of(pod)
        assert k is not None
        info = self.groups[k]
        ns = pod["metadata"].get("namespace", "default")
        name = pod["metadata"]["name"]
        cats = self._success_cats(result, j, pod, node_name, point_names)
        # the wrapped recorder stores the RAW plugin timeout; the waiting
        # map clamps to the 15 min max (framework_runner.schedule_one)
        cats["permit"] = {PLUGIN: WAIT_MESSAGE}
        cats["permitTimeout"] = {PLUGIN: _go_duration(info["timeout"])}
        self.fw.result_store.add_wave_results([(ns, name, cats)])
        t = info["timeout"] if info["timeout"] > 0 else MAX_PERMIT_TIMEOUT_S
        wp = WaitingPod(
            pod,
            node_name,
            CycleState(),
            {PLUGIN: min(t, MAX_PERMIT_TIMEOUT_S)},
            self.fw.clock(),
        )
        self.fw.waiting_pods[wp.key] = wp
        self.service._wait_move_seq[wp.key] = self.service.queue.move_seq
        if snapshot is not None:
            snapshot.assume(pod, node_name)
        self.parked[k].append(wp.key)
        self.parked_nodes[k].append(self.node_id.get(node_name, -1))
        self.service.stats["gang_parked"] += 1
        return ScheduleResult(waiting_on=node_name)

    # ------------------------------------------------------------- release

    def commit_release(
        self,
        result: Any,
        j: int,
        pod: Obj,
        node_name: str,
        snapshot: Any,
        point_names: dict,
    ) -> Any:
        """The quorum-completing member commits the whole gang atomically:
        one result-store wave, one bulk-update bind transaction (members
        in park order, the releasing member last — the oracle's release
        order), one reflector wave flush."""
        from kube_scheduler_simulator_tpu.scheduler.framework_runner import ScheduleResult

        svc = self.service
        fw = self.fw
        k = self._key_of(pod)
        assert k is not None
        self._prune_parked(k)
        sib_keys = list(self.parked[k])
        self.parked[k] = []
        self.parked_nodes[k] = []
        wps = [fw.waiting_pods.pop(sk) for sk in sib_keys if sk in fw.waiting_pods]
        ns = pod["metadata"].get("namespace", "default")
        name = pod["metadata"]["name"]

        prebind = {pn: SUCCESS_MESSAGE for pn in point_names["pre_bind"]}
        bindc = (
            {point_names["bind"][0]: SUCCESS_MESSAGE} if point_names["bind"] else None
        )
        entries: list[tuple[str, str, dict]] = []
        for w in wps:
            cats: dict = {}
            if prebind:
                cats["prebind"] = prebind
            if bindc:
                cats["bind"] = bindc
            entries.append(
                (
                    w.pod["metadata"].get("namespace", "default"),
                    w.pod["metadata"]["name"],
                    cats,
                )
            )
        self_cats = self._success_cats(result, j, pod, node_name, point_names)
        self_cats["permit"] = {PLUGIN: SUCCESS_MESSAGE}
        self_cats["permitTimeout"] = {PLUGIN: _go_duration(0)}
        if prebind:
            self_cats["prebind"] = prebind
        if bindc:
            self_cats["bind"] = bindc
        entries.append((ns, name, self_cats))

        with svc.cluster_store.journal_txn("gang-release"):
            return self._commit_release_txn(
                entries, wps, sib_keys, pod, ns, name, node_name, snapshot, k
            )

    def _commit_release_txn(
        self,
        entries: list,
        wps: list,
        sib_keys: list,
        pod: Obj,
        ns: str,
        name: str,
        node_name: str,
        snapshot: Any,
        k: "tuple[str, str]",
    ) -> Any:
        """The release's mutating tail, grouped into ONE atomic journal
        record (state/journal.py): the result-store wave, the bulk bind
        transaction, the reflector wave flush and the Scheduled event
        recover together or not at all — a crash can never leave a
        partially-bound gang."""
        from kube_scheduler_simulator_tpu.scheduler.framework_runner import ScheduleResult

        svc = self.service
        fw = self.fw
        fw.result_store.add_wave_results(entries)

        def bind_to(node: str):
            def mut(cur: "Obj | None") -> "Obj | None":
                if cur is None:
                    return None
                return {
                    **cur,
                    "metadata": dict(cur["metadata"]),
                    "spec": {**(cur.get("spec") or {}), "nodeName": node},
                }

            return mut

        svc.cluster_store.bulk_update(
            "pods",
            [
                (
                    w.pod["metadata"]["name"],
                    w.pod["metadata"].get("namespace", "default"),
                    bind_to(w.node_name),
                )
                for w in wps
            ]
            + [(name, ns, bind_to(node_name))],
        )
        for sk in sib_keys:
            svc._wait_move_seq.pop(sk, None)
        if snapshot is not None:
            snapshot.assume(pod, node_name)
        svc.reflector.flush_wave(svc.cluster_store, [w.pod for w in wps] + [pod])
        # the oracle records a Scheduled event for the RELEASING member
        # only (parked siblings bind through allow_waiting_pod, which the
        # service's event recorder never sees)
        svc._record_event(
            pod, "Normal", "Scheduled", f"Successfully assigned {ns}/{name} to {node_name}"
        )
        self.bound[k] += len(wps) + 1
        svc.stats["gang_released_groups"] += 1
        svc.stats["gang_released_pods"] += len(wps) + 1
        return ScheduleResult(selected_node=node_name)

    # -------------------------------------------------------- window verdict

    def note_window(self, result: Any, cnt: int) -> None:
        """ONE gang-kernel dispatch covering every group of this replay
        window: all-or-nothing verdict + distinct-topology-domain packing
        metric over the window's selections plus the currently parked
        members, cross-checked against host arithmetic."""
        if not self.engaged:
            return
        window = result.pending
        gids: list[int] = []
        sel_nodes: list[int] = []
        for j in range(cnt):
            k = self._key_of(window[j])
            if k is None:
                continue
            gids.append(self.gid[k])
            sel_nodes.append(int(result.selected[j]))
        for k in self.groups:
            self._prune_parked(k)
        for k, nodes in self.parked_nodes.items():
            for nid in nodes:
                gids.append(self.gid[k])
                sel_nodes.append(nid)
        if not gids:
            return
        G = len(self.groups)
        prior_bound = np.zeros(G, dtype=np.int32)
        for k, b in self.bound.items():
            prior_bound[self.gid[k]] = b
        t0 = time.perf_counter()
        out = GK.run_window_verdict(
            np.asarray(gids, np.int32),
            np.asarray(sel_nodes, np.int32),
            self.dom,
            prior_bound,
            self.min_member,
            self.D,
        )
        svc = self.service
        svc.stats["gang_kernel_s"] += time.perf_counter() - t0
        svc.stats["gang_kernel_dispatches"] += 1
        # host cross-check of the device arithmetic (a float/scatter bug
        # here must be LOUD, like the autoscaler's kernel-error counter)
        placed = np.zeros(G, dtype=np.int64)
        failed = np.zeros(G, dtype=np.int64)
        doms: list[set] = [set() for _ in range(G)]
        for g, n in zip(gids, sel_nodes):
            if n >= 0:
                placed[g] += 1
                doms[g].add(int(self.dom[g, n]))
            else:
                failed[g] += 1
        exp_ok = (failed == 0) & ((placed + prior_bound) >= self.min_member)
        exp_d = np.array([len(d) for d in doms], dtype=np.int32)
        if not (
            np.array_equal(np.asarray(out["feasible"], bool), exp_ok)
            and np.array_equal(np.asarray(out["distinct_domains"], np.int32), exp_d)
        ):
            svc.stats["gang_verdict_mismatch"] += 1
