"""External-scheduler E2E across all three integration surfaces.

The reference's story for external schedulers: watch the kube API for
pending pods, consult a scheduler extender for filter/prioritize, commit
with the Binding subresource.  This drives that loop against this build:
kube-API port (watch + binding) + the TPU scorer endpoint (extenderv1
wire) on the simulator port — a stand-in for a real kube-scheduler with
an `extenders:` stanza pointed at the TPU.
"""

from __future__ import annotations

import json
import urllib.request

from kube_scheduler_simulator_tpu.server import DIContainer, SimulatorServer


def _req(port, method, path, body=None):
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"},
    )
    # generous timeout: the tpuscorer's first call compiles its kernel
    with urllib.request.urlopen(r, timeout=120) as resp:
        data = resp.read()
        return resp.status, (json.loads(data) if data else None)


def test_external_scheduler_binds_via_kube_api_and_tpu_scorer():
    di = DIContainer(use_batch="off")  # the EXTERNAL scheduler does the scheduling
    srv = SimulatorServer(di, port=0, kube_api_port=0)
    sim_port = srv.start(background=True)
    kube_port = srv.kube_api_server.port
    try:
        # cluster: one full node, one free node
        for i, cpu in enumerate(("100m", "8")):
            _req(kube_port, "POST", "/api/v1/nodes", {
                "metadata": {"name": f"node-{i}"},
                "status": {"allocatable": {"cpu": cpu, "memory": "16Gi", "pods": "110"}},
            })
        _req(kube_port, "POST", "/api/v1/namespaces/default/pods", {
            "metadata": {"name": "ext-pod", "namespace": "default"},
            "spec": {"schedulerName": "tpu-external",
                     "containers": [{"name": "c", "resources": {"requests": {"cpu": "2"}}}]},
        })

        # the in-process scheduler must LEAVE the pod alone: its
        # spec.schedulerName names the external scheduler, not a profile
        di.scheduler_service().schedule_pending(max_rounds=1)

        # the external scheduler "watches" for pending pods (list is the
        # degenerate watch here; the streaming path is covered in
        # test_kubeapi) ...
        _code, pods = _req(kube_port, "GET", "/api/v1/pods")
        pending = [p for p in pods["items"] if not (p.get("spec") or {}).get("nodeName")]
        assert [p["metadata"]["name"] for p in pending] == ["ext-pod"]
        _code, nodes = _req(kube_port, "GET", "/api/v1/nodes")

        # ... consults the TPU scorer in extenderv1 wire format ...
        _code, fr = _req(sim_port, "POST", "/api/v1/tpuscorer/filter", {
            "pod": pending[0], "nodes": nodes,
        })
        assert fr["error"] == ""
        feasible = [n["metadata"]["name"] for n in (fr["nodes"] or {}).get("items") or []]
        assert feasible == ["node-1"], fr  # node-0 can't fit 2 cpu
        assert "node-0" in (fr["failedNodes"] or {}), fr
        _code, prio = _req(sim_port, "POST", "/api/v1/tpuscorer/prioritize", {
            "pod": pending[0], "nodes": nodes,
        })
        best = max((h for h in prio if h["host"] in feasible), key=lambda h: h["score"])

        # ... and commits through the Binding subresource.
        code, _ = _req(kube_port, "POST", "/api/v1/namespaces/default/pods/ext-pod/binding", {
            "target": {"name": best["host"]},
        })
        assert code == 201
        _code, bound = _req(kube_port, "GET", "/api/v1/namespaces/default/pods/ext-pod")
        assert bound["spec"]["nodeName"] == "node-1"
        # no kubelet in the simulator: bound pods stay Pending (reference
        # behavior — the Binding subresource only sets spec.nodeName)
        assert bound["status"]["phase"] == "Pending"
    finally:
        srv.shutdown()


def test_external_scheduler_driven_by_field_selector_watch():
    """The real client-go flow: the external scheduler WATCHES
    ``spec.schedulerName=<its name>,spec.nodeName=`` (what a second
    kube-scheduler's informers send to the reference's apiserver), binds
    each pod the stream hands it, and relies on the selector watch
    synthesizing DELETED once the bind moves the pod out of scope."""
    import http.client
    import urllib.parse

    di = DIContainer(use_batch="off")
    srv = SimulatorServer(di, port=0, kube_api_port=0)
    srv.start(background=True)
    kube_port = srv.kube_api_server.port
    try:
        _req(kube_port, "POST", "/api/v1/nodes", {
            "metadata": {"name": "node-0"},
            "status": {"allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}},
        })
        sel = urllib.parse.quote("spec.schedulerName=tpu-external,spec.nodeName=")
        conn = http.client.HTTPConnection("127.0.0.1", kube_port, timeout=30)
        conn.request("GET", f"/api/v1/pods?watch=true&fieldSelector={sel}")
        resp = conn.getresponse()
        assert resp.status == 200

        # two pods for the external scheduler, one for the simulator's own
        for name, sched in (("w-1", "tpu-external"), ("mine", None), ("w-2", "tpu-external")):
            body = {"metadata": {"name": name, "namespace": "default"},
                    "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "1"}}}]}}
            if sched:
                body["spec"]["schedulerName"] = sched
            _req(kube_port, "POST", "/api/v1/namespaces/default/pods", body)

        scheduled = []
        deleted = []
        # drive the loop: bind every ADDED pod, stop when both binds have
        # been confirmed back as synthetic DELETEDs
        while len(deleted) < 2:
            ev = json.loads(resp.readline())
            name = ev["object"]["metadata"]["name"]
            assert name != "mine", "selector watch leaked another scheduler's pod"
            if ev["type"] == "ADDED":
                code, _ = _req(kube_port, "POST",
                               f"/api/v1/namespaces/default/pods/{name}/binding",
                               {"target": {"name": "node-0"}})
                assert code == 201
                scheduled.append(name)
            elif ev["type"] == "DELETED":
                deleted.append(name)
        assert sorted(scheduled) == ["w-1", "w-2"]
        assert sorted(deleted) == ["w-1", "w-2"]
        for name in ("w-1", "w-2"):
            _code, pod = _req(kube_port, "GET", f"/api/v1/namespaces/default/pods/{name}")
            assert pod["spec"]["nodeName"] == "node-0"
        conn.close()
    finally:
        srv.shutdown()


def test_declared_second_profile_name_still_scheduled():
    """Pods naming ANY declared profile are scheduled (this build runs one
    framework for all declared names); only undeclared (external)
    schedulerNames are left alone."""
    from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
    from kube_scheduler_simulator_tpu.state.store import ClusterStore

    store = ClusterStore()
    store.create("nodes", {"metadata": {"name": "n0"},
                           "status": {"allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}}})
    for name, sched in (("p-default", None), ("p-second", "second-scheduler"), ("p-ext", "external")):
        pod = {"metadata": {"name": name, "namespace": "default"},
               "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "100m"}}}]}}
        if sched:
            pod["spec"]["schedulerName"] = sched
        store.create("pods", pod)
    svc = SchedulerService(store, tie_break="first")
    svc.start_scheduler({"profiles": [
        {"schedulerName": "default-scheduler"},
        {"schedulerName": "second-scheduler"},
    ]})
    svc.schedule_pending(max_rounds=1)
    assert store.get("pods", "p-default")["spec"].get("nodeName")
    assert store.get("pods", "p-second")["spec"].get("nodeName")
    assert not store.get("pods", "p-ext")["spec"].get("nodeName")
