#!/usr/bin/env python
"""Fast stream-parity smoke: the streaming wave pipeline vs the strictly
sequential path over a 3-wave churn scenario, byte-compared — the tier-1
step that catches pipeline-ordering bugs in scheduler/stream.py (stale
encode views, counter/rotation drift, commit interleaves) without the
slow markers.

Drives a real SchedulerService twice through the same deterministic
create/delete feed — once with the overlapped streaming pipeline, once
with the serial baseline (same admission loop, zero overlap) — then
byte-compares every pod's binding, annotation trail and conditions AND
asserts the streamed path actually engaged (waves counted, host work
overlapped with an in-flight kernel, delta encode riding along).
Exit 0 = parity; nonzero = diverged.
"""

from __future__ import annotations

import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")

from kube_scheduler_simulator_tpu.utils import SimClock

PER_TICK = 40
TICKS = 3


def mk_pod(i: int) -> dict:
    p = {
        "metadata": {
            "name": f"pod-{i}",
            "namespace": "default",
            "labels": {"app": f"a{i % 3}"},
            "creationTimestamp": (
                f"2024-03-01T{i // 3600 % 24:02d}:{i // 60 % 60:02d}:{i % 60:02d}Z"
            ),
        },
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "resources": {
                        "requests": {"cpu": f"{100 + (i % 4) * 50}m", "memory": "128Mi"}
                    },
                }
            ]
        },
    }
    if i % 4 == 0:
        p["spec"]["nodeSelector"] = {"disk": "ssd"}
    if i % 3 == 0:
        p["spec"]["topologySpreadConstraints"] = [
            {
                "maxSkew": 2,
                "topologyKey": "topology.kubernetes.io/zone",
                "whenUnsatisfiable": "DoNotSchedule",
                "labelSelector": {"matchLabels": {"app": f"a{i % 3}"}},
            }
        ]
    return p


def build():
    from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
    from kube_scheduler_simulator_tpu.state.store import ClusterStore

    store = ClusterStore(clock=SimClock(1_700_000_000.0))
    for i in range(16):
        store.create(
            "nodes",
            {
                "metadata": {
                    "name": f"node-{i}",
                    "labels": {
                        "kubernetes.io/hostname": f"node-{i}",
                        "topology.kubernetes.io/zone": f"z{i % 3}",
                        "disk": "ssd" if i % 2 else "hdd",
                    },
                },
                "status": {"allocatable": {"cpu": "16000m", "memory": "32Gi", "pods": "110"}},
                "spec": {},
            },
        )
    svc = SchedulerService(store, tie_break="first", use_batch="force", batch_min_work=1)
    svc.start_scheduler(None)
    return svc, store


def feed_factory(store):
    rng = random.Random(5)

    def feed(tick: int) -> bool:
        if tick >= TICKS:
            return False
        for i in range(tick * PER_TICK, (tick + 1) * PER_TICK):
            store.create("pods", mk_pod(i))
        if tick >= 2:
            # churn: delete pods SETTLED in both pipeline phases (created
            # two or more ticks ago) — a streamed feed runs one commit
            # earlier than the serial one
            settled = [f"pod-{i}" for i in range((tick - 1) * PER_TICK)]
            for nm in rng.sample(settled, 5):
                try:
                    store.delete("pods", nm, "default")
                except KeyError:
                    pass
        return True

    return feed


def run(streaming: bool):
    from kube_scheduler_simulator_tpu.utils.parity import pod_parity_state

    svc, store = build()
    svc.schedule_stream(feed=feed_factory(store), streaming=streaming)
    return pod_parity_state(store), svc.metrics(), svc, store


def steady_state_guard(svc, store) -> int:
    """One more streamed churn pass over the WARMED service: every
    executable this wave shape needs was compiled during the parity run,
    so the steady-state contract is zero new backend compiles — the
    RecompileGuard turns a silent recompile-per-wave regression (the PR 7
    pathology class) into a loud tier-1 failure."""
    from kube_scheduler_simulator_tpu.analysis import RecompileGuard
    from kube_scheduler_simulator_tpu.analysis.runtime import RecompileError

    def feed(tick: int) -> bool:
        if tick >= 1:
            return False
        for i in range(1000, 1000 + PER_TICK):
            store.create("pods", mk_pod(i))
        return True

    try:
        with RecompileGuard("stream steady-state waves") as g:
            svc.schedule_stream(feed=feed, streaming=True)
    except RecompileError as e:
        print(f"FAIL: {e}", file=sys.stderr)
        return 1
    print(f"stream-smoke steady state: {g.compiles} recompiles across the warmed pass")
    return 0


def main() -> int:
    d1, m1, svc1, store1 = run(True)
    d0, m0, _svc0, _store0 = run(False)
    if d1.keys() != d0.keys():
        print(f"stream-smoke: pod sets diverged ({len(d1)} vs {len(d0)})", file=sys.stderr)
        return 1
    bad = [k for k in sorted(d1) if d1[k] != d0[k]]
    if bad:
        print(f"stream-smoke: {len(bad)} pods diverged, first: {bad[0]}", file=sys.stderr)
        return 1
    if m1["stream_waves_total"] < TICKS:
        print(
            f"stream-smoke: pipeline never engaged — waves={m1['stream_waves_total']} "
            f"drains={m1['stream_drains_by_reason']}",
            file=sys.stderr,
        )
        return 1
    if m1["stream_overlap_s"] <= 0.0:
        print("stream-smoke: no host work overlapped an in-flight kernel", file=sys.stderr)
        return 1
    if m0["stream_overlap_s"] != 0.0:
        print("stream-smoke: the serial baseline reported overlap", file=sys.stderr)
        return 1
    rc = steady_state_guard(svc1, store1)
    if rc:
        return rc
    print(
        f"stream-smoke OK: {len(d1)} pods byte-identical; "
        f"waves={m1['stream_waves_total']} pods={m1['stream_pods_total']} "
        f"overlap_s={m1['stream_overlap_s']:.3f} stall_s={m1['stream_stall_s']:.3f} "
        f"drains={m1['stream_drains_by_reason']} "
        f"delta={m1['encode_delta_total']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
