"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).  These env vars must
be set before jax is imported anywhere.
"""

import os

# NOTE: the axon TPU plugin in this image ignores JAX_PLATFORMS but honors
# JAX_PLATFORM_NAME; set both so tests run on the virtual CPU mesh either way.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
# The axon plugin registers itself from sitecustomize at interpreter
# start (jax is ALREADY imported before this conftest runs) and its
# backend factory dials the TPU tunnel even in CPU-pinned processes —
# when the tunnel is down, every jax call hangs.  Tests never touch the
# TPU: deregister the factory and re-pin the (already-read) platform
# config so the suite is immune to tunnel health.
try:
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")
    try:
        _jax.config.update("jax_platform_name", "cpu")
    except Exception:
        pass
except Exception:  # pragma: no cover - plugin absent / jax internals moved
    pass
# x64 gives the batch kernels bit-exact integer semantics on CPU, which is
# what the parity suites assert; the TPU bench path runs float32 (kept
# near-exact by the encoder's GCD scaling) and reports max |Δscore|.
os.environ.setdefault("JAX_ENABLE_X64", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _substrate_reset():
    """The cross-engine executable substrate (tenancy/substrate.py) is a
    process-wide singleton; drop its tables and enable-refcount between
    tests so a session-plane test can never leak compiled fns (or the
    enabled state) into an engine test's compile/AOT expectations."""
    yield
    from kube_scheduler_simulator_tpu.tenancy.substrate import SUBSTRATE

    SUBSTRATE.clear()
