"""EXECUTE the web UI's JavaScript (VERDICT r3 weak #4, beyond the static
checker): the served script runs top-to-bottom in the ``utils.jseval``
interpreter against the ``utils.jsdom`` DOM/fetch stub — render paths,
table view, search debounce, the scheduling-result dialog, and the watch
loop all execute for real, and runtime-only defects (that parse and
scope-check clean) turn the suite red.

The reference web UI gets this from Nuxt/Vitest (reference
web/package.json:8-16); this is the no-toolchain analog.
"""

from __future__ import annotations

import json

import pytest

from kube_scheduler_simulator_tpu.server.webui import HTML, JS
from kube_scheduler_simulator_tpu.utils.jsdom import Harness, collect_text
from kube_scheduler_simulator_tpu.utils.jseval import ThrowSig

KINDS = [
    "pods", "nodes", "persistentvolumes", "persistentvolumeclaims",
    "storageclasses", "priorityclasses", "namespaces", "deployments",
    "replicasets", "scenarios", "nodegroups", "podgroups",
]


def make_harness(pods=(), nodes=()):
    h = Harness(HTML)
    for k in KINDS:
        h.routes[("GET", f"/api/v1/resources/{k}")] = {"items": []}
    h.routes[("GET", "/api/v1/resources/nodes")] = {"items": list(nodes)}
    h.routes[("GET", "/api/v1/resources/pods")] = {"items": list(pods)}
    h.routes[("GET", "/api/v1/autoscaler")] = {"mode": "off"}
    return h


def node_obj(name, cpu="8", mem="16Gi"):
    return {
        "metadata": {"name": name, "labels": {}},
        "status": {"allocatable": {"cpu": cpu, "memory": mem, "pods": "110"}},
    }


def pod_obj(name, node=None, annotations=None):
    o = {
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "100m"}}}]},
    }
    if annotations:
        o["metadata"]["annotations"] = annotations
    if node:
        o["spec"]["nodeName"] = node
    return o


SCORED = {
    "scheduler-simulator/finalscore-result": json.dumps(
        {"exec-node-1": {"NodeResourcesFit": "42", "TaintToleration": "100"}}
    ),
    "scheduler-simulator/score-result": json.dumps(
        {"exec-node-1": {"NodeResourcesFit": "37"}}
    ),
    "scheduler-simulator/selected-node": "exec-node-1",
    "scheduler-simulator/result-history": json.dumps(
        [{"scheduler-simulator/finalscore-result": '{"exec-node-1":{"NodeResourcesFit":"41"}}'}]
    ),
}


def test_boot_renders_cluster_view():
    h = make_harness(
        pods=[pod_obj("exec-pod-a", node="exec-node-1", annotations=SCORED), pod_obj("exec-pod-pending")],
        nodes=[node_obj("exec-node-1")],
    )
    h.boot(JS)
    text = collect_text(h.document._by_id["nodes"])
    # bound pod bucketed under its node; pending pod under (unscheduled)
    assert "exec-node-1" in text and "default/exec-pod-a" in text
    assert "(unscheduled)" in text and "default/exec-pod-pending" in text
    assert "cpu 8" in text and "mem 16Gi" in text


def test_tables_view_renders_columns_and_rows():
    h = make_harness(
        pods=[pod_obj("exec-pod-a", node="exec-node-1", annotations=SCORED)],
        nodes=[node_obj("exec-node-1")],
    )
    interp = h.boot(JS)
    interp.get_global("toggleView")()
    text = collect_text(h.document._by_id["tables"])
    assert "pods (1)" in text and "nodes (1)" in text
    for col in ("namespace", "name", "node", "phase", "selectedNode"):
        assert f"<b>{col}</b>" in text
    # the selectedNode column extractor read the annotation
    assert "exec-node-1" in text


def test_search_debounce_filters_render():
    h = make_harness(
        pods=[pod_obj("exec-pod-a", node="exec-node-1"), pod_obj("exec-pod-pending")],
        nodes=[node_obj("exec-node-1")],
    )
    interp = h.boot(JS)
    interp.get_global("toggleView")()
    assert "pods (2)" in collect_text(h.document._by_id["tables"])
    h.document._by_id["search"].value = "pending"
    interp.get_global("onSearch")()
    # nothing re-rendered until the debounce timer fires
    assert "pods (2)" in collect_text(h.document._by_id["tables"])
    assert h.flush_timers() >= 1
    text = collect_text(h.document._by_id["tables"])
    assert "pods (1)" in text and "nodes (0)" in text


def test_pod_dialog_shows_results_and_history():
    h = make_harness(
        pods=[pod_obj("exec-pod-a", node="exec-node-1", annotations=SCORED)],
        nodes=[node_obj("exec-node-1")],
    )
    interp = h.boot(JS)
    pod = interp.get_global("state")["pods"]["default/exec-pod-a"]
    interp.get_global("showPod")(pod)
    dlg = h.document._by_id["dlg"]
    assert dlg.open, "showPod must open the dialog"
    body = collect_text(h.document._by_id["dlgbody"])
    assert "default/exec-pod-a" in body
    assert "finalscore-result" in body and "NodeResourcesFit" in body
    # the history viewer rendered the prior attempt's finalscore (41)
    assert '"41"' in body


def test_watch_loop_applies_added_and_deleted_events():
    h = make_harness(nodes=[node_obj("exec-node-1")])
    ev_add = json.dumps({"Kind": "pods", "EventType": "ADDED", "Obj": pod_obj("watch-pod", node="exec-node-1")})
    ev_del = json.dumps({"Kind": "pods", "EventType": "DELETED", "Obj": pod_obj("watch-pod")})
    ev_add2 = json.dumps({"Kind": "pods", "EventType": "ADDED", "Obj": pod_obj("watch-pod-2")})
    # split mid-line across chunks: exercises the stream buffering
    whole = ev_add + "\n" + ev_del + "\n" + ev_add2 + "\n"
    h.watch_chunks = [whole[:25], whole[25:60], whole[60:]]
    interp = h.boot(JS)
    pods = interp.get_global("state")["pods"]
    assert "default/watch-pod" not in pods, "DELETED event must remove the pod"
    assert "default/watch-pod-2" in pods
    assert "default/watch-pod-2" in collect_text(h.document._by_id["nodes"])


def test_node_dialog_capacity_bars():
    h = make_harness(
        pods=[pod_obj("exec-pod-a", node="exec-node-1")],
        nodes=[node_obj("exec-node-1", cpu="2000m", mem="4Gi")],
    )
    interp = h.boot(JS)
    node = interp.get_global("state")["nodes"]["exec-node-1"]
    interp.get_global("showNode")(node)
    body = collect_text(h.document._by_id["dlgbody"])
    assert "exec-node-1" in body
    assert "cpu" in body and "%" in body  # usage bars rendered


def test_reset_flow_issues_put():
    h = make_harness()
    interp = h.boot(JS)
    h.routes[("PUT", "/api/v1/reset")] = {}
    h.confirm_response = True
    interp.get_global("doReset")()
    assert ("PUT", "/api/v1/reset", None) in h.requests
    # declining the confirm must NOT issue the call
    h.requests.clear()
    h.confirm_response = False
    interp.get_global("doReset")()
    assert not any(p == "/api/v1/reset" for _m, p, _b in h.requests)


@pytest.mark.parametrize(
    "name,mutate",
    [
        # parses clean, scope-checks clean — only EXECUTION catches these
        ("wrong-dom-id", lambda js: js.replace('document.getElementById("nodes")', 'document.getElementById("nodez")', 1)),
        ("state-type-confusion", lambda js: js.replace("state[k] = {};", "state[k] = 0;", 1)),
        ("bad-items-field", lambda js: js.replace("lst.items", "lst.item", 1)),
    ],
)
def test_runtime_defect_turns_suite_red(name, mutate):
    broken = mutate(JS)
    assert broken != JS, f"{name}: mutation did not apply — marker moved?"
    # the static checker accepts all of these
    from kube_scheduler_simulator_tpu.utils import jscheck

    jscheck.check(broken)
    h = make_harness(pods=[pod_obj("p1", node="n1")], nodes=[node_obj("n1")])
    with pytest.raises(ThrowSig):
        h.boot(broken)


# ---- editor pane (editor.js: the reference's monaco role) ---------------


def test_yaml_highlight_classes():
    h = make_harness()
    interp = h.boot(JS)
    out = interp.get_global("yamlHighlight")(
        "# comment\nmetadata:\n  name: pod-1\n  weight: 10\n  note: \"quoted\""
    )
    assert '<span class="y-c"># comment</span>' in out
    assert '<span class="y-k">metadata</span>:' in out
    assert '<span class="y-k">name</span>:' in out
    assert '<span class="y-n"> 10</span>' in out
    assert '<span class="y-s"> "quoted"</span>' in out


def test_edit_object_yaml_roundtrip_and_error_line_marking():
    pod = pod_obj("edit-me", node="n1")
    h = make_harness(pods=[pod], nodes=[node_obj("n1")])
    path = "/api/v1/resources/pods/edit-me?namespace=default"
    h.routes[("GET", path + "&format=yaml")] = "metadata:\n  name: edit-me\n"
    interp = h.boot(JS)
    state_pod = interp.get_global("state")["pods"]["default/edit-me"]
    interp.get_global("editObject")("pods", state_pod)
    ed = interp.get_global("activeEditor")
    assert ed is not None and ed["ta"].value.startswith("metadata:")
    # gutter numbered per line
    assert ed["gutter"].innerHTML.splitlines()[0] == "1"
    # edit + apply -> YAML PUT with the edited body
    h.routes[("PUT", path)] = {}
    ed["ta"].value = "metadata:\n  name: edit-me\n  labels: {a: b}\n"
    ed["ta"].oninput()
    assert ed["gutter"].dataset["count"] == 4  # 3 lines + trailing newline
    apply_btn = _find_button(h.document._by_id["dlgbody"], "Apply")
    apply_btn.click()
    assert ("PUT", path, ed["ta"].value) in h.requests
    assert not h.document._by_id["dlg"].open  # closed on success

    # error path: server rejects with a line-numbered message; the
    # gutter marks the line and the dialog stays open
    interp.get_global("editObject")("pods", state_pod)
    ed = interp.get_global("activeEditor")
    h.routes[("PUT", path)] = (400, "yaml parse error at line 3: bad mapping")
    _find_button(h.document._by_id["dlgbody"], "Apply").click()
    assert '<span class="errline">3</span>' in ed["gutter"].innerHTML


def test_new_resource_template_flows_into_editor():
    h = make_harness()
    h.routes[("GET", "/api/v1/templates/pods")] = "metadata:\n  generateName: pod-\n"
    h.routes[("GET", "/api/v1/templates/nodes")] = "metadata:\n  generateName: node-\n"
    interp = h.boot(JS)
    interp.get_global("newResource")()
    ed = interp.get_global("activeEditor")
    assert "generateName: pod-" in ed["ta"].value
    # switching kind re-loads the template into the live editor
    interp.get_global("loadTemplate")("nodes")
    assert "generateName: node-" in ed["ta"].value
    # create posts the edited YAML
    h.routes[("POST", "/api/v1/resources/pods")] = {}
    ed["ta"].value = "metadata:\n  name: created-1\n"
    _find_button(h.document._by_id["dlgbody"], "Apply").click()
    assert ("POST", "/api/v1/resources/pods", ed["ta"].value) in h.requests


def test_sched_config_editor_posts_parsed_json():
    h = make_harness()
    h.routes[("GET", "/api/v1/schedulerconfiguration")] = {"profiles": [{"schedulerName": "default-scheduler"}]}
    interp = h.boot(JS)
    interp.get_global("openSchedConfig")()
    ed = interp.get_global("activeEditor")
    assert "default-scheduler" in ed["ta"].value
    h.routes[("POST", "/api/v1/schedulerconfiguration")] = {}
    _find_button(h.document._by_id["dlgbody"], "Apply").click()
    posted = next(b for m, p, b in h.requests if (m, p) == ("POST", "/api/v1/schedulerconfiguration"))
    assert json.loads(posted)["profiles"][0]["schedulerName"] == "default-scheduler"


def test_cluster_view_utilization_badges():
    # 1000m requested on a 2000m node -> 50% "cool" badge
    h = make_harness(
        pods=[
            {
                "metadata": {"name": "hot-pod", "namespace": "default"},
                "spec": {"nodeName": "n1", "containers": [{"name": "c", "resources": {"requests": {"cpu": "1000m"}}}]},
            }
        ],
        nodes=[node_obj("n1", cpu="2000m")],
    )
    h.boot(JS)
    badges = _collect_by_class(h.document._by_id["nodes"], "util")
    assert badges and badges[0].textContent == "50%"
    assert "cool" in badges[0].className


def _find_button(root, label):
    for el in _walk(root):
        if getattr(el, "tagName", "") == "BUTTON" and el.textContent == label:
            return el
    raise AssertionError(f"no {label!r} button in dialog")


def _collect_by_class(root, cls):
    return [el for el in _walk(root) if cls in getattr(el, "className", "").split()]


def _walk(el):
    yield el
    for c in getattr(el, "children", []):
        if hasattr(c, "children"):
            yield from _walk(c)


def test_scenario_run_button_posts_and_reopens_with_result():
    scenario = {
        "metadata": {"name": "sc-1", "namespace": "default"},
        "spec": {"operations": [{"id": "op1", "createOperation": {}}]},
    }
    h = make_harness()
    h.routes[("GET", "/api/v1/resources/scenarios")] = {"items": [scenario]}
    finished = dict(scenario, status={"phase": "Succeeded"})
    h.routes[("POST", "/api/v1/scenarios")] = finished
    interp = h.boot(JS)
    obj = interp.get_global("state")["scenarios"]["default/sc-1"]
    interp.get_global("showObject")("scenarios", obj)
    _find_button(h.document._by_id["dlgbody"], "Run").click()
    sent = next(b for m, p, b in h.requests if (m, p) == ("POST", "/api/v1/scenarios"))
    assert json.loads(sent)["metadata"]["name"] == "sc-1"
    # the dialog re-rendered on the finished object
    assert "Succeeded" in collect_text(h.document._by_id["dlgbody"])
