// ---- metrics panel -------------------------------------------------------

async function openMetrics() {
  let text = "";
  try { text = await api("GET", "/api/v1/metrics"); }
  catch (e) { alert(e.message); return; }
  const rows = [];
  for (const line of text.split("\n")) {
    if (!line || line.startsWith("#")) continue;
    const sp = line.lastIndexOf(" ");
    rows.push([line.slice(0, sp), line.slice(sp + 1)]);
  }
  const body = document.getElementById("dlgbody");
  body.innerHTML = `<h2>Metrics</h2>`;
  const tbl = document.createElement("table");
  tbl.className = "kv";
  for (const [k, v] of rows) {
    const tr = document.createElement("tr");
    const td1 = document.createElement("td"); td1.textContent = k;
    const td2 = document.createElement("td"); td2.textContent = v;
    tr.appendChild(td1); tr.appendChild(td2); tbl.appendChild(tr);
  }
  body.appendChild(tbl);
  dlg.showModal();
}
