"""The web UI, served at GET / by the simulator server.

Functional rebuild of the reference's Nuxt2/Vuetify SPA (reference web/,
SURVEY.md §2.2) as a single static page (no build step, no node_modules):

- per-resource views with pods bucketed under their node (or
  "unscheduled"), mirroring web/store/pod.ts:12-50
- per-kind DATA TABLES for every kind (the reference's
  web/components/ResourceViews/DataTables), toggled with the cluster view
- create resources from editable YAML templates served by the backend
  (web/components/lib/templates/*), POSTed as application/yaml; EDIT any
  object as YAML and apply (?format=yaml GET + YAML PUT — the reference's
  monaco editor role, no client-side YAML lib)
- per-pod scheduling-result dialog rendering every
  scheduler-simulator/* annotation, with the result-history annotation
  expanded into a per-attempt viewer (the reference's result dialog)
- scheduler configuration editor (GET/POST /api/v1/schedulerconfiguration)
- export / import / reset buttons
- live updates over the /api/v1/listwatchresources stream
"""

HTML = r"""<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>kube-scheduler-simulator (TPU)</title>
<style>
  :root { --bg:#fafafa; --panel:#fff; --line:#e0e0e0; --accent:#326ce5; --mono:ui-monospace,Menlo,Consolas,monospace; }
  * { box-sizing:border-box; }
  body { margin:0; font:14px/1.45 system-ui,sans-serif; background:var(--bg); color:#222; }
  header { background:var(--accent); color:#fff; padding:10px 16px; display:flex; gap:12px; align-items:center; }
  header h1 { font-size:16px; margin:0 auto 0 0; font-weight:600; }
  button { background:#fff; color:var(--accent); border:1px solid #fff3; border-radius:4px; padding:5px 10px; cursor:pointer; font-weight:600; }
  main button { border-color:var(--accent); }
  main { display:grid; grid-template-columns: 2fr 1fr; gap:12px; padding:12px; }
  .panel { background:var(--panel); border:1px solid var(--line); border-radius:6px; padding:10px 12px; overflow:auto; }
  .node { border:1px solid var(--line); border-radius:6px; margin:8px 0; }
  .node>h3 { margin:0; padding:6px 10px; background:#f0f4ff; font-size:13px; border-bottom:1px solid var(--line); }
  .pod { display:inline-block; margin:6px; padding:4px 10px; background:#e8f0fe; border:1px solid #c6d7fb; border-radius:12px; cursor:pointer; font-size:12px; }
  .pod.unsched { background:#fdecea; border-color:#f6c8c4; }
  .kindrow { margin:4px 0; } .kindrow b { display:inline-block; width:160px; }
  .item { display:inline-block; margin:2px; padding:2px 8px; border:1px solid var(--line); border-radius:10px; font-size:12px; cursor:pointer; }
  dialog { width:min(900px,90vw); border:1px solid var(--line); border-radius:8px; }
  pre, textarea { font-family:var(--mono); font-size:12px; }
  textarea { width:100%; min-height:220px; }
  table.kv { border-collapse:collapse; width:100%; } .kv td { border-bottom:1px solid var(--line); padding:4px 6px; vertical-align:top; }
  .kv td:first-child { white-space:nowrap; color:#555; }
  .muted { color:#777; font-size:12px; }
  h2 { font-size:14px; margin:4px 0 8px; }
</style>
</head>
<body>
<header>
  <h1>kube-scheduler-simulator <span class="muted" style="color:#cfe0ff">TPU-native</span></h1>
  <input id="search" type="search" placeholder="filter…" style="border:none;border-radius:4px;padding:5px 8px;min-width:140px" oninput="onSearch()">
  <button id="viewtoggle" onclick="toggleView()">Tables</button>
  <button onclick="openMetrics()">Metrics</button>
  <button onclick="newResource()">+ Create</button>
  <button onclick="openSchedConfig()">Scheduler&nbsp;Config</button>
  <button onclick="doExport()">Export</button>
  <button onclick="doImport()">Import</button>
  <button onclick="doReset()">Reset</button>
</header>
<main id="clusterview">
  <div class="panel">
    <h2>Nodes &amp; Pods</h2>
    <div id="nodes"></div>
  </div>
  <div class="panel">
    <h2>Other resources</h2>
    <div id="others"></div>
  </div>
</main>
<main id="tablesview" style="display:none; grid-template-columns:1fr;">
  <div class="panel"><div id="tables"></div></div>
</main>
<dialog id="dlg"><div id="dlgbody"></div><p style="text-align:right"><button onclick="dlg.close()">Close</button></p></dialog>
<script src="/webui.js"></script>

</body>
</html>
"""

# The UI behavior, served as its own asset at /webui.js (kept out of
# the inline page so the server tests can assert on it directly).
JS = r"""const KINDS = ["pods","nodes","persistentvolumes","persistentvolumeclaims","storageclasses","priorityclasses","namespaces","deployments","replicasets","scenarios"];
const state = Object.fromEntries(KINDS.map(k=>[k,{}]));
const dlg = document.getElementById("dlg");
const key = o => (o.metadata.namespace? o.metadata.namespace+"/" : "") + o.metadata.name;

async function api(method, path, body, ctype) {
  // JSON round-trip by default; string bodies pass through raw (the YAML
  // create/edit paths set ctype="application/yaml"), and non-JSON
  // responses (?format=yaml, templates) come back as text
  const raw = typeof body === "string";
  const r = await fetch(path, {method, headers:{"Content-Type": ctype || "application/json"},
                               body: body===undefined? undefined : (raw? body : JSON.stringify(body))});
  const text = await r.text();
  if (!r.ok) throw new Error(text || r.status);
  if (!text) return null;
  return (r.headers.get("Content-Type")||"").includes("json") ? JSON.parse(text) : text;
}

async function refreshAll() {
  for (const k of KINDS) {
    const lst = await api("GET", `/api/v1/resources/${k}`);
    state[k] = {};
    for (const o of lst.items) state[k][key(o)] = o;
  }
  render();
}

let filterText = "";
let searchTimer = null;
function onSearch() {
  // debounced: at benchmark scale a per-keystroke full re-render of
  // thousands of DOM nodes would freeze the tab
  clearTimeout(searchTimer);
  searchTimer = setTimeout(() => {
    filterText = document.getElementById("search").value.toLowerCase();
    render();
  }, 150);
}
function matchesFilter(o) {
  if (!filterText) return true;
  const hay = key(o).toLowerCase() + " " + JSON.stringify(o.metadata.labels || {}).toLowerCase();
  return hay.includes(filterText);
}

function render() {
  if (tablesMode) { renderTables(); return; }
  const nodesDiv = document.getElementById("nodes");
  nodesDiv.innerHTML = "";
  const buckets = {"(unscheduled)": []};
  for (const n of Object.values(state.nodes)) buckets[n.metadata.name] = [];
  for (const p of Object.values(state.pods)) {
    if (!matchesFilter(p)) continue;
    const nn = (p.spec||{}).nodeName;
    (buckets[nn] || buckets["(unscheduled)"]).push(p);
  }
  for (const [nodeName, pods] of Object.entries(buckets)) {
    if (nodeName === "(unscheduled)" && !pods.length) continue;
    const div = document.createElement("div");
    div.className = "node";
    const node = state.nodes[nodeName];
    const h = document.createElement("h3");
    h.textContent = nodeName + (node ? `  —  cpu ${((node.status||{}).allocatable||{}).cpu||"?"} / mem ${((node.status||{}).allocatable||{}).memory||"?"}` : "");
    if (node) { h.style.cursor = "pointer"; h.onclick = () => showNode(node); }
    div.appendChild(h);
    for (const p of pods) {
      const s = document.createElement("span");
      s.className = "pod" + (nodeName === "(unscheduled)" ? " unsched" : "");
      s.textContent = key(p);
      s.onclick = () => showPod(p);
      div.appendChild(s);
    }
    nodesDiv.appendChild(div);
  }
  const others = document.getElementById("others");
  others.innerHTML = "";
  for (const k of KINDS) {
    if (k === "pods" || k === "nodes") continue;
    const row = document.createElement("div");
    row.className = "kindrow";
    row.innerHTML = `<b>${k}</b>`;
    for (const o of Object.values(state[k])) {
      if (!matchesFilter(o)) continue;
      const s = document.createElement("span");
      s.className = "item";
      s.textContent = key(o);
      s.onclick = () => showObject(k, o);
      row.appendChild(s);
    }
    others.appendChild(row);
  }
}


// ---- node detail: capacity vs requested, with usage bars ----------------

function parseCpu(v) {
  if (v === undefined || v === null || v === "") return 0;
  v = String(v);
  return v.endsWith("m") ? parseFloat(v) / 1000 : parseFloat(v);
}
function parseMem(v) {
  if (!v) return 0;
  // kube resource.Quantity suffixes: binary Ki..Ei, decimal k/M/G/T/P/E,
  // and milli (m)
  const m = String(v).match(/^([0-9.]+)(Ki|Mi|Gi|Ti|Pi|Ei|k|M|G|T|P|E|m)?$/);
  if (!m) return parseFloat(v) || 0;
  const mult = {Ki: 2**10, Mi: 2**20, Gi: 2**30, Ti: 2**40, Pi: 2**50, Ei: 2**60,
                k: 1e3, M: 1e6, G: 1e9, T: 1e12, P: 1e15, E: 1e18, m: 1e-3}[m[2]] || 1;
  return parseFloat(m[1]) * mult;
}
function bar(frac, label) {
  const pct = Math.min(100, Math.round(frac * 100));
  const color = pct > 90 ? "#d93025" : pct > 70 ? "#f9ab00" : "#1e8e3e";
  return `<div style="margin:4px 0"><span class="muted">${esc(label)} — ${pct}%</span>
    <div style="background:#eee;border-radius:4px;height:10px"><div style="width:${pct}%;background:${color};height:10px;border-radius:4px"></div></div></div>`;
}

function showNode(node) {
  const name = node.metadata.name;
  const alloc = (node.status||{}).allocatable || {};
  const pods = Object.values(state.pods).filter(p => (p.spec||{}).nodeName === name);
  let cpuReq = 0, memReq = 0;
  for (const p of pods) {
    for (const c of (p.spec||{}).containers || []) {
      const r = ((c.resources||{}).requests) || {};
      cpuReq += parseCpu(r.cpu); memReq += parseMem(r.memory);
    }
  }
  const cpuCap = parseCpu(alloc.cpu), memCap = parseMem(alloc.memory);
  const body = document.getElementById("dlgbody");
  body.innerHTML = `<h2>Node / ${esc(name)}</h2>` +
    bar(cpuCap ? cpuReq / cpuCap : 0, `cpu ${cpuReq.toFixed(2)} / ${esc(alloc.cpu||"?")}`) +
    bar(memCap ? memReq / memCap : 0, `memory ${(memReq/2**30).toFixed(2)}Gi / ${esc(alloc.memory||"?")}`) +
    bar((parseFloat(alloc.pods)||0) ? pods.length / parseFloat(alloc.pods) : 0,
        `pods ${pods.length} / ${esc(alloc.pods||"?")}`) +
    `<p class="muted">taints: ${esc((((node.spec||{}).taints)||[]).map(t=>`${t.key}=${t.value}:${t.effect}`).join(", ") || "none")}</p>`;
  const list = document.createElement("div");
  for (const p of pods) {
    const sp = document.createElement("span");
    sp.className = "pod"; sp.textContent = key(p); sp.onclick = () => showPod(p);
    list.appendChild(sp);
  }
  body.appendChild(list);
  body.appendChild(editButton("nodes", node));
  const raw = document.createElement("pre");
  raw.textContent = JSON.stringify(node, null, 2);
  body.appendChild(raw);
  dlg.showModal();
}

// ---- metrics panel -------------------------------------------------------

async function openMetrics() {
  let text = "";
  try { text = await api("GET", "/api/v1/metrics"); }
  catch (e) { alert(e.message); return; }
  const rows = [];
  for (const line of text.split("\n")) {
    if (!line || line.startsWith("#")) continue;
    const sp = line.lastIndexOf(" ");
    rows.push([line.slice(0, sp), line.slice(sp + 1)]);
  }
  const body = document.getElementById("dlgbody");
  body.innerHTML = `<h2>Metrics</h2>`;
  const tbl = document.createElement("table");
  tbl.className = "kv";
  for (const [k, v] of rows) {
    const tr = document.createElement("tr");
    const td1 = document.createElement("td"); td1.textContent = k;
    const td2 = document.createElement("td"); td2.textContent = v;
    tr.appendChild(td1); tr.appendChild(td2); tbl.appendChild(tr);
  }
  body.appendChild(tbl);
  dlg.showModal();
}

function esc(s){ return String(s).replace(/&/g,"&amp;").replace(/</g,"&lt;"); }

let tablesMode = false;
function toggleView() {
  tablesMode = !tablesMode;
  document.getElementById("clusterview").style.display = tablesMode ? "none" : "";
  document.getElementById("tablesview").style.display = tablesMode ? "grid" : "";
  document.getElementById("viewtoggle").textContent = tablesMode ? "Cluster" : "Tables";
  render();
}

// column extractors per kind (the reference's DataTables headers)
const TABLE_COLS = {
  pods: [["namespace", o=>(o.metadata||{}).namespace||""], ["name", o=>o.metadata.name],
         ["node", o=>(o.spec||{}).nodeName||""], ["phase", o=>(o.status||{}).phase||""],
         ["cpu req", o=>{try{return o.spec.containers[0].resources.requests.cpu||""}catch(e){return ""}}],
         ["selectedNode", o=>((o.metadata||{}).annotations||{})["scheduler-simulator/selected-node"]||""]],
  nodes: [["name", o=>o.metadata.name], ["cpu", o=>{try{return o.status.allocatable.cpu}catch(e){return ""}}],
          ["memory", o=>{try{return o.status.allocatable.memory}catch(e){return ""}}],
          ["pods", o=>{try{return o.status.allocatable.pods}catch(e){return ""}}],
          ["taints", o=>(((o.spec||{}).taints)||[]).map(t=>t.key).join(",")]],
  persistentvolumes: [["name", o=>o.metadata.name], ["capacity", o=>{try{return o.spec.capacity.storage}catch(e){return ""}}],
                      ["class", o=>(o.spec||{}).storageClassName||""], ["claim", o=>{try{return o.spec.claimRef.name}catch(e){return ""}}]],
  persistentvolumeclaims: [["namespace", o=>(o.metadata||{}).namespace||""], ["name", o=>o.metadata.name],
                           ["class", o=>(o.spec||{}).storageClassName||""], ["phase", o=>(o.status||{}).phase||""]],
  storageclasses: [["name", o=>o.metadata.name], ["provisioner", o=>o.provisioner||""]],
  priorityclasses: [["name", o=>o.metadata.name], ["value", o=>o.value]],
  namespaces: [["name", o=>o.metadata.name], ["phase", o=>(o.status||{}).phase||""]],
  deployments: [["namespace", o=>(o.metadata||{}).namespace||""], ["name", o=>o.metadata.name],
                ["replicas", o=>(o.spec||{}).replicas]],
  replicasets: [["namespace", o=>(o.metadata||{}).namespace||""], ["name", o=>o.metadata.name],
                ["replicas", o=>(o.spec||{}).replicas]],
  scenarios: [["namespace", o=>(o.metadata||{}).namespace||""], ["name", o=>o.metadata.name],
              ["phase", o=>(o.status||{}).phase||"(queued)"],
              ["operations", o=>(((o.spec||{}).operations)||[]).length]],
};

function renderTables() {
  const root = document.getElementById("tables");
  root.innerHTML = "";
  for (const k of KINDS) {
    const cols = TABLE_COLS[k] || [["name", o=>o.metadata.name]];
    const objs = Object.values(state[k]).filter(matchesFilter);
    const h = document.createElement("h2");
    h.textContent = `${k} (${objs.length})`;
    root.appendChild(h);
    const tbl = document.createElement("table");
    tbl.className = "kv";
    tbl.dataset.kind = k;
    const hr = document.createElement("tr");
    for (const [label] of cols) {
      const th = document.createElement("td");
      th.innerHTML = `<b>${esc(label)}</b>`;
      hr.appendChild(th);
    }
    tbl.appendChild(hr);
    for (const o of objs) {
      const tr = document.createElement("tr");
      tr.style.cursor = "pointer";
      tr.addEventListener("click", () => k === "pods" ? showPod(o) : showObject(k, o));
      for (const [, fn] of cols) {
        const td = document.createElement("td");
        let v = ""; try { v = fn(o); } catch (e) {}
        td.textContent = v === undefined ? "" : v;
        tr.appendChild(td);
      }
      tbl.appendChild(tr);
    }
    root.appendChild(tbl);
  }
}

function deleteButton(kind, k) {
  // built via DOM (not inline onclick) so stored object names can't inject
  // script through attribute strings
  const b = document.createElement("button");
  b.textContent = "Delete";
  b.addEventListener("click", () => del(kind, k));
  const p = document.createElement("p");
  p.appendChild(b);
  return p;
}

function historyViewer(annos) {
  // result-history is a JSON array of per-attempt maps; render newest
  // last, one expandable block per attempt (the reference appends every
  // scheduling attempt's full result set, storereflector.go:148-167)
  const raw = annos["scheduler-simulator/result-history"];
  if (!raw) return "";
  let hist;
  try { hist = JSON.parse(raw); } catch (e) { return ""; }
  if (!Array.isArray(hist)) return "";
  let out = `<h3 style="margin:10px 0 4px">result history (${hist.length} attempt${hist.length===1?"":"s"})</h3>`;
  hist.forEach((attempt, idx) => {
    let rows = "";
    for (const [k,v] of Object.entries(attempt)) {
      let pretty = v;
      try { pretty = JSON.stringify(JSON.parse(v), null, 1); } catch (e) {}
      rows += `<tr><td>${esc(String(k).replace("scheduler-simulator/",""))}</td><td><pre style="margin:0;white-space:pre-wrap">${esc(pretty)}</pre></td></tr>`;
    }
    out += `<details ${idx===hist.length-1?"open":""}><summary>attempt ${idx+1}</summary><table class="kv">${rows}</table></details>`;
  });
  return out;
}

function showPod(p) {
  const annos = (p.metadata||{}).annotations || {};
  let rows = "";
  for (const [k,v] of Object.entries(annos)) {
    if (!k.startsWith("scheduler-simulator/") || k === "scheduler-simulator/result-history") continue;
    let pretty = v;
    try { pretty = JSON.stringify(JSON.parse(v), null, 1); } catch (e) {}
    rows += `<tr><td>${esc(k.replace("scheduler-simulator/",""))}</td><td><pre style="margin:0;white-space:pre-wrap">${esc(pretty)}</pre></td></tr>`;
  }
  const body = document.getElementById("dlgbody");
  body.innerHTML =
    `<h2>Pod ${esc(key(p))} — scheduling results</h2>
     <p class="muted">node: ${esc((p.spec||{}).nodeName||"(unscheduled)")}</p>
     <table class="kv">${rows || "<tr><td>no scheduler-simulator/* annotations yet</td></tr>"}</table>
     ${historyViewer(annos)}
     <details><summary>manifest</summary><pre>${esc(JSON.stringify(p,null,2))}</pre></details>`;
  body.appendChild(editButton("pods", p));
  body.appendChild(deleteButton("pods", key(p)));
  dlg.showModal();
}

function showObject(kind, o) {
  const body = document.getElementById("dlgbody");
  body.innerHTML =
    `<h2>${esc(kind)} / ${esc(key(o))}</h2>
     <pre>${esc(JSON.stringify(o,null,2))}</pre>`;
  body.appendChild(editButton(kind, o));
  body.appendChild(deleteButton(kind, key(o)));
  dlg.showModal();
}

function editButton(kind, o) {
  const b = document.createElement("button");
  b.textContent = "Edit";
  b.addEventListener("click", () => editObject(kind, o));
  const p = document.createElement("p");
  p.appendChild(b);
  return p;
}

async function editObject(kind, o) {
  // YAML round-trip through the backend (?format=yaml GET, YAML PUT) —
  // the reference's monaco editor role, no client-side YAML lib needed
  const ns = (o.metadata||{}).namespace;
  const path = `/api/v1/resources/${kind}/${o.metadata.name}` + (ns?`?namespace=${ns}`:"");
  let yamlText;
  try {
    yamlText = await api("GET", path + (ns?"&":"?") + "format=yaml");
  } catch (e) { alert(e.message); return; }
  const body = document.getElementById("dlgbody");
  body.innerHTML = `<h2>Edit ${esc(kind)} / ${esc(key(o))} (YAML)</h2>`;
  const ta = document.createElement("textarea");
  ta.id = "editbody";
  ta.value = yamlText;
  ta.style.minHeight = "340px";
  body.appendChild(ta);
  const b = document.createElement("button");
  b.textContent = "Apply";
  b.addEventListener("click", async () => {
    try {
      await api("PUT", path, ta.value, "application/yaml");
      dlg.close();
    } catch (e) { alert(e.message); }
  });
  const p = document.createElement("p");
  p.appendChild(b);
  body.appendChild(p);
  dlg.showModal();
}

async function del(kind, k) {
  const [ns, name] = k.includes("/") ? k.split("/") : [null, k];
  await api("DELETE", `/api/v1/resources/${kind}/${name}` + (ns?`?namespace=${ns}`:""));
  dlg.close();
}

// Creation templates are YAML served by the backend (the reference ships
// web/components/lib/templates/*.yaml); bodies POST as application/yaml.
const TEMPLATE_KINDS = ["pods","nodes","deployments","persistentvolumes","persistentvolumeclaims","storageclasses","priorityclasses","namespaces","scenarios"];

async function loadTemplate(kind) {
  document.getElementById("newbody").value = await api("GET", `/api/v1/templates/${kind}`);
}

async function newResource() {
  const opts = TEMPLATE_KINDS.map(k=>`<option>${k}</option>`).join("");
  document.getElementById("dlgbody").innerHTML =
    `<h2>Create resource (YAML)</h2>
     <p><select id="newkind" onchange="loadTemplate(this.value)">${opts}</select></p>
     <textarea id="newbody"></textarea>
     <p><button onclick="createResource()">Create</button></p>`;
  await loadTemplate("pods");
  dlg.showModal();
}

async function createResource() {
  const kind = document.getElementById("newkind").value;
  try {
    await api("POST", `/api/v1/resources/${kind}`,
              document.getElementById("newbody").value, "application/yaml");
    dlg.close();
  } catch (e) { alert(e.message); }
}

async function openSchedConfig() {
  const cfg = await api("GET", "/api/v1/schedulerconfiguration");
  document.getElementById("dlgbody").innerHTML =
    `<h2>KubeSchedulerConfiguration</h2>
     <p class="muted">POST honors only .profiles (reference behavior)</p>
     <textarea id="schedcfg">${esc(JSON.stringify(cfg,null,2))}</textarea>
     <p><button onclick="applySchedConfig()">Apply</button></p>`;
  dlg.showModal();
}

async function applySchedConfig() {
  try {
    await api("POST", "/api/v1/schedulerconfiguration", JSON.parse(document.getElementById("schedcfg").value));
    dlg.close();
  } catch (e) { alert(e.message); }
}

async function doExport() {
  const snap = await api("GET", "/api/v1/export");
  const blob = new Blob([JSON.stringify(snap, null, 2)], {type: "application/json"});
  const a = Object.assign(document.createElement("a"), {href: URL.createObjectURL(blob), download: "snapshot.json"});
  a.click();
}

function doImport() {
  const inp = Object.assign(document.createElement("input"), {type: "file", accept: ".json"});
  inp.onchange = async () => {
    const text = await inp.files[0].text();
    await api("POST", "/api/v1/import", JSON.parse(text));
  };
  inp.click();
}

async function doReset() { if (confirm("Reset the simulator?")) await api("PUT", "/api/v1/reset"); }

async function watchLoop() {
  while (true) {
    try {
      const resp = await fetch("/api/v1/listwatchresources");
      const reader = resp.body.getReader();
      const decoder = new TextDecoder();
      let buf = "";
      for (;;) {
        const {done, value} = await reader.read();
        if (done) break;
        buf += decoder.decode(value, {stream: true});
        const lines = buf.split("\n");
        buf = lines.pop();
        let dirty = false;
        for (const line of lines) {
          if (!line.trim()) continue;
          const ev = JSON.parse(line);
          const k = key(ev.Obj);
          if (!(ev.Kind in state)) continue;
          if (ev.EventType === "DELETED") delete state[ev.Kind][k];
          else state[ev.Kind][k] = ev.Obj;
          dirty = true;
        }
        if (dirty) render();
      }
    } catch (e) { /* server restart — retry */ }
    await new Promise(r => setTimeout(r, 1000));
  }
}

// deployments/replicasets/scenarios are kinds the watch stream doesn't
// carry (it mirrors the reference's 7 kinds) — poll them instead.
async function pollWorkloads() {
  for (;;) {
    try {
      for (const k of ["deployments", "replicasets", "scenarios"]) {
        const lst = await api("GET", `/api/v1/resources/${k}`);
        state[k] = {};
        for (const o of lst.items) state[k][key(o)] = o;
      }
      render();
    } catch (e) {}
    await new Promise(r => setTimeout(r, 3000));
  }
}

refreshAll().then(() => { watchLoop(); pollWorkloads(); });
"""


# YAML creation templates per store kind, served at /api/v1/templates/{kind}
# (the role of the reference's web/components/lib/templates/*.yaml files).
# generateName is honored by the store with a deterministic counter suffix.
TEMPLATES_YAML = {
    "pods": """metadata:
  generateName: pod-
  namespace: default
  labels: {}
spec:
  containers:
    - name: main
      image: registry.k8s.io/pause:3.5
      resources:
        requests:
          cpu: 100m
          memory: 128Mi
  restartPolicy: Always
""",
    "nodes": """metadata:
  generateName: node-
  labels:
    topology.kubernetes.io/zone: zone-a
spec: {}
status:
  capacity:
    cpu: "4"
    memory: 32Gi
    pods: "110"
  allocatable:
    cpu: "4"
    memory: 32Gi
    pods: "110"
""",
    "deployments": """metadata:
  generateName: deployment-
  namespace: default
spec:
  replicas: 3
  selector:
    matchLabels:
      app: example
  template:
    metadata:
      labels:
        app: example
    spec:
      containers:
        - name: main
          resources:
            requests:
              cpu: 100m
              memory: 128Mi
""",
    "persistentvolumes": """metadata:
  generateName: pv-
spec:
  capacity:
    storage: 1Gi
  accessModes:
    - ReadWriteOnce
  persistentVolumeReclaimPolicy: Delete
  storageClassName: standard
""",
    "persistentvolumeclaims": """metadata:
  generateName: pvc-
  namespace: default
spec:
  accessModes:
    - ReadWriteOnce
  storageClassName: standard
  resources:
    requests:
      storage: 1Gi
""",
    "storageclasses": """metadata:
  generateName: storageclass-
provisioner: kubernetes.io/no-provisioner
volumeBindingMode: WaitForFirstConsumer
reclaimPolicy: Delete
""",
    "priorityclasses": """metadata:
  generateName: priorityclass-
value: 1000000
globalDefault: false
""",
    "namespaces": """metadata:
  generateName: namespace-
""",
    "scenarios": """metadata:
  generateName: scenario-
  namespace: default
spec:
  operations:
    - id: "1"
      step:
        major: 1
      createOperation:
        typeMeta:
          kind: Node
        object:
          metadata:
            generateName: node-
          status:
            allocatable:
              cpu: "4"
              memory: 32Gi
              pods: "110"
    - id: "2"
      step:
        major: 2
      createOperation:
        typeMeta:
          kind: Pod
        object:
          metadata:
            generateName: pod-
            namespace: default
          spec:
            containers:
              - name: main
                resources:
                  requests:
                    cpu: 100m
                    memory: 128Mi
    - id: "3"
      step:
        major: 3
      doneOperation: {}
""",
}
