"""Differential scenario fuzzer + chaos engine (fuzz/).

Covers the seeded composite generator (determinism, composition floor,
coverage-bucket diversity), the shrinker's determinism pin (same seed +
same divergence -> byte-identical minimized fixture), the differential
runner's byte-parity verdicts, the chaos degrade (injected kernel
failures must fall back to the sequential path at exact parity, counted),
the committed-fixture replay (every file under fuzz/fixtures/ re-runs in
tier-1 against its exact expected bytes), and the /metrics wiring.
"""

import json

import pytest

from kube_scheduler_simulator_tpu.fuzz import (
    FEATURES,
    MIN_COMPOSE,
    CoverageMap,
    FuzzHarness,
    KernelChaos,
    canonical_json,
    encode_state,
    fuzz_knobs,
    generate_scenario,
    iter_fixture_paths,
    load_fixture,
    make_fixture,
    replay_fixture,
    run_differential,
    shrink,
)
from kube_scheduler_simulator_tpu.fuzz.coverage import all_buckets
from kube_scheduler_simulator_tpu.fuzz.verdict import diff_states, gate_delta


# one long-lived harness for the whole module: services (and their
# compiled executables) are the expensive part, scenarios are not
@pytest.fixture(scope="module")
def harness():
    return FuzzHarness()


class TestCoverage:
    def test_bucket_lattice(self):
        # C(5,3) + C(5,4) + C(5,5)
        assert len(all_buckets()) == 16

    def test_choose_features_seeks_unseen_buckets(self):
        import random

        cov = CoverageMap()
        rng = random.Random(0)
        seen = set()
        for _ in range(30):
            feats = cov.choose_features(rng)
            assert len(feats) >= MIN_COMPOSE
            assert feats <= set(FEATURES)
            cov.note(feats)
            seen.add(feats)
        # diversity-seeking sampling must spread over the 16-bucket
        # lattice instead of piling onto a mode
        assert len(seen) >= 12

    def test_deterministic_under_rng(self):
        import random

        a = CoverageMap().choose_features(random.Random(7))
        b = CoverageMap().choose_features(random.Random(7))
        assert a == b

    def test_exec_mode_bucket_extends_lattice_without_biasing_sampling(self):
        """The mesh×stream execution tag lands in the coverage summary
        as its own bucket but never leaks into the generator's
        least-covered feature sampling."""
        import random

        from kube_scheduler_simulator_tpu.fuzz.coverage import MESH_STREAM

        cov = CoverageMap()
        feats = frozenset({"churn", "retune", "preemption"})
        cov.note(feats)
        cov.note_exec(feats, MESH_STREAM)
        summary = cov.summary()
        assert summary["churn+preemption+retune"] == 1
        assert summary[f"churn+{MESH_STREAM}+preemption+retune"] == 1
        # sampling still draws from the plain FEATURES lattice only
        for _ in range(20):
            chosen = cov.choose_features(random.Random(3))
            assert MESH_STREAM not in chosen


class TestGenerator:
    def test_byte_deterministic(self):
        a = generate_scenario(3, 1)
        b = generate_scenario(3, 1)
        assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)

    def test_composition_floor_and_shape(self):
        cov = CoverageMap()
        for i in range(8):
            scn = generate_scenario(0, i, coverage=cov)
            assert len(scn["features"]) >= MIN_COMPOSE
            assert scn["profile"] == ("gang" if "gang" in scn["features"] else "default")
            for ops in scn["ticks"]:
                for op in ops:
                    assert op["op"] in ("create", "delete", "patch", "weights")
                    if op["op"] == "create" and op["kind"] == "pods":
                        # PrioritySort tie-breaks on creationTimestamp:
                        # every pod must carry an explicit deterministic one
                        assert op["object"]["metadata"]["creationTimestamp"]

    def test_churn_deletes_only_settled_pods(self):
        # the stream-feed phase-insensitivity rule: a delete may only
        # target a pod created >= 2 ticks earlier
        for i in range(12):
            scn = generate_scenario(1, i)
            created_at = {}
            for t, ops in enumerate(scn["ticks"]):
                for op in ops:
                    if op["op"] == "create" and op["kind"] == "pods":
                        created_at[op["object"]["metadata"]["name"]] = t
                    if op["op"] == "delete" and op["kind"] == "pods":
                        name = op["name"]
                        if name in created_at:  # gang completions checked too
                            assert t - created_at[name] >= 2, (scn["name"], name)

    def test_features_override(self):
        scn = generate_scenario(0, 0, features=frozenset({"churn", "retune", "preemption"}))
        assert sorted(scn["features"]) == ["churn", "preemption", "retune"]


class TestShrinker:
    def _scenario(self):
        ticks = []
        for t in range(5):
            ops = [
                {"op": "create", "kind": "nodes", "object": {"metadata": {"name": f"n{t}-{j}"}}}
                for j in range(3)
            ]
            ops.append({"op": "delete", "kind": "pods", "name": f"p{t}", "namespace": "default"})
            ticks.append(ops)
        ticks[2].append({"op": "weights", "weights": {"NodeResourcesFit": 2.0}})
        return {"name": "synthetic", "features": ["churn"], "stepSeconds": 1.0, "ticks": ticks}

    @staticmethod
    def _fails(s):
        # "diverges" iff the weights op survives AND >= 2 node creates do
        has_w = any(op["op"] == "weights" for t in s["ticks"] for op in t)
        nodes = sum(1 for t in s["ticks"] for op in t if op.get("kind") == "nodes")
        return has_w and nodes >= 2

    def test_deterministic_minimization(self):
        # the satellite pin: same divergence -> byte-identical minimized
        # scenario (and fixture bytes)
        a, sa = shrink(self._scenario(), self._fails)
        b, sb = shrink(self._scenario(), self._fails)
        assert canonical_json(a) == canonical_json(b)
        assert sa == sb
        fx_a = make_fixture(a, ("batch-vs-oracle",), expected=[], note="pin")
        fx_b = make_fixture(b, ("batch-vs-oracle",), expected=[], note="pin")
        assert canonical_json(fx_a) == canonical_json(fx_b)

    def test_minimal_result_still_fails_and_is_1_minimal(self):
        mini, _ = shrink(self._scenario(), self._fails)
        assert self._fails(mini)
        ops = sum(len(t) for t in mini["ticks"])
        assert ops == 3  # the weights op + exactly 2 node creates
        # removing ANY single op flips the predicate
        for ti in range(len(mini["ticks"])):
            for oi in range(len(mini["ticks"][ti])):
                ticks = [list(t) for t in mini["ticks"]]
                del ticks[ti][oi]
                assert not self._fails({**mini, "ticks": ticks})

    def test_budget_bounds_checks(self):
        calls = {"n": 0}

        def fails(s):
            calls["n"] += 1
            return self._fails(s)

        _mini, stats = shrink(self._scenario(), fails, max_checks=5)
        assert stats["checks"] == 5 == calls["n"]

    def test_knobs_validate(self, monkeypatch):
        monkeypatch.setenv("KSS_FUZZ_SHRINK_STEPS", "not-a-number")
        with pytest.raises(ValueError, match="KSS_FUZZ_SHRINK_STEPS"):
            fuzz_knobs()
        monkeypatch.setenv("KSS_FUZZ_SHRINK_STEPS", "64")
        monkeypatch.setenv("KSS_FUZZ_SEED", "3")
        k = fuzz_knobs()
        assert k["shrink_steps"] == 64 and k["seed"] == 3


class TestDifferentialParity:
    def test_composite_parity_both_comparisons(self, harness):
        scn = generate_scenario(11, 0, features=frozenset({"preemption", "churn", "retune"}))
        v, states = run_differential(scn, harness)
        assert v["divergences"] == []
        assert {c["kind"] for c in v["comparisons"]} == {"batch-vs-oracle", "stream-vs-serial"}
        for c in v["comparisons"]:
            assert c["equal"] and c["mismatch_count"] == 0 and c["first_mismatch"] is None
        # the runner actually scheduled pods on every path
        assert any(node for node, *_ in states["oracle"].values())
        assert states["oracle"].keys() == states["batch"].keys()

    def test_gang_composite_parity(self, harness):
        scn = generate_scenario(11, 1, features=frozenset({"gang", "churn", "retune"}))
        v, _states = run_differential(scn, harness, comparisons=("batch-vs-oracle",))
        assert v["divergences"] == []

    def test_shard_stream_fusion_parity(self, harness):
        """The stream × mesh fusion as a first-class comparison: the
        timeline streamed on a 2-device sharded engine, byte-identical
        to the serial single-device projection, with the sharded
        streamed dispatches demonstrably engaged."""
        scn = generate_scenario(11, 2, features=frozenset({"preemption", "churn", "retune"}))
        v, states = run_differential(scn, harness, comparisons=("shard-stream-vs-serial",))
        assert v["divergences"] == []
        assert {c["kind"] for c in v["comparisons"]} == {"shard-stream-vs-serial"}
        assert states["shard-stream"].keys() == states["shard-stream-off"].keys()
        _store, svc = harness.service("default", "shard-stream")
        m = svc.metrics()
        assert m["sharded_dispatches_total"] > 0
        assert m["stream_waves_total"] > 0

    def test_diff_states_reports_first_mismatch(self):
        a = {"default/p": ("n1", (("k", "v"),), "c")}
        b = {"default/p": ("n2", (("k", "v"),), "c")}
        d = diff_states(a, b)
        assert len(d) == 1 and d[0]["pod"] == "default/p"
        assert d[0]["a"][0] == "n1" and d[0]["b"][0] == "n2"

    def test_gate_delta(self):
        before = {"batch_fallbacks": {"x": 1}}
        after = {"batch_fallbacks": {"x": 3, "y": 1}}
        assert gate_delta(before, after) == {"batch_fallbacks": {"x": 2, "y": 1}}


class TestChaos:
    def test_batch_chaos_degrades_at_exact_parity(self, harness):
        scn = generate_scenario(12, 0, features=frozenset({"preemption", "churn", "retune"}))
        store, svc = harness.reset("default", "batch")
        with KernelChaos(svc, fail_events={0}) as kc:
            from kube_scheduler_simulator_tpu.fuzz.runner import run_ticks

            state_chaos = run_ticks(scn, store, svc)
        assert kc.trips == 1
        # degrade is COUNTED — nonzero without injected chaos = bug
        assert svc.stats["batch_fallbacks"].get("kernel error: ChaosError", 0) >= 1
        # the proxy uninstalled cleanly
        assert "_engine_for" not in svc.__dict__
        store_o, svc_o = harness.reset("default", "oracle")
        from kube_scheduler_simulator_tpu.fuzz.runner import run_ticks as rt

        state_oracle = rt(scn, store_o, svc_o)
        assert diff_states(state_chaos, state_oracle) == []

    def test_stream_chaos_drains_and_matches_serial(self, harness):
        scn = generate_scenario(12, 1, features=frozenset({"churn", "retune", "preemption"}))
        v, _ = run_differential(
            scn, harness,
            comparisons=("stream-vs-serial",),
            chaos={"roles": ["stream-on"], "fail_events": [1, 4]},
        )
        assert v["divergences"] == []
        explained = v["comparisons"][0]["explained"]
        drains = explained.get("stream_drains_by_reason", {})
        kerr = {r: n for r, n in drains.items() if r.startswith("kernel error")}
        fallbacks = explained.get("batch_fallbacks", {})
        kerr.update({r: n for r, n in fallbacks.items() if r.startswith("kernel error")})
        assert kerr, f"chaos degrade not counted: {explained}"


class TestFixtures:
    def test_fixtures_committed(self):
        assert len(iter_fixture_paths()) >= 2

    @pytest.mark.parametrize("path", iter_fixture_paths(), ids=lambda p: p.rsplit("/", 1)[-1])
    def test_fixture_replays_to_exact_bytes(self, path):
        # a committed fixture can never silently regress: the replay must
        # show zero divergence AND reproduce the recorded bytes exactly
        fx = load_fixture(path)
        v, oracle_encoded = replay_fixture(fx)
        assert v["divergences"] == [], f"{fx['name']}: {v['comparisons']}"
        assert oracle_encoded == fx["expected"], f"{fx['name']}: expected bytes drifted"


class TestMetricsWiring:
    def test_note_fuzz_report_and_prometheus_render(self):
        from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
        from kube_scheduler_simulator_tpu.server.metrics import render_metrics
        from kube_scheduler_simulator_tpu.state.store import ClusterStore
        from kube_scheduler_simulator_tpu.utils import SimClock

        store = ClusterStore(clock=SimClock(0.0))
        svc = SchedulerService(store, use_batch="off", clock=SimClock(0.0))
        svc.start_scheduler(None)
        svc.note_fuzz_report(
            {"scenarios": 5, "divergences": {"stream-vs-serial": 1}, "shrink_steps": 7}
        )
        svc.note_fuzz_report({"scenarios": 2})
        m = svc.metrics()
        assert m["fuzz_scenarios_total"] == 7
        assert m["fuzz_divergences_by_kind"] == {"stream-vs-serial": 1}
        assert m["fuzz_shrink_steps_total"] == 7

        class _DI:
            cluster_store = store

            def scheduler_service(self):
                return svc

        text = render_metrics(_DI())
        assert "simulator_fuzz_scenarios_total 7" in text
        assert 'simulator_fuzz_divergences_total{kind="stream-vs-serial"} 1' in text
        assert "simulator_fuzz_shrink_steps_total 7" in text

    def test_divergence_none_row(self):
        from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
        from kube_scheduler_simulator_tpu.server.metrics import render_metrics
        from kube_scheduler_simulator_tpu.state.store import ClusterStore
        from kube_scheduler_simulator_tpu.utils import SimClock

        store = ClusterStore(clock=SimClock(0.0))
        svc = SchedulerService(store, use_batch="off", clock=SimClock(0.0))
        svc.start_scheduler(None)

        class _DI:
            cluster_store = store

            def scheduler_service(self):
                return svc

        assert 'simulator_fuzz_divergences_total{kind="none"} 0' in render_metrics(_DI())


class TestEncodeState:
    def test_round_trip_shape(self):
        state = {"default/p": ("n1", (("a", "1"), ("b", "2")), "conds")}
        enc = encode_state(state)
        assert enc == [["default/p", ["n1", [["a", "1"], ["b", "2"]], "conds"]]]
        # canonical: json round-trip is identity on the encoded form
        assert json.loads(json.dumps(enc)) == enc
