"""PodDisruptionBudget dry-run accounting, shared by every component
that plans evictions: DefaultPreemption's victim selection
(plugins/intree/queue_bind.py) and the autoscaler's scale-down drain
(autoscaler/engine.py).  One implementation so the two can never
diverge on what "violates a PDB" means.
"""

from __future__ import annotations

from typing import Any

Obj = dict[str, Any]


def violates_pdb(victim: Obj, pdbs: list[Obj], budget: dict[int, int]) -> bool:
    """Would evicting ``victim`` violate any matching PDB?

    ``budget`` is the dry run's remaining disruptions per PDB index —
    shared across the whole planning pass (each planned eviction
    consumes one from every matching budget), seeded lazily from
    ``status.disruptionsAllowed``.  Mutates ``budget``; callers
    roll back by keeping their own trial copy."""
    from kube_scheduler_simulator_tpu.utils.labels import match_label_selector

    vio = False
    for idx, pdb in enumerate(pdbs):
        if (pdb["metadata"].get("namespace") or "default") != (
            victim["metadata"].get("namespace") or "default"
        ):
            continue
        if not match_label_selector(
            (pdb.get("spec") or {}).get("selector"), victim["metadata"].get("labels") or {}
        ):
            continue
        if idx not in budget:
            budget[idx] = int(((pdb.get("status") or {}).get("disruptionsAllowed")) or 0)
        budget[idx] -= 1
        if budget[idx] < 0:
            vio = True
    return vio
