"""The multi-tenant session plane (tenancy/): isolated sessions over a
shared compiled-executable substrate, admission/TTL lifecycle, per-session
journal namespaces with boot recovery, and the HTTP routing surface
(/api/v1/sessions CRUD, prefix + X-KSS-Session routing, per-session
/metrics labels).  docs/multitenancy.md is the prose for everything
pinned here."""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request
from typing import Any

import pytest

from kube_scheduler_simulator_tpu.server import DIContainer, SimulatorServer
from kube_scheduler_simulator_tpu.tenancy import (
    SUBSTRATE,
    InvalidSessionError,
    SessionError,
    SessionExistsError,
    SessionManager,
    TooManySessionsError,
    UnknownSessionError,
    session_knobs,
)

Obj = dict[str, Any]


# ---------------------------------------------------------------- substrate


def test_substrate_disabled_by_default_is_inert():
    assert not SUBSTRATE.enabled
    assert SUBSTRATE.lookup("scan", ("k",)) is None
    fn = object()
    assert SUBSTRATE.publish("scan", ("k",), fn) is fn
    SUBSTRATE.enable()
    try:
        # nothing was registered while disabled, and the disabled probes
        # did not count
        assert SUBSTRATE.lookup("scan", ("k",)) is None
        s = SUBSTRATE.stats()
        assert s["substrate_fn_entries"] == 0
        assert s["substrate_fn_misses_total"] == 1  # the enabled lookup
    finally:
        SUBSTRATE.disable()


def test_substrate_dedupes_first_wins_and_counts():
    SUBSTRATE.enable()
    try:
        a, b = object(), object()
        assert SUBSTRATE.publish("scan", ("cfg1",), a) is a
        # a concurrent second builder loses the race: first-wins, the
        # duplicate build is discarded and every caller shares one fn
        assert SUBSTRATE.publish("scan", ("cfg1",), b) is a
        assert SUBSTRATE.lookup("scan", ("cfg1",)) is a
        assert SUBSTRATE.lookup("compact", ("cfg1",)) is None  # family-keyed
        s = SUBSTRATE.stats()
        assert s["substrate_fn_hits_total"] == 1
        assert s["substrate_fn_misses_total"] == 1
        assert s["substrate_fn_entries"] == 1
    finally:
        SUBSTRATE.disable()


def test_substrate_refcount_nests():
    SUBSTRATE.enable()
    SUBSTRATE.enable()
    SUBSTRATE.disable()
    assert SUBSTRATE.enabled  # still held by the first enable
    SUBSTRATE.disable()
    assert not SUBSTRATE.enabled


# -------------------------------------------------------------------- knobs


def test_session_knobs_defaults_and_validation(monkeypatch):
    monkeypatch.delenv("KSS_SESSION_TTL_S", raising=False)
    monkeypatch.delenv("KSS_MAX_SESSIONS", raising=False)
    assert session_knobs() == {"ttl_s": 0.0, "max_sessions": 16}
    monkeypatch.setenv("KSS_SESSION_TTL_S", "2.5")
    monkeypatch.setenv("KSS_MAX_SESSIONS", "3")
    assert session_knobs() == {"ttl_s": 2.5, "max_sessions": 3}
    for var, bad in (
        ("KSS_SESSION_TTL_S", "soon"),
        ("KSS_SESSION_TTL_S", "-1"),
        ("KSS_MAX_SESSIONS", "many"),
        ("KSS_MAX_SESSIONS", "0"),
    ):
        monkeypatch.setenv("KSS_SESSION_TTL_S", "1")
        monkeypatch.setenv("KSS_MAX_SESSIONS", "1")
        monkeypatch.setenv(var, bad)
        with pytest.raises(SessionError):
            session_knobs()


# ------------------------------------------------------------ manager (unit)


@pytest.fixture()
def default_di():
    di = DIContainer(use_batch="off")
    yield di
    di.close()


def test_manager_admission_and_lifecycle(monkeypatch, default_di):
    monkeypatch.setenv("KSS_MAX_SESSIONS", "2")
    mgr = SessionManager(default_di, use_batch="off")
    try:
        info = mgr.create("t1")
        assert info["id"] == "t1" and info["useBatch"] == "off"
        with pytest.raises(SessionExistsError):
            mgr.create("t1")
        with pytest.raises(InvalidSessionError):
            mgr.create("default")
        with pytest.raises(InvalidSessionError):
            mgr.create("Bad_ID!")
        with pytest.raises(InvalidSessionError):
            mgr.create("t2", use_batch="warp")
        mgr.create("t2")
        with pytest.raises(TooManySessionsError):
            mgr.create("t3")
        assert mgr.stats()["sessions_rejected_total"] == 1
        assert mgr.ids() == ["t1", "t2"]
        assert [s["id"] for s in mgr.list()] == ["t1", "t2"]
        # routing: blank/default → the boot container, named → its own
        assert mgr.resolve_di(None) is default_di
        assert mgr.resolve_di("default") is default_di
        assert mgr.resolve_di("t1") is not default_di
        assert mgr.resolve_store("t1") is not default_di.cluster_store
        with pytest.raises(UnknownSessionError):
            mgr.resolve_di("nope")
        mgr.destroy("t1")
        with pytest.raises(UnknownSessionError):
            mgr.destroy("t1")
        with pytest.raises(InvalidSessionError):
            mgr.destroy("default")
        st = mgr.stats()
        assert st["sessions_active"] == 1
        assert st["sessions_created_total"] == 2
        assert st["sessions_destroyed_total"] == 1
    finally:
        mgr.close()


def test_manager_store_isolation(default_di):
    mgr = SessionManager(default_di, use_batch="off")
    try:
        mgr.create("a")
        mgr.create("b")
        sa = mgr.resolve_store("a")
        sb = mgr.resolve_store("b")
        sa.create("nodes", {"metadata": {"name": "only-in-a"}})
        assert [o["metadata"]["name"] for o in sa.list("nodes")] == ["only-in-a"]
        assert sb.list("nodes") == []
        assert default_di.cluster_store.list("nodes") == []
    finally:
        mgr.close()


def test_manager_ttl_reaps_idle_sessions(monkeypatch, default_di):
    monkeypatch.setenv("KSS_SESSION_TTL_S", "10")
    now = [0.0]
    mgr = SessionManager(default_di, clock=lambda: now[0], use_batch="off")
    try:
        mgr.create("old")
        now[0] = 5.0
        mgr.create("young")
        assert mgr.sweep() == 0
        now[0] = 12.0
        mgr.resolve_di("young")  # touch: routing resets the idle clock
        now[0] = 14.0
        assert mgr.sweep() == 1  # "old" idle 14s > 10s; "young" idle 2s
        assert mgr.ids() == ["young"]
        assert mgr.stats()["sessions_expired_total"] == 1
        # the default session never expires — nothing to sweep for it
        now[0] = 1000.0
        mgr.sweep()
        assert mgr.resolve_di(None) is default_di
    finally:
        mgr.close()


def test_manager_substrate_held_for_lifetime(default_di):
    assert not SUBSTRATE.enabled
    mgr = SessionManager(default_di, use_batch="off")
    assert SUBSTRATE.enabled
    mgr.close()
    assert not SUBSTRATE.enabled


# -------------------------------------------------- journal-namespace recovery


def test_sessions_recover_from_journal_namespaces(tmp_path):
    jdir = str(tmp_path / "journal")
    di = DIContainer(use_batch="off", journal_dir=jdir)
    mgr = SessionManager(di, use_batch="off")
    mgr.create("t1", seed=7)
    mgr.create("t2")
    mgr.resolve_store("t1").create("nodes", {"metadata": {"name": "n1"}})
    mgr.resolve_store("t2").create("pods", {"metadata": {"name": "p1", "namespace": "default"}})
    di.cluster_store.create("nodes", {"metadata": {"name": "boot-node"}})
    # crash: close keeps every namespace on disk
    mgr.close()
    di.close()

    di2 = DIContainer(use_batch="off", journal_dir=jdir)
    mgr2 = SessionManager(di2, use_batch="off")
    try:
        assert mgr2.ids() == ["t1", "t2"]
        assert mgr2.stats()["sessions_recovered_total"] == 2
        assert [o["metadata"]["name"] for o in mgr2.resolve_store("t1").list("nodes")] == ["n1"]
        assert [o["metadata"]["name"] for o in mgr2.resolve_store("t2").list("pods")] == ["p1"]
        assert [o["metadata"]["name"] for o in di2.cluster_store.list("nodes")] == ["boot-node"]
        # the recovered manifest round-trips the boot parameters
        t1 = {s["id"]: s for s in mgr2.list()}["t1"]
        assert t1["seed"] == 7
        # destroy purges the namespace durably: a THIRD boot must not
        # resurrect it
        mgr2.destroy("t1")
        assert not os.path.isdir(os.path.join(jdir, "sessions", "t1"))
    finally:
        mgr2.close()
        di2.close()

    di3 = DIContainer(use_batch="off", journal_dir=jdir)
    mgr3 = SessionManager(di3, use_batch="off")
    try:
        assert mgr3.ids() == ["t2"]
    finally:
        mgr3.close()
        di3.close()


# ------------------------------------------------------------------- HTTP


@pytest.fixture()
def server():
    di = DIContainer(use_batch="off")
    srv = SimulatorServer(di, port=0, kube_api_port=0)
    srv.start(background=True)
    yield srv, di
    srv.shutdown()


def _req(port: int, method: str, path: str, body: "Obj | None" = None, headers: "Obj | None" = None):
    h = {"Content-Type": "application/json"}
    h.update(headers or {})
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}", data=data, method=method, headers=h)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            raw = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            return resp.status, (json.loads(raw) if "json" in ctype else raw.decode())
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            return e.code, json.loads(raw)
        except ValueError:
            return e.code, raw.decode()


def test_http_sessions_crud_and_routing(server):
    srv, di = server
    p = srv.port

    code, body = _req(p, "GET", "/api/v1/sessions")
    assert code == 200 and body["items"] == [] and body["sessions_active"] == 0

    code, s1 = _req(p, "POST", "/api/v1/sessions", {"id": "t1"})
    assert code == 201 and s1["id"] == "t1"
    code, _ = _req(p, "POST", "/api/v1/sessions", {"id": "t1"})
    assert code == 409
    code, _ = _req(p, "POST", "/api/v1/sessions", {"id": "Bad!"})
    assert code == 400
    code, info = _req(p, "GET", "/api/v1/sessions/t1")
    assert code == 200 and info["id"] == "t1"
    code, dflt = _req(p, "GET", "/api/v1/sessions/default")
    assert code == 200 and dflt.get("default") is True
    code, _ = _req(p, "GET", "/api/v1/sessions/ghost")
    assert code == 404

    # prefix routing: the session's store, not the boot store
    code, _ = _req(p, "POST", "/api/v1/sessions/t1/resources/nodes",
                   {"metadata": {"name": "t1-node"}})
    assert code == 201
    code, lst = _req(p, "GET", "/api/v1/sessions/t1/resources/nodes")
    assert code == 200 and [o["metadata"]["name"] for o in lst["items"]] == ["t1-node"]
    code, lst = _req(p, "GET", "/api/v1/resources/nodes")
    assert code == 200 and lst["items"] == []
    assert di.cluster_store.list("nodes") == []

    # header routing reaches the same container
    code, lst = _req(p, "GET", "/api/v1/resources/nodes", headers={"X-KSS-Session": "t1"})
    assert code == 200 and [o["metadata"]["name"] for o in lst["items"]] == ["t1-node"]
    code, _ = _req(p, "GET", "/api/v1/resources/nodes", headers={"X-KSS-Session": "ghost"})
    assert code == 404

    code, _ = _req(p, "DELETE", "/api/v1/sessions/t1")
    assert code == 200
    code, _ = _req(p, "DELETE", "/api/v1/sessions/t1")
    assert code == 404
    code, _ = _req(p, "DELETE", "/api/v1/sessions/default")
    assert code == 400


def test_http_session_cap_is_429(monkeypatch):
    monkeypatch.setenv("KSS_MAX_SESSIONS", "1")
    di = DIContainer(use_batch="off")
    srv = SimulatorServer(di, port=0, kube_api_port=0)
    srv.start(background=True)
    try:
        code, _ = _req(srv.port, "POST", "/api/v1/sessions", {"id": "t1"})
        assert code == 201
        code, body = _req(srv.port, "POST", "/api/v1/sessions", {"id": "t2"})
        assert code == 429 and "KSS_MAX_SESSIONS" in json.dumps(body)
    finally:
        srv.shutdown()


def test_http_kube_api_session_routing(server):
    srv, _di = server
    _req(srv.port, "POST", "/api/v1/sessions", {"id": "k1"})
    kp = srv.kube_api_port
    code, _ = _req(kp, "POST", "/sessions/k1/api/v1/nodes", {"metadata": {"name": "kn"}})
    assert code == 201
    code, lst = _req(kp, "GET", "/sessions/k1/api/v1/nodes")
    assert code == 200 and [o["metadata"]["name"] for o in lst["items"]] == ["kn"]
    code, lst = _req(kp, "GET", "/api/v1/nodes")
    assert code == 200 and lst["items"] == []
    code, _ = _req(kp, "GET", "/sessions/ghost/api/v1/nodes")
    assert code == 404


def test_http_session_metrics_labels_and_default_purity(server):
    srv, _di = server
    p = srv.port
    code, before = _req(p, "GET", "/metrics")
    assert code == 200
    # an unused session plane leaves the default scrape byte-identical:
    # no session labels, no session-plane series
    assert 'session="' not in before and "simulator_sessions_active" not in before

    _req(p, "POST", "/api/v1/sessions", {"id": "m1"})
    code, labeled = _req(p, "GET", "/api/v1/sessions/m1/metrics")
    assert code == 200 and 'session="m1"' in labeled

    code, after = _req(p, "GET", "/metrics")
    assert code == 200
    assert "simulator_sessions_active 1" in after
    assert "simulator_substrate_fn_entries" in after


def test_http_simulator_kinds_disabled_in_sessions(server):
    srv, _di = server
    _req(srv.port, "POST", "/api/v1/sessions", {"id": "nosim"})
    code, _ = _req(srv.port, "GET", "/api/v1/sessions/nosim/resources/simulators")
    assert code == 404
    # ...but still served by the default session
    code, _ = _req(srv.port, "GET", "/api/v1/resources/simulators")
    assert code == 200
