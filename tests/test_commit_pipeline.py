"""The bulk-commit pipeline vs the per-pod commit path.

The batch engine's commit side was rebuilt around waves (PR: pipelined
bulk-commit): annotation payloads materialize wave-at-a-time through the
native wave tables, land in the result store under one lock, and flush
through the cluster store's bulk-apply with one batched event dispatch,
while the kernel double-buffers pod windows under the host commit.  The
contract is BYTE identity: every annotation (and the result-history
trail) must equal what the sequential per-pod path writes.  The golden
suite (tests/test_golden_reference.py) pins the underlying byte formats
against the reference's Go tests; these suites pin the new path against
the old ones on mixed-profile workloads, plus the ordering/atomicity
properties of the pipeline itself.
"""

from __future__ import annotations

import random
from typing import Any

from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
from kube_scheduler_simulator_tpu.state.store import ClusterStore

from tests.test_batch_parity import mk_node, mk_pod, profile_with

Obj = dict[str, Any]


def _mixed_cluster(n_nodes: int = 48):
    rng = random.Random(99)
    nodes = []
    for i in range(n_nodes):
        labels = {
            "kubernetes.io/hostname": f"node-{i}",
            "topology.kubernetes.io/zone": f"z{i % 3}",
            "disk": "ssd" if i % 2 else "hdd",
        }
        taints = (
            [{"key": "spot", "value": "true", "effect": "NoSchedule"}]
            if i % 11 == 0
            else None
        )
        nodes.append(
            mk_node(
                f"node-{i}",
                cpu_m=rng.choice([4000, 8000, 16000]),
                mem_mi=16384,
                labels=labels,
                taints=taints,
            )
        )
    return nodes


def _mixed_pods(lo: int, hi: int):
    """A mixed-profile workload: plain fits, selector-pinned pods, spread
    constraints, and unschedulable giants (failure paths must stay
    byte-identical too)."""
    rng = random.Random(7)
    pods = []
    for i in range(lo, hi):
        extra: dict = {}
        if i % 5 == 0:
            extra["nodeSelector"] = {"disk": "ssd"}
        if i % 7 == 0:
            extra["topologySpreadConstraints"] = [
                {
                    "maxSkew": 2,
                    "topologyKey": "topology.kubernetes.io/zone",
                    "whenUnsatisfiable": "DoNotSchedule",
                    "labelSelector": {"matchLabels": {"app": f"a{i % 2}"}},
                }
            ]
        cpu = 900000 if i % 17 == 0 else rng.choice([100, 300, 700])
        pods.append(
            mk_pod(
                f"pod-{i}",
                cpu_m=cpu,
                mem_mi=rng.choice([128, 512]),
                labels={"app": f"a{i % 2}"},
                **extra,
            )
        )
    return pods


def _run_rounds(svc: SchedulerService, store: ClusterStore, rounds: list[list[Obj]]):
    for pods in rounds:
        for p in pods:
            store.create("pods", dict(p))
        svc.schedule_pending(max_rounds=1)


def _pod_states(store: ClusterStore) -> dict:
    out = {}
    for p in store.list("pods"):
        name = p["metadata"]["name"]
        out[name] = (
            (p.get("spec") or {}).get("nodeName"),
            p["metadata"].get("annotations") or {},
        )
    return out


def test_bulk_commit_bytes_identical_to_per_pod_path():
    """The acceptance oracle: the SAME workload committed through the
    bulk wave path (pipeline forced on, small commit waves so several
    waves + windows engage) and through the per-pod path (pipeline off,
    wave size 1 → every pod takes `_commit_batch_pod`+`flush_pod`) must
    leave byte-identical annotations, result-history included, across
    TWO rounds (history splices on the second attempt's flush)."""
    nodes = _mixed_cluster()
    rounds = [_mixed_pods(0, 40), _mixed_pods(40, 64)]

    def build(commit_wave: int, pipeline):
        store = ClusterStore()
        for n in nodes:
            store.create("nodes", n)
        svc = SchedulerService(
            store,
            seed=5,
            use_batch="force",
            batch_min_work=0,
            commit_wave=commit_wave,
            pipeline=pipeline,
        )
        svc.start_scheduler(
            {"profiles": [profile_with(["NodeResourcesFit", "TaintToleration",
                                        "NodeAffinity", "PodTopologySpread"])],
             "percentageOfNodesToScore": 100}
        )
        return store, svc

    store_bulk, svc_bulk = build(commit_wave=8, pipeline=True)
    store_pp, svc_pp = build(commit_wave=1, pipeline=False)
    _run_rounds(svc_bulk, store_bulk, rounds)
    _run_rounds(svc_pp, store_pp, rounds)

    bulk = _pod_states(store_bulk)
    pp = _pod_states(store_pp)
    assert bulk.keys() == pp.keys()
    for name in bulk:
        assert bulk[name][0] == pp[name][0], f"{name}: node divergence"
        b_ann, p_ann = bulk[name][1], pp[name][1]
        assert b_ann.keys() == p_ann.keys(), f"{name}: annotation keys differ"
        for k in p_ann:
            assert b_ann[k] == p_ann[k], (
                f"{name} annotation {k} diverges:\n bulk={b_ann[k][:300]}\n"
                f" perpod={p_ann[k][:300]}"
            )


def test_bulk_commit_matches_sequential_cycle_bytes():
    """Bulk-committed annotations must also match the SEQUENTIAL cycle
    (use_batch=off) — the reference semantics — not merely the old batch
    commit path."""
    nodes = _mixed_cluster(24)
    rounds = [_mixed_pods(0, 24)]

    def build(mode: str, **kw):
        store = ClusterStore()
        for n in nodes:
            store.create("nodes", n)
        svc = SchedulerService(store, seed=3, use_batch=mode, batch_min_work=0, **kw)
        svc.start_scheduler(
            {"profiles": [profile_with(["NodeResourcesFit", "TaintToleration"])],
             "percentageOfNodesToScore": 100}
        )
        return store, svc

    store_seq, svc_seq = build("off")
    store_bulk, svc_bulk = build("auto", commit_wave=6, pipeline=True)
    _run_rounds(svc_seq, store_seq, rounds)
    _run_rounds(svc_bulk, store_bulk, rounds)
    assert svc_bulk.stats["batch_pods"] > 0
    seq = _pod_states(store_seq)
    bulk = _pod_states(store_bulk)
    assert seq.keys() == bulk.keys()
    for name in seq:
        assert seq[name] == bulk[name], f"{name}: bulk != sequential"


def test_windowed_rounds_match_single_dispatch_rounds():
    """schedule_waves' carry-chained pod windows must reproduce the one-
    dispatch kernel exactly: same placements, same annotation bytes —
    windows are forced small so several chain per round."""
    from kube_scheduler_simulator_tpu.scheduler.batch_engine import BatchEngine

    nodes = _mixed_cluster(16)
    pods = _mixed_pods(0, 40)

    def build():
        store = ClusterStore()
        for n in nodes:
            store.create("nodes", n)
        for p in pods:
            store.create("pods", dict(p))
        svc = SchedulerService(store, seed=1, use_batch="off")
        svc.start_scheduler({"percentageOfNodesToScore": 100})
        return store, svc

    _store_a, svc_a = build()
    fw = svc_a.framework
    eng = BatchEngine.from_framework(fw, trace=True)
    pending = fw.sort_pods(svc_a.pending_pods())
    args = (
        svc_a.cluster_store.list("nodes"),
        svc_a.cluster_store.list("pods"),
        pending,
        svc_a.cluster_store.list("namespaces"),
    )
    full = eng.schedule(*args)
    eng2 = BatchEngine.from_framework(fw, trace=True)
    parts = list(eng2.schedule_waves(*args, wave_pods=8))
    assert len(parts) > 1, "expected several windows"
    got_sel: list = []
    for result, off, cnt in parts:
        assert len(result.pending) == cnt
        got_sel.extend(result.selected_nodes[:cnt])
        for j in range(cnt):
            i = off + j
            assert result.filter_annotation_json(j) == full.filter_annotation_json(i), (
                f"pod {i}: windowed filter annotation diverges"
            )
            ws, wf = result.score_annotations_json(j)
            fs, ff = full.score_annotations_json(i)
            assert (ws, wf) == (fs, ff), f"pod {i}: windowed score annotations diverge"
    assert got_sel == full.selected_nodes[: len(pending)]
    assert parts[-1][0].final_start == full.final_start


def test_mid_wave_store_conflict_preserves_order_and_skips_deleted():
    """A pod deleted between the kernel's decision and the wave flush
    must be skipped (no resurrection, no error), while every OTHER pod in
    the wave still commits in queue order — the bulk apply reads each
    object fresh under the store lock, so the per-pod path's conflict
    retry has nothing left to race against."""
    nodes = _mixed_cluster(12)
    store = ClusterStore()
    for n in nodes:
        store.create("nodes", n)
    svc = SchedulerService(
        store, seed=2, use_batch="force", batch_min_work=0,
        commit_wave=4, pipeline=True,
    )
    svc.start_scheduler(
        {"profiles": [profile_with(["NodeResourcesFit"])],
         "percentageOfNodesToScore": 100}
    )
    pods = [mk_pod(f"pod-{i}", cpu_m=100, mem_mi=128) for i in range(12)]
    for p in pods:
        store.create("pods", dict(p))

    # delete one mid-wave: hook the FIRST bind event of the round and
    # remove a LATER pod before its wave flushes
    deleted = {"done": False}

    def on_event(ev):
        if (
            not deleted["done"]
            and ev.type == "MODIFIED"
            and (ev.obj.get("spec") or {}).get("nodeName")
        ):
            deleted["done"] = True
            store.delete("pods", "pod-9", "default")

    store.subscribe(["pods"], on_event)
    svc.schedule_pending(max_rounds=1)

    remaining = {p["metadata"]["name"]: p for p in store.list("pods")}
    assert "pod-9" not in remaining, "deleted pod must not be resurrected"
    # every surviving pod committed: bound, annotated, history present
    for name, pod in remaining.items():
        assert (pod.get("spec") or {}).get("nodeName"), f"{name} not bound"
        annos = pod["metadata"].get("annotations") or {}
        assert "scheduler-simulator/result-history" in annos, f"{name} missing history"
    # queue order preserved: attempt counters assigned in pod order means
    # identical placements to a run without the mid-wave delete for the
    # pods BEFORE the deletion point
    store2 = ClusterStore()
    for n in nodes:
        store2.create("nodes", n)
    svc2 = SchedulerService(
        store2, seed=2, use_batch="force", batch_min_work=0,
        commit_wave=4, pipeline=True,
    )
    svc2.start_scheduler(
        {"profiles": [profile_with(["NodeResourcesFit"])],
         "percentageOfNodesToScore": 100}
    )
    for p in pods:
        store2.create("pods", dict(p))
    svc2.schedule_pending(max_rounds=1)
    for i in range(9):  # pods before the deleted one
        a = store.get("pods", f"pod-{i}")["spec"].get("nodeName")
        b = store2.get("pods", f"pod-{i}")["spec"].get("nodeName")
        assert a == b, f"pod-{i}: order disturbed by mid-wave delete ({a} != {b})"


def test_mid_wave_preemption_restart_counter_and_tail_bytes():
    """A successful preemption landing MID-wave (pods already accumulated
    in the current commit wave) must flush the partial wave, re-run the
    kernel on the remaining tail, bump batch_restarts (surfaced as
    batch_restarts_total on /metrics), and leave the tail's annotations
    byte-identical to the all-sequential run."""
    N = 12
    WAVE = 8

    def build_store():
        store = ClusterStore()
        toleration = [{"key": "special", "operator": "Exists", "effect": "NoSchedule"}]
        for i in range(N):
            labels = {"kubernetes.io/hostname": f"node-{i}"}
            if i == 0:
                labels["special"] = "true"
            store.create(
                "nodes",
                mk_node(
                    f"node-{i}",
                    cpu_m=4000,
                    mem_mi=8192,
                    labels=labels,
                    taints=[{"key": "special", "effect": "NoSchedule"}] if i == 0 else None,
                ),
            )
        victim = mk_pod("victim", cpu_m=3900, mem_mi=128)
        victim["spec"]["nodeName"] = "node-0"
        victim["spec"]["priority"] = 0
        victim["spec"]["tolerations"] = toleration
        store.create("pods", victim)

        def stamped(p, i):
            p["metadata"]["creationTimestamp"] = f"2024-01-01T00:{i // 60:02d}:{i % 60:02d}Z"
            return p

        # queue order is (priority desc, creationTimestamp): 10 high-pri
        # fillers, THEN the preemptor — mid-wave, 2 pods already
        # accumulated in the second WAVE=8 wave — then 13 low-pri fillers
        # forming the tail the restart re-runs
        for i in range(10):
            p = stamped(mk_pod(f"head-{i}", cpu_m=20, mem_mi=16), i)
            p["spec"]["priority"] = 100
            store.create("pods", p)
        pre = stamped(mk_pod("preemptor", cpu_m=3800, mem_mi=128), 10)
        pre["spec"]["priority"] = 50
        pre["spec"]["nodeSelector"] = {"special": "true"}
        pre["spec"]["tolerations"] = toleration
        store.create("pods", pre)
        for i in range(13):
            p = stamped(mk_pod(f"tail-{i}", cpu_m=20, mem_mi=16), 11 + i)
            p["spec"]["priority"] = 10
            store.create("pods", p)
        return store

    cfg = {"percentageOfNodesToScore": 100}
    store_seq = build_store()
    svc_seq = SchedulerService(store_seq, tie_break="first", use_batch="off")
    svc_seq.start_scheduler(cfg)
    svc_seq.schedule_pending(max_rounds=2)

    store_bat = build_store()
    svc_bat = SchedulerService(
        store_bat, tie_break="first", use_batch="auto", batch_min_work=0, commit_wave=WAVE
    )
    svc_bat.start_scheduler(cfg)
    svc_bat.schedule_pending(max_rounds=2)

    # the restart: one successful mid-round preemption re-ran the kernel
    assert svc_bat.stats["batch_restarts"] == 1
    # wave accounting saw multiple flushed waves (the partial pre-restart
    # wave included) and feeds the /metrics commit-path gauges
    m = svc_bat.metrics()
    assert m["commit_waves"] >= 2
    assert m["wave_commit_s"] >= 0.0 and m["commit_pods_per_s"] >= 0.0

    # the counter is visible on the Prometheus surface
    class _DI:
        def __init__(self, svc):
            self._svc = svc
            self.cluster_store = svc.cluster_store

        def scheduler_service(self):
            return self._svc

    from kube_scheduler_simulator_tpu.server.metrics import render_metrics

    text = render_metrics(_DI(svc_bat))
    assert "simulator_batch_restarts_total 1" in text
    assert "simulator_commit_waves_total" in text
    assert "simulator_wave_commit_seconds" in text

    assert store_bat.get("pods", "preemptor")["spec"].get("nodeName") == "node-0"
    # byte-identical annotations everywhere — the post-restart tail included
    names = [f"head-{i}" for i in range(10)] + ["preemptor"] + [f"tail-{i}" for i in range(13)]
    for nm in names:
        seq_pod = store_seq.get("pods", nm)
        bat_pod = store_bat.get("pods", nm)
        assert seq_pod["spec"].get("nodeName") == bat_pod["spec"].get("nodeName"), nm
        seq_annos = seq_pod["metadata"].get("annotations") or {}
        bat_annos = bat_pod["metadata"].get("annotations") or {}
        assert seq_annos == bat_annos, (
            f"{nm} annotation divergence:\n"
            + "\n".join(
                f"  {k}:\n   seq={seq_annos.get(k)}\n   bat={bat_annos.get(k)}"
                for k in sorted(set(seq_annos) | set(bat_annos))
                if seq_annos.get(k) != bat_annos.get(k)
            )
        )


def test_bulk_update_skips_missing_and_batches_events():
    """ClusterStore.bulk_update: one lock, per-object RV bumps, missing
    objects skipped, events delivered for exactly the applied set."""
    store = ClusterStore()
    for i in range(4):
        store.create("pods", mk_pod(f"p-{i}"))
    seen: list = []
    store.subscribe(["pods"], lambda ev: seen.append((ev.type, ev.obj["metadata"]["name"])))

    def mark(o):
        # bulk_update contract: the live object is read-only — rebuild
        # the changed path, share the rest
        annotations = dict(o["metadata"].get("annotations") or {})
        annotations["marked"] = "yes"
        return {**o, "metadata": {**o["metadata"], "annotations": annotations}}

    applied = store.bulk_update(
        "pods",
        [("p-0", "default", mark), ("missing", "default", mark),
         ("p-2", "default", mark), ("p-3", "default", lambda o: None)],
    )
    assert applied == 2
    assert [n for t, n in seen if t == "MODIFIED"] == ["p-0", "p-2"]
    assert store.get("pods", "p-0")["metadata"]["annotations"]["marked"] == "yes"
    assert "annotations" not in store.get("pods", "p-3")["metadata"]
    rv0 = int(store.get("pods", "p-0")["metadata"]["resourceVersion"])
    rv2 = int(store.get("pods", "p-2")["metadata"]["resourceVersion"])
    assert rv2 == rv0 + 1, "per-object resourceVersions stay monotonic per mutation"
