"""An external "debuggable scheduler" binary built on the library surface.

Mirrors the reference's third sample (reference
docs/sample/debuggable-scheduler/main.go:20-34): a user's own scheduler
program that embeds the debuggable machinery — a custom out-of-tree
plugin (the nodenumber sample) enabled next to the default profile,
every plugin wrapped so per-plugin results land on pod annotations —
driven here against the in-memory cluster, with an external scheduler
committing through the same service.

Run:  PYTHONPATH=. python examples/debuggable_scheduler.py
"""

from __future__ import annotations

import json

from examples.nodenumber import node_number_factory
from kube_scheduler_simulator_tpu.pkg import debuggablescheduler
from kube_scheduler_simulator_tpu.state.store import ClusterStore


def main() -> None:
    store = ClusterStore()
    for i in range(4):
        store.create(
            "nodes",
            {
                "metadata": {"name": f"node-{i}"},
                "status": {"allocatable": {"cpu": "4", "memory": "8Gi", "pods": "110"}},
            },
        )
    # pod name ends in "3": the NodeNumber sample plugin scores nodes whose
    # name ends with the same digit
    store.create(
        "pods",
        {
            "metadata": {"name": "pod-3", "namespace": "default"},
            "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "100m"}}}]},
        },
    )

    config = {
        "profiles": [
            {
                "schedulerName": "default-scheduler",
                "plugins": {"multiPoint": {"enabled": [{"name": "NodeNumber"}]}},
            }
        ]
    }
    scheduler, _result_store = debuggablescheduler.new_scheduler(
        store,
        plugins={"NodeNumber": node_number_factory},
        config=config,
    )
    scheduler.schedule_pending(max_rounds=1)

    pod = store.get("pods", "pod-3", "default")
    print("bound to:", pod["spec"].get("nodeName"))
    score = json.loads(pod["metadata"]["annotations"]["scheduler-simulator/score-result"])
    for node, plugins in sorted(score.items()):
        print(f"  {node}: NodeNumber={plugins.get('NodeNumber')}")


if __name__ == "__main__":
    main()
