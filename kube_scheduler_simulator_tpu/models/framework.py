"""Scheduling-framework contracts: Status, CycleState, plugin interfaces.

This is the Python analog of k8s.io/kubernetes scheduler framework types that
the reference's wrapped plugins delegate to (reference
simulator/scheduler/plugin/wrappedplugin.go:253-364 type-asserts 12 extension
points against these interfaces).  Semantics follow the v1.26 framework the
reference pins (reference simulator/go.mod:3-30):

- A nil/None status means Success.
- ``Status.message()`` joins reasons with ", " — this exact string is what
  lands in the filter/score annotations (reference
  simulator/scheduler/plugin/resultstore/store.go:38-89).
- Scores are int64 in [MIN_NODE_SCORE, MAX_NODE_SCORE].
"""

from __future__ import annotations

import enum
from typing import Any, Protocol, Sequence, runtime_checkable

Obj = dict[str, Any]

MAX_NODE_SCORE = 100
MIN_NODE_SCORE = 0


class Code(enum.IntEnum):
    """framework.Code (upstream framework/interface.go)."""

    SUCCESS = 0
    ERROR = 1
    UNSCHEDULABLE = 2
    UNSCHEDULABLE_AND_UNRESOLVABLE = 3
    WAIT = 4
    SKIP = 5


class Status:
    """framework.Status: a code plus human-readable reasons."""

    __slots__ = ("code", "reasons", "plugin")

    def __init__(self, code: Code = Code.SUCCESS, reasons: "Sequence[str] | None" = None, plugin: str = ""):
        self.code = code
        self.reasons = list(reasons or [])
        self.plugin = plugin

    @staticmethod
    def success() -> "Status":
        return Status(Code.SUCCESS)

    @staticmethod
    def unschedulable(*reasons: str) -> "Status":
        return Status(Code.UNSCHEDULABLE, reasons)

    @staticmethod
    def unresolvable(*reasons: str) -> "Status":
        return Status(Code.UNSCHEDULABLE_AND_UNRESOLVABLE, reasons)

    @staticmethod
    def error(*reasons: str) -> "Status":
        return Status(Code.ERROR, reasons)

    @staticmethod
    def skip() -> "Status":
        return Status(Code.SKIP)

    @staticmethod
    def wait(*reasons: str) -> "Status":
        return Status(Code.WAIT, reasons)

    def is_success(self) -> bool:
        return self.code == Code.SUCCESS

    def is_skip(self) -> bool:
        return self.code == Code.SKIP

    def is_wait(self) -> bool:
        return self.code == Code.WAIT

    def is_rejected(self) -> bool:
        """framework.Status.IsRejected: unschedulable either way."""
        return self.code in (Code.UNSCHEDULABLE, Code.UNSCHEDULABLE_AND_UNRESOLVABLE)

    def message(self) -> str:
        return ", ".join(self.reasons)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Status({self.code.name}, {self.message()!r})"


def is_success(status: "Status | None") -> bool:
    return status is None or status.is_success()


class WaitingPod:
    """A pod parked at Permit (upstream framework.waitingPod): one or
    more permit plugins returned Wait with a timeout; the pod is bound
    only once every plugin calls ``allow`` (or rejected/expired).  The
    reference records the Wait status + timeout per plugin (reference
    wrappedplugin.go:582-611) and upstream's binding cycle blocks on this
    object; the simulator's synchronous loop keeps it in
    Framework.waiting_pods and finishes the bind on the triggering call.
    """

    def __init__(self, pod: Obj, node_name: str, state: "CycleState", plugin_timeouts: dict[str, float], now: float):
        self.pod = pod
        self.node_name = node_name
        self.state = state
        # plugin → absolute deadline
        self.deadlines = {p: now + t for p, t in plugin_timeouts.items()}
        self.pending = set(plugin_timeouts)
        self.rejected: "str | None" = None  # rejection message

    @property
    def key(self) -> str:
        return f"{self.pod['metadata'].get('namespace', 'default')}/{self.pod['metadata']['name']}"

    def pending_plugins(self) -> "set[str]":
        return set(self.pending)

    def earliest_deadline(self) -> float:
        return min(self.deadlines.values()) if self.deadlines else 0.0


class PreFilterResult:
    """framework.PreFilterResult: optional node-name allowlist."""

    __slots__ = ("node_names",)

    def __init__(self, node_names: "set[str] | None" = None):
        self.node_names = node_names

    def all_nodes(self) -> bool:
        return self.node_names is None

    def merge(self, other: "PreFilterResult | None") -> "PreFilterResult":
        if other is None or other.all_nodes():
            return self
        if self.all_nodes():
            return other
        assert self.node_names is not None and other.node_names is not None
        return PreFilterResult(self.node_names & other.node_names)


class CycleState:
    """framework.CycleState: per-scheduling-cycle plugin scratch space."""

    def __init__(self) -> None:
        self._data: dict[str, Any] = {}

    def write(self, key: str, value: Any) -> None:
        self._data[key] = value

    def read(self, key: str) -> Any:
        return self._data.get(key)

    def delete(self, key: str) -> None:
        self._data.pop(key, None)

    def clone(self) -> "CycleState":
        """Shallow clone (upstream CycleState.Clone): entries are shared;
        writers that mutate an entry on a clone must copy-on-write it
        (the ``add_pod_to_state`` extensions do)."""
        c = CycleState()
        c._data = dict(self._data)
        return c


class Plugin(Protocol):
    name: str


@runtime_checkable
class QueueSortPlugin(Protocol):
    name: str

    def less(self, pod_info1: Obj, pod_info2: Obj) -> bool: ...


@runtime_checkable
class PreFilterPlugin(Protocol):
    name: str

    def pre_filter(self, state: CycleState, pod: Obj) -> "tuple[PreFilterResult | None, Status | None]": ...


@runtime_checkable
class FilterPlugin(Protocol):
    name: str

    def filter(self, state: CycleState, pod: Obj, node_info: "Any") -> "Status | None": ...


@runtime_checkable
class PostFilterPlugin(Protocol):
    name: str

    def post_filter(
        self, state: CycleState, pod: Obj, filtered_node_status_map: dict[str, Status]
    ) -> "tuple[str | None, Status | None]": ...


@runtime_checkable
class PreScorePlugin(Protocol):
    name: str

    def pre_score(self, state: CycleState, pod: Obj, nodes: list[Obj]) -> "Status | None": ...


@runtime_checkable
class ScorePlugin(Protocol):
    name: str

    def score(self, state: CycleState, pod: Obj, node_name: str) -> "tuple[int, Status | None]": ...


@runtime_checkable
class ScoreExtensions(Protocol):
    def normalize_scores(self, state: CycleState, pod: Obj, scores: dict[str, int]) -> "Status | None": ...


@runtime_checkable
class ReservePlugin(Protocol):
    name: str

    def reserve(self, state: CycleState, pod: Obj, node_name: str) -> "Status | None": ...

    def unreserve(self, state: CycleState, pod: Obj, node_name: str) -> None: ...


@runtime_checkable
class PermitPlugin(Protocol):
    name: str

    def permit(self, state: CycleState, pod: Obj, node_name: str) -> "tuple[Status | None, float]": ...


@runtime_checkable
class PreBindPlugin(Protocol):
    name: str

    def pre_bind(self, state: CycleState, pod: Obj, node_name: str) -> "Status | None": ...


@runtime_checkable
class BindPlugin(Protocol):
    name: str

    def bind(self, state: CycleState, pod: Obj, node_name: str) -> "Status | None": ...


@runtime_checkable
class PostBindPlugin(Protocol):
    name: str

    def post_bind(self, state: CycleState, pod: Obj, node_name: str) -> None: ...
