"""Profile ONE cfg5 churn wave: 2000 pods x 5000 nodes, full default
profile, trace on — where do the seconds go?

Usage: python scripts/profile_cfg5.py [--pods 2000] [--nodes 5000] [--cprofile]
"""

from __future__ import annotations

import argparse
import cProfile
import pstats
import random
import sys
import time

sys.path.insert(0, ".")

from bench import mk_node, mk_pod  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=2000)
    ap.add_argument("--nodes", type=int, default=5000)
    ap.add_argument("--cprofile", action="store_true")
    ap.add_argument("--waves", type=int, default=1)
    args = ap.parse_args()

    from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
    from kube_scheduler_simulator_tpu.state.store import ClusterStore

    rng = random.Random(7)
    store = ClusterStore()
    for i in range(args.nodes):
        store.create("nodes", mk_node(i))
    svc = SchedulerService(store, tie_break="first", use_batch="auto")
    svc.start_scheduler(None)

    # warmup wave (pays compile)
    for i in range(256):
        store.create("pods", mk_pod(10_000_000 + i, rng, spread=i % 3 == 0))
    t0 = time.perf_counter()
    svc.schedule_pending(max_rounds=1)
    print(f"warmup wave (256 pods): {time.perf_counter() - t0:.2f}s", file=sys.stderr)

    created = 0
    for w in range(args.waves):
        for _ in range(args.pods):
            store.create("pods", mk_pod(created, rng, spread=created % 3 == 0))
            created += 1
        t0 = time.perf_counter()
        if args.cprofile and w == args.waves - 1:
            prof = cProfile.Profile()
            prof.enable()
            svc.schedule_pending(max_rounds=1)
            prof.disable()
            wall = time.perf_counter() - t0
            st = pstats.Stats(prof)
            st.sort_stats("cumulative")
            st.print_stats(45)
        else:
            svc.schedule_pending(max_rounds=1)
            wall = time.perf_counter() - t0
        eng = svc._batch_engine
        print(
            f"wave {w}: {wall:.2f}s for {args.pods} pods "
            f"({args.pods / wall:.0f} pods/s) timings={eng.last_timings if eng else {}}",
            file=sys.stderr,
        )


if __name__ == "__main__":
    main()
