"""Write-ahead journal: crash durability for the in-memory control plane.

The ``ClusterStore`` is the build's whole control plane (it replaces the
reference's kube-apiserver + etcd), and until this module its only
durability story was the manual snapshot export/import.  A ``Journal``
makes the store crash-consistent: every mutation event the store emits
is appended — before the process can observe a completed operation — to
an append-only, CRC-framed log under ``KSS_JOURNAL_DIR``, and
:mod:`state.recovery` replays it into a fresh process after a crash.

Design points:

- **Record framing.**  A segment file starts with an 8-byte magic
  (``KSSJRNL1``); each record is ``<u32 payload-length><u32 crc32>``
  followed by the JSON payload (sorted keys, compact separators — the
  same op sequence always produces the same bytes, which is what lets
  the torn-write fixtures commit exact files).  A torn tail — short
  header, short payload, or CRC mismatch — is detected by the reader
  and truncated by recovery (counted, never raised).
- **Wave atomicity.**  All store mutations funnel through
  ``ClusterStore._emit``; with a journal attached each event becomes a
  record.  ``ClusterStore.journal_txn`` groups the events of a bulk
  operation — a batch commit wave (``add_wave_results`` + the bind
  transaction + ``flush_wave``), a gang release, a ``bulk_update``, a
  sequential scheduling attempt — into ONE atomic record, so recovery
  can never observe a partially-committed wave or a partially-bound
  gang: a record either replays whole or (torn) is truncated whole.
- **Counters ride on every record.**  ``meta_providers`` are read at
  record-write time (under the store lock) and merged into the payload:
  the store contributes its resourceVersion/uid/generateName counters,
  the scheduler service its per-profile rotation and attempt counters.
  Recovery restores process state from the LAST record's meta, which by
  construction reflects the moment that record became durable.
- **Rotation + compaction.**  ``compact()`` snapshots the whole store
  through ``checkpoint_provider`` (which reuses
  ``SnapshotService.snap()`` — a checkpoint's ``resources`` field IS a
  ResourcesForSnap document) into ``checkpoint-<n>.ckpt``, then rotates
  to a fresh segment and deletes the segments and checkpoints the new
  checkpoint supersedes.  ``checkpoint_every`` (``KSS_CHECKPOINT_EVERY``)
  triggers it automatically every N records; 0 = boot/manual only.
- **fsync** (``KSS_JOURNAL_FSYNC``) is opt-in: the default flushes to
  the OS (surviving process death, the SIGKILL chaos model) without
  paying a disk sync per record; ``1`` syncs every record (surviving
  host power loss too).
- **Publish ordering (shipping).**  A record's header + payload are
  written as ONE buffered write followed by one flush, so a concurrent
  reader of the live segment (:mod:`replication.ship` tails it while
  the primary appends) always observes a strict PREFIX of the logical
  record stream — never reordered or interleaved frame bytes.  That
  prefix property is what makes the tailer's partial-vs-torn call
  deterministic: a short frame at the tail is a mid-write record
  (wait and re-poll), while a full-length frame whose CRC fails can
  only be real damage.  ``kill_at`` fsyncs before killing, so the
  "durable" a chaos kill publishes is the same durable a tailer reads.
- **Seal markers.**  Rotation and clean close append a ``seal`` record
  to the finished segment: a tailer that consumes a seal knows the
  segment is COMPLETE and continues at index+1; a segment superseded
  by a newer segment/checkpoint WITHOUT a seal marks a crash boundary.
  Seals are framing metadata, not state: they are excluded from
  ``stats["records"]`` and skipped (uncounted) by recovery and the
  replication applier.

- **Disk faults are policy, not stack traces.**  Append/fsync/rotation
  errors are classified by errno (ENOSPC, EIO, EROFS, …) and routed per
  ``KSS_JOURNAL_ON_ERROR``: ``wedge`` (default) raises
  :class:`JournalWedged` out of the failing commit and refuses every
  later transaction at ENTRY (before any store mutation) — the
  durability promise fails loudly; ``degrade`` counts
  ``journal_degraded_total{errno}`` once and continues NON-durable,
  with the log truncated back to the last record boundary so recovery
  and a live tailer both read a clean prefix of durable records.  All
  record bytes go through the injectable ``io`` seam so the chaos
  harness (fuzz/chaos.py ``DiskChaos``) can land a fault at an exact
  seeded record.

Everything here is opt-in: with no journal attached the store takes one
``None`` check per emit and tier-1 stays byte-for-byte today's behavior.

``kill_at`` is the crash adversary's hook (:mod:`fuzz.chaos`
``ProcessChaos``): the journal SIGKILLs its own process the instant the
N-th record is durable, which is what "SIGKILL at a seeded
journal-record index" means — deterministic, unmissable, and exactly at
a record boundary like a real mid-run kill.
"""

from __future__ import annotations

import errno as _errno
import json
import os
import struct
import zlib
from typing import Any, Callable, Iterator

Obj = dict[str, Any]

SEGMENT_MAGIC = b"KSSJRNL1"
CHECKPOINT_MAGIC = b"KSSCKPT1"
_HEADER = struct.Struct("<II")  # payload length, crc32(payload)
# sanity bound on a single record (a corrupt length field must not make
# the reader try to allocate gigabytes): 256 MiB
_MAX_RECORD = 256 << 20

SEAL_TYPE = "seal"

SEGMENT_PREFIX = "segment-"
SEGMENT_SUFFIX = ".kssj"
CHECKPOINT_PREFIX = "checkpoint-"
CHECKPOINT_SUFFIX = ".ckpt"


class JournalError(RuntimeError):
    """A journal WRITE-side invariant broke (bad configuration, closed
    journal).  Read-side damage is never an exception — recovery counts
    and truncates it."""


class JournalWedged(JournalError):
    """A disk fault hit the journal under ``KSS_JOURNAL_ON_ERROR=wedge``:
    the durability promise could not be kept, so the commit fails LOUDLY
    and the store refuses further mutations — every subsequent
    ``journal_txn`` raises at entry, BEFORE any store mutation.  The
    on-disk journal stays a clean prefix of durable records (the failed
    frame is truncated back to its record boundary)."""


def classify_errno(e: OSError) -> str:
    """A disk fault's errno as a stable label: the named classes the
    fault matrix drills (ENOSPC, EIO, EROFS), the symbolic name for any
    other errno, ``EUNKNOWN`` when the OSError carries none."""
    if e.errno is None:
        return "EUNKNOWN"
    return _errno.errorcode.get(e.errno, f"E{e.errno}")


class _DirectIO:
    """The journal's file-IO seam: every segment/seal byte goes through
    these three calls so the chaos harness (fuzz/chaos.py ``DiskChaos``)
    can inject ENOSPC/EIO/EROFS at a seeded record without touching a
    real filesystem's failure modes."""

    def write(self, f, data: bytes) -> None:
        f.write(data)

    def flush(self, f) -> None:
        f.flush()

    def fsync(self, fd: int) -> None:
        os.fsync(fd)


def _dumps(payload: Obj) -> bytes:
    # Compact separators, NO key sorting: replayed objects must keep
    # the live objects' dict insertion order byte-for-byte (condition
    # lists are compared as strings by the parity surface — sorting
    # keys here made a recovered pod's conditions differ from the
    # uninterrupted run's).  Determinism still holds: a deterministic
    # op sequence builds its dicts in a deterministic order, which is
    # what the byte-stable fixtures pin.
    return json.dumps(payload, separators=(",", ":")).encode("utf-8")


def segment_path(directory: str, index: int) -> str:
    return os.path.join(directory, f"{SEGMENT_PREFIX}{index:08d}{SEGMENT_SUFFIX}")


def checkpoint_path(directory: str, index: int) -> str:
    return os.path.join(directory, f"{CHECKPOINT_PREFIX}{index:08d}{CHECKPOINT_SUFFIX}")


def _indexed(directory: str, prefix: str, suffix: str) -> list[tuple[int, str]]:
    out: list[tuple[int, str]] = []
    if not os.path.isdir(directory):
        return out
    for fn in os.listdir(directory):
        if fn.startswith(prefix) and fn.endswith(suffix):
            mid = fn[len(prefix) : -len(suffix)]
            if mid.isdigit():
                out.append((int(mid), os.path.join(directory, fn)))
    return sorted(out)


def list_segments(directory: str) -> list[tuple[int, str]]:
    return _indexed(directory, SEGMENT_PREFIX, SEGMENT_SUFFIX)


def list_checkpoints(directory: str) -> list[tuple[int, str]]:
    return _indexed(directory, CHECKPOINT_PREFIX, CHECKPOINT_SUFFIX)


class Journal:
    """Append-only CRC-framed write-ahead log over one directory.

    Internally locked: appends arrive both from under the store lock
    (``ClusterStore._emit``) and from outside it (transaction exits,
    config/boot records, marks) on any thread — interleaved raw file
    writes would tear records, so ``append``/``compact`` serialize on
    the journal's own mutex.
    """

    def __init__(
        self,
        directory: str,
        fsync: bool = False,
        checkpoint_every: int = 0,
        kill_at: "int | None" = None,
        on_error: str = "wedge",
        io: "Any | None" = None,
    ):
        self.directory = directory
        self.fsync = bool(fsync)
        self.checkpoint_every = int(checkpoint_every)
        if self.checkpoint_every < 0:
            raise JournalError(f"checkpoint_every must be >= 0, got {checkpoint_every}")
        if on_error not in ("wedge", "degrade"):
            raise JournalError(
                f"on_error must be 'wedge' or 'degrade', got {on_error!r}"
            )
        # disk-fault policy (KSS_JOURNAL_ON_ERROR): 'wedge' fails the
        # commit loudly and refuses further mutations; 'degrade' counts
        # the errno and continues NON-DURABLE (appends become no-ops)
        # with the on-disk log truncated back to a record boundary so
        # recovery and a live tailer both read a clean prefix.
        self.on_error = on_error
        self.io = io if io is not None else _DirectIO()
        self.wedged = False
        self.degraded_errno: "str | None" = None
        self.degraded_by_errno: dict[str, int] = {}
        # test/chaos hook: SIGKILL this process once record #kill_at
        # (1-based) is durable (fuzz.chaos.ProcessChaos)
        self.kill_at = kill_at
        # read at record-write time and merged into the payload's "meta"
        self.meta_providers: list[Callable[[], Obj]] = []
        # called (no args) by compact(); returns the checkpoint payload
        self.checkpoint_provider: "Callable[[], Obj] | None" = None
        # the newest "mark" record's driver state: compaction may delete
        # the segment holding it, so every checkpoint embeds a copy —
        # recovery must never lose its resume point to a rotation.
        # A post-recovery epoch seeds it from the RecoveryReport (a
        # compaction BEFORE the resumed run's first mark must not prune
        # the only durable resume point).
        self.last_mark: "Obj | None" = None
        # last FULL meta emitted (append writes per-record deltas)
        self._last_meta: Obj = {}
        # set by ClusterStore.attach_journal: appends and compactions
        # serialize on the STORE lock (one total order for record bytes
        # AND their meta deltas — without it, two appenders could write
        # records in the opposite order to their delta computation and
        # recovery's meta merge would restore stale process state), and
        # compaction defers while any journal_txn is open (a checkpoint
        # must never snapshot a half-applied wave).
        self.append_lock: Any = None
        self.compaction_gate: "Callable[[], bool] | None" = None
        import threading

        self._mu = threading.Lock()
        self.stats: dict[str, int] = {
            "records": 0,
            "bytes": 0,
            "compactions": 0,
            "fsyncs": 0,
            "seals": 0,
            "wedges": 0,
            "records_dropped": 0,  # appends skipped while degraded
        }
        os.makedirs(directory, exist_ok=True)
        segs = list_segments(directory)
        self._seg_index = (segs[-1][0] + 1) if segs else 1
        self._records_since_checkpoint = 0
        self._f = self._open_segment(self._seg_index)
        self._closed = False

    # ------------------------------------------------------------------ write

    def _open_segment(self, index: int):
        f = open(segment_path(self.directory, index), "ab")
        if f.tell() == 0:
            f.write(SEGMENT_MAGIC)
            f.flush()
        return f

    def check_writable(self) -> None:
        """Raise :class:`JournalWedged` once a wedge-mode disk fault has
        hit.  ``ClusterStore.journal_txn`` calls this at transaction
        ENTRY: after the first loud failure, no further store mutation
        even begins against a journal that cannot make it durable."""
        if self.wedged:
            raise JournalWedged(
                "journal is wedged after a disk fault (KSS_JOURNAL_ON_ERROR=wedge): "
                "refusing further mutations"
            )

    def _handle_write_error(self, e: OSError, boundary: int) -> None:
        """Route a disk fault per ``on_error`` — called under ``_mu``
        with ``boundary`` the offset of the last durable record edge.
        Both policies first truncate the maybe-partial frame back to the
        boundary so the on-disk log stays a clean prefix of durable
        records; if even the truncate fails, the leftover partial tail
        is exactly the shape recovery and the tailer already classify
        (torn, counted, stepped over) — the logical prefix stays clean
        either way."""
        label = classify_errno(e)
        try:
            self._f.truncate(boundary)
            self._f.seek(boundary)
        except (OSError, ValueError):
            pass
        if self.on_error == "degrade":
            self.degraded_errno = label
            self.degraded_by_errno[label] = self.degraded_by_errno.get(label, 0) + 1
            return
        self.wedged = True
        self.stats["wedges"] += 1
        raise JournalWedged(
            f"journal write failed ({label}) under KSS_JOURNAL_ON_ERROR=wedge: "
            "the commit is NOT durable — refusing this and all further mutations"
        ) from e

    def add_meta_provider(self, provider: Callable[[], Obj]) -> None:
        self.meta_providers.append(provider)

    def _meta(self) -> Obj:
        meta: Obj = {}
        for p in self.meta_providers:
            meta.update(p())
        return meta

    def _meta_delta(self) -> Obj:
        """The meta fields that CHANGED since the last appended record.
        Meta can be O(cluster) (the scheduling queue snapshot); a churn
        run must not pay those bytes on every record, so recovery MERGES
        records' meta — an omitted key means "same as before".
        Checkpoints always embed the FULL meta (they are a fresh base:
        everything before them is pruned).  Bookkeeping races between
        concurrent appenders can at worst re-emit an unchanged field."""
        full = self._meta()
        prev = self._last_meta
        delta = {k: v for k, v in full.items() if k not in prev or prev[k] != v}
        self._last_meta = full
        return delta

    def append(self, rtype: str, events: "list | None" = None, extra: "Obj | None" = None) -> None:
        """Append one durable record.  ``events`` is a list of
        ``[kind, event_type, obj]`` triples (the store's emit stream);
        ``extra`` carries record-type-specific fields (a mark's tick, a
        config record's scheduler configuration).

        Lock order: ``append_lock`` (the store lock, when attached)
        FIRST — it serializes payload/meta-delta construction with the
        write order; meta providers re-take the store/queue locks
        reentrantly inside it; ``_mu`` (file writes only) LAST.  Taking
        the store lock while holding ``_mu`` would deadlock against the
        ``_emit`` path (store lock → append)."""
        import contextlib

        with self.append_lock if self.append_lock is not None else contextlib.nullcontext():
            self._append_ordered(rtype, events, extra)

    def _append_ordered(self, rtype: str, events: "list | None", extra: "Obj | None") -> None:
        payload: Obj = {"t": rtype, "meta": self._meta_delta()}
        if events:
            payload["events"] = events
        if extra:
            payload["x"] = extra
        data = _dumps(payload)
        compact_due = False
        with self._mu:
            if self._closed:
                raise JournalError("journal is closed")
            self.check_writable()
            if self.degraded_errno is not None:
                # non-durable continuation: the fault was counted when it
                # hit; further records drop (counted) so the on-disk
                # prefix stays exactly the pre-fault durable stream
                self.stats["records_dropped"] += 1
                return
            # ONE write for the whole frame, then one flush: a concurrent
            # tailer of the live segment sees a strict prefix of the
            # record stream, never a header published ahead of its
            # payload (replication/ship.py leans on this)
            boundary = self._f.tell()
            frame = _HEADER.pack(len(data), zlib.crc32(data) & 0xFFFFFFFF) + data
            try:
                self.io.write(self._f, frame)
                self.io.flush(self._f)
                if self.fsync:
                    self.io.fsync(self._f.fileno())
                    self.stats["fsyncs"] += 1
            except OSError as e:
                self._handle_write_error(e, boundary)
                return
            if rtype == "mark":
                self.last_mark = extra
            self.stats["records"] += 1
            self.stats["bytes"] += _HEADER.size + len(data)
            self._records_since_checkpoint += 1
            if self.kill_at is not None and self.stats["records"] >= self.kill_at:
                # the chaos adversary: die the instant this record is
                # durable (fsync even when the knob is off — the kill
                # point must not itself tear the record it is keyed on)
                os.fsync(self._f.fileno())
                import signal

                os.kill(os.getpid(), signal.SIGKILL)
            compact_due = (
                self.checkpoint_every > 0
                and self.checkpoint_provider is not None
                and self._records_since_checkpoint >= self.checkpoint_every
            )
        if compact_due:
            # still inside append_lock: the checkpoint cannot interleave
            # with other threads' mutations or open transactions
            self.compact()

    # ---------------------------------------------------------- compaction

    def compact(self) -> "str | None":
        """Snapshot the whole store into a checkpoint, rotate to a fresh
        segment, and delete everything the checkpoint supersedes.  The
        checkpoint is written and synced BEFORE any deletion, so a crash
        at any point leaves either (old segments + maybe the new
        checkpoint) or (new checkpoint + fresh segment) — recovery picks
        the newest valid checkpoint and replays segments >= its index
        (the stale-checkpoint fixture pins this).

        The checkpoint payload + meta are built under ``append_lock``
        (their providers take the store lock — see the lock-order note
        on ``append``); only the file rotation holds ``_mu``.  While
        any ``journal_txn`` is open (``compaction_gate`` false) the
        compaction DEFERS — its mutations are already in the store, so
        a checkpoint taken mid-transaction would persist a half-applied
        wave the journal promises can never be observed; the pending
        ``checkpoint_every`` threshold retries at the next append."""
        import contextlib

        with self.append_lock if self.append_lock is not None else contextlib.nullcontext():
            if self.checkpoint_provider is None:
                return None
            if self.compaction_gate is not None and not self.compaction_gate():
                return None
            payload = self.checkpoint_provider()
            meta = self._meta()
            return self._write_checkpoint(payload, meta)

    def _seal_locked(self) -> None:
        """Append the segment-sealed marker (``{"t": "seal"}``) to the
        CURRENT segment — called under ``_mu`` at rotation and clean
        close.  A tailer that reads a seal knows the segment is
        complete and continues at the next index; damage after a seal,
        or a superseded segment without one, is a crash, not a
        mid-write tail.  Framing metadata only: not counted in
        ``stats["records"]``, skipped by recovery and replication."""
        if self.wedged or self.degraded_errno is not None:
            return
        data = _dumps({"t": SEAL_TYPE})
        boundary = self._f.tell()
        frame = _HEADER.pack(len(data), zlib.crc32(data) & 0xFFFFFFFF) + data
        try:
            self.io.write(self._f, frame)
            self.io.flush(self._f)
            if self.fsync:
                self.io.fsync(self._f.fileno())
                self.stats["fsyncs"] += 1
        except OSError as e:
            self._handle_write_error(e, boundary)
            return
        self.stats["seals"] += 1
        self.stats["bytes"] += _HEADER.size + len(data)

    def _write_checkpoint(self, payload: Obj, meta: Obj) -> "str | None":
        import contextlib

        with self._mu:
            if self._closed:
                return None
            self.check_writable()
            if self.degraded_errno is not None:
                return None
            new_index = self._seg_index + 1
            doc: Obj = {"t": "checkpoint", "meta": meta, "x": payload}
            if self.last_mark is not None:
                doc["mark"] = self.last_mark
            data = _dumps(doc)
            path = checkpoint_path(self.directory, new_index)
            try:
                with open(path, "wb") as f:
                    f.write(CHECKPOINT_MAGIC)
                    f.write(_HEADER.pack(len(data), zlib.crc32(data) & 0xFFFFFFFF))
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
            except OSError as e:
                # a half-written checkpoint must never be discoverable —
                # remove it before routing the fault (recovery would
                # otherwise count it bad_checkpoint and fall back anyway)
                with contextlib.suppress(OSError):
                    os.unlink(path)
                self._handle_write_error(e, self._f.tell())
                return None
            # rotate, then prune: the checkpoint at index k covers every
            # record in segments < k.  Seal the finished segment FIRST —
            # a tailer mid-segment follows the seal into the new index
            # without ever needing the checkpoint it already replayed.
            self._seal_locked()
            if self.degraded_errno is not None:
                return None
            self._f.close()
            self._seg_index = new_index
            try:
                self._f = self._open_segment(new_index)
            except OSError as e:
                self._handle_write_error(e, 0)
                return None
            # prune failures (e.g. the fs flipped read-only between the
            # checkpoint fsync and here) are GC misses, not durability
            # faults: stale files linger, recovery still picks the
            # newest valid checkpoint
            with contextlib.suppress(OSError):
                for idx, p in list_segments(self.directory):
                    if idx < new_index:
                        os.unlink(p)
                for idx, p in list_checkpoints(self.directory):
                    if idx < new_index:
                        os.unlink(p)
            self._records_since_checkpoint = 0
            # the checkpoint is the new recovery BASE: later records'
            # meta deltas must diff against ITS full meta, or a field
            # that changed record-lessly and reverted would stay frozen
            # at the checkpoint's intermediate value after a merge
            self._last_meta = meta
            self.stats["compactions"] += 1
            return path

    def close(self) -> None:
        import contextlib

        with self._mu:
            if not self._closed:
                # clean shutdown seals the live segment: a follower can
                # tell "primary exited" from "primary crashed mid-write"
                # (_seal_locked is a no-op once wedged/degraded — the
                # unsealed tail is the honest crash-boundary signal)
                self._seal_locked()
                with contextlib.suppress(OSError, ValueError):
                    self._f.close()
                self._closed = True


# ------------------------------------------------------------------- read


def read_records(path: str, magic: bytes = SEGMENT_MAGIC) -> Iterator[tuple[int, "Obj | None"]]:
    """Yield ``(offset, payload)`` per record; a final ``(offset, None)``
    marks a torn tail (short header/payload, oversized length, bad CRC,
    or undecodable JSON) at ``offset`` — the reader NEVER raises on
    damage, matching recovery's truncate-and-count contract.  A file
    whose leading magic is wrong is treated as torn at offset 0."""
    try:
        with open(path, "rb") as f:
            head = f.read(len(magic))
            if head != magic:
                yield (0, None)
                return
            offset = len(magic)
            while True:
                hdr = f.read(_HEADER.size)
                if not hdr:
                    return  # clean EOF
                if len(hdr) < _HEADER.size:
                    yield (offset, None)
                    return
                length, crc = _HEADER.unpack(hdr)
                if length > _MAX_RECORD:
                    yield (offset, None)
                    return
                data = f.read(length)
                if len(data) < length or (zlib.crc32(data) & 0xFFFFFFFF) != crc:
                    yield (offset, None)
                    return
                try:
                    payload = json.loads(data)
                except ValueError:
                    yield (offset, None)
                    return
                yield (offset, payload)
                offset += _HEADER.size + length
    except OSError:
        yield (0, None)


def read_checkpoint(path: str) -> "Obj | None":
    """The checkpoint's payload, or None when the file is damaged
    (counted by recovery, never raised)."""
    for _off, payload in read_records(path, magic=CHECKPOINT_MAGIC):
        if payload is not None and payload.get("t") == "checkpoint":
            return payload
        return None
    return None


# ------------------------------------------------------------------- env


def _env_flag(raw: "str | None") -> bool:
    return (raw or "").strip().lower() not in ("", "0", "off", "false", "no")


def journal_knobs() -> "Obj | None":
    """The documented ``KSS_JOURNAL_*`` / ``KSS_CHECKPOINT_EVERY`` env
    knobs, validated here so a typo fails loudly at boot
    (docs/environment-variables.md).  Returns None when journaling is
    not enabled (``KSS_JOURNAL_DIR`` unset) — the default, under which
    nothing in this module runs."""
    directory = os.environ.get("KSS_JOURNAL_DIR", "").strip()
    if not directory:
        return None
    every_raw = os.environ.get("KSS_CHECKPOINT_EVERY", "").strip()
    try:
        every = int(every_raw) if every_raw else 0
    except ValueError:
        raise JournalError(
            f"KSS_CHECKPOINT_EVERY must be an integer >= 0, got {every_raw!r}"
        ) from None
    if every < 0:
        raise JournalError(f"KSS_CHECKPOINT_EVERY must be >= 0, got {every}")
    return {
        "directory": directory,
        "fsync": _env_flag(os.environ.get("KSS_JOURNAL_FSYNC")),
        "checkpoint_every": every,
        "on_error": on_error_from_env(),
    }


def on_error_from_env() -> str:
    """The validated ``KSS_JOURNAL_ON_ERROR`` policy — read separately
    from :func:`journal_knobs` because the promotion path builds a
    journal for a directory named by ``KSS_REPLICA_OF``, with
    ``KSS_JOURNAL_DIR`` unset."""
    on_error = os.environ.get("KSS_JOURNAL_ON_ERROR", "").strip().lower() or "wedge"
    if on_error not in ("wedge", "degrade"):
        raise JournalError(
            f"KSS_JOURNAL_ON_ERROR must be 'wedge' or 'degrade', got {on_error!r}"
        )
    return on_error


def journal_from_env() -> "Journal | None":
    """A Journal built from the env knobs, or None when disabled."""
    knobs = journal_knobs()
    if knobs is None:
        return None
    return Journal(
        knobs["directory"],
        fsync=knobs["fsync"],
        checkpoint_every=knobs["checkpoint_every"],
        on_error=knobs["on_error"],
    )
