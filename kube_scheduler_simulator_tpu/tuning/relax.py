"""Differentiable rollouts: the straight-through relaxed decision head.

The batch kernel's per-pod decision is an argmax over weighted plugin
scores — piecewise constant in the weights, gradient zero everywhere.
``BatchConfig.relax_tau > 0`` (ops/batch.py) rewrites the commit one-hot
as a straight-through estimator:

    soft = softmax(totals / τ) over the sampled nodes
    oh   = soft + stop_gradient(hard − soft)

Forward values are EXACTLY the hard rollout's (``oh == hard`` as
numbers; the relaxed and hard rollouts agree bit-for-bit — pinned by
tests/test_tuning.py), but the backward pass flows d(committed resource
planes)/d(weights) through the softmax, so a whole rollout's objective
differentiates in the plugin-weight vector.  This is the "Learning to
Score" setting (arXiv 2603.10545): a fixed feasibility oracle with a
learnable scoring head; the GFlowNets robust-scheduling line (arXiv
2302.05446) motivates the temperature-relaxed decision distribution.

Builders here compose the kernel's jitted scan with an on-device
objective (tuning/objective.py) so the tuner loop exchanges ONE scalar
(or one [S] gradient) per dispatch — rollouts never leave the device.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from kube_scheduler_simulator_tpu.ops import batch as B
from kube_scheduler_simulator_tpu.tuning.objective import objective_value


def build_value_fn(
    cfg: "B.BatchConfig",
    dims: dict,
    objective: str,
    relax_tau: float = 0.0,
) -> "Callable[[Any, Any, Any], Any]":
    """``value(dp, w, age_w) -> scalar`` (higher = better): one full
    rollout with the [S] weight vector ``w`` traced in, the objective
    reduced on device.  ``relax_tau > 0`` builds the straight-through
    head; forward values equal the hard build's."""
    cfg = cfg._replace(traced_weights=True, relax_tau=float(relax_tau), trace=False)
    fn = B.build_batch_fn(cfg, dims)

    def value(dp, w, age_w):
        ys = fn(dp._replace(plugin_w=jnp.asarray(w, dp.alloc.dtype)))
        return objective_value(objective, ys, dp, age_w)

    return value


def build_population_fn(value_fn: Callable) -> Callable:
    """``evaluate(dp, W[pop,S], age_w) -> [pop]`` hard objectives in ONE
    dispatch: the rollout vmaps over the weight axis only, the problem
    planes broadcast — a whole CEM generation is a single device call."""
    return jax.jit(jax.vmap(value_fn, in_axes=(None, 0, None)))


def build_grad_fn(value_fn: Callable) -> Callable:
    """``grad(dp, w, age_w) -> (value, dvalue/dw)`` in one dispatch —
    ``value_fn`` must come from a ``relax_tau > 0`` build for the
    gradient to be nonzero."""
    return jax.jit(
        lambda dp, w, age_w: jax.value_and_grad(
            lambda wv: value_fn(dp, wv, age_w)
        )(w)
    )
