"""Volume-plugin batch kernels vs the sequential oracle.

The volume filter family (VolumeBinding, VolumeZone, VolumeRestrictions,
EBS/GCE/AzureDisk limits, CSI NodeVolumeLimits) previously forced any
PVC-mounting workload off the batch path; these suites pin that the
kernels (ops/encode._encode_volumes + ops/batch.py) reproduce the oracle
(plugins/intree/volumes.py) exactly — including the in-round dynamics
(conflicts/counts against pods committed earlier in the same batch) and
byte-identical annotations through SchedulerService.
"""

from __future__ import annotations

from typing import Any

import pytest

from kube_scheduler_simulator_tpu.scheduler.batch_engine import BatchEngine
from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
from kube_scheduler_simulator_tpu.state.store import ClusterStore

from tests.test_batch_parity import mk_node, mk_pod

Obj = dict[str, Any]


def mk_pv(name: str, labels=None, node_affinity=None, csi_driver=None) -> Obj:
    pv: Obj = {
        "metadata": {"name": name, "labels": labels or {}},
        "spec": {"capacity": {"storage": "10Gi"}, "accessModes": ["ReadWriteOnce"]},
    }
    if node_affinity is not None:
        pv["spec"]["nodeAffinity"] = {"required": node_affinity}
    if csi_driver:
        pv["spec"]["csi"] = {"driver": csi_driver, "volumeHandle": name}
    return pv


def mk_pvc(name: str, ns: str = "default", volume_name=None, storage_class=None) -> Obj:
    pvc: Obj = {
        "metadata": {"name": name, "namespace": ns},
        "spec": {"accessModes": ["ReadWriteOnce"], "resources": {"requests": {"storage": "1Gi"}}},
    }
    if volume_name:
        pvc["spec"]["volumeName"] = volume_name
    if storage_class:
        pvc["spec"]["storageClassName"] = storage_class
    return pvc


def mk_sc(name: str, binding_mode: str = "Immediate", provisioner: str = "csi.example.com") -> Obj:
    return {
        "metadata": {"name": name},
        "provisioner": provisioner,
        "volumeBindingMode": binding_mode,
    }


def mk_csinode(node_name: str, driver: str, count: int) -> Obj:
    return {
        "metadata": {"name": node_name},
        "spec": {"drivers": [{"name": driver, "allocatable": {"count": count}}]},
    }


def pvc_volume(claim: str, vol_name: str = "v") -> Obj:
    return {"name": vol_name, "persistentVolumeClaim": {"claimName": claim}}


def run_both_services(build_store, cfg=None, expect_engaged=True):
    """Schedule the same cluster through the sequential and the batch
    service; assert batch engaged (no fallback) and byte-identical pod
    annotations + placements.  Returns the batch service."""
    store_seq = build_store()
    svc_seq = SchedulerService(store_seq, tie_break="first", use_batch="off")
    svc_seq.start_scheduler(cfg)
    svc_seq.schedule_pending(max_rounds=1)

    store_bat = build_store()
    svc_bat = SchedulerService(store_bat, tie_break="first", use_batch="auto", batch_min_work=0)
    svc_bat.start_scheduler(cfg)
    svc_bat.schedule_pending(max_rounds=1)
    if expect_engaged:
        assert svc_bat.stats["batch_commits"] >= 1, svc_bat.stats["batch_fallbacks"]
        assert not svc_bat.stats["batch_fallbacks"], svc_bat.stats["batch_fallbacks"]

    for p_seq in store_seq.list("pods"):
        name = p_seq["metadata"]["name"]
        ns = p_seq["metadata"].get("namespace") or "default"
        p_bat = store_bat.get("pods", name, ns)
        seq_annos = p_seq["metadata"].get("annotations") or {}
        bat_annos = p_bat["metadata"].get("annotations") or {}
        assert seq_annos == bat_annos, (
            f"{ns}/{name} annotation divergence:\n"
            + "\n".join(
                f"  {k}:\n   seq={seq_annos.get(k)}\n   bat={bat_annos.get(k)}"
                for k in sorted(set(seq_annos) | set(bat_annos))
                if seq_annos.get(k) != bat_annos.get(k)
            )
        )
        assert (p_seq.get("spec") or {}).get("nodeName") == (p_bat.get("spec") or {}).get("nodeName"), name
        assert (p_seq.get("status") or {}) == (p_bat.get("status") or {}), name
    return svc_bat


def test_volume_binding_parity():
    """Bound PVs with node affinity pin pods to matching nodes; unbound
    WaitForFirstConsumer passes everywhere; unbound Immediate fails the
    pod on every node — all byte-identical to the oracle."""

    def build_store():
        store = ClusterStore()
        for i in range(4):
            store.create(
                "nodes",
                mk_node(f"node-{i}", 4000, 8192, labels={"zone": f"z{i % 2}", "kubernetes.io/hostname": f"node-{i}"}),
            )
        store.create("storageclasses", mk_sc("wfc", binding_mode="WaitForFirstConsumer"))
        store.create("storageclasses", mk_sc("imm", binding_mode="Immediate"))
        store.create(
            "persistentvolumes",
            mk_pv(
                "pv-z1",
                node_affinity={
                    "nodeSelectorTerms": [
                        {"matchExpressions": [{"key": "zone", "operator": "In", "values": ["z1"]}]}
                    ]
                },
            ),
        )
        store.create("persistentvolumeclaims", mk_pvc("claim-bound", volume_name="pv-z1"))
        store.create("persistentvolumeclaims", mk_pvc("claim-wfc", storage_class="wfc"))
        store.create("persistentvolumeclaims", mk_pvc("claim-imm", storage_class="imm"))
        store.create("pods", mk_pod("pod-bound", cpu_m=100, volumes=[pvc_volume("claim-bound")]))
        store.create("pods", mk_pod("pod-wfc", cpu_m=100, volumes=[pvc_volume("claim-wfc")]))
        store.create("pods", mk_pod("pod-imm", cpu_m=100, volumes=[pvc_volume("claim-imm")]))
        store.create("pods", mk_pod("pod-plain", cpu_m=100))
        return store

    svc = run_both_services(build_store)
    store = svc.cluster_store
    # the bound claim's PV only matches z1 nodes
    assert store.get("pods", "pod-bound")["spec"]["nodeName"] in ("node-1", "node-3")
    assert store.get("pods", "pod-wfc")["spec"].get("nodeName")
    assert not store.get("pods", "pod-imm")["spec"].get("nodeName")


def test_volume_zone_parity():
    """A bound PV carrying zone labels restricts pods to nodes in that
    zone (first-failing-claim semantics, oracle VolumeZone)."""

    def build_store():
        store = ClusterStore()
        for i in range(4):
            store.create(
                "nodes",
                mk_node(
                    f"node-{i}",
                    4000,
                    8192,
                    labels={
                        "topology.kubernetes.io/zone": f"z{i % 2}",
                        "kubernetes.io/hostname": f"node-{i}",
                    },
                ),
            )
        store.create(
            "persistentvolumes",
            mk_pv("pv-zoned", labels={"topology.kubernetes.io/zone": "z0"}),
        )
        store.create("persistentvolumeclaims", mk_pvc("claim-zoned", volume_name="pv-zoned"))
        store.create("pods", mk_pod("pod-zoned", cpu_m=100, volumes=[pvc_volume("claim-zoned")]))
        store.create("pods", mk_pod("pod-free", cpu_m=100))
        return store

    svc = run_both_services(build_store)
    assert svc.cluster_store.get("pods", "pod-zoned")["spec"]["nodeName"] in ("node-0", "node-2")


def test_volume_restrictions_in_round_dynamics():
    """Two pending pods mounting the same (non-readOnly) GCE PD must land
    on different nodes — the second pod's conflict is against a pod
    committed EARLIER IN THE SAME BATCH (the carry update), and a bound
    pod seeds the conflict counts for a third node."""

    def gce_volume(pd: str, ro: bool = False) -> Obj:
        return {"name": "d", "gcePersistentDisk": {"pdName": pd, "readOnly": ro}}

    def build_store():
        store = ClusterStore()
        for i in range(3):
            store.create("nodes", mk_node(f"node-{i}", 4000, 8192))
        blocker = mk_pod("blocker", cpu_m=100, volumes=[gce_volume("disk-a")])
        blocker["spec"]["nodeName"] = "node-0"
        store.create("pods", blocker)
        store.create("pods", mk_pod("pod-1", cpu_m=100, volumes=[gce_volume("disk-a")]))
        store.create("pods", mk_pod("pod-2", cpu_m=100, volumes=[gce_volume("disk-a")]))
        store.create("pods", mk_pod("pod-3", cpu_m=100, volumes=[gce_volume("disk-a")]))
        return store

    svc = run_both_services(build_store)
    store = svc.cluster_store
    placed = {store.get("pods", f"pod-{i}")["spec"].get("nodeName") for i in (1, 2)}
    assert placed == {"node-1", "node-2"}  # node-0 blocked by the bound pod
    assert not store.get("pods", "pod-3")["spec"].get("nodeName")  # no node left


def test_csi_volume_limits_parity():
    """CSI NodeVolumeLimits: per-driver CSINode caps with unique-attachment
    dedup — two pods sharing one PVC consume ONE attachment (may co-locate)
    while distinct PVCs consume distinct ones."""

    def build_store():
        store = ClusterStore()
        for i in range(2):
            store.create("nodes", mk_node(f"node-{i}", 8000, 8192))
            store.create("csinodes", mk_csinode(f"node-{i}", "csi.example.com", 1))
        store.create("storageclasses", mk_sc("wfc", binding_mode="WaitForFirstConsumer"))
        for c in ("shared", "solo-a", "solo-b"):
            store.create("persistentvolumeclaims", mk_pvc(f"claim-{c}", storage_class="wfc"))
        # two pods share one claim: 1 attachment, both fit on one node
        store.create("pods", mk_pod("shared-1", cpu_m=100, volumes=[pvc_volume("claim-shared")]))
        store.create("pods", mk_pod("shared-2", cpu_m=100, volumes=[pvc_volume("claim-shared")]))
        # two pods with distinct claims: second must go to the other node
        store.create("pods", mk_pod("solo-a", cpu_m=100, volumes=[pvc_volume("claim-solo-a")]))
        store.create("pods", mk_pod("solo-b", cpu_m=100, volumes=[pvc_volume("claim-solo-b")]))
        return store

    run_both_services(build_store)


def test_ebs_limits_and_seeded_counts():
    """EBSLimits: per-family counts (no dedup), seeded from bound pods."""

    def ebs_volume(vid: str, name: str) -> Obj:
        return {"name": name, "awsElasticBlockStore": {"volumeID": vid}}

    def build_store():
        store = ClusterStore()
        for i in range(2):
            store.create("nodes", mk_node(f"node-{i}", 64000, 65536, pods=200))
        # node-0 already holds 38 of the 39 allowed EBS attachments
        heavy = mk_pod(
            "heavy", cpu_m=100, volumes=[ebs_volume(f"vol-{j}", f"v{j}") for j in range(38)]
        )
        heavy["spec"]["nodeName"] = "node-0"
        store.create("pods", heavy)
        # wants 2 → only node-1 fits; a 1-volume pod still fits node-0
        store.create(
            "pods", mk_pod("wants-two", cpu_m=100, volumes=[ebs_volume("vol-x", "x"), ebs_volume("vol-y", "y")])
        )
        store.create("pods", mk_pod("wants-one", cpu_m=100, volumes=[ebs_volume("vol-z", "z")]))
        return store

    svc = run_both_services(build_store)
    assert svc.cluster_store.get("pods", "wants-two")["spec"]["nodeName"] == "node-1"


def test_missing_pvc_falls_back_sequential():
    """A pod referencing a missing PVC is a VolumeBinding PreFilter reject
    — the round de-batches and the sequential path records the exact
    '%s not found' unresolvable result."""

    def build_store():
        store = ClusterStore()
        store.create("nodes", mk_node("node-0", 4000, 8192))
        store.create("pods", mk_pod("pod-ghost", cpu_m=100, volumes=[pvc_volume("nope")]))
        return store

    svc = run_both_services(build_store, expect_engaged=False)
    assert any(
        "missing PersistentVolumeClaim" in reason for reason in svc.stats["batch_fallbacks"]
    ), svc.stats["batch_fallbacks"]
    assert not svc.cluster_store.get("pods", "pod-ghost")["spec"].get("nodeName")


def test_volume_workload_no_longer_forces_fallback():
    """The default full profile with PVC-mounting pods stays on the batch
    path (was: any volume de-batched the whole round)."""
    store = ClusterStore()
    for i in range(3):
        store.create("nodes", mk_node(f"node-{i}", 4000, 8192))
    store.create("storageclasses", mk_sc("wfc", binding_mode="WaitForFirstConsumer"))
    store.create("persistentvolumeclaims", mk_pvc("c1", storage_class="wfc"))
    store.create("pods", mk_pod("p1", cpu_m=100, volumes=[pvc_volume("c1")]))

    svc = SchedulerService(store, tie_break="first", use_batch="auto", batch_min_work=0)
    svc.start_scheduler(None)  # FULL default profile
    fw = svc.framework
    eng = BatchEngine.from_framework(fw, trace=True)
    pending = fw.sort_pods(svc.pending_pods())
    ok, why = eng.supported(pending, store.list("nodes"))
    assert ok, why


@pytest.mark.parametrize("seed", [4242, 7, 99, 1001, 31337])
def test_mixed_everything_differential_full_default_profile(seed):
    """Cross-feature differential: one workload exercising EVERY kernel
    family at once — volumes (bound/WFC PVCs, gce conflicts, CSI limits),
    host ports, images, taints, node+inter-pod affinity, spread — through
    the FULL default profile with feasible-node sampling off, batch vs
    sequential byte-identical annotations and placements, across seeds."""
    import random

    def build_store():
        rng = random.Random(seed)  # seeded per build: both stores identical
        # fixed clock: PrioritySort orders the round by creationTimestamp,
        # and the two stores are built SECONDS apart under a loaded full
        # run — a wall-clock second boundary landing mid-build in one
        # store but not the other used to partition the name-ordered
        # pending set differently (older-stamp group first), diverging
        # the round order and thus the bytes (the rare full-run-only
        # flake).  Identical stamps make the two builds identical inputs.
        store = ClusterStore(clock=lambda: 1700000000.0)
        store.create("storageclasses", mk_sc("wfc", binding_mode="WaitForFirstConsumer"))
        store.create(
            "persistentvolumes",
            mk_pv(
                "pv-pinned",
                labels={"topology.kubernetes.io/zone": "z0"},
                node_affinity={
                    "nodeSelectorTerms": [
                        {"matchExpressions": [{"key": "disk", "operator": "In", "values": ["ssd"]}]}
                    ]
                },
            ),
        )
        store.create("persistentvolumeclaims", mk_pvc("claim-pinned", volume_name="pv-pinned"))
        for c in range(4):
            store.create("persistentvolumeclaims", mk_pvc(f"claim-wfc-{c}", storage_class="wfc"))
        for i in range(12):
            node = mk_node(
                f"node-{i}",
                8000,
                16384,
                labels={
                    "topology.kubernetes.io/zone": f"z{i % 3}",
                    "kubernetes.io/hostname": f"node-{i}",
                    "disk": "ssd" if i % 2 else "hdd",
                },
                taints=[{"key": "spot", "value": "t", "effect": "PreferNoSchedule"}] if i % 5 == 0 else None,
            )
            node["status"]["images"] = (
                [{"names": [f"img-{i % 2}:v1"], "sizeBytes": 400 * 1024 * 1024}] if i % 3 == 0 else []
            )
            store.create("nodes", node)
            store.create("csinodes", mk_csinode(f"node-{i}", "csi.example.com", 2))
        for i in range(36):
            p = mk_pod(
                f"pod-{i}",
                cpu_m=rng.choice([100, 250, 500]),
                mem_mi=rng.choice([128, 256]),
                labels={"app": f"app-{i % 4}"},
            )
            spec = p["spec"]
            spec["containers"][0]["image"] = f"img-{i % 2}:v1"
            if i % 6 == 0:
                spec["volumes"] = [pvc_volume("claim-pinned")]
            elif i % 6 == 1:
                spec["volumes"] = [pvc_volume(f"claim-wfc-{i % 4}")]
            elif i % 6 == 2:
                spec["volumes"] = [
                    {"name": "d", "gcePersistentDisk": {"pdName": f"disk-{i % 3}", "readOnly": i % 2 == 0}}
                ]
            if i % 7 == 0:
                spec["containers"][0]["ports"] = [{"containerPort": 80, "hostPort": 8000 + (i % 3)}]
            if i % 4 == 0:
                spec["nodeSelector"] = {"disk": "ssd"}
            if i % 3 == 0:
                spec["topologySpreadConstraints"] = [
                    {
                        "maxSkew": 3,
                        "topologyKey": "topology.kubernetes.io/zone",
                        "whenUnsatisfiable": "DoNotSchedule",
                        "labelSelector": {"matchLabels": {"app": f"app-{i % 4}"}},
                    }
                ]
            if i % 5 == 1:
                spec["affinity"] = {
                    "podAntiAffinity": {
                        "preferredDuringSchedulingIgnoredDuringExecution": [
                            {
                                "weight": 7,
                                "podAffinityTerm": {
                                    "labelSelector": {"matchLabels": {"app": f"app-{i % 4}"}},
                                    "topologyKey": "kubernetes.io/hostname",
                                },
                            }
                        ]
                    }
                }
            store.create("pods", p)
        return store

    svc = run_both_services(build_store, cfg={"percentageOfNodesToScore": 100})
    assert svc.stats["batch_pods"] > 0


def test_volume_kernels_mesh_sharded_parity():
    """The volume carries (restr_used / cloud_used / csi_attached /
    csi_seed_used / csi_limit) are node-axis state — under a mesh they
    shard like the resource carries, and the sharded engine must select
    identically to the single-device one."""
    from tests.test_batch_parity import run_single_vs_sharded

    nodes = [
        mk_node(f"node-{i}", 8000, 16384, labels={"zone": f"z{i % 2}", "kubernetes.io/hostname": f"node-{i}"})
        for i in range(16)
    ]
    volumes = {
        "storageclasses": [mk_sc("wfc", binding_mode="WaitForFirstConsumer")],
        "persistentvolumes": [
            mk_pv(
                "pv-z1",
                node_affinity={
                    "nodeSelectorTerms": [
                        {"matchExpressions": [{"key": "zone", "operator": "In", "values": ["z1"]}]}
                    ]
                },
            )
        ],
        "persistentvolumeclaims": [
            mk_pvc("claim-bound", volume_name="pv-z1"),
            mk_pvc("claim-a", storage_class="wfc"),
            mk_pvc("claim-b", storage_class="wfc"),
        ],
        "csinodes": [mk_csinode(f"node-{i}", "csi.example.com", 1) for i in range(16)],
    }
    pods = []
    for i in range(12):
        p = mk_pod(f"pod-{i}", cpu_m=300, mem_mi=256)
        if i % 4 == 0:
            p["spec"]["volumes"] = [pvc_volume("claim-bound")]
        elif i % 4 == 1:
            p["spec"]["volumes"] = [pvc_volume("claim-a" if i % 8 == 1 else "claim-b")]
        elif i % 4 == 2:
            p["spec"]["volumes"] = [{"name": "d", "gcePersistentDisk": {"pdName": f"disk-{i % 3}"}}]
        pods.append(p)

    filters = [
        "NodeUnschedulable", "NodeResourcesFit", "VolumeRestrictions", "EBSLimits",
        "GCEPDLimits", "NodeVolumeLimits", "AzureDiskLimits", "VolumeBinding", "VolumeZone",
    ]
    scores = [("NodeResourcesFit", 1)]

    res1, _res2 = run_single_vs_sharded(nodes, pods, filters, scores, volumes=volumes)
    # the volume constraints actually bit: bound-PV pods on z1 nodes only
    for i in (0, 4, 8):
        sel = res1.selected_nodes[i]
        assert sel is not None and int(sel.split("-")[1]) % 2 == 1, (i, sel)
