"""Byte-parity verdicts: diff two runs' annotation trails and classify.

A comparison's surface is :func:`utils.parity.pod_parity_state` — the
binding, the full sorted annotation trail, and the failure conditions,
per pod — the SAME surface every existing parity harness compares (a
drifting comparator copy is itself a bug class; see utils/parity.py).

Classification: the engines are allowed to take different *routes* to
the same bytes — exactness gates drain batch rounds to the sequential
cycle, stream waves to the serial path, preemption to the host oracle —
and every such drain is **counted** (``batch_fallbacks``,
``stream_drains_by_reason``, ``preempt_fallbacks``, ``gang_fallbacks``,
``kernel error: *``).  A verdict therefore carries two things: the byte
diff (any mismatch at all is a **divergence** — gates never excuse
bytes) and the counted-gate deltas observed during the run (the
*explained* routing detours, reported for triage and for the smoke's
composition histogram).
"""

from __future__ import annotations

from typing import Any

Obj = dict[str, Any]

# the service counters whose deltas "explain" a run's routing detours
GATE_COUNTERS = (
    "batch_fallbacks",
    "preempt_fallbacks",
    "gang_fallbacks",
    "stream_drains_by_reason",
    "encode_fallbacks_by_reason",
)


def gate_snapshot(metrics: Obj) -> dict[str, dict[str, int]]:
    """The counted exactness-gate maps out of a ``service.metrics()``."""
    return {k: dict(metrics.get(k) or {}) for k in GATE_COUNTERS}


def gate_delta(before: dict, after: dict) -> dict[str, dict[str, int]]:
    """Per-reason counter deltas between two gate snapshots, zero rows
    dropped."""
    out: dict[str, dict[str, int]] = {}
    for k in GATE_COUNTERS:
        d = {
            reason: after.get(k, {}).get(reason, 0) - before.get(k, {}).get(reason, 0)
            for reason in set(after.get(k, {})) | set(before.get(k, {}))
        }
        d = {r: n for r, n in sorted(d.items()) if n}
        if d:
            out[k] = d
    return out


def diff_states(a: Obj, b: Obj) -> list[Obj]:
    """Pod-level byte mismatches between two parity states: missing pods
    and differing rows, in sorted pod order."""
    out: list[Obj] = []
    for key in sorted(set(a) | set(b)):
        ra, rb = a.get(key), b.get(key)
        if ra != rb:
            out.append({"pod": key, "a": _row(ra), "b": _row(rb)})
    return out


def _row(row: Any) -> Any:
    """JSON-serializable form of a parity row (tuples -> lists)."""
    if row is None:
        return None
    node, annotations, *rest = row
    return [node, [list(kv) for kv in annotations], *rest]


def compare(kind: str, state_a: Obj, state_b: Obj, explained: "Obj | None" = None) -> Obj:
    """One comparison verdict; ``equal`` is the whole judgment — the
    ``explained`` gate deltas are triage context, never an excuse."""
    mismatches = diff_states(state_a, state_b)
    return {
        "kind": kind,
        "equal": not mismatches,
        "mismatch_count": len(mismatches),
        # the full diff can be megabytes of annotation text; the verdict
        # keeps the first mismatch (the shrinker re-derives the rest)
        "first_mismatch": mismatches[0] if mismatches else None,
        "explained": explained or {},
    }


def verdict(scenario: Obj, comparisons: list[Obj]) -> Obj:
    """The scenario-level verdict: comparisons + the divergence list."""
    return {
        "scenario": scenario["name"],
        "features": list(scenario["features"]),
        "comparisons": comparisons,
        "divergences": [c["kind"] for c in comparisons if not c["equal"]],
    }
