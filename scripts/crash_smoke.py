#!/usr/bin/env python
"""Crash-consistency smoke (tier-1): the process-kill adversary.

A fixed churn scenario runs as a JOURNALED subprocess on the batch
path (wave-atomic commit records, small commit waves, mid-run
checkpoint compaction), is SIGKILLed at three seeded journal-record
indices (early / middle / late), recovered in a fresh process, and
finished — the recovered run's full annotation trail must be
byte-identical to an uninterrupted run at every kill point, with

- ``recovery_truncated_records_total == 0`` (a SIGKILL at a record
  boundary never tears a record),
- zero partially-committed waves observable (wave records are atomic —
  divergence would expose one) and zero partially-bound gang groups,
- compaction engaged at least once (the checkpoint + rotation path is
  exercised, not just the flat log).

Then the metrics wiring: a live in-process journaled service must
surface the ``journal_*`` / ``checkpoint_*`` / ``recovery_*`` counters
through ``/metrics`` (docs/durability.md).

Exit 0 = crash parity holds; nonzero = divergence or harness failure.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:  # the axon plugin dials the TPU tunnel even when CPU-pinned
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass


def _node(i: int) -> dict:
    return {
        "op": "create",
        "kind": "nodes",
        "object": {
            "metadata": {"name": f"crn-{i}", "labels": {"zone": f"z{i % 2}"}},
            "status": {
                "allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"},
                "capacity": {"cpu": "8", "memory": "16Gi", "pods": "110"},
            },
        },
    }


def _pod(i: int, cpu: str = "500m") -> dict:
    return {
        "op": "create",
        "kind": "pods",
        "object": {
            "metadata": {"name": f"crp-{i}"},
            "spec": {
                "containers": [
                    {"name": "c", "resources": {"requests": {"cpu": cpu, "memory": "256Mi"}}}
                ]
            },
        },
    }


def smoke_scenario() -> dict:
    """Fixed journaled-churn timeline: node adds, pod storms sized to
    produce multiple commit waves (commit_wave=4), a pod delete, a node
    delete, and a taint patch — every tick a different mutation class."""
    return {
        "name": "crash-smoke",
        "features": ["churn"],
        "stepSeconds": 1.0,
        "profile": "default",
        "ticks": [
            [_node(0), _node(1)] + [_pod(i) for i in range(8)],
            [_pod(i) for i in range(8, 14)]
            + [{"op": "delete", "kind": "pods", "name": "crp-1", "namespace": "default"}],
            [
                _node(2),
                {
                    "op": "patch",
                    "kind": "nodes",
                    "name": "crn-0",
                    "body": {"spec": {"unschedulable": True}},
                },
            ]
            + [_pod(i) for i in range(14, 18)],
            [
                {"op": "delete", "kind": "nodes", "name": "crn-1"},
                {
                    "op": "patch",
                    "kind": "nodes",
                    "name": "crn-0",
                    "body": {"spec": {"unschedulable": None}},
                },
                _pod(18),
            ],
        ],
    }


def main() -> int:
    from kube_scheduler_simulator_tpu.fuzz.chaos import ProcessChaos

    t0 = time.monotonic()
    role = {"use_batch": "auto", "commit_wave": 4, "checkpoint_every": 10}
    # seeds normalize against the baseline's record count: 5 lands
    # early, the primes land mid/late (spread by modulo)
    chaos = ProcessChaos(
        smoke_scenario(), kill_records=(5, 19, 10**9 + 7), role=role, child_timeout_s=240
    )
    v = chaos.run()
    print(
        f"crash-smoke: records={v['records']} kill_points={v['kill_points']} "
        f"replayed={v['replayed_records']} compactions={v['journal'].get('compactions')}"
    )
    if v["divergences"]:
        print(
            "crash-smoke FAIL: recovered run diverged at kill points "
            f"{v['divergences']}: {json.dumps(v['first_mismatch'])[:4000]}",
            file=sys.stderr,
        )
        return 1
    if v["truncated_records"] != 0:
        print(
            f"crash-smoke FAIL: recovery_truncated_records_total={v['truncated_records']} "
            "after clean SIGKILLs (records must never tear at a kill boundary)",
            file=sys.stderr,
        )
        return 1
    if v["partial_gangs"] != 0:
        print(f"crash-smoke FAIL: {v['partial_gangs']} partially-bound gangs", file=sys.stderr)
        return 1
    if v["replayed_records"] <= 0:
        print("crash-smoke FAIL: recovery never replayed a record", file=sys.stderr)
        return 1
    if int(v["journal"].get("compactions") or 0) <= 0:
        print("crash-smoke FAIL: checkpoint compaction never engaged", file=sys.stderr)
        return 1

    # ---- metrics wiring: a live journaled service surfaces the counters
    from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
    from kube_scheduler_simulator_tpu.server.metrics import render_metrics
    from kube_scheduler_simulator_tpu.state.journal import Journal
    from kube_scheduler_simulator_tpu.state.recovery import RecoveryManager
    from kube_scheduler_simulator_tpu.state.store import ClusterStore
    from kube_scheduler_simulator_tpu.utils.simclock import SimClock

    with tempfile.TemporaryDirectory(prefix="kss-crash-metrics-") as td:
        store = ClusterStore(clock=SimClock(1_700_000_000.0))
        journal = Journal(td)
        store.attach_journal(journal)
        store.create("namespaces", {"metadata": {"name": "default"}})
        store.create("nodes", {"metadata": {"name": "m1"}})
        # recover the journaled history into a scratch store, then hang
        # the recovery stats on the RENDERED store — the wiring under
        # test is service.metrics() -> render_metrics surfacing them
        store2 = ClusterStore(clock=SimClock(0.0))
        store.recovery_stats = RecoveryManager(td).recover(store2).stats()
        svc = SchedulerService(store, use_batch="off")
        svc.start_scheduler(None)

        class _DI:
            cluster_store = store

            def scheduler_service(self):
                return svc

        text = render_metrics(_DI())
        for needle in (
            "simulator_journal_records_total",
            "simulator_journal_bytes_written_total",
            "simulator_checkpoint_compactions_total",
            "simulator_recovery_replayed_records_total",
            "simulator_recovery_truncated_records_total",
        ):
            if needle not in text:
                print(f"crash-smoke FAIL: /metrics missing {needle}", file=sys.stderr)
                return 1
        if "simulator_journal_records_total 0" in text:
            print("crash-smoke FAIL: journaled service reports zero records", file=sys.stderr)
            return 1
        if "simulator_recovery_replayed_records_total 0" in text:
            print("crash-smoke FAIL: recovery stats not surfaced in /metrics", file=sys.stderr)
            return 1

    wall = time.monotonic() - t0
    print(
        f"crash-smoke OK: {len(v['kill_points'])} kill points byte-identical after "
        f"recovery ({v['records']} records, {v['replayed_records']} replayed, "
        f"{v['journal'].get('compactions')} compactions, 0 torn, 0 partial waves/gangs), "
        f"metrics wired; {wall:.0f}s"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
