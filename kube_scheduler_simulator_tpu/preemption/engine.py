"""The preemption round context: supportability gates + decision driver.

``prepare_round`` builds (or refuses to build, with a reason) the encoded
victim-search state for one batch kernel run; ``PreemptionRound.decide``
then turns one replay window's kernel failures into oracle-identical
preemption decisions with ONE vmapped device dispatch for the whole
window (preemption/kernel.py), ranking candidates on the host with
pickOneNodeForPreemption's exact lexicographic criteria.

Exactness envelope (everything outside it falls back to the sequential
DefaultPreemption cycle, counted per reason):

- the profile's PostFilter is exactly DefaultPreemption, with no
  preempt-verb extenders;
- no pod in the cluster carries required anti-affinity (evicting such a
  victim could resolve an InterPodAffinity failure the kernel diagnosis
  recorded as final);
- the unschedulable pod requests no host ports and mounts no volumes,
  and has no required spread constraints or required pod
  (anti-)affinity — leaving NodeResourcesFit as the only resolvable
  filter, whose victim arithmetic the kernel reproduces bit-exactly.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from kube_scheduler_simulator_tpu.plugins.intree.queue_bind import pod_priority
from kube_scheduler_simulator_tpu.preemption import encode as PE
from kube_scheduler_simulator_tpu.preemption import kernel as PK

Obj = dict[str, Any]

_I64_MIN = np.iinfo(np.int64).min
_I64_MAX = np.iinfo(np.int64).max


class Decision:
    """One pod's PostFilter outcome: ``node_name`` (nomination) plus the
    victims in the oracle's delete order, or a no-candidates failure
    (``node_name is None``)."""

    __slots__ = ("node_name", "victims")

    def __init__(self, node_name: "str | None", victims: "list[Obj] | None" = None):
        self.node_name = node_name
        self.victims = victims or []


def _has_host_ports(pod: Obj) -> bool:
    for c in (pod.get("spec") or {}).get("containers") or []:
        for prt in c.get("ports") or []:
            if prt.get("hostPort"):
                return True
    return False


def _required_spread(pod: Obj) -> bool:
    for tsc in (pod.get("spec") or {}).get("topologySpreadConstraints") or []:
        if (tsc.get("whenUnsatisfiable") or "DoNotSchedule") == "DoNotSchedule":
            return True
    return False


def _required_pod_affinity(pod: Obj) -> bool:
    aff = (pod.get("spec") or {}).get("affinity") or {}
    for kind in ("podAffinity", "podAntiAffinity"):
        if (aff.get(kind) or {}).get("requiredDuringSchedulingIgnoredDuringExecution"):
            return True
    return False


def _required_anti_affinity(pod: Obj) -> bool:
    aff = (pod.get("spec") or {}).get("affinity") or {}
    return bool((aff.get("podAntiAffinity") or {}).get("requiredDuringSchedulingIgnoredDuringExecution"))


def pod_search_gate(pod: Obj) -> "str | None":
    """Why this unschedulable pod's victim search can't run batched (None
    = supported)."""
    if _has_host_ports(pod):
        return "preemptor requests host ports"
    if (pod.get("spec") or {}).get("volumes"):
        return "preemptor mounts volumes"
    if _required_spread(pod):
        return "preemptor has required topology spread constraints"
    if _required_pod_affinity(pod):
        return "preemptor has required pod (anti-)affinity"
    return None


def nomination_gate(nominated: "list[tuple[Obj, str]]", round_pods: list[Obj]) -> "str | None":
    """Why pending nominations can't be modeled as filter-only usage for
    this round's kernel runs (None = modelable).  The model adds each
    nominee's requests/count to the Fit filter state on its nominated
    node (ops/encode.py ``nominated=``); that is exact only when every
    round pod must unconditionally respect every reservation (priority
    <=) and no non-monotone filter can observe the difference."""
    if not nominated:
        return None
    min_nom = min(pod_priority(p) for p, _nn in nominated)
    for p, _nn in nominated:
        if _has_host_ports(p):
            return "nominated pod requests host ports"
        if (p.get("spec") or {}).get("volumes"):
            return "nominated pod mounts volumes"
        if _required_anti_affinity(p):
            return "nominated pod has required anti-affinity"
    for p in round_pods:
        if pod_priority(p) > min_nom:
            return "pending pod outranks a nomination"
        if _required_spread(p):
            return "pending pod has required topology spread constraints"
        if _required_pod_affinity(p):
            return "pending pod has required pod (anti-)affinity"
    return None


class PreemptionRound:
    """Victim-search state for one batch kernel run over ``tail``."""

    def __init__(self, pr: "PE.PreemptionProblem", tail: list[Obj], fit_k: int,
                 ureq_all: np.ndarray, uprio_all: np.ndarray,
                 pod_reasons: "list[str | None]", n_true: int, mesh: Any = None):
        self.pr = pr
        self.tail = tail
        self.fit_k = fit_k  # NodeResourcesFit's index in cfg.filters, -1 if absent
        self.ureq_all = ureq_all  # [T,R] GCD-scaled requests, tail order
        self.uprio_all = uprio_all  # [T]
        self.pod_reasons = pod_reasons  # per tail pod: unsupported reason or None
        self.n_true = n_true
        # the engine's node-axis mesh: the victim search shards its [N,...]
        # planes over the same devices the main scan shards over
        self.mesh = mesh
        # usage committed by earlier windows of this kernel run (scaled)
        self._extra_req = np.zeros_like(pr.base_req)
        self._extra_cnt = np.zeros_like(pr.base_cnt)
        self.kernel_s = 0.0
        self.dispatches = 0
        self.sharded_dispatches = 0

    def note_success(self, tail_idx: int, node_id: int) -> None:
        """Record a committed bind from an already-replayed window, so
        later windows' dry runs see its usage."""
        self._extra_req[node_id] += self.ureq_all[tail_idx]
        self._extra_cnt[node_id] += 1

    # ------------------------------------------------------------- decide

    def decide(self, result: Any, off: int, cnt: int) -> "dict[int, Decision | str]":
        """Decisions for every kernel-failed pod of one replay window
        (window-local index -> Decision, or a fallback-reason string for
        pods outside the exactness envelope).  One device dispatch."""
        sel = result.selected
        fails = [j for j in range(cnt) if int(sel[j]) < 0]
        if not fails:
            return {}
        out: dict[int, "Decision | str"] = {}
        batched: list[int] = []
        for j in fails:
            reason = self.pod_reasons[off + j]
            if reason is None:
                narrowed = result._prefilter_node_set(j)
                if narrowed is not None and not narrowed:
                    # the oracle returns BEFORE PostFilter when PreFilter
                    # narrowing excluded every node — only the sequential
                    # cycle reproduces that result shape
                    reason = "prefilter narrowed to zero nodes"
            if reason is not None:
                out[j] = reason
            else:
                batched.append(j)
        if not batched:
            return out
        pr = self.pr
        N = self.n_true
        U = len(batched)
        ucand = np.zeros((U, N), dtype=bool)
        any_cand = False
        for u, j in enumerate(batched):
            ids = result.fit_failed_ids(j)
            if ids.size:
                ucand[u, ids] = True
                any_cand = True
        if not any_cand or pr.V == 0:
            for j in batched:
                out[j] = Decision(None)
            return out
        ureq = self.ureq_all[[off + j for j in batched]]
        uprio = self.uprio_all[[off + j for j in batched]]
        # same-window prefix commits: successes at earlier queue positions
        succ = [j for j in range(cnt) if int(sel[j]) >= 0]
        snode = np.array([int(sel[j]) for j in succ], dtype=np.int32)
        sreq = (
            self.ureq_all[[off + j for j in succ]]
            if succ
            else np.zeros((0, ureq.shape[1]), dtype=np.int64)
        )
        smask = np.zeros((U, len(succ)), dtype=bool)
        for u, j in enumerate(batched):
            for s, js in enumerate(succ):
                smask[u, s] = js < j

        base_req, base_cnt = pr.base_req, pr.base_cnt
        pr.base_req = base_req + self._extra_req
        pr.base_cnt = base_cnt + self._extra_cnt
        t0 = time.perf_counter()
        try:
            masks = PK.run_search(
                pr, ucand, ureq, uprio, smask, sreq, snode, mesh=self.mesh
            )
        finally:
            pr.base_req, pr.base_cnt = base_req, base_cnt
        self.kernel_s += time.perf_counter() - t0
        self.dispatches += 1
        if self.mesh is not None:
            self.sharded_dispatches += 1

        cand, victims, viol = masks["cand"], masks["victims"], masks["viol"]
        vp = pr.vprio[None, :, :]
        vstart = pr.vstart[None, :, :]
        real = victims  # [U,N,V]
        num_viol = (real & viol).sum(axis=-1)
        nvict = real.sum(axis=-1)
        high_prio = np.max(np.where(real, vp, _I64_MIN), axis=-1)
        sum_prio = np.sum(np.where(real, vp, 0), axis=-1)
        is_high = real & (vp == high_prio[..., None])
        earliest = np.min(np.where(is_high, vstart, _I64_MAX), axis=-1)
        sample_start = result.out["sample_start"]
        for u, j in enumerate(batched):
            ids = np.nonzero(cand[u])[0]
            if ids.size == 0:
                out[j] = Decision(None)
                continue
            # pickOneNodeForPreemption's lexicographic criteria; final
            # tie-break = the oracle's diagnosis-map insertion order,
            # which is the filter loop's rotated visit order
            start_u = int(sample_start[j])
            rank = (ids - start_u) % self.n_true
            best, best_key = None, None
            for pos, n in enumerate(ids):
                key = (
                    int(num_viol[u, n]),
                    int(high_prio[u, n]),
                    int(sum_prio[u, n]),
                    int(nvict[u, n]),
                    -int(earliest[u, n]),
                    int(rank[pos]),
                )
                if best_key is None or key < best_key:
                    best, best_key = int(n), key
            sl = np.nonzero(victims[u, best])[0]
            vio_row = viol[u, best]
            ordered = [s for s in sl if vio_row[s]] + [s for s in sl if not vio_row[s]]
            out[j] = Decision(
                pr.node_names[best], [pr.victim_pods[best][int(s)] for s in ordered]
            )
        return out


def prepare_round(
    fw: Any,
    eng: Any,
    snapshot: Any,
    store: Any,
    nodes: list[Obj],
    tail: list[Obj],
    nominated: "list[tuple[Obj, str]] | None" = None,
) -> "tuple[PreemptionRound | None, str | None]":
    """Build the round context, or (None, reason) when the batched search
    can't be exact for this profile × cluster (per-POD gates are softer:
    they fall back pod-by-pod inside ``decide``)."""
    post = [wp.original.name for wp in fw.plugins["post_filter"]]
    if post != ["DefaultPreemption"]:
        return None, f"post-filter plugins {post} have no batch kernel"
    ext = getattr(fw, "extender_service", None)
    if ext is not None and any(e.preempt_verb for e in ext.extenders):
        return None, "preempt-verb extenders configured"
    if snapshot.have_pods_with_required_anti_affinity():
        return None, "pods with required anti-affinity present"

    try:
        pdbs = store.list("poddisruptionbudgets", copy_objects=False)
    except Exception:
        pdbs = []

    # node index space = the kernel run's ``nodes`` order (what the trace
    # planes' ids mean), NOT snapshot order
    from kube_scheduler_simulator_tpu.models.nodeinfo import NodeInfo

    by_name = {ni.name: ni for ni in snapshot.node_infos}
    nis = [
        by_name.get(nd["metadata"]["name"]) or NodeInfo(nd) for nd in nodes
    ]
    resource_names = PE.fit_resource_axis(tail)
    max_prio = max((pod_priority(p) for p in tail), default=0)
    pr = PE.encode_preemption(
        nis, resource_names, pdbs, nominated=nominated, max_pending_priority=max_prio
    )
    T, R = len(tail), len(resource_names)
    res_idx = pr.res_idx
    ureq_all = np.zeros((T, R), dtype=np.int64)
    uprio_all = np.zeros(T, dtype=np.int64)
    reasons: "list[str | None]" = []
    for t, p in enumerate(tail):
        ureq_all[t] = PE._req_vec(p, res_idx)
        uprio_all[t] = pod_priority(p)
        reasons.append(pod_search_gate(p))
    # one GCD per resource column across every array that meets in a
    # compare — device floats stay exact (see ops/encode.py)
    for r in range(R):
        PE.gcd_scale_columns(
            [pr.alloc[:, r], pr.base_req[:, r], pr.vreq[:, :, r], ureq_all[:, r]]
        )
    cfg_filters = eng.cfg.filters
    fit_k = cfg_filters.index("NodeResourcesFit") if "NodeResourcesFit" in cfg_filters else -1
    return (
        PreemptionRound(
            pr, tail, fit_k, ureq_all, uprio_all, reasons, len(nis),
            mesh=getattr(eng, "mesh", None),
        ),
        None,
    )
