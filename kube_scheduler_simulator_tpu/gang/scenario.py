"""The distributed-training scenario family: gangs with churn.

``make_training_scenario`` emits a KEP-140 Scenario whose operations
model a DL training cluster: jobs (PodGroup + member pods) arrive over
MajorSteps, run for a few steps, and complete (members + group deleted),
so every replay exercises arrival churn, all-or-nothing release waves,
and the capacity freed by completions — the workload class the gang
engine exists for.  Everything is seeded ``random.Random`` + counter
names, so the same arguments always produce the same Scenario and — with
a ScenarioClock-driven service — the same byte-identical replay.

Used by tests/test_gang.py, the cfg8-gang bench row (bench.py
--gang-report), and the tier-1 gang smoke (scripts/gang_smoke.py).
"""

from __future__ import annotations

import random
from typing import Any

from kube_scheduler_simulator_tpu.gang.podgroups import POD_GROUP_LABEL

Obj = dict[str, Any]

ZONES = ("zone-a", "zone-b", "zone-c", "zone-d")


def make_node(name: str, cpu: int, zone: str) -> Obj:
    return {
        "metadata": {
            "name": name,
            "labels": {
                "kubernetes.io/hostname": name,
                "topology.kubernetes.io/zone": zone,
            },
        },
        "status": {
            "allocatable": {"cpu": str(cpu), "memory": "256Gi", "pods": "110"}
        },
    }


def make_member(name: str, group: str, cpu: str = "1") -> Obj:
    return {
        "metadata": {"name": name, "namespace": "default", "labels": {POD_GROUP_LABEL: group}},
        "spec": {
            "containers": [
                {"name": "trainer", "resources": {"requests": {"cpu": cpu, "memory": "1Gi"}}}
            ]
        },
    }


def make_training_scenario(
    jobs: int = 12,
    min_members: int = 2,
    max_members: int = 8,
    nodes: int = 8,
    node_cpu: int = 16,
    arrival_majors: int = 4,
    complete_after: int = 2,
    member_cpu: str = "1",
    timeout_s: float = 120.0,
    seed: int = 0,
) -> Obj:
    """A Scenario: ``nodes`` nodes at major 1, then ``jobs`` training
    jobs arriving round-robin over ``arrival_majors`` majors, each
    completing (pods + group deleted) ``complete_after`` majors after
    arrival."""
    rng = random.Random(seed)
    ops: list[Obj] = []
    oid = 0

    def op(major: int, field: str, body: Obj) -> None:
        nonlocal oid
        oid += 1
        ops.append({"id": str(oid), "step": {"major": major}, field: body})

    for i in range(nodes):
        op(
            1,
            "createOperation",
            {
                "typeMeta": {"kind": "Node"},
                "object": make_node(f"node-{i}", node_cpu, ZONES[i % len(ZONES)]),
            },
        )

    job_members: dict[int, int] = {}
    job_major: dict[int, int] = {}
    for j in range(jobs):
        arrive = 2 + (j % max(arrival_majors, 1))
        job_major[j] = arrive
        members = rng.randint(min_members, max_members)
        job_members[j] = members
        op(
            arrive,
            "createOperation",
            {
                "typeMeta": {"kind": "PodGroup"},
                "object": {
                    "metadata": {"name": f"job-{j}", "namespace": "default"},
                    "spec": {
                        "minMember": members,
                        "scheduleTimeoutSeconds": timeout_s,
                        "topologyPackKey": "topology.kubernetes.io/zone",
                    },
                },
            },
        )
        for m in range(members):
            op(
                arrive,
                "createOperation",
                {
                    "typeMeta": {"kind": "Pod"},
                    "object": make_member(f"job-{j}-m{m}", f"job-{j}", member_cpu),
                },
            )

    last_major = 2 + max(arrival_majors, 1) + complete_after
    for j in range(jobs):
        done_at = job_major[j] + complete_after
        for m in range(job_members[j]):
            op(
                done_at,
                "deleteOperation",
                {
                    "typeMeta": {"kind": "Pod"},
                    "objectMeta": {"name": f"job-{j}-m{m}", "namespace": "default"},
                },
            )
        op(
            done_at,
            "deleteOperation",
            {
                "typeMeta": {"kind": "PodGroup"},
                "objectMeta": {"name": f"job-{j}", "namespace": "default"},
            },
        )
        last_major = max(last_major, done_at)

    op(last_major + 1, "doneOperation", {})
    return {
        "metadata": {"name": f"training-churn-{seed}", "namespace": "default"},
        "spec": {"operations": ops, "stepSeconds": 1.0},
    }
