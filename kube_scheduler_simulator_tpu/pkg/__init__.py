"""Library surfaces for embedding the debuggable scheduler
(reference simulator/pkg/debuggablescheduler)."""
