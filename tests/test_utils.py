"""Unit tests for Go-JSON encoding, quantity parsing, selector matching."""

from fractions import Fraction

import pytest

from kube_scheduler_simulator_tpu.utils.gojson import go_marshal
from kube_scheduler_simulator_tpu.utils.labels import (
    find_untolerated_taint,
    match_label_selector,
    match_node_selector,
    match_node_selector_term,
    toleration_tolerates_taint,
)
from kube_scheduler_simulator_tpu.utils.quantity import milli_value, parse_quantity, value
from kube_scheduler_simulator_tpu.utils.retry import ConflictError, retry_on_conflict


class TestGoMarshal:
    def test_sorted_compact(self):
        assert go_marshal({"b": "2", "a": "1"}) == '{"a":"1","b":"2"}'

    def test_nested_maps(self):
        got = go_marshal({"node1": {"PluginB": "passed", "PluginA": "passed"}})
        assert got == '{"node1":{"PluginA":"passed","PluginB":"passed"}}'

    def test_html_escaping(self):
        # Go's json.Marshal escapes < > & by default.
        assert go_marshal({"k": "a<b>&c"}) == '{"k":"a\\u003cb\\u003e\\u0026c"}'

    def test_empty_map(self):
        assert go_marshal({}) == "{}"

    def test_string_list(self):
        assert go_marshal({"p": ["n1", "n2"]}) == '{"p":["n1","n2"]}'


class TestQuantity:
    @pytest.mark.parametrize(
        "q,expected",
        [
            ("1", 1),
            ("100m", Fraction(1, 10)),
            ("1500m", Fraction(3, 2)),
            ("128Mi", 128 * 1024**2),
            ("1Gi", 1024**3),
            ("1G", 10**9),
            ("2.5", Fraction(5, 2)),
            ("1e3", 1000),
            ("500k", 500_000),
            ("-2", -2),
            (2, 2),
        ],
    )
    def test_parse(self, q, expected):
        assert parse_quantity(q) == expected

    def test_milli_value_ceil(self):
        assert milli_value("100m") == 100
        assert milli_value("1") == 1000
        assert milli_value("0.1") == 100
        # MilliValue rounds up
        assert milli_value("1n") == 1

    def test_value_ceil(self):
        assert value("128Mi") == 134217728
        assert value("1.5") == 2
        assert value("100m") == 1

    def test_invalid(self):
        with pytest.raises(ValueError):
            parse_quantity("abc")
        with pytest.raises(ValueError):
            parse_quantity("1KiB")


class TestSelectors:
    def test_match_labels(self):
        sel = {"matchLabels": {"app": "web"}}
        assert match_label_selector(sel, {"app": "web", "x": "y"})
        assert not match_label_selector(sel, {"app": "db"})

    def test_nil_selector_matches_nothing(self):
        assert not match_label_selector(None, {"a": "b"})

    def test_empty_selector_matches_everything(self):
        assert match_label_selector({}, {"a": "b"})
        assert match_label_selector({}, {})

    def test_expressions(self):
        sel = {
            "matchExpressions": [
                {"key": "zone", "operator": "In", "values": ["us-a", "us-b"]},
                {"key": "gpu", "operator": "DoesNotExist"},
            ]
        }
        assert match_label_selector(sel, {"zone": "us-a"})
        assert not match_label_selector(sel, {"zone": "eu-a"})
        assert not match_label_selector(sel, {"zone": "us-a", "gpu": "yes"})
        assert not match_label_selector(sel, {})  # In requires presence

    def test_not_in_matches_absent_key(self):
        # apimachinery semantics: NotIn matches when the key is absent.
        sel = {"matchExpressions": [{"key": "a", "operator": "NotIn", "values": ["x"]}]}
        assert match_label_selector(sel, {})
        assert match_label_selector(sel, {"a": "y"})
        assert not match_label_selector(sel, {"a": "x"})

    def test_gt_lt(self):
        term = {"matchExpressions": [{"key": "cores", "operator": "Gt", "values": ["4"]}]}
        assert match_node_selector_term(term, {"cores": "8"}, "n1")
        assert not match_node_selector_term(term, {"cores": "2"}, "n1")
        assert not match_node_selector_term(term, {}, "n1")

    def test_empty_term_matches_nothing(self):
        assert not match_node_selector_term({}, {"a": "b"}, "n1")

    def test_match_fields(self):
        term = {
            "matchFields": [
                {"key": "metadata.name", "operator": "In", "values": ["node-1"]}
            ]
        }
        assert match_node_selector_term(term, {}, "node-1")
        assert not match_node_selector_term(term, {}, "node-2")

    def test_node_selector_or_of_terms(self):
        ns = {
            "nodeSelectorTerms": [
                {"matchExpressions": [{"key": "a", "operator": "Exists"}]},
                {"matchExpressions": [{"key": "b", "operator": "Exists"}]},
            ]
        }
        assert match_node_selector(ns, {"b": "1"}, "n")
        assert not match_node_selector(ns, {"c": "1"}, "n")


class TestTaints:
    def test_exists_tolerates_everything_with_key(self):
        tol = {"key": "k", "operator": "Exists"}
        assert toleration_tolerates_taint(tol, {"key": "k", "value": "v", "effect": "NoSchedule"})

    def test_empty_key_exists_tolerates_all(self):
        tol = {"operator": "Exists"}
        assert toleration_tolerates_taint(tol, {"key": "any", "effect": "NoExecute"})

    def test_equal(self):
        tol = {"key": "k", "operator": "Equal", "value": "v", "effect": "NoSchedule"}
        assert toleration_tolerates_taint(tol, {"key": "k", "value": "v", "effect": "NoSchedule"})
        assert not toleration_tolerates_taint(tol, {"key": "k", "value": "w", "effect": "NoSchedule"})

    def test_effect_mismatch(self):
        tol = {"key": "k", "operator": "Exists", "effect": "NoSchedule"}
        assert not toleration_tolerates_taint(tol, {"key": "k", "effect": "NoExecute"})

    def test_find_untolerated(self):
        taints = [
            {"key": "a", "effect": "PreferNoSchedule"},
            {"key": "b", "effect": "NoSchedule", "value": "x"},
        ]
        t = find_untolerated_taint(taints, [])
        assert t is not None and t["key"] == "b"
        t = find_untolerated_taint(taints, [{"key": "b", "operator": "Exists"}])
        assert t is None


class TestRetry:
    def test_retries_then_succeeds(self):
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise ConflictError("conflict")
            return "ok"

        assert retry_on_conflict(fn, sleep=lambda _: None) == "ok"
        assert len(calls) == 3

    def test_exhausts(self):
        def fn():
            raise ConflictError("always")

        with pytest.raises(ConflictError):
            retry_on_conflict(fn, sleep=lambda _: None)


class TestSimClock:
    """The promoted deterministic clock (utils/simclock.py): one helper
    serving both the ClusterStore (creationTimestamps) and the
    SchedulerService (queue backoff + Permit deadlines) roles, never
    advancing on read."""

    def test_callable_and_advance(self):
        from kube_scheduler_simulator_tpu.utils import SimClock

        clk = SimClock(10.0)
        assert clk() == 10.0
        assert clk() == 10.0  # reads NEVER advance (read counts differ
        # between the batch and sequential paths)
        assert clk.advance(2.5) == 12.5
        assert clk() == 12.5

    def test_never_backwards(self):
        from kube_scheduler_simulator_tpu.utils import SimClock

        with pytest.raises(ValueError):
            SimClock().advance(-1.0)

    def test_scenario_clock_is_simclock(self):
        from kube_scheduler_simulator_tpu.scenario.engine import ScenarioClock
        from kube_scheduler_simulator_tpu.utils import SimClock

        assert issubclass(ScenarioClock, SimClock)

    def test_pins_store_creation_timestamps(self):
        from kube_scheduler_simulator_tpu.state.store import ClusterStore
        from kube_scheduler_simulator_tpu.utils import SimClock

        store = ClusterStore(clock=SimClock(0.0))
        store.create("pods", {"metadata": {"name": "p", "namespace": "default"}})
        ts = store.get("pods", "p")["metadata"]["creationTimestamp"]
        assert ts == "1970-01-01T00:00:00Z"
