"""DI container: constructor-injection of every simulator service.

Rebuild of the reference's DI layer (reference simulator/server/di/di.go:
21-91): one place that wires the cluster store (our control plane), the
scheduler service, and the snapshot/reset/watcher/importer services, so
the HTTP server only sees interfaces.
"""

from __future__ import annotations

from typing import Any

from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
from kube_scheduler_simulator_tpu.services.importer import ClusterResourceImporter
from kube_scheduler_simulator_tpu.services.reset import ResetService
from kube_scheduler_simulator_tpu.services.resourcewatcher import ResourceWatcherService
from kube_scheduler_simulator_tpu.services.snapshot import SnapshotService
from kube_scheduler_simulator_tpu.state.store import ClusterStore


class DIContainer:
    def __init__(
        self,
        cluster_store: "ClusterStore | None" = None,
        initial_scheduler_cfg: "dict | None" = None,
        use_batch: str = "auto",
        external_snap_source: Any = None,
        seed: int = 0,
        enable_simulator_operator: bool = True,
        autoscale: str = "off",
        autoscaler_opts: "dict | None" = None,
        journal_dir: "str | None" = None,
    ):
        self.cluster_store = cluster_store or ClusterStore()
        # render-once wire-bytes cache (server/wirecache.py): every
        # list/watch/get consumer of this store shares one render per
        # object version.  KSS_WIRECACHE=0 keeps the pre-cache render
        # path byte-for-byte; invalidation hooks live in the store.
        from kube_scheduler_simulator_tpu.server.wirecache import (
            WireCache,
            wirecache_enabled,
        )

        if wirecache_enabled() and self.cluster_store.wirecache is None:
            self.cluster_store.wirecache = WireCache()
        # Durability boot (opt-in via KSS_JOURNAL_DIR, state/journal.py):
        # recover any prior crash state into the store BEFORE any
        # component subscribes (replay must not fire watch callbacks),
        # then attach a fresh journal epoch so everything from the
        # controllers onward is WAL-covered.  With the env unset this
        # whole block is inert and the store behaves exactly as before.
        from kube_scheduler_simulator_tpu.state.journal import (
            Journal,
            journal_knobs,
            on_error_from_env,
        )

        self._journal = None
        _recovery_report = None
        _jknobs = journal_knobs()
        if journal_dir is not None:
            # session-plane override (tenancy/manager.py): journal into
            # the given namespace regardless of KSS_JOURNAL_DIR, keeping
            # the env's durability knobs when it is set
            if _jknobs is not None:
                _jknobs = dict(_jknobs, directory=journal_dir)
            else:
                _jknobs = {
                    "directory": journal_dir,
                    "fsync": False,
                    "checkpoint_every": 0,
                    "on_error": on_error_from_env(),
                }
        self.journal_dir = _jknobs["directory"] if _jknobs is not None else None
        if _jknobs is not None:
            from kube_scheduler_simulator_tpu.state.recovery import boot_recover

            _recovery_report = boot_recover(_jknobs["directory"], self.cluster_store)
            if (
                _recovery_report is not None
                and _recovery_report.scheduler_config is not None
                and initial_scheduler_cfg is None
            ):
                # rebuild through the existing restart path with the
                # last journaled configuration
                initial_scheduler_cfg = _recovery_report.scheduler_config
            self._journal = Journal(
                _jknobs["directory"],
                fsync=_jknobs["fsync"],
                checkpoint_every=_jknobs["checkpoint_every"],
                on_error=_jknobs["on_error"],
            )
            if _recovery_report is not None:
                # the new epoch inherits the recovered resume point — a
                # compaction before the next mark must not prune it
                self._journal.last_mark = _recovery_report.last_mark
            self.cluster_store.attach_journal(self._journal)
        # Controllers start before the scheduler (reference boot order,
        # simulator.go:32-106: apiserver → controllers → … → scheduler).
        from kube_scheduler_simulator_tpu.controllers import ControllerManager

        self._controller_manager = ControllerManager(self.cluster_store)
        self._controller_manager.start()
        self._scheduler_service = SchedulerService(
            self.cluster_store,
            seed=seed,
            use_batch=use_batch,
            autoscale=autoscale,
            autoscaler_opts=autoscaler_opts,
        )
        if self.cluster_store.wirecache is not None:
            # miss renders stamp the profiler's watch_render stage
            self.cluster_store.wirecache.profiler = self._scheduler_service.profiler
        if self._journal is not None:
            from kube_scheduler_simulator_tpu.state.recovery import (
                scheduler_meta_provider,
            )

            self._journal.add_meta_provider(
                scheduler_meta_provider(self._scheduler_service)
            )
        self._scheduler_service.start_scheduler(initial_scheduler_cfg)
        if self._journal is not None and _recovery_report is not None:
            from kube_scheduler_simulator_tpu.state.recovery import (
                restore_scheduler_state,
            )

            restore_scheduler_state(self._scheduler_service, _recovery_report)
            # The 'config' record start_scheduler just journaled carries
            # PRE-restore meta (zeroed counters, empty queue).  Stamp a
            # boot record now so the journal's last meta reflects the
            # restored state — a crash before the next mutation must not
            # recover with reset rotation/queue state.
            self.cluster_store.journal_append("boot", {"recovered": True})
        # KEP-140 operator: reconciles Scenario OBJECTS (created via the
        # kube-API group or resource routes) into finished runs; the
        # synchronous POST /api/v1/scenarios path works without it.
        from kube_scheduler_simulator_tpu.scenario import ScenarioOperator

        self._scenario_operator = ScenarioOperator(
            self.cluster_store, self._scheduler_service, self._controller_manager
        )
        self._scenario_operator.start()
        # KEP-159/184 operator: reconciles Simulator objects into live
        # isolated in-process simulator instances (own store + scheduler
        # + HTTP servers) and SchedulerSimulation objects into one-shot
        # comparative runs.  Disabled for the ephemeral containers those
        # very features spawn (their stores never hold the CRs; a nested
        # operator would be thread overhead and recursion bait).
        self._simulator_operator = None
        if enable_simulator_operator:
            from kube_scheduler_simulator_tpu.scenario import SimulatorOperator

            self._simulator_operator = SimulatorOperator(self.cluster_store)
            self._simulator_operator.start()
        self._snapshot_service = SnapshotService(self.cluster_store, self._scheduler_service)
        if self._journal is not None:
            # periodic compaction reuses the snapshot service's
            # ResourcesForSnap export as the checkpoint's resources field
            from kube_scheduler_simulator_tpu.state.recovery import build_checkpoint

            self._journal.checkpoint_provider = lambda: build_checkpoint(
                self.cluster_store, self._snapshot_service
            )
        # Reset captures the post-boot state (reference NewDIContainer order:
        # reset service is built at boot, capturing the initial keyspace).
        self._reset_service = ResetService(self.cluster_store, self._scheduler_service)
        self._watcher_service = ResourceWatcherService(self.cluster_store)
        self._importer = (
            ClusterResourceImporter(external_snap_source, self._snapshot_service)
            if external_snap_source is not None
            else None
        )

    def scenario_operator(self):
        return self._scenario_operator

    def simulator_operator(self):
        return self._simulator_operator

    def close(self) -> None:
        """Tear down the container's background machinery (operator worker
        threads + store subscriptions, spawned simulator instances,
        controllers, scheduler loop)."""
        if self._simulator_operator is not None:
            self._simulator_operator.stop()
        self._scenario_operator.stop()
        self._controller_manager.stop()
        self._scheduler_service.stop_background()
        if self._journal is not None:
            self._journal.close()

    def scheduler_service(self) -> SchedulerService:
        return self._scheduler_service

    def controller_manager(self):
        return self._controller_manager

    def extender_service(self):
        return self._scheduler_service.extender_service

    def snapshot_service(self) -> SnapshotService:
        return self._snapshot_service

    def reset_service(self) -> ResetService:
        return self._reset_service

    def resource_watcher_service(self) -> ResourceWatcherService:
        return self._watcher_service

    def import_cluster_resource_service(self) -> "ClusterResourceImporter | None":
        return self._importer

    def tpu_scorer_bridge(self):
        """Lazily-built extenderv1 scorer endpoint backend (SURVEY §7 step
        8): lets a real Go scheduler delegate Filter/Prioritize to the TPU
        kernel."""
        if getattr(self, "_scorer_bridge", None) is None:
            from kube_scheduler_simulator_tpu.scheduler.scorer_bridge import TPUScorerBridge

            self._scorer_bridge = TPUScorerBridge(self._scheduler_service)
        return self._scorer_bridge
