"""PrioritySort (QueueSort), DefaultBinder (Bind), DefaultPreemption
(PostFilter) — upstream v1.26 semantics.
"""

from __future__ import annotations

from typing import Any

from kube_scheduler_simulator_tpu.models.framework import CycleState, Status
from kube_scheduler_simulator_tpu.models.nodeinfo import NodeInfo

Obj = dict[str, Any]


def pod_priority(pod: Obj) -> int:
    return int((pod.get("spec") or {}).get("priority") or 0)


class PrioritySort:
    name = "PrioritySort"

    def less(self, pod_info1: Obj, pod_info2: Obj) -> bool:
        p1 = pod_priority(pod_info1)
        p2 = pod_priority(pod_info2)
        if p1 != p2:
            return p1 > p2
        t1 = pod_info1["metadata"].get("creationTimestamp") or ""
        t2 = pod_info2["metadata"].get("creationTimestamp") or ""
        return t1 < t2


class DefaultBinder:
    name = "DefaultBinder"

    def __init__(self, args: "Obj | None" = None, handle: Any = None):
        self.handle = handle

    def bind(self, state: CycleState, pod: Obj, node_name: str) -> "Status | None":
        store = getattr(self.handle, "cluster_store", None) if self.handle else None
        if store is None:
            return Status.error("no cluster store to bind against")
        try:
            store.bind_pod(pod["metadata"].get("namespace", "default"), pod["metadata"]["name"], node_name)
        except KeyError as e:
            # Pod vanished mid-cycle: the binding API call fails, the cycle
            # reports an error status (upstream binder behavior).
            return Status.error(f"binding rejected: {e}")
        return None


class DefaultPreemption:
    """PostFilter: find a node where evicting lower-priority pods makes the
    pod schedulable; nominate it and delete the victims.

    Candidate selection follows upstream's core rules: only nodes whose
    filter status was plain Unschedulable are candidates; victims are
    lower-priority pods removed lowest-priority-first until the pod fits;
    the node needing the fewest/lowest-priority victims wins.
    """

    name = "DefaultPreemption"

    def __init__(self, args: "Obj | None" = None, handle: Any = None):
        self.handle = handle

    def post_filter(
        self, state: CycleState, pod: Obj, filtered_node_status_map: dict[str, Status]
    ) -> "tuple[str | None, Status | None]":
        fwk = getattr(self.handle, "framework", None) if self.handle else None
        snap = self.handle.snapshot() if self.handle else None
        if fwk is None or snap is None:
            return None, Status.unschedulable("preemption not possible")
        incoming_priority = pod_priority(pod)
        candidates: dict[str, list[Obj]] = {}
        for node_name, status in filtered_node_status_map.items():
            if status is not None and status.code.name == "UNSCHEDULABLE_AND_UNRESOLVABLE":
                continue
            ni = snap.get(node_name)
            if ni is None:
                continue
            victims = self._find_victims(fwk, state, pod, ni, incoming_priority)
            if victims is not None:
                candidates[node_name] = victims

        # Extender preempt pass (upstream Evaluator.callExtenders): preempt-
        # verb extenders narrow the candidate map before the best candidate
        # is picked; a non-ignorable extender failure aborts preemption.
        ext = getattr(fwk, "extender_service", None)
        if candidates and ext is not None and any(e.preempt_verb for e in ext.extenders):
            try:
                candidates = ext.run_preempt(pod, candidates)
            except Exception as e:
                return None, Status.error(f"preemption extender: {e}")

        best: "tuple[int, int, str] | None" = None  # (len, max prio, name)
        for node_name, victims in candidates.items():
            key = (len(victims), max((pod_priority(v) for v in victims), default=-(10**9)), node_name)
            if best is None or key < best:
                best = key
        if best is None:
            return None, Status.unschedulable("preemption: 0/%d nodes are available" % len(filtered_node_status_map))
        node_name = best[2]
        victims = candidates[node_name]
        store = getattr(self.handle, "cluster_store", None)
        for v in victims:
            if store is not None:
                try:
                    store.delete("pods", v["metadata"]["name"], v["metadata"].get("namespace"))
                except KeyError:
                    pass
            ni = snap.get(node_name)
            if ni is not None:
                ni.remove_pod(v)
        return node_name, None

    def _find_victims(self, fwk: Any, state: CycleState, pod: Obj, ni: NodeInfo, incoming_priority: int):
        """Remove lower-priority pods (lowest first) until the pod passes
        Filter on a scratch copy; None if impossible."""
        lower = sorted(
            (p for p in ni.pods if pod_priority(p) < incoming_priority),
            key=pod_priority,
        )
        if not lower:
            return None
        scratch = NodeInfo(ni.node)
        for p in ni.pods:
            scratch.add_pod(p)
        victims: list[Obj] = []
        for victim in lower:
            scratch.remove_pod(victim)
            victims.append(victim)
            if fwk.run_filter_plugins_silently(state, pod, scratch):
                return victims
        return None
