"""Learned scoring head: differentiable plugin weights + on-device tuner.

The batch scorer (ops/batch.py) materializes per-plugin score tensors in
pure JAX; this package lifts the one thing the scheduling-policy papers
tune — the plugin weight vector — into a traced kernel argument and
builds the machinery around it:

- ``validate``  — weight-vector validation at the API/config boundary
  (finite, non-negative, profile arity) + finalScore rendering shared by
  the batch formatter and the sequential result store.
- ``objective`` — utilization / fragmentation / pending-age scenario
  objectives, reduced on device from a rollout's committed planes.
- ``relax``     — the straight-through relaxed decision head: whole
  rollouts differentiable in the weights, forward bit-identical to hard.
- ``tuner``     — CEM (vmapped population per dispatch) and normalized
  gradient ascent; ``run_tuning`` is the entry every surface uses.
- ``scenario``  — deterministic scenario families with real weight/
  objective trade-offs.

Import discipline: this module stays jax-free so the server and service
can import the validation boundary cheaply; the heavy pieces load when a
tuning run actually starts.
"""

from kube_scheduler_simulator_tpu.tuning.validate import (  # noqa: F401
    WeightValidationError,
    format_weighted_score,
    validate_plugin_weights,
)

__all__ = [
    "WeightValidationError",
    "format_weighted_score",
    "validate_plugin_weights",
    "run_tuning",
    "tuning_defaults",
    "tuning_families",
]


def __getattr__(name: str):  # lazy: keep jax out of light importers
    if name in ("run_tuning", "tuning_defaults", "tuning_families", "TuningSession"):
        from kube_scheduler_simulator_tpu.tuning import tuner

        return getattr(tuner, name)
    raise AttributeError(name)
