async function watchLoop() {
  while (true) {
    try {
      const resp = await fetch("/api/v1/listwatchresources");
      const reader = resp.body.getReader();
      const decoder = new TextDecoder();
      let buf = "";
      for (;;) {
        const {done, value} = await reader.read();
        if (done) break;
        buf += decoder.decode(value, {stream: true});
        const lines = buf.split("\n");
        buf = lines.pop();
        let dirty = false;
        for (const line of lines) {
          if (!line.trim()) continue;
          const ev = JSON.parse(line);
          const k = key(ev.Obj);
          if (!(ev.Kind in state)) continue;
          if (ev.EventType === "DELETED") delete state[ev.Kind][k];
          else state[ev.Kind][k] = ev.Obj;
          dirty = true;
        }
        if (dirty) render();
      }
    } catch (e) { /* server restart — retry */ }
    await new Promise(r => setTimeout(r, 1000));
  }
}

// deployments/replicasets/scenarios/nodegroups are kinds the watch stream
// doesn't carry (it mirrors the reference's 7 kinds) — poll them instead,
// along with the autoscaler status panel.
async function pollWorkloads() {
  for (;;) {
    try {
      for (const k of ["deployments", "replicasets", "scenarios", "nodegroups", "podgroups"]) {
        const lst = await api("GET", `/api/v1/resources/${k}`);
        state[k] = {};
        for (const o of lst.items) state[k][key(o)] = o;
      }
      render();
      await refreshAutoscaler();
      await refreshTuning();
    } catch (e) {}
    await new Promise(r => setTimeout(r, 3000));
  }
}
