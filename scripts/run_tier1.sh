#!/usr/bin/env bash
# The tier-1 verification gate, encoding the ROADMAP.md "Tier-1 verify"
# command VERBATIM so builders and CI run the exact same thing: pipefail
# so the pytest exit code survives the tee, a hard timeout, and the
# DOTS_PASSED count extracted from the progress lines.
#
# Usage: scripts/run_tier1.sh   (from the repo root)
cd "$(dirname "$0")/.." || exit 1
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
# Transcript-provenance step (skip-if-absent): when the real `kubernetes`
# package is importable, capture its actual wire traffic and diff it
# against the authored transcripts (tests/wire_client_shim.py recorder).
if ! timeout -k 10 120 env JAX_PLATFORMS=cpu python tests/wire_client_shim.py --record-diff; then rc=1; fi
# Encode-parity smoke: a tiny churn sequence run with the incremental
# encoder on vs off, byte-compared (bindings + annotations) with a
# delta-path-engaged assertion — catches EncodeCache invalidation bugs
# fast, without the slow markers (scripts/encode_smoke.py).
if ! timeout -k 10 180 env JAX_PLATFORMS=cpu python scripts/encode_smoke.py; then rc=1; fi
# Gang-parity smoke: a training-job churn sweep on the batched gang
# replay byte-compared against the sequential Coscheduling oracle, with
# engaged/atomic/batched-dispatch assertions (scripts/gang_smoke.py).
if ! timeout -k 10 180 env JAX_PLATFORMS=cpu python scripts/gang_smoke.py; then rc=1; fi
# Stream-parity smoke: the streaming wave pipeline vs the strictly
# sequential path over a 3-wave churn scenario, byte-compared with
# engaged/overlapped assertions (scripts/stream_smoke.py).
if ! timeout -k 10 180 env JAX_PLATFORMS=cpu python scripts/stream_smoke.py; then rc=1; fi
# Tuning smoke: a tiny 2-step CEM run on a toy scenario (objective
# monotonicity + tuned >= default) plus the default-weight byte-parity
# pin — folded vs traced kernel paths (scripts/tune_smoke.py).
if ! timeout -k 10 180 env JAX_PLATFORMS=cpu python scripts/tune_smoke.py; then rc=1; fi
# Shard smoke: KSS_MESH_DEVICES=4 churn on a virtual CPU mesh
# byte-compared against single-device (sharded dispatches asserted), plus
# the f32-vs-x64 oracle spot check (scripts/shard_smoke.py).
if ! timeout -k 10 180 env JAX_PLATFORMS=cpu python scripts/shard_smoke.py; then rc=1; fi
# Sharded-streaming smoke: the stream x mesh FUSION — KSS_MESH_DEVICES=2
# streamed churn (sharded double-buffered placer banks, overlapped
# waves) byte-compared against the serial single-device path, with
# sharded_dispatches, stream_waves and bank rotations all asserted >0
# (scripts/shard_stream_smoke.py; bench cfg12 is the at-scale row).
if ! timeout -k 10 180 env JAX_PLATFORMS=cpu python scripts/shard_stream_smoke.py; then rc=1; fi
# Differential fuzz smoke (docs/fuzzing.md): a bounded seeded sweep of
# >= 25 composite scenarios (gang x preemption x autoscale x churn x
# retune) through batch-vs-oracle and streamed-vs-serial byte diffs,
# plus the chaos-degrade and 2-device mesh legs; any unexplained byte
# divergence is shrunk, dumped to /tmp for triage, and fails tier-1.
# Long-haul nightlies rerun it with KSS_FUZZ_BUDGET=<seconds>.
if ! timeout -k 10 420 env JAX_PLATFORMS=cpu python scripts/fuzz_smoke.py; then rc=1; fi
# Crash-consistency smoke (docs/durability.md): a journaled churn run
# on the batch path, SIGKILLed at three seeded record indices,
# recovered in fresh processes — byte parity vs uninterrupted,
# recovery_truncated_records_total == 0, zero partial waves/gangs,
# compaction engaged, /metrics wiring (scripts/crash_smoke.py).
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu python scripts/crash_smoke.py; then rc=1; fi
# Replication smoke (docs/replication.md): a journaled churn primary
# tailed LIVE by a hot-standby follower subprocess — follower lag <= 1
# commit wave under churn, SIGKILL-the-primary failovers whose promoted
# runs byte-match an uninterrupted baseline with zero truncated/torn
# records, and an in-process read replica served over HTTP (reads 200 +
# counted, writes 405, replication_* metrics, promotion unlocks writes).
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu python scripts/replica_smoke.py; then rc=1; fi
# Fault-matrix resilience smoke (docs/resilience.md): one leg per fault
# class — worker SIGKILL / SIGSTOP-hang / pipe-sever (supervised
# respawn, byte parity, zero extra recompiles, no leaked workers),
# ENOSPC under KSS_JOURNAL_ON_ERROR=degrade|wedge, and tailer EACCES
# (classified, counted per errno, seeded RetryPolicy backoff).  Every
# injected fault must end in a counted degradation with byte parity or
# a loud wedge; silent divergence fails tier-1.
if ! timeout -k 10 590 env JAX_PLATFORMS=cpu python scripts/resilience_smoke.py; then rc=1; fi
# Multi-tenant session-plane smoke (docs/multitenancy.md): three
# sessions churn concurrently over the shared compiled-executable
# substrate — per-session byte parity vs a solo single-tenant run,
# RecompileGuard(0) over tenants 2..3 admitting a seen config, and a
# SIGKILLed journaled manager recovering ALL sessions' stores plus the
# default (scripts/tenant_smoke.py; bench cfg15-tenant is the at-scale
# row).
if ! timeout -k 10 420 env JAX_PLATFORMS=cpu python scripts/tenant_smoke.py; then rc=1; fi
# Host-path perf smoke (docs/batch-engine.md "Where the wall goes"):
# the fused streamed path vs the serial per-tick loop at smoke size,
# min-of-3 walls, byte parity + per-wave stage profiles asserted, and
# the fused/serial ratio pinned above a generous committed floor, plus
# the attribution-coverage invariant (named stages >= 95% of fused span)
# — a host-path perf regression OR a new unattributed hot-path cost
# fails tier-1 loudly (scripts/perf_smoke.py; bench cfg13b-hostpath-v2
# / BENCH_hostpath.json is the at-scale row).
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu python scripts/perf_smoke.py; then rc=1; fi
# Kernel-contract checker (docs/static-analysis.md): FIRST the fixture
# self-test (every rule must fire on its known-bad fixtures and stay
# silent on the good ones — a broken rule must not silently pass the
# tree), THEN the live tree with analysis/baseline.toml applied; any
# unbaselined KSS-DTYPE/HOST-SYNC/DONATE/ENV/LOCK finding fails tier-1.
if ! timeout -k 10 120 env JAX_PLATFORMS=cpu python scripts/check_contracts.py --selftest; then rc=1; fi
if ! timeout -k 10 120 env JAX_PLATFORMS=cpu python scripts/check_contracts.py; then rc=1; fi
exit $rc
