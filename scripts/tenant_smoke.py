#!/usr/bin/env python
"""Multi-tenant session-plane smoke (tier-1; docs/multitenancy.md).

Three legs, each pinning a load-bearing session-plane contract:

- ISOLATION + PARITY: three sessions churn CONCURRENTLY (one thread per
  session, identical scenario) over the shared compiled-executable
  substrate; every session's binding + annotation trail must be
  byte-identical to the same churn run SOLO in a plain container with
  the substrate disengaged.  A shared executable that leaks state
  between tenants, or changes a single annotation byte, fails here.

- ZERO CROSS-SESSION RECOMPILES: tenant 1 warms the substrate; tenants
  2 and 3 then churn the identical scheduler config under a
  RecompileGuard(max_compiles=0) — admission of tenant k+1 with a seen
  BatchConfig must not trigger a single new backend compile.

- JOURNAL KILL + RECOVER: a child process boots a journaled manager,
  populates three sessions with distinct clusters, schedules them,
  reports every trail, then SIGKILLs itself mid-flight.  A fresh
  manager over the same journal root must recover ALL three sessions
  (plus the default store) with byte-identical trails.

Exit 0 = every leg green; any divergence prints the offending session
and differing keys.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")

SESSIONS = ("t1", "t2", "t3")
NODES = 8
WAVES = 2
PODS_PER_WAVE = 16


def seed_nodes(store) -> None:
    for i in range(NODES):
        store.create(
            "nodes",
            {
                "metadata": {
                    "name": f"node-{i}",
                    "labels": {
                        "kubernetes.io/hostname": f"node-{i}",
                        "topology.kubernetes.io/zone": f"z{i % 2}",
                        "disk": "ssd" if i % 2 else "hdd",
                    },
                },
                "status": {"allocatable": {"cpu": "8000m", "memory": "16Gi", "pods": "110"}},
                "spec": {},
            },
        )


def churn(svc, store) -> "dict[str, tuple]":
    import random

    rng = random.Random(7)
    created = 0
    for _ in range(WAVES):
        for _ in range(PODS_PER_WAVE):
            p = {
                "metadata": {
                    "name": f"pod-{created}",
                    "namespace": "default",
                    "labels": {"app": f"a{created % 3}"},
                },
                "spec": {
                    "containers": [
                        {
                            "name": "c",
                            "resources": {
                                "requests": {"cpu": f"{100 + (created % 4) * 50}m", "memory": "128Mi"}
                            },
                        }
                    ]
                },
            }
            if created % 4 == 0:
                p["spec"]["nodeSelector"] = {"disk": "ssd"}
            store.create("pods", p)
            created += 1
        svc.schedule_pending(max_rounds=2)
        bound = [p for p in store.list("pods") if (p.get("spec") or {}).get("nodeName")]
        for p in rng.sample(bound, max(1, len(bound) // 8)):
            store.delete("pods", p["metadata"]["name"], p["metadata"].get("namespace"))
        svc.schedule_pending(max_rounds=1)
    return trail(store)


def trail(store) -> "dict[str, tuple]":
    out = {}
    for p in store.list("pods"):
        k = p["metadata"]["namespace"] + "/" + p["metadata"]["name"]
        out[k] = (
            (p.get("spec") or {}).get("nodeName"),
            tuple(sorted((p["metadata"].get("annotations") or {}).items())),
        )
    return out


def diff(name: str, got: dict, want: dict) -> bool:
    if got == want:
        return True
    keys = sorted(set(got) | set(want))
    bad = [k for k in keys if got.get(k) != want.get(k)]
    print(f"FAIL {name}: {len(bad)} diverging pod(s): {bad[:6]}")
    for k in bad[:2]:
        print(f"  {k}:\n    got  {got.get(k)}\n    want {want.get(k)}")
    return False


def leg_isolation_and_recompiles() -> bool:
    from kube_scheduler_simulator_tpu.analysis.runtime import (
        RecompileError,
        RecompileGuard,
    )
    from kube_scheduler_simulator_tpu.server.di import DIContainer
    from kube_scheduler_simulator_tpu.tenancy import SUBSTRATE, SessionManager

    # solo baseline: plain container, substrate disengaged — the exact
    # single-tenant path the session plane must not perturb
    assert not SUBSTRATE.enabled, "substrate must be off outside a manager"
    solo_di = DIContainer(use_batch="force", enable_simulator_operator=False)
    seed_nodes(solo_di.cluster_store)
    want = churn(solo_di.scheduler_service(), solo_di.cluster_store)
    solo_di.close()
    assert any(v[0] for v in want.values()), "baseline churn bound nothing"

    boot_di = DIContainer(use_batch="off")
    mgr = SessionManager(boot_di, use_batch="force")
    ok = True
    try:
        assert SUBSTRATE.enabled, "manager must engage the substrate"
        for sid in SESSIONS:
            mgr.create(sid)
            seed_nodes(mgr.resolve_store(sid))

        # tenant 1 warms the shared substrate (builds + publishes)...
        got1 = churn(mgr.resolve_di(SESSIONS[0]).scheduler_service(),
                     mgr.resolve_store(SESSIONS[0]))
        ok &= diff("session t1 vs solo", got1, want)
        warmed = SUBSTRATE.stats()["substrate_fn_entries"]
        assert warmed > 0, "tenant 1 published nothing into the substrate"

        # ...then tenants 2+3 churn CONCURRENTLY with zero new compiles.
        # Retry-with-memory on a tripped guard: a timing-dependent round
        # split (loaded CI host) can present a tiny commit-path helper
        # shape for its FIRST compile — not a tenancy leak, and once
        # compiled it sits in the process-wide jit cache, so the retry can
        # only pass when the substrate genuinely serves every tenant; a
        # real per-tenant executable leak recompiles on every retry.
        results: "dict[str, dict]" = {}
        for attempt in range(3):
            tenants = [f"{sid}-r{attempt}" if attempt else sid for sid in SESSIONS[1:]]
            for sid in tenants:
                if attempt:
                    mgr.create(sid)
                    seed_nodes(mgr.resolve_store(sid))
            results.clear()
            errors: "list[BaseException]" = []

            def run(sid: str) -> None:
                try:
                    results[sid] = churn(mgr.resolve_di(sid).scheduler_service(),
                                         mgr.resolve_store(sid))
                except BaseException as e:  # noqa: BLE001 - reported below
                    errors.append(e)

            try:
                with RecompileGuard("tenant admission with a seen config",
                                    max_compiles=0):
                    threads = [threading.Thread(target=run, args=(sid,))
                               for sid in tenants]
                    for t in threads:
                        t.start()
                    for t in threads:
                        t.join()
            except RecompileError:
                if attempt == 2:
                    raise
                print("note: guard tripped on a first-sight helper shape — "
                      "retrying against the now-warm jit cache")
                continue
            if errors:
                print(f"FAIL concurrent churn raised: {errors[0]!r}")
                return False
            break
        for sid in tenants:
            ok &= diff(f"session {sid} vs solo", results[sid], want)
        hits = SUBSTRATE.stats()["substrate_fn_hits_total"]
        assert hits > 0, "tenants 2/3 never hit the shared substrate"
        print(
            f"ok isolation+parity: 3 sessions == solo baseline; substrate "
            f"entries={warmed} hits={hits}; 0 compiles for tenants 2..3"
        )
    finally:
        mgr.close()
        boot_di.close()
    assert not SUBSTRATE.enabled, "manager close must release the substrate"
    return ok


def child_populate(jdir: str) -> None:
    """Subprocess leg: journaled sessions, distinct data, then SIGKILL."""
    from kube_scheduler_simulator_tpu.server.di import DIContainer
    from kube_scheduler_simulator_tpu.tenancy import SessionManager

    di = DIContainer(use_batch="off", journal_dir=jdir)
    mgr = SessionManager(di, use_batch="off")
    di.cluster_store.create("nodes", {"metadata": {"name": "boot-node"},
                                      "status": {"allocatable": {"cpu": "4", "pods": "10"}}})
    trails = {}
    for i, sid in enumerate(SESSIONS):
        mgr.create(sid, seed=i)
        store = mgr.resolve_store(sid)
        for n in range(2 + i):  # distinct cluster per session
            store.create(
                "nodes",
                {"metadata": {"name": f"{sid}-node-{n}"},
                 "status": {"allocatable": {"cpu": "4000m", "memory": "8Gi", "pods": "20"}}},
            )
        for n in range(3 + i):
            store.create(
                "pods",
                {"metadata": {"name": f"{sid}-pod-{n}", "namespace": "default"},
                 "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "100m"}}}]}},
            )
        mgr.resolve_di(sid).scheduler_service().schedule_pending(max_rounds=2)
        trails[sid] = trail(store)
    with open(os.path.join(jdir, "trails.json"), "w", encoding="utf-8") as f:
        json.dump(trails, f)
        f.flush()
        os.fsync(f.fileno())
    # die mid-flight: no close(), no flush beyond what each journal_txn
    # already wrote — recovery must rebuild every tenant from its WAL
    os.kill(os.getpid(), signal.SIGKILL)


def leg_journal_kill_recover() -> bool:
    from kube_scheduler_simulator_tpu.server.di import DIContainer
    from kube_scheduler_simulator_tpu.tenancy import SessionManager

    ok = True
    with tempfile.TemporaryDirectory(prefix="kss-tenant-smoke-") as jdir:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--populate-child", jdir],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            timeout=240,
        )
        if proc.returncode != -signal.SIGKILL:
            print(f"FAIL child did not die by SIGKILL (rc={proc.returncode})")
            return False
        with open(os.path.join(jdir, "trails.json"), encoding="utf-8") as f:
            want = json.load(f)

        di = DIContainer(use_batch="off", journal_dir=jdir)
        mgr = SessionManager(di, use_batch="off")
        try:
            if mgr.ids() != sorted(SESSIONS):
                print(f"FAIL recovery: sessions {mgr.ids()} != {sorted(SESSIONS)}")
                return False
            assert mgr.stats()["sessions_recovered_total"] == len(SESSIONS)
            for sid in SESSIONS:
                # normalize tuples through the same JSON round-trip the
                # child's trail file took
                got = json.loads(json.dumps(trail(mgr.resolve_store(sid))))
                ok &= diff(f"recovered session {sid}", got, want[sid])
            boot = [n["metadata"]["name"] for n in di.cluster_store.list("nodes")]
            if boot != ["boot-node"]:
                print(f"FAIL recovery: default store nodes {boot}")
                ok = False
        finally:
            mgr.close()
            di.close()
    if ok:
        print(f"ok journal kill+recover: {len(SESSIONS)} sessions + default store restored")
    return ok


def main() -> int:
    ok = leg_isolation_and_recompiles()
    ok &= leg_journal_kill_recover()
    print("TENANT SMOKE " + ("PASSED" if ok else "FAILED"))
    return 0 if ok else 1


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "--populate-child":
        child_populate(sys.argv[2])
        sys.exit(0)  # unreachable — the child SIGKILLs itself
    sys.exit(main())
