"""The simulator HTTP server: the reference's exact REST surface.

Routes (reference simulator/server/server.go:42-57):

    GET  /api/v1/schedulerconfiguration      → 200 current config
    POST /api/v1/schedulerconfiguration      → 202 (only .profiles honored)
    PUT  /api/v1/reset                       → 202
    GET  /api/v1/export                      → 200 ResourcesForSnap
    POST /api/v1/import                      → 200
    GET  /api/v1/listwatchresources          → JSON-lines server push (SSE analog)
    POST /api/v1/extender/filter/:id | prioritize/:id | preempt/:id | bind/:id
    POST /api/v1/tpuscorer/filter | prioritize → extenderv1 endpoint backed by
                                               the TPU batch kernel (point a
                                               real scheduler's extender here;
                                               scheduler/scorer_bridge.py)
    POST /api/v1/scenarios                   → run a KEP-140 Scenario, return it
                                               with status/timeline (the
                                               reference only scaffolds this)
    GET  /api/v1/metrics (also /metrics)     → Prometheus text metrics (the
                                               reference exposes upstream
                                               Prometheus metrics via blank
                                               imports)

Because this build replaces the in-process kube-apiserver with the
in-memory cluster store (SURVEY.md §7 step 1), the direct kube-API CRUD
the reference's web UI performs is exposed here too:

    GET    /api/v1/resources/{kind}?namespace=        → list
    POST   /api/v1/resources/{kind}                   → create
    GET    /api/v1/resources/{kind}/{name}?namespace= → get
    PUT    /api/v1/resources/{kind}/{name}            → apply (upsert)
    DELETE /api/v1/resources/{kind}/{name}?namespace= → delete

Implementation: stdlib ThreadingHTTPServer — one thread per connection,
matching the store's synchronous, lock-guarded semantics; the watch
endpoint writes newline-delimited WatchEvent JSON with per-event flush
(what the reference's echo ResponseStream does, streamwriter.go:41-50).
"""

from __future__ import annotations

import copy
import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any
from urllib.parse import parse_qs, urlparse

from kube_scheduler_simulator_tpu import tenancy
from kube_scheduler_simulator_tpu.server.di import DIContainer
from kube_scheduler_simulator_tpu.services.resourcewatcher import PARAM_KINDS
from kube_scheduler_simulator_tpu.state.store import KINDS, AlreadyExistsError, NotFoundError
from kube_scheduler_simulator_tpu.tuning.validate import WeightValidationError

Obj = dict[str, Any]

_EXTENDER_RE = re.compile(r"^/api/v1/extender/(filter|prioritize|preempt|bind)/(\d+)$")
_RESOURCE_RE = re.compile(r"^/api/v1/resources/([a-z]+)(?:/([^/]+))?$")
_NODEGROUP_RE = re.compile(r"^/api/v1/nodegroups(?:/([^/]+))?$")
_PODGROUP_RE = re.compile(r"^/api/v1/podgroups(?:/([^/]+))?$")
# the session plane (tenancy/): CRUD at /api/v1/sessions[/<id>], every
# other simulator route session-scoped at /api/v1/sessions/<id>/<rest>
_SESSION_RE = re.compile(r"^/api/v1/sessions(?:/([^/]+))?(/.+)?$")
# session containers run without the simulator operator (a tenant
# spawning tenants is recursion bait) — their CRD kinds 404 per session,
# exactly as KEP-159 spawned instances already do
_SESSION_DISABLED = frozenset({"simulators", "schedulersimulations"})


def _run_tuning_request(svc: Any, body: Obj) -> Obj:
    """POST /api/v1/tuning: run the weight tuner on one or more scenario
    families against the live profile's plugin set and return the
    default-vs-tuned comparison.  Sizes/steps are capped — this runs
    synchronously in the request thread."""
    from kube_scheduler_simulator_tpu.tuning import run_tuning
    from kube_scheduler_simulator_tpu.tuning.scenario import FAMILIES

    families = body.get("families")
    if families is None:
        families = [body.get("family") or "imbalance"]
    if not isinstance(families, list) or not families:
        raise ValueError("families must be a non-empty list of scenario family names")
    for f in families:
        if f not in FAMILIES:
            raise ValueError(f"unknown scenario family {f!r}; choose from {sorted(FAMILIES)}")
    tuner = body.get("tuner") or "cem"
    clamp = lambda v, lo, hi, d: max(lo, min(int(v if v is not None else d), hi))
    kw = dict(
        objective=body.get("objective"),
        tuner=tuner,
        n_nodes=clamp(body.get("nodes"), 2, 64, 12),
        n_pods=clamp(body.get("pods"), 4, 512, 96),
        steps=clamp(body.get("steps"), 1, 64, 4),
        pop=clamp(body.get("pop"), 2, 64, 8),
        seed=clamp(body.get("seed"), 0, 1 << 30, 0),
        weights=body.get("weights"),
        svc=svc,
    )
    report = {
        "tuner": tuner,
        "results": [run_tuning(family=f, **kw) for f in families],
    }
    svc._last_tuning_report = report
    return report


class SimulatorServer:
    """NewSimulatorServer analog (reference server/server.go:26-66)."""

    def __init__(
        self,
        di: DIContainer,
        port: int = 1212,
        cors_allowed_origins: "list[str] | None" = None,
        kube_api_port: "int | None" = None,
    ):
        """``kube_api_port``: also serve the kube-API-compatible surface
        (server/kubeapi.py) on this port — the reference's two-port layout
        (kube API :3131 next to the simulator API :1212).  None disables
        it; 0 binds an ephemeral port (tests)."""
        self.di = di
        self.port = port
        self.cors = cors_allowed_origins or []
        self.kube_api_port = kube_api_port
        self.kube_api_server: Any = None
        # a container without the simulator operator (the isolated
        # instances KEP-159/184 spawn) must NOT serve the operator CRDs:
        # objects nothing reconciles would sit status-less forever —
        # a real apiserver without those CRDs installed 404s them, and
        # the KEP applies them to the USER cluster, not the simulator's
        self.disabled_kinds: "frozenset[str]" = (
            frozenset()
            if di.simulator_operator() is not None
            else frozenset({"simulators", "schedulersimulations"})
        )
        # The session plane (tenancy/): a read replica stays single-
        # surface (its store is FED by journal shipping — per-session
        # stores would have no feeder), every primary gets a manager.
        # Sessions created over HTTP schedule continuously like the
        # default container (start_background=True).
        self.sessions: Any = None
        if not getattr(di, "read_only", False):
            from kube_scheduler_simulator_tpu.tenancy import SessionManager

            self.sessions = SessionManager(di, start_background=True)
        self._httpd: "ThreadingHTTPServer | None" = None
        self._thread: "threading.Thread | None" = None
        self._stop = threading.Event()  # ends open watch streams on shutdown

    # --------------------------------------------------------------- serve

    def start(self, background: bool = True) -> int:
        """Start serving; returns the bound port (0 requests an ephemeral
        port, handy for tests)."""
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        if self.kube_api_port is not None:
            from kube_scheduler_simulator_tpu.server.kubeapi import KubeAPIServer

            self.kube_api_server = KubeAPIServer(
                self.di.cluster_store,
                port=self.kube_api_port,
                disabled_kinds=self.disabled_kinds,
                sessions=self.sessions,
            )
            self.kube_api_port = self.kube_api_server.start(background=True)
        # The scheduler runs continuously like the reference's
        # `go sched.Run(ctx)` (scheduler.go:183).  A read replica
        # (replication/replica.py) has no scheduler to run — its store
        # is FED by journal shipping, not driven — until promotion
        # starts one itself.
        if not getattr(self.di, "read_only", False):
            self.di.scheduler_service().start_background()
        if background:
            self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
            self._thread.start()
        else:
            self._httpd.serve_forever()
        return self.port

    def shutdown(self) -> None:
        self._stop.set()
        if self.kube_api_server is not None:
            self.kube_api_server.shutdown()
            self.kube_api_server = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
        if self.sessions is not None:
            # containers down, journal namespaces KEPT — a restarted
            # server recovers every session (tenancy/manager.py)
            self.sessions.close()
        self.di.close()


def _make_handler(server: SimulatorServer):
    di = server.di

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        # silence default request logging (echo's logger is opt-in)
        def log_message(self, fmt: str, *args: Any) -> None:
            pass

        # ----------------------------------------------------------- utils

        def _send_json(self, code: int, obj: Any) -> None:
            data = json.dumps(obj).encode()
            self.send_response(code)
            self._cors_headers()
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _send_bytes(self, content_type: str, data: bytes, code: int = 200) -> None:
            """Raw asset response (the UI page and its JS)."""
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _send_yaml(self, code: int, obj: Any, raw: bool = False) -> None:
            """YAML response (``?format=yaml`` / templates) — the
            reference UI's editors and templates speak YAML."""
            import yaml

            text = obj if raw else yaml.safe_dump(obj, sort_keys=False, default_flow_style=False)
            data = text.encode()
            self.send_response(code)
            self._cors_headers()
            self.send_header("Content-Type", "application/yaml; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _send_empty(self, code: int) -> None:
            self.send_response(code)
            self._cors_headers()
            self.send_header("Content-Length", "0")
            self.end_headers()

        def _cors_headers(self) -> None:
            origin = self.headers.get("Origin")
            if origin and (origin in server.cors or "*" in server.cors):
                self.send_header("Access-Control-Allow-Origin", origin)
                self.send_header("Access-Control-Allow-Methods", "GET, POST, PUT, DELETE, OPTIONS")
                self.send_header("Access-Control-Allow-Headers", "Content-Type")

        def _body(self) -> Any:
            """Request body as an object.  JSON by default; YAML when the
            Content-Type says so (the reference web UI is YAML-first —
            its monaco editor and creation templates speak YAML,
            web/components/lib/templates/*.yaml)."""
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length) if length else b""
            if not raw:
                return None
            ctype = (self.headers.get("Content-Type") or "").lower()
            if "yaml" in ctype:
                import yaml

                return yaml.safe_load(raw.decode())
            return json.loads(raw.decode())

        # --------------------------------------------------------- routing

        def _route(self, method: str):
            """Resolve this request's SESSION (tenancy/): the
            ``/api/v1/sessions/<id>/<rest>`` prefix (rewritten to the
            plain route) or the ``X-KSS-Session`` header select a
            session's container; no session → the default container,
            every route byte-for-byte as before the session plane
            existed.  Returns (di, url, q), or None when the response
            was already sent (sessions CRUD, unknown session)."""
            url = urlparse(self.path)
            q = parse_qs(url.query)
            self._disabled = server.disabled_kinds
            self._session = None
            mgr = server.sessions
            sid = None
            if mgr is not None:
                m = _SESSION_RE.match(url.path)
                if m:
                    sid, rest = m.group(1), m.group(2)
                    if not rest:
                        self._sessions_crud(method, sid, q)
                        return None
                    url = url._replace(path="/api/v1" + rest)
                else:
                    sid = (self.headers.get("X-KSS-Session") or "").strip() or None
                if sid and sid != tenancy.DEFAULT_SESSION:
                    try:
                        sdi = mgr.resolve_di(sid)
                    except tenancy.UnknownSessionError as e:
                        self._send_json(404, {"message": str(e)})
                        return None
                    self._disabled = server.disabled_kinds | _SESSION_DISABLED
                    self._session = sid
                    return sdi, url, q
            return di, url, q

        def _sessions_crud(self, method: str, sid: "str | None", q: dict) -> None:
            """/api/v1/sessions[/<id>]: the session plane's own CRUD."""
            mgr = server.sessions
            try:
                if method == "GET":
                    if sid is None:
                        self._send_json(200, {"items": mgr.list(), **mgr.stats()})
                    elif sid == tenancy.DEFAULT_SESSION:
                        self._send_json(200, {"id": sid, "default": True})
                    else:
                        self._send_json(200, mgr.info(mgr.get(sid)))
                elif method == "POST" and sid is None:
                    if self._reject_read_only():
                        return
                    body = self._body() or {}
                    info = mgr.create(
                        body.get("id"),
                        use_batch=body.get("useBatch"),
                        seed=int(body.get("seed") or 0),
                        scheduler_cfg=body.get("schedulerConfig"),
                    )
                    self._send_json(201, info)
                elif method == "DELETE" and sid is not None:
                    if self._reject_read_only():
                        return
                    mgr.destroy(sid)
                    self._send_empty(200)
                else:
                    self._send_json(404, {"message": "not found"})
            except tenancy.TooManySessionsError as e:
                self._send_json(429, {"message": str(e)})
            except tenancy.SessionExistsError as e:
                self._send_json(409, {"message": str(e)})
            except tenancy.InvalidSessionError as e:
                self._send_json(400, {"message": str(e)})
            except tenancy.UnknownSessionError as e:
                self._send_json(404, {"message": str(e)})
            except (ValueError, TypeError) as e:
                self._send_json(400, {"message": str(e)})
            except Exception as e:  # pragma: no cover - defensive 500
                self._send_json(500, {"message": f"{type(e).__name__}: {e}"})

        # --------------------------------------------------------- methods

        def _group_with_status(self, di: Any, group: Obj) -> Obj:
            """NodeGroup + live status (current size from the ownership
            label — the store is the source of truth, not a counter)."""
            from kube_scheduler_simulator_tpu.autoscaler.nodegroups import group_nodes

            nodes = sorted(
                n["metadata"]["name"]
                for n in group_nodes(di.cluster_store, group["metadata"]["name"])
            )
            out = dict(group)
            out["status"] = {"currentSize": len(nodes), "nodes": nodes}
            return out

        def do_OPTIONS(self) -> None:  # CORS preflight
            self._send_empty(204)

        def _reject_read_only(self) -> bool:
            """405 every write when the container is a read replica
            (replication/replica.py): the replica's store is owned by
            the journal-shipping applier, and a local mutation would
            fork it from the primary's record stream."""
            if not getattr(di, "read_only", False):
                return False
            data = json.dumps(
                {"message": "read-only replica: writes go to the primary (or promote)"}
            ).encode()
            self.send_response(405)
            self._cors_headers()
            self.send_header("Content-Type", "application/json")
            self.send_header("Allow", "GET, OPTIONS")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return True

        def do_GET(self) -> None:
            r = self._route("GET")
            if r is None:
                return
            di, url, q = r
            note = getattr(di, "note_replica_read", None)
            if note is not None:
                note()
            try:
                if url.path == "/api/v1/replication":
                    status = getattr(di, "replication_status", None)
                    if status is None:
                        self._send_json(404, {"message": "not a replica"})
                    else:
                        self._send_json(200, status())
                    return
                if url.path in ("/", "/index.html"):
                    from kube_scheduler_simulator_tpu.server.webui import HTML

                    self._send_bytes("text/html; charset=utf-8", HTML.encode())
                elif url.path == "/webui.js":
                    from kube_scheduler_simulator_tpu.server.webui import JS

                    self._send_bytes("application/javascript; charset=utf-8", JS.encode())
                elif url.path.startswith("/webui/"):
                    # individual component assets (the page loads the
                    # concatenated /webui.js; these serve component-level
                    # inspection and tests)
                    from kube_scheduler_simulator_tpu.server.webui import MODULES

                    mod = MODULES.get(url.path[len("/webui/") :])
                    if mod is None:
                        self._send_json(404, {"message": "no such UI module"})
                    else:
                        self._send_bytes("application/javascript; charset=utf-8", mod.encode())
                elif url.path == "/api/v1/schedulerconfiguration":
                    self._send_json(200, di.scheduler_service().get_scheduler_config())
                elif url.path in ("/api/v1/metrics", "/metrics"):
                    from kube_scheduler_simulator_tpu.server.metrics import render_metrics

                    data = render_metrics(
                        di, session=self._session, sessions=server.sessions
                    ).encode()
                    self.send_response(200)
                    self._cors_headers()
                    self.send_header("Content-Type", "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                elif url.path == "/api/v1/autoscaler":
                    svc = di.scheduler_service()
                    asc = svc.autoscaler
                    if asc is None:
                        self._send_json(200, {"mode": "off"})
                    else:
                        self._send_json(200, {"mode": svc.autoscale, **asc.status()})
                elif url.path == "/api/v1/tuning":
                    # the learned scoring head's state: active override,
                    # tunable families/objectives, and the last run's
                    # default-vs-tuned comparison (POST /api/v1/tuning runs one)
                    from kube_scheduler_simulator_tpu.tuning.objective import OBJECTIVES
                    from kube_scheduler_simulator_tpu.tuning.scenario import FAMILIES

                    svc = di.scheduler_service()
                    self._send_json(
                        200,
                        {
                            "pluginWeights": svc.plugin_weights(),
                            "scorePlugins": (
                                svc.score_plugin_names()
                                if svc.framework is not None
                                else []
                            ),
                            "families": sorted(FAMILIES),
                            "objectives": list(OBJECTIVES),
                            "lastReport": svc._last_tuning_report,
                        },
                    )
                elif m := _NODEGROUP_RE.match(url.path):
                    name = m.group(1)
                    if name is None:
                        items = [
                            self._group_with_status(di, g)
                            for g in di.cluster_store.list("nodegroups")
                        ]
                        self._send_json(200, {"items": items})
                    else:
                        g = di.cluster_store.get("nodegroups", name)
                        self._send_json(200, self._group_with_status(di, g))
                elif m := _PODGROUP_RE.match(url.path):
                    from kube_scheduler_simulator_tpu.gang import group_status

                    name = m.group(1)
                    ns = (q.get("namespace") or [None])[0]
                    fw = di.scheduler_service().framework
                    if name is None:
                        items = []
                        for g in di.cluster_store.list("podgroups", ns):
                            out = dict(g)
                            out["status"] = group_status(di.cluster_store, fw, g)
                            items.append(out)
                        self._send_json(200, {"items": items})
                    else:
                        g = di.cluster_store.get("podgroups", name, ns)
                        out = dict(g)
                        out["status"] = group_status(di.cluster_store, fw, g)
                        if (q.get("preview") or [""])[0] in ("1", "true"):
                            # gang-kernel feasibility + victim-search
                            # preview (estimation only, jax import lazy)
                            from kube_scheduler_simulator_tpu.gang.engine import (
                                group_preview,
                            )

                            out["status"]["preview"] = group_preview(
                                di.cluster_store, g
                            )
                        self._send_json(200, out)
                elif url.path == "/api/v1/export":
                    self._send_json(200, di.snapshot_service().snap())
                elif url.path == "/api/v1/listwatchresources":
                    self._list_watch(di, q)
                elif url.path.startswith("/api/v1/templates/"):
                    # YAML creation templates per kind (the reference web
                    # UI ships web/components/lib/templates/*.yaml)
                    from kube_scheduler_simulator_tpu.server.webui import TEMPLATES_YAML

                    kind = url.path.rsplit("/", 1)[1]
                    if kind in TEMPLATES_YAML:
                        self._send_yaml(200, TEMPLATES_YAML[kind], raw=True)
                    else:
                        self._send_json(404, {"message": f"no template for {kind}"})
                elif m := _RESOURCE_RE.match(url.path):
                    kind, name = m.group(1), m.group(2)
                    ns = (q.get("namespace") or [None])[0]
                    as_yaml = (q.get("format") or [""])[0] == "yaml"
                    if kind not in KINDS or kind in self._disabled:
                        self._send_json(404, {"message": f"unknown resource kind {kind}"})
                    elif name is None:
                        obj = {"items": di.cluster_store.list(kind, ns)}
                        self._send_yaml(200, obj) if as_yaml else self._send_json(200, obj)
                    else:
                        obj = di.cluster_store.get(kind, name, ns)
                        self._send_yaml(200, obj) if as_yaml else self._send_json(200, obj)
                else:
                    self._send_json(404, {"message": "not found"})
            except NotFoundError as e:
                self._send_json(404, {"message": str(e)})
            except Exception as e:  # pragma: no cover - defensive 500
                self._send_json(500, {"message": f"{type(e).__name__}: {e}"})

        def do_POST(self) -> None:
            r = self._route("POST")
            if r is None:
                return
            di, url, q = r
            if url.path == "/api/v1/replication/promote":
                # the ONE write a replica accepts: failover. 201 with the
                # promotion stats; idempotent (a repeat returns the first
                # promotion's report).
                promote = getattr(di, "promote", None)
                if promote is None:
                    self._send_json(404, {"message": "not a replica"})
                    return
                try:
                    self._send_json(201, promote().stats())
                except Exception as e:
                    self._send_json(500, {"message": f"{type(e).__name__}: {e}"})
                return
            if self._reject_read_only():
                return
            try:
                if url.path == "/api/v1/schedulerconfiguration":
                    body = self._body() or {}
                    # only .Profiles is honored (reference
                    # handler/schedulerconfig.go:39-60)
                    svc = di.scheduler_service()
                    cfg = svc.get_scheduler_config()
                    cfg["profiles"] = copy.deepcopy(body.get("profiles") or [])
                    svc.restart_scheduler(cfg)
                    self._send_empty(202)
                elif url.path == "/api/v1/import":
                    di.snapshot_service().load(self._body() or {})
                    self._send_empty(200)
                elif url.path == "/api/v1/scenarios":
                    from kube_scheduler_simulator_tpu.scenario import ScenarioEngine

                    body = self._body() or {}
                    svc = di.scheduler_service()
                    pw = (body.get("spec") or {}).get("pluginWeights")
                    if pw is not None and svc.framework is not None:
                        # reject a bad weight vector HERE with a 422 —
                        # not as a Failed scenario status deep in the
                        # run; the dry-run checks EVERY profile, exactly
                        # as applying will
                        svc.check_plugin_weights(pw)
                    engine = ScenarioEngine(
                        di.cluster_store, svc, di.controller_manager()
                    )
                    self._send_json(200, engine.run(body))
                elif url.path == "/api/v1/tuning":
                    # run/compare the learned scoring head: tune plugin
                    # weights on scenario families, report default-vs-
                    # tuned objectives (tuning/tuner.run_tuning)
                    self._send_json(
                        200, _run_tuning_request(di.scheduler_service(), self._body() or {})
                    )
                elif url.path == "/api/v1/schedulersimulations":
                    # KEP-184 one-shot runner: one Scenario × N isolated
                    # simulator instances, comparative report in status
                    from kube_scheduler_simulator_tpu.scenario.simulation import (
                        run_scheduler_simulation,
                    )

                    self._send_json(200, run_scheduler_simulation(self._body() or {}))
                elif m := _EXTENDER_RE.match(url.path):
                    verb, id_ = m.group(1), int(m.group(2))
                    ext = di.extender_service()
                    result = getattr(ext, verb)(id_, self._body() or {})
                    self._send_json(200, result)
                elif url.path in ("/api/v1/tpuscorer/filter", "/api/v1/tpuscorer/prioritize"):
                    # extenderv1 endpoint backed by the TPU batch kernel: a
                    # REAL scheduler's extender stanza can point here
                    bridge = di.tpu_scorer_bridge()
                    verb = url.path.rsplit("/", 1)[1]
                    self._send_json(200, getattr(bridge, verb)(self._body() or {}))
                elif (m := _NODEGROUP_RE.match(url.path)) and not m.group(1):
                    # collection URL only (POST to an item URL is 404, not
                    # a silent create of a differently-named group); the
                    # dedicated route ADMITS (validates) node groups — the
                    # generic resources route stores them raw
                    from kube_scheduler_simulator_tpu.autoscaler.nodegroups import (
                        validate_node_group,
                    )

                    body = self._body() or {}
                    validate_node_group(body)
                    self._send_json(201, di.cluster_store.create("nodegroups", body))
                elif (m := _PODGROUP_RE.match(url.path)) and not m.group(1):
                    # the dedicated route ADMITS (validates) pod groups —
                    # the generic resources route stores them raw
                    from kube_scheduler_simulator_tpu.gang import validate_pod_group

                    body = self._body() or {}
                    validate_pod_group(body)
                    self._send_json(201, di.cluster_store.create("podgroups", body))
                elif m := _RESOURCE_RE.match(url.path):
                    kind = m.group(1)
                    if kind not in KINDS or kind in self._disabled:
                        self._send_json(404, {"message": f"unknown resource kind {kind}"})
                    else:
                        self._send_json(201, di.cluster_store.create(kind, self._body() or {}))
                else:
                    self._send_json(404, {"message": "not found"})
            except AlreadyExistsError as e:
                self._send_json(409, {"message": str(e)})
            except NotFoundError as e:
                self._send_json(404, {"message": str(e)})
            except WeightValidationError as e:
                # a malformed plugin-weight vector is a semantic error in
                # an otherwise well-formed request: 422, named clearly
                self._send_json(422, {"message": str(e)})
            except ValueError as e:
                self._send_json(400, {"message": str(e)})
            except IndexError:
                self._send_json(400, {"message": "unknown extender id"})
            except Exception as e:
                self._send_json(500, {"message": f"{type(e).__name__}: {e}"})

        def do_PUT(self) -> None:
            r = self._route("PUT")
            if r is None:
                return
            di, url, q = r
            if self._reject_read_only():
                return
            try:
                if url.path == "/api/v1/reset":
                    di.reset_service().reset()
                    self._send_empty(202)
                elif m := _RESOURCE_RE.match(url.path):
                    kind, name = m.group(1), m.group(2)
                    if kind not in KINDS or kind in self._disabled or name is None:
                        self._send_json(404, {"message": "not found"})
                    else:
                        body = self._body() or {}
                        body.setdefault("metadata", {}).setdefault("name", name)
                        self._send_json(200, di.cluster_store.apply(kind, body))
                else:
                    self._send_json(404, {"message": "not found"})
            except Exception as e:
                self._send_json(500, {"message": f"{type(e).__name__}: {e}"})

        def do_DELETE(self) -> None:
            r = self._route("DELETE")
            if r is None:
                return
            di, url, q = r
            if self._reject_read_only():
                return
            try:
                if (m := _NODEGROUP_RE.match(url.path)) and m.group(1):
                    # deleting a group stops future scaling; its nodes stay
                    # (drain them first via scale-down, or delete directly)
                    di.cluster_store.delete("nodegroups", m.group(1))
                    self._send_empty(200)
                elif (m := _PODGROUP_RE.match(url.path)) and m.group(1):
                    # deleting a PodGroup orphans its member pods — they
                    # fail the PreFilter gate until the group is recreated
                    ns = (q.get("namespace") or [None])[0]
                    di.cluster_store.delete("podgroups", m.group(1), ns)
                    self._send_empty(200)
                elif m := _RESOURCE_RE.match(url.path):
                    kind, name = m.group(1), m.group(2)
                    ns = (q.get("namespace") or [None])[0]
                    if kind not in KINDS or kind in self._disabled or name is None:
                        self._send_json(404, {"message": "not found"})
                    else:
                        di.cluster_store.delete(kind, name, ns)
                        self._send_empty(200)
                else:
                    self._send_json(404, {"message": "not found"})
            except NotFoundError as e:
                self._send_json(404, {"message": str(e)})
            except Exception as e:
                self._send_json(500, {"message": f"{type(e).__name__}: {e}"})

        # ----------------------------------------------------------- watch

        def _list_watch(self, di: Any, q: dict) -> None:
            lrv = {}
            for param, kind in PARAM_KINDS:
                v = (q.get(f"{param}LastResourceVersion") or [""])[0]
                # docs also show the all-lowercase variant (api.md:118-130)
                v = v or (q.get(f"{param}lastResourceVersion") or [""])[0]
                if v:
                    lrv[kind] = v
            self.send_response(200)
            self._cors_headers()
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            handler = self

            class ChunkedStream:
                def write(self, data: bytes) -> None:
                    handler.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")

                def flush(self) -> None:
                    handler.wfile.flush()

            try:
                # No heartbeat: this endpoint carries the reference's exact
                # wire format (WatchEvent JSON lines only, streamwriter.go:
                # 41-50), so probe bytes must not be injected.  Like the
                # reference, a dead idle client is only detected at the
                # next event write (or at server stop).
                di.resource_watcher_service().list_watch(ChunkedStream(), lrv, stop=server._stop)
            finally:
                try:
                    handler.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass

    return Handler
