const KINDS = ["pods","nodes","persistentvolumes","persistentvolumeclaims","storageclasses","priorityclasses","namespaces","deployments","replicasets","scenarios","nodegroups","podgroups"];
const state = Object.fromEntries(KINDS.map(k=>[k,{}]));
const dlg = document.getElementById("dlg");
const key = o => (o.metadata.namespace? o.metadata.namespace+"/" : "") + o.metadata.name;

let filterText = "";
let searchTimer = null;
function onSearch() {
  // debounced: at benchmark scale a per-keystroke full re-render of
  // thousands of DOM nodes would freeze the tab
  clearTimeout(searchTimer);
  searchTimer = setTimeout(() => {
    filterText = document.getElementById("search").value.toLowerCase();
    render();
  }, 150);
}
function matchesFilter(o) {
  if (!filterText) return true;
  const hay = key(o).toLowerCase() + " " + JSON.stringify(o.metadata.labels || {}).toLowerCase();
  return hay.includes(filterText);
}
