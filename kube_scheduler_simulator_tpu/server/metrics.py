"""Prometheus-text-format metrics for the simulator.

The reference pulls in the upstream scheduler's Prometheus registration via
blank imports (reference pkg/debuggablescheduler/debuggable_scheduler.go:
13-15) and component-base metrics; this build exposes the simulator's own
counters natively: scheduling-round counts per path, batch-engine fallback
reasons, jit compile counts/cache size, and per-phase timings
(encode/lower/device), plus cluster-store object counts.

Served at ``GET /api/v1/metrics`` (and ``/metrics``, the conventional
scrape path) in Prometheus text exposition format v0.0.4.
"""

from __future__ import annotations

from typing import Any

_PREFIX = "simulator"


def _esc(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_session(text: str, session: str) -> str:
    """Stamp every sample line with a ``session`` label (the per-session
    ``/metrics`` view: ``/api/v1/sessions/<id>/metrics``).  Text-level so
    the histogram block and every counter family need no plumbing."""
    out = []
    for line in text.splitlines():
        if line.startswith("#") or not line:
            out.append(line)
            continue
        head, _, value = line.rpartition(" ")
        if head.endswith("}"):
            head = head[:-1] + f',session="{_esc(session)}"}}'
        else:
            head = head + f'{{session="{_esc(session)}"}}'
        out.append(f"{head} {value}")
    return "\n".join(out) + "\n"


def render_metrics(di: Any, session: "str | None" = None, sessions: Any = None) -> str:
    """Render the whole registry from the DI container's live services.

    ``session`` labels every sample with the session id (the container
    passed in is that session's).  ``sessions`` is the server's
    SessionManager; once the session plane has ever been used, the
    DEFAULT render additionally exposes the plane's lifecycle counters
    and the shared-substrate hit/miss counters — before first use the
    output stays byte-for-byte what a sessionless server rendered."""
    svc = di.scheduler_service()
    m = svc.metrics()
    lines: list[str] = []

    def counter(name: str, help_: str, value: float, labels: "dict[str, str] | None" = None, typ: str = "counter"):
        full = f"{_PREFIX}_{name}"
        if not any(ln.startswith(f"# HELP {full} ") for ln in lines):
            lines.append(f"# HELP {full} {help_}")
            lines.append(f"# TYPE {full} {typ}")
        lab = ""
        if labels:
            lab = "{" + ",".join(f'{k}="{_esc(str(v))}"' for k, v in sorted(labels.items())) + "}"
        lines.append(f"{full}{lab} {value}")

    counter("scheduled_pods_total", "Pods scheduled, by path.", m["batch_pods"], {"path": "batch"})
    counter("scheduled_pods_total", "Pods scheduled, by path.", m["sequential_pods"], {"path": "sequential"})
    counter("batch_rounds_total", "Scheduling rounds that ran on the TPU batch engine.", m["batch_commits"])
    counter("batch_kernel_runs_total", "Batch-kernel invocations (>= rounds: mid-round preemptions re-run the kernel on the tail).", m["engine_rounds"])
    counter("batch_restarts_total", "Mid-round kernel re-runs forced by successful preemptions.", m["batch_restarts"])
    for reason, n in sorted(m["batch_fallbacks"].items()):
        counter(
            "batch_fallbacks_total",
            "Rounds that fell back to the sequential cycle, by reason.",
            n,
            {"reason": reason},
        )
    if not m["batch_fallbacks"]:
        counter(
            "batch_fallbacks_total",
            "Rounds that fell back to the sequential cycle, by reason.",
            0,
            {"reason": "none"},
        )
    # scheduling-queue state (activeQ/backoffQ/unschedulableQ)
    counter("queue_pods", "Pods tracked by the scheduling queue, by state.", m["queue_active"], {"state": "active"}, typ="gauge")
    counter("queue_pods", "Pods tracked by the scheduling queue, by state.", m["queue_backoff"], {"state": "backoff"}, typ="gauge")
    counter("queue_pods", "Pods tracked by the scheduling queue, by state.", m["queue_unschedulable"], {"state": "unschedulable"}, typ="gauge")
    counter("queue_moves_total", "Unschedulable-queue moves triggered by cluster events.", m["queue_moves"])
    counter("queue_flushes_total", "Stuck unschedulable pods flushed by timeout.", m["queue_flushes"])
    # commit-pipeline trajectory (the bench's cfg5 columns, live — a
    # scrape can catch commit-path regressions between bench rounds)
    counter("commit_seconds_total", "Cumulative host-side commit wall within batch rounds.", round(m["commit_s"], 6))
    counter("commit_waves_total", "Bulk-commit waves flushed on the batch path.", m["commit_waves"])
    counter("wave_commit_seconds", "Host commit wall of the last bulk-commit wave.", round(m["wave_commit_s"], 6), typ="gauge")
    counter("commit_pods_per_s", "Pods committed per host-commit second (last wave).", round(m["commit_pods_per_s"], 3), typ="gauge")
    counter("overlap_efficiency", "Fraction of the last pipelined round's device time hidden under host commits (0 when un-pipelined).", round(m["overlap_efficiency"], 4), typ="gauge")
    # vectorized preemption engine (preemption/): batched PostFilter work
    counter("preemption_attempts_total", "Kernel-failed pods whose PostFilter ran on the batched victim-search engine.", m["preempt_attempts"])
    counter("preemption_nominations_total", "Successful batched preemptions (nominatedNodeName set).", m["preempt_nominations"])
    counter("preemption_victims_total", "Victims evicted by batched preemptions.", m["preempt_victims"])
    counter("preemption_dispatches_total", "Vmapped victim-search dispatches (one per replay window with kernel failures).", m["preempt_dispatches"])
    counter("preemption_kernel_seconds_total", "Cumulative victim-search kernel wall.", round(m["preempt_kernel_s"], 6))
    for reason, n in sorted(m["preempt_fallbacks"].items()):
        counter(
            "preemption_fallbacks_total",
            "PostFilter work that took the sequential DefaultPreemption path, by reason.",
            n,
            {"reason": reason},
        )
    if not m["preempt_fallbacks"]:
        counter(
            "preemption_fallbacks_total",
            "PostFilter work that took the sequential DefaultPreemption path, by reason.",
            0,
            {"reason": "none"},
        )
    # gang engine (gang/): all-or-nothing PodGroup placement
    counter("gang_rounds_total", "Batch rounds with the gang replay engaged (PodGroups present).", m["gang_rounds"])
    counter("gang_parked_pods_total", "Gang members parked at Permit by the batch replay.", m["gang_parked"])
    counter("gang_released_groups_total", "PodGroups released as atomic all-or-nothing waves.", m["gang_released_groups"])
    counter("gang_released_pods_total", "Gang members bound through atomic release waves.", m["gang_released_pods"])
    counter("gang_kernel_dispatches_total", "Gang-kernel verdict dispatches (one per replay window, not per group).", m["gang_kernel_dispatches"])
    counter("gang_kernel_seconds_total", "Cumulative gang-kernel wall.", round(m["gang_kernel_s"], 6))
    counter("gang_verdict_mismatch_total", "Device-vs-host gang verdict disagreements (nonzero = bug).", m["gang_verdict_mismatch"])
    for reason, n in sorted(m["gang_fallbacks"].items()):
        counter(
            "gang_fallbacks_total",
            "Gang rounds that took the sequential Coscheduling oracle, by reason.",
            n,
            {"reason": reason},
        )
    if not m["gang_fallbacks"]:
        counter(
            "gang_fallbacks_total",
            "Gang rounds that took the sequential Coscheduling oracle, by reason.",
            0,
            {"reason": "none"},
        )
    # streaming wave pipeline (scheduler/stream.py): wave k+1's
    # encode/upload/dispatch overlapped with wave k's kernel + commit
    counter("stream_waves_total", "Waves committed through the streaming pipeline's overlapped path.", m["stream_waves_total"])
    counter("stream_pods_total", "Pods committed by streamed waves.", m["stream_pods_total"])
    counter("stream_overlap_seconds_total", "Host seconds spent encoding/committing while a streamed kernel was in flight (hidden work).", round(m["stream_overlap_s"], 6))
    counter("stream_stall_seconds_total", "Host seconds blocked waiting on a streamed wave's device results.", round(m["stream_stall_s"], 6))
    for reason, n in sorted(m["stream_drains_by_reason"].items()):
        counter(
            "stream_drains_total",
            "Pipeline drains by exactness-gate reason (most reasons route the wave to the sequential path; kernel-failure and node-change gates only serialize the streamed boundary).",
            n,
            {"reason": reason},
        )
    if not m["stream_drains_by_reason"]:
        counter(
            "stream_drains_total",
            "Pipeline drains by exactness-gate reason (most reasons route the wave to the sequential path; kernel-failure and node-change gates only serialize the streamed boundary).",
            0,
            {"reason": "none"},
        )
    # learned scoring head (tuning/): on-device tuner activity + the
    # live weight-override state
    counter("tuning_runs_total", "Weight-tuning runs completed (one per family per /api/v1/tuning or bench invocation).", m["tuning_runs_total"])
    counter("tuning_rollouts_total", "On-device rollouts evaluated by the tuners (CEM counts every population member).", m["tuning_rollouts_total"])
    counter("tuning_grad_dispatches_total", "Straight-through value-and-grad dispatches (gradient tuner).", m["tuning_grad_dispatches_total"])
    for objective, v in sorted(m["tuning_objective"].items()):
        counter(
            "tuning_objective",
            "Tuned objective value of the most recent run, by objective name (higher = better).",
            round(float(v), 6),
            {"name": objective},
            typ="gauge",
        )
    counter("plugin_weights_overridden", "1 while a plugin-weight override (learned scoring head) is active on the live profiles.", m["plugin_weights_overridden"], typ="gauge")
    # differential fuzzer (fuzz/): sweep outcomes reported through
    # service.note_fuzz_report (scripts/fuzz_smoke.py, nightlong-haul runs)
    counter("fuzz_scenarios_total", "Composite fuzz scenarios judged through the differential runner.", m["fuzz_scenarios_total"])
    for kind, n in sorted(m["fuzz_divergences_by_kind"].items()):
        counter(
            "fuzz_divergences_total",
            "Unexplained byte divergences between differential paths, by comparison kind (nonzero = bug).",
            n,
            {"kind": kind},
        )
    if not m["fuzz_divergences_by_kind"]:
        counter(
            "fuzz_divergences_total",
            "Unexplained byte divergences between differential paths, by comparison kind (nonzero = bug).",
            0,
            {"kind": "none"},
        )
    counter("fuzz_shrink_steps_total", "Accepted shrinker reductions while minimizing diverging scenarios.", m["fuzz_shrink_steps_total"])
    # Permit wait machinery (waiting-pod map)
    counter("waiting_pods", "Pods parked at Permit holding a reservation.", m["waiting_pods"], typ="gauge")
    counter("permit_wait_expired_total", "Permit waits rejected on deadline expiry.", m["permit_wait_expired"])
    # incremental encoder + device-resident problem (delta re-encode
    # across waves — ops/encode.EncodeCache + ops/batch.DevicePlacer)
    counter("encode_rounds_total", "Encode passes, by mode (full cold encode vs incremental delta).", m["encode_full_total"], {"mode": "full"})
    counter("encode_rounds_total", "Encode passes, by mode (full cold encode vs incremental delta).", m["encode_delta_total"], {"mode": "delta"})
    counter("encode_rows_reencoded_total", "Per-object rows re-encoded on the delta path (changed bound pods + class-row cache misses).", m["encode_rows_reencoded_total"])
    for reason, n in sorted(m["encode_fallbacks_by_reason"].items()):
        counter(
            "encode_fallbacks_total",
            "Encode passes that fell back to a cold full encode, by exactness-gate reason.",
            n,
            {"reason": reason},
        )
    if not m["encode_fallbacks_by_reason"]:
        counter(
            "encode_fallbacks_total",
            "Encode passes that fell back to a cold full encode, by exactness-gate reason.",
            0,
            {"reason": "none"},
        )
    counter("device_bytes_uploaded_total", "Host-to-device bytes actually shipped for problem placement (reused resident planes upload nothing).", m["device_bytes_uploaded_total"])
    counter("device_plane_reuses_total", "Device-resident planes reused unchanged across rounds.", m["device_plane_reuses_total"])
    counter("device_scatter_updates_total", "Resident planes updated in place via jitted row scatter-updates.", m["device_scatter_updates_total"])
    # the streaming double buffer's per-bank view (DevicePlacer banks):
    # rotations plus scatter traffic / resident bytes per bank, so a
    # stuck rotation (one bank starving while the other churns) shows up
    # in a scrape
    counter("placer_bank_rotations_total", "DevicePlacer bank alternations (streamed waves flip banks so scatter-donations never touch an in-flight kernel's buffers).", m["placer_bank_rotations_total"])
    banks = m["placer_banks"] or {0: {"scatter_updates": 0, "resident_plane_bytes_per_device": 0, "planes": 0}}
    for bank, bs in sorted(banks.items()):
        counter(
            "placer_bank_scatter_updates_total",
            "Scatter-updates applied to resident planes, by DevicePlacer bank.",
            bs.get("scatter_updates", 0),
            {"bank": bank},
        )
        counter(
            "placer_bank_plane_bytes_per_device",
            "Per-device bytes of a bank's resident problem planes (node-sharded planes split across the mesh, replicated planes in full).",
            bs.get("resident_plane_bytes_per_device", 0),
            {"bank": bank},
            typ="gauge",
        )
        counter(
            "placer_bank_resident_planes",
            "Resident device planes held, by DevicePlacer bank.",
            bs.get("planes", 0),
            {"bank": bank},
            typ="gauge",
        )
    # AOT executable artifact cache (ops/aot.py — jax.export round-trips)
    counter("aot_cache_hits_total", "Scan executables loaded from on-disk jax.export artifacts (tracing skipped).", m["aot_cache_hits_total"])
    counter("aot_cache_misses_total", "Scan builds with no artifact on disk (fresh trace; saved when the cache is enabled).", m["aot_cache_misses_total"])
    counter("aot_cache_saves_total", "Scan executables exported + serialized to the artifact cache.", m["aot_cache_saves_total"])
    for reason, n in sorted(m["aot_cache_fallbacks_by_reason"].items()):
        counter(
            "aot_cache_fallbacks_total",
            "Artifacts present but rejected, by reason (jax-version / mesh-spec / dtype-regime / kernel-digest / corrupt ...) — a counted fresh trace, never a crash.",
            n,
            {"reason": reason},
        )
    if not m["aot_cache_fallbacks_by_reason"]:
        counter(
            "aot_cache_fallbacks_total",
            "Artifacts present but rejected, by reason (jax-version / mesh-spec / dtype-regime / kernel-digest / corrupt ...) — a counted fresh trace, never a crash.",
            0,
            {"reason": "none"},
        )
    # durability layer (state/journal.py + state/recovery.py): the
    # write-ahead journal's write side and the last boot's recovery —
    # all zeros when KSS_JOURNAL_DIR is unset (the default)
    counter("journal_enabled", "1 while a write-ahead journal is attached to the cluster store (KSS_JOURNAL_DIR).", m["journal_enabled"], typ="gauge")
    counter("journal_records_total", "Records appended to the write-ahead journal (one per mutation event, or one per atomic wave/gang/bulk transaction).", m["journal_records_total"])
    counter("journal_bytes_written_total", "Bytes appended to journal segments (record headers + payloads).", m["journal_bytes_written_total"])
    counter("journal_fsyncs_total", "Journal records synced to disk (KSS_JOURNAL_FSYNC=1).", m["journal_fsyncs_total"])
    # disk faults as policy (KSS_JOURNAL_ON_ERROR — docs/resilience.md)
    counter("journal_wedges_total", "Disk faults that wedged the journal (KSS_JOURNAL_ON_ERROR=wedge): the commit failed loudly and all further mutations are refused.", m["journal_wedges_total"])
    counter("journal_records_dropped_total", "Journal appends skipped while running non-durable after a degrade-mode disk fault.", m["journal_records_dropped_total"])
    for label, n in sorted(m["journal_degraded_by_errno"].items()):
        counter(
            "journal_degraded_total",
            "Disk faults absorbed by KSS_JOURNAL_ON_ERROR=degrade (journal marked torn at a record boundary, store continues non-durable), by errno.",
            n,
            {"errno": label},
        )
    counter("checkpoint_compactions_total", "Journal compactions: checkpoint written (SnapshotService.snap shape + extras), segments rotated and pruned.", m["checkpoint_compactions_total"])
    counter("recovery_replayed_records_total", "Journal records replayed into the store by the last boot-time recovery.", m["recovery_replayed_records_total"])
    counter("recovery_truncated_records_total", "Torn journal tails truncated by recovery (counted, never raised; nonzero after a clean SIGKILL = bug).", m["recovery_truncated_records_total"])
    counter("recovery_partial_gangs_total", "PodGroups observed partially bound at the recovery point (wave/gang records are atomic, so nonzero = bug).", m["recovery_partial_gangs_total"])
    # node-axis mesh sharding (ops/mesh.py): the scale axis across chips
    counter("shard_devices", "Devices in the node-axis sharding mesh (0 = single-device).", m["shard_devices"], typ="gauge")
    counter("sharded_dispatches_total", "Kernel dispatches executed with the node axis sharded over the mesh (main scan + victim search + estimator).", m["sharded_dispatches_total"])
    counter("plane_shard_bytes_per_device", "Cumulative per-device bytes of sharded problem placements (node-sharded planes split across the mesh, replicated planes counted in full).", m["plane_shard_bytes_per_device"])
    counter("batch_compiles_total", "XLA compilations of the batch kernel (jit cache misses).", m["engine_compiles"])
    counter("batch_executable_cache_entries", "Compiled batch executables held in the jit cache.", m["engine_cache_entries"], typ="gauge")
    for phase, secs in sorted(m["engine_cum_timings"].items()):
        counter(
            "batch_phase_seconds_total",
            "Cumulative batch-engine time by phase (encode/lower/device/total).",
            round(secs, 6),
            {"phase": phase.removesuffix("_s")},
        )
    for phase, secs in sorted(m["engine_last_timings"].items()):
        counter(
            "batch_phase_seconds_last",
            "Last-round batch-engine time by phase.",
            round(secs, 6),
            {"phase": phase.removesuffix("_s")},
            typ="gauge",
        )

    # per-wave stage profiler (ops/profile.py): where the wall goes.
    # Disjoint host stamps per wave; sum over stages == committed wall.
    prof = m.get("profile")
    if prof:
        counter("wave_profile_enabled", "1 while the per-wave stage profiler is on (KSS_PROFILE).", prof["enabled"], typ="gauge")
        counter("wave_profile_waves_total", "Waves closed by the stage profiler.", prof["waves"])
        counter("wave_profile_wall_seconds_total", "Cumulative profiled wave wall (== sum of all stage seconds).", round(prof["wall_s"], 6))
        for stage, st in sorted(prof["stages"].items()):
            counter(
                "wave_stage_seconds_total",
                "Cumulative host seconds attributed to a wave stage (disjoint stamps; host_other is the derived remainder).",
                round(st["total_s"], 6),
                {"stage": stage},
            )
            counter(
                "wave_stage_stamps_total",
                "Stamp count per wave stage.",
                st["count"],
                {"stage": stage},
            )
            counter(
                "wave_stage_seconds_max",
                "Largest single stamp observed per wave stage (cold-wave compiles spike dispatch).",
                round(st["max_s"], 6),
                {"stage": stage},
                typ="gauge",
            )
        # Prometheus histogram per stage (log4 buckets, cumulative le)
        hfull = f"{_PREFIX}_wave_stage_duration_seconds"
        lines.append(f"# HELP {hfull} Per-stamp stage latency histogram (log4 buckets).")
        lines.append(f"# TYPE {hfull} histogram")
        ubs = prof["hist_buckets"]
        for stage, hs in sorted(prof["hist"].items()):
            cum = 0
            for ub, n in zip(ubs, hs):
                cum += n
                lines.append(f'{hfull}_bucket{{stage="{stage}",le="{ub:g}"}} {cum}')
            cum += hs[-1]
            lines.append(f'{hfull}_bucket{{stage="{stage}",le="+Inf"}} {cum}')
            st = prof["stages"].get(stage, {"total_s": 0.0})
            lines.append(f'{hfull}_sum{{stage="{stage}"}} {round(st["total_s"], 6)}')
            lines.append(f'{hfull}_count{{stage="{stage}"}} {cum}')

    # multi-process shard ensemble (ops/procmesh.py) — only once the
    # KSS_MESH_PROCESSES knob has been exercised
    pm = m.get("procmesh")
    if pm is not None:
        counter("procmesh_requested_processes", "KSS_MESH_PROCESSES as last read by an engine.", pm["requested_processes"], typ="gauge")
        pool = pm.get("pool")
        counter("procmesh_engaged", "1 while a live worker ensemble is serving scans.", int(bool(pool and pool["engaged"])), typ="gauge")
        if pool:
            counter("procmesh_dispatches_total", "Scan waves dispatched to the worker ensemble.", pool["dispatches"])
            counter("procmesh_scans_loaded", "Distinct AOT scan executables resolved on every worker.", pool["scans_loaded"], typ="gauge")
            # supervision (docs/resilience.md): straggler-only kills,
            # ensemble respawns, and the breaker's degradation state
            counter("procmesh_respawns_total", "Worker-ensemble respawns after a supervised failure (straggler SIGKILLed, fresh ensemble re-loaded from the AOT cache).", pool["respawns"])
            counter("procmesh_hangs_detected_total", "Workers declared hung (alive but STOPPED for a full KSS_PROCMESH_HEARTBEAT_S — e.g. SIGSTOP'd), distinguished from dead ones.", pool["hangs_detected"])
            counter("procmesh_breaker_state", "Ensemble circuit breaker: 0 closed, 1 half-open, 2 open (open = counted permanent degradation to the in-process virtual mesh).", pool["breaker_state_code"], typ="gauge")
            for verdict, n in sorted(pool["failures_by_verdict"].items()):
                counter(
                    "procmesh_worker_failures_total",
                    "Supervised worker failures, by wait verdict (died/hang/timeout/error).",
                    n,
                    {"verdict": verdict},
                )
        for reason, n in sorted(pm["fallbacks_by_reason"].items()):
            counter(
                "procmesh_fallbacks_total",
                "Ensemble bring-up failures degraded (counted) to the in-process virtual mesh.",
                n,
                {"reason": reason.split(":", 1)[0]},
            )
        for reason, n in sorted(pm["run_fallbacks_by_reason"].items()):
            counter(
                "procmesh_run_fallbacks_total",
                "Dispatch-time ensemble degrades (per scan key or per wave), by reason.",
                n,
                {"reason": reason.split(":", 1)[0]},
            )

    # capacity engine (autoscaler/) — only once it has been constructed
    asc = m.get("autoscaler")
    if asc is not None:
        counter("autoscaler_passes_total", "Autoscaler passes run.", asc["passes"])
        counter("autoscaler_scale_ups_total", "Scale-up actions taken.", asc["scale_ups"])
        counter("autoscaler_scale_downs_total", "Scale-down (node drain) actions taken.", asc["scale_downs"])
        counter("autoscaler_nodes_added_total", "Nodes materialized by scale-up.", asc["nodes_added"])
        counter("autoscaler_nodes_removed_total", "Nodes drained by scale-down.", asc["nodes_removed"])
        counter("autoscaler_estimation_dispatches_total", "Vmapped estimation-kernel dispatches (one per scale-up estimate, all groups).", asc["estimate_dispatches"])
        counter("autoscaler_estimation_compiles_total", "XLA compilations of the estimation kernel.", asc["estimate_compiles"])
        counter("autoscaler_estimation_kernel_errors_total", "Kernel-path crashes degraded to the resource-only fallback (nonzero = bug).", asc["estimate_kernel_errors"])
        counter("autoscaler_estimation_sharded_dispatches_total", "Estimation dispatches executed with the template-row axis sharded over the mesh.", asc["estimate_sharded_dispatches"])
        counter("autoscaler_estimation_seconds_total", "Cumulative scale-up estimation wall.", round(asc["estimate_cum_s"], 6))
        counter("autoscaler_estimation_seconds_last", "Last scale-up estimation wall.", round(asc["estimate_last_s"], 6), typ="gauge")
        for gname, gs in sorted(asc["groups"].items()):
            for bound in ("current", "min", "max"):
                counter(
                    "autoscaler_node_group_size",
                    "Node-group size, by bound (current/min/max).",
                    gs[bound],
                    {"group": gname, "bound": bound},
                    typ="gauge",
                )

    # render-once wire-bytes cache (server/wirecache.py) — present only
    # once a DI container attached one (KSS_WIRECACHE=0 leaves it None)
    wc = getattr(di.cluster_store, "wirecache", None)
    if wc is not None:
        wcs = wc.stats()
        counter("wirecache_hits_total", "Wire renders served from the render-once byte cache (list items, watch events, single GETs).", wcs["hits"])
        counter("wirecache_misses_total", "Wire renders that had to json.dumps (first serve of an object version per groupVersion).", wcs["misses"])
        counter("wirecache_invalidations_total", "Cache entries purged by store mutations/replays (delete counts once; clear_for_replay counts each).", wcs["invalidations"])
        counter("wirecache_entries", "Object versions currently cached.", wcs["entries"], typ="gauge")

    # journal shipping / read replica (replication/) — present only on
    # a store fed by a ReplicaApplier (stays None on a primary)
    rep = getattr(di.cluster_store, "replication_stats", None)
    if rep is not None:
        counter("replication_records_shipped_total", "Journal records shipped from the primary's WAL and applied to this replica's store.", rep["records_shipped"])
        counter("replication_events_applied_total", "Store events applied by shipped records (a wave record carries many).", rep["events_applied"])
        counter("replication_lag_records", "Complete journal records readable but not yet applied (one record == one commit wave).", rep["lag_records"], typ="gauge")
        counter("replication_lag_seconds", "How long the apply backlog has been nonzero (0 when caught up with the durable stream).", round(rep["lag_seconds"], 6), typ="gauge")
        counter("replication_torn_records_total", "Partial/corrupt frames observed while tailing (counted read-only; the tailer never truncates the primary's files).", rep["torn_records"])
        counter("replication_rebases_total", "Follower rebases from a newer checkpoint after compaction pruned the segment being tailed.", rep["rebases"])
        counter("replica_promotions_total", "Failovers: this replica finalized replay and became the primary.", rep["promotions"])
        counter("replica_read_requests_total", "GET requests served by the replica's HTTP surface.", rep["read_requests"])
        # read-side disk faults on the primary's directory, classified
        # (ENOENT = not created yet, waits uncounted; everything else
        # counts here and paces the poll loop through RetryPolicy)
        counter("replication_backoffs_total", "Faulty polls that pushed the apply loop into seeded exponential backoff.", rep.get("backoffs", 0))
        for label, n in sorted((rep.get("read_errors_by_errno") or {}).items()):
            counter(
                "replication_read_errors_total",
                "Tailer read faults on the primary's journal directory (EACCES/EIO/...; never conflated with a journal that does not exist yet), by errno.",
                n,
                {"errno": label},
            )

    # per-seam retries (resilience/policy.py): every counted retry a
    # cross-process seam took and survived
    for seam, n in sorted((m.get("retry_by_seam") or {}).items()):
        counter(
            "retry_attempts_total",
            "Retries taken at a cross-process seam (procmesh re-dispatch, replication backoff, stream kernel-error drain), by seam.",
            n,
            {"seam": seam},
        )

    store = di.cluster_store
    from kube_scheduler_simulator_tpu.state.store import KINDS

    for kind in sorted(KINDS):
        counter(
            "cluster_objects",
            "Objects in the cluster store, by kind.",
            len(store.list(kind)),
            {"kind": kind},
            typ="gauge",
        )

    # session plane (tenancy/) — only once a session has ever existed,
    # and only on the default (unlabeled) render: a plain single-tenant
    # scrape stays byte-identical to the pre-session-plane output
    if session is None and sessions is not None and getattr(sessions, "ever_used", False):
        st = sessions.stats()
        counter("sessions_active", "Live sessions beyond the default (tenancy/manager.py).", st["sessions_active"], typ="gauge")
        counter("sessions_created_total", "Sessions created over /api/v1/sessions.", st["sessions_created_total"])
        counter("sessions_destroyed_total", "Sessions explicitly destroyed (journal namespace purged).", st["sessions_destroyed_total"])
        counter("sessions_expired_total", "Sessions reaped by the idle TTL (KSS_SESSION_TTL_S).", st["sessions_expired_total"])
        counter("sessions_rejected_total", "Session creations rejected by the admission cap (KSS_MAX_SESSIONS, HTTP 429).", st["sessions_rejected_total"])
        counter("sessions_recovered_total", "Sessions restored at boot from per-session journal namespaces.", st["sessions_recovered_total"])
        from kube_scheduler_simulator_tpu.tenancy.substrate import SUBSTRATE

        ss = SUBSTRATE.stats()
        counter("substrate_fn_hits_total", "Compiled executables another engine already published (tenant admission with a seen config = all hits, zero compiles).", ss["substrate_fn_hits_total"])
        counter("substrate_fn_misses_total", "Substrate lookups that found no published executable (first engine to see a value key).", ss["substrate_fn_misses_total"])
        counter("substrate_fn_entries", "Executables in the process-wide shared substrate, across families.", ss["substrate_fn_entries"], typ="gauge")

    text = "\n".join(lines) + "\n"
    if session is not None:
        return _label_session(text, session)
    return text
