"""The differential runner: one scenario, at least two independent paths.

Every subsystem shipped since PR 1 carries a sequential oracle and a
byte-parity bar; this module is the engine that drives them.  A fuzz
scenario executes through independent paths that must agree byte-for-byte
on the full annotation trail (:mod:`fuzz.verdict`):

- ``batch-vs-oracle``: the tick-driven drain through the TPU batch
  engine (``use_batch="auto"``, every exactness gate live) against the
  pure sequential cycle (``use_batch="off"``).
- ``stream-vs-serial``: the same timeline as a continuously draining
  admission feed, streamed (overlapped pipeline) vs strictly serial.
- ``shard-vs-single`` (opt-in): ``KSS_MESH_DEVICES=2`` node-axis
  sharding against the single-device engine, ``use_batch="force"``.
- ``shard-stream-vs-serial`` (opt-in): the stream × mesh FUSION — the
  timeline as a streamed feed on a ``KSS_MESH_DEVICES=2`` sharded
  engine (sharded double-buffered placer banks, overlapped waves)
  against the strictly serial single-device projection.

**Service reuse.**  XLA compiles dominate a fresh service's first round,
so a :class:`FuzzHarness` keeps one long-lived (store, service) pair per
(profile, role) and wipes the store between scenarios exactly the way
the scenario engine does (``store.restore({})``); executable caches
survive, and scenario workloads use disjoint name prefixes so queue /
backoff bookkeeping never collides.  Both members of a pair always
replay the same scenario sequence, so their rotation counters and
resourceVersion streams stay aligned — the property the tie-break draw
needs.  Divergences found mid-sequence are re-confirmed standalone (a
fresh harness) before shrinking.

Determinism: stores and services run on :class:`utils.SimClock` — wall
clock never reaches creationTimestamps, queue backoff, or Permit
deadlines during a fuzz run.
"""

from __future__ import annotations

import copy
import os
from typing import Any

from kube_scheduler_simulator_tpu.fuzz import verdict as V
from kube_scheduler_simulator_tpu.utils.parity import pod_parity_state
from kube_scheduler_simulator_tpu.utils.simclock import SimClock

Obj = dict[str, Any]

DEFAULT_COMPARISONS: tuple[str, ...] = ("batch-vs-oracle", "stream-vs-serial")

# simulated seconds appended after the last tick: past every gang
# timeout the generator emits, so parked waits always resolve before the
# final parity snapshot
EPILOGUE_ADVANCE_S = 330.0


class FuzzHarnessError(RuntimeError):
    """The harness itself broke an invariant (NOT a scenario divergence):
    e.g. a scenario left pods parked at Permit past the epilogue."""


def fuzz_knobs() -> Obj:
    """The documented ``KSS_FUZZ_*`` env knobs, validated here so a typo
    fails loudly at session start instead of silently fuzzing with
    defaults (docs/environment-variables.md)."""

    def _int(name: str, raw: str, default: int) -> int:
        raw = raw.strip()
        if not raw:
            return default
        try:
            v = int(raw)
        except ValueError:
            raise ValueError(f"{name} must be an integer, got {raw!r}") from None
        if v < 0:
            raise ValueError(f"{name} must be >= 0, got {v}")
        return v

    budget_raw = os.environ.get("KSS_FUZZ_BUDGET", "").strip()
    try:
        budget = float(budget_raw) if budget_raw else 0.0
    except ValueError:
        raise ValueError(f"KSS_FUZZ_BUDGET must be seconds (float), got {budget_raw!r}") from None
    return {
        "seed": _int("KSS_FUZZ_SEED", os.environ.get("KSS_FUZZ_SEED", ""), 0),
        "scenarios": _int("KSS_FUZZ_SCENARIOS", os.environ.get("KSS_FUZZ_SCENARIOS", ""), 25),
        "shrink_steps": _int(
            "KSS_FUZZ_SHRINK_STEPS", os.environ.get("KSS_FUZZ_SHRINK_STEPS", ""), 192
        ),
        "budget_s": budget,
    }


# ------------------------------------------------------------------ harness

_ROLE_KW: dict[str, dict] = {
    "oracle": {"use_batch": "off"},
    "batch": {"use_batch": "auto", "batch_min_work": 0},
    "stream-on": {"use_batch": "auto", "batch_min_work": 0},
    "stream-off": {"use_batch": "auto", "batch_min_work": 0},
    "shard": {"use_batch": "force", "batch_min_work": 0, "_mesh_devices": "2"},
    "shard-base": {"use_batch": "force", "batch_min_work": 0},
    # the stream × mesh fusion: sharded engines on a STREAMED feed,
    # byte-diffed against the serial single-device projection of the
    # same timeline (the cfg12 fusion's differential adversary)
    "shard-stream": {"use_batch": "force", "batch_min_work": 0, "_mesh_devices": "2"},
    "shard-stream-off": {"use_batch": "force", "batch_min_work": 0},
}


class FuzzHarness:
    """Long-lived (store, service) pairs keyed by (profile, role)."""

    def __init__(self) -> None:
        self._built: dict[tuple[str, str], tuple[Any, Any]] = {}

    def service(self, profile: str, role: str) -> tuple[Any, Any]:
        key = (profile, role)
        if key not in self._built:
            self._built[key] = self._build(profile, role)
        return self._built[key]

    def _build(self, profile: str, role: str) -> tuple[Any, Any]:
        from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
        from kube_scheduler_simulator_tpu.state.store import ClusterStore

        kw = dict(_ROLE_KW[role])
        mesh_devices = kw.pop("_mesh_devices", None)
        store = ClusterStore(clock=SimClock(1_700_000_000.0))
        store.create("namespaces", {"metadata": {"name": "default"}})
        cfg = None
        if profile == "gang":
            from kube_scheduler_simulator_tpu.gang import gang_scheduler_config

            cfg = gang_scheduler_config()
        prev_mesh = os.environ.get("KSS_MESH_DEVICES")
        if mesh_devices is not None:
            os.environ["KSS_MESH_DEVICES"] = mesh_devices
        try:
            svc = SchedulerService(
                store,
                tie_break="first",
                clock=SimClock(0.0),
                autoscale="on",
                # an EXPLICIT default-valued override: engines run the
                # traced-weights path from the start, so mid-run retunes
                # are value swaps (re-dispatch, never recompile) instead
                # of folded<->traced engine rebuilds
                weights={},
                **kw,
            )
        finally:
            if mesh_devices is not None:
                if prev_mesh is None:
                    os.environ.pop("KSS_MESH_DEVICES", None)
                else:
                    os.environ["KSS_MESH_DEVICES"] = prev_mesh
        svc.start_scheduler(cfg)
        return store, svc

    def reset(self, profile: str, role: str) -> tuple[Any, Any]:
        """The pair, wiped for the next scenario: cluster state cleared
        (the scenario-engine wipe), the default namespace restored, and
        the weight override back at the baseline.  Executable caches,
        clocks and rotation counters are deliberately KEPT — both members
        of a pair replay the same sequence, so they stay aligned."""
        store, svc = self.service(profile, role)
        store.restore({})
        store.create("namespaces", {"metadata": {"name": "default"}})
        svc.set_plugin_weights({})
        return store, svc


# ------------------------------------------------------------------- drive


def apply_op(store: Any, svc: Any, op: Obj) -> None:
    """Apply one scenario op.  Deletes/patches of absent objects are
    skipped (the shrinker removes creates without chasing references —
    forgiveness here keeps every shrunk scenario executable, and it is
    deterministic: under parity both paths see the same store)."""
    o = op["op"]
    if o == "create":
        try:
            store.create(op["kind"], copy.deepcopy(op["object"]))
        except (KeyError, ValueError):
            # admission failures (e.g. a pod naming a PriorityClass whose
            # create the shrinker deleted) skip the object, both paths
            pass
    elif o == "delete":
        try:
            store.delete(op["kind"], op["name"], op.get("namespace"))
        except KeyError:
            pass
    elif o == "patch":
        try:
            store.patch(op["kind"], op["name"], copy.deepcopy(op["body"]), op.get("namespace"))
        except KeyError:
            pass
    elif o == "weights":
        svc.set_plugin_weights(dict(op["weights"]))
    else:  # pragma: no cover - generator never emits unknown ops
        raise ValueError(f"unknown fuzz op {o!r}")


def _settle(store: Any, svc: Any, autoscaled: bool) -> None:
    """Post-timeline convergence: advance past every permit deadline,
    expire parked waits, and drain until quiescent."""
    clk = svc._clock
    clk.advance(EPILOGUE_ADVANCE_S)
    svc.process_waiting_pods()
    for _ in range(4):
        if autoscaled:
            results = svc.schedule_pending_autoscaled(max_rounds=2, max_passes=4)
        else:
            results = svc.schedule_pending(max_rounds=2)
        if not any(r.success or r.nominated_node for r in results.values()):
            break
        clk.advance(1.0)
    leftover = svc._all_waiting_keys()
    if leftover:
        raise FuzzHarnessError(f"pods still parked at Permit after epilogue: {sorted(leftover)}")


def run_ticks(scenario: Obj, store: Any, svc: Any) -> Obj:
    """The tick-driven projection: apply each tick's ops, drain the
    queue (autoscaled when the scenario composes the capacity engine),
    advance simulated time one step — then settle and snapshot."""
    clk = svc._clock
    step = float(scenario.get("stepSeconds") or 1.0)
    autoscaled = "autoscale" in scenario["features"]
    for ops in scenario["ticks"]:
        for op in ops:
            apply_op(store, svc, op)
        if autoscaled:
            svc.schedule_pending_autoscaled(max_rounds=2, max_passes=4)
        else:
            svc.schedule_pending(max_rounds=2)
        clk.advance(step)
    _settle(store, svc, autoscaled)
    return pod_parity_state(store)


def run_stream(scenario: Obj, store: Any, svc: Any, streaming: bool) -> Obj:
    """The stream projection: the same timeline as an admission feed
    (one tick per admission), streamed or strictly serial.  The capacity
    engine does not run mid-stream — autoscaler passes read in-flight
    state and would be legitimately phase-sensitive — so ``autoscale``
    scenarios exercise it only on the tick-driven comparison."""
    clk = svc._clock
    step = float(scenario.get("stepSeconds") or 1.0)
    ticks = scenario["ticks"]

    def feed(tick: int) -> bool:
        if tick >= len(ticks):
            return False
        for op in ticks[tick]:
            apply_op(store, svc, op)
        clk.advance(step)
        return True

    svc.schedule_stream(feed=feed, streaming=streaming, idle_sleep_s=0.0)
    _settle(store, svc, autoscaled=False)
    return pod_parity_state(store)


# ------------------------------------------------------------ differential

_COMPARISON_ROLES: dict[str, tuple[str, str]] = {
    "batch-vs-oracle": ("batch", "oracle"),
    "stream-vs-serial": ("stream-on", "stream-off"),
    "shard-vs-single": ("shard", "shard-base"),
    # sharded + streamed simultaneously vs serial single-device: the
    # fused fast path's parity bar (ISSUE 13 / ROADMAP "fuse stream ×
    # mesh"), driven from day one by the fuzzer's composite scenarios
    "shard-stream-vs-serial": ("shard-stream", "shard-stream-off"),
}


def _run_role(scenario: Obj, store: Any, svc: Any, role: str, chaos: "Obj | None") -> Obj:
    def drive() -> Obj:
        if role in ("stream-on", "shard-stream"):
            return run_stream(scenario, store, svc, streaming=True)
        if role in ("stream-off", "shard-stream-off"):
            return run_stream(scenario, store, svc, streaming=False)
        return run_ticks(scenario, store, svc)

    if chaos and role in (chaos.get("roles") or ("batch",)):
        from kube_scheduler_simulator_tpu.fuzz.chaos import KernelChaos

        with KernelChaos(svc, fail_events=frozenset(chaos["fail_events"])):
            return drive()
    return drive()


def run_differential(
    scenario: Obj,
    harness: "FuzzHarness | None" = None,
    comparisons: "tuple[str, ...]" = DEFAULT_COMPARISONS,
    chaos: "Obj | None" = None,
) -> tuple[Obj, dict[str, Obj]]:
    """Execute ``scenario`` through every requested comparison pair and
    judge the byte diffs.  Returns ``(verdict, states)`` where
    ``states`` maps role -> parity state (fixture replay pins the oracle
    state's exact bytes).  ``chaos`` is a plan dict
    ``{"roles": [...], "fail_events": [...]}`` applied to the named
    roles' services (:mod:`fuzz.chaos`)."""
    harness = harness or FuzzHarness()
    profile = scenario.get("profile") or "default"
    cmps: list[Obj] = []
    states: dict[str, Obj] = {}
    for kind in comparisons:
        role_a, role_b = _COMPARISON_ROLES[kind]
        store_a, svc_a = harness.reset(profile, role_a)
        before = V.gate_snapshot(svc_a.metrics())
        state_a = _run_role(scenario, store_a, svc_a, role_a, chaos)
        explained = V.gate_delta(before, V.gate_snapshot(svc_a.metrics()))
        store_b, svc_b = harness.reset(profile, role_b)
        state_b = _run_role(scenario, store_b, svc_b, role_b, chaos)
        states[role_a], states[role_b] = state_a, state_b
        cmps.append(V.compare(kind, state_a, state_b, explained))
    return V.verdict(scenario, cmps), states


def encode_state(state: Obj) -> list:
    """Canonical JSON-serializable form of a parity state — the exact
    bytes a fixture's ``expected`` field commits."""
    return [[k, V._row(state[k])] for k in sorted(state)]
