"""The weight tuners: whole-rollout optimization without leaving the device.

A :class:`TuningSession` encodes a scenario ONCE (host), places it ONCE
(one device_put), and then every tuner iteration exchanges only a weight
vector [S] against one scalar objective (or one [S] gradient) — the full
scan-over-pods rollout, the objective reduction, and (for CEM) the whole
population sweep run as single XLA dispatches:

- ``run_cem``: cross-entropy method over the HARD objective — one
  vmapped dispatch per generation evaluates the entire population.
  Needs nothing differentiable, so it covers every objective.
- ``run_grad``: normalized gradient ascent through the straight-through
  relaxed rollout (tuning/relax.py).  One value-and-grad dispatch per
  step; forward values are bit-identical to the hard rollout, so the
  reported objectives need no re-evaluation.

Knobs (all overridable per call, env defaults validated hard like
``KSS_PLACER_SCATTER_FRAC``):

- ``KSS_TUNING_STEPS`` (default 8): tuner iterations.
- ``KSS_TUNING_POP`` (default 16): CEM population per generation.
- ``KSS_TUNING_TAU`` (default 50.0): softmax temperature of the relaxed
  head — roughly the score-total gap (in weighted normalized-score
  points) at which two nodes share gradient mass.
- ``KSS_TUNING_LR`` (default 1.0): normalized-gradient step size, in
  weight units — large enough to cross a decision boundary (weights are
  O(1)–O(3)) within a few steps.
"""

from __future__ import annotations

import os
from typing import Any

import numpy as np

from kube_scheduler_simulator_tpu.tuning.objective import OBJECTIVES
from kube_scheduler_simulator_tpu.tuning.scenario import FAMILIES, build_family
from kube_scheduler_simulator_tpu.tuning.validate import (
    WeightValidationError,
    validate_plugin_weights,
)

Obj = dict[str, Any]


def _env_pos(name: str, default: float, integer: bool = False):
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return int(default) if integer else float(default)
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(f"{name} must be a positive number, got {raw!r}") from None
    if v <= 0 or (integer and v != int(v)):
        kind = "positive integer" if integer else "positive number"
        raise ValueError(f"{name} must be a {kind}, got {raw!r}")
    return int(v) if integer else v


def tuning_defaults() -> dict:
    return {
        "steps": _env_pos("KSS_TUNING_STEPS", 8, integer=True),
        "pop": _env_pos("KSS_TUNING_POP", 16, integer=True),
        "tau": _env_pos("KSS_TUNING_TAU", 50.0),
        "lr": _env_pos("KSS_TUNING_LR", 1.0),
    }


def profile_scores(svc: Any = None) -> "tuple[list[tuple[str, int]], list[str]]":
    """(score plugins with default weights, filter plugin names) — from a
    live SchedulerService's default profile when given, else from a
    throwaway default-config service (what the standalone bench/smoke
    paths tune against)."""
    if svc is None:
        from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
        from kube_scheduler_simulator_tpu.state.store import ClusterStore

        svc = SchedulerService(ClusterStore())
        svc.start_scheduler(None)
    fw = svc.framework
    assert fw is not None, "scheduler not started"
    scores = [
        (wp.original.name, fw.score_weights.get(wp.original.name, 1))
        for wp in fw.plugins["score"]
    ]
    filters = [wp.original.name for wp in fw.plugins["filter"]]
    return scores, filters


class TuningSession:
    """One scenario placed on device + the jitted rollout closures.

    ``rollouts`` counts objective evaluations (CEM counts every
    population member), ``dispatches`` device dispatches, and
    ``grad_dispatches`` the value-and-grad calls — the numbers
    ``/metrics`` and BENCH_tune.json rows report."""

    def __init__(
        self,
        nodes: "list[Obj]",
        pods: "list[Obj]",
        scores: "list[tuple[str, int]]",
        filters: "list[str] | None" = None,
        objective: str = "utilization",
        dtype: Any = None,
    ):
        import jax

        from kube_scheduler_simulator_tpu.ops import batch as B
        from kube_scheduler_simulator_tpu.ops import encode as E

        if objective not in OBJECTIVES:
            raise ValueError(f"unknown objective {objective!r}; choose from {OBJECTIVES}")
        if not scores:
            raise ValueError("tuning needs at least one score plugin")
        self.objective = objective
        self.scores = list(scores)
        kernel_filters = tuple(
            f
            for f in (filters if filters is not None else B.FILTER_KERNELS)
            if f in set(B.FILTER_KERNELS)
        )
        for s, _w in scores:
            if s not in set(B.SCORE_KERNELS):
                raise ValueError(f"score plugin {s} has no batch kernel to tune")
        pr = E.encode(nodes, pods, pods, None)
        pr = E.pad_problem(pr)
        dp, dims = B.lower(pr, dtype=dtype)
        self.cfg = B.BatchConfig(
            filters=kernel_filters,
            scores=tuple((s, w) for s, w in scores),
            trace=False,
            tie_break="first",
            sampling=False,
            traced_weights=True,
        )
        self.dims = dims
        self.pr = pr
        # ONE placement; every rollout reuses the resident planes and
        # ships only the [S] weight vector
        self.dp = jax.device_put(dp)
        self.age_w = jax.device_put(
            np.asarray(E.objective_planes(pr, pods)["age_w"], dtype=dp.alloc.dtype)
        )
        self._dtype = dp.alloc.dtype
        from kube_scheduler_simulator_tpu.tuning import relax

        self._jax = jax
        self._relax = relax
        self._value = jax.jit(relax.build_value_fn(self.cfg, dims, objective))
        self._pop_fn = None
        self._grad_fns: dict[float, Any] = {}
        self.rollouts = 0
        self.dispatches = 0
        self.grad_dispatches = 0

    def _w(self, w) -> np.ndarray:
        w = np.asarray(w, dtype=np.float64)
        if w.shape != (len(self.scores),):
            raise WeightValidationError(
                f"weight vector shape {w.shape} != ({len(self.scores)},)"
            )
        return w

    def evaluate(self, w) -> float:
        """One hard rollout → the objective scalar (higher = better)."""
        v = self._value(self.dp, self._w(w), self.age_w)
        self.rollouts += 1
        self.dispatches += 1
        return float(v)

    def evaluate_population(self, W: np.ndarray) -> np.ndarray:
        """[pop,S] weight matrix → [pop] objectives, ONE dispatch."""
        if self._pop_fn is None:
            self._pop_fn = self._relax.build_population_fn(
                self._relax.build_value_fn(self.cfg, self.dims, self.objective)
            )
        W = np.asarray(W, dtype=np.float64)
        v = np.asarray(self._pop_fn(self.dp, W, self.age_w))
        self.rollouts += len(W)
        self.dispatches += 1
        return v

    def value_and_grad(self, w, tau: float) -> "tuple[float, np.ndarray]":
        """Relaxed-rollout objective + d(objective)/d(weights); the value
        is bit-identical to ``evaluate`` (straight-through forward)."""
        fn = self._grad_fns.get(float(tau))
        if fn is None:
            fn = self._grad_fns[float(tau)] = self._relax.build_grad_fn(
                self._relax.build_value_fn(
                    self.cfg, self.dims, self.objective, relax_tau=float(tau)
                )
            )
        v, g = fn(self.dp, self._w(w), self.age_w)
        self.rollouts += 1
        self.dispatches += 1
        self.grad_dispatches += 1
        return float(v), np.asarray(g, dtype=np.float64)


def run_cem(
    session: TuningSession,
    init: np.ndarray,
    steps: "int | None" = None,
    pop: "int | None" = None,
    elite_frac: float = 0.25,
    seed: int = 0,
) -> dict:
    """Cross-entropy search from ``init``; returns best weights/objective
    plus the per-generation history (best-so-far is monotone by
    construction — the smoke test pins it)."""
    d = tuning_defaults()
    steps = int(steps if steps is not None else d["steps"])
    pop = max(int(pop if pop is not None else d["pop"]), 2)
    rng = np.random.default_rng(seed)
    mean = np.asarray(init, dtype=np.float64).copy()
    std = np.maximum(mean * 0.5, 0.5)
    n_elite = max(int(pop * elite_frac), 1)
    best_w, best_v = mean.copy(), -np.inf
    history = []
    # Generation-0 screening candidates: the zero vector and each
    # plugin's one-hot (at its default magnitude).  Gaussian samples
    # around the profile default can't reach structurally different
    # corners of the weight simplex (e.g. "ignore this plugin entirely")
    # within a few generations — the screen hands CEM every single-
    # plugin policy up front and the Gaussian refines from whichever
    # region wins.  At most half the population, so random exploration
    # survives even tiny pops.
    screen = [np.zeros_like(mean)] + [
        np.eye(len(mean))[j] * max(mean[j], 1.0) for j in range(len(mean))
    ]
    for t in range(steps):
        W = rng.normal(mean, std, size=(pop, len(mean))).clip(0.0, None)
        W[0] = mean  # elitist: the current mean is always a candidate
        if t == 0:
            for j, cand in enumerate(screen[: max(pop // 2, 1)]):
                W[1 + j] = cand
        vals = session.evaluate_population(W)
        order = np.argsort(-vals, kind="stable")
        elites = W[order[:n_elite]]
        mean = elites.mean(axis=0)
        std = np.maximum(elites.std(axis=0), 0.05)
        if float(vals[order[0]]) > best_v:
            best_v = float(vals[order[0]])
            best_w = W[order[0]].copy()
        history.append(
            {"step": t, "generationBest": float(vals[order[0]]), "bestSoFar": best_v}
        )
    return {"weights": best_w.tolist(), "objective": best_v, "history": history}


def run_grad(
    session: TuningSession,
    init: np.ndarray,
    steps: "int | None" = None,
    lr: "float | None" = None,
    tau: "float | None" = None,
) -> dict:
    """Normalized gradient ascent through the straight-through relaxed
    rollout.  The step is ``lr · g/‖g‖`` — weight-scale moves regardless
    of the objective's raw gradient magnitude."""
    d = tuning_defaults()
    steps = int(steps if steps is not None else d["steps"])
    lr = float(lr if lr is not None else d["lr"])
    tau = float(tau if tau is not None else d["tau"])
    w = np.asarray(init, dtype=np.float64).copy()
    best_w, best_v = w.copy(), -np.inf
    history = []
    for t in range(steps):
        v, g = session.value_and_grad(w, tau)
        if v > best_v:
            best_v, best_w = v, w.copy()
        gn = float(np.linalg.norm(g))
        history.append({"step": t, "objective": v, "gradNorm": gn, "bestSoFar": best_v})
        if gn < 1e-12:
            break  # flat surrogate (e.g. pending_age): stop honestly
        w = np.clip(w + lr * g / gn, 0.0, None)
    # the post-update endpoint may beat every visited point
    v_end = session.evaluate(w)
    if v_end > best_v:
        best_v, best_w = v_end, w.copy()
    return {"weights": best_w.tolist(), "objective": best_v, "history": history}


def run_tuning(
    family: str = "imbalance",
    objective: "str | None" = None,
    tuner: str = "cem",
    n_nodes: int = 12,
    n_pods: int = 96,
    steps: "int | None" = None,
    pop: "int | None" = None,
    lr: "float | None" = None,
    tau: "float | None" = None,
    seed: int = 0,
    weights: Any = None,
    svc: Any = None,
) -> dict:
    """One tuning run: build the scenario family, evaluate the profile's
    default weights, run the named tuner, and report the comparison —
    the shape ``/api/v1/tuning``, ``bench.py --tune-report`` and
    ``scripts/tune_smoke.py`` all consume.

    ``weights``: optional user-supplied STARTING vector (validated
    against the profile's score plugins — arity/finite/non-negative,
    :class:`WeightValidationError` on failure).  ``svc``: a live
    SchedulerService whose profile defines the plugin set and whose
    ``tuning_*`` counters absorb this run's dispatch counts."""
    if tuner not in ("cem", "grad"):
        raise ValueError(f"tuner must be cem|grad, got {tuner!r}")
    scores, filters = profile_scores(svc)
    names = [s for s, _w in scores]
    default_w = np.asarray([float(w) for _s, w in scores], dtype=np.float64)
    init = (
        validate_plugin_weights(weights, names, defaults=dict(scores))
        if weights is not None
        else default_w
    )
    nodes, pods, fam_obj = build_family(family, n_nodes=n_nodes, n_pods=n_pods, seed=seed)
    objective = objective or fam_obj
    session = TuningSession(nodes, pods, scores, filters=filters, objective=objective)
    default_v = session.evaluate(default_w)
    if tuner == "cem":
        res = run_cem(session, init, steps=steps, pop=pop, seed=seed)
    else:
        res = run_grad(session, init, steps=steps, lr=lr, tau=tau)
    tuned_v = float(res["objective"])
    report = {
        "family": family,
        "objective": objective,
        "tuner": tuner,
        "nodes": len(nodes),
        "pods": len(pods),
        "scorePlugins": names,
        "defaultWeights": default_w.tolist(),
        "defaultObjective": default_v,
        "weights": res["weights"],
        "tunedObjective": tuned_v,
        "improvement": tuned_v - default_v,
        "rollouts": session.rollouts,
        "dispatches": session.dispatches,
        "gradDispatches": session.grad_dispatches,
        "history": res["history"],
    }
    try:
        import jax

        report["kernelPlatform"] = jax.default_backend()
    except Exception:  # pragma: no cover - jax always present in-tree
        report["kernelPlatform"] = "unknown"
    if svc is not None and hasattr(svc, "note_tuning_run"):
        svc.note_tuning_run(session, report)
    return report


def tuning_families() -> "list[str]":
    return sorted(FAMILIES)
