"""The streaming wave pipeline: overlap wave k+1's encode/upload/dispatch
with wave k's in-flight kernel and host commit, fed by a continuously
draining admission queue.

Before this module the batch path was round-oriented: freeze a pending
snapshot, encode, dispatch, block, commit — host idle while the kernel
runs, device idle while the host formats annotations.  A StreamSession
dissolves the round boundary:

- **Admission** drains the scheduling queue fresh at every wave (pods
  that arrived while the previous wave was in flight join the very next
  encode) instead of freezing one pending set per round.
- **Overlap**: as soon as wave k's packed decisions are fetched (a tiny
  [5,P] int32 read — ``PendingBatch.decisions()``), wave k+1 is admitted,
  delta-encoded against a synthesized view of the store with wave k's
  placements applied, uploaded into the *other* DevicePlacer bank, and
  dispatched.  Wave k's trace fetch, annotation materialization and
  ``add_wave_results``/``flush_wave`` then run while wave k+1's kernel is
  in flight.
- **Exactness**: commit order is strict (wave k commits fully before any
  of wave k+1), the next wave's ``base_counter``/``start_index`` are the
  values the sequential path would have reached (every attempted pod
  advances the counter by one; the rotation start is wave k's
  ``final_start``), and the synthesized encode view differs from the
  post-commit store only in fields the encoder ignores (resourceVersion
  bumps, status conditions, annotations) — so a streamed run's
  annotation bytes are byte-identical to the serial path's
  (tests/test_stream.py, scripts/stream_smoke.py).

Mesh-sharded engines stream too (the stream × mesh fusion): a wave's
delta encode scatters into the *other* bank's SHARDED resident planes
(DevicePlacer preserves each plane's NamedSharding across bank
rotation), the scan dispatches with the node axis sharded over the
mesh, and on accelerator meshes the sharded initial carry is donated
shard-for-shard — so a 50k-node-class sharded kernel for wave k can be
in flight while wave k+1 encodes into the opposite bank
(scripts/shard_stream_smoke.py, bench cfg12).

Anything outside that envelope **drains the pipeline**, counted per
reason in ``stream_drains_by_reason``.  Most reasons route the wave to
the sequential path — gang profiles / parked waiting pods ("gang" — a
GangRound's atomic commit must never interleave with a streamed wave),
pending preemption nominations, multi-profile rounds, unsupported
workloads, trace-less engines, and kernel failures on profiles whose
PostFilter could preempt (a successful preemption rewrites cluster
state mid-round);
those waves run through ``SchedulerService.schedule_pending`` — the
pre-existing exact machinery — and streaming resumes at the next wave.
Three gates only SERIALIZE the streamed boundary: a mid-stream
node/config change commits wave k first and re-dispatches the gated
pods streamed against the settled store; force-mode kernel failures
stream their commit but hold the next admission until after it (so the
failed pods' requeue lands on the serial cadence); and a pod parked in
unschedulableQ holds the overlap admission until wave k's commit has
fired its events (binds move_all parked pods — an admission taken
before the commit could miss the reactivation the serial cadence would
see).  All three still count a drain event — the counter tracks
pipeline serialization points, not sequential-path rounds.

A device CRASH (dispatch, decision fetch, or result blob fetch raising
— real, or injected by fuzz/chaos.py) is survivable at every point: the
dying wave has committed nothing, so its pods simply re-drain through
the sequential path (or the next admission), counted as ``kernel
error: <type>`` in ``stream_drains_by_reason`` — never a partial or
divergent wave.

``KSS_STREAM_PIPELINE=0`` (or ``streaming=False``) keeps the admission
loop but runs every wave strictly serially — the A/B baseline the bench
compares against (``bench.py --stream-report``).
"""

from __future__ import annotations

import time
from typing import Any, Callable

from kube_scheduler_simulator_tpu.utils.keys import pod_key as _pod_key

Obj = dict[str, Any]


def stream_pipeline_enabled(default: bool = True) -> bool:
    """Resolve the ``KSS_STREAM_PIPELINE`` env knob ("0"/"off"/"false"/
    "no" disables the overlap; anything else — including unset — keeps
    the default)."""
    import os

    env = os.environ.get("KSS_STREAM_PIPELINE", "").strip().lower()
    if env in ("0", "off", "false", "no"):
        return False
    return default


class StreamSession:
    """One continuous streaming run over a SchedulerService.

    ``feed``: called once per admission tick with the tick index; it may
    create/delete store objects (the arrival stream) and returns False
    when the source is exhausted (the session then runs until the queue
    and the pipeline are empty).  ``duration_s`` bounds the admission
    phase by wall clock instead (external feeder thread); ``max_waves``
    bounds the streamed wave count; ``wave_pods`` caps the pods admitted
    per wave (None = drain everything ready).  ``streaming`` overrides
    the ``KSS_STREAM_PIPELINE`` knob."""

    def __init__(
        self,
        service: Any,
        feed: "Callable[[int], bool] | None" = None,
        duration_s: "float | None" = None,
        max_waves: "int | None" = None,
        wave_pods: "int | None" = None,
        streaming: "bool | None" = None,
        idle_sleep_s: float = 0.002,
        gc_every_waves: int = 32,
    ):
        self.svc = service
        self.feed = feed
        self.duration_s = duration_s
        self.max_waves = max_waves
        self.wave_pods = wave_pods
        self.streaming = (
            stream_pipeline_enabled() if streaming is None else bool(streaming)
        )
        self.idle_sleep_s = idle_sleep_s
        # gc is disabled for the whole session (a collection pause
        # mid-wave would serialize the pipeline at a random point), but a
        # long stream allocates continuously and unswept garbage degrades
        # every allocation — so collect at wave BOUNDARIES, every this
        # many commits, where the pause overlaps the next wave's
        # in-flight kernel instead of splitting a wave
        self.gc_every_waves = gc_every_waves
        self._commits_since_gc = 0
        # waves committed by THIS session — ``max_waves`` is a
        # per-session budget, while stats["stream_waves"] accumulates
        # over the service's whole lifetime (a second session on the
        # same service must not inherit the first one's spend)
        self._session_waves = 0
        self.results: dict[str, Any] = {}
        self._feed_alive = feed is not None
        self._tick = 0
        self._t0 = 0.0
        # set when an overlap admission was GATED: its pods were drained
        # from the queue conceptually but not dispatched — the next
        # admission re-drains them without consuming a new feed tick, so
        # wave composition stays aligned with the serial cadence
        self._feed_hold = False

    # ------------------------------------------------------------- stats

    def _count_drain(self, reason: str) -> None:
        if reason.startswith("kernel error"):
            # a kernel-error drain re-dispatches the wave's pods through
            # the sequential path — a retry at the stream seam, counted
            # like every other (retry_attempts_total{seam="stream"})
            from kube_scheduler_simulator_tpu.resilience import note_retry

            note_retry("stream")
        with self.svc._stats_lock:
            d = self.svc.stats["stream_drains"]
            d[reason] = d.get(reason, 0) + 1

    def _note_wave(self, cnt: int) -> None:
        # lock-free: single-writer scalar bumps (only the session thread
        # commits waves); each += is GIL-atomic under fixed dict keys, and
        # the metrics scrape tolerates one-wave skew between counters —
        # _stats_lock is reserved for multi-key read-modify-write publishes
        # like the stream_drains dict (_count_drain)
        self._session_waves += 1
        self.svc.stats["stream_waves"] += 1
        self.svc.stats["stream_pods"] += cnt

    # --------------------------------------------------------- admission

    def _admitting(self) -> bool:
        """Is the arrival stream still open?"""
        if self.duration_s is not None:
            return time.perf_counter() - self._t0 < self.duration_s
        return self._feed_alive

    def _admit(self, exclude: "frozenset[str] | set[str]") -> list[Obj]:
        """One admission tick: pull the feed, expire permits, and drain
        everything the queue allows minus the in-flight wave."""
        svc = self.svc
        if self._feed_hold:
            # re-draining a gated admission: its feed tick already fired
            self._feed_hold = False
        elif self._feed_alive and self.feed is not None and (
            self.duration_s is None
            or time.perf_counter() - self._t0 < self.duration_s
        ):
            self._feed_alive = bool(self.feed(self._tick))
            self._tick += 1
        # queue maintenance carve-out: waiting-pod processing, backoff
        # gates and QueueSort stamp as their own stage (exclusive of any
        # store mutations they trigger — those stamp store_mutate)
        prof = svc.profiler
        rec = prof.current
        tq = time.perf_counter()
        n0 = prof.nested(rec)
        svc.process_waiting_pods()
        cands = svc._ready_pending(respect_backoff=False)
        if exclude:
            cands = [p for p in cands if _pod_key(p) not in exclude]
        pending = svc.framework.sort_pods(cands)
        prof.note_excl(rec, "queue_maint", time.perf_counter() - tq, n0)
        if self.wave_pods is not None:
            pending = pending[: self.wave_pods]
        return pending

    # ------------------------------------------------------------- gates

    def _gate(
        self, pending: list[Obj], nodes: list[Obj]
    ) -> "tuple[str | None, dict | None]":
        """``(reason, volumes)``: why this wave must take the sequential
        path (reason None = streamable), plus the volume listing the
        supported() check already paid for — handed to the immediately
        following dispatch so the store isn't scanned twice per wave.
        Mirrors _schedule_pending_batch's envelope, but conservatively:
        a streamed wave must be committable from its trace alone."""
        svc = self.svc
        fw = svc.framework
        if svc.use_batch not in ("auto", "force"):
            return "batch disabled", None
        if any(svc.framework_for(p) is not fw for p in pending):
            return "multi-profile", None
        # gang profiles park members at Permit and commit whole groups
        # atomically — a GangRound must never interleave with a streamed
        # wave's commit, so both the profile shape and any already-parked
        # waiting pod drain the pipeline
        if fw.plugins["permit"] or svc._all_waiting_keys():
            return "gang", None
        if svc._pending_nominations():
            return "nominated pods", None
        eng = svc._engine_for(fw)
        if not eng.trace:
            # a trace-less engine cannot commit a wave from its result
            # (no annotation trail) — the pre-existing exact path.
            # Mesh-sharded engines STREAM (the stream × mesh fusion):
            # schedule_async uploads into sharded double-buffered placer
            # banks and dispatches the node-sharded scan.
            return "trace disabled", None
        if (
            svc.use_batch == "auto"
            and len(pending) * max(len(nodes), 1) < svc.batch_min_work
        ):
            return "below batch_min_work", None
        volumes = eng._volumes()
        ok, why = eng.supported(pending, nodes, volumes=volumes)
        if not ok:
            return f"unsupported: {why}", None
        return None, volumes

    @staticmethod
    def _node_fp(nodes: list[Obj]) -> tuple:
        return tuple(
            (n["metadata"]["name"], n["metadata"].get("resourceVersion"))
            for n in nodes
        )

    # ---------------------------------------------------------- pipeline

    def _view_pods(self, binds: "dict[str, str]") -> list[Obj]:
        """The store's pods with the in-flight wave's placements applied
        as synthesized binds — what the next wave's encode must see.
        Differs from the post-commit store only in resourceVersion (a
        pure cache key: the delta encoder re-checks such rows and
        produces identical values) and status/annotation fields the
        encoder never reads."""
        pods = self.svc.cluster_store.list("pods", copy_objects=False)
        if not binds:
            return pods
        out = []
        for p in pods:
            nn = binds.get(_pod_key(p))
            if nn is not None and not (p.get("spec") or {}).get("nodeName"):
                out.append({**p, "spec": {**(p.get("spec") or {}), "nodeName": nn}})
            else:
                out.append(p)
        return out

    def _dispatch(
        self,
        pending: list[Obj],
        nodes: list[Obj],
        base_counter: int,
        start_index: int,
        bank: int,
        volumes: "dict | None",
        binds: "dict[str, str] | None" = None,
        prof_rec: "dict | None" = None,
    ) -> dict:
        """Encode + upload + dispatch one wave (non-blocking); returns
        the in-flight record the commit step consumes.  ``volumes`` is
        the listing the gate's supported() check already built.
        ``prof_rec``: the wave-profiler record opened at this wave's
        admission (the "admit" stage accrued there; encode/upload/
        dispatch accrue inside the engine)."""
        svc = self.svc
        fw = svc.framework
        eng = svc._engine_for(fw)
        ta = time.perf_counter()
        pods_view = self._view_pods(binds or {})
        namespaces = svc.cluster_store.list("namespaces", copy_objects=False)
        eng.profiler.note(prof_rec, "admit", time.perf_counter() - ta)
        pb = eng.schedule_async(
            nodes,
            pods_view,
            pending,
            namespaces,
            base_counter=base_counter,
            start_index=start_index,
            volumes=volumes if volumes is not None else eng._volumes(),
            bank=bank,
            prof_rec=prof_rec,
        )
        return {
            "pb": pb,
            "fw": fw,
            "keys": {_pod_key(p) for p in pending},
            "node_fp": self._node_fp(nodes),
        }

    def _seq_failures(self) -> bool:
        """Would the serial path route kernel failures through PostFilter
        (preemption)?  Mirrors _run_segment_batch's seq_failures."""
        fw = self.svc.framework
        return bool(fw.plugins["post_filter"]) and self.svc.use_batch != "force"

    def _fetch_result(self, flight: dict) -> bool:
        """Block on the wave's compaction blob — the LAST device
        interaction of a wave, guarded so a crash (real, or injected by
        fuzz/chaos.py) drains cleanly while NOTHING is committed yet.
        Only this fetch is guarded: a failure inside ``_commit`` proper
        is a host-commit bug after pods may have bound, and must crash
        loudly (the batch path guards only its window fetches for the
        same reason).  The blocked wait lands in ``stream_stall_s``
        here; the fetch is cached, so ``_commit``'s own accounting sees
        zero further device wait."""
        pb = flight["pb"]
        dev0 = pb._dev_wait
        try:
            pb.result()
        except Exception as e:
            self._count_drain(f"kernel error: {type(e).__name__}")
            return False
        finally:
            # lock-free: single-writer scalar bump on the session thread
            # (GIL-atomic += on a fixed stats key)
            self.svc.stats["stream_stall_s"] += pb._dev_wait - dev0
        return True

    def _commit(self, flight: dict, overlapped: bool) -> None:
        """Commit one streamed wave in strict order: trace fetch,
        annotation materialization, bulk result-store fill, bind +
        reflector flush — byte-identical to the serial batch round
        (the commit runs through the very same _replay_window /
        _commit_batch_wave machinery)."""
        svc = self.svc
        fw = flight["fw"]
        pb = flight["pb"]
        t0 = time.perf_counter()
        dev0 = pb._dev_wait
        result = pb.result()  # blocks on the compaction blob only
        # seconds of that window spent BLOCKED on the device (the blob
        # fetch) are a stall, not hidden work — keep them out of the
        # overlap bucket so overlap_efficiency stays honest
        dev_wait = pb._dev_wait - dev0
        # lock-free: single-writer scalar bumps on the session thread
        # (fixed keys, GIL-atomic +=); _stats_lock guards only multi-key
        # dict publishes — see _count_drain
        svc.stats["stream_stall_s"] += dev_wait
        cnt = len(pb.pending)
        point_names = {
            p: [wp.original.name for wp in fw.plugins[p]]
            for p in ("pre_filter", "pre_score", "reserve", "permit", "pre_bind", "bind")
        }
        restart = svc._replay_window(
            result, 0, 0, cnt, None, point_names, fw,
            False,  # kernel failures commit from the trace (gated earlier)
            self.results, None, None,
        )
        assert restart is None, "streamed waves never request kernel restarts"
        fw.next_start_node_index = result.final_start
        svc._sync_rotation(fw)
        svc.stats["batch_commits"] += 1
        self._note_wave(cnt)
        dt = time.perf_counter() - t0
        if overlapped:
            # host seconds spent while the NEXT wave's kernel was in
            # flight — the pipeline's hidden work (minus the stalled part)
            self.svc.stats["stream_overlap_s"] += max(dt - dev_wait, 0.0)

    def _maybe_gc(self) -> None:
        """Bounded-garbage sweep: a full collection every
        ``gc_every_waves`` committed waves, always at a wave boundary (a
        kernel may be in flight — the pause hides in the device shadow;
        what it must never do is land mid-wave via the allocator)."""
        self._commits_since_gc += 1
        if self._commits_since_gc >= self.gc_every_waves:
            self._commits_since_gc = 0
            import gc

            gc.collect()

    def _drain_round(self, reason: "str | None") -> None:
        """Drain the (empty) pipeline to the sequential path: one full
        pre-existing scheduling round with its exact preemption / gang /
        nomination machinery, counted per reason."""
        if reason is not None:
            self._count_drain(reason)
        self.results.update(self.svc.schedule_pending(max_rounds=1))
        self._maybe_gc()

    # --------------------------------------------------------------- run

    def run(self) -> dict[str, Any]:
        svc = self.svc
        assert svc.framework is not None, "scheduler not started"
        import gc

        # register with the service's quiesce machinery: an exclusive
        # store operation (snapshot load) waits until every busy session
        # has parked at a wave boundary (svc.pause_streams)
        with svc._stream_cv:
            svc._stream_busy += 1
        self._t0 = time.perf_counter()
        gc_was_enabled = gc.isenabled()
        if gc_was_enabled:
            gc.disable()
        try:
            self._loop()
        finally:
            # the busy slot MUST come back even if the final flush
            # raises — a leaked count would make every future
            # pause_streams stall its full timeout and proceed without
            # the exclusivity it exists to provide
            try:
                if gc_was_enabled:
                    gc.enable()
                svc.reflector.flush_all(
                    svc.cluster_store, skip_keys=svc._all_waiting_keys()
                )
            finally:
                with svc._stream_cv:
                    svc._stream_busy -= 1
                    svc._stream_cv.notify_all()
        return self.results

    def _park_for_pause(self) -> None:
        """An exclusive store operation requested the pipeline idle:
        count ONE drain under its reason, hand back the busy slot, and
        block until the pause lifts.  Runs only at a wave boundary — the
        pipeline is empty here, so the operation never interleaves with
        an in-flight wave commit."""
        svc = self.svc
        with svc._stream_cv:
            reason = svc._stream_pause_reason
            if reason is None:
                return
            self._count_drain(reason)
            svc._stream_busy -= 1
            svc._stream_cv.notify_all()
            # no timeout: the pauser's own wait is the bounded one — a
            # parked session resuming early would re-enter dispatch
            # inside the exclusive window, which is exactly the
            # interleaving the gate exists to prevent
            svc._stream_cv.wait_for(lambda: svc._stream_pause_reason is None)
            svc._stream_busy += 1

    def _waves_left(self, in_flight: int = 0) -> bool:
        """May another streamed wave be DISPATCHED?  ``in_flight`` counts
        dispatched-but-uncommitted waves (the overlap prefetch point has
        one), which the committed-wave counter hasn't seen yet."""
        return (
            self.max_waves is None
            or self._session_waves + in_flight < self.max_waves
        )

    def _loop(self) -> None:
        svc = self.svc
        flight: "dict | None" = None  # the in-flight wave
        bank = 0
        while True:
            if flight is None:
                # an exclusive store operation (snapshot load) may be
                # waiting on the pipeline: park here, at the empty-
                # pipeline boundary, until it finishes (counted drain)
                if svc._stream_pause_reason is not None:
                    self._park_for_pause()
                    continue
                # pipeline empty: admit and dispatch without overlap.
                # The wave budget is checked BEFORE the admission tick —
                # _admit() pulls the feed (side effects in the store), and
                # a capped session must not consume a tick it will never
                # schedule (pause/resume callers would lose one tick of
                # arrivals).
                if not self._waves_left():
                    break
                # profiler record opens at the wave's first host touch;
                # abandoned records (empty admission, gated round) are
                # simply dropped — nothing aggregates before note()
                rec = svc.profiler.open()
                ta = time.perf_counter()
                # ambient record: the feed tick's store creates and the
                # queue carve-out stamp into THIS wave while it admits
                svc.profiler.current = rec
                try:
                    pending = self._admit(frozenset())
                    gate = volumes = nodes = None
                    if pending:
                        nodes = svc.cluster_store.list("nodes", copy_objects=False)
                        gate, volumes = self._gate(pending, nodes)
                finally:
                    svc.profiler.current = None
                if not pending:
                    if not self._admitting():
                        break
                    time.sleep(self.idle_sleep_s)
                    continue
                if gate is not None:
                    self._drain_round(gate)
                    continue
                # exclusive of the sub-stages carved out above — the
                # record's stage vector stays a partition of its wall
                svc.profiler.note_excl(rec, "admit", time.perf_counter() - ta)
                fw = svc.framework
                try:
                    flight = self._dispatch(
                        pending, nodes, fw.sched_counter,
                        fw.next_start_node_index, bank, volumes,
                        prof_rec=rec,
                    )
                except Exception as e:  # device crash: nothing committed
                    # the same pods re-drain through the sequential path
                    # (fuzz/chaos.py injects exactly this; a real crash
                    # degrades the same way — never a partial wave)
                    self._drain_round(f"kernel error: {type(e).__name__}")
                continue

            # a wave is in flight: learn its decisions (tiny fetch)
            pb = flight["pb"]
            t0 = time.perf_counter()
            try:
                pb.decisions()
            except Exception as e:
                # the in-flight wave died before ANY commit: abandon its
                # device work, hand the same pods to the exact sequential
                # round, and stream on at the next wave
                flight = None
                self._drain_round(f"kernel error: {type(e).__name__}")
                continue
            # lock-free: single-writer scalar bumps on the session thread
            # (GIL-atomic += on fixed keys; the lock is for dict publishes)
            svc.stats["stream_stall_s"] += time.perf_counter() - t0
            n_fail = int((pb.selected[: len(pb.pending)] < 0).sum())
            if n_fail and self._seq_failures():
                # a PostFilter could preempt (victim deletes, restarts):
                # outside the streamable envelope.  Nothing of this wave
                # has been committed — abandon its device work and hand
                # the SAME pods to the exact sequential-path round.
                flight = None
                self._drain_round("kernel failures (preemption path)")
                continue
            if n_fail and self.streaming:
                # trace-committable failures (force mode / no PostFilter)
                # still stream their commit, but the BOUNDARY serializes:
                # a failed pod re-enters the queue at its commit, and the
                # next admission must observe that requeue exactly when
                # the serial path would — overlapping it would retry the
                # pod one wave late.  Commit first, admit after.
                self._count_drain("kernel failures")
                if self._fetch_result(flight):
                    self._commit(flight, overlapped=False)
                # on a failed fetch the pods stay pending and re-drain
                # at the next admission
                flight = None
                self._maybe_gc()
                continue

            next_flight: "dict | None" = None
            if svc._stream_pause_reason is not None:
                # an exclusive store operation is waiting: skip the
                # overlap prefetch, commit wave k below, and park at the
                # loop top (the drain is counted there)
                pass
            elif (
                self.streaming
                and self._waves_left(in_flight=1)
                and svc.queue.has_unschedulable()
            ):
                # a pod parked in unschedulableQ could be reactivated by
                # wave k's commit events (binds fire move_all) — the
                # serial cadence admits it into wave k+1, so an overlap
                # admission taken BEFORE the commit would miss it and
                # shift wave composition.  Serialize this boundary:
                # commit first, admit on the next pipeline-empty pass
                # (no feed tick is consumed here).
                self._count_drain("unschedulable requeue")
            elif self.streaming and self._waves_left(in_flight=1):
                rec2 = svc.profiler.open()
                ta2 = time.perf_counter()
                svc.profiler.current = rec2
                try:
                    pending2 = self._admit(flight["keys"])
                    gate = volumes = nodes = None
                    if pending2:
                        nodes = svc.cluster_store.list("nodes", copy_objects=False)
                        gate, volumes = self._gate(pending2, nodes)
                finally:
                    svc.profiler.current = None
                if pending2:
                    if gate is None and self._node_fp(nodes) != flight["node_fp"]:
                        # the cluster changed under the in-flight wave:
                        # drain the pipeline (commit first, re-encode on
                        # the settled store) — counted here because the
                        # re-admission will see a CONSISTENT node set and
                        # stream normally
                        gate = "node/config change"
                        self._count_drain(gate)
                    if gate is None:
                        # overlap: wave k+1's encode + upload + dispatch
                        # runs against wave k's synthesized placements,
                        # into the other placer bank, with the counters
                        # the serial path would reach after wave k
                        sel = pb.selected
                        binds = {}
                        for j, p in enumerate(pb.pending):
                            s = int(sel[j])
                            if s >= 0:
                                binds[_pod_key(p)] = pb.node_names[s]
                        fw = flight["fw"]
                        svc.profiler.note_excl(
                            rec2, "admit", time.perf_counter() - ta2
                        )
                        t0 = time.perf_counter()
                        bank ^= 1
                        try:
                            next_flight = self._dispatch(
                                pending2, nodes,
                                fw.sched_counter + len(pb.pending),
                                pb.final_start, bank, volumes, binds=binds,
                                prof_rec=rec2,
                            )
                        except Exception as e:
                            # overlap dispatch crashed: wave k commits
                            # normally below, and the gated pods re-drain
                            # at the next pipeline-empty pass (their feed
                            # tick already fired — hold it) on the serial
                            # cadence the commit establishes
                            next_flight = None
                            self._count_drain(f"kernel error: {type(e).__name__}")
                            self._feed_hold = True
                        svc.stats["stream_overlap_s"] += time.perf_counter() - t0
                    else:
                        # gated waves are NOT admitted into the overlap;
                        # the next pipeline-empty iteration re-drains the
                        # SAME pods (feed tick held) and routes them —
                        # through _drain_round for sequential-path gates,
                        # or a fresh streamed dispatch after a node change
                        self._feed_hold = True

            # commit wave k — overlapping wave k+1's in-flight kernel
            # when one was dispatched (serial mode never prefetches, so
            # the same commit machinery runs un-overlapped)
            if self._fetch_result(flight):
                self._commit(flight, overlapped=next_flight is not None)
            else:
                # wave k's blob fetch died before ANY host commit.  Wave
                # k+1 (if prefetched) was encoded against placements that
                # now never landed — abandon it too; both waves' pods are
                # still pending and re-drain in one admission (creation
                # order preserved, so bytes match the serial cadence),
                # without consuming the feed tick k+1 already pulled.
                if next_flight is not None:
                    self._feed_hold = True
                next_flight = None
            flight = next_flight
            self._maybe_gc()
