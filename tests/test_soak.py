"""Concurrency soak: the full simulator under concurrent API traffic.

The design is thread-heavy — background scheduler loop, controller
manager on the synchronous event bus, scenario-operator worker, HTTP
threads mutating the store — and the review history shows races live
here.  This soak drives them all at once for a bounded wall time and
asserts liveness (no deadlock: operations keep completing) and
invariants (no duplicate bindings, scheduler still functional, store
consistent) at the end.
"""

from __future__ import annotations

import json
import random
import threading
import urllib.request

from kube_scheduler_simulator_tpu.server import DIContainer, SimulatorServer


def _req(port, method, path, body=None, timeout=10):
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        data = resp.read()
        return json.loads(data) if data else None


def test_concurrent_api_traffic_soak():
    di = DIContainer(use_batch="auto")
    svc = di.scheduler_service()
    svc.batch_min_work = 64
    srv = SimulatorServer(di, port=0)
    port = srv.start(background=True)
    svc.start_background(poll_interval=0.05)

    try:
        for i in range(12):
            _req(port, "POST", "/api/v1/resources/nodes", {
                "metadata": {"name": f"node-{i}", "labels": {"kubernetes.io/hostname": f"node-{i}"}},
                "status": {"allocatable": {"cpu": "16", "memory": "32Gi", "pods": "110"}},
            })

        stop = threading.Event()
        errors: list[str] = []
        op_counts = {"create": 0, "delete": 0, "deploy": 0, "read": 0}

        def guard(fn):
            def run():
                rng = random.Random(threading.get_ident())
                while not stop.is_set():
                    try:
                        fn(rng)
                    except urllib.error.HTTPError as e:
                        if e.code not in (404, 409):  # expected racy outcomes
                            errors.append(f"{fn.__name__}: HTTP {e.code} {e.read()[:200]}")
                            return
                    except Exception as e:  # liveness failure or server bug
                        errors.append(f"{fn.__name__}: {type(e).__name__}: {e}")
                        return
            return run

        seq = {"n": 0, "lock": threading.Lock()}

        def next_id():
            with seq["lock"]:
                seq["n"] += 1
                return seq["n"]

        @guard
        def pod_creator(rng):
            _req(port, "POST", "/api/v1/resources/pods", {
                "metadata": {"name": f"soak-pod-{next_id()}", "namespace": "default",
                             "labels": {"app": f"a{rng.randrange(3)}"}},
                "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "50m"}}}]},
            })
            op_counts["create"] += 1

        @guard
        def pod_deleter(rng):
            pods = _req(port, "GET", "/api/v1/resources/pods")["items"]
            if pods:
                victim = rng.choice(pods)["metadata"]["name"]
                _req(port, "DELETE", f"/api/v1/resources/pods/{victim}?namespace=default")
                op_counts["delete"] += 1

        @guard
        def deployer(rng):
            name = f"soak-dep-{next_id()}"
            _req(port, "POST", "/api/v1/resources/deployments", {
                "metadata": {"name": name, "namespace": "default"},
                "spec": {"replicas": rng.randrange(1, 4),
                         "selector": {"matchLabels": {"dep": name}},
                         "template": {"metadata": {"labels": {"dep": name}},
                                      "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "25m"}}}]}}},
            })
            op_counts["deploy"] += 1

        @guard
        def reader(rng):
            _req(port, "GET", "/api/v1/export")
            _req(port, "GET", "/api/v1/schedulerconfiguration")
            op_counts["read"] += 1

        @guard
        def simulator_churner(rng):
            """KEP-159 lifecycle under storm: create a Simulator object,
            wait for Available, drive ONE scenario into the isolated
            instance, delete the object — all while the host store is
            being hammered by the other workers."""
            import time as _t

            name = f"soak-sim-{next_id()}"
            _req(port, "POST", "/api/v1/resources/simulators",
                 {"metadata": {"name": name, "namespace": "default"}, "spec": {}})
            inst_port = None
            deadline = _t.monotonic() + 20
            while _t.monotonic() < deadline and not stop.is_set():
                obj = _req(port, f"GET", f"/api/v1/resources/simulators/{name}?namespace=default")
                st = obj.get("status") or {}
                if st.get("phase") == "Available":
                    inst_port = st["simulatorServerPort"]
                    break
                _t.sleep(0.05)
            if inst_port:
                doc = _req(inst_port, "POST", "/api/v1/scenarios", {"spec": {"operations": [
                    {"id": "n", "step": {"major": 1},
                     "createOperation": {"typeMeta": {"kind": "Node"},
                                         "object": {"metadata": {"name": f"{name}-node"}}}},
                    {"id": "d", "step": {"major": 2}, "doneOperation": {}},
                ]}})
                assert doc["status"]["phase"] == "Succeeded", doc["status"]
            _req(port, "DELETE", f"/api/v1/resources/simulators/{name}?namespace=default")
            op_counts["simulator"] += 1
            _t.sleep(0.2)

        op_counts["simulator"] = 0
        threads = [threading.Thread(target=t, daemon=True)
                   for t in (pod_creator, pod_creator, pod_deleter, deployer, reader, simulator_churner)]
        import time

        try:
            for t in threads:
                t.start()
            time.sleep(8.0)
        finally:
            stop.set()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive(), "worker thread hung (deadlock?)"
        assert not errors, errors
        # every op family actually exercised
        assert all(c > 0 for c in op_counts.values()), op_counts

        # liveness after the storm: the scheduler still schedules a new pod
        _req(port, "POST", "/api/v1/resources/pods", {
            "metadata": {"name": "post-soak-pod", "namespace": "default"},
            "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "50m"}}}]},
        })
        deadline = time.monotonic() + 30
        bound = None
        while time.monotonic() < deadline:
            pod = _req(port, "GET", "/api/v1/resources/pods/post-soak-pod?namespace=default")
            bound = (pod.get("spec") or {}).get("nodeName")
            if bound:
                break
            time.sleep(0.1)
        assert bound, "scheduler wedged after soak"

        # invariants: bound pods reference existing nodes; no phantom objects
        nodes = {n["metadata"]["name"] for n in _req(port, "GET", "/api/v1/resources/nodes")["items"]}
        for p in _req(port, "GET", "/api/v1/resources/pods")["items"]:
            nn = (p.get("spec") or {}).get("nodeName")
            assert nn is None or nn in nodes, f"{p['metadata']['name']} bound to missing node {nn}"
        # simulator instances match surviving Simulator objects — every
        # deleted object's instance was torn down despite the storm
        di.simulator_operator().wait_idle(timeout=30)
        live_objs = {
            ("default", s["metadata"]["name"])
            for s in _req(port, "GET", "/api/v1/resources/simulators")["items"]
        }
        assert set(di.simulator_operator().instances) <= live_objs

    finally:
        # always tear down the background machinery — leaked daemon
        # threads would keep mutating the store under later tests
        srv.shutdown()


def test_background_queue_absorbs_unschedulable_churn():
    """Background mode with the scheduling queue: a permanently
    unschedulable pod must be attempted a BOUNDED number of times while
    schedulable churn flows around it (the round-2 throughput cliff was
    this pod being re-filtered on every event)."""
    import time

    from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
    from kube_scheduler_simulator_tpu.state.store import ClusterStore

    store = ClusterStore()
    for i in range(8):
        store.create("nodes", {
            "metadata": {"name": f"n{i}", "labels": {"kubernetes.io/hostname": f"n{i}"}},
            "status": {"allocatable": {"cpu": "4000m", "memory": "8Gi", "pods": "50"}},
        })
    svc = SchedulerService(store, tie_break="first")
    svc.start_scheduler(None)
    svc.start_background(poll_interval=0.02)
    try:
        store.create("pods", {"metadata": {"name": "impossible"},
                              "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "64"}}}]}})
        # churn: a stream of schedulable pods, each create/bind emitting
        # events that would have re-filtered "impossible" pre-queue
        for i in range(40):
            store.create("pods", {"metadata": {"name": f"churn-{i}"},
                                  "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "50m"}}}]}})
            time.sleep(0.005)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            pods = store.list("pods", copy_objects=False)
            if sum(1 for p in pods if (p.get("spec") or {}).get("nodeName")) == 40:
                break
            time.sleep(0.05)
        bound = sum(1 for p in store.list("pods", copy_objects=False) if (p.get("spec") or {}).get("nodeName"))
        assert bound == 40, f"only {bound}/40 churn pods bound"
        assert not store.get("pods", "impossible")["spec"].get("nodeName")
        # the impossible pod's attempts are bounded: with 1s initial
        # backoff and ~1s of churn, it can be tried only a handful of
        # times (pre-queue it was re-filtered per event: hundreds)
        m = svc.metrics()
        total_attempts = m["sequential_pods"] + m["batch_pods"]
        assert total_attempts <= 40 + 8, f"churn refilter storm: {total_attempts} attempts"
        # still tracked by the queue in SOME gated state (which one
        # depends on whether the final bind's move fired before or after
        # its last attempt) — the bounded attempt count above is the
        # actual anti-storm assertion
        assert m["queue_unschedulable"] + m["queue_backoff"] + m["queue_active"] >= 1
    finally:
        svc.stop_background()
