"""Gang scheduling engine (gang/): all-or-nothing PodGroup placement.

Covers the PodGroup kind + admission, the Coscheduling oracle plugin
(park/release/cascade/timeout on the Permit machinery), the batched gang
replay's byte parity against the oracle across randomized job churn, the
gang kernels, the scenario family with the deterministic timeline clock,
and the gang observability counters.
"""

import json

import pytest

from kube_scheduler_simulator_tpu.gang import (
    POD_GROUP_LABEL,
    gang_scheduler_config,
    group_gate,
    validate_pod_group,
)
from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
from kube_scheduler_simulator_tpu.state import ClusterStore
from kube_scheduler_simulator_tpu.utils import SimClock


def mk_node(name, cpu="8", zone="zone-a"):
    return {
        "metadata": {
            "name": name,
            "labels": {"kubernetes.io/hostname": name, "topology.kubernetes.io/zone": zone},
        },
        "status": {"allocatable": {"cpu": cpu, "memory": "64Gi", "pods": "110"}},
    }


def mk_member(name, group, cpu="1", **spec_extra):
    labels = {POD_GROUP_LABEL: group} if group else {}
    return {
        "metadata": {"name": name, "labels": labels},
        "spec": {
            "containers": [
                {"name": "c", "resources": {"requests": {"cpu": cpu, "memory": "1Gi"}}}
            ],
            **spec_extra,
        },
    }


def mk_group(name, min_member, timeout=120, **spec_extra):
    return {
        "metadata": {"name": name},
        "spec": {"minMember": min_member, "scheduleTimeoutSeconds": timeout, **spec_extra},
    }


def new_store():
    s = ClusterStore(clock=SimClock(0.0))
    s.create("namespaces", {"metadata": {"name": "default"}})
    return s


def gang_service(store, use_batch="off", clock=None, **kw):
    svc = SchedulerService(
        store, tie_break="first", use_batch=use_batch, batch_min_work=0, clock=clock, **kw
    )
    svc.start_scheduler(gang_scheduler_config())
    return svc


def pod_state(store):
    """Comparable per-pod state: binding + annotations + conditions
    (resourceVersions excluded — the two paths batch writes differently)."""
    out = {}
    for p in store.list("pods"):
        out[f"{p['metadata'].get('namespace', 'default')}/{p['metadata']['name']}"] = (
            (p.get("spec") or {}).get("nodeName"),
            p["metadata"].get("annotations") or {},
            (p.get("status") or {}).get("conditions"),
            (p.get("status") or {}).get("nominatedNodeName"),
        )
    return out


def assert_no_partial_groups(store):
    """The all-or-nothing acceptance bar: no group is ever PARTIALLY
    bound in committed state (0 bound, or >= minMember bound)."""
    from kube_scheduler_simulator_tpu.gang import partially_bound_groups

    assert partially_bound_groups(store) == []


class TestPodGroupAdmission:
    def test_validate_rejects_bad_shapes(self):
        with pytest.raises(ValueError):
            validate_pod_group({"metadata": {"name": "g"}, "spec": {}})
        with pytest.raises(ValueError):
            validate_pod_group({"metadata": {"name": "g"}, "spec": {"minMember": 0}})
        with pytest.raises(ValueError):
            validate_pod_group(
                {"metadata": {"name": "g"}, "spec": {"minMember": 2, "scheduleTimeoutSeconds": -1}}
            )
        with pytest.raises(ValueError):
            validate_pod_group(
                {"metadata": {"name": "g"}, "spec": {"minMember": 2, "minResources": {"cpu": "4x"}}}
            )
        validate_pod_group(
            {
                "metadata": {"name": "g"},
                "spec": {
                    "minMember": 2,
                    "minResources": {"cpu": "4", "memory": "8Gi"},
                    "topologyPackKey": "topology.kubernetes.io/zone",
                },
            }
        )

    def test_group_gate_quorum_and_min_resources(self):
        store = new_store()
        store.create("nodes", mk_node("node-0", cpu="4"))
        store.create("podgroups", mk_group("g", 2))
        assert "quorum not met" in group_gate(store, "default", "g")
        store.create("pods", mk_member("m0", "g"))
        store.create("pods", mk_member("m1", "g"))
        assert group_gate(store, "default", "g") is None
        assert "not found" in group_gate(store, "default", "nope")
        store.create(
            "podgroups",
            mk_group("big", 2, minResources={"cpu": "64"}),
        )
        store.create("pods", mk_member("b0", "big"))
        store.create("pods", mk_member("b1", "big"))
        assert "minResources" in group_gate(store, "default", "big")

    def test_podgroups_api_routes(self):
        from kube_scheduler_simulator_tpu.server.di import DIContainer
        from kube_scheduler_simulator_tpu.server.server import SimulatorServer
        import urllib.request

        di = DIContainer()
        server = SimulatorServer(di, port=0)
        port = server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            body = json.dumps(
                {"metadata": {"name": "train"}, "spec": {"minMember": 2}}
            ).encode()
            req = urllib.request.Request(
                f"{base}/api/v1/podgroups", data=body, method="POST",
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req) as r:
                assert r.status == 201
            # invalid group -> 400 from admission
            bad = json.dumps({"metadata": {"name": "x"}, "spec": {}}).encode()
            req = urllib.request.Request(
                f"{base}/api/v1/podgroups", data=bad, method="POST",
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 400
            with urllib.request.urlopen(f"{base}/api/v1/podgroups") as r:
                items = json.loads(r.read())["items"]
            assert [g["metadata"]["name"] for g in items] == ["train"]
            assert items[0]["status"]["phase"] == "Pending"
            assert items[0]["status"]["minMember"] == 2
            with urllib.request.urlopen(f"{base}/api/v1/podgroups/train") as r:
                one = json.loads(r.read())
            assert one["status"]["members"] == 0
            req = urllib.request.Request(f"{base}/api/v1/podgroups/train", method="DELETE")
            with urllib.request.urlopen(req) as r:
                assert r.status == 200
            assert di.cluster_store.list("podgroups") == []
        finally:
            server.shutdown()


class TestCoschedulingOracle:
    def test_park_then_release_binds_whole_gang(self):
        store = new_store()
        for i in range(4):
            store.create("nodes", mk_node(f"node-{i}"))
        store.create("podgroups", mk_group("g", 3, timeout=60))
        for i in range(3):
            store.create("pods", mk_member(f"m{i}", "g"))
        svc = gang_service(store)
        res = svc.schedule_pending(max_rounds=1)
        assert res["default/m0"].waiting_on and res["default/m1"].waiting_on
        assert res["default/m2"].success
        assert svc.framework.waiting_pods == {}
        for i in range(3):
            pod = store.get("pods", f"m{i}")
            assert pod["spec"].get("nodeName")
            permit = json.loads(pod["metadata"]["annotations"]["scheduler-simulator/permit-result"])
            assert permit["Coscheduling"] == ("success" if i == 2 else "wait")
        assert_no_partial_groups(store)

    def test_quorum_gate_rejects_before_node_work(self):
        store = new_store()
        store.create("nodes", mk_node("node-0"))
        store.create("podgroups", mk_group("g", 3))
        store.create("pods", mk_member("m0", "g"))
        svc = gang_service(store)
        res = svc.schedule_pending(max_rounds=1)["default/m0"]
        assert not res.success
        assert "quorum not met" in res.status.message()

    def test_member_failure_rejects_parked_siblings(self):
        store = new_store()
        for i in range(3):
            store.create("nodes", mk_node(f"node-{i}", cpu="4"))
        store.create("podgroups", mk_group("g", 3))
        store.create("pods", mk_member("m0", "g"))
        store.create("pods", mk_member("m1", "g"))
        store.create("pods", mk_member("m2", "g", cpu="64"))  # fits nowhere
        svc = gang_service(store)
        res = svc.schedule_pending(max_rounds=1)
        assert not any(r.success for r in res.values())
        assert svc.framework.waiting_pods == {}
        cond = store.get("pods", "m0")["status"]["conditions"][0]
        assert "gang rejected" in cond["message"]
        assert_no_partial_groups(store)

    def test_timeout_expiry_tears_down_gang(self):
        t = [0.0]
        store = new_store()
        for i in range(3):
            store.create("nodes", mk_node(f"node-{i}"))
        store.create("podgroups", mk_group("g", 3, timeout=60))
        store.create("pods", mk_member("m0", "g"))
        store.create("pods", mk_member("m1", "g"))
        # the third member belongs to an EXTERNAL scheduler: it counts for
        # quorum (it exists) but is never scheduled here, so the first two
        # park until the gang timeout expires
        store.create("pods", mk_member("m2", "g", schedulerName="external-sched"))
        svc = gang_service(store, clock=lambda: t[0])
        svc.schedule_pending(max_rounds=1)
        assert len(svc.framework.waiting_pods) == 2
        t[0] = 59.0
        assert svc.process_waiting_pods() == {}
        t[0] = 60.0
        expired = svc.process_waiting_pods()
        # ONE deadline fired; its unreserve cascade rejected the sibling
        assert len(expired) == 1
        assert svc.framework.waiting_pods == {}
        assert svc.stats["permit_wait_expired"] == 1
        for name in ("m0", "m1"):
            cond = store.get("pods", name)["status"]["conditions"][0]
            assert "timeout" in cond["message"] or "gang rejected" in cond["message"]


class TestGangBatchParity:
    """The acceptance bar: batch gang decisions and the per-pod
    annotation trail byte-identical to the oracle coscheduling plugin's
    trace across a randomized job-churn sweep."""

    @staticmethod
    def _churn(store, svc, seed):
        """Three churn waves: jobs arrive, schedule, some complete."""
        import random

        rng = random.Random(seed)
        jid = 0
        live = []
        for wave in range(3):
            for _ in range(rng.randint(1, 3)):
                members = rng.randint(2, 5)
                g = f"job-{seed}-{jid}"
                jid += 1
                store.create("podgroups", mk_group(g, members, timeout=300))
                for m in range(members):
                    store.create(
                        "pods", mk_member(f"{g}-m{m}", g, cpu=str(rng.choice([1, 2])))
                    )
                live.append((g, members))
            for _ in range(rng.randint(0, 2)):
                store.create("pods", mk_member(f"s-{seed}-{wave}-{rng.randint(0, 9)}-{jid}", None))
                jid += 1
            svc.schedule_pending(max_rounds=3)
            assert_no_partial_groups(store)
            # completion churn: the oldest live job finishes
            if wave and live:
                g, members = live.pop(0)
                for m in range(members):
                    try:
                        store.delete("pods", f"{g}-m{m}")
                    except KeyError:
                        pass
                store.delete("podgroups", g)
                svc.schedule_pending(max_rounds=2)
                assert_no_partial_groups(store)

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_randomized_churn_parity(self, seed):
        def build():
            store = new_store()
            for i in range(6):
                store.create("nodes", mk_node(f"node-{i}", cpu="8", zone=f"zone-{i % 3}"))
            return store

        s_oracle = build()
        svc_o = gang_service(s_oracle, use_batch="off")
        self._churn(s_oracle, svc_o, seed)

        s_batch = build()
        svc_b = gang_service(s_batch, use_batch="auto")
        self._churn(s_batch, svc_b, seed)

        assert pod_state(s_oracle) == pod_state(s_batch)
        # the gang machinery actually engaged on the batch path, with the
        # feasibility verdict batched per window — and never disagreed
        assert svc_b.stats["gang_rounds"] > 0
        assert svc_b.stats["gang_released_groups"] > 0
        assert svc_b.stats["gang_kernel_dispatches"] > 0
        assert svc_b.stats["gang_verdict_mismatch"] == 0

    def test_failed_member_parity_and_force_mode(self):
        def build():
            store = new_store()
            for i in range(3):
                store.create("nodes", mk_node(f"node-{i}", cpu="4"))
            store.create("podgroups", mk_group("bad", 3))
            store.create("pods", mk_member("bad-0", "bad"))
            store.create("pods", mk_member("bad-1", "bad"))
            store.create("pods", mk_member("bad-2", "bad", cpu="64"))
            store.create("podgroups", mk_group("ok", 2))
            store.create("pods", mk_member("ok-0", "ok"))
            store.create("pods", mk_member("ok-1", "ok"))
            return store

        s1 = build()
        gang_service(s1, use_batch="off").schedule_pending()
        s2 = build()
        svc2 = gang_service(s2, use_batch="auto")
        svc2.schedule_pending()
        assert pod_state(s1) == pod_state(s2)
        assert_no_partial_groups(s2)
        assert svc2.stats["gang_released_groups"] >= 1

    def test_cascade_rejection_never_completes_stale_quorum(self):
        """A kernel-failed member's sequential cascade rejects parked
        siblings MID-segment; later members must see the live waiting
        map, not stale park bookkeeping — else a later member would
        'complete' the quorum and commit a PARTIAL gang (fewer than
        minMember bound)."""
        def build():
            store = new_store()
            for i in range(4):
                store.create("nodes", mk_node(f"node-{i}", cpu="4"))
            store.create("podgroups", mk_group("g", 3))
            # queue order = name order: a-0 parks, a-1 fails (cascade
            # rejects a-0), a-2 and a-3 must re-park at 1/3 and 2/3 —
            # never release
            store.create("pods", mk_member("a-0", "g"))
            store.create("pods", mk_member("a-1", "g", cpu="64"))
            store.create("pods", mk_member("a-2", "g"))
            store.create("pods", mk_member("a-3", "g"))
            return store

        s1 = build()
        svc1 = gang_service(s1, use_batch="off")
        svc1.schedule_pending(max_rounds=1)
        s2 = build()
        svc2 = gang_service(s2, use_batch="auto")
        svc2.schedule_pending(max_rounds=1)
        assert pod_state(s1) == pod_state(s2)
        assert_no_partial_groups(s2)
        assert svc2.stats["gang_released_groups"] == 0
        # a-2 / a-3 hold their reservations waiting for a third member
        assert len(svc2.framework.waiting_pods) == len(svc1.framework.waiting_pods) == 2

    def test_gang_knob_disables_batch_path(self, monkeypatch):
        monkeypatch.setenv("KSS_GANG_BATCH", "0")
        store = new_store()
        for i in range(3):
            store.create("nodes", mk_node(f"node-{i}"))
        store.create("podgroups", mk_group("g", 2))
        store.create("pods", mk_member("m0", "g"))
        store.create("pods", mk_member("m1", "g"))
        svc = gang_service(store, use_batch="auto")
        svc.schedule_pending()
        # the round ran on the sequential oracle, counted
        assert svc.stats["gang_rounds"] == 0
        assert any("disabled" in r for r in svc.stats["gang_fallbacks"])
        assert store.get("pods", "m0")["spec"].get("nodeName")
        assert_no_partial_groups(store)

    def test_waiting_pod_capacity_respected_by_batch_waves(self):
        """Satellite pin: the batch encoder must count Permit-parked
        waiting pods on their reserved node (the nodeName-bearing
        fingerprint keeps the DELTA path honest too)."""
        store = new_store()
        store.create("nodes", mk_node("node-0", cpu="4"))
        store.create("nodes", mk_node("node-1", cpu="4"))
        store.create("podgroups", mk_group("g", 3, timeout=600))
        store.create("pods", mk_member("m0", "g", cpu="3"))
        store.create("pods", mk_member("m1", "g", cpu="3"))
        store.create("pods", mk_member("m2", "g", schedulerName="external-sched"))
        svc = gang_service(store, use_batch="auto")
        svc.schedule_pending(max_rounds=1)
        assert len(svc.framework.waiting_pods) == 2  # 3 cpu reserved on each node
        # a second BATCH round: the fillers need 2 cpu — more than any
        # node's remaining 1 cpu — so they must all fail, parked capacity
        # honored on the kernel path (rounds 2+ take the delta encoder)
        for r in range(2):
            store.create("pods", mk_member(f"intruder-{r}", None, cpu="2"))
            res = svc.schedule_pending(max_rounds=1)
            assert not res[f"default/intruder-{r}"].success
            assert store.get("pods", f"intruder-{r}")["spec"].get("nodeName") is None
        # the reservation itself still completes when quorum arrives
        assert len(svc.framework.waiting_pods) == 2


class TestGangKernels:
    def test_feasibility_scan_packs_domains(self):
        from kube_scheduler_simulator_tpu.gang.encode import encode_feasibility
        from kube_scheduler_simulator_tpu.gang.kernel import run_feasibility
        from kube_scheduler_simulator_tpu.models.nodeinfo import build_node_infos

        nodes = [
            mk_node("a0", cpu="4", zone="za"),
            mk_node("a1", cpu="4", zone="za"),
            mk_node("b0", cpu="4", zone="zb"),
        ]
        nis = build_node_infos(nodes, [])
        members = [mk_member(f"m{i}", "g", cpu="2") for i in range(4)]
        pr = encode_feasibility([members], ["topology.kubernetes.io/zone"], nis)
        out = run_feasibility(pr)
        assert bool(out["feasible"][0])
        # 4 members × 2cpu fit into zone za's two 4cpu nodes: one domain
        assert int(out["distinct_domains"][0]) == 1
        assert all(int(x) >= 0 for x in out["assignment"][0])

    def test_feasibility_scan_flags_infeasible_group(self):
        from kube_scheduler_simulator_tpu.gang.encode import encode_feasibility
        from kube_scheduler_simulator_tpu.gang.kernel import run_feasibility
        from kube_scheduler_simulator_tpu.models.nodeinfo import build_node_infos

        nis = build_node_infos([mk_node("n0", cpu="2")], [])
        members = [mk_member(f"m{i}", "g", cpu="2") for i in range(2)]
        pr = encode_feasibility([members], ["topology.kubernetes.io/zone"], nis)
        out = run_feasibility(pr)
        assert not bool(out["feasible"][0])

    def test_group_victim_search_previews_evictions(self):
        from kube_scheduler_simulator_tpu.gang.kernel import group_victim_search
        from kube_scheduler_simulator_tpu.models.nodeinfo import build_node_infos

        victim = mk_member("low-prio", None, cpu="6")
        victim["spec"]["nodeName"] = "n0"
        victim["spec"]["priority"] = 0
        victim["status"] = {"startTime": "2024-01-01T00:00:00Z"}
        nis = build_node_infos([mk_node("n0", cpu="8")], [victim])
        members = [mk_member(f"m{i}", "g", cpu="3") for i in range(2)]
        for m in members:
            m["spec"]["priority"] = 100
        out = group_victim_search(nis, [(members, 100)])
        assert out[0]["node"] == "n0"
        assert out[0]["victims"] == ["low-prio"]

    def test_preview_endpoint_shape(self):
        from kube_scheduler_simulator_tpu.gang.engine import group_preview

        store = new_store()
        store.create("nodes", mk_node("n0", cpu="8"))
        g = mk_group("g", 2)
        store.create("podgroups", g)
        store.create("pods", mk_member("m0", "g"))
        store.create("pods", mk_member("m1", "g"))
        out = group_preview(store, store.get("podgroups", "g"))
        assert out["feasible"] is True
        assert set(out["assignment"]) == {"m0", "m1"}


class TestScenarioReplay:
    def _run(self, use_batch):
        from kube_scheduler_simulator_tpu.gang.scenario import make_training_scenario
        from kube_scheduler_simulator_tpu.scenario.engine import ScenarioClock, ScenarioEngine

        store = ClusterStore(clock=SimClock(0.0))
        svc = SchedulerService(
            store, tie_break="first", use_batch=use_batch, batch_min_work=0,
            clock=ScenarioClock(),
        )
        svc.start_scheduler(gang_scheduler_config())
        engine = ScenarioEngine(store, svc)
        scn = make_training_scenario(jobs=5, min_members=2, max_members=4, nodes=4, seed=7)
        result = engine.run(scn)
        assert result["status"]["phase"] == "Succeeded"
        return store.dump(), result["status"]["scenarioResult"], svc

    def test_training_churn_replay_deterministic_and_batch_parity(self):
        dump_a, res_a, _ = self._run("off")
        dump_b, res_b, _ = self._run("off")
        assert dump_a == dump_b and res_a == res_b  # byte-deterministic
        dump_c, res_c, svc_c = self._run("auto")

        def strip(d):
            # events + resourceVersions differ by write batching; the
            # scheduling outcome (bindings, annotations, conditions) and
            # the timeline must not
            out = {}
            for kind, objs in d.items():
                if kind == "events":
                    continue
                rows = []
                for o in objs:
                    o = json.loads(json.dumps(o))
                    o.get("metadata", {}).pop("resourceVersion", None)
                    rows.append(o)
                out[kind] = rows
            return out

        assert strip(dump_a) == strip(dump_c)
        assert svc_c.stats["gang_verdict_mismatch"] == 0

    def test_scenario_clock_expires_gang_timeouts(self):
        from kube_scheduler_simulator_tpu.scenario.engine import ScenarioClock, ScenarioEngine

        store = ClusterStore(clock=SimClock(0.0))
        clock = ScenarioClock()
        svc = SchedulerService(store, tie_break="first", use_batch="off", clock=clock)
        svc.start_scheduler(gang_scheduler_config())
        engine = ScenarioEngine(store, svc)
        ops = [
            {"id": "1", "step": {"major": 1}, "createOperation": {
                "typeMeta": {"kind": "Node"}, "object": mk_node("n0")}},
            {"id": "2", "step": {"major": 1}, "createOperation": {
                "typeMeta": {"kind": "PodGroup"},
                "object": mk_group("g", 3, timeout=2)}},
            {"id": "3", "step": {"major": 1}, "createOperation": {
                "typeMeta": {"kind": "Pod"}, "object": mk_member("m0", "g")}},
            {"id": "4", "step": {"major": 1}, "createOperation": {
                "typeMeta": {"kind": "Pod"}, "object": mk_member("m1", "g")}},
            {"id": "5", "step": {"major": 1}, "createOperation": {
                "typeMeta": {"kind": "Pod"},
                "object": mk_member("m2", "g", schedulerName="external-sched")}},
            # majors 2..4 advance the timeline clock past the 2 s timeout
            {"id": "6", "step": {"major": 4}, "doneOperation": {}},
        ]
        result = engine.run({"spec": {"operations": ops, "stepSeconds": 1.0}})
        assert result["status"]["phase"] == "Succeeded"
        assert svc.framework.waiting_pods == {}
        assert svc.stats["permit_wait_expired"] == 1
        cond = store.get("pods", "m0")["status"]["conditions"][0]
        assert "timeout" in cond["message"] or "gang rejected" in cond["message"]


class TestGangObservability:
    def test_service_metrics_and_prometheus_render(self):
        store = new_store()
        for i in range(3):
            store.create("nodes", mk_node(f"node-{i}"))
        store.create("podgroups", mk_group("g", 2))
        store.create("pods", mk_member("m0", "g"))
        store.create("pods", mk_member("m1", "g"))
        svc = gang_service(store, use_batch="auto")
        svc.schedule_pending()
        m = svc.metrics()
        assert m["gang_released_groups"] == 1
        assert m["gang_kernel_dispatches"] >= 1
        assert m["waiting_pods"] == 0
        assert m["permit_wait_expired"] == 0

        class FakeDI:
            cluster_store = store

            def scheduler_service(self):
                return svc

        from kube_scheduler_simulator_tpu.server.metrics import render_metrics

        text = render_metrics(FakeDI())
        assert "simulator_gang_released_groups_total 1" in text
        assert "simulator_waiting_pods 0" in text
        assert "simulator_permit_wait_expired_total 0" in text
        assert "simulator_gang_kernel_dispatches_total" in text
        assert 'simulator_cluster_objects{kind="podgroups"} 1' in text
