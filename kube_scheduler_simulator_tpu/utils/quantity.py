"""Kubernetes resource.Quantity parsing.

The reference relies on ``k8s.io/apimachinery``'s Quantity throughout (pod
resource requests, node allocatable).  We parse the same textual forms into
exact integers so the TPU feature encoder and the host-side parity oracle
agree with the Go scheduler:

- plain / decimal numbers: ``2``, ``0.5``, ``1e3``
- binary-SI suffixes: ``Ki Mi Gi Ti Pi Ei``
- decimal-SI suffixes: ``n u m k M G T P E``

``milli_value`` mirrors Quantity.MilliValue (ceil to the nearest milli unit,
used for CPU); ``value`` mirrors Quantity.Value (ceil to the nearest integer,
used for memory/pods/storage).
"""

from __future__ import annotations

import functools
import math
import re
from fractions import Fraction

_BINARY = {"Ki": 1024, "Mi": 1024**2, "Gi": 1024**3, "Ti": 1024**4, "Pi": 1024**5, "Ei": 1024**6}
_DECIMAL = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 1000),
    "": Fraction(1),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
}

# A quantity is signedNumber followed by ONE suffix form: a binary-SI or
# decimal-SI suffix, OR a decimal exponent (e/E notation) — never both
# ("1e3Ki" is invalid in apimachinery).
_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>[0-9]+(?:\.[0-9]*)?|\.[0-9]+)"
    r"(?:(?:[eE](?P<exp>[+-]?[0-9]+))|(?P<suffix>Ki|Mi|Gi|Ti|Pi|Ei|n|u|m|k|M|G|T|P|E))?$"
)


def parse_quantity(q: "str | int | float") -> Fraction:
    """Parse a Kubernetes quantity into an exact Fraction of base units.

    String parses are cached: a cluster snapshot repeats a handful of
    distinct quantity strings across thousands of pods, and the Fraction
    arithmetic dominates encoding time otherwise (Fractions are immutable,
    so sharing the returned object is safe)."""
    if isinstance(q, bool):
        raise ValueError(f"invalid quantity: {q!r}")
    if isinstance(q, int):
        return Fraction(q)
    if isinstance(q, float):
        return Fraction(str(q))
    return _parse_quantity_str(q)


@functools.lru_cache(maxsize=4096)
def _parse_quantity_str(q: str) -> Fraction:
    s = q.strip()
    m = _QUANTITY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity: {q!r}")
    num = Fraction(m.group("num"))
    if m.group("exp"):
        num *= Fraction(10) ** int(m.group("exp"))
    suffix = m.group("suffix") or ""
    if suffix in _BINARY:
        num *= _BINARY[suffix]
    else:
        num *= _DECIMAL[suffix]
    if m.group("sign") == "-":
        num = -num
    return num


def milli_value(q: "str | int | float") -> int:
    """Quantity.MilliValue: value * 1000, rounded up (away from zero)."""
    if isinstance(q, str):
        return _milli_value_str(q)
    return _ceil(parse_quantity(q) * 1000)


@functools.lru_cache(maxsize=4096)
def _milli_value_str(q: str) -> int:
    return _ceil(_parse_quantity_str(q) * 1000)


def value(q: "str | int | float") -> int:
    """Quantity.Value: rounded up (away from zero) to an integer."""
    if isinstance(q, str):
        return _value_str(q)
    return _ceil(parse_quantity(q))


@functools.lru_cache(maxsize=4096)
def _value_str(q: str) -> int:
    return _ceil(_parse_quantity_str(q))


def _ceil(v: Fraction) -> int:
    if v >= 0:
        return math.ceil(v)
    return -math.ceil(-v)
