"""Webhook-extender tracing proxy + scheduling-cycle integration.

Rebuild of the reference's extender layer (reference
simulator/scheduler/extender/{extender.go,service.go} and
extender/resultstore): the user's KubeSchedulerConfiguration extenders are
proxied so every Filter/Prioritize/Preempt/Bind webhook round-trip is
recorded and written to the pod's annotations
(``scheduler-simulator/extender-*-result``, reference
extender/annotation/annotation.go:3-12).

Wire format is the upstream extenderv1 JSON (lowercase keys: ``pod``,
``nodes``, ``nodenames``, ``failedNodes`` …) so real extender webhooks work
unmodified.  ``override_extenders_cfg_to_simulator`` rewrites the config
the way the reference does (service.go:88-109) so an *external* scheduler
can also be pointed at this simulator's /api/v1/extender/<verb>/<id>
endpoints; the in-process scheduler calls the Service directly (same
topological position, one fewer HTTP hop).
"""

from __future__ import annotations

import json
import threading
import urllib.request
from typing import Any

from kube_scheduler_simulator_tpu.utils.gojson import go_marshal

Obj = dict[str, Any]

MAX_EXTENDER_PRIORITY = 10  # extenderv1.MaxExtenderPriority
MAX_NODE_SCORE = 100
DEFAULT_TIMEOUT_S = 5.0  # reference extender.go:22-24

EXTENDER_FILTER_RESULT = "scheduler-simulator/extender-filter-result"
EXTENDER_PRIORITIZE_RESULT = "scheduler-simulator/extender-prioritize-result"
EXTENDER_PREEMPT_RESULT = "scheduler-simulator/extender-preempt-result"
EXTENDER_BIND_RESULT = "scheduler-simulator/extender-bind-result"


class ExtenderError(Exception):
    """A non-ignorable extender failed (transport or body error); upstream
    fails the scheduling attempt in this case."""


class HTTPExtender:
    """One configured extender webhook (reference extender.go:55-199)."""

    def __init__(self, config: Obj):
        self.config = dict(config)
        self.url_prefix: str = config.get("urlPrefix") or ""
        self.filter_verb: str = config.get("filterVerb") or ""
        self.prioritize_verb: str = config.get("prioritizeVerb") or ""
        self.preempt_verb: str = config.get("preemptVerb") or ""
        self.bind_verb: str = config.get("bindVerb") or ""
        self.weight: int = int(config.get("weight") or 1)
        self.node_cache_capable: bool = bool(config.get("nodeCacheCapable"))
        # upstream: an ignorable extender's failures don't fail scheduling
        self.ignorable: bool = bool(config.get("ignorable"))
        self.managed_resources = {r.get("name") for r in config.get("managedResources") or []}
        timeout = config.get("httpTimeout")
        self.timeout_s = _parse_go_duration(timeout) if timeout else DEFAULT_TIMEOUT_S

    @property
    def name(self) -> str:
        return self.url_prefix

    def is_interested(self, pod: Obj) -> bool:
        """Upstream IsInterested: no managed resources → always."""
        if not self.managed_resources:
            return True
        for c in (pod.get("spec") or {}).get("containers") or []:
            for section in ("requests", "limits"):
                for r in ((c.get("resources") or {}).get(section) or {}):
                    if r in self.managed_resources:
                        return True
        return False

    def is_binder(self) -> bool:
        return bool(self.bind_verb)

    # ------------------------------------------------------------- verbs

    def filter(self, args: Obj) -> Obj:
        if not self.filter_verb:
            raise ValueError("filterVerb is empty")
        return self._send(self.filter_verb, args)

    def prioritize(self, args: Obj) -> list[Obj]:
        """Returns the webhook's response AS IS (raw [0,10] priorities —
        weight scaling happens at score-combination time in the cycle, so
        the recorded annotation and the proxy endpoint expose exactly what
        the extender returned)."""
        if not self.prioritize_verb:
            raise ValueError("prioritizeVerb is empty")
        return self._send(self.prioritize_verb, args) or []

    def preempt(self, args: Obj) -> Obj:
        if not self.preempt_verb:
            raise ValueError("preemptVerb is empty")
        return self._send(self.preempt_verb, args)

    def bind(self, args: Obj) -> Obj:
        if not self.bind_verb:
            raise ValueError("bindVerb is empty")
        return self._send(self.bind_verb, args)

    def _send(self, action: str, args: Any) -> Any:
        url = self.url_prefix.rstrip("/") + "/" + action
        req = urllib.request.Request(
            url,
            data=json.dumps(args).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            if resp.status != 200:
                raise RuntimeError(f"failed {action} with extender at URL {url}, code {resp.status}")
            return json.loads(resp.read().decode() or "null")


class ExtenderResultStore:
    """Per-pod extender results → 4 annotations (reference
    extender/resultstore/resultstore.go)."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._results: dict[str, dict[str, dict[str, Any]]] = {}

    @staticmethod
    def _pod_key(pod: Obj) -> str:
        from kube_scheduler_simulator_tpu.utils.keys import pod_key

        return pod_key(pod)

    def _entry(self, pod: Obj) -> dict[str, dict[str, Any]]:
        k = self._pod_key(pod)
        if k not in self._results:
            self._results[k] = {"filter": {}, "prioritize": {}, "preempt": {}, "bind": {}}
        return self._results[k]

    def add_filter_result(self, args: Obj, result: Obj, host_name: str) -> None:
        with self._mu:
            self._entry(args["pod"])["filter"][host_name] = result

    def add_prioritize_result(self, args: Obj, result: Any, host_name: str) -> None:
        with self._mu:
            self._entry(args["pod"])["prioritize"][host_name] = result

    def add_preempt_result(self, args: Obj, result: Obj, host_name: str) -> None:
        with self._mu:
            self._entry(args["pod"])["preempt"][host_name] = result

    def add_bind_result(self, args: Obj, result: Obj, host_name: str) -> None:
        with self._mu:
            key = f"{args.get('podNamespace', 'default')}/{args.get('podName', '')}"
            if key not in self._results:
                self._results[key] = {"filter": {}, "prioritize": {}, "preempt": {}, "bind": {}}
            self._results[key]["bind"][host_name] = result

    # ResultStore interface for the shared store reflector:

    def get_stored_result(self, pod: Obj) -> dict[str, str]:
        with self._mu:
            e = self._results.get(self._pod_key(pod))
            if e is None:
                return {}
            out = {}
            for cat, anno_key in (
                ("filter", EXTENDER_FILTER_RESULT),
                ("prioritize", EXTENDER_PRIORITIZE_RESULT),
                ("preempt", EXTENDER_PREEMPT_RESULT),
                ("bind", EXTENDER_BIND_RESULT),
            ):
                if e[cat]:
                    out[anno_key] = go_marshal(e[cat])
            return out

    def has_result(self, pod: Obj) -> bool:
        with self._mu:
            return self._pod_key(pod) in self._results

    def delete_data(self, pod: Obj) -> None:
        with self._mu:
            self._results.pop(self._pod_key(pod), None)


EXTENDER_RESULT_STORE_KEY = "ExtenderResultStoreKey"


class ExtenderService:
    """Proxy + recorder for the configured extenders (reference
    extender/service.go:18-85)."""

    def __init__(self, extender_cfgs: "list[Obj] | None", reflector: Any = None):
        self.extenders = [HTTPExtender(c) for c in (extender_cfgs or [])]
        self.store = ExtenderResultStore()
        if reflector is not None:
            reflector.add_result_store(self.store, EXTENDER_RESULT_STORE_KEY)

    def filter(self, id_: int, args: Obj) -> Obj:
        result = self.extenders[id_].filter(args)
        self.store.add_filter_result(args, result, self.extenders[id_].name)
        return result

    def prioritize(self, id_: int, args: Obj) -> list[Obj]:
        result = self.extenders[id_].prioritize(args)
        self.store.add_prioritize_result(args, result, self.extenders[id_].name)
        return result

    def preempt(self, id_: int, args: Obj) -> Obj:
        result = self.extenders[id_].preempt(args)
        self.store.add_preempt_result(args, result, self.extenders[id_].name)
        return result

    def bind(self, id_: int, args: Obj) -> Obj:
        result = self.extenders[id_].bind(args)
        self.store.add_bind_result(args, result, self.extenders[id_].name)
        return result

    # ----------------------------------------------- scheduling-cycle hooks

    def run_filter(self, pod: Obj, feasible_nodes: list[Obj]) -> "tuple[list[Obj], dict[str, str]]":
        """findNodesThatPassExtenders: each extender narrows the feasible
        set; failed nodes carry reasons into the diagnosis.  A transport or
        body error fails the attempt (ExtenderError) unless the extender is
        marked ignorable — upstream findNodesThatPassExtenders semantics."""
        failed: dict[str, str] = {}
        nodes = feasible_nodes
        for i, ext in enumerate(self.extenders):
            if not ext.filter_verb or not nodes:
                continue
            if not ext.is_interested(pod):
                continue
            if ext.node_cache_capable:
                args = {"pod": pod, "nodenames": [n["metadata"]["name"] for n in nodes]}
            else:
                args = {"pod": pod, "nodes": {"items": nodes}}
            try:
                result = self.filter(i, args)
            except Exception as e:
                if ext.ignorable:
                    continue
                raise ExtenderError(f"extender {ext.name} filter: {e}") from e
            if result.get("error"):
                if ext.ignorable:
                    continue
                raise ExtenderError(f"extender {ext.name} filter: {result['error']}")
            by_name = {n["metadata"]["name"]: n for n in nodes}
            if result.get("nodenames") is not None:
                nodes = [by_name[nm] for nm in result["nodenames"] if nm in by_name]
            elif result.get("nodes") is not None:
                items = result["nodes"].get("items") or []
                nodes = [by_name[n["metadata"]["name"]] for n in items if n["metadata"]["name"] in by_name]
            for nm, reason in (result.get("failedNodes") or {}).items():
                failed[nm] = reason
            for nm, reason in (result.get("failedAndUnresolvableNodes") or {}).items():
                failed[nm] = reason
        return nodes, failed

    def run_prioritize(self, pod: Obj, feasible_nodes: list[Obj]) -> dict[str, int]:
        """prioritizeNodes' extender pass: raw [0,10] webhook priorities
        scaled by weight × MaxNodeScore/MaxExtenderPriority at combination
        time (upstream prioritizeNodes).  Errors here are always ignorable
        (upstream logs and skips failed prioritize calls)."""
        totals: dict[str, int] = {}
        for i, ext in enumerate(self.extenders):
            if not ext.prioritize_verb:
                continue
            if not ext.is_interested(pod):
                continue
            if ext.node_cache_capable:
                args = {"pod": pod, "nodenames": [n["metadata"]["name"] for n in feasible_nodes]}
            else:
                args = {"pod": pod, "nodes": {"items": feasible_nodes}}
            try:
                items = self.prioritize(i, args)
            except Exception:
                continue
            scale = ext.weight * (MAX_NODE_SCORE // MAX_EXTENDER_PRIORITY)
            for item in items:
                totals[item["host"]] = totals.get(item["host"], 0) + int(item["score"]) * scale
        return totals

    def run_preempt(
        self, pod: Obj, node_to_victims: dict[str, list[Obj]]
    ) -> dict[str, list[Obj]]:
        """Upstream Evaluator.callExtenders: each preempt-verb extender
        narrows the candidate node→victims map.  A failing extender is
        skipped when ignorable, otherwise the error propagates
        (ExtenderError) and the preemption attempt fails."""
        candidates = node_to_victims

        def _uid(v: Obj) -> str:
            return (
                v["metadata"].get("uid")
                or f"{v['metadata'].get('namespace', 'default')}/{v['metadata']['name']}"
            )

        for i, ext in enumerate(self.extenders):
            if not ext.preempt_verb or not candidates:
                continue
            if not ext.is_interested(pod):
                continue
            if ext.node_cache_capable:
                # upstream ProcessPreemption sends uid-only meta victims to
                # nodeCacheCapable extenders
                args: Obj = {
                    "pod": pod,
                    "nodeNameToMetaVictims": {
                        nm: {"pods": [{"uid": _uid(v)} for v in victims], "numPDBViolations": 0}
                        for nm, victims in candidates.items()
                    },
                }
            else:
                args = {
                    "pod": pod,
                    "nodeNameToVictims": {
                        nm: {"pods": victims, "numPDBViolations": 0}
                        for nm, victims in candidates.items()
                    },
                }
            try:
                result = self.preempt(i, args) or {}
            except Exception as e:
                if ext.ignorable:
                    continue
                raise ExtenderError(f"extender {ext.name} preempt: {e}") from e
            narrowed = result.get("nodeNameToVictims")
            if narrowed is None:
                narrowed = result.get("nodeNameToMetaVictims")
            if narrowed is None:
                continue  # extender expressed no opinion
            # an empty map is an explicit all-veto, not "no opinion"
            by_uid = {_uid(v): v for victims in candidates.values() for v in victims}

            def resolve(entry: Any) -> list[Obj]:
                pods = (entry or {}).get("pods") or []
                out: list[Obj] = []
                for p in pods:
                    if "metadata" in p:  # full victims response
                        out.append(p)
                    else:  # meta victims: {"uid": ...}
                        v = by_uid.get(p.get("uid", ""))
                        if v is not None:
                            out.append(v)
                return out

            # A node whose returned victims are empty/unresolvable is
            # dropped (upstream errors "expected at least one victim pod on
            # node"); victims the extender didn't approve are never used.
            candidates = {
                nm: victims
                for nm, entry in narrowed.items()
                if nm in candidates
                for victims in [resolve(entry)]
                if victims
            }
        return candidates

    def find_binder(self, pod: Obj) -> "tuple[int, HTTPExtender] | None":
        for i, ext in enumerate(self.extenders):
            if ext.is_binder() and ext.is_interested(pod):
                return i, ext
        return None


def override_extenders_cfg_to_simulator(cfg: Obj, simulator_port: int) -> None:
    """Rewrite extender configs to point at the simulator proxy endpoints
    (reference service.go:88-109) — used when an EXTERNAL scheduler should
    round-trip through this simulator's HTTP server."""
    for i, ext in enumerate(cfg.get("extenders") or []):
        ext["enableHTTPS"] = False
        ext.pop("tlsConfig", None)
        ext["urlPrefix"] = f"http://localhost:{simulator_port}/api/v1/extender/"
        for verb in ("filterVerb", "prioritizeVerb", "preemptVerb", "bindVerb"):
            if ext.get(verb):
                ext[verb] = f"{verb.removesuffix('Verb').lower()}/{i}"


def _parse_go_duration(d: Any) -> float:
    """Parse a metav1.Duration-ish value ("5s", "100ms", nanoseconds int)."""
    if isinstance(d, (int, float)):
        return float(d) / 1e9  # Go time.Duration marshals as nanoseconds
    s = str(d)
    units = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0, "us": 1e-6, "µs": 1e-6, "ns": 1e-9}
    for suffix in ("ms", "us", "µs", "ns", "s", "m", "h"):
        if s.endswith(suffix):
            try:
                return float(s[: -len(suffix)]) * units[suffix]
            except ValueError:
                break
    return DEFAULT_TIMEOUT_S
