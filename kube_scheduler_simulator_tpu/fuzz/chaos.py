"""The chaos layer: inject mid-run faults the system must survive.

The scheduling engines promise that a kernel failure is never fatal and
never partial: a crashed dispatch, window fetch, or streamed
decision/result fetch degrades the round (or wave) to the sequential
path, byte-identical to a run where the crash never happened, with the
event counted (``batch_fallbacks`` / ``stream_drains_by_reason`` under
``kernel error: *``).  This module is the adversary that earns that
promise: a :class:`KernelChaos` context deterministically fails chosen
*device events* — every engine interaction gets a global sequence
number — and the differential runner then byte-compares the chaotic run
against a clean oracle.

Device events, in occurrence order across the whole context:

- ``schedule`` / ``schedule_async`` / ``schedule_waves`` — one event per
  engine call, ticked BEFORE dispatch (a failing event aborts with
  nothing committed);
- ``window`` — one per window fetched from a ``schedule_waves``
  iterator (failing event k leaves windows < k committed: the mid-round
  wave-restart shape);
- ``decisions`` / ``result`` — one per streamed fetch (failing before
  any of that wave committed).

Injection is via the service's ``_engine_for`` seam, so every profile
engine — and the stream session riding on it — sees the same chaos.

:class:`ProcessChaos` is the second adversary, pointed at *process*
crashes instead of kernel crashes: it runs a scenario in a journaled
subprocess (state/journal.py), SIGKILLs it at a seeded journal-record
index, recovers in a fresh process (state/recovery.py), finishes the
scenario, and byte-diffs the full annotation trail against an
uninterrupted run — the same parity bar, extended across a crash
boundary.  Any divergence shrinks through the existing ddmin machinery
(fuzz/shrink.py) exactly like a kernel-chaos divergence.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
from typing import Any, Iterator

Obj = dict[str, Any]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class ChaosError(RuntimeError):
    """The injected device fault (looks like any other kernel crash to
    the engines — they must not special-case it)."""


class _ChaosPendingBatch:
    """Wraps a PendingBatch so the streamed fetch points tick too."""

    def __init__(self, pb: Any, chaos: "KernelChaos"):
        object.__setattr__(self, "_pb", pb)
        object.__setattr__(self, "_chaos", chaos)

    def __getattr__(self, name: str) -> Any:
        return getattr(object.__getattribute__(self, "_pb"), name)

    def decisions(self) -> Any:
        self._chaos._tick("decisions")
        return self._pb.decisions()

    def result(self) -> Any:
        self._chaos._tick("result")
        return self._pb.result()


class _ChaosEngineProxy:
    """Forwards everything to the real engine; the dispatch surface
    (schedule / schedule_async / schedule_waves / window fetches) ticks
    the chaos counter first."""

    def __init__(self, eng: Any, chaos: "KernelChaos"):
        object.__setattr__(self, "_eng", eng)
        object.__setattr__(self, "_chaos", chaos)

    def __getattr__(self, name: str) -> Any:
        return getattr(object.__getattribute__(self, "_eng"), name)

    def schedule(self, *a: Any, **kw: Any) -> Any:
        self._chaos._tick("schedule")
        return self._eng.schedule(*a, **kw)

    def schedule_async(self, *a: Any, **kw: Any) -> Any:
        self._chaos._tick("schedule_async")
        return _ChaosPendingBatch(self._eng.schedule_async(*a, **kw), self._chaos)

    def schedule_waves(self, *a: Any, **kw: Any) -> Iterator:
        self._chaos._tick("schedule_waves")
        return self._chaos._wrap_windows(self._eng.schedule_waves(*a, **kw))


class KernelChaos:
    """Context manager failing the device events whose global sequence
    numbers are in ``fail_events``.  ``events`` counts all events seen,
    ``trips`` the injected failures — a test asserting chaos actually
    fired checks ``trips > 0``."""

    def __init__(self, svc: Any, fail_events: "frozenset[int] | set[int]" = frozenset({0})):
        self.svc = svc
        self.fail_events = frozenset(int(e) for e in fail_events)
        self.events = 0
        self.trips = 0
        self._orig: Any = None

    def _tick(self, what: str) -> None:
        e = self.events
        self.events += 1
        if e in self.fail_events:
            self.trips += 1
            raise ChaosError(f"injected kernel failure at device event #{e} ({what})")

    def _wrap_windows(self, gen: Iterator) -> Iterator:
        for item in gen:
            self._tick("window")
            yield item

    def __enter__(self) -> "KernelChaos":
        self._orig = self.svc._engine_for  # the bound method
        self.svc._engine_for = lambda fw: _ChaosEngineProxy(self._orig(fw), self)
        return self

    def __exit__(self, *exc: Any) -> None:
        # remove the instance attribute shadowing the class method
        self.svc.__dict__.pop("_engine_for", None)
        self._orig = None


# --------------------------------------------------------------- processes


class ProcessChaosError(RuntimeError):
    """The harness itself broke (a child failed to launch, recover, or
    report) — NOT a parity divergence."""


class ProcessChaos:
    """Kill-and-recover differential over one scenario.

    For each seeded kill record index, three subprocess legs run
    (:mod:`fuzz.crash_child`): the uninterrupted baseline (once), the
    journaled run SIGKILLed at the index, and the recovery that resumes
    and finishes the scenario.  The verdict's ``divergences`` lists the
    kill points whose recovered annotation trail differed from the
    baseline's — byte parity is the whole judgment, exactly as in the
    kernel-chaos and differential legs.

    ``kill_records`` are SEEDS, normalized into ``[1, records-1]``
    against the baseline's actual record count, so a caller can pin
    "early / middle / late" without knowing the run length.  ``role``
    overrides the child service configuration
    (:data:`fuzz.crash_child.DEFAULT_ROLE` — e.g. ``use_batch="auto"``
    to exercise the wave-atomic batch commit path, ``checkpoint_every``
    to exercise compaction mid-run).
    """

    def __init__(
        self,
        scenario: Obj,
        kill_records: "tuple[int, ...] | list[int]" = (1,),
        role: "Obj | None" = None,
        child_timeout_s: float = 300.0,
    ):
        self.scenario = scenario
        self.kill_records = tuple(int(k) for k in kill_records)
        self.role = dict(role or {})
        self.child_timeout_s = child_timeout_s

    # ------------------------------------------------------------- children

    @staticmethod
    def _child_env() -> dict:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.setdefault("JAX_PLATFORM_NAME", "cpu")
        env["PYTHONPATH"] = _REPO_ROOT + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        return env

    @staticmethod
    def _child_argv(mode: str, journal_dir: str, plan_path: str, out_path: str) -> list:
        return [
            sys.executable,
            "-m",
            "kube_scheduler_simulator_tpu.fuzz.crash_child",
            "--mode",
            mode,
            "--journal-dir",
            journal_dir,
            "--plan",
            plan_path,
            "--out",
            out_path,
        ]

    def _child(
        self, mode: str, journal_dir: str, plan_path: str, out_path: str
    ) -> subprocess.CompletedProcess:
        try:
            return self._exec(mode, journal_dir, plan_path, out_path, self._child_env())
        except subprocess.TimeoutExpired as e:
            raise ProcessChaosError(
                f"{mode} child hung past {self.child_timeout_s:.0f}s"
            ) from e

    def _exec(
        self, mode: str, journal_dir: str, plan_path: str, out_path: str, env: dict
    ) -> subprocess.CompletedProcess:
        return subprocess.run(
            self._child_argv(mode, journal_dir, plan_path, out_path),
            cwd=_REPO_ROOT,
            env=env,
            capture_output=True,
            timeout=self.child_timeout_s,
        )

    @staticmethod
    def _read_out(out_path: str, leg: str, proc: subprocess.CompletedProcess) -> Obj:
        try:
            with open(out_path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            raise ProcessChaosError(
                f"{leg} child produced no report (rc={proc.returncode}): "
                f"{proc.stderr.decode(errors='replace')[-2000:]}"
            ) from None

    # ------------------------------------------------------------------ run

    def run(self) -> Obj:
        """Execute the kill/recover differential; returns the verdict:
        ``{"scenario", "records", "kill_points", "divergences",
        "truncated_records", "partial_gangs", "first_mismatch"}``."""
        verdict: Obj = {
            "scenario": self.scenario.get("name", "scenario"),
            "kill_points": [],
            "divergences": [],
            "truncated_records": 0,
            "partial_gangs": 0,
            "replayed_records": 0,
            "first_mismatch": None,
        }
        with tempfile.TemporaryDirectory(prefix="kss-crash-") as td:
            plan_path = os.path.join(td, "plan.json")
            with open(plan_path, "w", encoding="utf-8") as f:
                json.dump({"scenario": self.scenario, "role": self.role}, f, sort_keys=True)
            base_out = os.path.join(td, "baseline.json")
            proc = self._child("run", os.path.join(td, "jr-base"), plan_path, base_out)
            if proc.returncode != 0:
                raise ProcessChaosError(
                    f"baseline child rc={proc.returncode}: "
                    f"{proc.stderr.decode(errors='replace')[-2000:]}"
                )
            baseline = self._read_out(base_out, "baseline", proc)
            records = int(baseline["records"])
            verdict["records"] = records
            verdict["journal"] = dict(baseline.get("journal") or {})

            for seed_k in self.kill_records:
                # normalize the seed into a real mid-run record index
                k = 1 + (seed_k - 1) % max(records - 1, 1)
                verdict["kill_points"].append(k)
                jdir = os.path.join(td, f"jr-kill-{k}")
                kill_plan = os.path.join(td, f"plan-kill-{k}.json")
                with open(kill_plan, "w", encoding="utf-8") as f:
                    json.dump(
                        {"scenario": self.scenario, "role": self.role, "kill_at": k},
                        f,
                        sort_keys=True,
                    )
                crash_out = os.path.join(td, f"crash-{k}.json")
                proc = self._child("crash", jdir, kill_plan, crash_out)
                if proc.returncode != -signal.SIGKILL:
                    raise ProcessChaosError(
                        f"crash child at record {k} exited rc={proc.returncode} "
                        f"instead of dying by SIGKILL: "
                        f"{proc.stderr.decode(errors='replace')[-2000:]}"
                    )
                rec_out = os.path.join(td, f"recover-{k}.json")
                proc = self._child("recover", jdir, kill_plan, rec_out)
                if proc.returncode != 0:
                    raise ProcessChaosError(
                        f"recovery child at record {k} rc={proc.returncode}: "
                        f"{proc.stderr.decode(errors='replace')[-2000:]}"
                    )
                recovered = self._read_out(rec_out, f"recover@{k}", proc)
                stats = recovered.get("recovery") or {}
                verdict["truncated_records"] += int(stats.get("truncated_records", 0))
                verdict["partial_gangs"] += int(stats.get("partial_gangs", 0))
                verdict["replayed_records"] += int(stats.get("replayed_records", 0))
                if recovered["state"] != baseline["state"]:
                    verdict["divergences"].append(k)
                    if verdict["first_mismatch"] is None:
                        verdict["first_mismatch"] = _first_state_mismatch(
                            baseline["state"], recovered["state"], k
                        )
        return verdict


class FailoverChaos(ProcessChaos):
    """Kill-the-primary-mid-wave failover drill (replication/).

    A hot-standby ``--mode follow`` child runs CONCURRENTLY with the
    primary, tailing its live journal through a ``ReplicaApplier``.
    The parent coordinates via a marker file: once the primary exits —
    SIGKILLed at a seeded record index (the failover legs) or cleanly
    (the ``kill_records=()`` churn leg) — the parent creates the plan's
    ``promote_file`` and the follower promotes, resumes the scenario,
    and reports.  The verdict extends ProcessChaos's with the
    follower's ``max_lag`` (max post-drain backlog in records — one
    record == one commit wave, so the ISSUE's "within one wave" bar is
    ``max_lag <= 1``), ``torn_records`` and ``records_shipped``; byte
    parity of the promoted state against the uninterrupted baseline is
    the judgment, exactly as in the kill/recover differential.
    """

    def _spawn_follow(self, journal_dir: str, plan_path: str, out_path: str) -> subprocess.Popen:
        return subprocess.Popen(
            self._child_argv("follow", journal_dir, plan_path, out_path),
            cwd=_REPO_ROOT,
            env=self._child_env(),
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )

    def _follow_leg(
        self, td: str, tag: str, jdir: str, promote_file: str
    ) -> "tuple[subprocess.Popen, str]":
        plan_path = os.path.join(td, f"plan-follow-{tag}.json")
        with open(plan_path, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "scenario": self.scenario,
                    "role": self.role,
                    "promote_file": promote_file,
                    "follow_deadline_s": self.child_timeout_s,
                },
                f,
                sort_keys=True,
            )
        out_path = os.path.join(td, f"follow-{tag}.json")
        return self._spawn_follow(jdir, plan_path, out_path), out_path

    def _join_follow(
        self, follower: subprocess.Popen, out_path: str, tag: str
    ) -> Obj:
        try:
            _stdout, stderr = follower.communicate(timeout=self.child_timeout_s)
        except subprocess.TimeoutExpired:
            follower.kill()
            follower.communicate()
            raise ProcessChaosError(f"follow child {tag} hung past {self.child_timeout_s:.0f}s")
        if follower.returncode != 0:
            raise ProcessChaosError(
                f"follow child {tag} rc={follower.returncode}: "
                f"{stderr.decode(errors='replace')[-2000:]}"
            )
        try:
            with open(out_path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            raise ProcessChaosError(f"follow child {tag} produced no report") from None

    def run(self) -> Obj:
        verdict: Obj = {
            "scenario": self.scenario.get("name", "scenario"),
            "kill_points": [],
            "divergences": [],
            "truncated_records": 0,
            "torn_records": 0,
            "partial_gangs": 0,
            "replayed_records": 0,
            "records_shipped": 0,
            "max_lag": 0,
            "promotions": 0,
            "first_mismatch": None,
        }
        with tempfile.TemporaryDirectory(prefix="kss-failover-") as td:
            plan_path = os.path.join(td, "plan.json")
            with open(plan_path, "w", encoding="utf-8") as f:
                json.dump({"scenario": self.scenario, "role": self.role}, f, sort_keys=True)
            base_jdir = os.path.join(td, "jr-base")
            churn = not self.kill_records
            follower = follow_out = None
            promote_file = os.path.join(td, "promote-base")
            if churn:
                # the churn leg follows the BASELINE primary itself —
                # clean exit, then promotion must reproduce its state
                follower, follow_out = self._follow_leg(td, "base", base_jdir, promote_file)
            base_out = os.path.join(td, "baseline.json")
            proc = self._child("run", base_jdir, plan_path, base_out)
            if proc.returncode != 0:
                raise ProcessChaosError(
                    f"baseline child rc={proc.returncode}: "
                    f"{proc.stderr.decode(errors='replace')[-2000:]}"
                )
            baseline = self._read_out(base_out, "baseline", proc)
            records = int(baseline["records"])
            verdict["records"] = records
            if churn:
                with open(promote_file, "w", encoding="utf-8") as f:
                    f.write("promote\n")
                self._absorb(verdict, baseline, self._join_follow(follower, follow_out, "base"), 0)

            for seed_k in self.kill_records:
                k = 1 + (seed_k - 1) % max(records - 1, 1)
                verdict["kill_points"].append(k)
                jdir = os.path.join(td, f"jr-kill-{k}")
                promote_file = os.path.join(td, f"promote-{k}")
                follower, follow_out = self._follow_leg(td, str(k), jdir, promote_file)
                kill_plan = os.path.join(td, f"plan-kill-{k}.json")
                with open(kill_plan, "w", encoding="utf-8") as f:
                    json.dump(
                        {"scenario": self.scenario, "role": self.role, "kill_at": k},
                        f,
                        sort_keys=True,
                    )
                crash_out = os.path.join(td, f"crash-{k}.json")
                try:
                    proc = self._child("crash", jdir, kill_plan, crash_out)
                except ProcessChaosError:
                    follower.kill()
                    follower.communicate()
                    raise
                if proc.returncode != -signal.SIGKILL:
                    follower.kill()
                    follower.communicate()
                    raise ProcessChaosError(
                        f"crash child at record {k} exited rc={proc.returncode} "
                        f"instead of dying by SIGKILL: "
                        f"{proc.stderr.decode(errors='replace')[-2000:]}"
                    )
                with open(promote_file, "w", encoding="utf-8") as f:
                    f.write("promote\n")
                self._absorb(verdict, baseline, self._join_follow(follower, follow_out, str(k)), k)
        return verdict

    @staticmethod
    def _absorb(verdict: Obj, baseline: Obj, followed: Obj, kill_point: int) -> None:
        stats = followed.get("recovery") or {}
        promo = followed.get("promotion") or {}
        verdict["truncated_records"] += int(stats.get("truncated_records", 0))
        verdict["partial_gangs"] += int(stats.get("partial_gangs", 0))
        verdict["replayed_records"] += int(stats.get("replayed_records", 0))
        verdict["torn_records"] += int(promo.get("torn_records", 0))
        verdict["records_shipped"] += int(followed.get("records_shipped", 0))
        verdict["max_lag"] = max(verdict["max_lag"], int(followed.get("max_lag", 0)))
        verdict["promotions"] += 1
        if followed["state"] != baseline["state"]:
            verdict["divergences"].append(kill_point)
            if verdict["first_mismatch"] is None:
                verdict["first_mismatch"] = _first_state_mismatch(
                    baseline["state"], followed["state"], kill_point
                )


def _first_state_mismatch(a: list, b: list, kill_point: int) -> Obj:
    """The first differing parity row between two encoded states
    (fuzz.runner.encode_state lists) — triage context for a divergence."""
    da, db = dict((k, v) for k, v in a), dict((k, v) for k, v in b)
    for key in sorted(set(da) | set(db)):
        if da.get(key) != db.get(key):
            return {
                "kill_point": kill_point,
                "pod": key,
                "baseline": da.get(key),
                "recovered": db.get(key),
            }
    return {"kill_point": kill_point, "pod": None}


# ------------------------------------------------------------ fault matrix


def _env_scope(overrides: "dict[str, str | None]"):
    """Context manager applying env overrides (None = delete) and
    restoring the previous values on exit — the chaos legs flip the
    procmesh/AOT knobs per leg without leaking into the caller."""
    import contextlib

    @contextlib.contextmanager
    def scope():
        saved = {k: os.environ.get(k) for k in overrides}
        try:
            for k, v in overrides.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            yield
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    return scope()


def leaked_worker_pids() -> list[int]:
    """Every live ``procmesh_worker`` process on the host (cmdline scan
    — zombies excluded, they are reaped, not leaked).  The no-leak bar
    every worker-fault leg ends on."""
    pids = []
    for entry in os.listdir("/proc"):
        if not entry.isdigit():
            continue
        try:
            with open(f"/proc/{entry}/cmdline", "rb") as f:
                cmd = f.read()
        except OSError:
            continue
        if b"ops.procmesh_worker" in cmd.replace(b"\x00", b" "):
            pids.append(int(entry))
    return pids


class WorkerChaos:
    """Supervised-ensemble differential: fault a shard worker mid-churn,
    demand byte parity plus a counted recovery.

    Two in-process legs over the same scenario (a ``{"name", "nodes",
    "pods"}`` dict of raw store objects):

    - the BASELINE runs on the in-process path with the AOT cache
      enabled — it both sets the parity bytes and exports the scan
      artifacts the ensemble workers will load;
    - the CHAOS leg runs with ``KSS_MESH_PROCESSES`` engaged and
      ``ProcMeshPool.run`` wrapped so dispatch #``fault_at`` first
      injects the fault into a seeded worker: ``kill`` SIGKILLs it,
      ``stop`` SIGSTOPs it (the hang shape — alive, never replying),
      ``sever`` writes a partial frame header down its command pipe and
      closes it (a mid-frame pipe break: the worker reads a short
      header and exits, the parent's next send fails).

    The supervisor must detect the fault (``died`` or ``hang``
    verdict), SIGKILL the straggler only, respawn the ensemble from the
    AOT cache, and re-dispatch the abandoned wave — so the verdict's
    bar is ``divergences == []`` AND a counted recovery (``respawns``
    / ``hangs_detected`` / a run-fallback reason).  Silent divergence
    is the only failing shape.  On hosts where the ensemble cannot
    engage at all the verdict says so (``engaged == 0`` with the
    counted bring-up reason) and the caller skips loudly — the no-leak
    check still applies.
    """

    def __init__(
        self,
        scenario: Obj,
        mode: str = "kill",
        fault_at: int = 1,
        worker_rank: int = 0,
        nprocs: int = 1,
        heartbeat_s: float = 0.3,
        timeout_s: float = 120.0,
        role: "Obj | None" = None,
        clean_leg: bool = False,
    ):
        if mode not in ("kill", "stop", "sever"):
            raise ValueError(f"mode must be kill|stop|sever, got {mode!r}")
        self.scenario = scenario
        self.mode = mode
        self.fault_at = int(fault_at)
        self.worker_rank = int(worker_rank)
        self.nprocs = int(nprocs)
        self.heartbeat_s = float(heartbeat_s)
        self.timeout_s = float(timeout_s)
        self.role = dict(role or {})
        # clean_leg=True runs the ensemble once WITHOUT the fault first
        # and reports both legs' backend-compile counts: the respawn
        # must add ZERO recompiles over the identical clean run (the
        # RecompileGuard bar — workers load-never-compile structurally,
        # and the parent re-resolves from the same AOT cache)
        self.clean_leg = bool(clean_leg)

    # ------------------------------------------------------------------ legs

    def _leg(self) -> Obj:
        """One full scheduling pass over the scenario; returns the
        annotation trail {pod: (nodeName, annotations)}."""
        from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
        from kube_scheduler_simulator_tpu.state.store import ClusterStore

        store = ClusterStore()
        for n in self.scenario.get("nodes", []):
            store.create("nodes", json.loads(json.dumps(n)))
        for p in self.scenario.get("pods", []):
            store.create("pods", json.loads(json.dumps(p)))
        kw = dict(tie_break="first", seed=3, use_batch="force", batch_min_work=0)
        kw.update(self.role)
        svc = SchedulerService(store, **kw)
        svc.start_scheduler({"percentageOfNodesToScore": 100})
        svc.schedule_pending()
        return {
            p["metadata"]["name"]: (
                (p.get("spec") or {}).get("nodeName"),
                p["metadata"].get("annotations") or {},
            )
            for p in store.list("pods")
        }

    def _inject(self, pool: Any) -> None:
        w = pool.workers[self.worker_rank % len(pool.workers)]
        if self.mode == "kill":
            os.kill(w.proc.pid, signal.SIGKILL)
        elif self.mode == "stop":
            os.kill(w.proc.pid, signal.SIGSTOP)
        else:  # sever: half a frame header, then EOF — a mid-frame break
            try:
                w.proc.stdin.write(b"\xde\xad\xbe\xef")
                w.proc.stdin.flush()
            except Exception:
                pass
            try:
                w.proc.stdin.close()
            except Exception:
                pass

    # ------------------------------------------------------------------- run

    def run(self) -> Obj:
        import tempfile

        from kube_scheduler_simulator_tpu.ops import procmesh

        verdict: Obj = {
            "scenario": self.scenario.get("name", "scenario"),
            "mode": self.mode,
            "fault_at": self.fault_at,
            "engaged": 0,
            "fired": 0,
            "dispatches": 0,
            "respawns": 0,
            "hangs_detected": 0,
            "breaker_state": None,
            "bringup_verdict": None,
            "run_fallbacks": {},
            "divergences": [],
            "first_mismatch": None,
            "leaked_workers": [],
            "clean_compiles": None,
            "chaos_compiles": None,
        }
        from kube_scheduler_simulator_tpu.analysis.runtime import RecompileGuard

        with tempfile.TemporaryDirectory(prefix="kss-worker-chaos-") as td:
            cache = os.path.join(td, "aot")
            with _env_scope({"KSS_AOT_CACHE_DIR": cache, "KSS_MESH_PROCESSES": None}):
                baseline = self._leg()  # in-process; exports the artifacts
            ensemble_env = {
                "KSS_AOT_CACHE_DIR": cache,
                "KSS_MESH_PROCESSES": str(self.nprocs),
                "KSS_PROCMESH_TIMEOUT_S": str(self.timeout_s),
                "KSS_PROCMESH_HEARTBEAT_S": str(self.heartbeat_s),
            }
            if self.clean_leg:
                with _env_scope(ensemble_env):
                    procmesh.reset()
                    with RecompileGuard("clean ensemble leg", max_compiles=1 << 30) as g:
                        clean = self._leg()
                    procmesh.reset()
                verdict["clean_compiles"] = g.compiles
                for name in sorted(set(baseline) | set(clean)):
                    if baseline.get(name) != clean.get(name):
                        verdict["divergences"].append(f"clean:{name}")
            state = {"dispatch": 0, "fired": 0}
            harness = self
            orig_run = procmesh.ProcMeshPool.run

            def chaotic_run(pool_self, key, host_dp):
                i = state["dispatch"]
                state["dispatch"] += 1
                if i == harness.fault_at and not state["fired"]:
                    state["fired"] = 1
                    harness._inject(pool_self)
                return orig_run(pool_self, key, host_dp)

            with _env_scope(ensemble_env):
                procmesh.reset()
                procmesh.ProcMeshPool.run = chaotic_run
                try:
                    with RecompileGuard("chaotic ensemble leg", max_compiles=1 << 30) as g:
                        chaotic = self._leg()
                finally:
                    procmesh.ProcMeshPool.run = orig_run
                st = procmesh.stats()
                procmesh.reset()
            verdict["chaos_compiles"] = g.compiles
        pool = st.get("pool")
        verdict["fired"] = state["fired"]
        verdict["bringup_verdict"] = st.get("verdict")
        verdict["run_fallbacks"] = dict(st.get("run_fallbacks_by_reason") or {})
        if pool is not None:
            verdict["engaged"] = 1
            verdict["dispatches"] = pool["dispatches"]
            verdict["respawns"] = pool["respawns"]
            verdict["hangs_detected"] = pool["hangs_detected"]
            verdict["breaker_state"] = pool["breaker_state"]
        for name in sorted(set(baseline) | set(chaotic)):
            if baseline.get(name) != chaotic.get(name):
                verdict["divergences"].append(name)
                if verdict["first_mismatch"] is None:
                    verdict["first_mismatch"] = {
                        "pod": name,
                        "baseline": baseline.get(name),
                        "chaotic": chaotic.get(name),
                    }
        verdict["leaked_workers"] = leaked_worker_pids()
        return verdict


class _FaultyIO:
    """Counting ``state.journal._DirectIO`` stand-in: the ``op``
    (``write`` | ``fsync``) raises ``OSError(err)`` on its
    ``fail_at``-th invocation (0-based, counted per op).  ``once`` makes
    the fault transient (ENOSPC that clears) vs persistent (a dead
    disk); the journal's policy must hold either way because degrade is
    terminal for the journal's lifetime."""

    def __init__(self, fail_at: int, op: str = "write", err: int = 28, once: bool = True):
        if op not in ("write", "fsync"):
            raise ValueError(f"op must be write|fsync, got {op!r}")
        self.fail_at = int(fail_at)
        self.op = op
        self.err = int(err)
        self.once = bool(once)
        self.counts = {"write": 0, "fsync": 0}
        self.trips = 0

    def _tick(self, op: str) -> None:
        i = self.counts[op]
        self.counts[op] += 1
        if op == self.op and (i == self.fail_at or (not self.once and i >= self.fail_at)):
            self.trips += 1
            raise OSError(self.err, os.strerror(self.err))

    def write(self, f, data: bytes) -> None:
        self._tick("write")
        f.write(data)

    def flush(self, f) -> None:
        f.flush()

    def fsync(self, fd: int) -> None:
        self._tick("fsync")
        os.fsync(fd)


class DiskChaos:
    """Disk-fault differential under state/journal.py: a seeded
    write/fsync fault mid-journal must end in the POLICY outcome —
    ``degrade``: the store keeps scheduling byte-identically to an
    unjournaled baseline, the fault is counted per errno, appends stop,
    and the on-disk log is a clean prefix a fresh recovery replays with
    ZERO torn records; ``wedge``: the faulting commit raises
    :class:`state.journal.JournalWedged` loudly and every subsequent
    transaction refuses at entry, BEFORE any store mutation.  Anything
    else — an uncounted continuation, a torn prefix, a silent partial
    commit — fails the verdict.

    The scenario is a deterministic mutation plan: ``events`` pods
    created then bound via ``journal_txn``-grouped waves, mirroring the
    store traffic a scheduling run emits without dragging jax into a
    disk-fault test."""

    def __init__(
        self,
        mode: str = "degrade",
        op: str = "write",
        err: int = 28,  # ENOSPC
        fail_record: int = 3,
        events: int = 8,
        fsync: bool = False,
    ):
        if mode not in ("degrade", "wedge"):
            raise ValueError(f"mode must be degrade|wedge, got {mode!r}")
        self.mode = mode
        self.op = op
        self.err = int(err)
        self.fail_record = int(fail_record)
        self.events = int(events)
        self.fsync = bool(fsync) or op == "fsync"

    @staticmethod
    def _mutate(store: Any, i: int) -> None:
        """One journaled wave: create a pod and bind it — two events,
        one atomic record (the journal_txn shape scheduling commits
        use)."""
        with store.journal_txn("wave"):
            created = store.create(
                "pods",
                {
                    "apiVersion": "v1",
                    "kind": "Pod",
                    "metadata": {"name": f"dc-{i}", "namespace": "default"},
                    "spec": {"containers": [{"name": "c", "image": "pause"}]},
                },
            )
            created["spec"]["nodeName"] = f"n{i % 3}"
            store.update("pods", created)

    @staticmethod
    def _trail(store: Any) -> list:
        return sorted(
            (p["metadata"]["name"], (p.get("spec") or {}).get("nodeName"))
            for p in store.list("pods")
        )

    def run(self) -> Obj:
        import tempfile

        from kube_scheduler_simulator_tpu.state import journal as J
        from kube_scheduler_simulator_tpu.state.recovery import RecoveryManager
        from kube_scheduler_simulator_tpu.state.store import ClusterStore

        verdict: Obj = {
            "mode": self.mode,
            "op": self.op,
            "errno": self.err,
            "fail_record": self.fail_record,
            "fired": 0,
            "wedged": 0,
            "wedge_raised": 0,
            "degraded_by_errno": {},
            "records_dropped": 0,
            "post_fault_refusals": 0,
            "divergences": [],
            "recovered_records": 0,
            "recovered_torn": 0,
        }
        baseline = ClusterStore()
        for i in range(self.events):
            self._mutate(baseline, i)

        with tempfile.TemporaryDirectory(prefix="kss-disk-chaos-") as td:
            jdir = os.path.join(td, "journal")
            io = _FaultyIO(self.fail_record, op=self.op, err=self.err)
            jr = J.Journal(jdir, fsync=self.fsync, on_error=self.mode, io=io)
            store = ClusterStore()
            store.attach_journal(jr)
            for i in range(self.events):
                try:
                    self._mutate(store, i)
                except J.JournalWedged:
                    verdict["wedge_raised"] += 1
                    if self.mode == "wedge":
                        # post-fault transactions must refuse AT ENTRY,
                        # before any store mutation
                        before = self._trail(store)
                        for j in range(i + 1, self.events):
                            try:
                                self._mutate(store, j)
                            except J.JournalWedged:
                                verdict["post_fault_refusals"] += 1
                        if self._trail(store) != before:
                            verdict["divergences"].append("mutation_after_wedge")
                        break
            verdict["fired"] = io.trips
            verdict["wedged"] = int(jr.wedged)
            verdict["degraded_by_errno"] = dict(jr.degraded_by_errno)
            verdict["records_dropped"] = jr.stats["records_dropped"]
            jr.close()

            if self.mode == "degrade":
                # non-durable continuation must stay byte-identical
                if self._trail(store) != self._trail(baseline):
                    verdict["divergences"].append("degrade_trail")
                # ... and the on-disk log must be a clean prefix
                fresh = ClusterStore()
                report = RecoveryManager(jdir).recover(fresh)
                verdict["recovered_records"] = report.replayed_records
                verdict["recovered_torn"] = report.truncated_records
                if report.truncated_records:
                    verdict["divergences"].append("torn_prefix")
                recovered = {k: v for k, v in self._trail(fresh)}
                full = {k: v for k, v in self._trail(store)}
                for name, node in recovered.items():
                    if full.get(name) != node:
                        verdict["divergences"].append(f"recovered:{name}")
        return verdict
