"""Mini controller-manager (reference simulator/controller/controller.go).

The reference runs exactly three upstream controllers — deployment,
replicaset, and persistentvolume (newControllerInitializers,
controller.go:77-83) — so users can create Deployments/ReplicaSets and see
Pods appear, and PVCs bind to PVs.  This package reconciles the same three
on the in-memory store, synchronously and deterministically (scenario
replay needs convergence to be observable, KEP-140 ControllerWaiter).
"""

from kube_scheduler_simulator_tpu.controllers.manager import ControllerManager

__all__ = ["ControllerManager"]
