"""Run the web UI's JS under a REAL engine when one exists, and pin the
interpreter to a frozen language subset either way (VERDICT r4 missing
#4 / weak #4).

The differential half: ONE driver script (written in JS, appended to the
served UI source) executes in BOTH runtimes — the in-repo interpreter
(``utils.jseval`` + ``utils.jsdom``) and any real engine
``utils.jsengine`` discovers (node/deno/bun/qjs/d8/js) against the
mirrored harness ``tests/webui_js_harness.js`` — and every value it
emits must MATCH across runtimes: an interpreter-vs-engine divergence on
these render paths fails the suite wherever an engine exists.  This
image ships no engine and has no network to fetch one, so the engine leg
skips here with a loud reason; the interpreter leg still runs and pins
the expected values, so the scenarios themselves can never rot.

The freeze half (always runs): the served UI JS must stay within the
exact AST-node-kind subset the interpreter implements today — new UI
code using syntax outside the frozen set fails THIS test before it can
silently mean something different in a real browser (the containment
answer to "every future UI feature also costs interpreter features").
"""

from __future__ import annotations

import json

import pytest

from kube_scheduler_simulator_tpu.server.webui import HTML, JS
from kube_scheduler_simulator_tpu.utils import jsengine
from kube_scheduler_simulator_tpu.utils.jsdom import Harness, collect_text
from kube_scheduler_simulator_tpu.utils.jseval import UNDEF, _native, to_str

KINDS = [
    "pods", "nodes", "persistentvolumes", "persistentvolumeclaims",
    "storageclasses", "priorityclasses", "namespaces", "deployments",
    "replicasets", "scenarios", "nodegroups", "podgroups",
]


def _node(name):
    return {
        "metadata": {"name": name, "labels": {}},
        "status": {"allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}},
    }


def _pod(name, node=None, annotations=None):
    o = {
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "100m"}}}]},
    }
    if annotations:
        o["metadata"]["annotations"] = annotations
    if node:
        o["spec"]["nodeName"] = node
    return o


SCORED = {
    "scheduler-simulator/finalscore-result": json.dumps(
        {"diff-node-1": {"NodeResourcesFit": "42", "TaintToleration": "100"}}
    ),
    "scheduler-simulator/selected-node": "diff-node-1",
    "scheduler-simulator/result-history": json.dumps(
        [{"scheduler-simulator/finalscore-result": '{"diff-node-1":{"NodeResourcesFit":"41"}}'}]
    ),
}


def _routes():
    routes = {("GET", f"/api/v1/resources/{k}"): {"items": []} for k in KINDS}
    routes[("GET", "/api/v1/autoscaler")] = {"mode": "off"}
    routes[("GET", "/api/v1/resources/nodes")] = {"items": [_node("diff-node-1")]}
    routes[("GET", "/api/v1/resources/pods")] = {
        "items": [
            _pod("diff-pod-a", node="diff-node-1", annotations=SCORED),
            _pod("diff-pod-pending"),
        ]
    }
    return routes


# ONE driver, two runtimes.  Every __emit value must match across them.
DRIVER = """
(async function () {
  await __drain();
  __emit("boot_nodes", __collectText("nodes"));
  toggleView();
  await __drain();
  __emit("tables_initial", __collectText("tables"));
  document.getElementById("search").value = "pending";
  onSearch();
  __emit("tables_before_flush", __collectText("tables"));
  __emit("flushed", __flushTimers() >= 1);
  await __drain();
  __emit("tables_filtered", __collectText("tables"));
  document.getElementById("search").value = "";
  onSearch();
  __flushTimers();
  await __drain();
  showPod(state.pods["default/diff-pod-a"]);
  await __drain();
  __emit("dlg_open", __elementOpen("dlg"));
  __emit("dlg_body", __collectText("dlgbody"));
  __done();
})();
"""


def run_driver_in_interpreter() -> "list[tuple[str, object]]":
    h = Harness(HTML)
    h.routes.update(_routes())
    emitted: "list[tuple[str, object]]" = []

    def norm(v):
        if v is UNDEF or v is None:
            return None
        if isinstance(v, bool):
            return v
        return to_str(v) if not isinstance(v, (int, float)) else v

    g = h.globals()
    g["__emit"] = _native(lambda name, value=UNDEF, *a: emitted.append((to_str(name), norm(value))))
    g["__collectText"] = _native(
        lambda id, *a: collect_text(h.document._by_id[to_str(id)])
        if to_str(id) in h.document._by_id
        else ""
    )
    g["__elementOpen"] = _native(
        lambda id, *a: bool(getattr(h.document._by_id.get(to_str(id)), "open", False))
    )
    g["__flushTimers"] = _native(lambda *a: h.flush_timers())
    g["__drain"] = _native(lambda *a: UNDEF)
    g["__done"] = _native(lambda *a: UNDEF)

    from kube_scheduler_simulator_tpu.utils.jseval import Interp, PendingAwait

    interp = Interp(g)
    # two programs, one interpreter: the UI bootstrap parks on its idle
    # sleep (PendingAwait ends the first run), then the driver executes
    # against the booted globals — the engine leg runs them concatenated
    # because a real engine's awaits don't block further top-level code
    for src in (JS, DRIVER):
        try:
            interp.run(src)
        except PendingAwait:
            pass
    return emitted


def build_engine_program() -> str:
    import os

    routes = [
        [m, p, json.dumps(payload)] for (m, p), payload in _routes().items()
    ]
    harness_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "webui_js_harness.js")
    with open(harness_path) as f:
        harness_src = f.read()
    return (
        f"var __HTML__ = {json.dumps(HTML)};\n"
        f"var __ROUTES__ = {json.dumps(routes)};\n"
        f"var __WATCH__ = [];\n"
        + harness_src
        + "\n"
        + JS
        + "\n"
        + DRIVER
    )


def test_interpreter_leg_pins_render_paths():
    """Always runs: the driver's emitted values under the interpreter
    must be the known-good render behavior (guards the scenarios against
    rot even where no engine exists)."""
    emitted = dict(run_driver_in_interpreter())
    assert "diff-node-1" in emitted["boot_nodes"]
    assert "default/diff-pod-a" in emitted["boot_nodes"]
    assert "(unscheduled)" in emitted["boot_nodes"]
    assert "pods (2)" in emitted["tables_initial"]
    assert "pods (2)" in emitted["tables_before_flush"]  # debounced: not yet
    assert emitted["flushed"] is True
    assert "pods (1)" in emitted["tables_filtered"]
    assert emitted["dlg_open"] is True
    assert "default/diff-pod-a" in emitted["dlg_body"]
    assert '"41"' in emitted["dlg_body"]  # history viewer rendered


def test_engine_vs_interpreter_divergence_fails():
    """Where ANY real JS engine exists, the same program must emit the
    same values under it as under the interpreter."""
    engine = jsengine.find_engine()
    if engine is None:
        pytest.skip(
            "NO REAL JS ENGINE ON THIS HOST (probed: "
            + ", ".join(jsengine.probed_engines())
            + ") — interpreter-vs-engine differential did not run; the "
            "interpreter leg (test_interpreter_leg_pins_render_paths) "
            "still pinned the scenarios"
        )
    out = jsengine.run_under_engine(engine, build_engine_program(), timeout=120)
    marker = "__RESULT__"
    lines = [ln for ln in out.splitlines() if ln.startswith(marker)]
    assert lines, f"engine produced no result line; stdout tail: {out[-2000:]}"
    engine_emitted = [(k, v) for k, v in json.loads(lines[-1][len(marker):])]
    interp_emitted = run_driver_in_interpreter()
    assert len(engine_emitted) == len(interp_emitted)
    for (ek, ev), (ik, iv) in zip(engine_emitted, interp_emitted):
        assert ek == ik
        assert ev == iv, f"divergence at {ek!r}:\n engine: {ev!r}\n interp: {iv!r}"


def test_engine_program_parses():
    """Always runs: the assembled engine-side program (JS harness +
    injected data + UI source + driver) must at least parse — a host
    WITH an engine must hit real differential results, not a syntax
    error in the harness."""
    from kube_scheduler_simulator_tpu.utils.jscheck import parse

    parse(build_engine_program())


# ---------------------------------------------------------------- freeze

# The interpreter's supported structural subset, frozen (VERDICT r4 weak
# #4): the exact AST node kinds utils/jscheck produces for the served UI
# today.  Growing the UI's language use requires a DELIBERATE extension
# of this list (and of jseval), not an accident.
FROZEN_NODE_KINDS = frozenset(
    {
        "array", "arrow", "assign", "bin", "block", "break", "call",
        "cond", "continue", "done", "expr", "for", "forof", "funcdecl",
        "id", "if", "index", "lit", "member", "new", "num", "object",
        "parr", "pid", "pobj", "program", "prop", "regex", "return",
        "shorthand", "str", "template", "throw", "try", "unary",
        "update", "value", "vardecl", "while",
    }
)


def _node_kinds(n, acc):
    if isinstance(n, tuple) and n and isinstance(n[0], str):
        acc.add(n[0])
    if isinstance(n, (list, tuple)):
        for x in n:
            _node_kinds(x, acc)
    return acc


def test_ui_js_stays_within_frozen_interpreter_subset():
    from kube_scheduler_simulator_tpu.utils.jscheck import parse

    kinds = _node_kinds(parse(JS), set())
    overflow = kinds - FROZEN_NODE_KINDS
    assert not overflow, (
        f"the served UI JS uses syntax outside the frozen interpreter "
        f"subset: {sorted(overflow)} — extend utils/jseval + "
        f"FROZEN_NODE_KINDS deliberately (and cover the new forms in "
        f"tests/test_jseval.py) before shipping UI code that needs them"
    )
