"""TaintToleration plugin (upstream v1.26).

Filter: first NoSchedule/NoExecute taint not tolerated fails the node with
the exact upstream message ``node(s) had untolerated taint {key: value}``.
Score: count of PreferNoSchedule taints not tolerated by the pod's
PreferNoSchedule-effect-compatible tolerations, normalized reversed.
Vectorized twin: ops/taints.py (host pre-matches strings into matrices).
"""

from __future__ import annotations

from typing import Any

from kube_scheduler_simulator_tpu.models.framework import CycleState, Status
from kube_scheduler_simulator_tpu.models.nodeinfo import NodeInfo
from kube_scheduler_simulator_tpu.plugins.intree.helpers import default_normalize_score
from kube_scheduler_simulator_tpu.utils.labels import (
    find_untolerated_taint,
    tolerations_tolerate_taint,
)

Obj = dict[str, Any]


def node_taints(node: Obj) -> list[Obj]:
    return (node.get("spec") or {}).get("taints") or []


def pod_tolerations(pod: Obj) -> list[Obj]:
    return (pod.get("spec") or {}).get("tolerations") or []


class TaintToleration:
    name = "TaintToleration"

    PRE_SCORE_KEY = "PreScoreTaintToleration"

    def filter(self, state: CycleState, pod: Obj, node_info: NodeInfo) -> "Status | None":
        taint = find_untolerated_taint(node_taints(node_info.node), pod_tolerations(pod))
        if taint is None:
            return None
        return Status.unresolvable(
            f"node(s) had untolerated taint {{{taint.get('key', '')}: {taint.get('value', '')}}}"
        )

    def pre_score(self, state: CycleState, pod: Obj, nodes: list[Obj]) -> "Status | None":
        # Keep only tolerations that could tolerate a PreferNoSchedule taint
        # (upstream getAllTolerationPreferNoSchedule: effect empty or
        # PreferNoSchedule).
        tolerations = [
            t for t in pod_tolerations(pod) if not t.get("effect") or t.get("effect") == "PreferNoSchedule"
        ]
        state.write(self.PRE_SCORE_KEY, tolerations)
        return None

    def score(self, state: CycleState, pod: Obj, node_info: NodeInfo) -> "tuple[int, Status | None]":
        tolerations = state.read(self.PRE_SCORE_KEY)
        if tolerations is None:
            tolerations = []
        count = 0
        for taint in node_taints(node_info.node):
            if taint.get("effect") == "PreferNoSchedule" and not tolerations_tolerate_taint(tolerations, taint):
                count += 1
        return count, None

    def normalize_scores(self, state: CycleState, pod: Obj, scores: dict[str, int]) -> "Status | None":
        default_normalize_score(scores, reverse=True)
        return None
