"""Multi-process shard workers (ops/procmesh.py — ``KSS_MESH_PROCESSES``).

The ensemble is an opt-in execution substrate, not a semantics change:
with the knob set, scan dispatches run on ``jax.distributed`` worker
processes that LOAD the PR-11 AOT artifacts (never compile), and every
way the ensemble can be unavailable is a counted fallback to the
in-process virtual mesh with byte-identical scheduling either way.

The end-to-end tests SKIP LOUDLY (with the counted bring-up verdict)
when the ensemble can't engage on the host: on jax CPU backends
``jax.distributed.initialize`` succeeds but cross-process collectives
are unimplemented, so the N>=2 ensemble only engages on real multi-chip
hosts — the N=1 ensemble exercises the whole protocol (spawn, init
handshake, probe, artifact load, dispatch/fetch) everywhere.
"""

from __future__ import annotations

import random
from typing import Any

import pytest

from kube_scheduler_simulator_tpu.ops import procmesh
from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
from kube_scheduler_simulator_tpu.state.store import ClusterStore

from tests.test_batch_parity import mk_node, mk_pod

Obj = dict[str, Any]


@pytest.fixture
def pm_state():
    """Reset the module-level pool/verdict memo around each test — the
    bring-up verdict is memoized per process by design."""
    procmesh.reset()
    yield procmesh
    procmesh.reset()


# ------------------------------------------------------------------ unit


def test_procs_from_env(monkeypatch):
    monkeypatch.delenv("KSS_MESH_PROCESSES", raising=False)
    assert procmesh.procs_from_env() == 0
    monkeypatch.setenv("KSS_MESH_PROCESSES", "0")
    assert procmesh.procs_from_env() == 0
    monkeypatch.setenv("KSS_MESH_PROCESSES", "3")
    assert procmesh.procs_from_env() == 3
    monkeypatch.setenv("KSS_MESH_PROCESSES", "two")
    with pytest.raises(ValueError):
        procmesh.procs_from_env()
    monkeypatch.setenv("KSS_MESH_PROCESSES", "-1")
    with pytest.raises(ValueError):
        procmesh.procs_from_env()


def test_heartbeat_from_env(monkeypatch):
    monkeypatch.delenv("KSS_PROCMESH_HEARTBEAT_S", raising=False)
    assert procmesh.heartbeat_from_env() == 1.0
    monkeypatch.setenv("KSS_PROCMESH_HEARTBEAT_S", "0.25")
    assert procmesh.heartbeat_from_env() == 0.25
    monkeypatch.setenv("KSS_PROCMESH_HEARTBEAT_S", "fast")
    with pytest.raises(ValueError):
        procmesh.heartbeat_from_env()
    monkeypatch.setenv("KSS_PROCMESH_HEARTBEAT_S", "0")
    with pytest.raises(ValueError):
        procmesh.heartbeat_from_env()


def test_max_respawns_from_env(monkeypatch):
    monkeypatch.delenv("KSS_PROCMESH_MAX_RESPAWNS", raising=False)
    assert procmesh.max_respawns_from_env() == 3
    monkeypatch.setenv("KSS_PROCMESH_MAX_RESPAWNS", "5")
    assert procmesh.max_respawns_from_env() == 5
    monkeypatch.setenv("KSS_PROCMESH_MAX_RESPAWNS", "0")
    with pytest.raises(ValueError):
        procmesh.max_respawns_from_env()
    monkeypatch.setenv("KSS_PROCMESH_MAX_RESPAWNS", "many")
    with pytest.raises(ValueError):
        procmesh.max_respawns_from_env()


def test_terminate_reaps_a_stopped_child():
    """The shutdown-path satellite fix: ``kill()`` alone leaves a
    SIGSTOP'd child unreaped (SIGKILL is delivered but ``wait`` can park
    while the tracer state settles under load); ``_terminate`` SIGCONTs
    first and must reap within its timeout."""
    import signal
    import subprocess
    import sys
    import time

    proc = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(600)"])
    try:
        procmesh._register_child(proc)
        import os

        os.kill(proc.pid, signal.SIGSTOP)
        t0 = time.monotonic()
        procmesh._terminate(proc, timeout=10.0)
        assert proc.poll() is not None, "stopped child was not reaped"
        assert time.monotonic() - t0 < 10.0
        with procmesh._CHILD_MU:
            assert proc not in procmesh._CHILDREN
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_metrics_silent_until_knob_exercised(pm_state, monkeypatch):
    """metrics()['procmesh'] stays None (and /metrics renders nothing)
    while KSS_MESH_PROCESSES has never been set — the common case pays
    no payload."""
    monkeypatch.delenv("KSS_MESH_PROCESSES", raising=False)
    assert SchedulerService._procmesh_stats() is None


def test_acquire_without_aot_cache_counts_fallback(pm_state, monkeypatch):
    """An engine with the knob set but no AOT cache drops the pool with
    a counted reason (workers load, never compile — no cache means
    nothing for them to load)."""
    monkeypatch.delenv("KSS_AOT_CACHE_DIR", raising=False)
    monkeypatch.setenv("KSS_MESH_PROCESSES", "1")
    store = _cluster()
    svc = _service(store)
    svc.schedule_pending()
    st = procmesh.stats()
    assert st["requested_processes"] == 1
    assert st["run_fallbacks_by_reason"].get("aot_cache_disabled", 0) >= 1
    # scheduling was unaffected
    assert any((p.get("spec") or {}).get("nodeName") for p in store.list("pods"))


# ------------------------------------------------------------------- e2e


def _cluster() -> ClusterStore:
    rng = random.Random(7)
    store = ClusterStore()
    for i in range(12):
        taints = (
            [{"key": "dedicated", "value": "infra", "effect": "NoSchedule"}]
            if i % 5 == 0
            else None
        )
        store.create(
            "nodes", mk_node(f"n{i}", cpu_m=4000 + 500 * (i % 3), mem_mi=8192,
                             taints=taints)
        )
    for i in range(30):
        p = mk_pod(
            f"p{i}",
            cpu_m=rng.choice([100, 250, 3900]),
            mem_mi=rng.choice([64, 256]),
            labels={"app": f"a{i % 4}"},
        )
        if i % 7 == 0:
            p["spec"]["tolerations"] = [{"key": "dedicated", "operator": "Exists"}]
        store.create("pods", p)
    return store


def _service(store) -> SchedulerService:
    svc = SchedulerService(
        store, tie_break="first", seed=3, use_batch="force", batch_min_work=0
    )
    svc.start_scheduler({"percentageOfNodesToScore": 100})
    return svc


def _run() -> dict:
    store = _cluster()
    svc = _service(store)
    svc.schedule_pending()
    return {
        p["metadata"]["name"]: (
            (p.get("spec") or {}).get("nodeName"),
            p["metadata"].get("annotations") or {},
        )
        for p in store.list("pods")
    }


def test_single_worker_ensemble_end_to_end(pm_state, monkeypatch, tmp_path):
    """N=1: the full protocol — spawn, jax.distributed handshake,
    collectives probe, AOT artifact load on the worker, async
    dispatch/fetch — with scheduling byte-identical to the in-process
    run that exported the artifacts."""
    monkeypatch.setenv("KSS_AOT_CACHE_DIR", str(tmp_path / "aot"))
    monkeypatch.setenv("KSS_PROCMESH_TIMEOUT_S", "120")
    baseline = _run()  # in-process; exports the scan artifact

    monkeypatch.setenv("KSS_MESH_PROCESSES", "1")
    ensemble = _run()
    st = procmesh.stats()
    assert ensemble == baseline, "ensemble scheduling diverged from in-process run"
    if st["pool"] is None:
        pytest.skip(
            "SKIPPING LOUDLY: single-worker jax.distributed ensemble could not "
            f"engage on this host — verdict={st['verdict']!r}, "
            f"fallbacks={st['fallbacks_by_reason']}"
        )
    assert st["pool"]["engaged"] == 1
    assert st["pool"]["dispatches"] >= 1
    # load-never-compile: the scan resolved from the artifact cache on
    # every worker (a compile inside a worker is structurally impossible
    # — procmesh_worker.py has no build path)
    assert st["pool"]["scans_loaded"] >= 1
    # Under CPU contention a worker wait may time out mid-run; the wave
    # then finishes through the engine's counted donate=False local
    # rebuild (parity already asserted above).  Deterministic either
    # way: a quiet host shows zero run fallbacks; a loaded host shows
    # ONLY contention verdicts, each matched by a counted local-rebuild
    # retry — anything else (artifact_missing, breaker_open) still
    # fails.
    contention = {"worker_lost", "timeout"}
    unexpected = {
        r: n for r, n in st["run_fallbacks_by_reason"].items()
        if r.split(":", 1)[0] not in contention
    }
    assert unexpected == {}, st
    if st["run_fallbacks_by_reason"]:
        from kube_scheduler_simulator_tpu.resilience.policy import retry_stats

        assert retry_stats().get("procmesh_local_rebuild", 0) >= 1


def test_multiprocess_ensemble_parity_or_loud_skip(pm_state, monkeypatch, tmp_path):
    """N=2: on hosts where cross-process collectives exist the ensemble
    engages and must match the in-process bytes; everywhere else the
    bring-up probe fails, the fallback is COUNTED, scheduling still
    matches, and the test skips loudly with the verdict."""
    monkeypatch.setenv("KSS_AOT_CACHE_DIR", str(tmp_path / "aot"))
    monkeypatch.setenv("KSS_PROCMESH_TIMEOUT_S", "120")
    baseline = _run()

    monkeypatch.setenv("KSS_MESH_PROCESSES", "2")
    ensemble = _run()
    st = procmesh.stats()
    # parity holds whether or not the ensemble engaged
    assert ensemble == baseline, "N=2 run diverged from in-process run"
    if st["pool"] is None:
        assert st["fallbacks_by_reason"], st
        assert st["verdict"], st
        pytest.skip(
            "SKIPPING LOUDLY: multi-process jax.distributed ensemble could not "
            f"engage on this host — verdict={st['verdict']!r} "
            "(expected on CPU backends: initialize() succeeds but "
            "cross-process collectives are unimplemented)"
        )
    assert st["pool"]["processes"] == 2
    assert st["pool"]["dispatches"] >= 1


# ------------------------------------------------------------- supervision


def test_worker_respawn_parity_or_loud_skip(pm_state):
    """The supervised-failure pin: SIGKILL a worker at the first
    dispatch — the pool must detect the death, respawn the ensemble
    from the AOT cache (``procmesh_respawns_total == 1``), re-dispatch
    the abandoned wave, and match the in-process bytes, leaking no
    worker processes.  Skips loudly where the ensemble can't engage."""
    from kube_scheduler_simulator_tpu.fuzz.chaos import WorkerChaos, leaked_worker_pids

    scn = {
        "name": "respawn-parity",
        "nodes": [mk_node(f"sn{i}", cpu_m=4000, mem_mi=8192) for i in range(4)],
        "pods": [mk_pod(f"sp{i}", cpu_m=250, mem_mi=64) for i in range(12)],
    }
    v = WorkerChaos(scn, mode="kill", fault_at=0, nprocs=1, heartbeat_s=0.3).run()
    if not v["engaged"]:
        pytest.skip(
            "SKIPPING LOUDLY: single-worker ensemble could not engage on this "
            f"host — verdict={v['bringup_verdict']!r}"
        )
    assert v["fired"] == 1
    assert v["divergences"] == [], v["first_mismatch"]
    assert v["respawns"] == 1
    assert v["breaker_state"] == "closed"
    assert v["leaked_workers"] == []
    assert leaked_worker_pids() == []
