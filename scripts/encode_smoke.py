#!/usr/bin/env python
"""Fast encode-parity smoke: incremental vs full over a tiny churn
sequence, byte-compared — the tier-1 step that catches cache-invalidation
bugs in ops/encode.EncodeCache without the slow markers.

Drives a real SchedulerService twice (KSS_ENCODE_INCREMENTAL latched per
engine) through create/schedule/delete/mutate waves on a fixed-clock
store, then byte-compares every pod's binding and annotation trail AND
asserts the delta path actually engaged (a silent full re-encode would
otherwise mask invalidation bugs).  Exit 0 = parity; nonzero = diverged.
"""

from __future__ import annotations

import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")


from kube_scheduler_simulator_tpu.utils import SimClock


def build(inc: bool):
    os.environ["KSS_ENCODE_INCREMENTAL"] = "1" if inc else "0"

    from kube_scheduler_simulator_tpu.scheduler.service import SchedulerService
    from kube_scheduler_simulator_tpu.state.store import ClusterStore

    store = ClusterStore(clock=SimClock(1_700_000_000.0))
    for i in range(12):
        store.create(
            "nodes",
            {
                "metadata": {
                    "name": f"node-{i}",
                    "labels": {
                        "kubernetes.io/hostname": f"node-{i}",
                        "topology.kubernetes.io/zone": f"z{i % 3}",
                        "disk": "ssd" if i % 2 else "hdd",
                    },
                },
                "status": {"allocatable": {"cpu": "8000m", "memory": "16Gi", "pods": "110"}},
                "spec": {},
            },
        )
    svc = SchedulerService(store, tie_break="first", use_batch="force", batch_min_work=1)
    svc.start_scheduler(None)
    svc._engine_for(svc.framework)  # latch the env knob into the engine
    return svc, store


def churn(svc, store, waves: int = 3):
    rng = random.Random(5)
    created = 0
    for _ in range(waves):
        for _ in range(30):
            p = {
                "metadata": {
                    "name": f"pod-{created}",
                    "namespace": "default",
                    "labels": {"app": f"a{created % 3}"},
                },
                "spec": {
                    "containers": [
                        {
                            "name": "c",
                            "resources": {
                                "requests": {"cpu": f"{100 + (created % 4) * 50}m", "memory": "128Mi"}
                            },
                        }
                    ]
                },
            }
            if created % 3 == 0:
                p["spec"]["topologySpreadConstraints"] = [
                    {
                        "maxSkew": 2,
                        "topologyKey": "topology.kubernetes.io/zone",
                        "whenUnsatisfiable": "DoNotSchedule",
                        "labelSelector": {"matchLabels": {"app": f"a{created % 3}"}},
                    }
                ]
            if created % 4 == 0:
                p["spec"]["nodeSelector"] = {"disk": "ssd"}
            store.create("pods", p)
            created += 1
        svc.schedule_pending(max_rounds=2)
        bound = [p for p in store.list("pods") if (p.get("spec") or {}).get("nodeName")]
        for p in rng.sample(bound, max(1, len(bound) // 10)):
            store.delete("pods", p["metadata"]["name"], p["metadata"].get("namespace"))
        if bound:
            t = rng.choice(bound)
            try:
                store.patch(
                    "pods",
                    t["metadata"]["name"],
                    {"metadata": {"labels": {"app": "mut"}}},
                    t["metadata"].get("namespace"),
                )
            except KeyError:
                pass
    out = {}
    for p in store.list("pods"):
        k = p["metadata"]["namespace"] + "/" + p["metadata"]["name"]
        out[k] = (
            (p.get("spec") or {}).get("nodeName"),
            tuple(sorted((p["metadata"].get("annotations") or {}).items())),
        )
    return out


def main() -> int:
    svc1, store1 = build(inc=True)
    svc0, store0 = build(inc=False)
    d1 = churn(svc1, store1)
    d0 = churn(svc0, store0)
    m1 = svc1.metrics()
    if d1.keys() != d0.keys():
        print(f"encode-smoke: pod sets diverged ({len(d1)} vs {len(d0)})", file=sys.stderr)
        return 1
    bad = [k for k in sorted(d1) if d1[k] != d0[k]]
    if bad:
        print(f"encode-smoke: {len(bad)} pods diverged, first: {bad[0]}", file=sys.stderr)
        return 1
    if m1["encode_delta_total"] < 1:
        print(
            f"encode-smoke: delta path never engaged — fallbacks: "
            f"{m1['encode_fallbacks_by_reason']}",
            file=sys.stderr,
        )
        return 1
    print(
        f"encode-smoke OK: {len(d1)} pods byte-identical; "
        f"delta={m1['encode_delta_total']} full={m1['encode_full_total']} "
        f"rows={m1['encode_rows_reencoded_total']} "
        f"uploaded={m1['device_bytes_uploaded_total']}B "
        f"reuses={m1['device_plane_reuses_total']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
