refreshSessions().then(() => refreshAll()).then(() => { watchLoop(); pollWorkloads(); });
