"""KEP-184 SchedulerSimulation: one-shot Scenario × N-scheduler runs.

The reference designs (design-only — no code ships) a `SchedulerSimulation`
CRD whose controller spins a `Simulator` Pod per run, injects a
"scenario-runner" container that posts the Scenario into the simulator's
apiserver, waits for completion, and collects the result file
(reference keps/184-scheduler-simulation/README.md:44-158).  The
motivation is comparative: "run the same scenario with various schedulers
and see which scheduler is the best one" (README.md:18).

This build realizes that flow tpu-natively and in process: each entry in
``spec.simulators`` gets an ISOLATED simulator instance — its own
ClusterStore, controller manager, and SchedulerService (the in-process
analog of the KEP's Simulator Pod; KEP-159's Simulator objects ride the
same substrate) — the Scenario runs deterministically in each via the
KEP-140 engine, and the status carries a per-simulator report built from
the KEP-140 result-calculation package (allocation rate, per-node
utilization — keps/140-scenario-based-simulation/README.md:553-565) plus
a cross-simulator comparison, which is the part the reference leaves to
"analyzes the results ... and calculates a score" user code
(keps/184 README.md:186-190).

Spec (`simulation.kube-scheduler-simulator.sigs.k8s.io/v1alpha1`,
kind ``SchedulerSimulation``):

    spec:
      scenario: {<ScenarioSpec>}          # inline; or
      scenarioTemplateFilePath: path.yaml # the KEP's file indirection
      simulators:
        - name: default                   # one isolated run per entry
          schedulerConfig: {<KubeSchedulerConfiguration>}  # optional
          useBatch: auto|off|force        # optional (default auto)
          seed: 0                         # optional

Status: ``phase`` (Completed/Failed), RFC3339 ``startTime`` /
``completionTime``, ``message`` (on failure), ``results[]`` (per
simulator: scenario phase, step count, report) and ``comparison``.
"""

from __future__ import annotations

import copy
import time
from typing import Any

from kube_scheduler_simulator_tpu.scenario.result import allocation_rate, node_utilization

Obj = dict[str, Any]


def now_rfc3339() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())

GROUP = "simulation.kube-scheduler-simulator.sigs.k8s.io"
API_VERSION = f"{GROUP}/v1alpha1"
KIND = "SchedulerSimulation"


class SchedulerSimulationError(Exception):
    pass


def _resolve_template_path(path: str) -> str:
    """Resolve ``spec.scenarioTemplateFilePath`` INSIDE the configured
    template directory ($KSS_SCENARIO_TEMPLATE_DIR).  The field arrives
    from API clients (POST /api/v1/schedulersimulations, the CRD), so an
    unrestricted open() is a file-disclosure primitive; with no directory
    configured the indirection is disabled outright."""
    import os

    base = os.environ.get("KSS_SCENARIO_TEMPLATE_DIR")
    if not base:
        raise SchedulerSimulationError(
            "spec.scenarioTemplateFilePath is disabled: set "
            "KSS_SCENARIO_TEMPLATE_DIR to the scenario-template directory"
        )
    root = os.path.realpath(base)
    full = os.path.realpath(os.path.join(root, path))
    if full != root and not full.startswith(root + os.sep):
        raise SchedulerSimulationError(
            "spec.scenarioTemplateFilePath escapes the scenario-template directory"
        )
    return full


def _load_scenario_spec(spec: Obj) -> Obj:
    scenario = spec.get("scenario")
    if scenario is None:
        path = spec.get("scenarioTemplateFilePath")
        if not path:
            raise SchedulerSimulationError(
                "spec.scenario or spec.scenarioTemplateFilePath is required"
            )
        import json

        full = _resolve_template_path(path)
        try:
            with open(full) as f:
                text = f.read()
        except OSError:
            raise SchedulerSimulationError(f"cannot read scenario template {path!r}")
        # Parser exceptions embed file-content snippets (YAML error
        # context) — never reflect their text into status.message.
        try:
            doc = json.loads(text)
        except ValueError:
            try:
                import yaml

                doc = yaml.safe_load(text)
            except ImportError:  # pragma: no cover - yaml is bundled
                raise SchedulerSimulationError(
                    f"cannot parse scenario template {path!r} (yaml unavailable)"
                )
            except Exception:
                raise SchedulerSimulationError(
                    f"cannot parse scenario template {path!r} as JSON or YAML"
                )
        # accept either a full Scenario object or a bare spec
        scenario = doc.get("spec", doc) if isinstance(doc, dict) else None
    if not isinstance(scenario, dict):
        raise SchedulerSimulationError("scenario must be an object")
    return scenario


def _run_in_isolated_simulator(scenario_spec: Obj, sim: Obj) -> "tuple[Obj, Obj]":
    """One simulator instance, one deterministic scenario run — returns
    (final scenario status, report).  The instance is the in-process
    analog of the KEP's Simulator Pod: nothing is shared with the caller
    or with sibling runs."""
    from kube_scheduler_simulator_tpu.scenario.engine import ScenarioEngine
    from kube_scheduler_simulator_tpu.server.di import DIContainer

    di = DIContainer(
        initial_scheduler_cfg=sim.get("schedulerConfig"),
        use_batch=sim.get("useBatch", "auto"),
        seed=int(sim.get("seed") or 0),
        # the ephemeral store never holds Simulator/SchedulerSimulation
        # CRs — don't boot an operator that reconciles nothing
        enable_simulator_operator=False,
    )
    try:
        engine = ScenarioEngine(
            di.cluster_store, di.scheduler_service(), di.controller_manager()
        )
        done = engine.run({"spec": copy.deepcopy(scenario_spec)})
        status = done.get("status") or {}
        store = di.cluster_store
        pods = store.list("pods", copy_objects=False)
        scheduled = sum(1 for p in pods if (p.get("spec") or {}).get("nodeName"))
        timeline = ((status.get("scenarioResult") or {}).get("timeline")) or {}
        report = {
            "allocationRate": round(allocation_rate(store), 6),
            "nodeUtilization": node_utilization(store),
            "pods": len(pods),
            "scheduledPods": scheduled,
            "unscheduledPods": len(pods) - scheduled,
            "timelineEvents": sum(len(v) for v in timeline.values()),
            "steps": len(timeline),
        }
        return status, report
    finally:
        di.close()


def _bindings_of(status: Obj) -> dict[str, str]:
    """pod → node bindings drawn from the scenario timeline's generated
    ``podScheduled`` events (the KEP-140 result "simple data"), for
    divergence reporting."""
    out: dict[str, str] = {}
    timeline = ((status.get("scenarioResult") or {}).get("timeline")) or {}
    for events in timeline.values():
        for ev in events:
            pod = (ev.get("podScheduled") or {}).get("result") or {}
            name = (pod.get("metadata") or {}).get("name")
            node = (pod.get("spec") or {}).get("nodeName")
            if name and node:
                out[name] = node
    return out


def run_scheduler_simulation(obj: Obj) -> Obj:
    """Execute a SchedulerSimulation object to completion (the KEP's
    controller flow, steps 1-7, collapsed into one synchronous pass over
    in-process simulator instances).  Returns the object with status."""
    obj = copy.deepcopy(obj)
    spec = obj.get("spec") or {}
    status: Obj = {"phase": "Running", "startTime": now_rfc3339()}
    obj["status"] = status
    try:
        scenario_spec = _load_scenario_spec(spec)
        simulators = spec.get("simulators") or [{"name": "default"}]
        if not isinstance(simulators, list) or not simulators:
            raise SchedulerSimulationError("spec.simulators must be a non-empty list")
        names = [s.get("name") or f"simulator-{i}" for i, s in enumerate(simulators)]
        if len(set(names)) != len(names):
            raise SchedulerSimulationError(f"duplicate simulator names: {names}")
        results = []
        bindings: dict[str, dict[str, str]] = {}
        for name, sim in zip(names, simulators):
            scn_status, report = _run_in_isolated_simulator(scenario_spec, sim)
            if scn_status.get("phase") not in ("Succeeded", "Paused"):
                raise SchedulerSimulationError(
                    f"simulator {name!r}: scenario phase {scn_status.get('phase')!r}: "
                    f"{scn_status.get('message')}"
                )
            bindings[name] = _bindings_of(scn_status)
            results.append(
                {"simulator": name, "scenarioPhase": scn_status.get("phase"), "report": report}
            )
        status["results"] = results
        status["comparison"] = _compare(results, bindings)
        status["phase"] = "Completed"
    except Exception as e:
        status["phase"] = "Failed"
        status["message"] = f"{type(e).__name__}: {e}"
    status["completionTime"] = now_rfc3339()
    return obj


def _compare(results: list[Obj], bindings: dict[str, dict[str, str]]) -> Obj:
    """The cross-simulator table the KEP's user stories compute by hand:
    headline metrics side by side plus where the schedulers diverged."""
    metrics = {
        r["simulator"]: {
            "allocationRate": r["report"]["allocationRate"],
            "scheduledPods": r["report"]["scheduledPods"],
            "unscheduledPods": r["report"]["unscheduledPods"],
        }
        for r in results
    }
    names = list(bindings)
    divergent: dict[str, dict[str, "str | None"]] = {}
    if len(names) > 1:
        all_pods = sorted(set().union(*[set(b) for b in bindings.values()]))
        for pod in all_pods:
            placed = {n: bindings[n].get(pod) for n in names}
            if len(set(placed.values())) > 1:
                divergent[pod] = placed
    best = max(metrics, key=lambda n: metrics[n]["allocationRate"]) if metrics else None
    return {
        "metrics": metrics,
        "divergentPlacements": divergent,
        "divergentCount": len(divergent),
        "bestAllocationRate": best,
    }
