"""Unit tests for the in-memory cluster store (control-plane replacement)."""

import pytest

from kube_scheduler_simulator_tpu.state import (
    AlreadyExistsError,
    ClusterStore,
    NotFoundError,
)
from kube_scheduler_simulator_tpu.utils.retry import ConflictError


def pod(name, ns="default", node=None):
    p = {"metadata": {"name": name, "namespace": ns}, "spec": {}}
    if node:
        p["spec"]["nodeName"] = node
    return p


def node(name):
    return {"metadata": {"name": name}, "status": {"allocatable": {"cpu": "4", "memory": "8Gi"}}}


class TestCRUD:
    def test_create_get(self):
        s = ClusterStore(clock=lambda: 0.0)
        s.create("pods", pod("p1"))
        got = s.get("pods", "p1")
        assert got["metadata"]["name"] == "p1"
        # k8s wire format: resourceVersion is a string
        assert got["metadata"]["resourceVersion"] == "1"
        assert got["metadata"]["uid"]
        assert got["metadata"]["creationTimestamp"] == "1970-01-01T00:00:00Z"
        assert got["status"]["phase"] == "Pending"

    def test_create_duplicate(self):
        s = ClusterStore()
        s.create("pods", pod("p1"))
        with pytest.raises(AlreadyExistsError):
            s.create("pods", pod("p1"))

    def test_namespace_isolation(self):
        s = ClusterStore()
        s.create("pods", pod("p1", ns="a"))
        s.create("pods", pod("p1", ns="b"))
        assert len(s.list("pods")) == 2
        assert len(s.list("pods", namespace="a")) == 1

    def test_update_conflict(self):
        s = ClusterStore()
        created = s.create("pods", pod("p1"))
        created["metadata"]["resourceVersion"] = 999
        with pytest.raises(ConflictError):
            s.update("pods", created)

    def test_update_bumps_rv(self):
        s = ClusterStore()
        created = s.create("pods", pod("p1"))
        created["spec"]["priority"] = 5
        updated = s.update("pods", created)
        assert int(updated["metadata"]["resourceVersion"]) > int(created["metadata"]["resourceVersion"])
        assert updated["metadata"]["uid"] == created["metadata"]["uid"]

    def test_apply_upserts_and_ignores_stale_rv(self):
        s = ClusterStore()
        s.apply("nodes", node("n1"))
        o = node("n1")
        o["metadata"]["resourceVersion"] = 12345
        o["metadata"]["uid"] = "stale"
        applied = s.apply("nodes", o)
        assert applied["metadata"]["uid"] != "stale"

    def test_delete(self):
        s = ClusterStore()
        s.create("pods", pod("p1"))
        s.delete("pods", "p1")
        with pytest.raises(NotFoundError):
            s.get("pods", "p1")

    def test_patch_merges(self):
        s = ClusterStore()
        s.create("pods", pod("p1"))
        s.patch("pods", "p1", {"metadata": {"annotations": {"k": "v"}}})
        s.patch("pods", "p1", {"metadata": {"annotations": {"k2": "v2"}}})
        got = s.get("pods", "p1")
        assert got["metadata"]["annotations"] == {"k": "v", "k2": "v2"}

    def test_list_sorted(self):
        s = ClusterStore()
        for n in ["c", "a", "b"]:
            s.create("nodes", node(n))
        assert [o["metadata"]["name"] for o in s.list("nodes")] == ["a", "b", "c"]

    def test_unknown_kind(self):
        s = ClusterStore()
        with pytest.raises(NotFoundError):
            s.list("widgets")


class TestEvents:
    def test_subscribe(self):
        s = ClusterStore()
        events = []
        s.subscribe(["pods"], events.append)
        s.create("pods", pod("p1"))
        s.bind_pod("default", "p1", "n1")
        s.delete("pods", "p1")
        assert [e.type for e in events] == ["ADDED", "MODIFIED", "DELETED"]
        assert events[1].obj["spec"]["nodeName"] == "n1"

    def test_unsubscribe(self):
        s = ClusterStore()
        events = []
        unsub = s.subscribe(["pods"], events.append)
        unsub()
        s.create("pods", pod("p1"))
        assert events == []

    def test_events_since(self):
        s = ClusterStore()
        s.create("pods", pod("p1"))
        rv = s.resource_version
        s.create("pods", pod("p2"))
        evs = s.events_since("pods", rv)
        assert len(evs) == 1
        assert evs[0].obj["metadata"]["name"] == "p2"

    def test_events_since_expired_raises_gone(self):
        from kube_scheduler_simulator_tpu.state import ResourceExpiredError

        s = ClusterStore(event_log_size=4)
        for i in range(10):
            s.create("pods", pod(f"p{i}"))
        with pytest.raises(ResourceExpiredError):
            s.events_since("pods", 1)
        # Recent enough resourceVersions still resume fine.
        assert len(s.events_since("pods", 8)) == 2

    def test_update_hook_sees_old_and_new(self):
        s = ClusterStore()
        seen = []
        s.on_update("pods", lambda old, new: seen.append((old["spec"].get("nodeName"), new["spec"].get("nodeName"))))
        s.create("pods", pod("p1"))
        s.bind_pod("default", "p1", "n9")
        assert seen == [(None, "n9")]


class TestDumpRestore:
    def test_roundtrip(self):
        s = ClusterStore()
        s.create("nodes", node("n1"))
        s.create("pods", pod("p1"))
        snap = s.dump()
        s.delete("pods", "p1")
        s.create("pods", pod("p2"))
        s.restore(snap)
        names = [o["metadata"]["name"] for o in s.list("pods")]
        assert names == ["p1"]
        assert len(s.list("nodes")) == 1

    def test_restore_without_namespace_updates_not_recreates(self):
        s = ClusterStore()
        s.create("pods", pod("p1"))
        uid = s.get("pods", "p1")["metadata"]["uid"]
        events = []
        s.subscribe(["pods"], events.append)
        # namespaced object without explicit namespace must match default/p1
        s.restore({"pods": [{"metadata": {"name": "p1"}, "spec": {}}]})
        assert s.get("pods", "p1")["metadata"]["uid"] == uid
        assert all(e.type == "MODIFIED" for e in events)

    def test_deterministic_uids(self):
        s1 = ClusterStore(clock=lambda: 0.0)
        s2 = ClusterStore(clock=lambda: 0.0)
        for s in (s1, s2):
            s.create("pods", pod("p1"))
        assert s1.get("pods", "p1")["metadata"]["uid"] == s2.get("pods", "p1")["metadata"]["uid"]


class TestPriorityAdmission:
    """The reference disables ALL admission plugins except Priority
    (k8sapiserver.go:158-163); the store emulates it at pod create."""

    def test_priority_class_resolved(self):
        from kube_scheduler_simulator_tpu.state import ClusterStore

        store = ClusterStore()
        store.create("priorityclasses", {"metadata": {"name": "high"}, "value": 1000})
        pod = store.create("pods", {"metadata": {"name": "p"}, "spec": {"priorityClassName": "high",
                           "containers": [{"name": "c"}]}})
        assert pod["spec"]["priority"] == 1000

    def test_global_default_applied(self):
        from kube_scheduler_simulator_tpu.state import ClusterStore

        store = ClusterStore()
        store.create("priorityclasses", {"metadata": {"name": "team-default"}, "value": 7, "globalDefault": True})
        pod = store.create("pods", {"metadata": {"name": "p"}, "spec": {"containers": [{"name": "c"}]}})
        assert pod["spec"]["priority"] == 7
        assert pod["spec"]["priorityClassName"] == "team-default"

    def test_unknown_class_rejected_and_system_classes_builtin(self):
        import pytest

        from kube_scheduler_simulator_tpu.state import ClusterStore

        store = ClusterStore()
        with pytest.raises(ValueError):
            store.create("pods", {"metadata": {"name": "p"}, "spec": {"priorityClassName": "nope",
                         "containers": [{"name": "c"}]}})
        pod = store.create("pods", {"metadata": {"name": "crit"}, "spec": {
            "priorityClassName": "system-node-critical", "containers": [{"name": "c"}]}})
        assert pod["spec"]["priority"] == 2000001000

    def test_explicit_priority_wins(self):
        from kube_scheduler_simulator_tpu.state import ClusterStore

        store = ClusterStore()
        pod = store.create("pods", {"metadata": {"name": "p"}, "spec": {"priority": 42,
                           "containers": [{"name": "c"}]}})
        assert pod["spec"]["priority"] == 42
