"""Host-side encoding for the batched DefaultPreemption victim search.

The sequential oracle (plugins/intree/queue_bind.DefaultPreemption)
walks ``ni.pods`` per candidate node per unschedulable pod; this module
lifts the same data into per-node victim SLOT tables the kernel can scan:

- slots are ALL pods on the node with priority strictly below the
  round's highest pending priority, stably sorted by MoreImportantPod
  (priority desc, start time asc) — exactly ``sorted(lower, key=...)``
  in the oracle, because a stable sort of a superset restricted to any
  priority threshold equals the stable sort of the subset;
- resource columns are the union of the fit-checked resources any
  pending pod requests, GCD-scaled per column so the device floats stay
  exact (the same trick ops/encode.py uses for the batch kernel);
- PDB matching (namespace + label selector vs victim labels) becomes a
  [N, V, PDB] bool matrix against the per-PDB ``disruptionsAllowed``
  budget, reusing the matcher under ``utils/pdb.py``'s rule.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from kube_scheduler_simulator_tpu.models.podresources import (
    is_fit_resource,
    pod_resource_request,
)
from kube_scheduler_simulator_tpu.ops.encode import gcd_scale_columns
from kube_scheduler_simulator_tpu.plugins.intree.queue_bind import (
    DefaultPreemption,
    pod_priority,
)
from kube_scheduler_simulator_tpu.utils.labels import match_label_selector

Obj = dict[str, Any]

# MoreImportantPod's timestamp rule comes FROM the oracle — one source of
# truth, so the kernel's victim ordering can never drift from it
_start_time = DefaultPreemption._start_time


def fit_resource_axis(pods: list[Obj]) -> list[str]:
    """The union of fit-checked resources any of ``pods`` requests with a
    nonzero want — the only columns the Fit filter (and therefore the
    victim search) ever compares."""
    res: set[str] = set()
    for p in pods:
        for r, v in pod_resource_request(p).items():
            if v > 0 and is_fit_resource(r):
                res.add(r)
    return sorted(res)


def _req_vec(pod: Obj, res_idx: dict[str, int]) -> np.ndarray:
    v = np.zeros(len(res_idx), dtype=np.int64)
    for r, val in pod_resource_request(pod).items():
        j = res_idx.get(r)
        if j is not None:
            v[j] = val
    return v


class PreemptionProblem:
    """Encoded victim-search state for one batch kernel run."""

    __slots__ = (
        "node_names", "resource_names", "alloc", "base_req", "base_cnt",
        "max_pods", "vreq", "vprio", "vstart", "vvalid", "vmatch",
        "allowed", "victim_pods", "res_idx", "V", "PDB",
    )

    def __init__(self, node_names, resource_names):
        self.node_names = node_names
        self.resource_names = resource_names


def encode_preemption(
    node_infos: list[Any],
    resource_names: list[str],
    pdbs: list[Obj],
    nominated: "list[tuple[Obj, str]] | None" = None,
    max_pending_priority: int = 0,
) -> PreemptionProblem:
    """Build the per-node victim tables from the round snapshot's
    NodeInfos (which already account this round's earlier commits the
    service assumed — scheduler/service.py keeps them in step).

    ``nominated``: unbound (pod, node) nominations every victim search
    must respect as non-evictable usage (the oracle adds them to the
    scratch NodeInfo via ``run_filter_plugins_silently(snapshot=...)``;
    the caller's gate guarantees every nominee outranks every pending
    pod, so they are unconditionally accounted).
    """
    N = len(node_infos)
    R = len(resource_names)
    res_idx = {r: j for j, r in enumerate(resource_names)}
    pr = PreemptionProblem([ni.name for ni in node_infos], resource_names)
    pr.res_idx = res_idx
    pr.alloc = np.zeros((N, R), dtype=np.int64)
    pr.base_req = np.zeros((N, R), dtype=np.int64)
    pr.base_cnt = np.zeros(N, dtype=np.int64)
    pr.max_pods = np.zeros(N, dtype=np.int64)

    # victims: pods below the round's top pending priority, stably in
    # MoreImportantPod order — slot order IS the oracle's scan order
    victim_pods: list[list[Obj]] = []
    for j, ni in enumerate(node_infos):
        for r, v in ni.allocatable.items():
            if r in res_idx:
                pr.alloc[j, res_idx[r]] = v
        for r, v in ni.requested.items():
            if r in res_idx:
                pr.base_req[j, res_idx[r]] = v
        pr.base_cnt[j] = len(ni.pods)
        pr.max_pods[j] = ni.allowed_pod_number()
        lows = [p for p in ni.pods if pod_priority(p) < max_pending_priority]
        lows.sort(key=lambda p: (-pod_priority(p), _start_time(p)))
        victim_pods.append(lows)
    for npod, nn in nominated or []:
        try:
            j = pr.node_names.index(nn)
        except ValueError:
            continue
        pr.base_cnt[j] += 1
        pr.base_req[j] += _req_vec(npod, res_idx)

    V = max((len(v) for v in victim_pods), default=0)
    pr.V = V
    pr.victim_pods = victim_pods
    pr.vreq = np.zeros((N, V, R), dtype=np.int64)
    pr.vprio = np.zeros((N, V), dtype=np.int64)
    pr.vvalid = np.zeros((N, V), dtype=bool)
    # start-time RANK (global order over all slots): pickOneNodeForPreemption
    # compares start-time STRINGS; equal strings must stay equal as ranks
    starts = sorted({_start_time(p) for lows in victim_pods for p in lows})
    start_rank = {s: k for k, s in enumerate(starts)}
    pr.vstart = np.zeros((N, V), dtype=np.int64)
    for j, lows in enumerate(victim_pods):
        for s, p in enumerate(lows):
            pr.vreq[j, s] = _req_vec(p, res_idx)
            pr.vprio[j, s] = pod_priority(p)
            pr.vstart[j, s] = start_rank[_start_time(p)]
            pr.vvalid[j, s] = True

    PDB = len(pdbs)
    pr.PDB = PDB
    pr.vmatch = np.zeros((N, V, PDB), dtype=bool)
    pr.allowed = np.zeros(PDB, dtype=np.int64)
    for k, pdb in enumerate(pdbs):
        pr.allowed[k] = int(((pdb.get("status") or {}).get("disruptionsAllowed")) or 0)
        pdb_ns = pdb["metadata"].get("namespace") or "default"
        sel = (pdb.get("spec") or {}).get("selector")
        for j, lows in enumerate(victim_pods):
            for s, p in enumerate(lows):
                if (p["metadata"].get("namespace") or "default") != pdb_ns:
                    continue
                if match_label_selector(sel, p["metadata"].get("labels") or {}):
                    pr.vmatch[j, s, k] = True
    return pr


# gcd_scale_columns is re-exported from ops/encode.py: ONE implementation
# keeps the incremental batch encoder and the victim-search encoder from
# ever drifting on column scaling (tests/test_encode_incremental.py pins
# the identity and the scaling semantics).
__all__ = [
    "PreemptionProblem",
    "encode_preemption",
    "fit_resource_axis",
    "gcd_scale_columns",
]
