"""The session plane: N isolated simulations in one process.

A :class:`SessionManager` owns named sessions, each a full
:class:`~kube_scheduler_simulator_tpu.server.di.DIContainer` — its own
``ClusterStore`` (own resourceVersions, event log, watch epoch), its own
``SchedulerService`` (own queue, result annotations, plugin weights),
controllers, snapshot/reset services.  What sessions deliberately SHARE
is the expensive state: the process-wide compiled-executable substrate
(tenancy/substrate.py) and the on-disk AOT artifact cache, so tenant
k+1 with an already-seen scheduler config admits with zero new backend
compiles.

Lifecycle discipline (the knobs are validated here, loudly):

- ``KSS_MAX_SESSIONS``: admission cap; ``create`` past it raises
  :class:`TooManySessionsError`, which the HTTP layer maps to 429.
- ``KSS_SESSION_TTL_S``: idle TTL; sessions untouched for longer are
  reaped by :meth:`sweep` (called on every session CRUD, and cheap
  enough to call anywhere).  The default session never expires.
- destroy drains in-flight streamed waves through the scheduler's
  existing ``pause_streams`` seam before tearing the container down, so
  a tenant deletion can never abandon a half-committed wave.

Durability: with ``KSS_JOURNAL_DIR`` set, each session journals into
its own namespace ``<dir>/sessions/<id>`` (a manifest ``session.json``
records the boot parameters) and the manager's constructor re-creates
every manifest's session through the normal DIContainer boot — which
replays that namespace's journal — so a crashed multi-tenant server
comes back with EVERY tenant's store restored, not just the default
one.  Explicit destroy removes the namespace; process death does not.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time
from typing import Any, Callable

Obj = dict[str, Any]

DEFAULT_SESSION = "default"
DEFAULT_MAX_SESSIONS = 16
_ID_RE = re.compile(r"^[a-z0-9][a-z0-9-]{0,62}$")
MANIFEST = "session.json"


class SessionError(Exception):
    """Base class for session-plane admission/lookup failures."""


class InvalidSessionError(SessionError):
    """Malformed or reserved session id (HTTP 400)."""


class SessionExistsError(SessionError):
    """Create of an id that is already live (HTTP 409)."""


class UnknownSessionError(SessionError):
    """Routing or CRUD against an id that does not exist (HTTP 404)."""


class TooManySessionsError(SessionError):
    """Admission past KSS_MAX_SESSIONS (HTTP 429)."""


def session_knobs() -> Obj:
    """The documented ``KSS_SESSION_*`` env knobs, validated so a typo
    fails loudly at manager construction (docs/environment-variables.md;
    docs/multitenancy.md)."""
    ttl_raw = os.environ.get("KSS_SESSION_TTL_S", "").strip()
    ttl_s = 0.0
    if ttl_raw:
        try:
            ttl_s = float(ttl_raw)
        except ValueError:
            raise SessionError(
                f"KSS_SESSION_TTL_S must be a number of seconds >= 0, got {ttl_raw!r}"
            ) from None
        if ttl_s < 0:
            raise SessionError(f"KSS_SESSION_TTL_S must be >= 0, got {ttl_raw!r}")
    max_raw = os.environ.get("KSS_MAX_SESSIONS", "").strip()
    max_sessions = DEFAULT_MAX_SESSIONS
    if max_raw:
        try:
            max_sessions = int(max_raw)
        except ValueError:
            raise SessionError(
                f"KSS_MAX_SESSIONS must be an integer >= 1, got {max_raw!r}"
            ) from None
        if max_sessions < 1:
            raise SessionError(f"KSS_MAX_SESSIONS must be >= 1, got {max_raw!r}")
    return {"ttl_s": ttl_s, "max_sessions": max_sessions}


class Session:
    __slots__ = ("id", "di", "use_batch", "seed", "created_wall", "last_used")

    def __init__(self, id: str, di: Any, use_batch: str, seed: int, created_wall: float, now: float):
        self.id = id
        self.di = di
        self.use_batch = use_batch
        self.seed = seed
        self.created_wall = created_wall
        self.last_used = now


class SessionManager:
    """Create/destroy/route isolated sessions over one shared substrate.

    ``default_di`` is the boot container — it IS the ``default``
    session: never created, never destroyed, never expired, and every
    un-prefixed route keeps hitting it byte-for-byte.
    """

    def __init__(
        self,
        default_di: Any,
        clock: "Callable[[], float] | None" = None,
        use_batch: str = "auto",
        start_background: bool = False,
        recover: bool = True,
    ):
        from kube_scheduler_simulator_tpu.tenancy.substrate import SUBSTRATE

        knobs = session_knobs()
        self.ttl_s: float = knobs["ttl_s"]
        self.max_sessions: int = knobs["max_sessions"]
        # the shared-executable seam engages for the manager's lifetime,
        # so even the DEFAULT session's engines publish — tenant 1 with
        # the boot config admits warm
        SUBSTRATE.enable()
        self._substrate_held = True
        self.default_di = default_di
        self.use_batch_default = use_batch
        self.start_background = start_background
        self._clock = clock or time.monotonic
        self._lock = threading.RLock()
        self._sessions: dict[str, Session] = {}
        # lifecycle counters (rendered on /metrics once the plane is used)
        self.created = 0
        self.destroyed = 0
        self.expired = 0
        self.rejected = 0
        self.recovered = 0
        self.ever_used = False
        # per-session journal namespaces live under the DEFAULT journal
        # directory — one tree to back up, one tree recovery walks
        self.journal_root: "str | None" = getattr(default_di, "journal_dir", None)
        if recover and self.journal_root:
            self._recover_sessions()

    # ----------------------------------------------------------- internals

    def _sessions_dir(self) -> "str | None":
        return os.path.join(self.journal_root, "sessions") if self.journal_root else None

    def _namespace(self, session_id: str) -> "str | None":
        root = self._sessions_dir()
        return os.path.join(root, session_id) if root else None

    def _build_di(self, session_id: str, use_batch: str, seed: int, scheduler_cfg: "Obj | None"):
        from kube_scheduler_simulator_tpu.server.di import DIContainer

        di = DIContainer(
            initial_scheduler_cfg=scheduler_cfg,
            use_batch=use_batch,
            seed=seed,
            # a nested operator per tenant would be recursion bait — the
            # same reasoning as the KEP-159/184 ephemeral containers
            enable_simulator_operator=False,
            journal_dir=self._namespace(session_id),
        )
        if self.start_background:
            di.scheduler_service().start_background()
        return di

    def _recover_sessions(self) -> None:
        """Boot-time restore: every manifest under the sessions tree
        becomes a live session again, its store replayed from its own
        journal namespace by the DIContainer's normal recovery path."""
        root = self._sessions_dir()
        if root is None or not os.path.isdir(root):
            return
        for session_id in sorted(os.listdir(root)):
            path = os.path.join(root, session_id, MANIFEST)
            if not os.path.isfile(path):
                continue
            try:
                with open(path, encoding="utf-8") as f:
                    manifest = json.load(f)
            except (OSError, ValueError):
                continue  # a torn manifest names nothing recoverable
            use_batch = manifest.get("useBatch") or self.use_batch_default
            seed = int(manifest.get("seed") or 0)
            di = self._build_di(session_id, use_batch, seed, None)
            now = self._clock()
            # lock-free: runs only from __init__, before the manager is
            # published to any other thread
            self._sessions[session_id] = Session(
                session_id, di, use_batch, seed,
                float(manifest.get("createdAt") or 0.0), now,
            )
            self.recovered += 1
            self.ever_used = True  # lock-free: __init__-only, see above

    # -------------------------------------------------------------- create

    def create(
        self,
        session_id: "str | None" = None,
        use_batch: "str | None" = None,
        seed: int = 0,
        scheduler_cfg: "Obj | None" = None,
    ) -> Obj:
        with self._lock:
            self.sweep()
            if session_id is None:
                n = self.created
                while f"s-{n}" in self._sessions:
                    n += 1
                session_id = f"s-{n}"
            if session_id == DEFAULT_SESSION:
                raise InvalidSessionError(
                    "'default' is the boot container's session — it always exists"
                )
            if not _ID_RE.match(session_id):
                raise InvalidSessionError(
                    f"session id must match {_ID_RE.pattern}, got {session_id!r}"
                )
            if session_id in self._sessions:
                raise SessionExistsError(f"session {session_id!r} already exists")
            if len(self._sessions) >= self.max_sessions:
                self.rejected += 1
                raise TooManySessionsError(
                    f"session cap reached (KSS_MAX_SESSIONS={self.max_sessions}); "
                    "destroy one or raise the cap"
                )
            use_batch = use_batch or self.use_batch_default
            if use_batch not in ("off", "auto", "force"):
                raise InvalidSessionError(
                    f"useBatch must be off|auto|force, got {use_batch!r}"
                )
            created_wall = time.time()
            ns = self._namespace(session_id)
            if ns is not None:
                # manifest lands BEFORE the container boots: a crash
                # mid-create recovers an empty-but-present session, never
                # an orphaned journal namespace nothing re-adopts
                os.makedirs(ns, exist_ok=True)
                with open(os.path.join(ns, MANIFEST), "w", encoding="utf-8") as f:
                    json.dump(
                        {"id": session_id, "useBatch": use_batch, "seed": seed,
                         "createdAt": created_wall},
                        f,
                    )
            di = self._build_di(session_id, use_batch, int(seed), scheduler_cfg)
            s = Session(session_id, di, use_batch, int(seed), created_wall, self._clock())
            self._sessions[session_id] = s
            self.created += 1
            self.ever_used = True
            return self.info(s)

    # ------------------------------------------------------------- destroy

    def destroy(self, session_id: str, purge: bool = True, _expired: bool = False) -> None:
        with self._lock:
            if session_id == DEFAULT_SESSION:
                raise InvalidSessionError("the default session cannot be destroyed")
            s = self._sessions.pop(session_id, None)
            if s is None:
                raise UnknownSessionError(f"no session {session_id!r}")
            # drain first: in-flight streamed waves commit or park before
            # the container's services disappear under them
            try:
                with s.di.scheduler_service().pause_streams("session destroy"):
                    pass
            finally:
                s.di.close()
            ns = self._namespace(session_id)
            if purge and ns is not None and os.path.isdir(ns):
                # explicit destroy forgets the tenant durably — recovery
                # must not resurrect it
                shutil.rmtree(ns, ignore_errors=True)
            if _expired:
                self.expired += 1
            else:
                self.destroyed += 1

    def sweep(self) -> int:
        """Reap idle-expired sessions; returns how many went."""
        if self.ttl_s <= 0:
            return 0
        with self._lock:
            now = self._clock()
            stale = [
                sid for sid, s in self._sessions.items()
                if now - s.last_used > self.ttl_s
            ]
            for sid in stale:
                self.destroy(sid, purge=True, _expired=True)
            return len(stale)

    def close(self) -> None:
        """Server shutdown: tear containers down, KEEP journal
        namespaces — only an explicit destroy forgets a tenant."""
        with self._lock:
            for s in list(self._sessions.values()):
                try:
                    s.di.close()
                except Exception:
                    pass
            self._sessions.clear()
            if self._substrate_held:
                from kube_scheduler_simulator_tpu.tenancy.substrate import SUBSTRATE

                SUBSTRATE.disable()
                self._substrate_held = False

    # ------------------------------------------------------------- routing

    def get(self, session_id: str) -> Session:
        with self._lock:
            s = self._sessions.get(session_id)
            if s is None:
                raise UnknownSessionError(f"no session {session_id!r}")
            s.last_used = self._clock()
            return s

    def resolve_di(self, session_id: "str | None"):
        """The routing seam: '' / None / 'default' → the boot container;
        anything else → that session's container (touching its TTL
        clock) or :class:`UnknownSessionError`."""
        if not session_id or session_id == DEFAULT_SESSION:
            return self.default_di
        return self.get(session_id).di

    def resolve_store(self, session_id: "str | None"):
        """Same, for the kube-API port (store-only surface)."""
        return self.resolve_di(session_id).cluster_store

    # ------------------------------------------------------------- surface

    def info(self, s: Session) -> Obj:
        now = self._clock()
        return {
            "id": s.id,
            "useBatch": s.use_batch,
            "seed": s.seed,
            "createdAt": s.created_wall,
            "idleSeconds": round(max(0.0, now - s.last_used), 3),
            "journalNamespace": self._namespace(s.id),
        }

    def list(self) -> "list[Obj]":
        with self._lock:
            self.sweep()
            return [self.info(s) for _, s in sorted(self._sessions.items())]

    def ids(self) -> "list[str]":
        with self._lock:
            return sorted(self._sessions)

    def stats(self) -> Obj:
        with self._lock:
            return {
                "sessions_active": len(self._sessions),
                "sessions_created_total": self.created,
                "sessions_destroyed_total": self.destroyed,
                "sessions_expired_total": self.expired,
                "sessions_rejected_total": self.rejected,
                "sessions_recovered_total": self.recovered,
                "session_ttl_s": self.ttl_s,
                "max_sessions": self.max_sessions,
            }
