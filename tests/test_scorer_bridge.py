"""extenderv1 TPU scorer bridge (SURVEY §7 step 8 / VERDICT r1 item 5).

A real Go scheduler configures an extender stanza pointing at
``/api/v1/tpuscorer/{filter,prioritize}``; these tests POST the exact
extenderv1 wire shapes the reference's extender client sends (reference
simulator/scheduler/extender/extender.go:122-148) and assert the responses
carry the batch kernel's decisions.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Any

import numpy as np
import pytest

from kube_scheduler_simulator_tpu.scheduler.batch_engine import BatchEngine
from kube_scheduler_simulator_tpu.server import DIContainer, SimulatorServer

Obj = dict[str, Any]


def mk_node(name: str, cpu_m: int, taints=None, labels=None) -> Obj:
    n: Obj = {
        "metadata": {"name": name, "labels": {"kubernetes.io/hostname": name, **(labels or {})}},
        "spec": {"taints": taints} if taints else {},
        "status": {"allocatable": {"cpu": f"{cpu_m}m", "memory": "8Gi", "pods": "110"}},
    }
    return n


def mk_pod(name: str, cpu_m: int, **spec_extra) -> Obj:
    spec: Obj = {"containers": [{"name": "c", "resources": {"requests": {"cpu": f"{cpu_m}m"}}}]}
    spec.update(spec_extra)
    return {"metadata": {"name": name, "namespace": "default"}, "spec": spec}


@pytest.fixture()
def server():
    di = DIContainer(use_batch="off")
    store = di.cluster_store
    store.create("nodes", mk_node("node-free", 8000))
    store.create("nodes", mk_node("node-tight", 1000))
    store.create(
        "nodes",
        mk_node("node-tainted", 8000, taints=[{"key": "gpu", "value": "yes", "effect": "NoSchedule"}]),
    )
    # a bound pod consuming node-tight, shaping LeastAllocated scores
    bound = mk_pod("existing", 800)
    bound["spec"]["nodeName"] = "node-tight"
    store.create("pods", bound)
    srv = SimulatorServer(di, port=0)
    srv.start(background=True)
    yield srv, di
    srv.shutdown()


def _post(srv: SimulatorServer, path: str, body: Obj):
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}",
        data=json.dumps(body).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=300) as resp:  # first call compiles
        return resp.status, json.loads(resp.read())


def test_filter_splits_failures(server):
    srv, di = server
    nodes = di.cluster_store.list("nodes")
    pod = mk_pod("incoming", 2000)
    code, out = _post(srv, "/api/v1/tpuscorer/filter", {"pod": pod, "nodes": {"items": nodes}})
    assert code == 200
    assert out["error"] == ""
    passed = {n["metadata"]["name"] for n in out["nodes"]["items"]}
    assert passed == {"node-free"}
    # Fit failure is resolvable (preemption can free cpu); a NoSchedule
    # taint is UnschedulableAndUnresolvable upstream (tainttoleration
    # Filter) — preemption cannot remove a taint
    assert set(out["failedNodes"]) == {"node-tight"}
    assert "Insufficient cpu" in out["failedNodes"]["node-tight"]
    assert set(out["failedAndUnresolvableNodes"]) == {"node-tainted"}
    assert "untolerated taint" in out["failedAndUnresolvableNodes"]["node-tainted"]


def test_filter_unresolvable_and_nodenames_mode(server):
    srv, di = server
    pod = mk_pod("incoming", 100, nodeSelector={"zone": "z9"})
    code, out = _post(
        srv,
        "/api/v1/tpuscorer/filter",
        {"pod": pod, "nodenames": ["node-free", "node-tight"]},
    )
    assert code == 200
    # node-cache-capable callers get names back, not objects
    assert out["nodes"] is None
    assert out["nodenames"] == []
    # NodeAffinity (nodeSelector) failures are UnschedulableAndUnresolvable
    assert set(out["failedAndUnresolvableNodes"]) == {"node-free", "node-tight"}


def test_prioritize_matches_kernel_trace(server):
    srv, di = server
    nodes = [n for n in di.cluster_store.list("nodes") if n["metadata"]["name"] != "node-tainted"]
    pod = mk_pod("incoming", 500)

    code, out = _post(srv, "/api/v1/tpuscorer/prioritize", {"pod": pod, "nodes": {"items": nodes}})
    assert code == 200
    got = {e["host"]: e["score"] for e in out}

    # expected: the kernel trace's weighted totals for the same pass
    fw = di.scheduler_service().framework
    eng = BatchEngine.from_framework(fw, trace=True)
    eng.percentage_of_nodes_to_score = 100
    res = eng.schedule(
        nodes, di.cluster_store.list("pods"), [pod], di.cluster_store.list("namespaces")
    )
    totals = res.totals_map(0)
    feasible = res.feasible_idx(0)
    want = {
        n["metadata"]["name"]: (totals.get(j, 0) if j in feasible else 0)
        for j, n in enumerate(nodes)
    }
    assert got == want
    # 500m + the existing 800m exceed node-tight's 1000m: infeasible → 0;
    # the free node carries the kernel's weighted total
    assert got["node-free"] > 0
    assert got["node-tight"] == 0


def test_unsupported_workload_falls_back_exactly(server):
    srv, di = server
    nodes = di.cluster_store.list("nodes")
    # a PVC volume exercises VolumeRestrictions/VolumeBinding → no kernel
    pod = mk_pod("incoming", 100, volumes=[{"name": "v", "persistentVolumeClaim": {"claimName": "c"}}])
    code, out = _post(srv, "/api/v1/tpuscorer/filter", {"pod": pod, "nodes": {"items": nodes}})
    assert code == 200
    assert di.tpu_scorer_bridge().fallbacks >= 1
    passed = {n["metadata"]["name"] for n in out["nodes"]["items"]}
    # sequential oracle still answers: taint keeps node-tainted out
    assert "node-free" in passed and "node-tainted" not in passed
