from kube_scheduler_simulator_tpu.state.store import (
    KINDS,
    NAMESPACED_KINDS,
    ClusterStore,
    Event,
    NotFoundError,
    AlreadyExistsError,
    ResourceExpiredError,
)

__all__ = [
    "KINDS",
    "NAMESPACED_KINDS",
    "ClusterStore",
    "Event",
    "NotFoundError",
    "AlreadyExistsError",
    "ResourceExpiredError",
]
