"""KSS-DONATE: a donated buffer is dead after the dispatch.

``jax.jit(..., donate_argnums=)`` hands the argument's device buffer to
XLA for in-place reuse — after the call the old array is INVALID, and
reading it raises a deleted-buffer error on real accelerators while the
CPU backend (no donation support) silently keeps it alive, so the bug
ships green on CPU and explodes on a TPU.  The repo's contract (the
DevicePlacer bank rule): after dispatching through a donating callable,
the donated binding is never read again in that function — the result
replaces it (``buf = donate_fn(buf, ...)``) or the function returns.

Statically: collect name bindings to donating callables —
``X = jax.jit(f, donate_argnums=(0,))`` at module or function level,
including conditional aliases (``fn = copy_variant if on_cpu else
donate_variant`` makes ``fn`` a *maybe*-donating callable, flagged all
the same: the read is broken exactly on the hardware where donation is
real).  At every call ``X(a, b, ...)`` inside a function, the
positional args named by ``donate_argnums`` (or keyword args named by
``donate_argnames``) that are plain names are checked for loads after
the call line; a rebind of the name (including the canonical
``a = X(a, ...)`` self-replace) ends the liveness of the stale buffer.
"""

from __future__ import annotations

import ast

from kube_scheduler_simulator_tpu.analysis.framework import Finding, Project, Rule, SourceFile


def _donation_spec(call: ast.Call) -> "tuple[tuple[int, ...], tuple[str, ...]] | None":
    """``jax.jit(f, donate_argnums=..., donate_argnames=...)`` → the
    literal donated positions/names, or None when not a donating jit."""
    f = call.func
    is_jit = (isinstance(f, ast.Attribute) and f.attr == "jit") or (
        isinstance(f, ast.Name) and f.id == "jit"
    )
    if not is_jit:
        return None
    nums: list[int] = []
    names: list[str] = []
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            vals = kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    nums.append(v.value)
        elif kw.arg == "donate_argnames":
            vals = kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    names.append(v.value)
    if not nums and not names:
        return None
    return tuple(nums), tuple(names)


class DonateRule(Rule):
    name = "KSS-DONATE"
    paths = None

    def check_file(self, src: SourceFile, ctx: Project) -> "list[Finding]":
        # name → (argnums, argnames); conditional aliases join in
        donating: "dict[str, tuple[tuple[int, ...], tuple[str, ...]]]" = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                tgt = node.targets[0].id
                for rhs in (
                    [node.value.body, node.value.orelse]
                    if isinstance(node.value, ast.IfExp)
                    else [node.value]
                ):
                    spec = _donation_spec(rhs) if isinstance(rhs, ast.Call) else None
                    if spec is None and isinstance(rhs, ast.Name) and rhs.id in donating:
                        spec = donating[rhs.id]  # alias of a donating name
                    if spec is not None:
                        donating[tgt] = spec
        out: list[Finding] = []
        for fn in ast.walk(src.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._check_fn(src, fn, donating))
        return out

    # ----------------------------------------------------------- per-func

    def _check_fn(
        self,
        src: SourceFile,
        fn: ast.FunctionDef,
        module_donating: "dict[str, tuple[tuple[int, ...], tuple[str, ...]]]",
    ) -> "list[Finding]":
        donating = dict(module_donating)
        # local bindings/aliases shadow module ones
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                tgt = node.targets[0].id
                for rhs in (
                    [node.value.body, node.value.orelse]
                    if isinstance(node.value, ast.IfExp)
                    else [node.value]
                ):
                    spec = _donation_spec(rhs) if isinstance(rhs, ast.Call) else None
                    if spec is None and isinstance(rhs, ast.Name) and rhs.id in donating:
                        spec = donating[rhs.id]
                    if spec is not None:
                        donating[tgt] = spec

        out: list[Finding] = []
        # every donating call site: (call node, donated plain-name args)
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            spec = None
            if isinstance(call.func, ast.Name) and call.func.id in donating:
                spec = donating[call.func.id]
            elif (d := _donation_spec(call.func) if isinstance(call.func, ast.Call) else None):
                spec = d  # direct jax.jit(f, donate_argnums=...)(args)
            if spec is None:
                continue
            nums, names = spec
            donated_names: list[str] = []
            for i in nums:
                if i < len(call.args) and isinstance(call.args[i], ast.Name):
                    donated_names.append(call.args[i].id)
            for kw in call.keywords:
                if kw.arg in names and isinstance(kw.value, ast.Name):
                    donated_names.append(kw.value.id)
            if not donated_names:
                continue
            out.extend(self._reads_after(src, fn, call, donated_names))
        return out

    def _reads_after(
        self, src: SourceFile, fn: ast.FunctionDef, call: ast.Call, donated: "list[str]"
    ) -> "list[Finding]":
        out: list[Finding] = []
        call_line = call.end_lineno or call.lineno
        for name in donated:
            rebind_line = None
            # the canonical self-replace: name = donating(name, ...) on the
            # call's own statement rebinds at the call line
            for node in ast.walk(fn):
                if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                    for t in targets:
                        if isinstance(t, ast.Name) and t.id == name:
                            ln = node.lineno
                            if ln >= call.lineno and (rebind_line is None or ln < rebind_line):
                                rebind_line = ln
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Name)
                    and node.id == name
                    and isinstance(node.ctx, ast.Load)
                    and node.lineno > call_line
                    and (rebind_line is None or node.lineno <= rebind_line)
                ):
                    # the canonical self-replace (name = donating(name,…))
                    # needs no special case: its rebind line IS the call
                    # line, so the (call_line, rebind_line] window is
                    # empty — any load that lands here, including the RHS
                    # of a LATER rebind (buf = buf + 1), reads the stale
                    # buffer and is flagged
                    out.append(
                        src.finding(
                            self.name,
                            node,
                            f"read of '{name}' after it was donated to the "
                            f"dispatch on line {call.lineno}: the buffer is "
                            "deleted on accelerators with donation support "
                            "(CPU silently keeps it alive, so tests stay "
                            "green and TPUs crash). Use the dispatch result, "
                            "or rebind the name before reading it.",
                        )
                    )
                    break  # one finding per donated name per call
        return out
